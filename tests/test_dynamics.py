"""Training-dynamics observatory (ISSUE 19): the fused on-device
parameter/gradient health reduction and its host-side verdict layer.

The acceptance properties pinned here: turning the observatory on does
not perturb training numerics AT ALL (bitwise parity of final weights,
stats on vs off — the reduction is appended to the traced step, never
inserted into it); the run_steps scan samples exactly one row per
period boundary (no per-step host sync); the verdict layer classifies
synthetic time-series into the stable health codes dashboards key on
(dead-layer, frozen-param, exploding-update, nonfinite); GradientAudit's
thresholds come from the SAME constants table (single source of truth,
ISSUE 19 satellite); and /dynamics answers over real HTTP with the
payload schema the CLI and dashboards consume."""

import http.client
import json
import math
import os

import numpy as np
import pytest

import paddle_tpu as fluid
from paddle_tpu import dynamics, obs_server, telemetry
from paddle_tpu import executor as executor_mod
from paddle_tpu.framework import unique_name


@pytest.fixture(autouse=True)
def _fresh_dynamics_state():
    telemetry.reset()
    dynamics.reset()
    yield
    obs_server.stop()
    telemetry.reset()
    dynamics.reset()


def _build_program(seed=7):
    main, startup = fluid.Program(), fluid.Program()
    main.random_seed = seed
    startup.random_seed = seed
    with unique_name.guard():
        with fluid.program_guard(main, startup):
            x = fluid.layers.data(name="x", shape=[4], dtype="float32")
            y = fluid.layers.data(name="y", shape=[1], dtype="float32")
            h = fluid.layers.fc(input=x, size=8, act="relu")
            pred = fluid.layers.fc(input=h, size=1)
            loss = fluid.layers.mean(
                fluid.layers.square_error_cost(input=pred, label=y))
            fluid.optimizer.Momentum(
                learning_rate=0.01, momentum=0.9).minimize(
                    loss, startup_program=startup)
    return main, startup, loss


def _batches(n, batch=8, seed=0):
    rng = np.random.RandomState(seed)
    out = []
    for _ in range(n):
        xb = rng.rand(batch, 4).astype(np.float32)
        yb = (xb.sum(axis=1, keepdims=True) * 0.5).astype(np.float32)
        out.append({"x": xb, "y": yb})
    return out


def _param_names(main):
    return sorted(p.name for p in main.global_block().all_parameters())


def _train(steps, *, dyn_enabled, period=1):
    """Fresh program + scope, `steps` per-step runs; -> {param: ndarray}."""
    main, startup, loss = _build_program()
    feeds = _batches(steps)
    scope = executor_mod.Scope()
    with dynamics.override(dyn_enabled, period):
        with executor_mod.scope_guard(scope):
            exe = fluid.Executor(fluid.CPUPlace())
            exe.run(startup)
            for feed in feeds:
                exe.run(main, feed=feed, fetch_list=[loss.name])
            return {n: np.array(scope.find_var(n))
                    for n in _param_names(main)}


def test_bitwise_parity_stats_on_vs_off():
    """The fused reduction reads the step's values; it must never feed
    back into them. Same seed, same batches: final weights are bitwise
    identical with the observatory off and sampling every step."""
    base = _train(5, dyn_enabled=False)
    dynamics.reset()
    telemetry.reset()
    observed = _train(5, dyn_enabled=True, period=1)
    assert base.keys() == observed.keys()
    for name in base:
        assert np.array_equal(base[name], observed[name]), (
            f"{name} diverged with dynamics enabled")
    # and the observed run actually sampled (the parity is not vacuous)
    assert dynamics.payload()["samples_recorded"] >= 5


def test_per_step_sampling_respects_period():
    """period=2: the startup run advances the counter to 1, so steps
    commit counters 2..7 and exactly 2|counter samples land."""
    with dynamics.override(True, 2):
        main, startup, loss = _build_program()
        scope = executor_mod.Scope()
        with executor_mod.scope_guard(scope):
            exe = fluid.Executor(fluid.CPUPlace())
            exe.run(startup)
            for feed in _batches(6):
                exe.run(main, feed=feed, fetch_list=[loss.name])
    assert dynamics.payload()["samples_recorded"] == 3


def test_run_steps_window_samples_period_boundaries():
    """The scan stacks a [K, G, 8] row block on-device; the host unpack
    must record exactly one sample per period boundary inside the
    window — here counters 2..9 with period 4 hit 4 and 8."""
    with dynamics.override(True, 4):
        main, startup, loss = _build_program()
        scope = executor_mod.Scope()
        with executor_mod.scope_guard(scope):
            exe = fluid.Executor(fluid.CPUPlace())
            exe.run(startup)
            exe.run_steps(main, feed_window=_batches(8),
                          fetch_list=[loss.name])
    assert dynamics.payload()["samples_recorded"] == 2
    # both samples belong to every series' ring (one program, 4 params)
    progs = dynamics.payload()["programs"]
    assert len(progs) == 1
    for series in next(iter(progs.values()))["series"].values():
        assert series["samples"] == 2


# -- verdict layer on synthetic series --------------------------------------


def _plan_one(name="fc_0.w_0", role="ffn_up"):
    ent = dynamics._ParamEntry(name, name + "@GRAD", False, [], role)
    grp = dynamics._Group(name, role, [ent])
    return dynamics.DynamicsPlan([grp], (ent.grad,), 1, 1)


def _row(weight_l2=1.0, weight_rms=0.1, weight_max_abs=0.5, grad_l2=1.0,
         grad_rms=0.1, grad_zero_frac=0.0, update_ratio=0.01,
         moment_rms=-1.0):
    vals = dict(weight_l2=weight_l2, weight_rms=weight_rms,
                weight_max_abs=weight_max_abs, grad_l2=grad_l2,
                grad_rms=grad_rms, grad_zero_frac=grad_zero_frac,
                update_ratio=update_ratio, moment_rms=moment_rms)
    return np.array([[vals[f] for f in dynamics.STAT_FIELDS]], np.float64)


def _feed(plan, rows, prog="pX"):
    for step, row in enumerate(rows):
        dynamics._OBS.record(prog, step, plan, row)


def _verdict_codes():
    return {(v["program"], v["series"]): v["code"]
            for v in dynamics.verdicts()}


def test_dead_layer_verdict_and_gauge():
    plan = _plan_one()
    win = int(dynamics.THRESHOLDS["verdict_window"])
    _feed(plan, [_row(grad_l2=0.0, grad_rms=0.0, update_ratio=0.0)] * win)
    assert _verdict_codes() == {("pX", "fc_0.w_0"): "dead-layer"}
    assert telemetry.read_gauge("dynamics_dead_layers", program="pX") == 1.0


def test_frozen_param_needs_live_gradients():
    """Zero updates with LIVE gradients is frozen-param (an optimizer
    or lr problem), distinct from dead-layer (a gradient-flow one)."""
    plan = _plan_one()
    win = int(dynamics.THRESHOLDS["verdict_window"])
    _feed(plan, [_row(grad_rms=0.1, update_ratio=0.0)] * win)
    assert _verdict_codes() == {("pX", "fc_0.w_0"): "frozen-param"}
    assert telemetry.read_gauge(
        "dynamics_frozen_params", program="pX") == 1.0


def test_exploding_update_vs_ewma_baseline():
    """A ratio 50x the EWMA baseline (and above the absolute floor)
    flips the verdict the LR-spike pager keys on; a steady ratio at the
    baseline never does."""
    plan = _plan_one()
    _feed(plan, [_row(update_ratio=0.01)] * 8)
    assert not dynamics.verdicts()
    _feed(plan, [_row(update_ratio=0.5)])
    assert _verdict_codes() == {("pX", "fc_0.w_0"): "exploding-update"}


def test_nonfinite_wins_over_history():
    plan = _plan_one()
    win = int(dynamics.THRESHOLDS["verdict_window"])
    _feed(plan, [_row(grad_rms=0.0, update_ratio=0.0)] * win)
    _feed(plan, [_row(weight_l2=float("nan"))])
    assert _verdict_codes() == {("pX", "fc_0.w_0"): "nonfinite"}


def test_absent_optional_fields_round_trip_as_none():
    """-1 is the on-device 'absent' sentinel for optional fields (no
    grad this step, no optimizer moment); it must surface as null, not
    a negative statistic."""
    plan = _plan_one()
    _feed(plan, [_row(grad_l2=-1.0, grad_rms=-1.0, grad_zero_frac=-1.0,
                      update_ratio=-1.0, moment_rms=-1.0)])
    series = dynamics.payload()["programs"]["pX"]["series"]["fc_0.w_0"]
    last = series["last"]
    for field in ("grad_l2", "grad_rms", "update_ratio", "moment_rms"):
        assert last[field] is None
    assert last["weight_l2"] == 1.0


def test_jsonl_export(tmp_path, monkeypatch):
    path = str(tmp_path / "dyn.jsonl")
    monkeypatch.setenv("PADDLE_TPU_DYNAMICS_LOG", path)
    plan = _plan_one()
    _feed(plan, [_row()] * 2)
    recs = [json.loads(ln) for ln in open(path)]
    assert len(recs) == 2
    assert recs[0]["series"] == "fc_0.w_0"
    assert recs[0]["code"] == "ok"
    assert math.isclose(recs[1]["update_ratio"], 0.01)


# -- threshold unification (GradientAudit satellite) ------------------------


def test_gradient_audit_thresholds_come_from_dynamics_table():
    """ISSUE 19 satellite: GradientAudit's band edges resolve from
    dynamics.THRESHOLDS — one constants table, not two drifting ones."""
    from paddle_tpu.inspector import GradientAudit

    main, _, _ = _build_program()
    audit = GradientAudit(main)
    assert audit.vanishing_threshold == \
        dynamics.THRESHOLDS["grad_vanishing_abs_mean"]
    assert audit.exploding_threshold == \
        dynamics.THRESHOLDS["grad_exploding_max_abs"]


def test_gradient_audit_tracks_table_edits(monkeypatch):
    """Editing the shared table moves a FRESH audit's bands — the
    regression this pins is someone re-hardcoding the literals."""
    from paddle_tpu.inspector import GradientAudit

    main, _, _ = _build_program()
    monkeypatch.setitem(dynamics.THRESHOLDS,
                        "grad_vanishing_abs_mean", 3e-5)
    assert GradientAudit(main).vanishing_threshold == 3e-5


def test_classify_grad_bands():
    cg = dynamics.classify_grad
    assert cg(True, 1.0, 1.0, 1.0) == "nonfinite"
    assert cg(False, 0.0, 0.0, 0.0) == "zero"
    assert cg(False, 1e-9, 1e-9, 1e-9) == "vanishing"
    assert cg(False, 1e4, 1.0, 1e4) == "exploding"
    assert cg(False, 0.1, 0.05, 0.2) == "ok"
    # explicit overrides (the audit's constructor args) still win
    assert cg(False, 1e-3, 1e-3, 1e-3,
              vanishing_threshold=1e-2) == "vanishing"


# -- HTTP surface -----------------------------------------------------------


def test_dynamics_endpoint_serves_payload():
    plan = _plan_one()
    win = int(dynamics.THRESHOLDS["verdict_window"])
    _feed(plan, [_row(grad_l2=0.0, grad_rms=0.0, update_ratio=0.0)] * win)
    srv = obs_server.start(port=0)
    conn = http.client.HTTPConnection("127.0.0.1", srv.port, timeout=10)
    try:
        conn.request("GET", "/dynamics?n=4")
        resp = conn.getresponse()
        assert resp.status == 200
        body = json.loads(resp.read())
    finally:
        conn.close()
    assert body["enabled"] in (True, False)
    assert body["samples_recorded"] == win
    series = body["programs"]["pX"]["series"]["fc_0.w_0"]
    assert series["verdict"] == "dead-layer"
    assert len(series["recent"]) == 4
    assert [v["code"] for v in body["verdicts"]] == ["dead-layer"]
