"""Spawn-context training worker for test_elastic_training: imports ONLY
stdlib + numpy + master.py loaded by path (never the paddle_tpu package
__init__, which imports jax — forking/spawning into jax is the documented
hazard). One worker = one elastic trainer: lease tasks from the shared
TaskQueue, compute the task's gradient against the pass-start parameters,
write it to an idempotent per-task file (re-execution after a crash
overwrites the same file — at-least-once dispatch composes with sync SGD
without double counting), mark finished."""

import json
import os
import time


def _load_master_standalone():
    import importlib.util
    path = os.path.join(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))), "paddle_tpu", "parallel", "master.py")
    spec = importlib.util.spec_from_file_location("_master_standalone", path)
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


def worker(qdir, wid, data_path, params_path, grads_dir, log_path,
           slow_s=0.0, marker_path=None):
    """Drain the current pass: for each leased task, grad of 0.5*||Xw-y||^2
    over the task's sample ids, saved as grads_dir/task_<tid>.npy."""
    import numpy as np

    master = _load_master_standalone()
    q = master.TaskQueue(qdir, timeout_s=2.0)
    blob = np.load(data_path)
    x_all, y_all = blob["x"], blob["y"]
    w = np.load(params_path)
    consumed = []
    first = True
    while True:
        leased = q.get_task(wid)
        if leased is None:
            if q.pass_done():
                break
            time.sleep(0.05)
            continue
        tid, chunks = leased
        sample_ids = [s for chunk in chunks for s in chunk]
        if first and marker_path is not None:
            with open(marker_path, "w") as f:
                f.write(wid)
        first = False
        if slow_s:
            time.sleep(slow_s)         # window for the parent's SIGKILL
        ids = np.asarray(sample_ids)
        xb, yb = x_all[ids], y_all[ids]
        grad = xb.T @ (xb @ w - yb)    # sum-reduction: task-additive
        tmp = os.path.join(grads_dir, f".task_{tid}.tmp.{wid}")
        np.save(tmp, grad)
        os.replace(tmp + ".npy", os.path.join(grads_dir,
                                              f"task_{tid}.npy"))
        consumed.extend(int(i) for i in sample_ids)
        q.task_finished(tid)
    with open(log_path, "w") as f:
        json.dump(consumed, f)
