"""ModelAverage optimizer + average_accumulates op (reference:
optimizer.py:811, average_accumulates_op.h, test_model_average tests)."""

import numpy as np

import paddle_tpu as fluid
from paddle_tpu import executor as executor_mod


class TestModelAverage:
    def test_apply_restores_and_averages(self):
        main = fluid.Program()
        startup = fluid.Program()
        with fluid.program_guard(main, startup):
            x = fluid.layers.data(name="x", shape=[2], dtype="float32")
            y = fluid.layers.data(name="y", shape=[1], dtype="float32")
            pred = fluid.layers.fc(input=x, size=1,
                                   param_attr=fluid.ParamAttr(name="w"),
                                   bias_attr=False)
            loss = fluid.layers.mean(
                fluid.layers.square_error_cost(pred, y))
            fluid.optimizer.SGDOptimizer(learning_rate=0.2).minimize(loss)
            model_avg = fluid.optimizer.ModelAverage(
                average_window_rate=1.0, min_average_window=1,
                max_average_window=1000)

        exe = fluid.Executor(fluid.CPUPlace())
        rng = np.random.RandomState(0)
        xs = rng.randn(16, 2).astype(np.float32)
        w_true = np.array([[1.5], [-2.0]], np.float32)
        ys = xs @ w_true
        scope = executor_mod.Scope()
        with executor_mod.scope_guard(scope):
            exe.run(startup)
            traj = []
            for _ in range(6):
                exe.run(main, feed={"x": xs, "y": ys}, fetch_list=[loss])
                traj.append(np.asarray(scope.find_var("w")).copy())
            trained = traj[-1]
            want_avg = np.mean(traj, axis=0)
            with model_avg.apply(exe):
                inside = np.asarray(scope.find_var("w")).copy()
                np.testing.assert_allclose(inside, want_avg, rtol=1e-5)
                assert not np.allclose(inside, trained)
            restored = np.asarray(scope.find_var("w"))
            np.testing.assert_allclose(restored, trained, rtol=1e-7)


class TestAverageAccumulatesOpSemantics:
    def test_window_roll(self):
        """Numpy step-by-step simulation of the reference kernel vs the op
        across a window rollover."""
        import jax
        main = fluid.Program()
        startup = fluid.Program()
        with fluid.program_guard(main, startup):
            p = fluid.layers.data(name="p", shape=[3], dtype="float32",
                                  append_batch_size=False)
            blk = main.global_block()
            vals = {}
            for nm, shape, dt in [("s1", [3], "float32"), ("s2", [3], "float32"),
                                  ("s3", [3], "float32"), ("na", [1], "int32"),
                                  ("on", [1], "int32"), ("nu", [1], "int32")]:
                vals[nm] = blk.create_var(name=nm, shape=shape, dtype=dt,
                                          persistable=True)
            blk.append_op(
                type="average_accumulates",
                inputs={"param": [p], "in_sum_1": [vals["s1"]],
                        "in_sum_2": [vals["s2"]], "in_sum_3": [vals["s3"]],
                        "in_num_accumulates": [vals["na"]],
                        "in_old_num_accumulates": [vals["on"]],
                        "in_num_updates": [vals["nu"]]},
                outputs={"out_sum_1": [vals["s1"]], "out_sum_2": [vals["s2"]],
                         "out_sum_3": [vals["s3"]],
                         "out_num_accumulates": [vals["na"]],
                         "out_old_num_accumulates": [vals["on"]],
                         "out_num_updates": [vals["nu"]]},
                attrs={"average_window": 0.5, "min_average_window": 2,
                       "max_average_window": 3})
        exe = fluid.Executor(fluid.CPUPlace())
        scope = executor_mod.Scope()
        with executor_mod.scope_guard(scope):
            for nm, dt in [("s1", np.float32), ("s2", np.float32),
                           ("s3", np.float32)]:
                scope.set_var(nm, np.zeros(3, dt))
            for nm in ("na", "on", "nu"):
                scope.set_var(nm, np.zeros(1, np.int32))

            # numpy oracle
            s1 = np.zeros(3); s2 = np.zeros(3); s3 = np.zeros(3)
            na = on = nu = 0
            rng = np.random.RandomState(2)
            for step in range(6):
                pv = rng.rand(3).astype(np.float32)
                exe.run(main, feed={"p": pv}, fetch_list=[vals["s1"]])
                nu += 1; na += 1; s1 = s1 + pv
                if na >= 2 and na >= min(3, int(nu * 0.5)):
                    s3 = s1 + s2; s1 = np.zeros(3); s2 = np.zeros(3)
                    on = na; na = 0
                np.testing.assert_allclose(
                    np.asarray(scope.find_var("s1")), s1, rtol=1e-6,
                    err_msg=f"s1 step {step}")
                np.testing.assert_allclose(
                    np.asarray(scope.find_var("s3")), s3, rtol=1e-6,
                    err_msg=f"s3 step {step}")
                assert int(np.asarray(scope.find_var("na"))[0]) == na
                assert int(np.asarray(scope.find_var("nu"))[0]) == nu
