"""Thread-safety analyzer + deterministic interleaving harness (ISSUE 18).

Three layers under test:

  1. the lockset lint (`paddle_tpu.analysis.threads`): one planted-defect
     fixture per diagnostic code, compiled into a throwaway package tree
     and analyzed with `analyze_threads(root=...)`;
  2. the clean-tree contract: the shipped `paddle_tpu/` package analyzes
     with zero errors and zero warnings, and THREAD_CATALOG pins both
     directions;
  3. the interleaving harness (`paddle_tpu.testing.interleave`): the
     planted PR 17 drop-count race is found by a seed sweep, replays
     deterministically from the recorded seed, disappears in the fixed
     ordering, and the scheduler can drive a real threaded subsystem.
"""

import os
import textwrap
import threading

import pytest

from paddle_tpu.analysis import threads
from paddle_tpu.testing import (DropCountFixture, explore, run_interleaved)


# ---------------------------------------------------------------------------
# planted-defect fixtures, one per diagnostic code
# ---------------------------------------------------------------------------

def _analyze(tmp_path, sources):
    """Write `sources` ({filename: code}) as a package and lint it."""
    pkg = tmp_path / "pkg"
    pkg.mkdir(exist_ok=True)
    (pkg / "__init__.py").write_text("")
    for name, src in sources.items():
        (pkg / name).write_text(textwrap.dedent(src))
    return threads.analyze_threads(root=str(pkg))


def _codes(report, severity=None):
    return [d.code for d in report.diagnostics
            if severity is None or d.severity == severity]


def test_planted_mixed_guard(tmp_path):
    """A field written under the lock in one method and bare in another
    is the classic lost-update shape; uniformly-bare fields stay quiet."""
    rep = _analyze(tmp_path, {"m.py": """
        import threading

        class Counter:
            def __init__(self):
                self._lock = threading.Lock()
                self.n = 0
                self.tag = ""

            def bump(self):
                with self._lock:
                    self.n += 1

            def reset(self):
                self.n = 0

            def label(self, s):
                self.tag = s
    """})
    hits = [d for d in rep.errors if d.code == "lockset-mixed-guard"]
    assert hits, rep.to_dict()
    assert any("n" in d.message for d in hits), [d.message for d in hits]
    # `tag` is never guarded anywhere -> not a lockset violation
    assert not any("tag" in d.message for d in hits), \
        [d.message for d in hits]


def test_planted_lock_order_cycle(tmp_path):
    rep = _analyze(tmp_path, {"m.py": """
        import threading

        class AB:
            def __init__(self):
                self._a = threading.Lock()
                self._b = threading.Lock()

            def ab(self):
                with self._a:
                    with self._b:
                        pass

            def ba(self):
                with self._b:
                    with self._a:
                        pass
    """})
    assert "lock-order-cycle" in _codes(rep, "error"), rep.to_dict()


def test_planted_blocking_under_lock(tmp_path):
    rep = _analyze(tmp_path, {"m.py": """
        import threading
        import time

        class Sleepy:
            def __init__(self):
                self._lock = threading.Lock()

            def nap(self):
                with self._lock:
                    time.sleep(0.1)
    """})
    hits = [d for d in rep.errors if d.code == "blocking-under-lock"]
    assert hits, rep.to_dict()
    assert any("sleep" in d.message for d in hits), \
        [d.message for d in hits]


def test_planted_unnamed_and_non_daemon_threads(tmp_path):
    rep = _analyze(tmp_path, {"m.py": """
        import threading

        def work():
            pass

        def spawn_anonymous():
            threading.Thread(target=work, daemon=True).start()

        def spawn_non_daemon():
            t = threading.Thread(target=work, name="pd-test-worker")
            t.start()
            t.join()
    """})
    assert "thread-unnamed" in _codes(rep, "error"), rep.to_dict()
    assert "thread-non-daemon" in _codes(rep, "warning"), rep.to_dict()


def test_planted_uncataloged_thread(tmp_path):
    """Any creation site outside THREAD_CATALOG is an error: the census
    is the authoritative inventory of background threads."""
    rep = _analyze(tmp_path, {"m.py": """
        import threading

        def work():
            pass

        def spawn():
            t = threading.Thread(target=work, name="pd-rogue",
                                 daemon=True)
            t.start()
            t.join()
    """})
    assert "thread-uncataloged" in _codes(rep, "error"), rep.to_dict()
    # every site also emits its census info line
    assert "thread-census" in _codes(rep, "info"), rep.to_dict()


def test_planted_never_joined(tmp_path, monkeypatch):
    """Catalog says joined=True but no join site exists in the module."""
    monkeypatch.setitem(
        threads.THREAD_CATALOG, "pd-fixture-worker",
        dict(module="pkg/m.py", daemon=True, joined=True,
             help="planted fixture"))
    rep = _analyze(tmp_path, {"m.py": """
        import threading

        def work():
            pass

        def spawn():
            threading.Thread(target=work, name="pd-fixture-worker",
                             daemon=True).start()
    """})
    assert "thread-never-joined" in _codes(rep, "warning"), rep.to_dict()


def test_planted_catalog_stale_entry(tmp_path, monkeypatch):
    """A catalog entry whose module exists but whose thread is gone."""
    monkeypatch.setitem(
        threads.THREAD_CATALOG, "pd-ghost",
        dict(module="pkg/m.py", daemon=True, joined=False,
             help="planted stale entry"))
    rep = _analyze(tmp_path, {"m.py": """
        def nothing_threaded():
            pass
    """})
    hits = [d for d in rep.errors if d.code == "thread-catalog-stale"]
    assert hits, rep.to_dict()
    assert any("pd-ghost" in d.message for d in hits), \
        [d.message for d in hits]


def test_waiver_comment_suppresses(tmp_path):
    """`# thread-lint: ok <code>` on the flagged line waives exactly
    that code, nothing else."""
    rep = _analyze(tmp_path, {"m.py": """
        import threading

        class Counter:
            def __init__(self):
                self._lock = threading.Lock()
                self.n = 0

            def bump(self):
                with self._lock:
                    self.n += 1

            def peek(self):
                return self.n  # thread-lint: ok lockset-mixed-guard
    """})
    assert "lockset-mixed-guard" not in _codes(rep, "error"), \
        rep.to_dict()


def test_locked_suffix_convention(tmp_path):
    """`*_locked` methods are lint-contracted to run with the class's
    primary lock held: their bare field accesses are guarded accesses."""
    rep = _analyze(tmp_path, {"m.py": """
        import threading

        class Box:
            def __init__(self):
                self._lock = threading.Lock()
                self.v = 0

            def set(self, v):
                with self._lock:
                    self._set_locked(v)

            def _set_locked(self, v):
                self.v = v
    """})
    assert "lockset-mixed-guard" not in _codes(rep, "error"), \
        rep.to_dict()


# ---------------------------------------------------------------------------
# clean-tree contract over the shipped package
# ---------------------------------------------------------------------------

def test_shipped_tree_is_clean():
    """`python -m paddle_tpu analyze --threads` must exit 0: the shipped
    package carries zero lint errors and zero warnings."""
    rep = threads.analyze_threads()
    assert rep.ok, "\n".join(d.format() for d in rep.errors)
    assert not rep.warnings, "\n".join(d.format() for d in rep.warnings)
    # the census itself is non-trivial: the framework owns real threads
    assert len([d for d in rep.infos if d.code == "thread-census"]) >= 8


def test_shipped_catalog_pins_both_directions():
    assert threads.catalog_problems() == []


def test_cli_analyze_threads_exit_code():
    import json as _json
    import subprocess
    import sys
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    out = subprocess.run(
        [sys.executable, "-m", "paddle_tpu", "analyze", "--threads",
         "--json"],
        cwd=repo, capture_output=True, text=True, timeout=120,
        env=dict(os.environ, JAX_PLATFORMS="cpu"))
    assert out.returncode == 0, out.stdout + out.stderr
    payload = _json.loads(out.stdout)
    assert payload["counts"]["error"] == 0, payload


# ---------------------------------------------------------------------------
# interleaving harness: determinism + the planted drop-count race
# ---------------------------------------------------------------------------

def _build_buggy():
    fix = DropCountFixture(buggy=True)
    return fix.workers(), fix.check


def test_harness_finds_planted_drop_count_race():
    """A bounded seed sweep must hit the PR 17 drop-count ordering bug:
    consumer observes the STOP marker before the builder books the
    dropped count."""
    failures = explore(_build_buggy, seeds=range(64))
    assert failures, "no seed exposed the planted race in 64 tries"
    seed, err, res = failures[0]
    assert isinstance(err, AssertionError)
    assert "drop-count race" in str(err)
    assert res.seed == seed and res.steps > 0 and not res.stuck


def test_same_seed_same_schedule_same_failure():
    """Replaying the recorded seed reproduces byte-identical schedules
    and the identical failure — the debugging contract of the harness."""
    failures = explore(_build_buggy, seeds=range(64))
    assert failures
    seed = failures[0][0]

    runs = []
    for _ in range(3):
        fix = DropCountFixture(buggy=True)
        res = run_interleaved(fix.workers(), seed=seed)
        assert res.ok, (res.errors, res.stuck)
        runs.append((res.signature(), fix.observed))

    sigs = {sig for sig, _ in runs}
    obs = {o for _, o in runs}
    assert len(sigs) == 1, "schedule varied across replays of one seed"
    assert len(obs) == 1, f"outcome varied across replays: {obs}"
    # and it is the *failing* outcome every time
    assert obs.pop() != DropCountFixture().remainder


def test_different_seeds_explore_different_schedules():
    sigs = set()
    for seed in range(6):
        fix = DropCountFixture(buggy=True)
        sigs.add(run_interleaved(fix.workers(), seed=seed).signature())
    assert len(sigs) > 1, "scheduler ignored the seed"


def test_fixed_ordering_survives_the_sweep():
    """buggy=False is the shipped count-before-marker ordering; no seed
    in the sweep may falsify it."""
    def build():
        fix = DropCountFixture(buggy=False)
        return fix.workers(), fix.check
    assert explore(build, seeds=range(64), stop_at_first=True) == []


def test_harness_drives_real_telemetry_registry():
    """Schedule two real writers hammering one MetricsRegistry counter:
    whatever interleaving the seed picks, the count must be exact."""
    from paddle_tpu import telemetry

    reg = telemetry.MetricsRegistry()
    c = reg.counter("ilv_test_total", "interleave drive test")

    def writer():
        for _ in range(20):
            c.inc()

    res = run_interleaved([("w0", writer), ("w1", writer)],
                          seed=7, watch=[telemetry])
    assert res.ok, (res.errors, res.stuck)
    assert res.steps > 0
    snap = reg.local_snapshot()["counters"]["ilv_test_total"]
    assert sum(snap.values()) == 40.0, snap


def test_worker_exception_is_captured_not_raised():
    def boom():
        raise RuntimeError("planted")

    res = run_interleaved([("boom", boom)], seed=0)
    assert isinstance(res.first_error(), RuntimeError)
    assert not res.ok


# ---------------------------------------------------------------------------
# regression tests for the real findings fixed in this PR
# ---------------------------------------------------------------------------

def test_step_log_swap_is_safe_and_closes_old(tmp_path):
    """enable_step_log now opens the file before taking _events_lock and
    swaps references under it; re-enabling closes the previous file."""
    from paddle_tpu import telemetry

    p1, p2 = str(tmp_path / "a.jsonl"), str(tmp_path / "b.jsonl")
    telemetry.enable_step_log(p1)
    try:
        first = telemetry._log_file
        telemetry.log_event("test_swap", i=1)
        telemetry.enable_step_log(p2)
        assert first.closed, "old step-log file leaked open"
        assert telemetry.step_log_path() == p2
        telemetry.log_event("test_swap", i=2)
    finally:
        telemetry.disable_step_log()
    assert telemetry.step_log_path() is None
    assert "test_swap" in open(p1).read()
    assert "test_swap" in open(p2).read()


def test_program_label_stable_under_threads():
    """program_label's cache fill is now double-checked under a lock:
    concurrent first calls agree on one label."""
    from paddle_tpu import telemetry

    class P:
        pass

    prog = P()
    out = []

    def worker():
        out.append(telemetry.program_label(prog))

    ts = [threading.Thread(target=worker, daemon=True) for _ in range(8)]
    for t in ts:
        t.start()
    for t in ts:
        t.join()
    assert len(set(out)) == 1, out


def test_sentinel_and_obs_stop_idempotent():
    """Module-level stop() now swaps the singleton out under the lock
    and stops outside it; calling it with nothing running is a no-op."""
    from paddle_tpu import obs_server, sentinel

    sentinel.stop()
    sentinel.stop()
    assert sentinel.active() is None
    obs_server.stop()
    obs_server.stop()
    assert obs_server.active() is None
