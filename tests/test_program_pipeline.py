"""Program-level pipeline parallelism (reference ancestor:
gserver/gradientmachines/ParallelNeuralNetwork.h layer-to-device
assignment; VERDICT r2 missing #2): a Program split at cut vars into
pp=4 stages on the 8-device CPU mesh must train with losses matching
single-device execution exactly (mean-loss microbatching contract)."""

import numpy as np

import paddle_tpu as fluid
from paddle_tpu import executor as executor_mod
from paddle_tpu.parallel.program_pipeline import PipelineTranspiler


def _build_mlp():
    """4-layer MLP regression: three natural cut points."""
    x = fluid.layers.data(name="x", shape=[16], dtype="float32")
    y = fluid.layers.data(name="y", shape=[1], dtype="float32")
    h1 = fluid.layers.fc(input=x, size=32, act="tanh",
                         param_attr=fluid.ParamAttr(name="w1"),
                         bias_attr=fluid.ParamAttr(name="b1"))
    h2 = fluid.layers.fc(input=h1, size=32, act="tanh",
                         param_attr=fluid.ParamAttr(name="w2"),
                         bias_attr=fluid.ParamAttr(name="b2"))
    h3 = fluid.layers.fc(input=h2, size=16, act="tanh",
                         param_attr=fluid.ParamAttr(name="w3"),
                         bias_attr=fluid.ParamAttr(name="b3"))
    pred = fluid.layers.fc(input=h3, size=1,
                           param_attr=fluid.ParamAttr(name="w4"),
                           bias_attr=fluid.ParamAttr(name="b4"))
    loss = fluid.layers.mean(fluid.layers.square_error_cost(pred, y))
    return loss, [h1, h2, h3]


def _batches(steps, bsz=32):
    rng = np.random.RandomState(0)
    w = rng.randn(16, 1).astype(np.float32)
    for _ in range(steps):
        xs = rng.randn(bsz, 16).astype(np.float32)
        yield {"x": xs, "y": np.tanh(xs) @ w}


def _init_weights(scope):
    rng = np.random.RandomState(7)
    shapes = {"w1": (16, 32), "b1": (32,), "w2": (32, 32), "b2": (32,),
              "w3": (32, 16), "b3": (16,), "w4": (16, 1), "b4": (1,)}
    for n, s in shapes.items():
        scope.set_var(n, (rng.randn(*s) * 0.3).astype(np.float32))


class TestProgramPipeline:
    def test_pp4_matches_single_device(self):
        steps = 5

        # single-device oracle
        main_s, startup_s = fluid.Program(), fluid.Program()
        with fluid.program_guard(main_s, startup_s):
            loss_s, _ = _build_mlp()
            fluid.optimizer.SGD(learning_rate=0.1).minimize(loss_s)
        exe = fluid.Executor(fluid.CPUPlace())
        scope_s = executor_mod.Scope()
        oracle = []
        with executor_mod.scope_guard(scope_s):
            exe.run(startup_s)
            _init_weights(scope_s)
            for feed in _batches(steps):
                v, = exe.run(main_s, feed=feed, fetch_list=[loss_s])
                oracle.append(float(np.asarray(v).ravel()[0]))

        # pp=4 pipeline through the transpiler API
        main_p, startup_p = fluid.Program(), fluid.Program()
        with fluid.program_guard(main_p, startup_p):
            loss_p, cuts = _build_mlp()
        t = PipelineTranspiler()
        trainer = t.transpile(
            loss_p, cut_vars=cuts,
            optimizer=lambda: fluid.optimizer.SGD(learning_rate=0.1),
            num_microbatches=4)
        assert len(trainer.stages) == 4
        scope_p = executor_mod.Scope()
        piped = []
        with executor_mod.scope_guard(scope_p):
            trainer.startup(startup_p)
            _init_weights(scope_p)
            for feed in _batches(steps):
                piped.append(trainer.train_step(feed))

        np.testing.assert_allclose(piped, oracle, rtol=2e-4, atol=1e-6)

    def test_stage_partition_is_disjoint_and_placed(self):
        main_p, startup_p = fluid.Program(), fluid.Program()
        with fluid.program_guard(main_p, startup_p):
            loss_p, cuts = _build_mlp()
        trainer = PipelineTranspiler().transpile(
            loss_p, cut_vars=cuts,
            optimizer=lambda: fluid.optimizer.SGD(learning_rate=0.1),
            num_microbatches=2)
        own = [set(s.param_names) for s in trainer.stages]
        for i in range(len(own)):
            for j in range(i + 1, len(own)):
                assert not (own[i] & own[j]), (own[i], own[j])
        assert set().union(*own) == {"w1", "b1", "w2", "b2",
                                     "w3", "b3", "w4", "b4"}
        # stages sit on distinct devices of the virtual mesh
        places = {s.place.device_id for s in trainer.stages}
        assert len(places) == 4

    def test_skip_connection_across_cut_rejected(self):
        import pytest
        main_p, startup_p = fluid.Program(), fluid.Program()
        with fluid.program_guard(main_p, startup_p):
            x = fluid.layers.data(name="x", shape=[8], dtype="float32")
            y = fluid.layers.data(name="y", shape=[1], dtype="float32")
            h1 = fluid.layers.fc(input=x, size=8, act="tanh",
                                 param_attr=fluid.ParamAttr(name="sw1"))
            h2 = fluid.layers.fc(input=h1, size=8, act="tanh",
                                 param_attr=fluid.ParamAttr(name="sw2"))
            # skip: h1 feeds past the h2 cut
            h3 = fluid.layers.elementwise_add(
                fluid.layers.fc(input=h2, size=8,
                                param_attr=fluid.ParamAttr(name="sw3")), h1)
            pred = fluid.layers.fc(input=h3, size=1,
                                   param_attr=fluid.ParamAttr(name="sw4"))
            loss = fluid.layers.mean(fluid.layers.square_error_cost(pred, y))
        with pytest.raises(ValueError, match="separate the graph"):
            PipelineTranspiler().transpile(
                loss, cut_vars=[h2],
                optimizer=lambda: fluid.optimizer.SGD(learning_rate=0.1),
                num_microbatches=2)

    def test_regularization_matches_single_device(self):
        steps = 3
        reg = fluid.regularizer.L2Decay(1e-3)

        main_s, startup_s = fluid.Program(), fluid.Program()
        with fluid.program_guard(main_s, startup_s):
            loss_s, _ = _build_mlp()
            fluid.optimizer.SGD(learning_rate=0.1,
                                regularization=reg).minimize(loss_s)
        exe = fluid.Executor(fluid.CPUPlace())
        scope_s = executor_mod.Scope()
        oracle = []
        with executor_mod.scope_guard(scope_s):
            exe.run(startup_s)
            _init_weights(scope_s)
            for feed in _batches(steps):
                v, = exe.run(main_s, feed=feed, fetch_list=[loss_s])
                oracle.append(float(np.asarray(v).ravel()[0]))

        main_p, startup_p = fluid.Program(), fluid.Program()
        with fluid.program_guard(main_p, startup_p):
            loss_p, cuts = _build_mlp()
        trainer = PipelineTranspiler().transpile(
            loss_p, cut_vars=cuts,
            optimizer=lambda: fluid.optimizer.SGD(learning_rate=0.1,
                                                  regularization=reg),
            num_microbatches=4)
        scope_p = executor_mod.Scope()
        piped = []
        with executor_mod.scope_guard(scope_p):
            trainer.startup(startup_p)
            _init_weights(scope_p)
            for feed in _batches(steps):
                piped.append(trainer.train_step(feed))
        np.testing.assert_allclose(piped, oracle, rtol=2e-4, atol=1e-6)
