"""Test harness config: run on a virtual 8-device CPU platform so sharding
paths are exercised without TPU hardware (SURVEY.md §4.1 TPU-build
translation)."""

import os

os.environ.setdefault("JAX_PLATFORMS", "cpu")
xla_flags = os.environ.get("XLA_FLAGS", "")
if "host_platform_device_count" not in xla_flags:
    os.environ["XLA_FLAGS"] = (
        xla_flags + " --xla_force_host_platform_device_count=8").strip()

import numpy as np  # noqa: E402
import pytest  # noqa: E402


@pytest.fixture(autouse=True)
def _fresh_programs():
    """Each test gets fresh default programs, scope and name generator."""
    import paddle_tpu as fluid
    from paddle_tpu.framework import unique_name
    from paddle_tpu import executor as executor_mod

    main, startup = fluid.Program(), fluid.Program()
    old_main = fluid.switch_main_program(main)
    old_startup = fluid.switch_startup_program(startup)
    gen = unique_name.switch()
    old_scope = executor_mod._scope_stack[:]
    executor_mod._scope_stack[:] = [executor_mod.Scope()]
    yield
    fluid.switch_main_program(old_main)
    fluid.switch_startup_program(old_startup)
    unique_name.switch(gen)
    executor_mod._scope_stack[:] = old_scope
