"""Test harness config: run on a virtual 8-device CPU platform so sharding
paths are exercised without TPU hardware (SURVEY.md §4.1 TPU-build
translation)."""

import os

# Force the CPU platform even when the ambient environment points jax at an
# accelerator (e.g. JAX_PLATFORMS=axon): the suite's multi-device tests need
# the 8 virtual host devices, and a setdefault would silently leave them on
# one real chip. Override with PADDLE_TPU_TEST_PLATFORM to run elsewhere.
# jax may be preloaded by the environment, in which case JAX_PLATFORMS was
# already read at import time — jax.config.update is the reliable path;
# XLA_FLAGS is read later, at backend init, so the env var suffices for it.
_platform = os.environ.get("PADDLE_TPU_TEST_PLATFORM", "cpu")
os.environ["JAX_PLATFORMS"] = _platform
xla_flags = os.environ.get("XLA_FLAGS", "")
if "host_platform_device_count" not in xla_flags:
    os.environ["XLA_FLAGS"] = (
        xla_flags + " --xla_force_host_platform_device_count=8").strip()

import jax  # noqa: E402

jax.config.update("jax_platforms", _platform)

# Persistent XLA compilation cache: the suite compiles ~100 distinct
# programs (book chapters dominate); caching them across runs cuts warm
# wall time substantially on the 1-core CI box. Repo-local dir, gitignored.
_cache_dir = os.environ.get(
    "PADDLE_TPU_XLA_CACHE",
    os.path.join(os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
                 ".xla_cache"))
if _cache_dir:
    try:
        jax.config.update("jax_compilation_cache_dir", _cache_dir)
        jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.5)
    except Exception:
        pass

import numpy as np  # noqa: E402
import pytest  # noqa: E402


@pytest.fixture(autouse=True)
def _fresh_programs():
    """Each test gets fresh default programs, scope and name generator."""
    import paddle_tpu as fluid
    from paddle_tpu.framework import unique_name
    from paddle_tpu import executor as executor_mod

    main, startup = fluid.Program(), fluid.Program()
    old_main = fluid.switch_main_program(main)
    old_startup = fluid.switch_startup_program(startup)
    gen = unique_name.switch()
    old_scope = executor_mod._scope_stack[:]
    executor_mod._scope_stack[:] = [executor_mod.Scope()]
    yield
    fluid.switch_main_program(old_main)
    fluid.switch_startup_program(old_startup)
    unique_name.switch(gen)
    executor_mod._scope_stack[:] = old_scope
