"""Op correctness + grad checks for the math op corpus
(reference: tests/unittests/test_mul_op.py, test_elementwise_*_op.py,
test_activation_op.py, test_reduce_op.py, test_sum_op.py …)."""

import numpy as np
import pytest

from op_test import OpTest


class TestMulOp(OpTest):
    op_type = "mul"

    def setup(self):
        rng = np.random.RandomState(1)
        x = rng.uniform(-1, 1, (4, 5)).astype(np.float32)
        y = rng.uniform(-1, 1, (5, 3)).astype(np.float32)
        self.inputs = {"X": x, "Y": y}
        self.attrs = {"x_num_col_dims": 1, "y_num_col_dims": 1}
        self.outputs = {"Out": x @ y}

    def test_output(self):
        self.setup()
        self.check_output()

    def test_grad(self):
        self.setup()
        self.check_grad(["X", "Y"], "Out")


class TestMulOpFlatten(OpTest):
    op_type = "mul"

    def setup(self):
        rng = np.random.RandomState(2)
        x = rng.uniform(-1, 1, (2, 3, 4)).astype(np.float32)
        y = rng.uniform(-1, 1, (4, 6)).astype(np.float32)
        self.inputs = {"X": x, "Y": y}
        self.attrs = {"x_num_col_dims": 2, "y_num_col_dims": 1}
        self.outputs = {"Out": (x.reshape(6, 4) @ y).reshape(2, 3, 6)}

    def test_output(self):
        self.setup()
        self.check_output()

    def test_grad(self):
        self.setup()
        self.check_grad(["X", "Y"], "Out")


class TestMatmulTranspose(OpTest):
    op_type = "matmul"

    def setup(self):
        rng = np.random.RandomState(3)
        x = rng.uniform(-1, 1, (5, 4)).astype(np.float32)
        y = rng.uniform(-1, 1, (3, 5)).astype(np.float32)
        self.inputs = {"X": x, "Y": y}
        self.attrs = {"transpose_X": True, "transpose_Y": True}
        self.outputs = {"Out": x.T @ y.T}

    def test_output(self):
        self.setup()
        self.check_output()

    def test_grad(self):
        self.setup()
        self.check_grad(["X", "Y"], "Out")


@pytest.mark.parametrize("op,fn", [
    ("elementwise_add", np.add), ("elementwise_sub", np.subtract),
    ("elementwise_mul", np.multiply), ("elementwise_div", np.divide),
    ("elementwise_max", np.maximum), ("elementwise_min", np.minimum),
])
def test_elementwise_same_shape(op, fn):
    rng = np.random.RandomState(4)
    x = rng.uniform(0.5, 2, (3, 4)).astype(np.float32)
    y = rng.uniform(0.5, 2, (3, 4)).astype(np.float32)

    class T(OpTest):
        pass
    t = T()
    t.op_type = op
    t.inputs = {"X": x, "Y": y}
    t.attrs = {}
    t.outputs = {"Out": fn(x, y)}
    t.check_output()
    t.check_grad(["X", "Y"], "Out", max_relative_error=0.02)


def test_elementwise_add_broadcast_axis():
    rng = np.random.RandomState(5)
    x = rng.uniform(-1, 1, (2, 3, 4)).astype(np.float32)
    y = rng.uniform(-1, 1, (3,)).astype(np.float32)

    class T(OpTest):
        pass
    t = T()
    t.op_type = "elementwise_add"
    t.inputs = {"X": x, "Y": y}
    t.attrs = {"axis": 1}
    t.outputs = {"Out": x + y.reshape(1, 3, 1)}
    t.check_output()
    t.check_grad(["X", "Y"], "Out", max_relative_error=0.02)


_ACT_CASES = {
    "sigmoid": lambda x: 1 / (1 + np.exp(-x)),
    "tanh": np.tanh,
    "relu": lambda x: np.maximum(x, 0),
    "exp": np.exp,
    "log": np.log,
    "sqrt": np.sqrt,
    "square": np.square,
    "abs": np.abs,
    "reciprocal": lambda x: 1 / x,
    "softplus": lambda x: np.log1p(np.exp(x)),
    "softsign": lambda x: x / (1 + np.abs(x)),
}


@pytest.mark.parametrize("act", sorted(_ACT_CASES))
def test_activation(act):
    rng = np.random.RandomState(6)
    # keep away from non-differentiable points / domain edges
    x = rng.uniform(0.2, 1.5, (3, 5)).astype(np.float32)

    class T(OpTest):
        pass
    t = T()
    t.op_type = act
    t.inputs = {"X": x}
    t.attrs = {}
    t.outputs = {"Out": _ACT_CASES[act](x.astype(np.float64)).astype(
        np.float32)}
    t.check_output(atol=1e-5)
    t.check_grad(["X"], "Out", max_relative_error=0.02)


@pytest.mark.parametrize("op,fn", [
    ("reduce_sum", np.sum), ("reduce_mean", np.mean), ("reduce_max", np.max),
])
@pytest.mark.parametrize("dim,keep", [([0], False), ([1], True), (None, False)])
def test_reduce(op, fn, dim, keep):
    rng = np.random.RandomState(7)
    x = rng.uniform(-1, 1, (4, 6)).astype(np.float32)

    class T(OpTest):
        pass
    t = T()
    t.op_type = op
    t.inputs = {"X": x}
    reduce_all = dim is None
    t.attrs = {"dim": dim or [0], "keep_dim": keep, "reduce_all": reduce_all}
    if reduce_all:
        want = np.asarray([fn(x)])
    else:
        want = fn(x, axis=tuple(dim), keepdims=keep)
    t.outputs = {"Out": want.astype(np.float32)}
    t.check_output()
    if op != "reduce_max":
        t.check_grad(["X"], "Out", max_relative_error=0.02)


def test_sum_multi_input():
    rng = np.random.RandomState(8)
    a = rng.uniform(-1, 1, (3, 4)).astype(np.float32)
    b = rng.uniform(-1, 1, (3, 4)).astype(np.float32)
    c = rng.uniform(-1, 1, (3, 4)).astype(np.float32)

    class T(OpTest):
        pass
    t = T()
    t.op_type = "sum"
    t.inputs = {"X": [("x0", a), ("x1", b), ("x2", c)]}
    t.attrs = {}
    t.outputs = {"Out": a + b + c}
    t.check_output()
    t.check_grad(["x0", "x1", "x2"], "Out", max_relative_error=0.02)


def test_mean():
    rng = np.random.RandomState(9)
    x = rng.uniform(-1, 1, (5, 7)).astype(np.float32)

    class T(OpTest):
        pass
    t = T()
    t.op_type = "mean"
    t.inputs = {"X": x}
    t.outputs = {"Out": np.asarray([x.mean()], dtype=np.float32)}
    t.check_output()
    t.check_grad(["X"], "Out", max_relative_error=0.02)


def test_concat_and_grad():
    rng = np.random.RandomState(10)
    a = rng.uniform(-1, 1, (2, 3)).astype(np.float32)
    b = rng.uniform(-1, 1, (2, 5)).astype(np.float32)

    class T(OpTest):
        pass
    t = T()
    t.op_type = "concat"
    t.inputs = {"X": [("a", a), ("b", b)]}
    t.attrs = {"axis": 1}
    t.outputs = {"Out": np.concatenate([a, b], axis=1)}
    t.check_output()
    t.check_grad(["a", "b"], "Out", max_relative_error=0.02)


def test_scale():
    x = np.arange(6, dtype=np.float32).reshape(2, 3)

    class T(OpTest):
        pass
    t = T()
    t.op_type = "scale"
    t.inputs = {"X": x}
    t.attrs = {"scale": 2.5, "bias": 1.0}
    t.outputs = {"Out": x * 2.5 + 1.0}
    t.check_output()
    t.check_grad(["X"], "Out", max_relative_error=0.02)


def test_reshape_transpose():
    x = np.arange(24, dtype=np.float32).reshape(2, 3, 4)

    class TR(OpTest):
        pass
    t = TR()
    t.op_type = "reshape"
    t.inputs = {"X": x}
    t.attrs = {"shape": [2, 12]}
    t.outputs = {"Out": x.reshape(2, 12)}
    t.check_output()
    t.check_grad(["X"], "Out", max_relative_error=0.02)

    t2 = TR()
    t2.op_type = "transpose"
    t2.inputs = {"X": x}
    t2.attrs = {"axis": [1, 0, 2]}
    t2.outputs = {"Out": x.transpose(1, 0, 2)}
    t2.check_output()
    t2.check_grad(["X"], "Out", max_relative_error=0.02)
