"""Beyond-HBM embedding tables (ISSUE 14): device hot-row cache over a
host-DRAM authoritative store. The contract under test: with the table
bigger than the device budget, training through the cache is NUMERICALLY
IDENTICAL to the all-HBM path — bitwise for sgd/momentum, tolerance for
adam — because feed-time id→slot remapping is elementwise and the
scatter-apply kernels (PR 10) run unmodified against the slab. Plus the
residency machinery itself: LRU-with-frequency eviction, occurrence-
weighted hit/miss counting with the compulsory/capacity split,
prefetch's count-later protocol, checkpoint flush ordering, the
read-only serving variant, and enable()'s soundness validations."""

import numpy as np
import pytest

import paddle_tpu as fluid
from paddle_tpu.parallel import emb_cache

ROWS, DIM, BSZ = 120, 8, 16


def _build(opt, rows=ROWS):
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        ids = fluid.layers.data(name="ids", shape=[1], dtype="int64")
        lab = fluid.layers.data(name="lab", shape=[1], dtype="float32")
        emb = fluid.layers.embedding(
            input=ids, size=[rows, DIM], is_sparse=True,
            param_attr=fluid.ParamAttr(name="emb_w"))
        pred = fluid.layers.fc(input=emb, size=1,
                               param_attr=fluid.ParamAttr(name="fc_w"))
        loss = fluid.layers.mean(
            fluid.layers.square_error_cost(pred, lab))
        opt().minimize(loss)
    return main, startup, loss, pred


def _batches(n, rows=ROWS, seed=0):
    rng = np.random.default_rng(seed)
    return [(rng.integers(0, rows, (BSZ, 1)).astype(np.int64),
             rng.standard_normal((BSZ, 1)).astype(np.float32))
            for _ in range(n)]


def _train(opt, cache_rows, data):
    """One full run in its own scope/name universe; returns (losses,
    final table). cache_rows=None is the all-HBM reference."""
    scope = fluid.Scope()
    with fluid.scope_guard(scope):
        with fluid.unique_name.guard():
            main, startup, loss, _ = _build(opt)
        exe = fluid.Executor(fluid.CPUPlace())
        exe.run(startup)
        cache = None
        if cache_rows:
            cache = emb_cache.enable(main, tables={"emb_w": cache_rows})
            assert cache is not None
        losses = []
        for ids, lab in data:
            lv, = exe.run(main, feed={"ids": ids, "lab": lab},
                          fetch_list=[loss])
            losses.append(float(np.ravel(lv)[0]))
        if cache:
            cache.flush()
            w = np.array(cache.host_value("emb_w"))
        else:
            w = np.array(scope.find_var("emb_w"))
    return np.asarray(losses, np.float32), w


class TestParity:
    """Cached-vs-dense numerics with rows > cache_rows, so the run
    crosses real evictions (the uniform draws touch most of the table
    while the slab holds less than half of it)."""

    def test_sgd_bitwise(self):
        data = _batches(10, seed=0)
        opt = lambda: fluid.optimizer.SGD(learning_rate=0.1)
        l0, w0 = _train(opt, None, data)
        l1, w1 = _train(opt, 48, data)
        np.testing.assert_array_equal(l0, l1)
        np.testing.assert_array_equal(w0, w1)

    def test_momentum_bitwise(self):
        data = _batches(10, seed=1)
        opt = lambda: fluid.optimizer.Momentum(learning_rate=0.1,
                                               momentum=0.9)
        l0, w0 = _train(opt, None, data)
        l1, w1 = _train(opt, 48, data)
        np.testing.assert_array_equal(l0, l1)
        np.testing.assert_array_equal(w0, w1)
        # the velocity accumulator rides along as a cached slab
        # (state_names beyond the param itself)

    def test_adam_windowed_with_checkpoint(self, tmp_path):
        """The full training shape: run_steps fused windows fed by a
        DoubleBufferedFeeder, a save/load_persistables round-trip at
        the midpoint (save must flush dirty slots FIRST and checkpoint
        the host slab, restore must invalidate residency), adam
        accumulators cached alongside the param. Tolerance, not
        bitwise: adam's per-element update math reassociates."""
        from paddle_tpu.reader.pipeline import DoubleBufferedFeeder

        data = _batches(16, seed=2)
        opt = lambda: fluid.optimizer.Adam(learning_rate=0.01)

        def run(cache_rows, ckpt_dir):
            scope = fluid.Scope()
            with fluid.scope_guard(scope):
                with fluid.unique_name.guard():
                    main, startup, loss, _ = _build(opt)
                exe = fluid.Executor(fluid.CPUPlace())
                exe.run(startup)
                cache = None
                if cache_rows:
                    cache = emb_cache.enable(
                        main, tables={"emb_w": cache_rows})

                def train(lo, hi):
                    f = DoubleBufferedFeeder(
                        lambda: ({"ids": i, "lab": l}
                                 for i, l in data[lo:hi]),
                        window_prefetch=2)
                    out = []
                    try:
                        while True:
                            o = exe.run_steps(
                                main, reader=f, steps=4,
                                fetch_list=[loss], fetch_mode="stack")
                            out.extend(np.asarray(o[0]).ravel().tolist())
                    except StopIteration:
                        pass
                    finally:
                        f.stop()
                    return out

                losses = train(0, 8)
                fluid.io.save_persistables(exe, str(ckpt_dir), main)
                fluid.io.load_persistables(exe, str(ckpt_dir), main)
                losses += train(8, 16)
                if cache:
                    cache.flush()
                    w = np.array(cache.host_value("emb_w"))
                    assert len(
                        cache.tables()["emb_w"].state_names) == 3
                else:
                    w = np.array(scope.find_var("emb_w"))
            return np.asarray(losses), w

        l0, w0 = run(None, tmp_path / "dense")
        # 64 holds a 4-batch window's id union (~52 uniques) but not
        # the 120-row table: windows still evict each other's rows
        l1, w1 = run(64, tmp_path / "cached")
        assert l0.size == l1.size == 16
        np.testing.assert_allclose(l1, l0, rtol=1e-5, atol=1e-6)
        np.testing.assert_allclose(w1, w0, rtol=1e-5, atol=1e-6)


class TestResidency:
    """The map/eviction machinery driven directly via prepare_feed on
    a tiny enabled program — no training, just residency transitions."""

    def _cache(self, cache_rows=3, rows=6):
        self.scope = fluid.Scope()
        self._guard = fluid.scope_guard(self.scope)
        self._guard.__enter__()
        try:
            with fluid.unique_name.guard():
                main, startup, _, _ = _build(
                    lambda: fluid.optimizer.SGD(learning_rate=0.1),
                    rows=rows)
            exe = fluid.Executor(fluid.CPUPlace())
            exe.run(startup)
            return emb_cache.enable(main,
                                    tables={"emb_w": cache_rows})
        finally:
            self._guard.__exit__(None, None, None)

    def _feed(self, cache, ids):
        return cache.prepare_feed(
            {"ids": np.asarray(ids, np.int64).reshape(-1, 1)})

    def test_counting_and_compulsory_split(self):
        c = self._cache()
        # occurrence-weighted: id 0 appears twice -> 2 misses, not 1
        self._feed(c, [0, 0, 1, 2])
        s = c.stats()
        assert (s["hits"], s["misses"]) == (0, 4)
        assert s["compulsory_misses"] == 4       # all first-ever touch
        # 0,1 hit; 3 is a first touch -> compulsory miss; full cache
        # means 3 evicts someone
        self._feed(c, [0, 1, 3])
        s = c.stats()
        assert (s["hits"], s["misses"]) == (2, 5)
        assert s["compulsory_misses"] == 5
        assert s["evictions"] == 1
        # 2 was the eviction victim; re-touching it is the CAPACITY
        # miss — the only kind an eviction-policy gate may count
        t = c.tables()["emb_w"]
        assert t.id2slot[2] == -1
        self._feed(c, [2])
        s = c.stats()
        assert s["misses"] == 6
        assert s["compulsory_misses"] == 5       # unchanged: seen before

    def test_lru_freq_victim_choice(self):
        c = self._cache()
        self._feed(c, [0, 0, 1, 2])   # same tick: freq 0:2, 1:1, 2:1
        self._feed(c, [1])            # 1 most recent
        # victim must be 2: among {0, 2} (LRU ties at tick 1), the
        # frequency tiebreak keeps the hotter row 0
        self._feed(c, [3])
        t = c.tables()["emb_w"]
        assert t.id2slot[2] == -1
        assert t.id2slot[0] >= 0 and t.id2slot[1] >= 0

    def test_remap_matches_slots_and_marks_dirty(self):
        c = self._cache()
        out = self._feed(c, [4, 1, 4])
        t = c.tables()["emb_w"]
        np.testing.assert_array_equal(
            out["ids"].ravel(), t.id2slot[[4, 1, 4]])
        assert out["ids"].dtype == np.int64     # dtype preserved
        assert t.dirty[t.id2slot[[4, 1]]].all()

    def test_window_union_must_fit(self):
        c = self._cache(cache_rows=3)
        with pytest.raises(RuntimeError, match="window union must fit"):
            self._feed(c, [0, 1, 2, 3])

    def test_out_of_range_ids_rejected(self):
        c = self._cache(rows=6)
        with pytest.raises(ValueError, match="out of range"):
            self._feed(c, [0, 6])

    def test_flush_writes_host_and_clears_dirty(self):
        c = self._cache()
        self._feed(c, [0, 1])
        n = c.flush()
        t = c.tables()["emb_w"]
        # param + sgd has no accumulator -> 2 rows x dim x 4 bytes
        assert n == 2 * DIM * 4 * len(t.state_names)
        assert not t.dirty.any()
        assert c.flush() == 0                    # idempotent


class TestPrefetch:
    def _setup(self, cache_rows=48):
        scope = fluid.Scope()
        guard = fluid.scope_guard(scope)
        guard.__enter__()
        try:
            with fluid.unique_name.guard():
                main, startup, _, _ = _build(
                    lambda: fluid.optimizer.SGD(learning_rate=0.1))
            exe = fluid.Executor(fluid.CPUPlace())
            exe.run(startup)
            return emb_cache.enable(main,
                                    tables={"emb_w": cache_rows})
        finally:
            guard.__exit__(None, None, None)

    def test_prefetched_rows_still_count_as_misses(self):
        """The count-later protocol: prefetch stages silently
        (count=False), the consuming prepare_feed charges the staged
        rows as misses — they are transfer traffic whether or not the
        latency was hidden. Hit/miss totals must be IDENTICAL to the
        unprefetched run of the same feed."""
        c = self._setup()
        ids = np.array([[3], [5], [3], [9]], np.int64)
        c.prefetch({"ids": np.unique(ids)}).wait()
        s = c.stats()
        assert (s["hits"], s["misses"]) == (0, 0)
        t = c.tables()["emb_w"]
        assert (t.id2slot[[3, 5, 9]] >= 0).all()   # already resident
        c.prepare_feed({"ids": ids})
        s = c.stats()
        assert (s["hits"], s["misses"]) == (0, 4)
        assert s["compulsory_misses"] == 4
        # second touch of the same ids: genuine hits
        c.prepare_feed({"ids": ids})
        assert c.stats()["hits"] == 4

    def test_partial_coverage_prefetch_is_discarded(self):
        c = self._setup()
        c.prefetch({"ids": np.array([1, 2])}).wait()
        # the feed touches an id the prefetch never saw -> fall back to
        # counting from the live map (1, 2 are resident -> hits)
        c.prepare_feed({"ids": np.array([[1], [2], [7]], np.int64)})
        s = c.stats()
        assert (s["hits"], s["misses"]) == (2, 1)

    def test_overlap_accounting(self):
        c = self._setup()
        h = c.prefetch({"ids": np.arange(16)})
        h.wait()
        s = c.stats()
        assert s["prefetch_seconds"] > 0
        assert 0.0 <= s["overlap_fraction"] <= 1.0


class TestEnableValidation:
    def _prog(self, **emb_kw):
        main, startup = fluid.Program(), fluid.Program()
        with fluid.program_guard(main, startup):
            ids = fluid.layers.data(name="ids", shape=[1],
                                    dtype="int64")
            lab = fluid.layers.data(name="lab", shape=[1],
                                    dtype="float32")
            emb = fluid.layers.embedding(
                input=ids, size=[ROWS, DIM],
                param_attr=fluid.ParamAttr(name="emb_w"), **emb_kw)
            pred = fluid.layers.fc(input=emb, size=1)
            loss = fluid.layers.mean(
                fluid.layers.square_error_cost(pred, lab))
            fluid.optimizer.SGD(learning_rate=0.1).minimize(loss)
        return main, startup

    def test_dense_gradient_rejected(self):
        with fluid.unique_name.guard():
            main, _ = self._prog(is_sparse=False)
        with pytest.raises(ValueError, match="is_sparse=False"):
            emb_cache.enable(main, tables={"emb_w": 32})

    def test_padding_idx_rejected(self):
        with fluid.unique_name.guard():
            main, _ = self._prog(is_sparse=True, padding_idx=0)
        with pytest.raises(ValueError, match="padding_idx"):
            emb_cache.enable(main, tables={"emb_w": 32})

    def test_kill_switch(self, monkeypatch):
        monkeypatch.setenv("PADDLE_TPU_EMB_CACHE", "0")
        with fluid.unique_name.guard():
            main, _ = self._prog(is_sparse=True)
        assert emb_cache.enable(main, tables={"emb_w": 32}) is None

    def test_table_fitting_in_budget_stays_uncached(self):
        scope = fluid.Scope()
        with fluid.scope_guard(scope):
            with fluid.unique_name.guard():
                main, startup = self._prog(is_sparse=True)
            exe = fluid.Executor(fluid.CPUPlace())
            exe.run(startup)
            # budget covers the whole table: caching would only add
            # remap overhead, enable() declines
            assert emb_cache.enable(
                main, budget_bytes=ROWS * DIM * 4 * 8) is None

    def test_layer_cache_rows_request_routes_to_enable(self):
        scope = fluid.Scope()
        with fluid.scope_guard(scope):
            with fluid.unique_name.guard():
                main, startup = self._prog(is_sparse=True,
                                           cache_rows=40)
            exe = fluid.Executor(fluid.CPUPlace())
            exe.run(startup)
            c = emb_cache.enable(main)
            assert c is not None
            assert c.tables()["emb_w"].cache_rows == 40
            # device slab really is budget-shaped now
            assert np.asarray(
                scope.find_var("emb_w")).shape == (40, DIM)


class TestServing:
    def test_read_only_cache_parity_and_hits(self, tmp_path):
        from paddle_tpu.serving import ServingEngine

        rng = np.random.default_rng(3)
        data = _batches(6, seed=3)
        scope = fluid.Scope()
        with fluid.scope_guard(scope):
            with fluid.unique_name.guard():
                main, startup, loss, pred = _build(
                    lambda: fluid.optimizer.SGD(learning_rate=0.1))
            exe = fluid.Executor(fluid.CPUPlace())
            exe.run(startup)
            cache = emb_cache.enable(main, tables={"emb_w": 48})
            for ids, lab in data:
                exe.run(main, feed={"ids": ids, "lab": lab},
                        fetch_list=[loss])
            # export: save flushes dirty slots and checkpoints the
            # FULL host table, so the engine sees [rows, dim]
            fluid.io.save_inference_model(
                str(tmp_path), ["ids"], [pred], exe, main)

        eng0 = ServingEngine(str(tmp_path))
        eng1 = ServingEngine(str(tmp_path),
                             emb_cache_budget_bytes=48 * DIM * 4)
        assert eng1.stats()["emb_cache"]["tables"]["emb_w"][
            "cache_rows"] == 48
        q = rng.integers(0, ROWS, (8, 1)).astype(np.int64)
        (a0,), (a1,) = eng0.run_batch({"ids": q}), eng1.run_batch(
            {"ids": q})
        np.testing.assert_array_equal(np.asarray(a0), np.asarray(a1))
        # repeat ids: the read-only cache must register hits and never
        # dirty a slot (no flush path at inference)
        eng1.run_batch({"ids": q})
        st = eng1.stats()["emb_cache"]
        assert st["hits"] > 0
        assert st["flush_bytes"] == 0
