"""Correctness + grad checks for conv/pool/norm/softmax/loss/embedding ops
(reference: tests/unittests/test_conv2d_op.py, test_pool2d_op.py,
test_batch_norm_op.py, test_layer_norm_op.py, test_softmax_op.py,
test_cross_entropy_op.py, test_lookup_table_op.py …)."""

import numpy as np
import pytest

from op_test import OpTest


def _ref_conv2d(x, w, stride, pad):
    n, c, h, wd = x.shape
    co, ci, kh, kw = w.shape
    oh = (h + 2 * pad - kh) // stride + 1
    ow = (wd + 2 * pad - kw) // stride + 1
    xp = np.pad(x, ((0, 0), (0, 0), (pad, pad), (pad, pad)))
    out = np.zeros((n, co, oh, ow), dtype=np.float64)
    for i in range(oh):
        for j in range(ow):
            patch = xp[:, :, i * stride:i * stride + kh,
                       j * stride:j * stride + kw]
            out[:, :, i, j] = np.tensordot(patch, w, axes=([1, 2, 3],
                                                           [1, 2, 3]))
    return out


class TestConv2d(OpTest):
    op_type = "conv2d"

    def setup(self, stride=1, pad=1):
        rng = np.random.RandomState(11)
        x = rng.uniform(-1, 1, (2, 3, 6, 6)).astype(np.float32)
        w = rng.uniform(-1, 1, (4, 3, 3, 3)).astype(np.float32)
        self.inputs = {"Input": x, "Filter": w}
        self.attrs = {"strides": [stride, stride], "paddings": [pad, pad],
                      "dilations": [1, 1], "groups": 1}
        self.outputs = {"Output": _ref_conv2d(
            x.astype(np.float64), w.astype(np.float64), stride,
            pad).astype(np.float32)}

    def test_output(self):
        self.setup()
        self.check_output(atol=1e-4)

    def test_output_stride2(self):
        self.setup(stride=2, pad=0)
        self.check_output(atol=1e-4)

    def test_grad(self):
        self.setup()
        self.check_grad(["Input", "Filter"], "Output",
                        max_relative_error=0.03)


class TestPool2d(OpTest):
    op_type = "pool2d"

    def _ref_pool(self, x, k, s, ptype):
        n, c, h, w = x.shape
        oh = (h - k) // s + 1
        ow = (w - k) // s + 1
        out = np.zeros((n, c, oh, ow), dtype=x.dtype)
        for i in range(oh):
            for j in range(ow):
                win = x[:, :, i * s:i * s + k, j * s:j * s + k]
                out[:, :, i, j] = win.max((2, 3)) if ptype == "max" \
                    else win.mean((2, 3))
        return out

    def setup(self, ptype="max"):
        rng = np.random.RandomState(12)
        x = rng.uniform(-1, 1, (2, 3, 6, 6)).astype(np.float32)
        self.inputs = {"X": x}
        self.attrs = {"pooling_type": ptype, "ksize": [2, 2],
                      "strides": [2, 2], "paddings": [0, 0],
                      "global_pooling": False}
        self.outputs = {"Out": self._ref_pool(x, 2, 2, ptype)}

    def test_max(self):
        self.setup("max")
        self.check_output()

    def test_avg(self):
        self.setup("avg")
        self.check_output()

    def test_avg_grad(self):
        self.setup("avg")
        self.check_grad(["X"], "Out", max_relative_error=0.02)


class TestSoftmax(OpTest):
    op_type = "softmax"

    def setup(self):
        rng = np.random.RandomState(13)
        x = rng.uniform(-2, 2, (5, 7)).astype(np.float32)
        e = np.exp(x - x.max(-1, keepdims=True))
        self.inputs = {"X": x}
        self.outputs = {"Out": (e / e.sum(-1, keepdims=True)).astype(
            np.float32)}

    def test_output(self):
        self.setup()
        self.check_output()

    def test_grad(self):
        self.setup()
        self.check_grad(["X"], "Out", max_relative_error=0.02)


class TestCrossEntropy(OpTest):
    op_type = "cross_entropy"

    def setup(self):
        rng = np.random.RandomState(14)
        logits = rng.uniform(0.1, 1.0, (6, 4)).astype(np.float32)
        probs = logits / logits.sum(-1, keepdims=True)
        label = rng.randint(0, 4, (6, 1)).astype(np.int64)
        loss = -np.log(probs[np.arange(6), label.ravel()]).reshape(6, 1)
        self.inputs = {"X": probs, "Label": label}
        self.outputs = {"Y": loss.astype(np.float32)}

    def test_output(self):
        self.setup()
        self.check_output()

    def test_grad(self):
        self.setup()
        self.check_grad(["X"], "Y", max_relative_error=0.05,
                        no_grad_set={"Label"})


class TestSoftmaxWithCE(OpTest):
    op_type = "softmax_with_cross_entropy"

    def setup(self):
        rng = np.random.RandomState(15)
        logits = rng.uniform(-2, 2, (6, 5)).astype(np.float32)
        label = rng.randint(0, 5, (6, 1)).astype(np.int64)
        e = np.exp(logits - logits.max(-1, keepdims=True))
        sm = e / e.sum(-1, keepdims=True)
        loss = -np.log(sm[np.arange(6), label.ravel()]).reshape(6, 1)
        self.inputs = {"Logits": logits, "Label": label}
        self.outputs = {"Softmax": sm.astype(np.float32),
                        "Loss": loss.astype(np.float32)}

    def test_output(self):
        self.setup()
        self.check_output(atol=1e-5)

    def test_grad(self):
        self.setup()
        self.check_grad(["Logits"], "Loss", max_relative_error=0.02,
                        no_grad_set={"Label"})


class TestLookupTable(OpTest):
    op_type = "lookup_table"

    def setup(self):
        rng = np.random.RandomState(16)
        w = rng.uniform(-1, 1, (10, 4)).astype(np.float32)
        ids = rng.randint(0, 10, (5, 1)).astype(np.int64)
        self.inputs = {"W": w, "Ids": ids}
        self.attrs = {}
        self.outputs = {"Out": w[ids.ravel()]}

    def test_output(self):
        self.setup()
        self.check_output()

    def test_grad(self):
        self.setup()
        self.check_grad(["W"], "Out", max_relative_error=0.02,
                        no_grad_set={"Ids"})


class TestBatchNormTrain(OpTest):
    op_type = "batch_norm"

    def setup(self):
        rng = np.random.RandomState(17)
        x = rng.uniform(-1, 1, (3, 4, 2, 2)).astype(np.float32)
        scale = rng.uniform(0.5, 1.5, (4,)).astype(np.float32)
        bias = rng.uniform(-0.3, 0.3, (4,)).astype(np.float32)
        mean = np.zeros(4, np.float32)
        var = np.ones(4, np.float32)
        eps, mom = 1e-5, 0.9
        bm = x.mean((0, 2, 3))
        bv = x.var((0, 2, 3))
        y = (x - bm.reshape(1, 4, 1, 1)) / np.sqrt(
            bv.reshape(1, 4, 1, 1) + eps) * scale.reshape(1, 4, 1, 1) \
            + bias.reshape(1, 4, 1, 1)
        self.inputs = {"X": x, "Scale": scale, "Bias": bias,
                       "Mean": mean, "Variance": var}
        self.attrs = {"epsilon": eps, "momentum": mom, "is_test": False}
        self.outputs = {
            "Y": y.astype(np.float32),
            "MeanOut": (mean * mom + bm * (1 - mom)).astype(np.float32),
            "VarianceOut": (var * mom + bv * (1 - mom)).astype(np.float32),
            "SavedMean": bm.astype(np.float32),
            "SavedVariance": bv.astype(np.float32),
        }

    def test_output(self):
        self.setup()
        self.check_output(atol=1e-4)

    def test_grad(self):
        self.setup()
        self.check_grad(["X", "Scale", "Bias"], "Y",
                        max_relative_error=0.05,
                        no_grad_set={"Mean", "Variance"})


class TestLayerNorm(OpTest):
    op_type = "layer_norm"

    def setup(self):
        rng = np.random.RandomState(18)
        x = rng.uniform(-1, 1, (4, 6)).astype(np.float32)
        scale = rng.uniform(0.5, 1.5, (6,)).astype(np.float32)
        bias = rng.uniform(-0.3, 0.3, (6,)).astype(np.float32)
        eps = 1e-5
        mean = x.mean(1, keepdims=True)
        var = x.var(1, keepdims=True)
        y = (x - mean) / np.sqrt(var + eps) * scale + bias
        self.inputs = {"X": x, "Scale": scale, "Bias": bias}
        self.attrs = {"epsilon": eps, "begin_norm_axis": 1}
        self.outputs = {"Y": y.astype(np.float32),
                        "Mean": mean.ravel().astype(np.float32),
                        "Variance": var.ravel().astype(np.float32)}

    def test_output(self):
        self.setup()
        self.check_output(atol=1e-4)

    def test_grad(self):
        self.setup()
        self.check_grad(["X", "Scale", "Bias"], "Y",
                        max_relative_error=0.05)


class TestTopKAccuracy(OpTest):
    op_type = "top_k"

    def test_output(self):
        rng = np.random.RandomState(19)
        x = rng.uniform(-1, 1, (4, 6)).astype(np.float32)
        k = 2
        idx = np.argsort(-x, axis=1)[:, :k]
        vals = np.take_along_axis(x, idx, axis=1)
        self.inputs = {"X": x}
        self.attrs = {"k": k}
        self.outputs = {"Out": vals, "Indices": idx.astype(np.int64)}
        self.check_output()


def test_dropout_train_eval():
    import paddle_tpu as fluid
    x = fluid.layers.data(name="x", shape=[100], dtype="float32")
    out_train = fluid.layers.dropout(x, dropout_prob=0.3, is_test=False)
    out_eval = fluid.layers.dropout(x, dropout_prob=0.3, is_test=True)
    exe = fluid.Executor(fluid.CPUPlace())
    xs = np.ones((10, 100), np.float32)
    tr, ev = exe.run(fluid.default_main_program(), feed={"x": xs},
                     fetch_list=[out_train, out_eval])
    # eval mode scales by (1-p); train mode zeroes ~p of entries
    np.testing.assert_allclose(ev, xs * 0.7, rtol=1e-6)
    frac_zero = (tr == 0).mean()
    assert 0.15 < frac_zero < 0.45
    assert set(np.unique(tr)) <= {0.0, 1.0}


class TestHsigmoid:
    def test_cost_matches_manual_and_trains(self):
        import paddle_tpu as fluid
        from paddle_tpu import executor as executor_mod
        num_classes = 10
        x = fluid.layers.data(name="x", shape=[8], dtype="float32",
                              append_batch_size=False, stop_gradient=False)
        label = fluid.layers.data(name="hl", shape=[1], dtype="int64",
                                  append_batch_size=False)
        cost = fluid.layers.hsigmoid(
            x, label, num_classes,
            param_attr=fluid.ParamAttr(name="hs_w"),
            bias_attr=fluid.ParamAttr(name="hs_b"))
        loss = fluid.layers.mean(cost)
        fluid.backward.append_backward(loss)
        exe = fluid.Executor(fluid.CPUPlace())
        sc = executor_mod.Scope()
        rng = np.random.RandomState(0)
        with executor_mod.scope_guard(sc):
            exe.run(fluid.default_startup_program())
            w = (rng.randn(num_classes - 1, 8) * 0.3).astype(np.float32)
            b = (rng.randn(1, num_classes - 1) * 0.1).astype(np.float32)
            sc.set_var("hs_w", w)
            sc.set_var("hs_b", b)
            xv = rng.randn(4, 8).astype(np.float32)
            lv = np.array([[3], [0], [9], [5]], np.int64)
            block = fluid.default_main_program().global_block()
            cv, gx = exe.run(fluid.default_main_program(),
                             feed={"x": xv, "hl": lv},
                             fetch_list=[cost, block.var("x@GRAD")])
        # manual reference: walk the SimpleCode tree per sample
        def manual(xr, lab):
            c = int(lab) + num_classes
            total, j = 0.0, 0
            while (c >> (j + 1)) >= 1:
                idx = (c >> (j + 1)) - 1
                bit = (c >> j) & 1
                pre = float(xr @ w[idx] + b[0, idx])
                total += np.logaddexp(0.0, pre) - bit * pre
                j += 1
            return total
        want = [manual(xv[i], lv[i, 0]) for i in range(4)]
        np.testing.assert_allclose(np.ravel(cv), want, rtol=1e-5)
        assert np.abs(gx).sum() > 0      # differentiable

    def test_probabilities_normalize(self):
        """sum_c P(c) = 1 under the tree factorization: exp(-cost) summed
        over all labels must be ~1 for any x."""
        import paddle_tpu as fluid
        from paddle_tpu import executor as executor_mod
        num_classes = 8
        x = fluid.layers.data(name="x", shape=[4], dtype="float32",
                              append_batch_size=False)
        label = fluid.layers.data(name="hl", shape=[1], dtype="int64",
                                  append_batch_size=False)
        cost = fluid.layers.hsigmoid(x, label, num_classes,
                                     bias_attr=False)
        exe = fluid.Executor(fluid.CPUPlace())
        sc = executor_mod.Scope()
        rng = np.random.RandomState(1)
        with executor_mod.scope_guard(sc):
            exe.run(fluid.default_startup_program())
            xv = np.repeat(rng.randn(1, 4).astype(np.float32),
                           num_classes, axis=0)
            lv = np.arange(num_classes, dtype=np.int64)[:, None]
            cv, = exe.run(fluid.default_main_program(),
                          feed={"x": xv, "hl": lv}, fetch_list=[cost])
        probs = np.exp(-np.ravel(cv))
        np.testing.assert_allclose(probs.sum(), 1.0, rtol=1e-4)


class TestBilinearInterp:
    def test_matches_manual_align_corners(self):
        import paddle_tpu as fluid
        x = fluid.layers.data(name="x", shape=[1, 2, 2], dtype="float32")
        up = fluid.layers.bilinear_interp(x, out_h=3, out_w=3)
        exe = fluid.Executor(fluid.CPUPlace())
        exe.run(fluid.default_startup_program())
        xv = np.array([[[[0.0, 1.0], [2.0, 3.0]]]], np.float32)
        r, = exe.run(feed={"x": xv}, fetch_list=[up])
        want = np.array([[0.0, 0.5, 1.0], [1.0, 1.5, 2.0],
                         [2.0, 2.5, 3.0]], np.float32)
        np.testing.assert_allclose(r[0, 0], want, rtol=1e-6)

    def test_gradient_flows(self):
        import paddle_tpu as fluid
        x = fluid.layers.data(name="x", shape=[1, 2, 2], dtype="float32",
                              stop_gradient=False)
        up = fluid.layers.bilinear_interp(x, out_h=4, out_w=4)
        loss = fluid.layers.reduce_sum(up)
        fluid.backward.append_backward(loss)
        exe = fluid.Executor(fluid.CPUPlace())
        exe.run(fluid.default_startup_program())
        block = fluid.default_main_program().global_block()
        g, = exe.run(feed={"x": np.ones((1, 1, 2, 2), np.float32)},
                     fetch_list=[block.var("x@GRAD")])
        # conservation: sum of grads equals number of output elements
        np.testing.assert_allclose(g.sum(), 16.0, rtol=1e-5)


class TestSelectiveFC:
    def test_masked_columns_zero_and_match_fc(self):
        import paddle_tpu as fluid
        from paddle_tpu import executor as executor_mod
        x = fluid.layers.data(name="x", shape=[4], dtype="float32")
        sel = fluid.layers.data(name="sel", shape=[6], dtype="float32")
        out = fluid.layers.selective_fc(
            x, sel, size=6, param_attr=fluid.ParamAttr(name="sfc_w"),
            bias_attr=fluid.ParamAttr(name="sfc_b"))
        exe = fluid.Executor(fluid.CPUPlace())
        sc = executor_mod.Scope()
        rng = np.random.RandomState(0)
        with executor_mod.scope_guard(sc):
            exe.run(fluid.default_startup_program())
            w = rng.randn(4, 6).astype(np.float32)
            b = rng.randn(6).astype(np.float32)
            sc.set_var("sfc_w", w)
            sc.set_var("sfc_b", b)
            xv = rng.randn(3, 4).astype(np.float32)
            sv = (rng.rand(3, 6) < 0.5).astype(np.float32)
            r, = exe.run(feed={"x": xv, "sel": sv}, fetch_list=[out])
        np.testing.assert_allclose(r, (xv @ w + b) * sv, rtol=1e-5)
