"""Pins for the attention op's explicit-backward machinery: the op emits
a correct LSE residual, append_backward selects the EXPLICIT grad op
(scaled_dot_product_attention_grad) rather than the generic vjp maker —
the property that keeps pallas forwards from running twice per step
(XLA does not CSE duplicated custom calls) — and the grad op's outputs
match autodiff through the einsum reference."""

import numpy as np
import pytest

import paddle_tpu as fluid
from paddle_tpu import executor as executor_mod


def _build(use_flash):
    q = fluid.layers.data(name="q", shape=[2, 64, 2, 16], dtype="float32",
                          append_batch_size=False)
    k = fluid.layers.data(name="k", shape=[2, 64, 2, 16], dtype="float32",
                          append_batch_size=False)
    v = fluid.layers.data(name="v", shape=[2, 64, 2, 16], dtype="float32",
                          append_batch_size=False)
    for var in (q, k, v):
        # data vars default to no-grad on BOTH the py Variable and desc
        var.stop_gradient = False
        var.desc.stop_gradient = False
    out = fluid.layers.fused_attention(q, k, v, causal=True,
                                       use_flash=use_flash)
    loss = fluid.layers.mean(fluid.layers.elementwise_mul(out, out))
    return (q, k, v), out, loss


def _feed(seed=5):
    rng = np.random.default_rng(seed)
    return {n: rng.standard_normal((2, 64, 2, 16)).astype(np.float32)
            for n in ("q", "k", "v")}


@pytest.mark.parametrize("use_flash", [False, True])
def test_lse_output_matches_logsumexp(use_flash):
    (q, k, v), out, _loss = _build(use_flash)
    main = fluid.framework.framework.default_main_program()
    sdpa_op, = [op for op in main.global_block().ops
                if op.type == "scaled_dot_product_attention"]
    lse_name = sdpa_op.output("LSE")[0]
    feed = _feed()
    exe = fluid.Executor(fluid.CPUPlace())
    with executor_mod.scope_guard(executor_mod.Scope()):
        lse, = exe.run(main, feed=feed, fetch_list=[lse_name])
    d = 16
    s = np.einsum("bqhd,bkhd->bhqk", feed["q"], feed["k"]) / np.sqrt(d)
    mask = np.tril(np.ones((64, 64), bool))
    s = np.where(mask, s, -np.inf)
    want = np.log(np.sum(np.exp(s - s.max(-1, keepdims=True)), -1)) + \
        s.max(-1)
    np.testing.assert_allclose(np.asarray(lse), want, rtol=1e-4, atol=1e-4)


def test_backward_uses_explicit_grad_op():
    (q, k, v), out, loss = _build(True)
    fluid.backward.append_backward(loss)
    main = fluid.framework.framework.default_main_program()
    types = [op.type for op in main.global_block().ops]
    assert "scaled_dot_product_attention_grad" in types, types
    # exactly one forward attention op: the grad op must NOT have cloned it
    assert types.count("scaled_dot_product_attention") == 1, types


@pytest.mark.parametrize("use_flash", [False, True])
def test_grads_match_einsum_autodiff(use_flash):
    import jax
    import jax.numpy as jnp
    from paddle_tpu.parallel.ring_attention import attention_reference
    from paddle_tpu.framework.framework import grad_var_name

    (q, k, v), out, loss = _build(use_flash)
    fluid.backward.append_backward(loss)
    main = fluid.framework.framework.default_main_program()
    feed = _feed()
    exe = fluid.Executor(fluid.CPUPlace())
    with executor_mod.scope_guard(executor_mod.Scope()):
        grads = exe.run(main, feed=feed,
                        fetch_list=[grad_var_name(n)
                                    for n in ("q", "k", "v")])

    def loss_fn(a, b, c):
        o = attention_reference(a, b, c, causal=True)
        return jnp.mean(o * o)

    want = jax.grad(loss_fn, argnums=(0, 1, 2))(
        *[jnp.asarray(feed[n]) for n in ("q", "k", "v")])
    for g, w in zip(grads, want):
        np.testing.assert_allclose(np.asarray(g), np.asarray(w),
                                   rtol=1e-3, atol=1e-4)


class TestAutoSelection:
    """use_flash defaults to 'auto' (VERDICT r4 #2): einsum below the
    threshold T (fuses into neighboring HLO), flash at/above it; explicit
    True/False always wins."""

    class _Op:
        def __init__(self, attrs):
            self._attrs = attrs

        def attr(self, name, default=None):
            return self._attrs.get(name, default)

    class _Ctx:
        class _P:
            _mesh = None
        program = _P()

    def _mode(self, t, attrs, threshold=None, dtype="float32"):
        import os
        import jax
        import paddle_tpu.ops.nn_ops as nn_ops
        probe = jax.ShapeDtypeStruct((2, t, 4, 64), dtype)
        prev = os.environ.get("PADDLE_TPU_FLASH_AUTO_T")
        if threshold is not None:
            os.environ["PADDLE_TPU_FLASH_AUTO_T"] = str(threshold)
        try:
            mode, _ = nn_ops._sdpa_paths(self._Ctx(), self._Op(attrs),
                                         probe, probe, probe)
        finally:
            if threshold is not None:
                if prev is None:
                    del os.environ["PADDLE_TPU_FLASH_AUTO_T"]
                else:
                    os.environ["PADDLE_TPU_FLASH_AUTO_T"] = prev
        return mode

    def test_auto_short_t_takes_einsum(self):
        assert self._mode(512, {"use_flash": "auto"},
                          threshold=2048) == "einsum"

    def test_auto_long_t_takes_flash(self):
        assert self._mode(4096, {"use_flash": "auto"},
                          threshold=2048) == "flash"

    def test_explicit_true_forces_flash_below_threshold(self):
        assert self._mode(512, {"use_flash": True},
                          threshold=2048) == "flash"

    def test_explicit_false_forces_einsum_above_threshold(self):
        assert self._mode(8192, {"use_flash": False},
                          threshold=2048) == "einsum"

    def test_untileable_shape_falls_back_to_einsum(self):
        assert self._mode(100, {"use_flash": True}) == "einsum"

    def test_default_attr_is_auto(self):
        import paddle_tpu as fluid
        _build(use_flash="auto")  # layer default; explicit for clarity
        main = fluid.framework.framework.default_main_program()
        sdpa_op, = [op for op in main.global_block().ops
                    if op.type == "scaled_dot_product_attention"]
        assert sdpa_op.attr("use_flash") == "auto"
