"""RecordIO native library tests (reference: paddle/fluid/recordio/
*_test.cc, python tests test_recordio_reader.py)."""

import os

import numpy as np
import pytest

from paddle_tpu import recordio


def test_roundtrip_plain(tmp_path):
    path = str(tmp_path / "plain.recordio")
    recs = [os.urandom(n) for n in (1, 10, 1000, 65536)]
    with recordio.RecordIOWriter(path, compressor="none") as w:
        for r in recs:
            w.write(r)
    got = list(recordio.RecordIOScanner(path))
    assert got == recs


def test_roundtrip_compressed_many_chunks(tmp_path):
    path = str(tmp_path / "z.recordio")
    rng = np.random.RandomState(0)
    # > 1MB total to force multiple chunks
    recs = [rng.randint(0, 10, 65536).astype(np.uint8).tobytes()
            for _ in range(32)]
    with recordio.RecordIOWriter(path, compressor="snappy") as w:
        for r in recs:
            w.write(r)
    got = list(recordio.RecordIOScanner(path))
    assert got == recs


def test_sample_pickle_roundtrip(tmp_path):
    path = str(tmp_path / "samples.recordio")
    samples = [(np.arange(4, dtype=np.float32), i) for i in range(100)]
    recordio.write_samples(path, samples)
    out = list(recordio.read_samples(path))
    assert len(out) == 100
    np.testing.assert_array_equal(out[7][0], samples[7][0])
    assert out[7][1] == 7


def test_crc_detects_corruption(tmp_path):
    path = str(tmp_path / "c.recordio")
    recordio.write_samples(path, [b"x" * 1000])
    raw = bytearray(open(path, "rb").read())
    raw[len(raw) // 2] ^= 0xFF
    open(path, "wb").write(bytes(raw))
    # a corrupted chunk must raise, not silently truncate the dataset
    # (reference scanner raises on CRC mismatch)
    import pytest
    with pytest.raises(IOError):
        list(recordio.RecordIOScanner(path))


def test_highly_compressible_chunk_not_flagged_corrupt(tmp_path):
    """zlib can legitimately reach ~1030:1 on redundant data; the corruption
    guard must not reject such chunks (cap is 1200, above deflate's max)."""
    import numpy as np
    from paddle_tpu import recordio
    path = str(tmp_path / "zeros.recordio")
    payload = b"\x00" * (1 << 20)   # 1 MiB of zeros -> ~1000:1 deflate
    w = recordio.RecordIOWriter(path)
    w.write(payload)
    w.close()
    got = list(recordio.RecordIOScanner(path))
    assert len(got) == 1 and got[0] == payload
