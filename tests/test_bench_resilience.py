"""bench.py must survive transient infra failures (VERDICT r4 weak #1: a
single `remote_compile: response body closed` cost round 4 its official
number). These tests drive the retry/partial-result machinery directly with
injected failures — no TPU needed."""

import importlib.util
import io
import json
import os
import sys

import numpy as np
import pytest


def _load_bench():
    path = os.path.join(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))), "bench.py")
    spec = importlib.util.spec_from_file_location("bench_under_test", path)
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


@pytest.fixture(scope="module")
def bench():
    return _load_bench()


@pytest.fixture(autouse=True)
def _no_sleep(bench, monkeypatch):
    monkeypatch.setattr(bench.time, "sleep", lambda s: None)
    # _emit appends to the BENCH_HISTORY.jsonl ledger (ISSUE 17); keep
    # test emissions out of the repo's standing ledger
    monkeypatch.setenv("BENCH_HISTORY", "0")


class _FlakyStep:
    """Raises a transient-looking error on selected calls, else returns a
    finite on-device-like scalar."""

    def __init__(self, fail_on=(), exc=None):
        self.calls = 0
        self.fail_on = set(fail_on)
        self.exc = exc or RuntimeError(
            "INTERNAL: remote_compile: response body closed")

    def __call__(self):
        self.calls += 1
        if self.calls in self.fail_on:
            raise self.exc
        return np.ones(())


def test_transient_classification(bench):
    assert bench._is_transient(RuntimeError(
        "INTERNAL: remote_compile: response body closed"))
    assert bench._is_transient(OSError("Connection reset by peer"))

    class JaxRuntimeError(Exception):
        pass

    assert bench._is_transient(JaxRuntimeError("something opaque"))
    assert not bench._is_transient(AssertionError("non-finite fetch nan"))
    assert not bench._is_transient(TypeError("bad arg"))


def test_once_raising_step_still_yields_number(bench, monkeypatch):
    """The VERDICT r4 acceptance case: a step that raises once (the r4
    failure mode) must not kill the measurement."""
    monkeypatch.setattr(bench, "RETRIES", 2)
    step = _FlakyStep(fail_on={1})          # dies on the first warmup call
    dt, done = bench._timed_loop(step, warmup=2, steps=4)
    assert done == 4 and dt > 0

    step = _FlakyStep(fail_on={4})          # dies mid-timed-loop
    errors = []
    dt, done = bench._timed_loop(step, warmup=1, steps=4, errors=errors)
    assert done == 4 and dt > 0
    assert any("timed" in e for e in errors)


def test_partial_chunks_survive_persistent_failure(bench, monkeypatch):
    """A late persistent failure keeps the chunks completed by RETRY
    attempts (attempt 0 is single-sync for clean timing; retries chunk
    so progress accumulates): the round still gets a number."""
    monkeypatch.setattr(bench, "RETRIES", 2)
    # warmup call 1; attempt0 single chunk: calls 2,3,4 -> call4 dies;
    # attempt1 (chunks of 1): call5 OK (done=1), call6 dies;
    # attempt2: call7 dies -> budget gone, partial done=1 survives
    step = _FlakyStep(fail_on={4, 6, 7, 8, 9, 10})
    dt, done = bench._timed_loop(step, warmup=1, steps=4)
    assert done == 1 and dt > 0


def test_persistent_warmup_failure_raises_bench_error(bench, monkeypatch):
    monkeypatch.setattr(bench, "RETRIES", 1)
    step = _FlakyStep(fail_on=set(range(1, 20)))
    with pytest.raises(bench.BenchError) as ei:
        bench._timed_loop(step, warmup=1, steps=2)
    assert any("warmup" in e for e in ei.value.errors)


def test_non_transient_fails_fast(bench, monkeypatch):
    monkeypatch.setattr(bench, "RETRIES", 3)
    step = _FlakyStep(fail_on={1}, exc=AssertionError("non-finite"))
    with pytest.raises(AssertionError):
        bench._timed_loop(step, warmup=1, steps=2)
    assert step.calls == 1  # no retry burned on a real bug


def test_non_transient_after_completed_chunk_still_raises(bench,
                                                          monkeypatch):
    """A NaN divergence late in the run must NOT become a partial
    'success' — only transient infra errors may yield partial numbers."""
    monkeypatch.setattr(bench, "RETRIES", 2)
    # warmup=1 (call 1), chunk1 calls 2-3 complete, then the NaN guard
    step = _FlakyStep(fail_on={4}, exc=AssertionError("non-finite fetch"))
    with pytest.raises(AssertionError):
        bench._timed_loop(step, warmup=1, steps=4)


def _capture_main(bench, monkeypatch, dispatch):
    monkeypatch.setattr(bench, "_dispatch", dispatch)
    monkeypatch.setattr(bench.time, "sleep", lambda s: None)
    monkeypatch.setenv("BENCH_ROOFLINE", "0")
    bench._ROOFLINE = None
    bench._CARRIED_ERRORS[:] = []
    buf = io.StringIO()
    monkeypatch.setattr(sys, "stdout", buf)
    rc = bench.main()
    sys.stdout = sys.__stdout__
    return rc, buf.getvalue()


def test_main_emits_json_on_persistent_failure(bench, monkeypatch):
    """parsed must never be null for a transient cause: even when every
    attempt dies, ONE parseable JSON line with the error log comes out."""
    def dispatch(mode):
        raise RuntimeError("INTERNAL: remote_compile: response body closed")

    rc, out = _capture_main(bench, monkeypatch, dispatch)
    assert rc == 1
    payload = json.loads(out.strip().splitlines()[-1])
    assert payload["value"] is None
    assert payload["errors"]
    assert "remote_compile" in " ".join(payload["errors"])


def test_main_rebuilds_family_once_on_transient(bench, monkeypatch):
    """First whole-family attempt dies transiently -> one rebuild attempt
    runs the family to completion."""
    calls = []

    def dispatch(mode):
        calls.append(mode)
        if len(calls) == 1:
            raise RuntimeError("UNAVAILABLE: tunnel reset")
        bench._emit({"metric": "fake", "value": 1.0,
                     "unit": "x", "vs_baseline": 1.0})

    rc, out = _capture_main(bench, monkeypatch, dispatch)
    assert rc is None and len(calls) == 2
    payload = json.loads(out.strip().splitlines()[-1])
    assert payload["value"] == 1.0
    # the rebuilt run must still disclose that attempt 0 died
    assert any("attempt0" in e for e in payload["errors"])
