"""Fleet observability (ISSUE 8): collective-kind classification, busbw
factor math, HLO collective parsing (shape -> bytes, pd.coll sites, the
GSPMD `near` fallback), the exposed-vs-overlapped split, the synthetic
xplane -> collective_table join, the goodput ledger arithmetic, and a
real 2-process FleetSnapshot reduce over the coordination service. The
synthetic traces hand-encode the XSpace wire format (same encoder as
test_roofline.py) so the tests pin the parser and the attribution logic
together without a device."""

import json
import os
import socket
import subprocess
import sys

import pytest

from paddle_tpu import fleet, xplane

HERE = os.path.dirname(os.path.abspath(__file__))


# --- hand-rolled XSpace encoder (mirrors xplane.py's decoder) ---------------

def _varint(n):
    out = b""
    while True:
        b = n & 0x7F
        n >>= 7
        out += bytes([b | (0x80 if n else 0)])
        if not n:
            return out


def _field(fno, wt, payload):
    key = _varint((fno << 3) | wt)
    if wt == 2:
        return key + _varint(len(payload)) + payload
    return key + _varint(payload)


def _event(mid, off_ps, dur_ps):
    return (_field(1, 0, mid) + _field(2, 0, off_ps)
            + _field(3, 0, dur_ps))


def _line(name, ts_ns, events):
    buf = _field(2, 2, name.encode()) + _field(3, 0, ts_ns)
    for e in events:
        buf += _field(4, 2, e)
    return buf


def _meta(mid, name):
    inner = _field(1, 0, mid) + _field(2, 2, name.encode())
    return _field(1, 0, mid) + _field(2, 2, inner)


def _plane(name, lines, metas):
    buf = _field(2, 2, name.encode())
    for ln in lines:
        buf += _field(3, 2, ln)
    for m in metas:
        buf += _field(4, 2, m)
    return buf


def _write_xspace(path, planes):
    path.write_bytes(b"".join(_field(1, 2, p) for p in planes))


# --- classification + busbw factors ------------------------------------------

class TestCollectiveKind:
    def test_hlo_spellings(self):
        assert xplane.collective_kind("all-reduce.3") == "all-reduce"
        assert xplane.collective_kind("all-gather-start.1") == "all-gather"
        assert (xplane.collective_kind("reduce-scatter.2")
                == "reduce-scatter")
        assert xplane.collective_kind("all-to-all.7") == "all-to-all"
        assert (xplane.collective_kind("collective-permute-start")
                == "collective-permute")
        assert xplane.collective_kind("send.1") == "send/recv"
        assert xplane.collective_kind("recv-done.4") == "send/recv"

    def test_runtime_and_framework_spellings(self):
        assert xplane.collective_kind("AllReduce") == "all-reduce"
        assert (xplane.collective_kind("cross-replica-sum.1")
                == "all-reduce")
        assert xplane.collective_kind("ppermute") == "collective-permute"

    def test_non_collectives_are_none(self):
        for name in ("fusion.3", "dot.1", "infeed", "copy.2",
                     "dynamic-update-slice.9"):
            assert xplane.collective_kind(name) is None, name

    def test_reduce_scatter_not_shadowed_by_all_reduce(self):
        # match order matters: 'reduce-scatter' must win over the broader
        # reduce-family patterns (tools/check_registry.py lints the table)
        assert (xplane.collective_kind("reduce-scatter-start.1")
                == "reduce-scatter")


class TestBusbwFactor:
    def test_nccl_tests_convention(self):
        assert xplane.busbw_factor("all-reduce", 4) == pytest.approx(1.5)
        assert xplane.busbw_factor("all-gather", 4) == pytest.approx(0.75)
        assert (xplane.busbw_factor("reduce-scatter", 8)
                == pytest.approx(7 / 8))
        assert xplane.busbw_factor("collective-permute", 4) == 1.0
        assert xplane.busbw_factor("send/recv", 2) == 1.0

    def test_degenerate(self):
        assert xplane.busbw_factor("all-reduce", 1) == 0.0
        assert xplane.busbw_factor("not-a-kind", 4) == 0.0


# --- HLO parsing --------------------------------------------------------------

_HLO = """\
HloModule jit_step

ENTRY main {
  %p0 = f32[1024,1024]{1,0} parameter(0)
  %dot.3 = f32[8,8]{1,0} dot(%p0, %p0)
  %all-reduce.1 = f32[1024,1024]{1,0} all-reduce(%p0), channel_id=1, \
replica_groups=[1,4]<=[4], to_apply=%add, \
metadata={op_name="jit(step)/jit(main)/pd.mul_grad/pd.coll.dp_grad/add"}
  %all-gather-start.2 = (f32[256]{0}, f32[1024]{0}) \
all-gather-start(%p0), replica_groups=[1,4]<=[4], dimensions={0}, \
metadata={op_name="jit(step)/pd.mul/pd.coll.tp_gather/g"}
  %all-gather-done.2 = f32[1024]{0} all-gather-done(%all-gather-start.2), \
metadata={op_name="jit(step)/pd.mul/pd.coll.tp_gather/g"}
  %all-reduce.9 = f32[64]{0} all-reduce(%p0), replica_groups=[1,4]<=[4], \
to_apply=%add, metadata={op_name="jit(step)/pd.mean/reduce_sum"}
}
"""


class TestHloCollectives:
    def test_sites_bytes_and_done_halves(self):
        out = xplane.hlo_collectives(_HLO)
        assert set(out) == {"all-reduce.1", "all-gather-start.2",
                            "all-gather-done.2", "all-reduce.9"}
        ar = out["all-reduce.1"]
        assert ar["kind"] == "all-reduce"
        assert ar["site"] == "dp_grad"
        assert ar["bytes"] == 1024 * 1024 * 4
        # async start carries an (input, output) tuple aliasing ONE
        # transfer: bytes is the largest component, not the sum
        ag = out["all-gather-start.2"]
        assert ag["kind"] == "all-gather"
        assert ag["site"] == "tp_gather"
        assert ag["bytes"] == 1024 * 4
        # the -done half joins time but contributes 0 bytes (no double
        # counting of the pair's payload)
        assert out["all-gather-done.2"]["bytes"] == 0
        # GSPMD-inserted collective: no pd.coll scope, but the inherited
        # op_name names the responsible layer
        g = out["all-reduce.9"]
        assert g["site"] is None
        assert g["near"] == "mean"

    def test_participants(self):
        assert xplane.hlo_participants(_HLO) == 4
        assert xplane.hlo_participants(
            "replica_groups={{0,1},{2,3}}, x") == 2
        assert xplane.hlo_participants("no groups here") is None


# --- exposed-vs-overlapped split ---------------------------------------------

class TestExposedInLine:
    def test_partial_overlap(self):
        # all-reduce 50..150; compute covers [0,100] and [120,140]
        # -> 70 covered, 30 exposed
        events = [("fusion.1", 0, 100), ("all-reduce.5", 50, 100),
                  ("copy.2", 120, 20)]
        assert xplane.exposed_in_line(events) == {"all-reduce.5": 30}

    def test_fully_hidden_and_fully_exposed(self):
        events = [("fusion.1", 0, 200), ("all-reduce.5", 50, 100),
                  ("ppermute.2", 300, 40)]
        out = xplane.exposed_in_line(events)
        assert out["all-reduce.5"] == 0
        assert out["ppermute.2"] == 40

    def test_zero_duration_events_ignored(self):
        assert xplane.exposed_in_line([("all-reduce.1", 0, 0)]) == {}


# --- synthetic trace -> collective_table join --------------------------------

@pytest.fixture
def pinned_ici(monkeypatch):
    """Pin the link roofline to 100 GB/s and keep the probe cache clean on
    both sides, so pct_link is deterministic and probe-free."""
    from paddle_tpu import roofline
    monkeypatch.setenv("PADDLE_TPU_ICI_GBPS", "100")
    roofline._PROBES.pop("ici_gbps", None)
    yield 100.0
    roofline._PROBES.pop("ici_gbps", None)


def _write_trace(tmp_path):
    # device plane, two lines: the raw XLA-op line (all-reduce.1 4us, of
    # which 1us hides under fusion.1) and a derived line repeating the
    # same event shorter — per-name MAX across lines must pick the raw one
    metas = [_meta(1, "fusion.1"), _meta(2, "all-reduce.1")]
    raw = _line("xla-ops", 0, [
        _event(1, 0, 2_000_000),            # fusion.1: 0..2us
        _event(2, 1_000_000, 4_000_000),    # all-reduce.1: 1..5us
    ])
    derived = _line("steps", 0, [_event(2, 0, 3_000_000)])
    _write_xspace(tmp_path / "t.xplane.pb",
                  [_plane("/device:TPU:0", [raw, derived], metas)])


class TestCollectiveEventsDir:
    def test_max_across_lines_and_exposed(self, tmp_path):
        _write_trace(tmp_path)
        evs = xplane.collective_events_dir(str(tmp_path))
        assert set(evs) == {"all-reduce.1"}
        rec = evs["all-reduce.1"]
        assert rec["kind"] == "all-reduce"
        assert rec["total_ps"] == 4_000_000          # max, not 4+3
        assert rec["exposed_ps"] == 3_000_000        # 1us under fusion.1


class TestCollectiveTable:
    def test_join_busbw_and_roofline_pct(self, tmp_path, pinned_ici):
        _write_trace(tmp_path)
        table = fleet.collective_table(str(tmp_path), [_HLO], steps=2,
                                       probe=False)
        assert table["ici_gbps"] == pinned_ici
        assert table["participants"] == 4
        assert len(table["rows"]) == 1
        r = table["rows"][0]
        assert r["kind"] == "all-reduce"
        assert r["site"] == "dp_grad"
        assert r["count"] == 1
        assert r["bytes"] == 1024 * 1024 * 4 * 2     # payload x steps
        assert r["time_ms"] == pytest.approx(0.004)
        assert r["exposed_ms"] == pytest.approx(0.003)
        assert r["overlap_frac"] == pytest.approx(0.25)
        algbw = r["bytes"] / 4e-6 / 1e9
        assert r["algbw_gbps"] == pytest.approx(algbw)
        assert r["busbw_gbps"] == pytest.approx(algbw * 1.5)   # 2(n-1)/n
        assert r["pct_link"] == pytest.approx(algbw * 1.5 / pinned_ici)

    def test_unjoined_event_pools_under_gspmd(self, tmp_path, pinned_ici):
        _write_trace(tmp_path)
        table = fleet.collective_table(str(tmp_path), [], probe=False)
        (r,) = table["rows"]
        assert r["site"] == "(gspmd)"
        assert r["bytes"] == 0
        assert r["algbw_gbps"] == 0.0   # time joined, payload unknown


class TestBusbwByKind:
    def test_time_weighted_fold(self):
        table = {"rows": [
            {"kind": "all-reduce", "busbw_gbps": 10.0, "time_ms": 1.0},
            {"kind": "all-reduce", "busbw_gbps": 20.0, "time_ms": 3.0},
            {"kind": "all-gather", "busbw_gbps": 5.0, "time_ms": 2.0},
            {"kind": "send/recv", "busbw_gbps": None, "time_ms": 9.0},
        ]}
        out = fleet.busbw_by_kind(table)
        assert out == {"all-reduce": 17.5, "all-gather": 5.0}

    def test_empty(self):
        assert fleet.busbw_by_kind(None) == {}
        assert fleet.busbw_by_kind({"rows": []}) == {}


# --- goodput ledger -----------------------------------------------------------

class TestGoodput:
    def test_bucket_arithmetic(self):
        events = [
            {"kind": "run", "mono": 100.0, "seconds": 10.0,
             "compile_s": 4.0, "execute_s": 5.0},
            {"kind": "run_window", "mono": 106.0, "seconds": 5.0,
             "execute_s": 5.0},
            {"kind": "checkpoint", "op": "save", "seconds": 1.0},
            # io.py's save event nests inside the multihost one above —
            # the ledger must prefer the multihost marker, not add both
            {"kind": "checkpoint_save", "seconds": 0.4},
            # ...but with no multihost load marker, io's load counts
            {"kind": "checkpoint_load", "seconds": 0.3},
        ]
        gp = fleet.goodput_report(events, input_stall_s=0.5,
                                  collective_wait_s=2.0)
        # span: first run start (100-10=90) .. last run end (106)
        assert gp["span_s"] == pytest.approx(16.0)
        assert gp["runs"] == 2
        b = gp["buckets"]
        assert b["productive"] == pytest.approx(8.0)   # 10 exec - 2 wait
        assert b["compile"] == pytest.approx(4.0)
        assert b["checkpoint_save"] == pytest.approx(1.0)
        assert b["restore"] == pytest.approx(0.3)
        assert b["input_stall"] == pytest.approx(0.5)
        assert b["collective_wait"] == pytest.approx(2.0)
        assert b["idle"] == pytest.approx(16.0 - 15.8)
        assert gp["goodput_fraction"] == pytest.approx(0.5)

    def test_collective_wait_clamped_to_execute(self):
        events = [{"kind": "run", "mono": 10.0, "seconds": 10.0,
                   "execute_s": 3.0}]
        gp = fleet.goodput_report(events, input_stall_s=0.0,
                                  collective_wait_s=99.0)
        assert gp["buckets"]["collective_wait"] == pytest.approx(3.0)
        assert gp["buckets"]["productive"] == 0.0
        assert gp["goodput_fraction"] == 0.0

    def test_no_runs_is_none(self):
        assert fleet.goodput_report([{"kind": "checkpoint",
                                      "op": "save", "seconds": 1.0}]) is None

    def test_publishes_gauges(self):
        from paddle_tpu import telemetry
        events = [{"kind": "run", "mono": 50.0, "seconds": 4.0,
                   "execute_s": 2.0}]
        gp = fleet.goodput_report(events, input_stall_s=0.0,
                                  collective_wait_s=0.0)
        assert (telemetry.read_gauge("goodput_fraction")
                == pytest.approx(gp["goodput_fraction"]))
        assert (telemetry.read_gauge("goodput_seconds", bucket="productive")
                == pytest.approx(2.0))

    def test_formatting(self):
        assert fleet.format_goodput(None) == \
            ["[goodput] no run events recorded"]
        gp = fleet.goodput_report(
            [{"kind": "run", "mono": 10.0, "seconds": 4.0,
              "execute_s": 2.0}],
            input_stall_s=0.0, collective_wait_s=0.0)
        lines = fleet.format_goodput(gp)
        assert "50.0% productive" in lines[0]
        assert any("productive" in ln for ln in lines[1:])


# --- fleet snapshot -----------------------------------------------------------

class TestFleetSnapshot:
    def test_local_snapshot_shape(self):
        snap = fleet.local_snapshot()
        assert set(snap) >= {"host", "steps", "step_time_s",
                             "infeed_wait_s", "collective_wait_s",
                             "hbm_bytes_in_use", "hbm_peak_bytes"}
        # read-only peeks: a host that never stepped contributes numbers
        # (or None for never-set gauges), never raises
        json.dumps(snap)   # must stay JSON-serializable for the allgather

    def test_single_process_reduce(self):
        from paddle_tpu import telemetry
        local = {"host": 3, "step_time_s": 0.25, "infeed_wait_s": 0.0,
                 "collective_wait_s": 0.0}
        snap = fleet.fleet_snapshot(local)
        assert snap["n_hosts"] == 1
        assert snap["step_skew"] == 1.0
        assert snap["median_step_s"] == pytest.approx(0.25)
        assert snap["straggler"] == {"host": 3, "cause": "compute",
                                     "alerts_total": 0.0}
        assert telemetry.read_gauge("fleet_step_skew") == 1.0
        assert "straggler host 3 (compute)" in fleet.format_fleet(snap)

    def test_two_process_reduce(self):
        """Real 2-process FleetSnapshot allgather + skew reduce over the
        coordination service (harness: test_telemetry's reduce test)."""
        with socket.socket() as s:
            s.bind(("127.0.0.1", 0))
            coordinator = f"127.0.0.1:{s.getsockname()[1]}"
        env = dict(os.environ, JAX_PLATFORMS="cpu")
        env.pop("XLA_FLAGS", None)
        env.pop("PADDLE_TRAINER_ID", None)
        env["PYTHONPATH"] = os.pathsep.join(
            [os.path.dirname(HERE), env.get("PYTHONPATH", "")])
        procs = [subprocess.Popen(
            [sys.executable, os.path.join(HERE, "_fleet_worker.py"),
             coordinator, "2", str(pid)],
            env=env, stdout=subprocess.PIPE, stderr=subprocess.PIPE,
            text=True) for pid in (0, 1)]
        outs = []
        try:
            for p in procs:
                out, err = p.communicate(timeout=180)
                outs.append((p.returncode, out, err))
        finally:
            for p in procs:
                if p.poll() is None:
                    p.kill()
        for rc, out, err in outs:
            assert rc == 0, f"worker failed rc={rc}\nstdout:{out}\n" \
                            f"stderr:{err}"
            assert "RESULT" in out, out
        results = [json.loads(out.split("RESULT", 1)[1])
                   for _, out, _ in outs]
        # both sides agree: host 1 is the straggler, blamed on infeed,
        # skew = 0.2 / median(0.1, 0.2)
        for r in results:
            assert r["skew"] == pytest.approx(0.2 / 0.15)
            assert r["straggler"] == {"host": 1, "cause": "infeed",
                                      "alerts_total": 0.0}
