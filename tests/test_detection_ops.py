"""Detection op + layer tests (reference: test_prior_box_op.py,
test_iou_similarity_op.py, test_box_coder_op.py, test_bipartite_match_op.py,
test_mine_hard_examples_op.py, test_target_assign_op.py,
test_multiclass_nms_op.py, test_detection_map_op.py, plus an SSD-style
acceptance test mirroring the book SSD config)."""

import math

import numpy as np
import pytest

import paddle_tpu as fluid
from paddle_tpu import executor as executor_mod
from paddle_tpu.executor import LoDTensor

RNG = np.random.RandomState(5)


def make_lod(rows):
    flat = np.concatenate(rows, axis=0)
    offs = [0]
    for r in rows:
        offs.append(offs[-1] + len(r))
    return LoDTensor(flat, [offs])


def run(build, feed):
    main = fluid.Program()
    startup = fluid.Program()
    with fluid.program_guard(main, startup):
        fetches = build()
    exe = fluid.Executor(fluid.CPUPlace())
    with executor_mod.scope_guard(executor_mod.Scope()):
        exe.run(startup)
        return exe.run(main, feed=feed, fetch_list=list(fetches),
                       return_numpy=False)


def np_iou(a, b):
    ixmin = max(a[0], b[0]); iymin = max(a[1], b[1])
    ixmax = min(a[2], b[2]); iymax = min(a[3], b[3])
    iw = max(ixmax - ixmin, 0.0); ih = max(iymax - iymin, 0.0)
    inter = iw * ih
    a1 = (a[2] - a[0]) * (a[3] - a[1])
    a2 = (b[2] - b[0]) * (b[3] - b[1])
    return inter / max(a1 + a2 - inter, 1e-6)


class TestPriorBox:
    def test_vs_oracle(self):
        feat = np.zeros((1, 8, 2, 2), np.float32)
        img = np.zeros((1, 3, 32, 32), np.float32)
        min_sizes, max_sizes = [4.0], [9.0]
        ars, variance = [2.0], [0.1, 0.1, 0.2, 0.2]

        def build():
            f = fluid.layers.data(name="f", shape=[8, 2, 2],
                                  dtype="float32")
            im = fluid.layers.data(name="im", shape=[3, 32, 32],
                                   dtype="float32")
            boxes, var = fluid.layers.detection.prior_box(
                f, im, min_sizes, max_sizes, ars, variance, flip=True)
            return boxes, var

        boxes, var = run(build, {"f": feat, "im": img})
        boxes = np.asarray(boxes)
        var = np.asarray(var)
        # expanded ARs: [1, 2, 0.5]; priors = 3*1 + 1 = 4
        assert boxes.shape == (2, 2, 4, 4)
        # cell (0,0): center (8, 8) with step 16, offset .5
        cx = cy = 8.0
        m = min_sizes[0] / 2
        np.testing.assert_allclose(
            boxes[0, 0, 0], [(cx - m) / 32, (cy - m) / 32,
                             (cx + m) / 32, (cy + m) / 32], rtol=1e-5)
        s2 = math.sqrt(min_sizes[0] * max_sizes[0]) / 2
        np.testing.assert_allclose(
            boxes[0, 0, 1], [(cx - s2) / 32, (cy - s2) / 32,
                             (cx + s2) / 32, (cy + s2) / 32], rtol=1e-5)
        w2 = min_sizes[0] * math.sqrt(2.0) / 2
        h2 = min_sizes[0] / math.sqrt(2.0) / 2
        np.testing.assert_allclose(
            boxes[0, 0, 2], [(cx - w2) / 32, (cy - h2) / 32,
                             (cx + w2) / 32, (cy + h2) / 32], rtol=1e-5)
        np.testing.assert_allclose(var[1, 1, 3], variance, rtol=1e-6)


class TestIouSimilarity:
    def test_vs_oracle(self):
        x = np.abs(RNG.rand(4, 4)).astype("float32")
        x[:, 2:] = x[:, :2] + np.abs(RNG.rand(4, 2)) + 0.1
        y = np.abs(RNG.rand(3, 4)).astype("float32")
        y[:, 2:] = y[:, :2] + np.abs(RNG.rand(3, 2)) + 0.1

        def build():
            xv = fluid.layers.data(name="x", shape=[4, 4], dtype="float32",
                                   append_batch_size=False)
            yv = fluid.layers.data(name="y", shape=[3, 4], dtype="float32",
                                   append_batch_size=False)
            return (fluid.layers.detection.iou_similarity(xv, yv),)

        out, = run(build, {"x": x, "y": y})
        want = np.array([[np_iou(a, b) for b in y] for a in x], np.float32)
        np.testing.assert_allclose(np.asarray(out), want, rtol=1e-4)


class TestBoxCoder:
    def test_encode_decode_roundtrip(self):
        p = np.array([[0.1, 0.1, 0.5, 0.5], [0.2, 0.3, 0.7, 0.8]],
                     np.float32)
        pv = np.tile(np.array([0.1, 0.1, 0.2, 0.2], np.float32), (2, 1))
        t = np.array([[0.15, 0.2, 0.4, 0.6]], np.float32)

        def build_enc():
            pb = fluid.layers.data(name="p", shape=[2, 4], dtype="float32",
                                   append_batch_size=False)
            pbv = fluid.layers.data(name="pv", shape=[2, 4], dtype="float32",
                                    append_batch_size=False)
            tb = fluid.layers.data(name="t", shape=[1, 4], dtype="float32",
                                   append_batch_size=False)
            enc = fluid.layers.detection.box_coder(pb, pbv, tb,
                                                   "encode_center_size")
            dec = fluid.layers.detection.box_coder(pb, pbv, enc,
                                                   "decode_center_size")
            return enc, dec

        enc, dec = run(build_enc, {"p": p, "pv": pv, "t": t})
        enc = np.asarray(enc)
        dec = np.asarray(dec)
        assert enc.shape == (1, 2, 4)
        # decode(encode(t)) == t broadcast over priors
        np.testing.assert_allclose(dec[0, 0], t[0], rtol=1e-4, atol=1e-5)
        np.testing.assert_allclose(dec[0, 1], t[0], rtol=1e-4, atol=1e-5)
        # oracle for one encode cell
        pw, ph = 0.4, 0.4
        pcx, pcy = 0.3, 0.3
        tcx, tcy = 0.275, 0.4
        tw, th = 0.25, 0.4
        want = [(tcx - pcx) / pw / 0.1, (tcy - pcy) / ph / 0.1,
                math.log(tw / pw) / 0.2, math.log(th / ph) / 0.2]
        np.testing.assert_allclose(enc[0, 0], want, rtol=1e-4, atol=1e-5)


def bipartite_oracle(dist):
    g, p = dist.shape
    match = -np.ones(p, int)
    mdist = np.zeros(p)
    rows = set(range(g))
    d = dist.copy()
    while rows:
        best = (-1, -1, -1.0)
        for r in rows:
            for c in range(p):
                if match[c] == -1 and d[r, c] > best[2] and d[r, c] >= 1e-6:
                    best = (r, c, d[r, c])
        if best[0] < 0:
            break
        match[best[1]] = best[0]
        mdist[best[1]] = best[2]
        rows.remove(best[0])
    return match, mdist


class TestBipartiteMatch:
    def test_vs_oracle(self):
        rows = [RNG.rand(3, 5).astype("float32"),
                RNG.rand(2, 5).astype("float32")]

        def build():
            d = fluid.layers.data(name="d", shape=[5], dtype="float32",
                                  lod_level=1)
            mi, md = fluid.layers.detection.bipartite_match(d)
            return mi, md

        mi, md = run(build, {"d": make_lod(rows)})
        mi = np.asarray(mi)
        md = np.asarray(md)
        for b, r in enumerate(rows):
            want_i, want_d = bipartite_oracle(r)
            np.testing.assert_array_equal(mi[b], want_i)
            np.testing.assert_allclose(md[b], want_d, rtol=1e-5)

    def test_per_prediction(self):
        dist = np.array([[0.8, 0.2, 0.6], [0.3, 0.7, 0.65]], np.float32)

        def build():
            d = fluid.layers.data(name="d", shape=[2, 3], dtype="float32",
                                  append_batch_size=False)
            mi, md = fluid.layers.detection.bipartite_match(
                d, match_type="per_prediction", dist_threshold=0.5)
            return mi, md

        mi, md = run(build, {"d": dist})
        # bipartite picks (0,0) and (1,1); col 2 argmax row 1 (0.65 >= 0.5)
        np.testing.assert_array_equal(np.asarray(mi)[0], [0, 1, 1])


class TestTargetAssign:
    def test_basic(self):
        x = RNG.rand(2, 3, 4).astype("float32")
        match = np.array([[0, -1, 2, 1], [-1, 1, -1, 0]], np.int32)

        def build():
            xv = fluid.layers.data(name="x", shape=[2, 3, 4],
                                   dtype="float32", append_batch_size=False)
            mv = fluid.layers.data(name="m", shape=[2, 4], dtype="int32",
                                   append_batch_size=False)
            out, w = fluid.layers.detection.target_assign(
                xv, mv, mismatch_value=0)
            return out, w

        out, w = run(build, {"x": x, "m": match})
        out = np.asarray(out)
        w = np.asarray(w)
        for b in range(2):
            for m in range(4):
                if match[b, m] >= 0:
                    np.testing.assert_allclose(out[b, m], x[b, match[b, m]],
                                               rtol=1e-6)
                    assert w[b, m, 0] == 1.0
                else:
                    assert (out[b, m] == 0).all() and w[b, m, 0] == 0.0


def nms_oracle(boxes, scores, score_thr, nms_thr, top_k):
    idx = np.argsort(-scores, kind="stable")
    if top_k > -1:
        idx = idx[:top_k]
    keep = []
    for i in idx:
        if scores[i] <= score_thr:
            continue
        ok = True
        for j in keep:
            if np_iou(boxes[i], boxes[j]) > nms_thr:
                ok = False
                break
        if ok:
            keep.append(i)
    return keep


class TestMulticlassNMS:
    def test_vs_oracle(self):
        p, c = 6, 3
        boxes = np.zeros((1, p, 4), np.float32)
        for i in range(p):
            x0, y0 = RNG.rand(2) * 0.5
            boxes[0, i] = [x0, y0, x0 + 0.3 + RNG.rand() * 0.2,
                           y0 + 0.3 + RNG.rand() * 0.2]
        scores = RNG.rand(1, c, p).astype("float32")

        def build():
            b = fluid.layers.data(name="b", shape=[1, p, 4],
                                  dtype="float32", append_batch_size=False)
            s = fluid.layers.data(name="s", shape=[1, c, p],
                                  dtype="float32", append_batch_size=False)
            out = fluid.layers.detection.multiclass_nms(
                b, s, background_label=0, score_threshold=0.1,
                nms_threshold=0.4, keep_top_k=4)
            return (out,)

        out, = run(build, {"b": boxes, "s": scores})
        got = out.array() if isinstance(out, LoDTensor) else np.asarray(out)
        got = got.reshape(-1, 6)
        lod = out.lod[0] if isinstance(out, LoDTensor) else None
        n_det = (lod[1] - lod[0]) if lod is not None else got.shape[0]

        # oracle: per-class NMS (skip class 0), global top-4 by score
        cand = []
        for cls in range(1, c):
            for i in nms_oracle(boxes[0], scores[0, cls], 0.1, 0.4, -1):
                cand.append((cls, scores[0, cls, i], i))
        cand.sort(key=lambda t: -t[1])
        cand = cand[:4]
        assert n_det == len(cand)
        for row, (cls, sc, i) in zip(got, cand):
            assert int(row[0]) == cls
            np.testing.assert_allclose(row[1], sc, rtol=1e-5)
            np.testing.assert_allclose(row[2:], boxes[0, i], rtol=1e-5)


class TestDetectionMAP:
    def test_perfect_and_half(self):
        # 1 image, 2 gt boxes of class 1 and 2; detections: exact hit on
        # class 1, a miss (wrong location) on class 2
        gt = np.array([[[1, 0, 0.1, 0.1, 0.4, 0.4],
                        [2, 0, 0.5, 0.5, 0.9, 0.9]]], np.float32)
        det_perfect = np.array([[[1, 0.9, 0.1, 0.1, 0.4, 0.4],
                                 [2, 0.8, 0.5, 0.5, 0.9, 0.9]]], np.float32)
        det_half = np.array([[[1, 0.9, 0.1, 0.1, 0.4, 0.4],
                              [2, 0.8, 0.0, 0.0, 0.05, 0.05]]], np.float32)

        def build(det_name):
            d = fluid.layers.data(name=det_name, shape=[1, 2, 6],
                                  dtype="float32", append_batch_size=False)
            g = fluid.layers.data(name="g", shape=[1, 2, 6],
                                  dtype="float32", append_batch_size=False)
            m = fluid.layers.detection.detection_map(
                d, g, overlap_threshold=0.5, ap_version="integral",
                background_label=0)
            return (m,)

        m1, = run(lambda: build("d1"), {"d1": det_perfect, "g": gt})
        m2, = run(lambda: build("d2"), {"d2": det_half, "g": gt})
        np.testing.assert_allclose(float(np.asarray(m1)[0]), 1.0, atol=1e-6)
        np.testing.assert_allclose(float(np.asarray(m2)[0]), 0.5, atol=1e-6)


class TestSSDAcceptance:
    def test_ssd_loss_builds_and_descends(self):
        """Tiny SSD: multi_box_head over two feature maps + ssd_loss; one
        optimizer step must run and reduce the loss (reference book SSD
        config, layers/detection.py:350)."""
        B, C = 2, 4
        img_np = RNG.rand(B, 3, 32, 32).astype("float32")
        gt_boxes = [np.array([[0.1, 0.1, 0.45, 0.45]], np.float32),
                    np.array([[0.5, 0.5, 0.9, 0.9],
                              [0.2, 0.6, 0.5, 0.95]], np.float32)]
        gt_labels = [np.array([[1]], np.int64),
                     np.array([[2], [3]], np.int64)]

        main = fluid.Program()
        startup = fluid.Program()
        with fluid.program_guard(main, startup):
            img = fluid.layers.data(name="img", shape=[3, 32, 32],
                                    dtype="float32")
            gb = fluid.layers.data(name="gt_box", shape=[4], dtype="float32",
                                   lod_level=1)
            gl = fluid.layers.data(name="gt_label", shape=[1], dtype="int64",
                                   lod_level=1)
            c1 = fluid.layers.conv2d(img, num_filters=8, filter_size=3,
                                     stride=2, padding=1, act="relu")
            c2 = fluid.layers.conv2d(c1, num_filters=8, filter_size=3,
                                     stride=2, padding=1, act="relu")
            loc, conf, boxes, variances = \
                fluid.layers.detection.multi_box_head(
                    inputs=[c1, c2], image=img, base_size=32,
                    num_classes=C, aspect_ratios=[[2.0], [2.0]],
                    min_sizes=[4.0, 8.0], max_sizes=[8.0, 16.0],
                    flip=True, clip=True)
            loss = fluid.layers.detection.ssd_loss(
                loc, conf, gb, gl, boxes, variances)
            avg = fluid.layers.reduce_mean(loss)
            opt = fluid.optimizer.SGDOptimizer(learning_rate=0.1)
            opt.minimize(avg)

        exe = fluid.Executor(fluid.CPUPlace())
        feed = {"img": img_np, "gt_box": make_lod(gt_boxes),
                "gt_label": make_lod(gt_labels)}
        with executor_mod.scope_guard(executor_mod.Scope()):
            exe.run(startup)
            losses = []
            for _ in range(8):
                v, = exe.run(main, feed=feed, fetch_list=[avg])
                losses.append(float(np.asarray(v).reshape(-1)[0]))
        assert np.isfinite(losses).all()
        assert losses[-1] < losses[0]
