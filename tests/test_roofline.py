"""Roofline attribution (ISSUE 6): analytic op costs, probe/ridge math,
synthetic-xplane report joins, waterfall bucketing, and the bench-facing
top_ops summary. The synthetic traces hand-encode the XSpace wire format
so the tests pin the parser and the report logic together without a
device."""

import numpy as np

from paddle_tpu import roofline, xplane


class A:
    """Minimal aval stand-in: anything with .shape/.dtype."""

    def __init__(self, shape, dtype=np.float32):
        self.shape = tuple(shape)
        self.dtype = np.dtype(dtype)


# --- hand-rolled XSpace encoder (mirrors xplane.py's decoder) ---------------

def _varint(n):
    out = b""
    while True:
        b = n & 0x7F
        n >>= 7
        out += bytes([b | (0x80 if n else 0)])
        if not n:
            return out


def _field(fno, wt, payload):
    key = _varint((fno << 3) | wt)
    if wt == 2:
        return key + _varint(len(payload)) + payload
    return key + _varint(payload)


def _event(mid, off_ps, dur_ps):
    return (_field(1, 0, mid) + _field(2, 0, off_ps)
            + _field(3, 0, dur_ps))


def _line(name, ts_ns, events):
    buf = _field(2, 2, name.encode()) + _field(3, 0, ts_ns)
    for e in events:
        buf += _field(4, 2, e)
    return buf


def _meta(mid, name):
    inner = _field(1, 0, mid) + _field(2, 2, name.encode())
    return _field(1, 0, mid) + _field(2, 2, inner)


def _plane(name, lines, metas):
    buf = _field(2, 2, name.encode())
    for ln in lines:
        buf += _field(3, 2, ln)
    for m in metas:
        buf += _field(4, 2, m)
    return buf


def _write_xspace(path, planes):
    path.write_bytes(b"".join(_field(1, 2, p) for p in planes))


class TestOpCost:
    def test_matmul_flops_and_bytes(self):
        ins = {"X": [A((64, 128))], "Y": [A((128, 32))]}
        outs = {"Out": [A((64, 32))]}
        flops, bytes_ = roofline.op_cost("matmul", ins, outs)
        assert flops == 2 * 64 * 128 * 32
        assert bytes_ == 4 * (64 * 128 + 128 * 32 + 64 * 32)

    def test_matmul_transpose_x_uses_other_contraction_dim(self):
        ins = {"X": [A((128, 64))], "Y": [A((128, 32))]}
        outs = {"Out": [A((64, 32))]}
        flops, _ = roofline.op_cost("matmul", ins, outs,
                                    {"transpose_X": True})
        assert flops == 2 * 64 * 32 * 128

    def test_mul_respects_x_num_col_dims(self):
        ins = {"X": [A((8, 4, 16))], "Y": [A((64, 10))]}
        outs = {"Out": [A((8, 10))]}
        flops, _ = roofline.op_cost("mul", ins, outs, {"x_num_col_dims": 1})
        assert flops == 2 * 8 * 10 * (4 * 16)

    def test_conv2d_counts_macs_from_filter(self):
        ins = {"Input": [A((2, 3, 16, 16))], "Filter": [A((8, 3, 3, 3))]}
        outs = {"Output": [A((2, 8, 16, 16))]}
        flops, _ = roofline.op_cost("conv2d", ins, outs)
        assert flops == 2 * (2 * 8 * 16 * 16) * 3 * 3 * 3

    def test_grad_op_doubles_forward_work(self):
        ins = {"X": [A((64, 128))], "Y": [A((128, 32))],
               "Out@GRAD": [A((64, 32))]}
        outs = {"X@GRAD": [A((64, 128))], "Y@GRAD": [A((128, 32))]}
        flops, _ = roofline.op_cost("matmul_grad", ins, outs)
        assert flops == roofline._GRAD_FACTOR * 2 * 64 * 128 * 32

    def test_data_movement_is_zero_flops_nonzero_bytes(self):
        ins = {"X": [A((128, 64))]}
        outs = {"Out": [A((64, 128))]}
        flops, bytes_ = roofline.op_cost("reshape2", ins, outs)
        assert flops == 0.0
        assert bytes_ == 4 * 2 * 128 * 64

    def test_reduce_costs_input_elems(self):
        ins = {"X": [A((32, 32))]}
        outs = {"Out": [A(())]}
        flops, _ = roofline.op_cost("reduce_sum", ins, outs)
        assert flops == 32 * 32


class TestProbes:
    def test_env_overrides_and_ridge(self, monkeypatch):
        monkeypatch.setattr(roofline, "_PROBES", {})
        monkeypatch.setenv("PADDLE_TPU_SUSTAINED_TFLOPS", "0.5")
        monkeypatch.setenv("PADDLE_TPU_HBM_GBPS", "20")
        p = roofline.ensure_probes()
        assert p["sustained_tflops"] == 0.5
        assert p["hbm_gbps"] == 20.0
        assert p["ridge"] == (0.5e12) / (20e9)   # 25 flops/byte

    def test_probe_false_leaves_values_unmeasured(self, monkeypatch):
        monkeypatch.setattr(roofline, "_PROBES", {})
        monkeypatch.delenv("PADDLE_TPU_SUSTAINED_TFLOPS", raising=False)
        monkeypatch.delenv("PADDLE_TPU_HBM_GBPS", raising=False)
        p = roofline.ensure_probes(probe=False)
        assert p["sustained_tflops"] is None or "sustained_tflops" \
            not in roofline._PROBES
        assert p["ridge"] is None


class TestSyntheticReport:
    """End-to-end collect_report over a hand-encoded device plane: the
    attribution join, the per-row verdicts against the ridge, the
    (unattributed) pool, and the telemetry gauges."""

    HLO = """
  %fusion.1 = f32[256,256] fusion(f32[256,256] %p0), kind=kOutput, metadata={op_name="jit(step)/pd.matmul/dot_general"}
  %broadcast.7 = f32[256,256] broadcast(f32[] %c), metadata={op_name="jit(step)/pd.relu/max"}
"""

    def _trace(self, tmp_path):
        # fusion.1 appears on the raw line (40us) AND a derived line
        # (40us again): dedup must keep 40, not 80. unknown.9 has no HLO
        # mapping -> "(unattributed)".
        metas = [_meta(1, "fusion.1"), _meta(2, "broadcast.7"),
                 _meta(3, "unknown.9")]
        raw = _line("XLA Ops", 1000, [_event(1, 0, 40_000_000),
                                      _event(2, 40_000_000, 10_000_000),
                                      _event(3, 50_000_000, 10_000_000)])
        derived = _line("Steps", 1000, [_event(1, 0, 40_000_000)])
        _write_xspace(tmp_path / "t.xplane.pb",
                      [_plane("/device:TPU:0", [raw, derived], metas)])

    def _suppliers(self):
        n = 256
        cost = {"ops": {
            "matmul": {"flops": 2.0 * n ** 3,
                       "bytes": 3.0 * n * n * 4, "count": 1},
            "relu": {"flops": float(n * n),
                     "bytes": 2.0 * n * n * 4, "count": 1}}}
        cost["total_flops"] = sum(d["flops"] for d in cost["ops"].values())
        cost["total_bytes"] = sum(d["bytes"] for d in cost["ops"].values())
        return [(lambda: self.HLO, lambda: cost)]

    def test_verdicts_and_unattributed_pool(self, tmp_path, monkeypatch):
        monkeypatch.setattr(roofline, "_PROBES", {})
        monkeypatch.setenv("PADDLE_TPU_SUSTAINED_TFLOPS", "0.5")
        monkeypatch.setenv("PADDLE_TPU_HBM_GBPS", "20")
        self._trace(tmp_path)
        report = roofline.collect_report(str(tmp_path), self._suppliers(),
                                         steps=2)
        assert report is not None and report["mapped"]
        rows = {r["op"]: r for r in report["rows"]}
        assert set(rows) == {"matmul", "relu", roofline.UNATTRIBUTED}
        # dedup: 40us once, not the raw+derived 80us
        assert rows["matmul"]["ps"] == 40_000_000
        # matmul intensity 2*256^3/(3*256^2*4) ~ 42.7 >= ridge 25
        assert rows["matmul"]["bound"] == "compute"
        # relu intensity 256^2/(2*256^2*4) = 0.125 < 25
        assert rows["relu"]["bound"] == "memory"
        assert rows[roofline.UNATTRIBUTED]["bound"] == "unattributed"
        assert rows[roofline.UNATTRIBUTED]["flops"] is None
        assert abs(sum(r["frac"] for r in report["rows"]) - 1.0) < 1e-9
        # achieved TF/s: flops * steps over the op's device time
        mm = rows["matmul"]
        assert abs(mm["tflops"]
                   - (mm["flops"] * 2) / (mm["ps"] / 1e12) / 1e12) < 1e-9

    def test_format_report_and_top_ops(self, tmp_path, monkeypatch):
        monkeypatch.setattr(roofline, "_PROBES", {})
        monkeypatch.setenv("PADDLE_TPU_SUSTAINED_TFLOPS", "0.5")
        monkeypatch.setenv("PADDLE_TPU_HBM_GBPS", "20")
        self._trace(tmp_path)
        report = roofline.collect_report(str(tmp_path), self._suppliers(),
                                         steps=2)
        lines = roofline.format_report(report)
        device_rows = [ln for ln in lines if ln.startswith("[device] ")]
        assert device_rows[0].split()[1] == "matmul"
        assert any(roofline.UNATTRIBUTED in ln for ln in device_rows)
        assert any(ln.startswith("[roofline]") for ln in lines)
        top = roofline.top_ops(report, k=2)
        assert len(top) == 2 and top[0]["op"] == "matmul"
        assert top[0]["bound"] == "compute"
        assert top[0]["gflops"] == round(2.0 * 256 ** 3 / 1e9, 3)

    def test_foreign_trace_without_suppliers_still_reports(self, tmp_path,
                                                           monkeypatch):
        monkeypatch.setattr(roofline, "_PROBES", {})
        monkeypatch.setenv("PADDLE_TPU_SUSTAINED_TFLOPS", "0.5")
        monkeypatch.setenv("PADDLE_TPU_HBM_GBPS", "20")
        self._trace(tmp_path)
        report = roofline.collect_report(str(tmp_path), ())
        assert report is not None and not report["mapped"]
        assert all(r["bound"] == "unattributed" for r in report["rows"])


class TestWaterfall:
    def test_buckets_and_duty_cycle(self, tmp_path):
        # busiest line: compute 40us, all-reduce 20us, infeed copy 10us,
        # then a 30us hole before a final 0-width marker -> span 100us
        metas = [_meta(1, "fusion.1"), _meta(2, "all-reduce.2"),
                 _meta(3, "copy.3"), _meta(4, "fusion.4")]
        busy = _line("XLA Ops", 1000, [
            _event(1, 0, 40_000_000),
            _event(2, 40_000_000, 20_000_000),
            _event(3, 60_000_000, 10_000_000),
            _event(4, 100_000_000, 0)])
        idle = _line("Steps", 1000, [_event(1, 0, 40_000_000)])
        _write_xspace(tmp_path / "t.xplane.pb",
                      [_plane("/device:TPU:0", [busy, idle], metas)])
        wf = roofline.waterfall(str(tmp_path))
        assert wf is not None
        assert wf["compute_ps"] == 40_000_000
        assert wf["collective_ps"] == 20_000_000
        assert wf["infeed_ps"] == 10_000_000
        assert wf["span_ps"] == 100_000_000
        assert wf["host_gap_ps"] == 30_000_000
        assert abs(wf["device_duty_cycle"] - 0.7) < 1e-9

    def test_host_fallback_ignores_bookkeeping_lines(self, tmp_path):
        # CPU-backend shape: a python line spanning the whole session and
        # an XLA thread line with the real instructions. The waterfall
        # must anchor on the instruction line.
        metas = [_meta(1, "$profiler.py:226 trace"), _meta(2, "dot.3")]
        py = _line("python", 500, [_event(1, 0, 1_000_000_000)])
        xla = _line("tf_XLATfrtCpuClient/1", 500,
                    [_event(2, 0, 50_000_000)])
        _write_xspace(tmp_path / "t.xplane.pb",
                      [_plane("/host:CPU", [py, xla], metas)])
        wf = roofline.waterfall(str(tmp_path))
        assert wf is not None
        assert wf["compute_ps"] == 50_000_000
        assert wf["span_ps"] == 50_000_000
        assert wf["device_duty_cycle"] == 1.0


class TestAggregateDedup:
    def test_device_plane_max_across_lines_then_sum_across_planes(
            self, tmp_path):
        metas = [_meta(1, "fusion.1")]
        raw = _line("XLA Ops", 0, [_event(1, 0, 10)])
        derived = _line("Steps", 0, [_event(1, 0, 7)])
        p0 = _plane("/device:TPU:0", [raw, derived], metas)
        p1 = _plane("/device:TPU:1", [raw], metas)
        _write_xspace(tmp_path / "t.xplane.pb", [p0, p1])
        agg = xplane.aggregate_dir(str(tmp_path))
        # per plane: max(10, 7) = 10; across planes: 10 + 10
        assert agg == {"fusion.1": 20}
