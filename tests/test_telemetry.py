"""Unified telemetry subsystem (ISSUE 1): registry semantics, executor run
tracing + retrace cause, Prometheus exposition round-trip, JSONL step log,
merged chrome trace, CLI subcommand, cross-host reduce (real 2-process
jax.distributed, same harness as test_jax_distributed), and the satellite
fixes that rode along (print-op grad, conv_operator filter, threadpool
submit/shutdown atomicity, xplane device-plane aggregation)."""

import json
import math
import os
import socket
import subprocess
import sys
import threading

import numpy as np
import pytest

import paddle_tpu as fluid
from paddle_tpu import telemetry

HERE = os.path.dirname(os.path.abspath(__file__))


@pytest.fixture(autouse=True)
def _fresh_registry():
    telemetry.reset()
    yield
    telemetry.disable_step_log()
    telemetry.reset()


# --- registry semantics ------------------------------------------------------

class TestMetricPrimitives:
    def test_counter_inc_and_labels(self):
        c = telemetry.counter("t_total", "help txt", labels=("op",))
        c.labels(op="a").inc()
        c.labels(op="a").inc(2.5)
        c.labels(op="b").inc()
        snap = telemetry.snapshot()
        assert snap["counters"]["t_total"] == {"op=a": 3.5, "op=b": 1.0}

    def test_label_free_family_proxies_single_child(self):
        telemetry.counter("t_plain").inc(4)
        assert telemetry.snapshot()["counters"]["t_plain"] == {"": 4.0}

    def test_gauge_set_overwrites(self):
        g = telemetry.gauge("t_g")
        g.set(5)
        g.set(2.5)
        assert telemetry.snapshot()["gauges"]["t_g"][""] == 2.5

    def test_histogram_buckets_cumulative_sum_count(self):
        h = telemetry.histogram("t_h", buckets=(0.1, 1.0, 10.0))
        for v in (0.05, 0.5, 5.0, 50.0):   # one per bucket + overflow
            h.observe(v)
        s = telemetry.snapshot()["histograms"]["t_h"][""]
        assert s["buckets"] == [0.1, 1.0, 10.0]
        assert s["counts"] == [1, 1, 1, 1]
        assert s["count"] == 4
        assert abs(s["sum"] - 55.55) < 1e-9

    def test_registration_idempotent_but_kind_conflict_raises(self):
        assert telemetry.counter("t_dup") is telemetry.counter("t_dup")
        with pytest.raises(ValueError, match="already registered"):
            telemetry.gauge("t_dup")

    def test_wrong_label_names_raise(self):
        c = telemetry.counter("t_lbl", labels=("a",))
        with pytest.raises(ValueError, match="takes labels"):
            c.labels(b="x")
        with pytest.raises(ValueError, match="use .labels"):
            c.inc()

    def test_default_buckets_log_scale(self):
        b = telemetry.default_buckets()
        assert b[0] == pytest.approx(1e-6)
        assert all(hi / lo == pytest.approx(4.0)
                   for lo, hi in zip(b, b[1:]))

    def test_concurrent_increments_do_not_lose_updates(self):
        c = telemetry.counter("t_race")

        def spin():
            for _ in range(1000):
                c.inc()

        ts = [threading.Thread(target=spin) for _ in range(8)]
        for t in ts:
            t.start()
        for t in ts:
            t.join()
        assert telemetry.snapshot()["counters"]["t_race"][""] == 8000.0


def test_histogram_quantile_tail_clamp_is_counted():
    """When the requested rank falls in the +Inf bucket the returned
    value is the last finite edge — a floor, not an estimate. That clamp
    must be observable: telemetry_quantile_tail_clamped_total{name}
    increments exactly when it happens (ISSUE 16 satellite)."""
    h = telemetry.histogram("t_clamp", buckets=(0.1, 1.0), labels=("k",))
    h.labels(k="a").observe(0.05)
    h.labels(k="a").observe(50.0)      # +Inf tail
    # p25 resolves inside a finite bucket: no clamp counted
    assert telemetry.histogram_quantile("t_clamp", 0.25, k="a") \
        == pytest.approx(0.05, abs=0.05)
    assert telemetry.read_series(
        "telemetry_quantile_tail_clamped_total") == {}
    # p99's rank lands in the overflow: clamped to the last edge + count
    assert telemetry.histogram_quantile("t_clamp", 0.99, k="a") == 1.0
    clamped = telemetry.read_series("telemetry_quantile_tail_clamped_total")
    assert clamped == {"name=t_clamp": 1.0}
    telemetry.histogram_quantile("t_clamp", 0.99, k="a")
    clamped = telemetry.read_series("telemetry_quantile_tail_clamped_total")
    assert clamped == {"name=t_clamp": 2.0}


# --- executor run tracing (ISSUE acceptance criteria) ------------------------

def _build_train_program():
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        x = fluid.layers.data(name="x", shape=[4], dtype="float32")
        y = fluid.layers.data(name="y", shape=[1], dtype="float32")
        pred = fluid.layers.fc(input=x, size=1)
        loss = fluid.layers.mean(
            fluid.layers.square_error_cost(input=pred, label=y))
        fluid.optimizer.SGD(learning_rate=0.01).minimize(loss)
    return main, startup, loss


def _feed(n):
    rng = np.random.default_rng(0)
    return {"x": rng.standard_normal((n, 4)).astype("float32"),
            "y": rng.standard_normal((n, 1)).astype("float32")}


class TestExecutorTracing:
    def test_two_step_run_events_and_retrace_signature(self, tmp_path):
        log = str(tmp_path / "steps.jsonl")
        telemetry.enable_step_log(log)
        main, startup, loss = _build_train_program()
        exe = fluid.Executor(fluid.CPUPlace())
        exe.run(startup)
        for _ in range(2):
            exe.run(main, feed=_feed(8), fetch_list=[loss])

        events = telemetry.recent_events()
        compiles = [e for e in events if e["kind"] == "compile"]
        runs = [e for e in events if e["kind"] == "run"]
        # >= because the startup program compiles+runs too
        assert len(compiles) >= 1
        assert len(runs) >= 2
        assert all(e["kind"] != "cache_miss" for e in events)
        train_runs = [e for e in runs if e.get("mode") == "jit"
                      and e.get("feeds") == 2]
        assert len(train_runs) >= 2
        for e in train_runs:
            assert e["seconds"] >= e["execute_s"] >= 0
            assert e["compile_s"] >= 0
            assert e["feeds"] == 2 and e["fetches"] == 1
        assert train_runs[0]["cache"] == "miss"
        assert train_runs[1]["cache"] == "hit"

        # matching counters on the Prometheus surface
        text = telemetry.prometheus_text()
        assert "executor_runs_total" in text
        assert "executor_compiles_total" in text
        assert "optimizer_steps_total" in text
        snap = telemetry.snapshot()
        assert sum(snap["counters"]["executor_runs_total"].values()) == \
            len(runs)
        assert sum(snap["counters"]["executor_compiles_total"].values()) == \
            len(compiles)

        # changed batch size -> exactly one retrace event carrying the
        # NEW signature
        exe.run(main, feed=_feed(16), fetch_list=[loss])
        misses = [e for e in telemetry.recent_events()
                  if e["kind"] == "cache_miss"]
        assert len(misses) == 1
        sig = misses[0]["signature"]
        assert ["x", "(16, 4)", "float32"] in sig
        assert ["y", "(16, 1)", "float32"] in sig
        assert misses[0]["changed"]
        assert sum(telemetry.snapshot()["counters"]
                   ["executor_cache_misses_total"].values()) == 1

        # the same records landed in the JSONL file
        telemetry.disable_step_log()
        recs = telemetry.read_step_log(log)
        kinds = [r["kind"] for r in recs]
        assert kinds.count("run") >= 3
        assert kinds.count("compile") >= 1
        assert kinds.count("cache_miss") == 1
        assert all("ts" in r and "host" in r for r in recs)

    def test_global_norm_gauge_with_clipping(self):
        main, startup = fluid.Program(), fluid.Program()
        with fluid.program_guard(main, startup):
            x = fluid.layers.data(name="x", shape=[4], dtype="float32")
            y = fluid.layers.data(name="y", shape=[1], dtype="float32")
            pred = fluid.layers.fc(input=x, size=1)
            loss = fluid.layers.mean(
                fluid.layers.square_error_cost(input=pred, label=y))
            fluid.clip.set_gradient_clip(
                fluid.clip.GradientClipByGlobalNorm(clip_norm=1.0))
            fluid.optimizer.SGD(learning_rate=0.01).minimize(loss)
        exe = fluid.Executor(fluid.CPUPlace())
        exe.run(startup)
        out = exe.run(main, feed=_feed(8), fetch_list=[loss])
        assert len(out) == 1   # side-fetch must not leak to the caller
        gauges = telemetry.snapshot()["gauges"]
        (norm,) = gauges["optimizer_global_norm"].values()
        assert norm > 0
        # and minimize() counted the build
        assert telemetry.snapshot()["counters"][
            "optimizer_minimize_total"]["optimizer=sgd"] >= 1

    def test_feed_conversion_metrics(self):
        main, startup = fluid.Program(), fluid.Program()
        with fluid.program_guard(main, startup):
            x = fluid.layers.data(name="x", shape=[3], dtype="float32")
        feeder = fluid.DataFeeder(feed_list=[x], place=fluid.CPUPlace(),
                                  program=main)
        feeder.feed([(np.zeros(3, np.float32),) for _ in range(4)])
        snap = telemetry.snapshot()
        assert snap["counters"]["feed_conversion_seconds_total"][""] > 0
        assert snap["histograms"]["feed_conversion_seconds"][""]["count"] == 1

    def test_input_stall_histogram(self):
        from paddle_tpu.reader.pipeline import DoubleBufferedFeeder
        feeder = DoubleBufferedFeeder(
            lambda: iter([{"a": np.zeros(2)}] * 3))
        assert len(list(feeder)) == 3
        snap = telemetry.snapshot()
        assert snap["counters"]["input_batches_total"][""] == 3.0
        assert snap["histograms"]["input_stall_seconds"][""]["count"] >= 3


# --- Prometheus text round-trip ----------------------------------------------

def _parse_prometheus(text):
    """Minimal exposition-format parser: {(name, labels-string): value}."""
    out = {}
    for line in text.splitlines():
        if not line or line.startswith("#"):
            continue
        metric, val = line.rsplit(" ", 1)
        name, _, labels = metric.partition("{")
        out[(name, labels.rstrip("}"))] = float(
            "inf" if val == "+Inf" else val)
    return out


class TestPrometheusExport:
    def test_round_trip_counter_gauge(self):
        telemetry.counter("rt_total", labels=("k",)).labels(k='va"l').inc(7)
        telemetry.gauge("rt_g").set(0.25)
        parsed = _parse_prometheus(telemetry.prometheus_text())
        assert parsed[("rt_total", 'k="va\\"l"')] == 7.0
        assert parsed[("rt_g", "")] == 0.25

    def test_histogram_exposition_is_cumulative_with_inf(self):
        h = telemetry.histogram("rt_h", buckets=(0.1, 1.0))
        h.observe(0.05)
        h.observe(0.5)
        h.observe(5.0)
        text = telemetry.prometheus_text()
        parsed = _parse_prometheus(text)
        assert parsed[("rt_h_bucket", 'le="0.1"')] == 1
        assert parsed[("rt_h_bucket", 'le="1"')] == 2
        assert parsed[("rt_h_bucket", 'le="+Inf"')] == 3
        assert parsed[("rt_h_count", "")] == 3
        assert parsed[("rt_h_sum", "")] == pytest.approx(5.55)
        assert "# TYPE rt_h histogram" in text

    def test_help_and_type_lines(self):
        telemetry.counter("rt_doc_total", "documented metric").inc()
        text = telemetry.prometheus_text()
        assert "# HELP rt_doc_total documented metric" in text
        assert "# TYPE rt_doc_total counter" in text


# --- step log + chrome trace + CLI -------------------------------------------

class TestStepLogAndExports:
    def test_read_step_log_tolerates_torn_tail(self, tmp_path):
        p = tmp_path / "log.jsonl"
        telemetry.enable_step_log(str(p))
        telemetry.log_event("run", seconds=0.5)
        telemetry.disable_step_log()
        with open(p, "a") as f:
            f.write('{"kind": "run", "seco')   # crash mid-write
        recs = telemetry.read_step_log(str(p))
        assert len(recs) == 1 and recs[0]["seconds"] == 0.5

    def test_merged_chrome_trace(self, tmp_path):
        from paddle_tpu import profiler
        with profiler.profiler():
            with profiler.record("host_evt"):
                pass
        telemetry.log_event("run", seconds=0.001, program="p0")
        out = tmp_path / "trace.json"
        telemetry.export_chrome_trace(str(out))
        trace = json.loads(out.read_text())
        names = {e["name"] for e in trace["traceEvents"]}
        assert "host_evt" in names
        assert "run" in names
        cats = {e["name"]: e["cat"] for e in trace["traceEvents"]}
        assert cats["host_evt"] == "host"
        assert cats["run"] == "step"
        # profiler events publish into the registry too
        hist = telemetry.snapshot()["histograms"]["profiler_event_seconds"]
        assert hist["event=host_evt"]["count"] == 1

    def test_cli_snapshot_prometheus_and_log(self, tmp_path, capsys):
        from paddle_tpu import cli
        telemetry.counter("cli_total").inc(2)
        assert cli.main(["telemetry"]) == 0
        out = capsys.readouterr().out
        assert "cli_total = 2" in out
        assert cli.main(["telemetry", "--prometheus"]) == 0
        assert "cli_total 2" in capsys.readouterr().out

        log = tmp_path / "s.jsonl"
        telemetry.enable_step_log(str(log))
        telemetry.log_event("run", seconds=0.01)
        telemetry.log_event("cache_miss",
                            signature=[["x", "(8,)", "float32"]])
        telemetry.disable_step_log()
        assert cli.main(["telemetry", "--log", str(log)]) == 0
        out = capsys.readouterr().out
        assert "2 events" in out and "cache_miss" in out
        assert "retrace signature" in out
        assert cli.main(["telemetry", "--log", str(log), "--tail", "1"]) == 0
        (line,) = capsys.readouterr().out.strip().splitlines()
        assert json.loads(line)["kind"] == "cache_miss"

    def test_env_var_enables_step_log(self, tmp_path):
        p = tmp_path / "env.jsonl"
        env = dict(os.environ, JAX_PLATFORMS="cpu",
                   PADDLE_TPU_STEP_LOG=str(p))
        code = ("import paddle_tpu.telemetry as t; "
                "t.log_event('run', seconds=1.0)")
        subprocess.run([sys.executable, "-c", code], check=True, env=env)
        recs = telemetry.read_step_log(str(p))
        assert len(recs) == 1 and recs[0]["kind"] == "run"


# --- cross-host reduce -------------------------------------------------------

class TestReduce:
    def test_single_process_reduce_is_local(self):
        telemetry.counter("r_total").inc(3)
        snap = telemetry.snapshot(reduce=True)
        assert snap["counters"]["r_total"][""] == 3.0

    def test_merge_snapshots_sums_all_kinds(self):
        a = {"counters": {"c": {"k=a": 1.0}}, "gauges": {"g": {"": 2.0}},
             "histograms": {"h": {"": {"buckets": [1.0], "counts": [1, 0],
                                       "sum": 0.5, "count": 1}}}}
        b = {"counters": {"c": {"k=a": 2.0, "k=b": 5.0}},
             "gauges": {"g": {"": 3.0}},
             "histograms": {"h": {"": {"buckets": [1.0], "counts": [0, 2],
                                       "sum": 4.0, "count": 2}}}}
        m = telemetry._merge_snapshots([a, b])
        assert m["hosts"] == 2
        assert m["counters"]["c"] == {"k=a": 3.0, "k=b": 5.0}
        assert m["gauges"]["g"][""] == 5.0
        h = m["histograms"]["h"][""]
        assert h["counts"] == [1, 2] and h["count"] == 3
        assert h["sum"] == pytest.approx(4.5)

    def test_two_process_reduce(self):
        """Real 2-process jax.distributed reduce over the coordination
        service (harness: test_jax_distributed)."""
        with socket.socket() as s:
            s.bind(("127.0.0.1", 0))
            coordinator = f"127.0.0.1:{s.getsockname()[1]}"
        env = dict(os.environ, JAX_PLATFORMS="cpu")
        env.pop("XLA_FLAGS", None)
        env.pop("PADDLE_TRAINER_ID", None)
        env["PYTHONPATH"] = os.pathsep.join(
            [os.path.dirname(HERE), env.get("PYTHONPATH", "")])
        procs = [subprocess.Popen(
            [sys.executable, os.path.join(HERE, "_telemetry_worker.py"),
             coordinator, "2", str(pid)],
            env=env, stdout=subprocess.PIPE, stderr=subprocess.PIPE,
            text=True) for pid in (0, 1)]
        outs = []
        try:
            for p in procs:
                out, err = p.communicate(timeout=180)
                outs.append((p.returncode, out, err))
        finally:
            for p in procs:
                if p.poll() is None:
                    p.kill()
        for rc, out, err in outs:
            assert rc == 0, f"worker failed rc={rc}\nstdout:{out}\n" \
                            f"stderr:{err}"
            assert "RESULT" in out, out
        results = [json.loads(out.split("RESULT", 1)[1])
                   for _, out, _ in outs]
        assert all(r["counter"] == 3 for r in results)


# --- xplane aggregation (satellite) ------------------------------------------

def _varint(n):
    out = b""
    while True:
        b7 = n & 0x7F
        n >>= 7
        out += bytes([b7 | (0x80 if n else 0)])
        if not n:
            return out


def _ld(fno, payload):
    return _varint((fno << 3) | 2) + _varint(len(payload)) + payload


def _vi(fno, val):
    return _varint(fno << 3) + _varint(val)


def _xevent(mid, ps):
    return _ld(4, _vi(1, mid) + _vi(3, ps))   # XLine.events=4


def _xline(events):
    return b"".join(events)


def _xplane(name, lines, meta):
    body = _ld(2, name.encode())
    for line in lines:
        body += _ld(3, line)
    for mid, mname in meta.items():
        body += _ld(4, _vi(1, mid) + _ld(2, _vi(1, mid) +
                                         _ld(2, mname.encode())))
    return _ld(1, body)


class TestXplaneAggregation:
    def _write(self, tmp_path, planes):
        d = tmp_path / "trace"
        d.mkdir()
        (d / "host.xplane.pb").write_bytes(b"".join(planes))
        return str(d)

    def test_device_planes_dedup_derived_lines(self, tmp_path):
        from paddle_tpu import xplane
        meta = {1: "fusion.1", 2: "copy.2"}
        # raw XLA-op line + a derived step line repeating the instruction:
        # per-name MAX across lines, not the double-counted sum
        raw = _xline([_xevent(1, 100), _xevent(2, 30)])
        derived = _xline([_xevent(1, 100)])
        dev0 = _xplane("/device:TPU:0", [raw, derived], meta)
        dev1 = _xplane("/device:TPU:1", [raw], meta)
        host = _xplane("/host:CPU", [_xline([_xevent(1, 999)])], meta)
        agg = xplane.aggregate_dir(self._write(tmp_path, [dev0, dev1, host]))
        assert agg == {"fusion.1": 200, "copy.2": 60}   # summed per core

    def test_host_only_trace_falls_back(self, tmp_path):
        from paddle_tpu import xplane
        meta = {1: "op.a"}
        host = _xplane("/host:CPU",
                       [_xline([_xevent(1, 10)]), _xline([_xevent(1, 5)])],
                       meta)
        agg = xplane.aggregate_dir(self._write(tmp_path, [host]))
        # host fallback applies the SAME per-name max-across-lines dedup
        # as device planes (derived lines double-count there too)
        assert agg == {"op.a": 10}

    def test_aggregate_lines_per_line_view(self, tmp_path):
        from paddle_tpu import xplane
        meta = {1: "op.a"}
        plane = _xplane("/device:TPU:0",
                        [_xline([_xevent(1, 10)]), _xline([_xevent(1, 7)])],
                        meta)
        d = self._write(tmp_path, [plane])
        (path,) = [os.path.join(d, f) for f in os.listdir(d)]
        per = xplane.aggregate_lines(path)["/device:TPU:0"]
        assert [la.get("op.a") for la in per] == [10, 7]


# --- satellite regression tests ----------------------------------------------

class TestSatellites:
    def test_print_op_grad_is_identity(self):
        main, startup = fluid.Program(), fluid.Program()
        with fluid.program_guard(main, startup):
            x = fluid.layers.data(name="x", shape=[3], dtype="float32",
                                  append_batch_size=False,
                                  stop_gradient=False)
            printed = fluid.layers.Print(x, message="t: ")
            y = fluid.layers.reduce_sum(
                fluid.layers.elementwise_mul(printed, printed))
            (gx,) = fluid.calc_gradient(y, x)
        exe = fluid.Executor(fluid.CPUPlace())
        exe.run(startup)
        xv = np.array([1.0, -2.0, 3.0], np.float32)
        from paddle_tpu import executor as executor_mod
        with executor_mod.scope_guard(executor_mod.Scope()):
            (g,) = exe.run(main, feed={"x": xv}, fetch_list=[gx])
        np.testing.assert_allclose(np.asarray(g), 2 * xv, rtol=1e-6)

    def test_conv_operator_rejects_filter_layer(self):
        from paddle_tpu import v2
        main, startup = fluid.Program(), fluid.Program()
        with fluid.program_guard(main, startup):
            img = fluid.layers.data(name="img", shape=[1, 8, 8],
                                    dtype="float32")
            with pytest.raises(ValueError, match="filter"):
                v2.layer.conv_operator(img, filter=img, filter_size=3,
                                       num_filters=2)

    def test_threadpool_submit_vs_shutdown_no_stranded_task(self):
        """A task that passed the closed check must run even when
        shutdown() lands immediately after — previously its queue entry
        could sit behind the _SHUTDOWN sentinels forever."""
        from paddle_tpu.threadpool import ThreadPool
        for _ in range(50):
            pool = ThreadPool(2)
            barrier = threading.Barrier(2)
            futs = []

            def submitter():
                barrier.wait()
                try:
                    for _ in range(20):
                        futs.append(pool.run(lambda: None))
                except RuntimeError:
                    pass           # closed: acceptable, just not a hang

            t = threading.Thread(target=submitter)
            t.start()
            barrier.wait()
            pool.shutdown()
            t.join(timeout=10)
            assert not t.is_alive()
            for f in futs:         # accepted => must complete
                f.result(timeout=10)

    def test_threadpool_run_after_shutdown_raises(self):
        from paddle_tpu.threadpool import ThreadPool
        pool = ThreadPool(1)
        pool.shutdown()
        with pytest.raises(RuntimeError, match="shut down"):
            pool.run(lambda: None)
