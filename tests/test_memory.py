"""Memory observability (ISSUE 4): static HBM analysis parity, the HLO
peak-liveness walk, live tracker classification, the what-if headroom
predictor's error bound, donation audit, checkpoint-size telemetry,
per-shard parameter bytes under GSPMD, and OOMError forensics through the
flight-recorder crash report."""

import json
import warnings

import numpy as np
import pytest

import paddle_tpu as fluid
from paddle_tpu import cli, inspector, memory, parallel, telemetry
from paddle_tpu import executor as executor_mod
from paddle_tpu.errors import OOMError


@pytest.fixture(autouse=True)
def _fresh():
    telemetry.reset()
    memory.reset()
    yield
    inspector.disable_flight_recorder()
    telemetry.reset()
    memory.reset()


def _smoke(name="fit_a_line"):
    spec = memory.build_smoke(name)
    exe = fluid.Executor(fluid.CPUPlace())
    exe.run(spec["startup"])
    return exe, spec


# ---------------------------------------------------------------------------
# Pure helpers
# ---------------------------------------------------------------------------

class TestHelpers:
    def test_shape_bytes(self):
        assert memory.shape_bytes("f32[128,13]{1,0}") == 128 * 13 * 4
        assert memory.shape_bytes("bf16[8]") == 16
        assert memory.shape_bytes("(f32[8,16], s8[4])") == 8 * 16 * 4 + 4
        assert memory.shape_bytes("pred[]") == 1
        assert memory.shape_bytes("token[]") == 0

    def test_nbytes_of_never_reads_data(self):
        import jax
        aval = jax.ShapeDtypeStruct((1 << 20, 13), np.float32)
        assert memory.nbytes_of(aval) == (1 << 20) * 13 * 4
        assert memory.nbytes_of(np.zeros((2, 3), np.float64)) == 48
        assert memory.nbytes_of(None) == 0

    def test_is_oom(self):
        assert memory.is_oom(RuntimeError(
            "RESOURCE_EXHAUSTED: Out of memory allocating 123 bytes"))
        assert memory.is_oom(RuntimeError("ran Out of memory on chip"))
        assert not memory.is_oom(ValueError("shapes do not match"))

    def test_hlo_peak_liveness_synthetic(self):
        hlo = """\
HloModule test, is_scheduled=true

ENTRY %main (p0: f32[4]) -> f32[4] {
  %p0 = f32[4]{0} parameter(0), metadata={op_name="jit(f)/pd.feed/x"}
  %a = f32[4]{0} add(f32[4]{0} %p0, f32[4]{0} %p0), metadata={op_name="jit(f)/pd.elementwise_add/add"}
  %b = f32[4]{0} multiply(f32[4]{0} %a, f32[4]{0} %p0), metadata={op_name="jit(f)/pd.mul/mul"}
  ROOT %c = f32[4]{0} add(f32[4]{0} %b, f32[4]{0} %a)
}
"""
        peak = memory.hlo_peak_liveness(hlo)
        # all four 16-byte buffers overlap at the ROOT: param pinned to the
        # end, a/b both used at pos 3, plus the ROOT output itself
        assert peak["n_instructions"] == 4
        assert peak["peak_bytes"] == 64
        assert peak["live_at_peak"] == 4
        by_instr = {r["instruction"]: r for r in peak["top"]}
        assert by_instr["a"]["op"] == "elementwise_add"
        assert by_instr["c"]["op"] == "add"  # no metadata -> opcode

    def test_headroom_model_exact_linear(self):
        model = memory.HeadroomModel.fit([(4, 1400), (16, 2600),
                                          (64, 7400)])
        assert model.predict(32) == 1000 + 100 * 32
        assert model.max_batch(11_000) == 100
        assert model.max_batch(500) == 0
        flat = memory.HeadroomModel(1000, 0.0)
        assert flat.max_batch(1 << 30) is None
        with pytest.raises(ValueError):
            memory.HeadroomModel.fit([(8, 100), (8, 100)])


# ---------------------------------------------------------------------------
# Static analysis
# ---------------------------------------------------------------------------

class TestStaticAnalysis:
    def test_parity_with_param_bytes(self):
        scope = executor_mod.global_scope()
        exe, spec = _smoke()
        rec = exe.static_memory_analysis(
            spec["main"], feed=spec["feed_fn"](8),
            fetch_list=[spec["loss"]])
        param_bytes = sum(
            memory.nbytes_of(scope.find_var(p.name))
            for p in spec["main"].global_block().all_parameters())
        assert param_bytes > 0
        # the arguments of the compiled step include every parameter
        assert rec.argument_bytes >= param_bytes
        assert rec.total_bytes >= rec.argument_bytes - rec.alias_bytes
        assert rec.donated_bytes >= param_bytes
        # liveness walk found a peak and attributed it to IR ops
        assert rec.peak and rec.peak["peak_bytes"] > 0
        assert rec.peak["top"]
        assert rec is memory.latest_record(rec.program)

    def test_aval_feeds_never_materialize(self):
        # a ~52 GiB feed: static analysis must accept the aval without
        # allocating anything close to that on the host
        exe, spec = _smoke()
        rec = exe.static_memory_analysis(
            spec["main"], feed=spec["feed_fn"](1_000_000_000),
            fetch_list=[spec["loss"]])
        assert rec.argument_bytes > 52 * (1 << 30)

    def test_executor_on_compile_publishes(self, tmp_path):
        inspector.enable_flight_recorder(str(tmp_path / "crash.json"))
        exe, spec = _smoke()
        exe.run(spec["main"], feed=spec["data_fn"](4),
                fetch_list=[spec["loss"]])
        label = telemetry.program_label(spec["main"])
        assert memory.latest_record(label) is not None
        total = telemetry.read_gauge("memory_total_bytes", program=label)
        assert total and total > 0
        events = [e for e in telemetry.recent_events(100)
                  if e.get("kind") == "memory_analysis"]
        assert any(e.get("program") == label for e in events)
        # second signature does NOT re-run the analysis
        n_before = len(events)
        exe.run(spec["main"], feed=spec["data_fn"](6),
                fetch_list=[spec["loss"]])
        n_after = len([e for e in telemetry.recent_events(100)
                       if e.get("kind") == "memory_analysis"])
        assert n_after == n_before
        # flight-recorder step records carry the hbm sample
        rec = inspector._RECORDER.records[-1]
        assert rec.get("hbm_bytes_in_use") is not None


# ---------------------------------------------------------------------------
# Live tracker
# ---------------------------------------------------------------------------

class TestTracker:
    def test_classification(self):
        main, startup = fluid.Program(), fluid.Program()
        with fluid.program_guard(main, startup):
            x = fluid.layers.data(name="x", shape=[8], dtype="float32")
            y = fluid.layers.data(name="y", shape=[1], dtype="float32")
            pred = fluid.layers.fc(input=x, size=1)
            loss = fluid.layers.mean(
                fluid.layers.square_error_cost(input=pred, label=y))
            # Momentum so the state carries optimizer accumulators
            fluid.optimizer.Momentum(
                learning_rate=0.1, momentum=0.9).minimize(
                    loss, startup_program=startup)
        exe = fluid.Executor(fluid.CPUPlace())
        exe.run(startup)
        exe.run(main, feed={"x": np.zeros((4, 8), np.float32),
                            "y": np.zeros((4, 1), np.float32)},
                fetch_list=[loss])
        last = memory.tracker().last
        assert last["source"] in ("device", "live_arrays")
        assert last["bytes_in_use"] > 0
        cls = last["classes"]
        assert cls["params"] >= (8 * 1 + 1) * 4      # w + b
        assert cls["opt_state"] > 0                  # velocity + lr
        assert cls["feeds"] == 4 * 8 * 4 + 4 * 1 * 4
        assert cls["activations"] >= 0
        assert telemetry.read_series("hbm_bytes_in_use")
        assert telemetry.read_gauge(
            "hbm_class_bytes", device=last["device"],
            kind="params") == cls["params"]

    def test_peak_is_monotone(self):
        t = memory.MemoryTracker()
        t.sample()
        first = t.peak_bytes
        t.sample()
        assert t.peak_bytes >= first


# ---------------------------------------------------------------------------
# What-if headroom
# ---------------------------------------------------------------------------

class TestWhatIf:
    def test_predictor_error_bound(self):
        exe, spec = _smoke()

        def measure(b):
            return exe.static_memory_analysis(
                spec["main"], feed=spec["feed_fn"](b),
                fetch_list=[spec["loss"]])

        res = memory.what_if(measure, batches=(8, 32),
                             budget_bytes=1 << 20)
        assert res["max_batch"] > 32
        assert res["validate_batch"] == res["max_batch"]
        # acceptance bound: measured peak within 15% of the estimate
        assert res["rel_err"] <= 0.15
        assert res["model"]["per_item_bytes"] > 0

    @pytest.mark.slow
    def test_predictor_error_bound_resnet(self):
        exe, spec = _smoke("resnet")

        def measure(b):
            return exe.static_memory_analysis(
                spec["main"], feed=spec["feed_fn"](b),
                fetch_list=[spec["loss"]])

        res = memory.what_if(measure, batches=(2, 8),
                             budget_bytes=256 << 20)
        assert res["max_batch"] > 8
        assert res["rel_err"] <= 0.15


# ---------------------------------------------------------------------------
# Donation audit
# ---------------------------------------------------------------------------

class TestDonationAudit:
    def test_warns_once_and_counts(self):
        rec = memory.ProgramMemory(program="p_test")
        rec.donated_bytes = 1000
        rec.alias_bytes = 0
        rec.donation_lost_bytes = 1000
        with warnings.catch_warnings(record=True) as w:
            warnings.simplefilter("always")
            memory._audit_donation(rec)
            memory._audit_donation(rec)
        audits = [x for x in w if "not aliased by XLA" in str(x.message)]
        assert len(audits) == 1                       # once per process
        ctr = telemetry.read_series("donation_fallback_total")
        assert ctr.get("program=p_test") == 2.0       # counted per compile

    def test_fully_aliased_is_silent(self):
        rec = memory.ProgramMemory(program="p_ok")
        rec.donated_bytes = 1000
        rec.alias_bytes = 1000
        with warnings.catch_warnings(record=True) as w:
            warnings.simplefilter("always")
            memory._audit_donation(rec)
        assert not [x for x in w if "not aliased" in str(x.message)]
        assert not telemetry.read_series("donation_fallback_total")


# ---------------------------------------------------------------------------
# OOM forensics
# ---------------------------------------------------------------------------

class TestOOM:
    def test_forced_oom_raises_structured_error(self, tmp_path):
        crash = tmp_path / "crash.json"
        inspector.enable_flight_recorder(str(crash))
        exe, spec = _smoke()
        exe.run(spec["main"], feed=spec["data_fn"](4),
                fetch_list=[spec["loss"]])

        def boom(*a, **k):
            raise RuntimeError("RESOURCE_EXHAUSTED: Out of memory "
                               "allocating 12345678 bytes.")

        for blk in exe._cache.values():
            blk.fn = boom
        with pytest.raises(OOMError) as ei:
            exe.run(spec["main"], feed=spec["data_fn"](4),
                    fetch_list=[spec["loss"]])
        err = ei.value
        # retry loops matching the raw XLA status text must still fire
        assert "RESOURCE_EXHAUSTED" in str(err)
        assert err.breakdown                        # non-empty breakdown
        assert err.breakdown["feeds"] > 0
        assert err.breakdown["params"] > 0
        assert err.suggestions
        assert err.analysis and err.analysis["total_bytes"] > 0
        assert isinstance(err, MemoryError) and isinstance(err, RuntimeError)

        report = inspector.read_crash_report(str(crash))
        assert report["error"]["type"] == "OOMError"
        assert report["error"]["breakdown"]["feeds"] > 0
        assert report["memory"]["programs"]
        text = inspector.format_crash_report(report)
        assert "memory breakdown" in text
        assert "OOMError" in text

    def test_non_oom_errors_pass_through(self):
        exe, spec = _smoke()
        exe.run(spec["main"], feed=spec["data_fn"](4),
                fetch_list=[spec["loss"]])

        def boom(*a, **k):
            raise ValueError("not a memory problem")

        for blk in exe._cache.values():
            blk.fn = boom
        with pytest.raises(ValueError):
            exe.run(spec["main"], feed=spec["data_fn"](4),
                    fetch_list=[spec["loss"]])


# ---------------------------------------------------------------------------
# Satellites: checkpoint bytes, per-shard bytes, bench summary, CLI
# ---------------------------------------------------------------------------

class TestSatellites:
    def test_checkpoint_bytes_telemetry(self, tmp_path):
        exe, spec = _smoke()
        fluid.io.save_persistables(exe, str(tmp_path / "ckpt"),
                                   main_program=spec["main"])
        saved = telemetry.read_gauge("checkpoint_bytes", op="save")
        assert saved and saved > 0
        fluid.io.load_persistables(exe, str(tmp_path / "ckpt"),
                                   main_program=spec["main"])
        loaded = telemetry.read_gauge("checkpoint_bytes", op="load")
        assert loaded == saved
        kinds = {e.get("kind") for e in telemetry.recent_events(50)}
        assert {"checkpoint_save", "checkpoint_load"} <= kinds

    def test_per_shard_param_bytes(self):
        import jax
        from jax.sharding import Mesh
        main, startup = fluid.Program(), fluid.Program()
        with fluid.program_guard(main, startup):
            x = fluid.layers.data(name="x", shape=[16], dtype="float32")
            pred = fluid.layers.fc(input=x, size=8)
            fluid.layers.mean(pred)
        exe = fluid.Executor(fluid.CPUPlace())
        exe.run(startup)
        w = next(p.name for p in main.global_block().all_parameters()
                 if "w" in p.name)
        main._mesh = Mesh(np.array(jax.devices()[:4]), ("dp",))
        main._param_shardings = {w: ("dp", None)}
        out = parallel.per_shard_param_bytes(main)
        assert out["devices"] == 4
        assert out["replicated_bytes"] == 8 * 4          # bias
        assert out["sharded_bytes_per_device"] == 16 * 8 * 4 // 4
        assert out["per_device_bytes"] == \
            out["replicated_bytes"] + out["sharded_bytes_per_device"]
        assert out["params"][w]["factor"] == 4

    def test_bench_summary_and_report(self):
        exe, spec = _smoke()
        exe.run(spec["main"], feed=spec["data_fn"](4),
                fetch_list=[spec["loss"]])
        s = memory.bench_summary()
        assert s and s["peak_hbm_bytes"] > 0
        assert "hbm_utilization" in s
        rep = memory.memory_report()
        assert rep["programs"] and rep["tracker"]

    def test_memory_cli_what_if(self, capsys):
        rc = cli.main(["memory", "--smoke", "fit_a_line", "--batch", "16",
                       "--what-if", "--budget-gb", "0.001", "--json"])
        out = json.loads(capsys.readouterr().out)
        assert rc == 0
        entry = out["programs"][0]
        assert entry["static"]["total_bytes"] > 0
        assert entry["what_if"]["max_batch"] > 16
        assert entry["what_if"]["rel_err"] <= 0.15

    def test_read_series(self):
        telemetry.counter("rs_test", "x", labels=("k",)).labels(k="a").inc(2)
        telemetry.counter("rs_test", "x", labels=("k",)).labels(k="b").inc()
        assert telemetry.read_series("rs_test") == {"k=a": 2.0, "k=b": 1.0}
        assert telemetry.read_series("nope") == {}
