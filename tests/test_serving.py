"""Inference serving subsystem (ISSUE 13): AOT per-bucket program cache,
dynamic batching, and load shedding.

The load-bearing property is bitwise parity: a request served through
the batcher (coalesced with strangers, padded to a bucket, scattered
back) must equal the same rows served alone, which must equal a classic
`exe.run` on the pruned program. Everything else — the bucket ladder's
hit/miss accounting, deadline-vs-size batch closes, queue-full and
deadline sheds, the DLRM sparse path staying sparse — is checked
against the engine's python counters AND the telemetry series, so the
observability surface can't silently drift from the behavior.
"""

import os
import time

import numpy as np
import pytest

import paddle_tpu as fluid
from paddle_tpu import executor as executor_mod
from paddle_tpu import telemetry
from paddle_tpu.errors import ServingOverloadError
from paddle_tpu.serving import (DynamicBatcher, ServingEngine, bucket_ladder,
                                overload_report, run_load)
from paddle_tpu.serving import slo as slo_mod


def _build_fc(scope, train_steps=0, in_dim=16, classes=4):
    """Adam-trained 2-layer fc classifier; returns (main, logits_name).
    Startup (and optional training) run inside `scope`."""
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        x = fluid.layers.data(name="x", shape=[in_dim], dtype="float32")
        y = fluid.layers.data(name="y", shape=[1], dtype="int64")
        h = fluid.layers.fc(input=x, size=32, act="relu")
        logits = fluid.layers.fc(input=h, size=classes)
        loss = fluid.layers.mean(
            fluid.layers.softmax_with_cross_entropy(logits, y))
        fluid.optimizer.Adam(learning_rate=1e-3).minimize(loss)
    exe = fluid.Executor(fluid.CPUPlace())
    rng = np.random.RandomState(0)
    with executor_mod.scope_guard(scope):
        exe.run(startup)
        for _ in range(train_steps):
            exe.run(main,
                    feed={"x": rng.randn(8, in_dim).astype(np.float32),
                          "y": rng.randint(0, classes, (8, 1))
                          .astype(np.int64)},
                    fetch_list=[loss])
    return main, logits.name


def _feed(rng, n, in_dim=16):
    return {"x": rng.randn(n, in_dim).astype(np.float32)}


def _ctr(name, **labels):
    """One counter series' value (0.0 when absent). `labels` must be
    passed in the family's declared label order — read_series keys are
    the registry's serialized 'k=v,k=v' form."""
    key = ",".join(f"{k}={v}" for k, v in labels.items())
    return telemetry.read_series(name).get(key, 0.0)


def test_bucket_ladder_shape():
    assert bucket_ladder(8) == (1, 2, 4, 8)
    assert bucket_ladder(1) == (1,)
    # non-power-of-two max still caps the ladder at max_batch
    assert bucket_ladder(6)[-1] == 6


def test_batch_parity_bitwise():
    """Rows served alone == their slice of a padded batched run == the
    classic executor on the same pruned program (acceptance criterion).
    Bitwise parity holds within one bucket executable (rows are
    independent along the batch dim, so padding neighbors can't perturb
    them); across DIFFERENT buckets XLA may tile the matmul differently,
    so that comparison is allclose-at-ULP, not bitwise."""
    scope = executor_mod.Scope()
    main, logits = _build_fc(scope, train_steps=3)
    eng = ServingEngine(main, feed_names=["x"], fetch_names=[logits],
                        scope=scope, buckets=[4])
    rng = np.random.RandomState(1)
    batch = _feed(rng, 3)                       # pads into bucket 4
    batched = eng.run_batch(dict(batch))[0]
    assert batched.shape == (3, 4)
    for i in range(3):                          # same bucket: bitwise
        alone = eng.run_batch({"x": batch["x"][i:i + 1]})[0]
        assert np.array_equal(alone[0], batched[i])
    exe = fluid.Executor(fluid.CPUPlace())
    classic = exe.run(eng.program, feed=dict(batch),
                      fetch_list=[logits], scope=scope)[0]
    assert np.array_equal(np.asarray(classic), batched)
    eng.close()
    # cross-bucket (1-row executable vs 4-row executable): numerically
    # identical up to reassociation ULPs
    eng2 = ServingEngine(main, feed_names=["x"], fetch_names=[logits],
                         scope=scope, max_batch=8)
    batched2 = eng2.run_batch(dict(batch))[0]
    for i in range(3):
        alone = eng2.run_batch({"x": batch["x"][i:i + 1]})[0]
        np.testing.assert_allclose(alone[0], batched2[i],
                                   rtol=1e-6, atol=1e-6)
    eng2.close()


def test_bucket_cache_hit_miss_and_eviction():
    """Per-bucket AOT executables: first touch of a bucket is a compile
    miss, repeats are hits, and a capacity-1 cache LRU-evicts — all
    mirrored in the serving_cache_* telemetry series."""
    scope = executor_mod.Scope()
    main, logits = _build_fc(scope)
    eng = ServingEngine(main, feed_names=["x"], fetch_names=[logits],
                        scope=scope, max_batch=8)
    rng = np.random.RandomState(2)
    eng.run_batch(_feed(rng, 1))                # bucket 1: miss
    eng.run_batch(_feed(rng, 1))                # hit
    eng.run_batch(_feed(rng, 3))                # bucket 4: miss
    eng.run_batch(_feed(rng, 4))                # hit
    assert (eng.cache_misses, eng.cache_hits) == (2, 2)
    label = eng._label
    assert _ctr("serving_cache_miss_total", program=label, bucket="1") == 1
    assert _ctr("serving_cache_hit_total", program=label, bucket="4") == 1
    assert eng.bucket_runs == {1: 2, 4: 2}
    # compile time was observed per miss
    hist = telemetry.read_histogram("serving_compile_seconds",
                                    program=label, bucket="1")
    assert hist and hist["count"] == 1
    eng.close()

    eng2 = ServingEngine(main, feed_names=["x"], fetch_names=[logits],
                         scope=scope, max_batch=8, cache_capacity=1)
    eng2.run_batch(_feed(rng, 1))
    eng2.run_batch(_feed(rng, 2))               # evicts bucket 1
    eng2.run_batch(_feed(rng, 1))               # miss again
    assert eng2.evictions >= 1 and eng2.cache_misses == 3
    assert _ctr("serving_cache_evictions_total", program=eng2._label) >= 1
    eng2.close()


def test_run_batch_feed_validation():
    scope = executor_mod.Scope()
    main, logits = _build_fc(scope)
    eng = ServingEngine(main, feed_names=["x"], fetch_names=[logits],
                        scope=scope, max_batch=4)
    rng = np.random.RandomState(3)
    with pytest.raises(KeyError):
        eng.run_batch({})
    with pytest.raises(ValueError):
        eng.run_batch({"x": np.zeros((0, 16), np.float32)})
    with pytest.raises(ValueError):
        eng.run_batch(_feed(rng, 5))            # over max_batch
    # infer() chunks an oversized feed instead of rejecting it
    big = _feed(rng, 6)
    out = eng.infer(big)[0]
    assert out.shape == (6, 4)
    assert np.array_equal(out[:4], eng.run_batch({"x": big["x"][:4]})[0])
    eng.close()
    with pytest.raises(RuntimeError):
        eng.infer(_feed(rng, 1))


def test_batcher_coalesce_scatter_parity():
    """Requests from different clients coalesce into one bucket and each
    future gets exactly its own rows back, bitwise."""
    scope = executor_mod.Scope()
    main, logits = _build_fc(scope, train_steps=2)
    eng = ServingEngine(main, feed_names=["x"], fetch_names=[logits],
                        scope=scope, max_batch=8)
    rng = np.random.RandomState(4)
    feeds = [_feed(rng, n) for n in (1, 2, 3)]
    # bitwise reference: the same rows coalesced by hand into one
    # run_batch call — identical bucket, identical padded tensor
    concat = eng.run_batch(
        {"x": np.concatenate([f["x"] for f in feeds])})[0]
    singles = [concat[0:1], concat[1:3], concat[3:6]]
    b = DynamicBatcher(eng, max_delay_ms=40.0, max_queue_depth=16)
    futs = [b.submit(f) for f in feeds]         # queue while stopped...
    b.start()                                   # ...so they coalesce
    try:
        for fut, want in zip(futs, singles):
            got = fut.result(timeout=30.0)[0]
            assert np.array_equal(got, want)
        st = b.stats()
        assert st["completed"] == 3 and st["shed"] == 0
        assert st["goodput_fraction"] == 1.0
    finally:
        b.stop()


def test_size_close_vs_deadline_close():
    scope = executor_mod.Scope()
    main, logits = _build_fc(scope)
    eng = ServingEngine(main, feed_names=["x"], fetch_names=[logits],
                        scope=scope, max_batch=4)
    rng = np.random.RandomState(5)
    # a full bucket must close on size long before a 5s deadline
    b = DynamicBatcher(eng, max_delay_ms=5000.0, max_queue_depth=16)
    futs = [b.submit(_feed(rng, 1)) for _ in range(4)]
    t0 = time.monotonic()
    b.start()
    try:
        for fut in futs:
            fut.result(timeout=30.0)
        assert time.monotonic() - t0 < 4.0      # did not wait out 5s
        assert b.close_counts.get("size", 0) >= 1
        # a lone request must close on deadline, not hang for size
        fut = b.submit(_feed(rng, 1))
        fut.result(timeout=30.0)
        assert b.close_counts.get("deadline", 0) >= 1
    finally:
        b.stop()
    assert _ctr("serving_batches_total",
                program=eng._label, close="size") >= 1
    eng.close()


def test_overload_sheds_but_accepted_requests_keep_parity():
    """Bounded queue: overflow is rejected with ServingOverloadError
    (reason queue_full) instead of queue collapse, and the requests that
    WERE accepted still return bitwise-correct results."""
    scope = executor_mod.Scope()
    main, logits = _build_fc(scope, train_steps=2)
    # single-bucket ladder so the shed-test reference runs share the
    # coalesced batch's executable (bitwise, not just allclose)
    eng = ServingEngine(main, feed_names=["x"], fetch_names=[logits],
                        scope=scope, buckets=[2])
    rng = np.random.RandomState(6)
    feeds = [_feed(rng, 1) for _ in range(4)]
    singles = [eng.run_batch(dict(f))[0] for f in feeds]
    b = DynamicBatcher(eng, max_delay_ms=20.0, max_queue_depth=2)
    accepted = [b.submit(feeds[0]), b.submit(feeds[1])]
    shed = []
    for f in feeds[2:]:
        with pytest.raises(ServingOverloadError) as ei:
            b.submit(f)
        shed.append(ei.value)
    assert all(e.reason == "queue_full" for e in shed)
    b.start()
    try:
        for fut, want in zip(accepted, singles):
            assert np.array_equal(fut.result(timeout=30.0)[0], want)
    finally:
        b.stop()
    st = b.stats()
    assert st["shed"] == 2 and st["completed"] == 2
    assert st["goodput_fraction"] == pytest.approx(0.5)
    assert _ctr("serving_shed_total", program=eng._label,
                reason="queue_full") >= 2
    eng.close()


def test_expired_deadline_sheds_at_pop():
    scope = executor_mod.Scope()
    main, logits = _build_fc(scope)
    eng = ServingEngine(main, feed_names=["x"], fetch_names=[logits],
                        scope=scope, max_batch=4)
    rng = np.random.RandomState(7)
    b = DynamicBatcher(eng, max_delay_ms=30.0, max_queue_depth=8)
    fut = b.submit(_feed(rng, 1), deadline_ms=0.0)  # expired on arrival
    live = b.submit(_feed(rng, 2))                  # rides the same batch
    b.start()
    try:
        with pytest.raises(ServingOverloadError) as ei:
            fut.result(timeout=30.0)
        assert ei.value.reason == "deadline"
        assert live.result(timeout=30.0)[0].shape == (2, 4)
    finally:
        b.stop()
    eng.close()


def test_dlrm_fsdp_serve_stays_sparse():
    """Flagship scenario: DLRM scorer on an fsdp-row-sharded table. The
    serve path must never book a sparse_densify_fallback — the lookup
    lowers to a sparse take, not a dense one-hot matmul (acceptance
    criterion)."""
    import jax
    from paddle_tpu.parallel import embedding as emb_mod
    from paddle_tpu.parallel.mesh import make_mesh

    if len(jax.devices()) < 4:
        pytest.skip("needs 4 virtual devices")
    telemetry.reset()
    rows, dim, slots = 64, 4, 3
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        ids = fluid.layers.data(name="ids", shape=[slots], dtype="int64")
        emb = fluid.layers.embedding(
            ids, size=[rows, dim], is_sparse=True,
            param_attr=fluid.ParamAttr(name="emb_table"))
        flat = fluid.layers.reshape(emb, shape=[-1, slots * dim])
        h = fluid.layers.fc(input=flat, size=16, act="relu")
        prob = fluid.layers.softmax(fluid.layers.fc(input=h, size=2))
    main._mesh = make_mesh((4,), ("fsdp",))
    emb_mod.shard_table(main, "emb_table", "fsdp")
    scope = executor_mod.Scope()
    exe = fluid.Executor(fluid.CPUPlace())
    with executor_mod.scope_guard(scope):
        exe.run(startup)
    eng = ServingEngine(main, feed_names=["ids"], fetch_names=[prob.name],
                        scope=scope, max_batch=4)
    rng = np.random.RandomState(8)
    out = eng.run_batch(
        {"ids": rng.randint(0, rows, (3, slots)).astype(np.int64)})[0]
    assert out.shape == (3, 2)
    np.testing.assert_allclose(out.sum(axis=1), 1.0, rtol=1e-5)
    assert sum(telemetry.read_series(
        "sparse_densify_fallback_total").values()) == 0
    eng.close()


def test_save_load_roundtrip_matches_in_memory(tmp_path):
    """Satellite: the saved inference model reloads, analyzes clean, and
    serves bitwise-identically to the in-memory program."""
    from paddle_tpu.analysis import analyze_program

    scope = executor_mod.Scope()
    main, logits = _build_fc(scope, train_steps=3)
    exe = fluid.Executor(fluid.CPUPlace())
    with executor_mod.scope_guard(scope):
        target = main.global_block().var(logits)
        fluid.io.save_inference_model(str(tmp_path), ["x"], [target], exe,
                                      main_program=main)
    mem = ServingEngine(main, feed_names=["x"], fetch_names=[logits],
                        scope=scope, max_batch=4)
    disk = ServingEngine(str(tmp_path), max_batch=4)
    assert disk.feed_names == ["x"] and disk.fetch_names == [logits]
    report = analyze_program(disk.program, feeds=disk.feed_names,
                             fetches=disk.fetch_names)
    assert not report.errors, [str(d) for d in report.errors]
    rng = np.random.RandomState(9)
    feed = _feed(rng, 3)
    assert np.array_equal(mem.run_batch(dict(feed))[0],
                          disk.run_batch(dict(feed))[0])
    mem.close()
    disk.close()


def test_prune_drops_training_state(tmp_path):
    """Satellite: the pruned inference program keeps only the forward
    params — no Adam moments/beta pows, no grads, no optimizer ops — and
    requesting a gradient as a save target is refused."""
    scope = executor_mod.Scope()
    main, logits = _build_fc(scope)
    eng = ServingEngine(main, feed_names=["x"], fetch_names=[logits],
                        scope=scope, max_batch=2)
    # resident state is exactly the 4 fc params (2x weight + 2x bias)
    assert len(eng._state_names) == 4, eng._state_names
    for n in eng._state_names:
        assert "moment" not in n and "beta" not in n and "@GRAD" not in n
    block = eng.program.global_block()
    assert all(op.type not in ("adam", "sgd", "momentum")
               and not op.type.endswith("_grad") for op in block.ops)
    for n in block.desc.vars:
        assert "moment" not in n and "@GRAD" not in n, n
    eng.close()
    # converse: a gradient var as an inference target is refused — its
    # producer is stripped with the training tail, so the pruned program
    # can't compute it. Both the export path and the engine's admission
    # gate must say so, at build time, not at first compile.
    gname = next(n for n in main.global_block().desc.vars
                 if n.endswith("@GRAD"))
    exe = fluid.Executor(fluid.CPUPlace())
    with executor_mod.scope_guard(scope):
        with pytest.raises(ValueError, match="gradient"):
            fluid.io.save_inference_model(
                str(tmp_path), ["x", "y"],
                [type("V", (), {"name": gname})()], exe,
                main_program=main)
    with pytest.raises(ValueError, match="not computable"):
        ServingEngine(main, feed_names=["x", "y"], fetch_names=[gname],
                      scope=scope)


def test_capi_machine_serves_loaded_model(tmp_path):
    """Satellite: the C-API backend stub rides ServingEngine with
    create/feed/fetch/destroy handle semantics."""
    from paddle_tpu.capi_backend import Machine

    scope = executor_mod.Scope()
    main, logits = _build_fc(scope, train_steps=2)
    exe = fluid.Executor(fluid.CPUPlace())
    with executor_mod.scope_guard(scope):
        target = main.global_block().var(logits)
        fluid.io.save_inference_model(str(tmp_path), ["x"], [target], exe,
                                      main_program=main)
    m = Machine(str(tmp_path))
    rng = np.random.RandomState(10)
    x = rng.randn(2, 16).astype(np.float32)
    m.set_input("x", x.tobytes(), (2, 16), 0)
    outs = m.forward()
    assert len(outs) == 1
    payload, dims = outs[0]
    got = np.frombuffer(payload, np.float32).reshape(dims)
    want = m.engine.run_batch({"x": x})[0]
    assert np.array_equal(got, want)
    m.destroy()
    with pytest.raises(RuntimeError):
        m.set_input("x", x.tobytes(), (2, 16), 0)


def test_concurrent_client_smoke_latency_histograms():
    """In-process concurrent-client harness: non-degenerate p50 <= p99
    (acceptance criterion), and the same quantiles are recoverable from
    the serving_request_seconds telemetry histogram."""
    scope = executor_mod.Scope()
    main, logits = _build_fc(scope)
    eng = ServingEngine(main, feed_names=["x"], fetch_names=[logits],
                        scope=scope, max_batch=8)
    rng = np.random.RandomState(11)
    for b in (1, 2, 4):                         # pre-compile the ladder
        eng.run_batch(_feed(rng, b))

    def make_feed(ci, ri):
        return _feed(rng, 1 + (ci + ri) % 3)

    b = DynamicBatcher(eng, max_delay_ms=3.0, max_queue_depth=32)
    b.start()
    try:
        payload = run_load(b, make_feed, clients=3, requests_per_client=4)
    finally:
        b.stop()
    assert payload["requests"] == 12
    assert 0.0 < payload["p50_ms"] <= payload["p99_ms"]
    assert payload["qps"] > 0 and payload["goodput_fraction"] == 1.0
    assert sum(payload["bucket_hits"].values()) >= 1
    assert payload["telemetry_p50_ms"] is not None
    assert payload["telemetry_p50_ms"] <= payload["telemetry_p99_ms"]
    # per-phase latency histograms exist for queue and compute too
    for phase in ("queue", "compute", "total"):
        h = telemetry.read_histogram("serving_request_seconds",
                                     program=eng._label, phase=phase)
        assert h and h["count"] >= 12
    eng.close()


def test_bench_serving_mode_json_line():
    """BENCH_MODE=serving emits one JSON line with the required keys
    (satellite). Subprocess so bench's module-level env reads are fresh;
    roofline/perf probes off to keep it seconds, not minutes."""
    import json
    import subprocess
    import sys

    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    env = dict(os.environ, JAX_PLATFORMS="cpu", BENCH_MODE="serving",
               BENCH_ROOFLINE="0", BENCH_PERF="0", BENCH_SERVE_CLIENTS="2",
               BENCH_SERVE_REQUESTS="3", BENCH_HISTORY="0", PYTHONPATH=repo)
    r = subprocess.run([sys.executable, os.path.join(repo, "bench.py")],
                       capture_output=True, text=True, env=env,
                       timeout=420, cwd=repo)
    assert r.returncode == 0, r.stdout + r.stderr[-2000:]
    line = json.loads(r.stdout.strip().splitlines()[-1])
    for key in ("p50_ms", "p99_ms", "qps", "shed_fraction", "bucket_hits",
                "goodput_fraction", "overload"):
        assert key in line, (key, line)
    assert line["densify_fallbacks"] == 0
    assert 0.0 < line["p50_ms"] <= line["p99_ms"]

def test_overload_report_slo_and_latency_bound():
    """Overload acceptance (ISSUE 16): injected overload drives the SLO
    fast-window burn above 1.0 while the normal phase stays below, and
    the accepted-request p99 under overload stays within a bound of the
    normal phase (shedding absorbs the excess, latency doesn't
    collapse). `overload_report` must carry the `slo` sub-dict with both
    windows."""
    slo_mod.reset()   # monitors are process-wide keyed by program label
    scope = executor_mod.Scope()
    main, logits = _build_fc(scope, train_steps=2)
    eng = ServingEngine(main, feed_names=["x"], fetch_names=[logits],
                        scope=scope, buckets=[4])
    rng = np.random.RandomState(8)
    eng.run_batch(_feed(rng, 4))                # warm the only bucket

    # ~15ms per 2-request batch + a 4-deep queue: a normal-phase client
    # (4 clients, one in-flight request each) can see at most 3 queued
    # strangers, so normal NEVER sheds; an overload client (8 total) can
    # see up to 7, so overload must — the shed signal separates the
    # phases deterministically
    real_run_batch = eng.run_batch

    def slow_run_batch(feed, **kw):
        time.sleep(0.015)
        return real_run_batch(feed, **kw)

    eng.run_batch = slow_run_batch
    b = DynamicBatcher(eng, max_delay_ms=30.0, max_queue_depth=4)
    b.start()
    try:
        report = overload_report(
            b, lambda ci, ri: _feed(np.random.RandomState(ci * 97 + ri), 2),
            clients=4, requests_per_client=6)
    finally:
        b.stop()
        eng.run_batch = real_run_batch
        eng.close()

    normal, over = report["normal"], report["overload"]
    assert over["shed_fraction"] > 0.0
    assert normal["p99_ms"] is not None and over["p99_ms"] is not None
    # accepted-latency bound: overload p99 may grow (deeper queue) but
    # must stay within a small multiple of normal — not collapse
    assert over["p99_ms"] <= 6.0 * normal["p99_ms"] + 150.0

    slo = report["slo"]
    assert slo is not None
    assert set(slo["windows"]) == {"fast", "slow"}
    assert slo["objective"]["availability"] == pytest.approx(0.999)
    # queue_full sheds overspend the 0.1% error budget immediately
    assert slo["overload"]["fast"] > 1.0
    assert slo["normal"]["fast"] <= 1.0
    assert report["batcher"]["slo"]["windows"]["fast"]["bad"] > 0
