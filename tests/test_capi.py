"""C inference API: compile the shim + example and check C predictions match
Python (reference: paddle/capi/gradient_machine.h, capi/examples)."""

import os
import re
import subprocess

import numpy as np
import pytest

import paddle_tpu as fluid
from paddle_tpu import executor as executor_mod

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
NATIVE = os.path.join(REPO, "paddle_tpu", "native")


def _build_lib():
    r = subprocess.run(["make", "-s", "-C", NATIVE, "libpaddle_tpu_capi.so"],
                       capture_output=True, text=True)
    if r.returncode != 0:
        pytest.skip(f"capi build unavailable: {r.stderr[-500:]}")


def _build():
    _build_lib()
    r = subprocess.run(
        ["gcc", os.path.join(REPO, "examples/capi/infer_fit_a_line.c"),
         "-I", NATIVE, "-L", NATIVE, "-lpaddle_tpu_capi",
         "-o", os.path.join(NATIVE, "infer_fit_a_line")],
        capture_output=True, text=True)
    assert r.returncode == 0, r.stderr


class TestCAPI:
    def test_c_matches_python(self, tmp_path):
        _build()
        # train + save a fit_a_line model
        main, startup = fluid.Program(), fluid.Program()
        with fluid.program_guard(main, startup):
            x = fluid.layers.data(name="x", shape=[13], dtype="float32")
            y = fluid.layers.data(name="y", shape=[1], dtype="float32")
            pred = fluid.layers.fc(input=x, size=1)
            loss = fluid.layers.mean(fluid.layers.square_error_cost(pred, y))
            fluid.optimizer.SGD(learning_rate=0.01).minimize(loss)
        exe = fluid.Executor(fluid.CPUPlace())
        rng = np.random.RandomState(0)
        w = rng.randn(13, 1).astype(np.float32)
        with executor_mod.scope_guard(executor_mod.Scope()):
            exe.run(startup)
            for _ in range(30):
                xs = rng.randn(32, 13).astype(np.float32)
                exe.run(main, feed={"x": xs, "y": xs @ w},
                        fetch_list=[loss])
            fluid.io.save_inference_model(str(tmp_path), ["x"], [pred], exe,
                                          main_program=main)
            # python-side predictions on the C example's fixed input
            cx = np.array([[0.1 * 1 * j for j in range(13)],
                           [0.1 * 2 * j for j in range(13)]], np.float32)
            prog, feeds, fetches = fluid.io.load_inference_model(
                str(tmp_path), exe)
            want, = exe.run(prog, feed={"x": cx}, fetch_list=fetches)

        env = dict(os.environ)
        env["PYTHONPATH"] = REPO + os.pathsep + env.get("PYTHONPATH", "")
        env["LD_LIBRARY_PATH"] = NATIVE + os.pathsep + \
            env.get("LD_LIBRARY_PATH", "")
        env.setdefault("JAX_PLATFORMS", "cpu")
        r = subprocess.run([os.path.join(NATIVE, "infer_fit_a_line"),
                            str(tmp_path)],
                           capture_output=True, text=True, env=env,
                           timeout=300)
        assert r.returncode == 0, (r.stdout, r.stderr[-1500:])
        preds = [float(m) for m in
                 re.findall(r"pred\[\d+\]=([-\d.]+)", r.stdout)]
        assert len(preds) == 2
        np.testing.assert_allclose(preds, np.asarray(want).reshape(-1),
                                   rtol=1e-4, atol=1e-5)


def _build_generic():
    _build()
    r = subprocess.run(
        ["gcc", os.path.join(REPO, "examples/capi/infer_generic.c"),
         "-I", NATIVE, "-L", NATIVE, "-lpaddle_tpu_capi", "-lm",
         "-o", os.path.join(NATIVE, "infer_generic")],
        capture_output=True, text=True)
    assert r.returncode == 0, r.stderr


def _run_generic(model_dir, specs):
    """specs: list of infer_generic input specs
    (name:dtype:dims[:mod=M][:lod=o0,o1,..]); returns output 0 flat."""
    env = dict(os.environ)
    env["PYTHONPATH"] = REPO + os.pathsep + env.get("PYTHONPATH", "")
    env["LD_LIBRARY_PATH"] = NATIVE + os.pathsep + \
        env.get("LD_LIBRARY_PATH", "")
    env.setdefault("JAX_PLATFORMS", "cpu")
    r = subprocess.run([os.path.join(NATIVE, "infer_generic"),
                        str(model_dir)] + list(specs),
                       capture_output=True, text=True, env=env, timeout=300)
    assert r.returncode == 0, (r.stdout, r.stderr[-1500:])
    return np.array([float(m) for m in
                     re.findall(r"out0\[\d+\]=([-+0-9.eE]+)", r.stdout)])


def _c_float(shape, slot):
    """infer_generic's f32 fill: sin(0.01*i + slot)."""
    n = int(np.prod(shape))
    return np.sin(0.01 * np.arange(n) + slot).astype(np.float32) \
        .reshape(shape)


def _c_ids(shape, slot, mod):
    """infer_generic's int fill: (7*i + 3*slot) % mod."""
    n = int(np.prod(shape))
    return ((7 * np.arange(n) + 3 * slot) % mod).astype(np.int64) \
        .reshape(shape)


def _spec(name, arr, lod=None, mod=None):
    dims = "x".join(str(d) for d in arr.shape)
    dt = {np.dtype("float32"): "f32", np.dtype("int64"): "i64",
          np.dtype("int32"): "i32"}[arr.dtype]
    s = f"{name}:{dt}:{dims}"
    if mod is not None:
        s += f":mod={mod}"
    if lod is not None:
        s += ":lod=" + ",".join(str(o) for o in lod)
    return s


# --- the eight book chapters through the C API -------------------------------
# Reference ships a C++ inference test per chapter loading the Python-saved
# artifact (paddle/fluid/inference/tests/book/test_inference_fit_a_line.cc
# and 7 siblings); this table is the same acceptance matrix through
# infer_generic. Each builder returns (feed_inputs, fetch_target) where
# feed_inputs = [(name, array, lod_or_None, mod_or_None), ...] — the C
# driver regenerates the identical arrays from the spec strings.

def _ch_fit_a_line():
    x = fluid.layers.data(name="x", shape=[13], dtype="float32")
    y = fluid.layers.data(name="y", shape=[1], dtype="float32")
    pred = fluid.layers.fc(input=x, size=1)
    loss = fluid.layers.mean(fluid.layers.square_error_cost(pred, y))
    train = {"x": np.random.RandomState(0).randn(16, 13).astype(np.float32),
             "y": np.random.RandomState(1).randn(16, 1).astype(np.float32)}
    return train, loss, [("x", _c_float((2, 13), 0), None, None)], pred


def _ch_recognize_digits():
    from paddle_tpu import models
    img = fluid.layers.data(name="img", shape=[1, 28, 28], dtype="float32")
    label = fluid.layers.data(name="label", shape=[1], dtype="int64")
    avg_cost, predict, _acc = models.build_image_classifier(
        models.mnist_conv, img, label, class_dim=10)
    rng = np.random.RandomState(0)
    train = {"img": rng.rand(8, 1, 28, 28).astype(np.float32),
             "label": rng.randint(0, 10, (8, 1)).astype(np.int64)}
    return train, avg_cost, \
        [("img", _c_float((2, 1, 28, 28), 0), None, None)], predict


def _ch_image_classification():
    from paddle_tpu import models
    img = fluid.layers.data(name="img", shape=[3, 32, 32], dtype="float32")
    label = fluid.layers.data(name="label", shape=[1], dtype="int64")
    avg_cost, predict, _acc = models.build_image_classifier(
        models.resnet_cifar10, img, label, class_dim=10)
    rng = np.random.RandomState(0)
    train = {"img": rng.rand(4, 3, 32, 32).astype(np.float32),
             "label": rng.randint(0, 10, (4, 1)).astype(np.int64)}
    return train, avg_cost, \
        [("img", _c_float((2, 3, 32, 32), 0), None, None)], predict


_W2V_VOCAB = 64


def _ch_word2vec():
    """4 context words -> embeddings -> concat -> fc softmax (the N-gram
    config of reference tests/book/test_word2vec.py), multi-int-input."""
    embs = []
    names = ["firstw", "secondw", "thirdw", "fourthw"]
    for nm in names:
        w = fluid.layers.data(name=nm, shape=[1], dtype="int64")
        embs.append(fluid.layers.embedding(
            input=w, size=[_W2V_VOCAB, 16],
            param_attr=fluid.ParamAttr(name="shared_emb")))
    concat = fluid.layers.concat(embs, axis=1)
    hidden = fluid.layers.fc(input=concat, size=32, act="sigmoid")
    logits = fluid.layers.fc(input=hidden, size=_W2V_VOCAB)
    predict = fluid.layers.softmax(logits)
    nextw = fluid.layers.data(name="nextw", shape=[1], dtype="int64")
    cost = fluid.layers.mean(fluid.layers.softmax_with_cross_entropy(
        logits=logits, label=nextw))
    rng = np.random.RandomState(0)
    train = {nm: rng.randint(0, _W2V_VOCAB, (8, 1)).astype(np.int64)
             for nm in names}
    train["nextw"] = rng.randint(0, _W2V_VOCAB, (8, 1)).astype(np.int64)
    feeds = [(nm, _c_ids((4, 1), i, _W2V_VOCAB), None, _W2V_VOCAB)
             for i, nm in enumerate(names)]
    return train, cost, feeds, predict


def _ch_recommender_system():
    """ids + a LoD title sequence -> towers -> cos_sim score (reduced
    reference tests/book/test_recommender_system.py shape: multi-input,
    mixed dtypes, one sequence input)."""
    uid = fluid.layers.data(name="uid", shape=[1], dtype="int64")
    mid = fluid.layers.data(name="mid", shape=[1], dtype="int64")
    title = fluid.layers.data(name="title", shape=[1], dtype="int64",
                              lod_level=1)
    usr = fluid.layers.fc(
        input=fluid.layers.embedding(uid, size=[32, 16]), size=16)
    t_emb = fluid.layers.embedding(title, size=[48, 16])
    t_pool = fluid.layers.sequence_pool(t_emb, "sum")
    mov = fluid.layers.fc(input=[fluid.layers.embedding(
        mid, size=[40, 16]), t_pool], size=16)
    score = fluid.layers.cos_sim(usr, mov)
    label = fluid.layers.data(name="score", shape=[1], dtype="float32")
    cost = fluid.layers.mean(fluid.layers.square_error_cost(score, label))
    rng = np.random.RandomState(0)
    LoD = executor_mod.LoDTensor
    train = {"uid": rng.randint(0, 32, (4, 1)).astype(np.int64),
             "mid": rng.randint(0, 40, (4, 1)).astype(np.int64),
             "title": LoD(rng.randint(0, 48, (11, 1)).astype(np.int64),
                          [[0, 3, 6, 8, 11]]),
             "score": rng.rand(4, 1).astype(np.float32)}
    feeds = [("uid", _c_ids((2, 1), 0, 32), None, 32),
             ("mid", _c_ids((2, 1), 1, 40), None, 40),
             ("title", _c_ids((7, 1), 2, 48), [0, 4, 7], 48)]
    return train, cost, feeds, score


_SENT_VOCAB = 80


def _ch_understand_sentiment():
    """LoD word sequence -> conv_pool text net (reference
    tests/book/test_understand_sentiment.py convolution_net)."""
    words = fluid.layers.data(name="words", shape=[1], dtype="int64",
                              lod_level=1)
    label = fluid.layers.data(name="label", shape=[1], dtype="int64")
    emb = fluid.layers.embedding(input=words, size=[_SENT_VOCAB, 16])
    conv = fluid.nets.sequence_conv_pool(input=emb, num_filters=16,
                                         filter_size=3, act="tanh",
                                         pool_type="sqrt")
    logits = fluid.layers.fc(input=conv, size=2)
    cost = fluid.layers.mean(fluid.layers.softmax_with_cross_entropy(
        logits=logits, label=label))
    rng = np.random.RandomState(0)
    LoD = executor_mod.LoDTensor
    train = {"words": LoD(rng.randint(0, _SENT_VOCAB, (13, 1))
                          .astype(np.int64), [[0, 5, 9, 13]]),
             "label": rng.randint(0, 2, (3, 1)).astype(np.int64)}
    feeds = [("words", _c_ids((9, 1), 0, _SENT_VOCAB), [0, 5, 9],
              _SENT_VOCAB)]
    return train, cost, feeds, logits


def _ch_label_semantic_roles():
    """Two aligned LoD inputs (word + predicate mark) -> embeddings ->
    GRU -> per-token logits (reduced reference
    tests/book/test_label_semantic_roles.py: multiple sequence feeds,
    sequence-shaped output)."""
    word = fluid.layers.data(name="word", shape=[1], dtype="int64",
                             lod_level=1)
    mark = fluid.layers.data(name="mark", shape=[1], dtype="int64",
                             lod_level=1)
    tgt = fluid.layers.data(name="tgt", shape=[1], dtype="int64",
                            lod_level=1)
    w_emb = fluid.layers.embedding(input=word, size=[60, 16])
    m_emb = fluid.layers.embedding(input=mark, size=[2, 16])
    merged = fluid.layers.concat([w_emb, m_emb], axis=-1)
    proj = fluid.layers.fc(input=merged, size=16 * 3, num_flatten_dims=2)
    h = fluid.layers.dynamic_gru(input=proj, size=16)
    logits = fluid.layers.fc(input=h, size=10, num_flatten_dims=2)
    cost = fluid.layers.mean(fluid.layers.softmax_with_cross_entropy(
        logits=logits, label=tgt))
    rng = np.random.RandomState(0)
    LoD = executor_mod.LoDTensor
    lod = [[0, 4, 7]]
    train = {"word": LoD(rng.randint(0, 60, (7, 1)).astype(np.int64), lod),
             "mark": LoD(rng.randint(0, 2, (7, 1)).astype(np.int64), lod),
             "tgt": LoD(rng.randint(0, 10, (7, 1)).astype(np.int64), lod)}
    feeds = [("word", _c_ids((6, 1), 0, 60), [0, 3, 6], 60),
             ("mark", _c_ids((6, 1), 1, 2), [0, 3, 6], 2)]
    return train, cost, feeds, logits


def _ch_rnn_encoder_decoder():
    """Source LoD sequence -> GRU encoder -> decode projection (reduced
    reference inference/tests/book/test_inference_rnn_encoder_decoder.cc
    shape: sequence in, vocab logits out)."""
    src = fluid.layers.data(name="src", shape=[1], dtype="int64",
                            lod_level=1)
    trg = fluid.layers.data(name="trg", shape=[1], dtype="int64")
    emb = fluid.layers.embedding(input=src, size=[50, 16])
    proj = fluid.layers.fc(input=emb, size=16 * 3, num_flatten_dims=2)
    h = fluid.layers.dynamic_gru(input=proj, size=16)
    enc = fluid.layers.sequence_last_step(h)
    logits = fluid.layers.fc(input=enc, size=50)
    cost = fluid.layers.mean(fluid.layers.softmax_with_cross_entropy(
        logits=logits, label=trg))
    rng = np.random.RandomState(0)
    LoD = executor_mod.LoDTensor
    train = {"src": LoD(rng.randint(0, 50, (9, 1)).astype(np.int64),
                        [[0, 4, 9]]),
             "trg": rng.randint(0, 50, (2, 1)).astype(np.int64)}
    feeds = [("src", _c_ids((7, 1), 0, 50), [0, 3, 7], 50)]
    return train, cost, feeds, logits


_CHAPTERS = {
    "fit_a_line": _ch_fit_a_line,
    "recognize_digits": _ch_recognize_digits,
    "image_classification": _ch_image_classification,
    "word2vec": _ch_word2vec,
    "recommender_system": _ch_recommender_system,
    "understand_sentiment": _ch_understand_sentiment,
    "label_semantic_roles": _ch_label_semantic_roles,
    "rnn_encoder_decoder": _ch_rnn_encoder_decoder,
}


class TestCAPIErrorPaths:
    """The C surface must fail with TYPED error codes, not crashes
    (reference paddle_error contract, capi/error.h)."""

    def _lib(self):
        import ctypes
        _build_lib()   # the shared lib only — no example binary needed
        # PyDLL, not CDLL: these calls re-enter the ALREADY-RUNNING
        # interpreter (capi.cc embeds CPython); CDLL would release the
        # GIL around the foreign call and the embedded import would run
        # GIL-less and crash
        lib = ctypes.PyDLL(os.path.join(NATIVE, "libpaddle_tpu_capi.so"))
        lib.paddle_tpu_machine_create.argtypes = [
            ctypes.POINTER(ctypes.c_void_p), ctypes.c_char_p]
        return lib, ctypes

    def test_create_from_missing_dir_is_typed_error(self, tmp_path):
        lib, ctypes = self._lib()
        assert lib.paddle_tpu_init() == 0
        h = ctypes.c_void_p()
        rc = lib.paddle_tpu_machine_create(
            ctypes.byref(h), str(tmp_path / "nope").encode())
        assert rc == 3, rc       # PD_PROTOBUF_ERROR: artifact unreadable

    def test_null_arguments_rejected(self):
        lib, ctypes = self._lib()
        assert lib.paddle_tpu_machine_create(None, b"x") == 1  # PD_NULLPTR
        assert lib.paddle_tpu_machine_destroy(None) == 1
        assert lib.paddle_tpu_machine_forward(None) == 1

    def test_bad_input_name_and_missing_feed(self, tmp_path):
        lib, ctypes = self._lib()
        # a real model to open
        main, startup = fluid.Program(), fluid.Program()
        with fluid.program_guard(main, startup):
            x = fluid.layers.data(name="x", shape=[4], dtype="float32")
            pred = fluid.layers.fc(input=x, size=2)
        exe = fluid.Executor(fluid.CPUPlace())
        with executor_mod.scope_guard(executor_mod.Scope()):
            exe.run(startup)
            fluid.io.save_inference_model(str(tmp_path), ["x"], [pred],
                                          exe, main_program=main)
        assert lib.paddle_tpu_init() == 0
        h = ctypes.c_void_p()
        assert lib.paddle_tpu_machine_create(
            ctypes.byref(h), str(tmp_path).encode()) == 0
        dims = (ctypes.c_int64 * 2)(1, 4)
        buf = (ctypes.c_float * 4)(1, 2, 3, 4)
        # wrong feed name -> error, not crash
        rc = lib.paddle_tpu_machine_set_input(h, b"not_a_feed", buf, dims, 2)
        assert rc != 0
        # forward without staging the real input -> error
        assert lib.paddle_tpu_machine_forward(h) != 0
        # stage correctly -> forward succeeds
        assert lib.paddle_tpu_machine_set_input(h, b"x", buf, dims, 2) == 0
        assert lib.paddle_tpu_machine_forward(h) == 0
        assert lib.paddle_tpu_machine_destroy(h) == 0

    def test_bad_lod_offsets_rejected(self, tmp_path):
        lib, ctypes = self._lib()
        main, startup = fluid.Program(), fluid.Program()
        with fluid.program_guard(main, startup):
            w = fluid.layers.data(name="w", shape=[1], dtype="int64",
                                  lod_level=1)
            emb = fluid.layers.embedding(input=w, size=[10, 4])
            pooled = fluid.layers.sequence_pool(emb, "sum")
            pred = fluid.layers.fc(input=pooled, size=2)
        exe = fluid.Executor(fluid.CPUPlace())
        with executor_mod.scope_guard(executor_mod.Scope()):
            exe.run(startup)
            fluid.io.save_inference_model(str(tmp_path), ["w"], [pred],
                                          exe, main_program=main)
        assert lib.paddle_tpu_init() == 0
        h = ctypes.c_void_p()
        assert lib.paddle_tpu_machine_create(
            ctypes.byref(h), str(tmp_path).encode()) == 0
        ids = (ctypes.c_int64 * 3)(1, 2, 3)
        dims = (ctypes.c_int64 * 2)(3, 1)
        assert lib.paddle_tpu_machine_set_input_typed(
            h, b"w", ids, 1, dims, 2) == 0
        # non-monotonic offsets -> PD_OUT_OF_RANGE before touching python
        bad = (ctypes.c_int64 * 3)(0, 2, 1)
        assert lib.paddle_tpu_machine_set_input_lod(h, b"w", bad, 3) == 2
        # offsets not ending at the row count -> error from the backend
        short = (ctypes.c_int64 * 2)(0, 2)
        assert lib.paddle_tpu_machine_set_input_lod(h, b"w", short, 2) != 0
        # correct offsets work end to end
        good = (ctypes.c_int64 * 3)(0, 2, 3)
        assert lib.paddle_tpu_machine_set_input_lod(h, b"w", good, 3) == 0
        assert lib.paddle_tpu_machine_forward(h) == 0
        assert lib.paddle_tpu_machine_destroy(h) == 0


class TestCAPIBeamSearchDecode:
    def test_machine_translation_beam_decode_through_c(self, tmp_path):
        """The FULL generation-mode decoder — While loop + beam_search +
        beam_search_decode over trained encoder-decoder params — saved as
        an inference model and served through the C API (the reference's
        hardest book inference artifact). Output 0 is the int sentence
        tensor; ids survive the C float marshaling exactly."""
        _build_generic()
        from tests.book import test_machine_translation as mt
        main, startup = fluid.Program(), fluid.Program()
        with fluid.program_guard(main, startup):
            src, trg, logits = mt.encoder_decoder()
            label = fluid.layers.data(name="target_language_next_word",
                                      shape=[1], dtype="int64", lod_level=1)
            cost = fluid.layers.softmax_with_cross_entropy(
                logits=logits, label=label, seq_mask=True)
            fluid.optimizer.Adam(learning_rate=1e-3).minimize(
                fluid.layers.mean(cost))
        exe = fluid.Executor(fluid.CPUPlace())
        with executor_mod.scope_guard(executor_mod.Scope()):
            feeder = fluid.DataFeeder(place=exe.place,
                                      feed_list=[src, trg, label])
            exe.run(startup)
            rng = np.random.RandomState(0)
            exe.run(main, feed=mt._feed(mt._toy_pairs(8, rng), feeder),
                    fetch_list=[cost])

            dec_main, _dec_start = fluid.Program(), fluid.Program()
            with fluid.program_guard(dec_main, _dec_start):
                _s, sentences, scores = mt.decode_program(beam_size=3,
                                                          use_beam=True)
            fluid.io.save_inference_model(str(tmp_path), ["src_word_id"],
                                          [sentences, scores], exe,
                                          main_program=dec_main)
        # python-side expectation on the C driver's exact fill pattern
        lod = [0, 4, 7]
        plain = _c_ids((7, 1), 0, mt.DICT_SIZE)
        with executor_mod.scope_guard(executor_mod.Scope()):
            prog, _f, fetches = fluid.io.load_inference_model(
                str(tmp_path), exe)
            want = exe.run(prog,
                           feed={"src_word_id": executor_mod.LoDTensor(
                               plain, [lod])},
                           fetch_list=fetches)[0]
        got = _run_generic(
            tmp_path, [_spec("src_word_id", plain, lod=lod,
                             mod=mt.DICT_SIZE)])
        np.testing.assert_allclose(got, np.asarray(want, np.float64)
                                   .reshape(-1), rtol=1e-5, atol=1e-6)


class TestCAPIBookChapters:
    """All eight reference book chapters' saved artifacts load and match
    Python through the C API (reference inference/tests/book/*.cc)."""

    @pytest.mark.parametrize("chapter", sorted(_CHAPTERS))
    def test_chapter_through_c(self, chapter, tmp_path):
        _build_generic()
        main, startup = fluid.Program(), fluid.Program()
        with fluid.program_guard(main, startup):
            train_feed, loss, c_feeds, target = _CHAPTERS[chapter]()
            fluid.optimizer.SGD(learning_rate=0.01).minimize(loss)
        exe = fluid.Executor(fluid.CPUPlace())
        with executor_mod.scope_guard(executor_mod.Scope()):
            exe.run(startup)
            for _ in range(2):
                exe.run(main, feed=dict(train_feed), fetch_list=[loss])
            feed_names = [nm for nm, _a, _l, _m in c_feeds]
            fluid.io.save_inference_model(str(tmp_path), feed_names,
                                          [target], exe, main_program=main)
            # python-side predictions on the C driver's deterministic feeds
            prog, _feeds, fetches = fluid.io.load_inference_model(
                str(tmp_path), exe)
            py_feed = {}
            for nm, arr, lod, _mod in c_feeds:
                py_feed[nm] = executor_mod.LoDTensor(arr, [lod]) if lod \
                    else arr
            want, = exe.run(prog, feed=py_feed, fetch_list=fetches)
        specs = [_spec(nm, arr, lod=lod, mod=mod)
                 for nm, arr, lod, mod in c_feeds]
        got = _run_generic(tmp_path, specs)
        np.testing.assert_allclose(got, np.asarray(want).reshape(-1),
                                   rtol=1e-3, atol=1e-5,
                                   err_msg=f"chapter {chapter}: C API "
                                           "prediction diverged from Python")
