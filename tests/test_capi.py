"""C inference API: compile the shim + example and check C predictions match
Python (reference: paddle/capi/gradient_machine.h, capi/examples)."""

import os
import re
import subprocess

import numpy as np
import pytest

import paddle_tpu as fluid
from paddle_tpu import executor as executor_mod

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
NATIVE = os.path.join(REPO, "paddle_tpu", "native")


def _build():
    r = subprocess.run(["make", "-s", "-C", NATIVE, "libpaddle_tpu_capi.so"],
                       capture_output=True, text=True)
    if r.returncode != 0:
        pytest.skip(f"capi build unavailable: {r.stderr[-500:]}")
    r = subprocess.run(
        ["gcc", os.path.join(REPO, "examples/capi/infer_fit_a_line.c"),
         "-I", NATIVE, "-L", NATIVE, "-lpaddle_tpu_capi",
         "-o", os.path.join(NATIVE, "infer_fit_a_line")],
        capture_output=True, text=True)
    assert r.returncode == 0, r.stderr


class TestCAPI:
    def test_c_matches_python(self, tmp_path):
        _build()
        # train + save a fit_a_line model
        main, startup = fluid.Program(), fluid.Program()
        with fluid.program_guard(main, startup):
            x = fluid.layers.data(name="x", shape=[13], dtype="float32")
            y = fluid.layers.data(name="y", shape=[1], dtype="float32")
            pred = fluid.layers.fc(input=x, size=1)
            loss = fluid.layers.mean(fluid.layers.square_error_cost(pred, y))
            fluid.optimizer.SGD(learning_rate=0.01).minimize(loss)
        exe = fluid.Executor(fluid.CPUPlace())
        rng = np.random.RandomState(0)
        w = rng.randn(13, 1).astype(np.float32)
        with executor_mod.scope_guard(executor_mod.Scope()):
            exe.run(startup)
            for _ in range(30):
                xs = rng.randn(32, 13).astype(np.float32)
                exe.run(main, feed={"x": xs, "y": xs @ w},
                        fetch_list=[loss])
            fluid.io.save_inference_model(str(tmp_path), ["x"], [pred], exe,
                                          main_program=main)
            # python-side predictions on the C example's fixed input
            cx = np.array([[0.1 * 1 * j for j in range(13)],
                           [0.1 * 2 * j for j in range(13)]], np.float32)
            prog, feeds, fetches = fluid.io.load_inference_model(
                str(tmp_path), exe)
            want, = exe.run(prog, feed={"x": cx}, fetch_list=fetches)

        env = dict(os.environ)
        env["PYTHONPATH"] = REPO + os.pathsep + env.get("PYTHONPATH", "")
        env["LD_LIBRARY_PATH"] = NATIVE + os.pathsep + \
            env.get("LD_LIBRARY_PATH", "")
        env.setdefault("JAX_PLATFORMS", "cpu")
        r = subprocess.run([os.path.join(NATIVE, "infer_fit_a_line"),
                            str(tmp_path)],
                           capture_output=True, text=True, env=env,
                           timeout=300)
        assert r.returncode == 0, (r.stdout, r.stderr[-1500:])
        preds = [float(m) for m in
                 re.findall(r"pred\[\d+\]=([-\d.]+)", r.stdout)]
        assert len(preds) == 2
        np.testing.assert_allclose(preds, np.asarray(want).reshape(-1),
                                   rtol=1e-4, atol=1e-5)


def _build_generic():
    _build()
    r = subprocess.run(
        ["gcc", os.path.join(REPO, "examples/capi/infer_generic.c"),
         "-I", NATIVE, "-L", NATIVE, "-lpaddle_tpu_capi", "-lm",
         "-o", os.path.join(NATIVE, "infer_generic")],
        capture_output=True, text=True)
    assert r.returncode == 0, r.stderr


def _run_generic(model_dir, input_name, dims):
    env = dict(os.environ)
    env["PYTHONPATH"] = REPO + os.pathsep + env.get("PYTHONPATH", "")
    env["LD_LIBRARY_PATH"] = NATIVE + os.pathsep + \
        env.get("LD_LIBRARY_PATH", "")
    env.setdefault("JAX_PLATFORMS", "cpu")
    r = subprocess.run([os.path.join(NATIVE, "infer_generic"),
                        str(model_dir), input_name] +
                       [str(d) for d in dims],
                       capture_output=True, text=True, env=env, timeout=300)
    assert r.returncode == 0, (r.stdout, r.stderr[-1500:])
    return np.array([float(m) for m in
                     re.findall(r"out\[\d+\]=([-\d.]+)", r.stdout)])


def _c_pattern(shape):
    n = int(np.prod(shape))
    return np.sin(0.01 * np.arange(n)).astype(np.float32).reshape(shape)


class TestCAPIConvModel:
    def test_conv_model_through_c(self, tmp_path):
        """A convolutional book model served through the C API (reference
        inference/tests/book/test_inference_recognize_digits.cc)."""
        _build_generic()
        from paddle_tpu import models
        main, startup = fluid.Program(), fluid.Program()
        with fluid.program_guard(main, startup):
            img = fluid.layers.data(name="img", shape=[1, 28, 28],
                                    dtype="float32")
            label = fluid.layers.data(name="label", shape=[1],
                                      dtype="int64")
            avg_cost, predict, acc = models.build_image_classifier(
                models.mnist_conv, img, label, class_dim=10)
            fluid.optimizer.Adam(learning_rate=1e-3).minimize(avg_cost)
        exe = fluid.Executor(fluid.CPUPlace())
        rng = np.random.RandomState(0)
        with executor_mod.scope_guard(executor_mod.Scope()):
            exe.run(startup)
            for _ in range(3):
                xs = rng.rand(16, 1, 28, 28).astype(np.float32)
                ys = rng.randint(0, 10, (16, 1)).astype(np.int64)
                exe.run(main, feed={"img": xs, "label": ys},
                        fetch_list=[avg_cost])
            fluid.io.save_inference_model(str(tmp_path), ["img"], [predict],
                                          exe, main_program=main)
            cx = _c_pattern((2, 1, 28, 28))
            prog, feeds, fetches = fluid.io.load_inference_model(
                str(tmp_path), exe)
            want, = exe.run(prog, feed={"img": cx}, fetch_list=fetches)
        got = _run_generic(tmp_path, "img", (2, 1, 28, 28))
        np.testing.assert_allclose(got, np.asarray(want).reshape(-1),
                                   rtol=1e-3, atol=1e-5)


class TestCAPISequenceModel:
    def test_lstm_model_through_c(self, tmp_path):
        """A sequence (LSTM) model served through the C API: dense float
        sequence features [B,T,F] -> lstm -> last step -> fc."""
        _build_generic()
        main, startup = fluid.Program(), fluid.Program()
        with fluid.program_guard(main, startup):
            seq = fluid.layers.data(name="seq", shape=[-1, -1, 8],
                                    dtype="float32",
                                    append_batch_size=False)
            y = fluid.layers.data(name="y", shape=[1], dtype="float32")
            proj = fluid.layers.fc(input=seq, size=64, num_flatten_dims=2)
            h, _c = fluid.layers.dynamic_lstm(input=proj, size=64)
            last = fluid.layers.sequence_last_step(h)
            pred = fluid.layers.fc(input=last, size=1)
            loss = fluid.layers.mean(fluid.layers.square_error_cost(pred, y))
            fluid.optimizer.SGD(learning_rate=0.05).minimize(loss)
        exe = fluid.Executor(fluid.CPUPlace())
        rng = np.random.RandomState(0)
        with executor_mod.scope_guard(executor_mod.Scope()):
            exe.run(startup)
            for _ in range(3):
                xs = rng.randn(8, 6, 8).astype(np.float32)
                ys = xs.mean(axis=(1, 2), keepdims=False)[:, None]
                exe.run(main, feed={"seq": xs, "y": ys.astype(np.float32)},
                        fetch_list=[loss])
            fluid.io.save_inference_model(str(tmp_path), ["seq"], [pred],
                                          exe, main_program=main)
            cx = _c_pattern((2, 6, 8))
            prog, feeds, fetches = fluid.io.load_inference_model(
                str(tmp_path), exe)
            want, = exe.run(prog, feed={"seq": cx}, fetch_list=fetches)
        got = _run_generic(tmp_path, "seq", (2, 6, 8))
        np.testing.assert_allclose(got, np.asarray(want).reshape(-1),
                                   rtol=1e-3, atol=1e-5)
