"""OpTest harness: single-op correctness + numeric-vs-analytic grad checks
(reference: python/paddle/fluid/tests/unittests/op_test.py:212 OpTest,
:97 get_numeric_gradient, :290 check_output, :378 check_grad).

Subclasses set `op_type`, `inputs`, `outputs`, `attrs`. Inputs/outputs map
slot -> ndarray, or slot -> [(name, ndarray), ...] for multi-var slots.
check_grad builds loss = sum(mean(out) for out in output_names), runs
append_backward, and compares the fetched analytic grads against central
differences of the same loss.
"""

from __future__ import annotations

from typing import Dict, List, Optional, Sequence

import numpy as np

import paddle_tpu as fluid
from paddle_tpu.framework import unique_name
from paddle_tpu import executor as executor_mod


def _as_pairs(slot_value, slot):
    if isinstance(slot_value, (list, tuple)) and slot_value and \
            isinstance(slot_value[0], (list, tuple)):
        return [(n, np.asarray(a)) for n, a in slot_value]
    return [(slot, np.asarray(slot_value))]


class OpTest:
    op_type: str = ""
    inputs: Dict = {}
    outputs: Dict = {}
    attrs: Dict = {}

    # --- program building ---------------------------------------------------
    def _build(self, for_grad: Optional[Sequence[str]] = None,
               output_names: Optional[Sequence[str]] = None,
               no_grad_set=None):
        main = fluid.Program()
        startup = fluid.Program()
        feed = {}
        with fluid.program_guard(main, startup):
            with unique_name.guard():
                op_inputs = {}
                for slot, value in self.inputs.items():
                    names = []
                    for name, arr in _as_pairs(value, slot):
                        v = main.global_block().create_var(
                            name=name, shape=list(arr.shape),
                            dtype=arr.dtype.name, stop_gradient=False)
                        feed[name] = arr
                        names.append(name)
                    op_inputs[slot] = names
                op_outputs = {}
                out_vars = {}
                for slot, value in self.outputs.items():
                    names = []
                    for name, arr in _as_pairs(value, slot):
                        v = main.global_block().create_var(
                            name=name, dtype=np.asarray(arr).dtype.name)
                        names.append(name)
                        out_vars[name] = v
                    op_outputs[slot] = names
                main.global_block().append_op(
                    type=self.op_type, inputs=op_inputs, outputs=op_outputs,
                    attrs=dict(self.attrs))

                loss = None
                if output_names is not None:
                    parts = []
                    for name in output_names:
                        m = fluid.layers.mean(
                            fluid.layers.cast(out_vars[name], "float32"))
                        parts.append(m)
                    loss = parts[0]
                    for p in parts[1:]:
                        loss = fluid.layers.elementwise_add(loss, p)
                    fluid.append_backward(loss, no_grad_set=no_grad_set)
        return main, feed, out_vars, loss

    def _executor(self):
        return fluid.Executor(fluid.CPUPlace())

    # --- checks -------------------------------------------------------------
    def check_output(self, atol=1e-5, rtol=1e-4, no_check_set=()):
        main, feed, out_vars, _ = self._build()
        exe = self._executor()
        scope = executor_mod.Scope()
        with executor_mod.scope_guard(scope):
            fetch_names = []
            expected = []
            for slot, value in self.outputs.items():
                for name, arr in _as_pairs(value, slot):
                    if name in no_check_set:
                        continue
                    fetch_names.append(name)
                    expected.append(np.asarray(arr))
            results = exe.run(main, feed=feed, fetch_list=fetch_names)
        for name, got, want in zip(fetch_names, results, expected):
            np.testing.assert_allclose(
                got.astype(np.float64), want.astype(np.float64),
                atol=atol, rtol=rtol,
                err_msg=f"{self.op_type} output {name} mismatch")

    def check_grad(self, inputs_to_check: Sequence[str],
                   output_names, max_relative_error=0.005,
                   numeric_delta=0.005, no_grad_set=None):
        if isinstance(output_names, str):
            output_names = [output_names]
        main, feed, out_vars, loss = self._build(
            for_grad=inputs_to_check, output_names=output_names,
            no_grad_set=no_grad_set)
        exe = self._executor()
        scope = executor_mod.Scope()
        with executor_mod.scope_guard(scope):
            grad_names = [fluid.framework.grad_var_name(n)
                          for n in inputs_to_check]
            analytic = exe.run(main, feed=feed,
                               fetch_list=[loss.name] + grad_names)
            analytic_grads = analytic[1:]

            # numeric central differences on the same compiled program
            def run_loss(feed_dict):
                out, = exe.run(main, feed=feed_dict,
                               fetch_list=[loss.name])
                return float(np.asarray(out).reshape(-1)[0])

            for vname, ag in zip(inputs_to_check, analytic_grads):
                base = feed[vname].astype(np.float64)
                num = np.zeros_like(base, dtype=np.float64)
                flat = base.reshape(-1)
                for i in range(flat.size):
                    orig = flat[i]
                    delta = numeric_delta * max(1.0, abs(orig))
                    f = dict(feed)
                    pert = base.copy().reshape(-1)
                    pert[i] = orig + delta
                    f[vname] = pert.reshape(base.shape).astype(
                        feed[vname].dtype)
                    lp = run_loss(f)
                    pert[i] = orig - delta
                    f[vname] = pert.reshape(base.shape).astype(
                        feed[vname].dtype)
                    lm = run_loss(f)
                    num.reshape(-1)[i] = (lp - lm) / (2 * delta)
                ag = np.asarray(ag, dtype=np.float64)
                denom = np.maximum(np.maximum(np.abs(num), np.abs(ag)), 1e-3)
                rel = np.abs(num - ag) / denom
                assert rel.max() <= max_relative_error, (
                    f"{self.op_type} grad w.r.t. {vname}: max rel err "
                    f"{rel.max():.5f} > {max_relative_error} "
                    f"(numeric {num.reshape(-1)[:5]}, "
                    f"analytic {ag.reshape(-1)[:5]})")
