"""SelectedRows sparse embedding gradients (reference: selected_rows.h:19,
lookup_table_op.cc sparse grad path, selected_rows_functor.cc,
test_lookup_table_op.py). Sparse path must match the dense path bit-for-bit
on the updated table, and a word2vec-style step must train through it."""

import numpy as np

import paddle_tpu as fluid
from paddle_tpu import executor as executor_mod

RNG = np.random.RandomState(9)


def _train_once(is_sparse, steps=3):
    main = fluid.Program()
    startup = fluid.Program()
    with fluid.program_guard(main, startup):
        ids = fluid.layers.data(name="ids", shape=[4], dtype="int64")
        lbl = fluid.layers.data(name="lbl", shape=[1], dtype="int64")
        emb = fluid.layers.embedding(ids, size=[50, 8], is_sparse=is_sparse,
                                     param_attr=fluid.ParamAttr(name="emb_w"))
        flat = fluid.layers.reshape(emb, shape=[-1, 32])
        logits = fluid.layers.fc(input=flat, size=50,
                                 param_attr=fluid.ParamAttr(name="fc_w"))
        loss = fluid.layers.mean(
            fluid.layers.softmax_with_cross_entropy(logits, lbl))
        fluid.optimizer.SGDOptimizer(learning_rate=0.5).minimize(loss)
    exe = fluid.Executor(fluid.CPUPlace())
    scope = executor_mod.Scope()
    feed = {"ids": np.array([[1, 7, 7, 3], [0, 2, 2, 2]], np.int64),
            "lbl": np.array([[5], [9]], np.int64)}
    with executor_mod.scope_guard(scope):
        exe.run(startup)
        # deterministic init so sparse/dense runs start identical
        scope.set_var("emb_w", np.linspace(
            -1, 1, 50 * 8).astype(np.float32).reshape(50, 8))
        scope.set_var("fc_w", np.linspace(
            -0.5, 0.5, 32 * 50).astype(np.float32).reshape(32, 50))
        losses = []
        for _ in range(steps):
            v, = exe.run(main, feed=feed, fetch_list=[loss])
            losses.append(float(np.asarray(v).reshape(-1)[0]))
        w = np.asarray(scope.find_var("emb_w"))
    return losses, w


class TestSparseEmbeddingGrad:
    def test_sparse_matches_dense(self):
        l_dense, w_dense = _train_once(is_sparse=False)
        l_sparse, w_sparse = _train_once(is_sparse=True)
        # scatter-add order differs between the two paths; only float
        # accumulation noise is tolerated
        np.testing.assert_allclose(l_sparse, l_dense, rtol=1e-5)
        np.testing.assert_allclose(w_sparse, w_dense, rtol=1e-5, atol=1e-7)
        # rows never looked up must be untouched by the sparse update
        init = np.linspace(-1, 1, 50 * 8).astype(np.float32).reshape(50, 8)
        touched = {0, 1, 2, 3, 7}
        untouched = [i for i in range(50) if i not in touched]
        np.testing.assert_array_equal(w_sparse[untouched], init[untouched])

    def test_word2vec_step_sparse(self):
        """CBOW-style word2vec step through the sparse path converges
        (reference book test_word2vec config with is_sparse=True)."""
        main = fluid.Program()
        startup = fluid.Program()
        V, E = 40, 16
        with fluid.program_guard(main, startup):
            words = [fluid.layers.data(name=f"w{i}", shape=[1],
                                       dtype="int64") for i in range(4)]
            target = fluid.layers.data(name="tgt", shape=[1], dtype="int64")
            embs = [fluid.layers.embedding(
                w, size=[V, E], is_sparse=True,
                param_attr=fluid.ParamAttr(name="shared_emb"))
                for w in words]
            concat = fluid.layers.concat(embs, axis=1)
            hidden = fluid.layers.fc(input=concat, size=32, act="sigmoid")
            logits = fluid.layers.fc(input=hidden, size=V)
            loss = fluid.layers.mean(
                fluid.layers.softmax_with_cross_entropy(logits, target))
            fluid.optimizer.SGDOptimizer(learning_rate=1.0).minimize(loss)
        exe = fluid.Executor(fluid.CPUPlace())
        data = RNG.randint(0, V, size=(16, 5)).astype(np.int64)
        feed = {f"w{i}": data[:, i:i+1] for i in range(4)}
        feed["tgt"] = data[:, 4:5]
        with executor_mod.scope_guard(executor_mod.Scope()):
            exe.run(startup)
            first = None
            for _ in range(30):
                v, = exe.run(main, feed=feed, fetch_list=[loss])
                first = first or float(np.asarray(v).reshape(-1)[0])
            last = float(np.asarray(v).reshape(-1)[0])
        assert last < first * 0.5, (first, last)


def _train_opt(opt_factory, is_sparse, steps=3):
    """Shared net under a given optimizer: exercises the SelectedRows
    kernels (reference adam_op.h SparseAdamFunctor, momentum extension)."""
    main = fluid.Program()
    startup = fluid.Program()
    with fluid.program_guard(main, startup):
        ids = fluid.layers.data(name="ids", shape=[4], dtype="int64")
        lbl = fluid.layers.data(name="lbl", shape=[1], dtype="int64")
        emb = fluid.layers.embedding(ids, size=[50, 8], is_sparse=is_sparse,
                                     param_attr=fluid.ParamAttr(name="emb_w"))
        flat = fluid.layers.reshape(emb, shape=[-1, 32])
        logits = fluid.layers.fc(input=flat, size=50,
                                 param_attr=fluid.ParamAttr(name="fc_w"))
        loss = fluid.layers.mean(
            fluid.layers.softmax_with_cross_entropy(logits, lbl))
        opt_factory().minimize(loss)
    exe = fluid.Executor(fluid.CPUPlace())
    scope = executor_mod.Scope()
    feed = {"ids": np.array([[1, 7, 7, 3], [0, 2, 2, 2]], np.int64),
            "lbl": np.array([[5], [9]], np.int64)}
    with executor_mod.scope_guard(scope):
        exe.run(startup)
        scope.set_var("emb_w", np.linspace(
            -1, 1, 50 * 8).astype(np.float32).reshape(50, 8))
        scope.set_var("fc_w", np.linspace(
            -0.5, 0.5, 32 * 50).astype(np.float32).reshape(32, 50))
        losses = []
        for _ in range(steps):
            v, = exe.run(main, feed=feed, fetch_list=[loss])
            losses.append(float(np.asarray(v).reshape(-1)[0]))
        w = np.asarray(scope.find_var("emb_w"))
    return losses, w


def _train_adam(is_sparse, steps=3):
    return _train_opt(lambda: fluid.optimizer.Adam(learning_rate=0.1),
                      is_sparse, steps)


def _train_momentum(is_sparse, steps=3):
    return _train_opt(
        lambda: fluid.optimizer.Momentum(learning_rate=0.3, momentum=0.9),
        is_sparse, steps)


class TestSparseAdam:
    """Sparse adam semantics (reference adam_op.h sparse path): touched rows
    match... nothing — sparse adam is intentionally NOT equal to dense adam:
    dense adam decays every row's moments each step, sparse (lazy) only
    touches grad rows. Assert (a) the first step matches dense exactly
    (moments start at zero, so laziness is invisible), (b) untouched rows
    never move, (c) multi-step training still converges."""

    def test_first_step_matches_dense(self):
        l_d, w_d = _train_adam(is_sparse=False, steps=1)
        l_s, w_s = _train_adam(is_sparse=True, steps=1)
        np.testing.assert_allclose(l_s, l_d, rtol=1e-5)
        np.testing.assert_allclose(w_s, w_d, rtol=1e-5, atol=1e-6)

    def test_untouched_rows_frozen_and_trains(self):
        losses, w = _train_adam(is_sparse=True, steps=6)
        init = np.linspace(-1, 1, 50 * 8).astype(np.float32).reshape(50, 8)
        touched = {0, 1, 2, 3, 7}
        untouched = [i for i in range(50) if i not in touched]
        np.testing.assert_array_equal(w[untouched], init[untouched])
        assert losses[-1] < losses[0], losses


class TestSparseMomentum:
    def test_first_step_matches_dense(self):
        l_d, w_d = _train_momentum(is_sparse=False, steps=1)
        l_s, w_s = _train_momentum(is_sparse=True, steps=1)
        np.testing.assert_allclose(l_s, l_d, rtol=1e-5)
        np.testing.assert_allclose(w_s, w_d, rtol=1e-5, atol=1e-6)

    def test_untouched_rows_frozen_and_trains(self):
        losses, w = _train_momentum(is_sparse=True, steps=6)
        init = np.linspace(-1, 1, 50 * 8).astype(np.float32).reshape(50, 8)
        touched = {0, 1, 2, 3, 7}
        untouched = [i for i in range(50) if i not in touched]
        np.testing.assert_array_equal(w[untouched], init[untouched])
        assert losses[-1] < losses[0], losses
