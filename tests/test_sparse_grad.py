"""SelectedRows sparse embedding gradients (reference: selected_rows.h:19,
lookup_table_op.cc sparse grad path, selected_rows_functor.cc,
test_lookup_table_op.py). Sparse path must match the dense path bit-for-bit
on the updated table, and a word2vec-style step must train through it."""

import numpy as np

import paddle_tpu as fluid
from paddle_tpu import executor as executor_mod
from paddle_tpu import telemetry

RNG = np.random.RandomState(9)


def _train_once(is_sparse, steps=3):
    main = fluid.Program()
    startup = fluid.Program()
    with fluid.program_guard(main, startup):
        ids = fluid.layers.data(name="ids", shape=[4], dtype="int64")
        lbl = fluid.layers.data(name="lbl", shape=[1], dtype="int64")
        emb = fluid.layers.embedding(ids, size=[50, 8], is_sparse=is_sparse,
                                     param_attr=fluid.ParamAttr(name="emb_w"))
        flat = fluid.layers.reshape(emb, shape=[-1, 32])
        logits = fluid.layers.fc(input=flat, size=50,
                                 param_attr=fluid.ParamAttr(name="fc_w"))
        loss = fluid.layers.mean(
            fluid.layers.softmax_with_cross_entropy(logits, lbl))
        fluid.optimizer.SGDOptimizer(learning_rate=0.5).minimize(loss)
    exe = fluid.Executor(fluid.CPUPlace())
    scope = executor_mod.Scope()
    feed = {"ids": np.array([[1, 7, 7, 3], [0, 2, 2, 2]], np.int64),
            "lbl": np.array([[5], [9]], np.int64)}
    with executor_mod.scope_guard(scope):
        exe.run(startup)
        # deterministic init so sparse/dense runs start identical
        scope.set_var("emb_w", np.linspace(
            -1, 1, 50 * 8).astype(np.float32).reshape(50, 8))
        scope.set_var("fc_w", np.linspace(
            -0.5, 0.5, 32 * 50).astype(np.float32).reshape(32, 50))
        losses = []
        for _ in range(steps):
            v, = exe.run(main, feed=feed, fetch_list=[loss])
            losses.append(float(np.asarray(v).reshape(-1)[0]))
        w = np.asarray(scope.find_var("emb_w"))
    return losses, w


class TestSparseEmbeddingGrad:
    def test_sparse_matches_dense(self):
        l_dense, w_dense = _train_once(is_sparse=False)
        l_sparse, w_sparse = _train_once(is_sparse=True)
        # scatter-add order differs between the two paths; only float
        # accumulation noise is tolerated
        np.testing.assert_allclose(l_sparse, l_dense, rtol=1e-5)
        np.testing.assert_allclose(w_sparse, w_dense, rtol=1e-5, atol=1e-7)
        # rows never looked up must be untouched by the sparse update
        init = np.linspace(-1, 1, 50 * 8).astype(np.float32).reshape(50, 8)
        touched = {0, 1, 2, 3, 7}
        untouched = [i for i in range(50) if i not in touched]
        np.testing.assert_array_equal(w_sparse[untouched], init[untouched])

    def test_word2vec_step_sparse(self):
        """CBOW-style word2vec step through the sparse path converges
        (reference book test_word2vec config with is_sparse=True)."""
        main = fluid.Program()
        startup = fluid.Program()
        V, E = 40, 16
        with fluid.program_guard(main, startup):
            words = [fluid.layers.data(name=f"w{i}", shape=[1],
                                       dtype="int64") for i in range(4)]
            target = fluid.layers.data(name="tgt", shape=[1], dtype="int64")
            embs = [fluid.layers.embedding(
                w, size=[V, E], is_sparse=True,
                param_attr=fluid.ParamAttr(name="shared_emb"))
                for w in words]
            concat = fluid.layers.concat(embs, axis=1)
            hidden = fluid.layers.fc(input=concat, size=32, act="sigmoid")
            logits = fluid.layers.fc(input=hidden, size=V)
            loss = fluid.layers.mean(
                fluid.layers.softmax_with_cross_entropy(logits, target))
            fluid.optimizer.SGDOptimizer(learning_rate=1.0).minimize(loss)
        exe = fluid.Executor(fluid.CPUPlace())
        data = RNG.randint(0, V, size=(16, 5)).astype(np.int64)
        feed = {f"w{i}": data[:, i:i+1] for i in range(4)}
        feed["tgt"] = data[:, 4:5]
        with executor_mod.scope_guard(executor_mod.Scope()):
            exe.run(startup)
            first = None
            for _ in range(30):
                v, = exe.run(main, feed=feed, fetch_list=[loss])
                first = first or float(np.asarray(v).reshape(-1)[0])
            last = float(np.asarray(v).reshape(-1)[0])
        assert last < first * 0.5, (first, last)


def _train_opt(opt_factory, is_sparse, steps=3):
    """Shared net under a given optimizer: exercises the SelectedRows
    kernels (reference adam_op.h SparseAdamFunctor, momentum extension)."""
    main = fluid.Program()
    startup = fluid.Program()
    with fluid.program_guard(main, startup):
        ids = fluid.layers.data(name="ids", shape=[4], dtype="int64")
        lbl = fluid.layers.data(name="lbl", shape=[1], dtype="int64")
        emb = fluid.layers.embedding(ids, size=[50, 8], is_sparse=is_sparse,
                                     param_attr=fluid.ParamAttr(name="emb_w"))
        flat = fluid.layers.reshape(emb, shape=[-1, 32])
        logits = fluid.layers.fc(input=flat, size=50,
                                 param_attr=fluid.ParamAttr(name="fc_w"))
        loss = fluid.layers.mean(
            fluid.layers.softmax_with_cross_entropy(logits, lbl))
        opt_factory().minimize(loss)
    exe = fluid.Executor(fluid.CPUPlace())
    scope = executor_mod.Scope()
    feed = {"ids": np.array([[1, 7, 7, 3], [0, 2, 2, 2]], np.int64),
            "lbl": np.array([[5], [9]], np.int64)}
    with executor_mod.scope_guard(scope):
        exe.run(startup)
        scope.set_var("emb_w", np.linspace(
            -1, 1, 50 * 8).astype(np.float32).reshape(50, 8))
        scope.set_var("fc_w", np.linspace(
            -0.5, 0.5, 32 * 50).astype(np.float32).reshape(32, 50))
        losses = []
        for _ in range(steps):
            v, = exe.run(main, feed=feed, fetch_list=[loss])
            losses.append(float(np.asarray(v).reshape(-1)[0]))
        w = np.asarray(scope.find_var("emb_w"))
    return losses, w


def _train_adam(is_sparse, steps=3):
    return _train_opt(lambda: fluid.optimizer.Adam(learning_rate=0.1),
                      is_sparse, steps)


def _train_momentum(is_sparse, steps=3):
    return _train_opt(
        lambda: fluid.optimizer.Momentum(learning_rate=0.3, momentum=0.9),
        is_sparse, steps)


class TestSparseAdam:
    """Sparse adam semantics (reference adam_op.h sparse path): touched rows
    match... nothing — sparse adam is intentionally NOT equal to dense adam:
    dense adam decays every row's moments each step, sparse (lazy) only
    touches grad rows. Assert (a) the first step matches dense exactly
    (moments start at zero, so laziness is invisible), (b) untouched rows
    never move, (c) multi-step training still converges."""

    def test_first_step_matches_dense(self):
        l_d, w_d = _train_adam(is_sparse=False, steps=1)
        l_s, w_s = _train_adam(is_sparse=True, steps=1)
        np.testing.assert_allclose(l_s, l_d, rtol=1e-5)
        np.testing.assert_allclose(w_s, w_d, rtol=1e-5, atol=1e-6)

    def test_untouched_rows_frozen_and_trains(self):
        losses, w = _train_adam(is_sparse=True, steps=6)
        init = np.linspace(-1, 1, 50 * 8).astype(np.float32).reshape(50, 8)
        touched = {0, 1, 2, 3, 7}
        untouched = [i for i in range(50) if i not in touched]
        np.testing.assert_array_equal(w[untouched], init[untouched])
        assert losses[-1] < losses[0], losses


class TestSparseMomentum:
    def test_first_step_matches_dense(self):
        l_d, w_d = _train_momentum(is_sparse=False, steps=1)
        l_s, w_s = _train_momentum(is_sparse=True, steps=1)
        np.testing.assert_allclose(l_s, l_d, rtol=1e-5)
        np.testing.assert_allclose(w_s, w_d, rtol=1e-5, atol=1e-6)

    def test_untouched_rows_frozen_and_trains(self):
        losses, w = _train_momentum(is_sparse=True, steps=6)
        init = np.linspace(-1, 1, 50 * 8).astype(np.float32).reshape(50, 8)
        touched = {0, 1, 2, 3, 7}
        untouched = [i for i in range(50) if i not in touched]
        np.testing.assert_array_equal(w[untouched], init[untouched])
        assert losses[-1] < losses[0], losses


# ---------------------------------------------------------------------------
# Sharded scatter-apply parity (fsdp-partitioned tables, ISSUE 10)
# ---------------------------------------------------------------------------

# 64 rows so the table divides evenly over the 8 virtual devices conftest
# provides. Ids are unique within the batch, so merge_selected_rows is an
# identity permutation and sgd/momentum scatter-apply must be BITWISE equal
# to the dense reference (same adds, same order, no accumulation noise).
INIT64 = np.linspace(-1, 1, 64 * 8).astype(np.float32).reshape(64, 8)
INIT_FC64 = np.linspace(-0.5, 0.5, 32 * 50).astype(np.float32).reshape(32, 50)
UNIQUE_IDS = np.array([[1, 7, 12, 3], [0, 2, 9, 5]], np.int64)
TOUCHED64 = {0, 1, 2, 3, 5, 7, 9, 12}
LBL2 = np.array([[5], [9]], np.int64)


def _train64(opt_factory, *, is_sparse=True, devices=None, steps=3,
             step_ids=None):
    """64-row-table net. When `devices` is set, the table is row-sharded
    over an fsdp mesh of that many devices. Returns (per-step emb_w
    snapshots, per_shard_table_bytes report or None)."""
    from paddle_tpu.parallel import embedding as emb_mod
    from paddle_tpu.parallel.mesh import make_mesh
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        ids = fluid.layers.data(name="ids", shape=[4], dtype="int64")
        lbl = fluid.layers.data(name="lbl", shape=[1], dtype="int64")
        emb = fluid.layers.embedding(ids, size=[64, 8], is_sparse=is_sparse,
                                     param_attr=fluid.ParamAttr(name="emb_w"))
        flat = fluid.layers.reshape(emb, shape=[-1, 32])
        logits = fluid.layers.fc(input=flat, size=50,
                                 param_attr=fluid.ParamAttr(name="fc_w"))
        loss = fluid.layers.mean(
            fluid.layers.softmax_with_cross_entropy(logits, lbl))
        opt_factory().minimize(loss)
    per = None
    if devices is not None:
        main._mesh = make_mesh((devices,), ("fsdp",))
        emb_mod.shard_table(main, "emb_w", "fsdp")
    exe = fluid.Executor(fluid.CPUPlace())
    scope = executor_mod.Scope()
    snaps = []
    with executor_mod.scope_guard(scope):
        exe.run(startup)
        scope.set_var("emb_w", INIT64.copy())
        scope.set_var("fc_w", INIT_FC64.copy())
        for step in range(steps):
            cur = step_ids[step] if step_ids is not None else UNIQUE_IDS
            exe.run(main, feed={"ids": cur, "lbl": LBL2}, fetch_list=[loss])
            snaps.append(np.asarray(scope.find_var("emb_w")).copy())
        if devices is not None:
            per = emb_mod.per_shard_table_bytes(main, scope=scope)
    return snaps, per


class TestShardedScatterApplyParity:
    """Scatter-apply on an fsdp-sharded table vs the unsharded dense
    reference, at 1 and 8 devices. sgd/momentum are bitwise (unique ids:
    same floating-point ops in the same order); adam is float-tol (its
    per-row rescale tolerates reassociation under GSPMD)."""

    def _parity(self, opt_factory, devices, exact):
        dense, _ = _train64(opt_factory, is_sparse=False, devices=None)
        sharded, per = _train64(opt_factory, is_sparse=True, devices=devices)
        if exact:
            np.testing.assert_array_equal(sharded[-1], dense[-1])
        else:
            np.testing.assert_allclose(sharded[-1], dense[-1],
                                       rtol=1e-5, atol=1e-6)
        untouched = [i for i in range(64) if i not in TOUCHED64]
        np.testing.assert_array_equal(sharded[-1][untouched],
                                      INIT64[untouched])
        t = per["tables"]["emb_w"]
        assert t["factor"] == devices
        assert t["per_shard_bytes"] * devices == t["bytes"], t

    def test_sgd_1dev_bitwise(self):
        self._parity(lambda: fluid.optimizer.SGDOptimizer(0.5), 1, True)

    def test_sgd_8dev_bitwise(self):
        self._parity(lambda: fluid.optimizer.SGDOptimizer(0.5), 8, True)

    def test_momentum_1dev_bitwise(self):
        self._parity(lambda: fluid.optimizer.MomentumOptimizer(0.3, 0.9),
                     1, True)

    def test_momentum_8dev_bitwise(self):
        self._parity(lambda: fluid.optimizer.MomentumOptimizer(0.3, 0.9),
                     8, True)

    def test_adam_1dev(self):
        self._parity(lambda: fluid.optimizer.AdamOptimizer(0.1), 1, False)

    def test_adam_8dev(self):
        self._parity(lambda: fluid.optimizer.AdamOptimizer(0.1), 8, False)

    def test_adam_opt_state_shards_with_table(self):
        _, per = _train64(lambda: fluid.optimizer.AdamOptimizer(0.1),
                          is_sparse=True, devices=8, steps=1)
        t = per["tables"]["emb_w"]
        # two [64, 8] f32 moments shard 8-way; [1] beta-pows stay replicated
        assert t["opt_state_bytes"] == 2 * 64 * 8 * 4
        assert t["opt_state_per_shard_bytes"] == t["opt_state_bytes"] // 8


class TestLazyAdamSemantics:
    """Pin lazy-adam: a row with no gradient this step keeps both its value
    and its moments, while dense adam decays the moments and so keeps
    moving the row (reference adam_op.h sparse path)."""

    def test_row_absent_from_step2_is_frozen(self):
        step_ids = [UNIQUE_IDS,                       # row 5 touched
                    np.array([[1, 7, 12, 3], [0, 2, 9, 3]], np.int64)]
        adam = lambda: fluid.optimizer.AdamOptimizer(0.1)  # noqa: E731
        sparse, _ = _train64(adam, is_sparse=True, steps=2,
                             step_ids=step_ids)
        dense, _ = _train64(adam, is_sparse=False, steps=2,
                            step_ids=step_ids)
        # lazy: frozen bitwise at its post-step-1 value
        np.testing.assert_array_equal(sparse[1][5], sparse[0][5])
        # dense: decayed first moment still pushes row 5 in step 2
        assert np.any(dense[1][5] != dense[0][5])


class TestMergeSelectedRows:
    def test_duplicate_ids_merge_via_segment_sum(self):
        from paddle_tpu.ops.common import SelectedRowsVal, merge_selected_rows
        rows = np.array([7, 2, 7, 5, 2, 7], np.int32)
        vals = RNG.rand(6, 4).astype(np.float32)
        m_rows, m_vals = merge_selected_rows(SelectedRowsVal(rows, vals, 50))
        m_rows, m_vals = np.asarray(m_rows), np.asarray(m_vals)
        # static shapes survive the merge; freed slots park at height
        assert m_rows.shape == (6,) and m_vals.shape == (6, 4)
        keep = m_rows < 50
        assert sorted(m_rows[keep].tolist()) == [2, 5, 7]
        assert set(m_rows[~keep].tolist()) == {50}
        dense_ref = np.zeros((50, 4), np.float64)
        np.add.at(dense_ref, rows, vals.astype(np.float64))
        got = np.zeros((50, 4), np.float64)
        np.add.at(got, m_rows[keep], m_vals[keep].astype(np.float64))
        np.testing.assert_allclose(got, dense_ref, rtol=1e-6, atol=1e-7)
        # freed slots must scatter to nowhere, not to a live row
        assert not np.any(got[49] != dense_ref[49])


def _train_two_tables(opt_factory, is_sparse, steps=2):
    """Two sparse tables under one optimizer: the >= 2 same-dtype members
    the fusion pass needs to form a fused_sparse_* bucket."""
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        ids_a = fluid.layers.data(name="ids_a", shape=[4], dtype="int64")
        ids_b = fluid.layers.data(name="ids_b", shape=[4], dtype="int64")
        lbl = fluid.layers.data(name="lbl", shape=[1], dtype="int64")
        emb_a = fluid.layers.embedding(
            ids_a, size=[40, 8], is_sparse=is_sparse,
            param_attr=fluid.ParamAttr(name="two_emb_a"))
        emb_b = fluid.layers.embedding(
            ids_b, size=[30, 8], is_sparse=is_sparse,
            param_attr=fluid.ParamAttr(name="two_emb_b"))
        both = fluid.layers.concat([emb_a, emb_b], axis=1)
        flat = fluid.layers.reshape(both, shape=[-1, 64])
        logits = fluid.layers.fc(input=flat, size=20,
                                 param_attr=fluid.ParamAttr(name="two_fc"))
        loss = fluid.layers.mean(
            fluid.layers.softmax_with_cross_entropy(logits, lbl))
        opt_factory().minimize(loss)
    exe = fluid.Executor(fluid.CPUPlace())
    scope = executor_mod.Scope()
    feed = {"ids_a": np.array([[1, 7, 12, 3], [0, 2, 9, 5]], np.int64),
            "ids_b": np.array([[4, 8, 11, 6], [13, 10, 14, 15]], np.int64),
            "lbl": np.array([[5], [9]], np.int64)}
    with executor_mod.scope_guard(scope):
        exe.run(startup)
        scope.set_var("two_emb_a", np.linspace(
            -1, 1, 40 * 8).astype(np.float32).reshape(40, 8))
        scope.set_var("two_emb_b", np.linspace(
            -1, 1, 30 * 8).astype(np.float32).reshape(30, 8))
        scope.set_var("two_fc", np.linspace(
            -0.5, 0.5, 64 * 20).astype(np.float32).reshape(64, 20))
        for _ in range(steps):
            exe.run(main, feed=feed, fetch_list=[loss])
        w_a = np.asarray(scope.find_var("two_emb_a"))
        w_b = np.asarray(scope.find_var("two_emb_b"))
    return w_a, w_b


class TestFusedSparseBuckets:
    """Two same-dtype sparse tables bucket into one fused_sparse_<opt> op
    (ops/fusion.py): the fused execution must match the dense reference
    and the synthetic op must actually run (op-coverage gate)."""

    def _check(self, opt_factory, op_name):
        d_a, d_b = _train_two_tables(opt_factory, is_sparse=False)
        s_a, s_b = _train_two_tables(opt_factory, is_sparse=True)
        assert op_name in executor_mod._RECORDED_OPS
        np.testing.assert_allclose(s_a, d_a, rtol=1e-5, atol=1e-6)
        np.testing.assert_allclose(s_b, d_b, rtol=1e-5, atol=1e-6)

    def test_sgd_bucket(self):
        self._check(lambda: fluid.optimizer.SGDOptimizer(0.5),
                    "fused_sparse_sgd")

    def test_momentum_bucket(self):
        self._check(lambda: fluid.optimizer.MomentumOptimizer(0.3, 0.9),
                    "fused_sparse_momentum")

    def test_adam_bucket(self):
        self._check(lambda: fluid.optimizer.AdamOptimizer(0.1),
                    "fused_sparse_adam")


def _densify_delta(before):
    after = telemetry.read_series("sparse_densify_fallback_total")
    return {k: v - before.get(k, 0.0) for k, v in after.items()
            if v != before.get(k, 0.0)}


class TestDensifyCounters:
    """sparse_densify_fallback_total surfaces every silent dense fallback;
    the hot path (sgd/momentum/adam scatter-apply) must stay at zero."""

    def test_hot_path_never_densifies(self):
        before = telemetry.read_series("sparse_densify_fallback_total")
        _train_once(is_sparse=True)
        assert _densify_delta(before) == {}, _densify_delta(before)

    def test_gate_off_counts_and_matches_dense(self, monkeypatch):
        _, w_dense = _train_once(is_sparse=False)
        monkeypatch.setenv("PADDLE_TPU_SPARSE_APPLY", "0")
        before = telemetry.read_series("sparse_densify_fallback_total")
        _, w_gated = _train_once(is_sparse=True)
        delta = _densify_delta(before)
        assert delta.get("op=sgd,reason=gated_off", 0) >= 1, delta
        # the gated path densifies but must still train identically
        np.testing.assert_allclose(w_gated, w_dense, rtol=1e-5, atol=1e-7)

    def test_unsupported_optimizer_counts_fallback(self):
        # adagrad has no scatter-apply kernel, so the executor's sparse
        # boundary densifies its Grad input and attributes the fallback
        before = telemetry.read_series("sparse_densify_fallback_total")
        _train_opt(lambda: fluid.optimizer.AdagradOptimizer(0.1),
                   is_sparse=True)
        delta = _densify_delta(before)
        assert delta.get("op=adagrad,reason=sparse_unaware_op", 0) >= 1, delta

    def test_apply_rows_counter_fires(self):
        before = telemetry.read_series("sparse_apply_rows_total")
        _train_once(is_sparse=True, steps=1)
        after = telemetry.read_series("sparse_apply_rows_total")
        assert after.get("op=sgd", 0.0) > before.get("op=sgd", 0.0)


class TestSparseMemoryIndependence:
    """The acceptance bar for the scatter-apply path: step temporaries are
    independent of table rows (no [V, D] dense gradient or dense update
    ever materializes), proven by XLA's own static memory analysis."""

    def _temp_bytes(self, V, is_sparse):
        main, startup = fluid.Program(), fluid.Program()
        with fluid.program_guard(main, startup):
            ids = fluid.layers.data(name="ids", shape=[4], dtype="int64")
            lbl = fluid.layers.data(name="lbl", shape=[1], dtype="int64")
            emb = fluid.layers.embedding(
                ids, size=[V, 8], is_sparse=is_sparse,
                param_attr=fluid.ParamAttr(name="emb_w"))
            flat = fluid.layers.reshape(emb, shape=[-1, 32])
            logits = fluid.layers.fc(input=flat, size=50,
                                     param_attr=fluid.ParamAttr(name="fc_w"))
            loss = fluid.layers.mean(
                fluid.layers.softmax_with_cross_entropy(logits, lbl))
            fluid.optimizer.SGDOptimizer(learning_rate=0.5).minimize(loss)
        exe = fluid.Executor(fluid.CPUPlace())
        scope = executor_mod.Scope()
        with executor_mod.scope_guard(scope):
            exe.run(startup)
            rec = exe.static_memory_analysis(
                main, feed={"ids": UNIQUE_IDS, "lbl": LBL2},
                fetch_list=[loss], scope=scope)
        return rec.temp_bytes

    def test_temp_bytes_independent_of_table_rows(self):
        small, big = 2000, 34000
        table_delta = (big - small) * 8 * 4
        s_small = self._temp_bytes(small, is_sparse=True)
        s_big = self._temp_bytes(big, is_sparse=True)
        # sparse temporaries are a function of batch, not table height
        assert s_big == s_small, (s_small, s_big)
        # contrast: the dense path materializes [V, 8] grad + update
        d_small = self._temp_bytes(small, is_sparse=False)
        d_big = self._temp_bytes(big, is_sparse=False)
        assert d_big - d_small >= table_delta, (d_small, d_big, table_delta)
