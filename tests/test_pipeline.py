"""GPipe pipeline parallelism over a 'pp' mesh vs sequential oracle —
forward and gradients (parallel/pipeline.py)."""

import numpy as np
import pytest
import jax
import jax.numpy as jnp

from paddle_tpu.parallel import mesh as mesh_mod
from paddle_tpu.parallel.pipeline import gpipe, gpipe_reference

RNG = np.random.RandomState(23)


def stage_fn(params, h):
    w, b = params
    return jnp.tanh(h @ w + b)


@pytest.fixture(scope="module")
def mesh():
    return mesh_mod.make_mesh((8,), ("pp",))


def _setup(p=8, m=6, bsz=4, d=8):
    ws = jnp.asarray(RNG.randn(p, d, d).astype(np.float32) * 0.5)
    bs = jnp.asarray(RNG.randn(p, d).astype(np.float32) * 0.1)
    xs = jnp.asarray(RNG.randn(m, bsz, d).astype(np.float32))
    return (ws, bs), xs


class TestGPipe:
    def test_forward_matches_sequential(self, mesh):
        params, xs = _setup()
        want = gpipe_reference(stage_fn, params, xs)
        got = gpipe(stage_fn, params, xs, mesh)
        np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                                   rtol=2e-5, atol=2e-6)

    def test_gradients_match(self, mesh):
        params, xs = _setup(m=3)

        def loss_seq(params, xs):
            return jnp.sum(gpipe_reference(stage_fn, params, xs) ** 2)

        def loss_pipe(params, xs):
            return jnp.sum(gpipe(stage_fn, params, xs, mesh) ** 2)

        g_seq = jax.grad(loss_seq)(params, xs)
        g_pipe = jax.grad(loss_pipe)(params, xs)
        for a, b in zip(jax.tree_util.tree_leaves(g_pipe),
                        jax.tree_util.tree_leaves(g_seq)):
            np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                       rtol=1e-4, atol=1e-5)

    def test_microbatches_fewer_than_stages(self, mesh):
        params, xs = _setup(m=2)
        want = gpipe_reference(stage_fn, params, xs)
        got = gpipe(stage_fn, params, xs, mesh)
        np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                                   rtol=2e-5, atol=2e-6)
