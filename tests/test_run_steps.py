"""Fused multi-step loop (Executor.run_steps): parity with K sequential
run() calls must be BITWISE — same compiled per-step body, same rng
counter fold — plus fallback behavior (eager, LoD, check_nan_inf) and the
rng-counter atomicity contract."""

import numpy as np
import pytest

import paddle_tpu as fluid
from paddle_tpu import executor as executor_mod
from paddle_tpu import flags, telemetry
from paddle_tpu.errors import NonFiniteError

K = 4


def _clone_scope(src):
    """Deep-copy a scope so two executions start from identical state."""
    dst = executor_mod.Scope()
    for n, v in src.vars.items():
        if isinstance(v, executor_mod.LoDTensor):
            dst.set_var(n, executor_mod.LoDTensor(
                np.array(v.array(), copy=True), [list(l) for l in v.lod]))
        elif v is None or isinstance(v, (int, float)):
            dst.set_var(n, v)
        else:
            dst.set_var(n, np.array(v, copy=True))
    return dst


def _scope_arrays(scope):
    return {n: np.asarray(v.array())
            if isinstance(v, executor_mod.LoDTensor) else np.asarray(v)
            for n, v in scope.vars.items() if v is not None}


def _assert_scope_parity(sa, sb):
    a, b = _scope_arrays(sa), _scope_arrays(sb)
    assert set(a) == set(b), f"state keys differ: {set(a) ^ set(b)}"
    for n in a:
        np.testing.assert_array_equal(
            a[n], b[n], err_msg=f"state '{n}' diverged")


def _run_parity(prog, startup, loss, feeds, *, use_jit=None,
                expect_fallback_reason=None):
    """Run K sequential steps and one run_steps window from identical
    initial scopes; assert bitwise-equal losses and final state."""
    exe = fluid.Executor(fluid.CPUPlace())
    sa = executor_mod.Scope()
    exe.run(startup, scope=sa)
    sb = _clone_scope(sa)
    c0 = sa.find_var("__rng_counter__") or 0   # startup run advanced it

    seq_losses = []
    for f in feeds:
        out, = exe.run(prog, feed=f, fetch_list=[loss], scope=sa,
                       use_jit=use_jit)
        seq_losses.append(np.asarray(out))

    before = sum(telemetry.read_series(
        "executor_window_fallback_total").values())
    win_losses, = exe.run_steps(prog, feed_window=feeds, fetch_list=[loss],
                                scope=sb, fetch_mode="stack",
                                use_jit=use_jit)
    fell_back = sum(telemetry.read_series(
        "executor_window_fallback_total").values()) - before
    if expect_fallback_reason is None:
        assert fell_back == 0, "window path unexpectedly fell back"
    else:
        assert fell_back >= 1, \
            f"expected fallback ({expect_fallback_reason}) did not happen"
        series = telemetry.read_series("executor_window_fallback_total")
        assert any(expect_fallback_reason in k for k in series), series

    np.testing.assert_array_equal(np.stack(seq_losses),
                                  np.asarray(win_losses))
    # rng counter advanced identically (sequential: +1 per run; window: +K)
    assert (sa.find_var("__rng_counter__") or 0) == \
        (sb.find_var("__rng_counter__") or 0) == c0 + len(feeds)
    _assert_scope_parity(sa, sb)
    return exe, sa, sb


def _fit_a_line(dropout=False):
    prog, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(prog, startup):
        x = fluid.layers.data(name="x", shape=[13], dtype="float32")
        y = fluid.layers.data(name="y", shape=[1], dtype="float32")
        h = fluid.layers.fc(input=x, size=16, act="relu")
        if dropout:
            h = fluid.layers.dropout(h, dropout_prob=0.5)
        y_predict = fluid.layers.fc(input=h, size=1, act=None)
        cost = fluid.layers.square_error_cost(input=y_predict, label=y)
        avg_cost = fluid.layers.mean(cost)
        fluid.optimizer.SGD(learning_rate=0.05).minimize(
            avg_cost, startup_program=startup)
    rng = np.random.default_rng(7)
    w = rng.standard_normal((13, 1)).astype(np.float32)
    feeds = []
    for _ in range(K):
        xs = rng.standard_normal((8, 13)).astype(np.float32)
        feeds.append({"x": xs, "y": (xs @ w).astype(np.float32)})
    return prog, startup, avg_cost, feeds


def _conv_model():
    prog, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(prog, startup):
        img = fluid.layers.data(name="img", shape=[1, 8, 8],
                                dtype="float32")
        label = fluid.layers.data(name="label", shape=[1], dtype="int64")
        conv = fluid.layers.conv2d(input=img, num_filters=4, filter_size=3,
                                   act="relu")
        pool = fluid.layers.pool2d(conv, pool_size=2, pool_type="max",
                                   pool_stride=2)
        pred = fluid.layers.fc(input=pool, size=10, act="softmax")
        cost = fluid.layers.cross_entropy(input=pred, label=label)
        avg_cost = fluid.layers.mean(cost)
        fluid.optimizer.Momentum(learning_rate=0.01, momentum=0.9).minimize(
            avg_cost, startup_program=startup)
    rng = np.random.default_rng(3)
    feeds = [{"img": rng.standard_normal((4, 1, 8, 8)).astype(np.float32),
              "label": rng.integers(0, 10, (4, 1)).astype(np.int64)}
             for _ in range(K)]
    return prog, startup, avg_cost, feeds


def _seq_model():
    """Sequence (LoD) model: window stacking must reject the ragged feed
    and fall back to the per-step path with identical results."""
    prog, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(prog, startup):
        words = fluid.layers.data(name="words", shape=[1], dtype="int64",
                                  lod_level=1)
        label = fluid.layers.data(name="label", shape=[1], dtype="int64")
        emb = fluid.layers.embedding(input=words, size=[50, 8])
        pooled = fluid.layers.sequence_pool(input=emb, pool_type="sum")
        pred = fluid.layers.fc(input=pooled, size=2, act="softmax")
        cost = fluid.layers.cross_entropy(input=pred, label=label)
        avg_cost = fluid.layers.mean(cost)
        fluid.optimizer.SGD(learning_rate=0.05).minimize(
            avg_cost, startup_program=startup)
    rng = np.random.default_rng(11)
    feeds = []
    for _ in range(K):
        lens = [2, 3, 1]
        offs = np.concatenate([[0], np.cumsum(lens)]).tolist()
        flat = rng.integers(0, 50, (offs[-1], 1)).astype(np.int64)
        feeds.append({
            "words": executor_mod.LoDTensor(flat, [offs]),
            "label": rng.integers(0, 2, (3, 1)).astype(np.int64)})
    return prog, startup, avg_cost, feeds


class TestRunStepsParity:
    def test_fit_a_line_jit(self):
        _run_parity(*_fit_a_line())

    def test_conv_model_jit(self):
        _run_parity(*_conv_model())

    def test_dropout_rng_parity(self):
        """The scan carries the same uint32 counter the per-step path folds
        in: per-step dropout masks must be bitwise identical."""
        _run_parity(*_fit_a_line(dropout=True))

    def test_lod_feeds_fall_back(self):
        _run_parity(*_seq_model(), expect_fallback_reason="lod_feed")

    def test_eager_falls_back(self):
        _run_parity(*_fit_a_line(), use_jit=False,
                    expect_fallback_reason="eager")


class TestRunStepsAPI:
    def test_prestacked_dict_and_fetch_modes(self):
        prog, startup, loss, feeds = _fit_a_line()
        exe = fluid.Executor(fluid.CPUPlace())
        sa = executor_mod.Scope()
        exe.run(startup, scope=sa)
        sb = _clone_scope(sa)
        sc = _clone_scope(sa)

        stacked = {n: np.stack([f[n] for f in feeds]) for n in feeds[0]}
        all_losses, = exe.run_steps(prog, feed_window=feeds,
                                    fetch_list=[loss], scope=sa,
                                    fetch_mode="stack")
        last, = exe.run_steps(prog, feed_window=stacked, fetch_list=[loss],
                              scope=sb, fetch_mode="last")
        mean, = exe.run_steps(prog, feed_window=stacked, steps=K,
                              fetch_list=[loss], scope=sc, fetch_mode="mean")
        np.testing.assert_array_equal(all_losses[-1], last)
        np.testing.assert_allclose(np.asarray(all_losses).mean(axis=0),
                                   mean, rtol=1e-6)
        _assert_scope_parity(sa, sb)
        _assert_scope_parity(sa, sc)

    def test_window_shape_validation(self):
        prog, startup, loss, feeds = _fit_a_line()
        exe = fluid.Executor(fluid.CPUPlace())
        s = executor_mod.Scope()
        exe.run(startup, scope=s)
        with pytest.raises(ValueError, match="steps=3"):
            exe.run_steps(prog, feed_window=feeds, steps=3,
                          fetch_list=[loss], scope=s)
        bad = {n: np.stack([f[n] for f in feeds]) for n in feeds[0]}
        bad["y"] = bad["y"][:2]
        with pytest.raises(ValueError, match="leading dims"):
            exe.run_steps(prog, feed_window=bad, fetch_list=[loss], scope=s)
        with pytest.raises(ValueError, match="feed_window"):
            exe.run_steps(prog, fetch_list=[loss], scope=s)

    def test_steps_total_counts_k(self):
        prog, startup, loss, feeds = _fit_a_line()
        exe = fluid.Executor(fluid.CPUPlace())
        s = executor_mod.Scope()
        exe.run(startup, scope=s)
        before = sum(telemetry.read_series("executor_steps_total").values())
        exe.run_steps(prog, feed_window=feeds, fetch_list=[loss], scope=s)
        after = sum(telemetry.read_series("executor_steps_total").values())
        assert after - before == K


class TestRngCounterAtomicity:
    def test_failed_run_does_not_advance(self):
        prog, startup, loss, feeds = _fit_a_line()
        exe = fluid.Executor(fluid.CPUPlace())
        s = executor_mod.Scope()
        exe.run(startup, scope=s)
        c0 = s.find_var("__rng_counter__") or 0
        bad = dict(feeds[0])
        bad["x"] = np.full_like(bad["x"], np.nan)
        flags.set("check_nan_inf", True)
        try:
            with pytest.raises(NonFiniteError):
                exe.run(prog, feed=bad, fetch_list=[loss], scope=s)
        finally:
            flags.set("check_nan_inf", None)
        # the failed step must be replayable under the SAME key
        assert (s.find_var("__rng_counter__") or 0) == c0
        # state buffers were donated to the failed call; re-init (counter
        # survives in the scope) and confirm a good step advances by one
        exe.run(startup, scope=s)
        c1 = s.find_var("__rng_counter__")
        exe.run(prog, feed=feeds[0], fetch_list=[loss], scope=s)
        assert s.find_var("__rng_counter__") == c1 + 1

    def test_window_advances_atomically_by_k(self):
        prog, startup, loss, feeds = _fit_a_line()
        exe = fluid.Executor(fluid.CPUPlace())
        s = executor_mod.Scope()
        exe.run(startup, scope=s)
        c0 = s.find_var("__rng_counter__") or 0
        exe.run_steps(prog, feed_window=feeds, fetch_list=[loss], scope=s)
        assert s.find_var("__rng_counter__") == c0 + K
        exe.run_steps(prog, feed_window=feeds, fetch_list=[loss], scope=s)
        assert s.find_var("__rng_counter__") == c0 + 2 * K

    def test_fused_check_passes_finite_data(self):
        """The fused finiteness reduction (one sync per step) must not
        false-positive on a healthy step."""
        prog, startup, loss, feeds = _fit_a_line()
        exe = fluid.Executor(fluid.CPUPlace())
        s = executor_mod.Scope()
        exe.run(startup, scope=s)
        flags.set("check_nan_inf", True)
        try:
            out, = exe.run(prog, feed=feeds[0], fetch_list=[loss], scope=s)
        finally:
            flags.set("check_nan_inf", None)
        assert np.isfinite(np.asarray(out)).all()
