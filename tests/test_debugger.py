"""Program pretty printer + graphviz rendering (reference: debuger.py,
test_debugger.py)."""

import numpy as np

import paddle_tpu as fluid


def _toy_program():
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        x = fluid.layers.data(name="x", shape=[4], dtype="float32")
        y = fluid.layers.data(name="y", shape=[1], dtype="float32")
        pred = fluid.layers.fc(input=x, size=1)
        loss = fluid.layers.mean(fluid.layers.square_error_cost(pred, y))
        fluid.optimizer.SGD(learning_rate=0.1).minimize(loss)
    return main


class TestDebugger:
    def test_pprint_covers_ops_and_vars(self):
        main = _toy_program()
        out = []
        fluid.debugger.pprint_program(main, print_fn=out.append)
        text = "\n".join(out)
        assert "mul(" in text and "sgd(" in text
        assert "var x: float32" in text
        assert "persistable" in text        # parameters marked

    def test_draw_program_dot(self, tmp_path):
        main = _toy_program()
        path = str(tmp_path / "prog.dot")
        dot = fluid.debugger.draw_program(main, path=path, render=False)
        assert dot.startswith("digraph")
        assert 'label="mul"' in dot and 'label="sgd"' in dot
        assert "#c9e4ca" in dot             # parameter highlight present
        assert (tmp_path / "prog.dot").exists()
