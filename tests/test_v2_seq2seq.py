"""v2 seq2seq acceptance: the reference machine-translation demo shape —
bidirectional GRU encoder, simple_attention, gru_step decoder inside
recurrent_group for TRAINING, then beam_search + GeneratedInput
GENERATION sharing the trained parameters. Touches the v2 surface the
reference demo uses (networks.simple_attention is networks.py:1400)."""

import numpy as np
import pytest

import paddle_tpu as fluid
from paddle_tpu import executor as executor_mod
from paddle_tpu import v2 as paddle
from paddle_tpu.executor import LoDTensor
from paddle_tpu.v2 import layer as v2l

SRC_V, TRG_V = 16, 14
E, H = 8, 10
BOS, EOS = 0, 1


def _encoder(src):
    """Shared encoder config (train + generate): embedding ->
    bidirectional GRU -> per-step projection for attention."""
    emb = paddle.layer.embedding(input=src, size=E, vocab_size=SRC_V,
                                 param_attr="src_emb_w")
    enc = paddle.networks.bidirectional_gru(emb, size=H // 2)  # [.., H]
    enc_proj = fluid.layers.fc(input=enc, size=H, num_flatten_dims=2,
                               bias_attr=False,
                               param_attr=fluid.ParamAttr(name="att_u"))
    return enc, enc_proj


def test_attention_seq2seq_trains():
    """Training direction: per-step attention context + GRU decoder via
    recurrent_group, teacher-forced cross entropy; loss must drop on a
    learnable copy task."""
    rng = np.random.RandomState(0)
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        src = paddle.layer.data(
            name="src",
            type=paddle.data_type.integer_value_sequence(SRC_V))
        trg = paddle.layer.data(
            name="trg",
            type=paddle.data_type.integer_value_sequence(TRG_V))
        lab = paddle.layer.data(
            name="lab",
            type=paddle.data_type.integer_value_sequence(TRG_V))
        enc, enc_proj = _encoder(src)
        enc_last = fluid.layers.sequence_last_step(enc)
        enc_last = fluid.layers.fc(input=enc_last, size=H, act="tanh",
                                   param_attr=fluid.ParamAttr(name="boot_w"),
                                   bias_attr=fluid.ParamAttr(name="boot_b"))

        trg_emb = paddle.layer.embedding(input=trg, size=E,
                                         vocab_size=TRG_V,
                                         param_attr="trg_emb_w")

        def step(trg_word):
            prev = v2l.memory("dec_h", boot_layer=enc_last)
            context = paddle.networks.simple_attention(
                encoded_sequence=enc, encoded_proj=enc_proj,
                decoder_state=prev,
                transform_param_attr="att_w", softmax_param_attr="att_v")
            dec_in = fluid.layers.concat([context, trg_word, prev],
                                         axis=-1)
            proj = v2l.fc(dec_in, size=3 * H, param_attr="dec_proj_w",
                          bias_attr=False)
            return v2l.gru_step(proj, prev, size=H, name="dec_h",
                                param_attr="dec_gru_w",
                                bias_attr="dec_gru_b")

        dec_h = v2l.recurrent_group(step, trg_emb)
        logits = fluid.layers.fc(input=dec_h, size=TRG_V,
                                 num_flatten_dims=2,
                                 param_attr=fluid.ParamAttr(name="out_w"),
                                 bias_attr=fluid.ParamAttr(name="out_b"))
        flat = fluid.layers.reshape(logits, [-1, TRG_V])
        lab_flat = fluid.layers.reshape(lab, [-1, 1])
        cost = fluid.layers.mean(fluid.layers.softmax_with_cross_entropy(
            logits=flat, label=lab_flat))
        fluid.optimizer.Adam(learning_rate=0.02).minimize(
            cost, startup_program=startup)

    exe = fluid.Executor(fluid.CPUPlace())
    with executor_mod.scope_guard(executor_mod.Scope()):
        exe.run(startup)
        T = 5
        first = last = None
        for i in range(180):
            # copy task: target = source tokens shifted into trg vocab
            s = rng.randint(2, min(SRC_V, TRG_V) - 1, (2, T))
            flat_src = s.reshape(-1, 1).astype(np.int64)
            trg_in = np.concatenate(
                [np.full((2, 1), BOS), s[:, :-1]], axis=1) \
                .reshape(-1, 1).astype(np.int64)
            lab_np = s.reshape(-1, 1).astype(np.int64)
            offs = [0, T, 2 * T]
            l, = exe.run(main,
                         feed={"src": LoDTensor(flat_src, [offs]),
                               "trg": LoDTensor(trg_in, [offs]),
                               "lab": LoDTensor(lab_np, [offs])},
                         fetch_list=[cost])
            if first is None:
                first = float(l[0])
            last = float(l[0])
        assert last < first * 0.5, (first, last)


def test_generation_program_builds_and_runs():
    """Generation direction: the same encoder + attention-free GRU
    decoder under beam_search/GeneratedInput builds and produces sane
    hypotheses (the full attention step needs per-lane sequence expand —
    the fluid-level book decoder covers that; this pins the v2 path)."""
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        src = paddle.layer.data(
            name="src",
            type=paddle.data_type.integer_value_sequence(SRC_V))
        enc, _proj = _encoder(src)
        enc_last = fluid.layers.sequence_last_step(enc)
        enc_last = fluid.layers.fc(input=enc_last, size=H, act="tanh",
                                   param_attr=fluid.ParamAttr(name="boot_w"),
                                   bias_attr=fluid.ParamAttr(name="boot_b"))

        def gen_step(trg_emb, _enc_last):
            prev = v2l.memory("dec_h", boot_layer=_enc_last)
            dec_in = fluid.layers.concat([trg_emb, prev], axis=-1)
            proj = v2l.fc(dec_in, size=3 * H, num_flatten_dims=2,
                          param_attr="gen_proj_w", bias_attr=False)
            h = v2l.gru_step(proj, prev, size=H, name="dec_h",
                             param_attr="gen_gru_w", bias_attr="gen_gru_b")
            logits = v2l.fc(h, size=TRG_V, num_flatten_dims=2,
                            param_attr="out_w", bias_attr="out_b")
            return fluid.layers.softmax(logits)

        sentences, scores = v2l.beam_search(
            gen_step,
            input=[v2l.GeneratedInput(size=TRG_V,
                                      embedding_name="trg_emb_w",
                                      embedding_size=E),
                   v2l.StaticInput(enc_last)],
            bos_id=BOS, eos_id=EOS, beam_size=3, max_length=4)

    exe = fluid.Executor(fluid.CPUPlace())
    rng = np.random.RandomState(4)
    with executor_mod.scope_guard(executor_mod.Scope()):
        exe.run(startup)
        s = rng.randint(2, SRC_V, (6, 1)).astype(np.int64)
        out_ids, out_scores = exe.run(
            main, feed={"src": LoDTensor(s, [[0, 3, 6]])},
            fetch_list=[sentences, scores])
    out_ids = np.asarray(out_ids)
    assert out_ids.shape[:2] == (2, 3)
    assert (out_ids[:, :, 0] == BOS).all()
    assert (np.asarray(out_scores)[:, :-1]
            >= np.asarray(out_scores)[:, 1:] - 1e-5).all()
