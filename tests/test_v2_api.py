"""v2-style API facade (reference: python/paddle/v2 — layer DSL, SGD
event-loop trainer, Parameters numpy/tar access, infer): the reference's
pre-fluid user surface must work end-to-end over the fluid stack."""

import io

import numpy as np

import paddle_tpu as fluid
from paddle_tpu import v2 as paddle


def test_v2_fit_a_line_event_loop():
    x = paddle.layer.data(name="x", type=paddle.data_type.dense_vector(13))
    y = paddle.layer.fc(input=x, size=1)
    label = paddle.layer.data(name="y",
                              type=paddle.data_type.dense_vector(1))
    cost = paddle.layer.square_error_cost(input=y, label=label)

    parameters = paddle.parameters.create(cost)
    optimizer = paddle.optimizer.Momentum(momentum=0.9, learning_rate=1e-2)
    trainer = paddle.SGD(cost=cost, parameters=parameters,
                         update_equation=optimizer)

    rng = np.random.RandomState(0)
    w = rng.randn(13, 1).astype(np.float32)

    def reader():
        r = np.random.RandomState(1)
        for _ in range(8):
            batch = []
            for _ in range(32):
                xs = r.randn(13).astype(np.float32)
                batch.append((xs, (xs @ w).astype(np.float32)))
            yield batch

    events = {"iters": 0, "passes": 0, "costs": []}

    def handler(e):
        if isinstance(e, paddle.event.EndIteration):
            events["iters"] += 1
            events["costs"].append(e.cost)
        elif isinstance(e, paddle.event.EndPass):
            events["passes"] += 1

    trainer.train(reader, num_passes=3, event_handler=handler,
                  feeding={"x": 0, "y": 1})
    assert events["passes"] == 3 and events["iters"] == 24
    assert events["costs"][-1] < events["costs"][0] * 0.5, events["costs"]

    # parameters: numpy access + tar round-trip
    names = parameters.names()
    assert names, names
    buf = io.BytesIO()
    parameters.to_tar(buf)
    snap = {n: parameters[n].copy() for n in names}
    parameters[names[0]] = np.zeros_like(snap[names[0]])
    buf.seek(0)
    parameters.from_tar(buf)
    np.testing.assert_allclose(parameters[names[0]], snap[names[0]])

    # inference over the trained parameters
    out = paddle.infer(output_layer=y, parameters=parameters,
                       input=[(np.ones(13, np.float32),)],
                       feeding={"x": 0})
    assert out.shape == (1, 1) and np.isfinite(out).all()


def test_v2_classification_with_embedding():
    V = 40
    word = paddle.layer.data(name="w",
                             type=paddle.data_type.integer_value(V))
    emb = paddle.layer.embedding(input=word, size=16, vocab_size=V)
    hidden = paddle.layer.fc(input=emb, size=32,
                             act=paddle.activation.Tanh())
    logits = paddle.layer.fc(input=hidden, size=2)
    label = paddle.layer.data(name="l",
                              type=paddle.data_type.integer_value(2))
    cost = paddle.layer.classification_cost(input=logits, label=label)

    parameters = paddle.parameters.create(cost)
    trainer = paddle.SGD(cost=cost, parameters=parameters,
                         update_equation=paddle.optimizer.Adam(
                             learning_rate=5e-3))

    def reader():
        r = np.random.RandomState(0)
        for _ in range(20):
            ws = r.randint(0, V, 32)
            yield [([int(w)], [int(w % 2)]) for w in ws]

    costs = []
    trainer.train(reader, num_passes=2,
                  event_handler=lambda e: costs.append(e.cost)
                  if isinstance(e, paddle.event.EndIteration) else None,
                  feeding={"w": 0, "l": 1})
    assert costs[-1] < costs[0] * 0.6, (costs[0], costs[-1])


def test_v2_from_tar_then_infer_fresh_process_flow():
    """The save-then-load-elsewhere flow (reference parameters.from_tar +
    inference.infer without a trainer): loading into freshly created
    Parameters must drive inference with the LOADED weights."""
    # build once, train briefly, snapshot to tar
    x = paddle.layer.data(name="x", type=paddle.data_type.dense_vector(4))
    y = paddle.layer.fc(input=x, size=1)
    label = paddle.layer.data(name="l",
                              type=paddle.data_type.dense_vector(1))
    cost = paddle.layer.square_error_cost(input=y, label=label)
    params = paddle.parameters.create(cost)
    trainer = paddle.SGD(cost=cost, parameters=params,
                         update_equation=paddle.optimizer.SGD(
                             learning_rate=0.1))

    def reader():
        r = np.random.RandomState(0)
        for _ in range(5):
            yield [(r.randn(4).astype(np.float32),
                    np.array([1.0], np.float32)) for _ in range(16)]

    trainer.train(reader, num_passes=2, feeding={"x": 0, "l": 1})
    buf = io.BytesIO()
    params.to_tar(buf)
    probe = np.full((1, 4), 0.5, np.float32)
    want = paddle.infer(output_layer=y, parameters=params,
                        input=[(probe[0],)], feeding={"x": 0})

    # "fresh process": new Parameters object, from_tar BEFORE any trainer
    params2 = paddle.parameters.create(cost)
    buf.seek(0)
    params2.from_tar(buf)
    got = paddle.infer(output_layer=y, parameters=params2,
                       input=[(probe[0],)], feeding={"x": 0})
    np.testing.assert_allclose(got, want, rtol=1e-5)

    # and pre-loaded weights survive trainer creation
    trainer2 = paddle.SGD(cost=cost, parameters=params2,
                          update_equation=paddle.optimizer.SGD(
                              learning_rate=0.1))
    for n in params.names():
        np.testing.assert_allclose(params2[n], params[n], rtol=1e-6)
