"""Elastic end-to-end training proof (VERDICT r4 #6): multi-process
training over the shared TaskQueue where one worker is SIGKILLed mid-pass
and the job finishes with a DIFFERENT worker count — no sample lost, no
duplicate beyond the failure budget (the killed worker's in-flight task),
and the final parameters/loss match an uninterrupted single-process
oracle. Mirrors the Go master contract: go/master/service.go:341
timeout-requeue, :455 failure budget; trainers stateless, work
re-dispatched."""

import json
import multiprocessing as mp
import os
import signal
import time

import numpy as np
import pytest

from paddle_tpu.parallel.master import TaskQueue

N, D = 64, 4
TASKS = 8
PASSES = 3
LR = 0.01


def _spawn(ctx, wid, qdir, data, params, grads, log, **kw):
    from _elastic_worker import worker
    p = ctx.Process(target=worker,
                    args=(qdir, wid, data, params, grads, log),
                    kwargs=kw)
    p.start()
    return p


def test_sigkill_mid_pass_job_finishes_and_matches_oracle(tmp_path):
    rng = np.random.RandomState(3)
    x = rng.randn(N, D).astype(np.float64)
    w_true = rng.randn(D).astype(np.float64)
    y = x @ w_true
    data_path = str(tmp_path / "data.npz")
    np.savez(data_path, x=x, y=y)

    qdir = str(tmp_path / "queue")
    grads = str(tmp_path / "grads")
    os.makedirs(qdir)
    os.makedirs(grads)
    params_path = str(tmp_path / "params.npy")
    w = np.zeros(D)
    np.save(params_path, w)

    sample_ids = [list(range(i, N, TASKS)) for i in range(TASKS)]
    chunk_of = {str(t): set(ids) for t, ids in enumerate(sample_ids)}

    q = TaskQueue(qdir, timeout_s=2.0)
    q.partition(sample_ids, chunks_per_task=1)

    ctx = mp.get_context("spawn")
    logs = []
    killed_task_samples = None
    for pass_no in range(PASSES):
        procs = {}
        if pass_no == 0:
            # three workers; w0 is slowed so the parent can SIGKILL it
            # reliably mid-task (a real preemption, not a clean exit).
            # w0 starts ALONE and the parent waits for its lease marker
            # before spawning the fast workers — on a 1-core box the
            # fast pair can otherwise drain the whole pass before the
            # slow worker's spawn even finishes (observed in-suite).
            marker = str(tmp_path / "w0_started")
            log0 = str(tmp_path / f"log_w0_{pass_no}.json")
            procs["w0"] = _spawn(ctx, "w0", qdir, data_path, params_path,
                                 grads, log0, slow_s=30.0,
                                 marker_path=marker)
            logs.append(("w0", log0))
            deadline = time.time() + 60
            while not os.path.exists(marker) and time.time() < deadline:
                time.sleep(0.02)
            assert os.path.exists(marker), "w0 never leased a task"
            for wid in ("w1", "w2"):
                log = str(tmp_path / f"log_{wid}_{pass_no}.json")
                procs[wid] = _spawn(ctx, wid, qdir, data_path,
                                    params_path, grads, log)
                logs.append((wid, log))
            os.kill(procs["w0"].pid, signal.SIGKILL)
            procs["w0"].join(timeout=30)
            assert procs["w0"].exitcode == -signal.SIGKILL
            # which task did w0 die holding? (for the duplicate bound)
            state = json.load(open(os.path.join(qdir, "queue.json")))
            w0_pending = [t for t, lease in state["pending"].items()
                          if lease["worker"] == "w0"]
            assert len(w0_pending) <= 1
            if w0_pending:
                killed_task_samples = chunk_of[w0_pending[0]]
            del procs["w0"]
        else:
            # the job CONTINUES with a different worker count (2 not 3)
            for wid in ("w1", "w2"):
                log = str(tmp_path / f"log_{wid}_{pass_no}.json")
                procs[wid] = _spawn(ctx, wid, qdir, data_path,
                                    params_path, grads, log)
                logs.append((wid, log))
        for wid, p in procs.items():
            p.join(timeout=120)
            assert p.exitcode == 0, (wid, p.exitcode)
        assert q.pass_done()

        # reduce: per-task gradient files are idempotent, so the requeued
        # task contributes exactly once no matter how many times it ran
        files = sorted(os.listdir(grads))
        assert files == [f"task_{t}.npy" for t in range(TASKS)], files
        grad = sum(np.load(os.path.join(grads, f)) for f in files)
        w = w - LR * grad
        np.save(params_path, w)
        for f in files:
            os.remove(os.path.join(grads, f))
        q.reset_pass()

    # 1) parameters match the uninterrupted single-process oracle exactly
    #    (same full-batch GD, same reduction order)
    w_oracle = np.zeros(D)
    for _ in range(PASSES):
        order = sorted(range(TASKS), key=lambda t: f"task_{t}.npy")
        grad = sum(x[sample_ids[t]].T @ (x[sample_ids[t]] @ w_oracle
                                         - y[sample_ids[t]])
                   for t in order)
        w_oracle = w_oracle - LR * grad
    np.testing.assert_allclose(w, w_oracle, rtol=1e-12)
    loss = 0.5 * np.mean((x @ w - y) ** 2)
    loss_oracle = 0.5 * np.mean((x @ w_oracle - y) ** 2)
    assert abs(loss - loss_oracle) < 1e-12
    assert loss < 0.5 * np.mean(y ** 2)            # it actually trained

    # 2) per-pass sample accounting: every sample covered every pass; any
    #    duplicate consumption is confined to the killed worker's
    #    in-flight task (the at-least-once failure budget)
    for pass_no in range(PASSES):
        seen = []
        for wid, log in logs:
            if log.endswith(f"_{pass_no}.json") and os.path.exists(log):
                seen.extend(json.load(open(log)))
        covered = set(seen)
        assert covered == set(range(N)), f"pass {pass_no} lost samples"
        dupes = {s for s in covered if seen.count(s) > 1}
        if pass_no == 0 and killed_task_samples is not None:
            assert dupes <= killed_task_samples, (
                "duplicates outside the requeued task", dupes)
        else:
            assert not dupes
