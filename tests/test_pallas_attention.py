"""Pallas flash-attention kernel (ops/pallas_attention.py): online-softmax
VMEM kernel vs the XLA reference. On the CPU test platform the kernel runs
under the Pallas interpreter — the same code Mosaic compiles on TPU
(measured r3: 1.5x over the XLA reference at T=4096 causal on v5e)."""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

import paddle_tpu as fluid
from paddle_tpu.ops.pallas_attention import flash_attention, supports
from paddle_tpu.parallel.ring_attention import attention_reference

RNG = np.random.default_rng(7)


def _qkv(b, t, h, d):
    return tuple(jnp.asarray(RNG.standard_normal((b, t, h, d))
                             .astype(np.float32)) for _ in range(3))


class TestFlashKernel:
    @pytest.mark.parametrize("shape", [(2, 64, 2, 32), (1, 128, 4, 64),
                                       (2, 256, 2, 64)])
    @pytest.mark.parametrize("causal", [False, True])
    def test_matches_reference(self, shape, causal):
        q, k, v = _qkv(*shape)
        # ambient default matmul precision on this platform is bf16-class;
        # compare the algorithms at full precision
        with jax.default_matmul_precision("highest"):
            got = flash_attention(q, k, v, causal)
            want = attention_reference(q, k, v, causal=causal)
            np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                                       rtol=2e-5, atol=2e-6)

    @pytest.mark.parametrize("shape", [(1, 128, 2, 32), (2, 256, 2, 64)])
    @pytest.mark.parametrize("causal", [False, True])
    def test_gradients_match_reference(self, shape, causal):
        """The Pallas flash backward (dQ/dK/dV kernels recomputing from the
        saved logsumexp) vs autodiff through the einsum reference. A
        different algorithm at f32: tolerance 1e-3 abs (grads are O(1)
        here), the VERDICT r3 acceptance bar."""
        q, k, v = _qkv(*shape)
        with jax.default_matmul_precision("highest"):
            g1 = jax.grad(lambda a, b, c: jnp.sum(
                flash_attention(a, b, c, causal) ** 2), argnums=(0, 1, 2))(
                    q, k, v)
            g2 = jax.grad(lambda a, b, c: jnp.sum(
                attention_reference(a, b, c, causal=causal) ** 2),
                argnums=(0, 1, 2))(q, k, v)
        for a, b in zip(g1, g2):
            np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                       rtol=1e-3, atol=1e-3)

    def test_supports_gating(self):
        # T <= 128 takes the block = T path (works untiled, but must be
        # sublane-aligned: T % 8); larger T must tile by 128; rank-3
        # inputs are rejected
        assert supports(*_qkv(1, 104, 2, 32))
        assert supports(*_qkv(1, 256, 1, 64))
        assert supports(*_qkv(1, 64, 1, 64))
        assert not supports(*_qkv(1, 100, 2, 32))   # 100 % 8 != 0
        assert not supports(*_qkv(1, 257, 1, 64))
        q3 = jnp.zeros((2, 64, 32))
        assert not supports(q3, q3, q3)

    def test_sub128_untiled_path_matches(self):
        q, k, v = _qkv(1, 104, 1, 32)       # block = T = 104 (untiled)
        with jax.default_matmul_precision("highest"):
            got = flash_attention(q, k, v, True)
            want = attention_reference(q, k, v, causal=True)
        np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                                   rtol=2e-5, atol=2e-6)

    def test_saved_lse_matches_reference(self):
        """The forward's saved logsumexp equals log-sum-exp of the scaled
        (masked) scores — the invariant the backward kernels rely on."""
        from paddle_tpu.ops.pallas_attention import _forward
        q, k, v = _qkv(1, 128, 2, 32)
        with jax.default_matmul_precision("highest"):
            _, lse = _forward(q, k, v, True, return_lse=True)
            s = jnp.einsum("bqhd,bkhd->bhqk", q, k) / np.sqrt(q.shape[-1])
            mask = jnp.tril(jnp.ones((128, 128), bool))
            s = jnp.where(mask, s, -jnp.inf)
            want = jax.scipy.special.logsumexp(s, axis=-1)
        np.testing.assert_allclose(np.asarray(lse), np.asarray(want),
                                   rtol=1e-5, atol=1e-5)


class TestFlashThroughProgram:
    def test_layer_flash_matches_plain(self):
        """fused_attention(use_flash=True) through the executor equals the
        plain path on the same feed."""
        from paddle_tpu import executor as executor_mod
        outs = {}
        qv = RNG.standard_normal((2, 64, 2, 32)).astype(np.float32)
        kv = RNG.standard_normal((2, 64, 2, 32)).astype(np.float32)
        vv = RNG.standard_normal((2, 64, 2, 32)).astype(np.float32)
        for flash in (False, True):
            main, startup = fluid.Program(), fluid.Program()
            with fluid.program_guard(main, startup):
                q = fluid.layers.data(name="q", shape=[-1, 64, 2, 32],
                                      dtype="float32",
                                      append_batch_size=False)
                k = fluid.layers.data(name="k", shape=[-1, 64, 2, 32],
                                      dtype="float32",
                                      append_batch_size=False)
                v = fluid.layers.data(name="v", shape=[-1, 64, 2, 32],
                                      dtype="float32",
                                      append_batch_size=False)
                out = fluid.layers.fused_attention(q, k, v, causal=True,
                                                   use_flash=flash)
            exe = fluid.Executor(fluid.CPUPlace())
            sc = executor_mod.Scope()
            with executor_mod.scope_guard(sc):
                exe.run(startup)
                with jax.default_matmul_precision("highest"):
                    r, = exe.run(main, feed={"q": qv, "k": kv, "v": vv},
                                 fetch_list=[out])
            outs[flash] = np.asarray(r)
        np.testing.assert_allclose(outs[True], outs[False],
                                   rtol=2e-5, atol=2e-6)


class TestFlashRingComposition:
    def test_flash_within_shard_ring_across(self):
        """ring_attention_sharded(use_flash=True): the Pallas block kernels
        compute each shard's contribution in BOTH directions (forward
        online-softmax; backward dQ/dK/dV from saved LSE, with the dK/dV
        accumulators riding the ring) — output and gradients must match
        plain attention. 2-device mesh: interpret-mode pallas inside
        shard_map compiles slowly, and the composition logic is
        device-count independent."""
        from jax.sharding import Mesh
        mesh = Mesh(np.array(jax.devices()[:2]), ("sp",))
        q, k, v = _qkv(1, 64, 1, 16)
        with jax.default_matmul_precision("highest"):
            from paddle_tpu.parallel.ring_attention import (
                attention_reference, ring_attention_sharded)
            for causal in (False, True):
                got = ring_attention_sharded(q, k, v, mesh, causal=causal,
                                             use_flash=True)
                want = attention_reference(q, k, v, causal=causal)
                np.testing.assert_allclose(np.asarray(got),
                                           np.asarray(want),
                                           rtol=2e-5, atol=2e-6)
            g1 = jax.grad(lambda a, b, c: jnp.sum(ring_attention_sharded(
                a, b, c, mesh, causal=True, use_flash=True) ** 2),
                argnums=(0, 1, 2))(q, k, v)
            g2 = jax.grad(lambda a, b, c: jnp.sum(attention_reference(
                a, b, c, causal=True) ** 2), argnums=(0, 1, 2))(q, k, v)
        # flash backward recomputes from LSE — a different algorithm at
        # f32, so 1e-3-class tolerance (same bar as the kernel tests)
        for a, b in zip(g1, g2):
            np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                       rtol=1e-3, atol=1e-3)
