"""Every dataset module must parse the REFERENCE's real on-disk format
(VERDICT r4 missing #1). Each test builds a tiny format-faithful fixture
(the same container type, member layout and record syntax as the upstream
release), points DATA_HOME at it, and checks the reader yields the real
records — then that removing the fixture falls back to synthetic."""

import gzip
import io
import os
import pickle
import tarfile
import zipfile

import numpy as np
import pytest

import paddle_tpu.dataset as ds
from paddle_tpu.dataset import common


@pytest.fixture()
def data_home(tmp_path, monkeypatch):
    monkeypatch.setattr(common, "DATA_HOME", str(tmp_path))
    # modules with parse-once metadata caches must not leak between tests
    monkeypatch.setattr(ds.movielens, "_MOVIE_INFO", None)
    monkeypatch.setattr(ds.movielens, "_USER_INFO", None)
    monkeypatch.setattr(ds.sentiment, "_DATA_CACHE", None)
    monkeypatch.setattr(ds.imdb, "_DICT_CACHE", None)
    return tmp_path


def _tar_bytes(tar, name, payload):
    info = tarfile.TarInfo(name)
    info.size = len(payload)
    tar.addfile(info, io.BytesIO(payload))


# --- cifar -------------------------------------------------------------------

def test_cifar10_parses_pickled_tarball(data_home):
    d = data_home / "cifar"
    d.mkdir()
    rng = np.random.RandomState(0)
    with tarfile.open(d / "cifar-10-python.tar.gz", "w:gz") as tar:
        for name, labels in (("cifar-10-batches-py/data_batch_1", [3, 7]),
                             ("cifar-10-batches-py/test_batch", [1])):
            batch = {"data": rng.randint(0, 256, (len(labels), 3072))
                     .astype(np.uint8),
                     "labels": labels}
            _tar_bytes(tar, name, pickle.dumps(batch, protocol=2))
    got = list(ds.cifar.train10()())
    assert len(got) == 2
    img, label = got[0]
    assert img.shape == (3072,) and img.dtype == np.float32
    assert 0.0 <= img.min() and img.max() <= 1.0 and label == 3
    assert [lab for _, lab in ds.cifar.test10()()] == [1]


def test_cifar100_uses_fine_labels(data_home):
    d = data_home / "cifar"
    d.mkdir()
    rng = np.random.RandomState(1)
    with tarfile.open(d / "cifar-100-python.tar.gz", "w:gz") as tar:
        batch = {"data": rng.randint(0, 256, (2, 3072)).astype(np.uint8),
                 "fine_labels": [42, 99]}
        _tar_bytes(tar, "cifar-100-python/train",
                   pickle.dumps(batch, protocol=2))
    assert [lab for _, lab in ds.cifar.train100()()] == [42, 99]


# --- imdb --------------------------------------------------------------------

def test_imdb_parses_aclimdb_tarball(data_home):
    d = data_home / "imdb"
    d.mkdir()
    docs = {
        "aclImdb/train/pos/0_9.txt": b"A great, GREAT movie! great fun",
        "aclImdb/train/neg/0_1.txt": b"terrible. just terrible terrible",
        "aclImdb/test/pos/0_8.txt": b"great great great great",
        "aclImdb/test/neg/0_2.txt": b"terrible terrible terrible plot",
    }
    with tarfile.open(d / "aclImdb_v1.tar.gz", "w:gz") as tar:
        for name, text in docs.items():
            _tar_bytes(tar, name, text)
    w = ds.imdb.build_dict(
        __import__("re").compile(r"aclImdb/train/.*\.txt$"), cutoff=1)
    # punctuation stripped + lowercased: 'great' (4x) ranks before
    # 'terrible' (3x in train)
    assert w["great"] == 0 and w["terrible"] == 1
    assert "<unk>" in w
    got = list(ds.imdb.train(w)())
    assert len(got) == 2
    (pos_ids, pos_lab), (neg_ids, neg_lab) = got
    assert pos_lab == 0 and neg_lab == 1          # reference's assignment
    assert pos_ids.count(w["great"]) == 3         # 'great,' and 'GREAT!'
    assert all(isinstance(i, int) for i in pos_ids)


# --- imikolov ----------------------------------------------------------------

def test_imikolov_ngram_and_seq(data_home):
    d = data_home / "imikolov"
    d.mkdir()
    train_txt = b" the cat sat \n the cat ran \n"
    valid_txt = b" the cat sat \n"
    with tarfile.open(d / "simple-examples.tgz", "w:gz") as tar:
        _tar_bytes(tar, "./simple-examples/data/ptb.train.txt", train_txt)
        _tar_bytes(tar, "./simple-examples/data/ptb.valid.txt", valid_txt)
    w = ds.imikolov.build_dict(min_word_freq=0)
    assert w["<unk>"] == len(w) - 1
    assert set(w) == {"the", "cat", "sat", "ran", "<s>", "<e>", "<unk>"}
    grams = list(ds.imikolov.train(w, n=2)())
    # line 1: <s> the cat sat <e> -> 4 bigrams; line 2 same count
    assert len(grams) == 8
    assert grams[0] == (w["<s>"], w["the"])
    seqs = list(ds.imikolov.train(w, n=10,
                                  data_type=ds.imikolov.DataType.SEQ)())
    assert seqs[0][0] == [w["<s>"], w["the"], w["cat"], w["sat"]]
    assert seqs[0][1] == [w["the"], w["cat"], w["sat"], w["<e>"]]


# --- movielens ---------------------------------------------------------------

def test_movielens_parses_ml1m_zip(data_home):
    d = data_home / "movielens"
    d.mkdir()
    with zipfile.ZipFile(d / "ml-1m.zip", "w") as z:
        z.writestr("ml-1m/movies.dat",
                   "1::Toy Story (1995)::Animation|Comedy\n"
                   "2::Heat (1995)::Action\n")
        z.writestr("ml-1m/users.dat",
                   "1::F::1::10::48067\n2::M::56::16::70072\n")
        z.writestr("ml-1m/ratings.dat",
                   "1::1::5::978300760\n2::2::1::978298413\n")
    samples = list(ds.movielens.train()())
    # test_ratio split may route either record to test; whichever remain
    # must carry parsed metadata
    assert samples
    for s in samples:
        uid, gender, age, job, mid, cats, titles, score = s
        if uid == [1]:
            assert gender == [1]                  # F -> 1
            assert age == [0] and job == [10] and mid == [1]
            assert len(cats) == 2 and len(titles) == 2   # 'Toy Story'
            assert score == [5.0 * 2 - 5.0]
        else:
            assert uid == [2] and gender == [0]   # M -> 0
            assert age == [6]                     # 56 -> index 6
            assert score == [1.0 * 2 - 5.0]
    assert ds.movielens.max_user_id() == 2
    assert ds.movielens.max_movie_id() == 2
    cats = ds.movielens.movie_categories()
    assert set(cats) == {"Animation", "Comedy", "Action"}
    title_dict = ds.movielens.get_movie_title_dict()
    assert "toy" in title_dict and "heat" in title_dict


# --- conll05 -----------------------------------------------------------------

def test_conll05_parses_props_brackets(data_home):
    d = data_home / "conll05st"
    d.mkdir()
    # two-predicate sentence in the real column format: col0 = verb lemma
    # or '-', one tag-stream column per predicate
    words = "The\ncat\nchased\na\ndog\n\n"
    props = ("-   (A0*  *\n"
             "-   *)    (A0*)\n"
             "chase (V*V) *\n"
             "-   (A1*  (V*V)\n"
             "-   *)    (A1*)\n"
             "\n")
    # normalize: real props use (V*) for the verb; build faithful streams
    props = ("-\t(A0*\t*\n"
             "-\t*)\t(A0*)\n"
             "chase\t(V*)\t*\n"
             "see\t(A1*\t(V*)\n"
             "-\t*)\t(A1*)\n"
             "\n")
    for name, text in (("words", words), ("props", props)):
        buf = io.BytesIO()
        with gzip.GzipFile(fileobj=buf, mode="w") as g:
            g.write(text.encode())
        setattr(test_conll05_parses_props_brackets, name, buf.getvalue())
    with tarfile.open(d / "conll05st-tests.tar.gz", "w:gz") as tar:
        _tar_bytes(tar, "conll05st-release/test.wsj/words/"
                   "test.wsj.words.gz",
                   test_conll05_parses_props_brackets.words)
        _tar_bytes(tar, "conll05st-release/test.wsj/props/"
                   "test.wsj.props.gz",
                   test_conll05_parses_props_brackets.props)
    (d / "wordDict.txt").write_text(
        "The\ncat\nchased\na\ndog\nbos\neos\n")
    (d / "verbDict.txt").write_text("chase\nsee\n")
    (d / "targetDict.txt").write_text("B-A0\nI-A0\nB-A1\nI-A1\nB-V\nO\n")
    samples = list(ds.conll05.test()())
    assert len(samples) == 2                       # one per predicate
    word_d, verb_d, label_d = ds.conll05.get_dict()
    words_ids, pred, ctx_n2, ctx_n1, ctx_0, ctx_p1, ctx_p2, mark, \
        labels = samples[0]
    assert words_ids == [word_d[w] for w in
                         ("The", "cat", "chased", "a", "dog")]
    assert pred == [verb_d["chase"]] * 5
    assert ctx_0 == [word_d["chased"]] * 5         # the B-V word
    assert mark == [1, 1, 1, 1, 1]                 # v-2..v+2 window
    # first predicate: The..cat = A0 (B,I), chased = V, a..dog = A1 (B,I)
    assert labels == [label_d["B-A0"], label_d["I-A0"], label_d["B-V"],
                      label_d["B-A1"], label_d["I-A1"]]
    assert label_d["O"] == max(label_d.values())


# --- sentiment ---------------------------------------------------------------

def test_sentiment_parses_movie_reviews_dir(data_home):
    base = data_home / "sentiment" / "corpora" / "movie_reviews"
    (base / "neg").mkdir(parents=True)
    (base / "pos").mkdir(parents=True)
    (base / "neg" / "cv000.txt").write_text("bad bad plot .")
    (base / "pos" / "cv000.txt").write_text("good good good film !")
    wd = dict(ds.sentiment.get_word_dict())
    assert wd["good"] == 0 and wd["bad"] == 1      # freq-sorted
    samples = list(ds.sentiment.train()())
    assert len(samples) == 2                       # interleaved neg, pos
    assert samples[0][1] == 0 and samples[1][1] == 1
    assert samples[1][0].count(wd["good"]) == 3
    assert wd["."] in samples[0][0]                # punctuation tokenized


# --- wmt14 -------------------------------------------------------------------

def test_wmt14_parses_tarball(data_home):
    d = data_home / "wmt14"
    d.mkdir()
    src_dict = "<s>\n<e>\n<unk>\nles\nchats\n"
    trg_dict = "<s>\n<e>\n<unk>\nthe\ncats\n"
    train = "les chats\tthe cats\nles " + "x " * 100 + "\tthe\n"
    test = "les\tthe\n"
    with tarfile.open(d / "wmt14.tgz", "w:gz") as tar:
        _tar_bytes(tar, "wmt14/src.dict", src_dict.encode())
        _tar_bytes(tar, "wmt14/trg.dict", trg_dict.encode())
        _tar_bytes(tar, "wmt14/train/train", train.encode())
        _tar_bytes(tar, "wmt14/test/test", test.encode())
    got = list(ds.wmt14.train(dict_size=5)())
    assert len(got) == 1                           # >80-token pair dropped
    src_ids, trg_ids, trg_next = got[0]
    assert src_ids == [0, 3, 4, 1]                 # <s> les chats <e>
    assert trg_ids == [0, 3, 4]                    # <s> the cats
    assert trg_next == [3, 4, 1]                   # the cats <e>
    sd, td = ds.wmt14.get_dict(5)
    assert sd["chats"] == 4 and td["cats"] == 4
    rsd, _ = ds.wmt14.get_dict(5, reverse=True)
    assert rsd[4] == "chats"


# --- wmt16 -------------------------------------------------------------------

def test_wmt16_builds_dicts_and_parses(data_home):
    d = data_home / "wmt16"
    d.mkdir()
    train = ("two men\tzwei manner\n"
             "two dogs\tzwei hunde\n")
    val = "two men\tzwei manner\n"
    with tarfile.open(d / "wmt16.tar.gz", "w:gz") as tar:
        _tar_bytes(tar, "wmt16/train", train.encode())
        _tar_bytes(tar, "wmt16/val", val.encode())
        _tar_bytes(tar, "wmt16/test", val.encode())
    got = list(ds.wmt16.train(src_dict_size=6, trg_dict_size=6)())
    assert len(got) == 2
    src_ids, trg_ids, trg_next = got[0]
    en = ds.wmt16.get_dict("en", 6)
    de = ds.wmt16.get_dict("de", 6)
    assert en["<s>"] == 0 and en["<e>"] == 1 and en["<unk>"] == 2
    assert src_ids == [0, en["two"], en["men"], 1]
    assert trg_ids == [0, de["zwei"], de["manner"]]
    assert trg_next == [de["zwei"], de["manner"], 1]
    # dict files are cached on disk like the reference
    assert (d / "en_6.dict").exists()
    # de as source flips the columns
    got_de = list(ds.wmt16.train(6, 6, src_lang="de")())
    assert got_de[0][0][1] == ds.wmt16.get_dict("de", 6)["zwei"]
    with pytest.raises(ValueError):
        ds.wmt16.train(6, 6, src_lang="fr")


# --- flowers -----------------------------------------------------------------

def test_flowers_parses_tgz_and_mats(data_home):
    from PIL import Image
    import scipy.io as scio

    d = data_home / "flowers"
    d.mkdir()
    rng = np.random.RandomState(0)
    with tarfile.open(d / "102flowers.tgz", "w:gz") as tar:
        for i in (1, 2):
            img = Image.fromarray(
                rng.randint(0, 256, (300, 280, 3)).astype(np.uint8))
            buf = io.BytesIO()
            img.save(buf, format="JPEG")
            _tar_bytes(tar, f"jpg/image_{i:05d}.jpg", buf.getvalue())
    scio.savemat(d / "imagelabels.mat",
                 {"labels": np.array([[5, 102]], np.uint8)})
    scio.savemat(d / "setid.mat",
                 {"trnid": np.array([[1]], np.uint16),
                  "tstid": np.array([[2]], np.uint16),
                  "valid": np.array([[2]], np.uint16)})
    got = list(ds.flowers.train()())
    assert len(got) == 1
    img, label = got[0]
    assert img.shape == (3 * 224 * 224,) and img.dtype == np.float32
    assert 0.0 <= img.min() and img.max() <= 1.0
    assert label == 4                              # 1-based 5 -> 0-based 4
    assert [lab for _, lab in ds.flowers.test()()] == [101]


# --- voc2012 -----------------------------------------------------------------

def test_voc2012_parses_voc_tar(data_home):
    from PIL import Image

    d = data_home / "voc2012"
    d.mkdir()
    rng = np.random.RandomState(0)
    jpg = Image.fromarray(rng.randint(0, 256, (48, 64, 3)).astype(np.uint8))
    jpg_buf = io.BytesIO()
    jpg.save(jpg_buf, format="JPEG")
    mask = np.zeros((48, 64), np.uint8)
    mask[10:20, 10:30] = 15                        # class 15 region
    png = Image.fromarray(mask, mode="P")
    png.putpalette([0] * 768)
    png_buf = io.BytesIO()
    png.save(png_buf, format="PNG")
    with tarfile.open(d / "VOCtrainval_11-May-2012.tar", "w") as tar:
        _tar_bytes(tar, "VOCdevkit/VOC2012/ImageSets/Segmentation/"
                   "trainval.txt", b"2007_000001\n")
        _tar_bytes(tar, "VOCdevkit/VOC2012/JPEGImages/2007_000001.jpg",
                   jpg_buf.getvalue())
        _tar_bytes(tar, "VOCdevkit/VOC2012/SegmentationClass/"
                   "2007_000001.png", png_buf.getvalue())
    got = list(ds.voc2012.train()())
    assert len(got) == 1
    img, seg = got[0]
    assert img.shape == (3, 48, 64) and img.dtype == np.float32
    assert seg.shape == (48, 64) and seg.dtype == np.int32
    assert set(np.unique(seg)) == {0, 15}


# --- fallback ----------------------------------------------------------------

def test_all_modules_fall_back_to_synthetic(data_home):
    """With an empty DATA_HOME every module still serves schema-correct
    synthetic data — the zero-egress default."""
    next(ds.cifar.train10()())
    next(ds.imdb.train()())
    next(ds.imikolov.train(n=3)())
    next(ds.movielens.train()())
    next(ds.conll05.test()())
    next(ds.sentiment.train()())
    next(ds.wmt14.train(30)())
    next(ds.wmt16.train(30, 30)())
    next(ds.flowers.train()())
    next(ds.voc2012.train()())
    next(ds.mnist.train()())
    next(ds.uci_housing.train()())
    sample = next(ds.mq2007.train()())
    assert sample is not None
