"""Nested (level-2) LoD: feed/fetch roundtrip and a nested-RNN model
(reference: lod_tensor.h:55 two-level offsets, test_dyn_rnn nested configs,
RecurrentGradientMachine.h:32)."""

import numpy as np

import paddle_tpu as fluid
from paddle_tpu import executor as executor_mod
from paddle_tpu.executor import LoDTensor

RNG = np.random.RandomState(21)


def make_nested(doc_sent_lens, d):
    """doc_sent_lens: [[len(sent) for sent in doc] for doc]."""
    rows, outer, inner = [], [0], [0]
    for doc in doc_sent_lens:
        outer.append(outer[-1] + len(doc))
        for sl in doc:
            rows.append(RNG.randn(sl, d).astype(np.float32))
            inner.append(inner[-1] + sl)
    return LoDTensor(np.concatenate(rows, axis=0), [outer, inner]), rows


class TestNestedRoundtrip:
    def test_feed_fetch_identity(self):
        lod_t, rows = make_nested([[2, 3], [1], [4, 2, 1]], 3)
        with fluid.program_guard(fluid.Program(), fluid.Program()):
            x = fluid.layers.data(name="x", shape=[3], dtype="float32",
                                  lod_level=2)
            y = fluid.layers.scale(x, scale=2.0)
            exe = fluid.Executor(fluid.CPUPlace())
            with executor_mod.scope_guard(executor_mod.Scope()):
                got, = exe.run(fluid.default_main_program(),
                               feed={"x": lod_t}, fetch_list=[y],
                               return_numpy=False)
        assert isinstance(got, LoDTensor)
        assert got.lod == lod_t.lod
        np.testing.assert_allclose(got.array(),
                                   2 * np.asarray(lod_t.array()), rtol=1e-6)


class TestNestedModel:
    def test_hierarchical_pooling(self):
        """sum words within each sentence, then sum sentences within each
        doc — checked against a per-document numpy oracle."""
        structure = [[2, 3], [1], [4, 2, 1]]
        lod_t, rows = make_nested(structure, 3)
        with fluid.program_guard(fluid.Program(), fluid.Program()):
            x = fluid.layers.data(name="x", shape=[3], dtype="float32",
                                  lod_level=2)
            flat = fluid.layers.sequence_unfold(x)          # [B*S, T, 3]
            sent = fluid.layers.sequence_pool(flat, "sum")  # [B*S, 3]
            docs = fluid.layers.sequence_fold(sent, x)      # [B, S, 3]
            doc = fluid.layers.sequence_pool(docs, "sum")   # [B, 3]
            exe = fluid.Executor(fluid.CPUPlace())
            with executor_mod.scope_guard(executor_mod.Scope()):
                got, = exe.run(fluid.default_main_program(),
                               feed={"x": lod_t}, fetch_list=[doc])
        idx = 0
        want = []
        for dl in structure:
            tot = np.zeros(3, np.float32)
            for _ in dl:
                tot += rows[idx].sum(0)
                idx += 1
            want.append(tot)
        np.testing.assert_allclose(np.asarray(got), np.stack(want),
                                   rtol=1e-5)

    def test_nested_rnn_trains(self):
        """Inner GRU over words, pool, outer GRU over sentences — the
        nested-RNN pattern of test_dyn_rnn's nested config, trained a few
        steps."""
        structure = [[2, 3], [3, 1]]
        lod_t, _ = make_nested(structure, 4)
        lbl = np.array([[0], [1]], np.int64)
        with fluid.program_guard(fluid.Program(), fluid.Program()):
            x = fluid.layers.data(name="x", shape=[4], dtype="float32",
                                  lod_level=2)
            y = fluid.layers.data(name="y", shape=[1], dtype="int64")
            flat = fluid.layers.sequence_unfold(x)
            proj = fluid.layers.fc(input=flat, size=18, num_flatten_dims=2)
            inner = fluid.layers.dynamic_gru(input=proj, size=6)
            sent = fluid.layers.sequence_last_step(inner)     # [B*S, 6]
            docs = fluid.layers.sequence_fold(sent, x)        # [B, S, 6]
            proj2 = fluid.layers.fc(input=docs, size=18, num_flatten_dims=2)
            outer = fluid.layers.dynamic_gru(input=proj2, size=6)
            doc = fluid.layers.sequence_last_step(outer)      # [B, 6]
            logits = fluid.layers.fc(input=doc, size=2)
            loss = fluid.layers.mean(
                fluid.layers.softmax_with_cross_entropy(logits, y))
            fluid.optimizer.Adam(learning_rate=0.05).minimize(loss)
            exe = fluid.Executor(fluid.CPUPlace())
            with executor_mod.scope_guard(executor_mod.Scope()):
                exe.run(fluid.default_startup_program())
                first = None
                for _ in range(25):
                    v, = exe.run(fluid.default_main_program(),
                                 feed={"x": lod_t, "y": lbl},
                                 fetch_list=[loss])
                    first = first if first is not None else \
                        float(np.asarray(v).reshape(-1)[0])
                last = float(np.asarray(v).reshape(-1)[0])
        assert last < first * 0.5, (first, last)
