"""Sequence-op correctness on the padded-LoD convention: outputs compared
against per-sequence numpy references, plus gradient sanity via end-to-end
convergence through lax.scan (reference test models:
tests/unittests/test_lstm_op.py, test_gru_op.py, test_seq_pool.py)."""

import numpy as np
import pytest

import paddle_tpu as fluid
from paddle_tpu import executor as executor_mod
from paddle_tpu.executor import LoDTensor


def make_lod(rows):
    """rows: list of [len_i, D] arrays -> packed LoDTensor."""
    flat = np.concatenate(rows, axis=0)
    offs = [0]
    for r in rows:
        offs.append(offs[-1] + len(r))
    return LoDTensor(flat, [offs])


def run_prog(feed, fetch, return_numpy=True):
    exe = fluid.Executor(fluid.CPUPlace())
    exe.run(fluid.default_startup_program())
    return exe.run(fluid.default_main_program(), feed=feed,
                   fetch_list=fetch, return_numpy=return_numpy)


RNG = np.random.RandomState(7)


class TestSequencePool:
    def _run(self, pool_type):
        x = fluid.layers.data(name="x", shape=[3], dtype="float32",
                              lod_level=1)
        out = fluid.layers.sequence_pool(x, pool_type)
        rows = [RNG.randn(n, 3).astype(np.float32) for n in (2, 5, 1)]
        res, = run_prog({"x": make_lod(rows)}, [out])
        return rows, res

    def test_sum(self):
        rows, res = self._run("sum")
        want = np.stack([r.sum(0) for r in rows])
        np.testing.assert_allclose(res, want, rtol=1e-5)

    def test_average(self):
        rows, res = self._run("average")
        want = np.stack([r.mean(0) for r in rows])
        np.testing.assert_allclose(res, want, rtol=1e-5)

    def test_sqrt(self):
        rows, res = self._run("sqrt")
        want = np.stack([r.sum(0) / np.sqrt(len(r)) for r in rows])
        np.testing.assert_allclose(res, want, rtol=1e-5)

    def test_max(self):
        rows, res = self._run("max")
        want = np.stack([r.max(0) for r in rows])
        np.testing.assert_allclose(res, want, rtol=1e-5)

    def test_first_last(self):
        x = fluid.layers.data(name="x", shape=[3], dtype="float32",
                              lod_level=1)
        first = fluid.layers.sequence_first_step(x)
        last = fluid.layers.sequence_last_step(x)
        rows = [RNG.randn(n, 3).astype(np.float32) for n in (2, 5, 1)]
        f, l = run_prog({"x": make_lod(rows)}, [first, last])
        np.testing.assert_allclose(f, np.stack([r[0] for r in rows]), rtol=1e-5)
        np.testing.assert_allclose(l, np.stack([r[-1] for r in rows]), rtol=1e-5)


class TestSequenceSoftmax:
    def test_masked(self):
        x = fluid.layers.data(name="x", shape=[1], dtype="float32",
                              lod_level=1)
        out = fluid.layers.sequence_softmax(x)
        rows = [RNG.randn(n, 1).astype(np.float32) for n in (3, 6)]
        # fetched sequence vars come back packed ([sum_len, 1], reference
        # layout)
        res, = run_prog({"x": make_lod(rows)}, [out])
        off = 0
        for r in rows:
            e = np.exp(r[:, 0] - r[:, 0].max())
            want = e / e.sum()
            np.testing.assert_allclose(res[off: off + len(r), 0], want,
                                       rtol=1e-5)
            off += len(r)
        assert res.shape[0] == off


def _np_lstm(x_rows, w, b, h_dim, peep):
    """Per-sequence numpy LSTM matching ops/sequence_ops.py gate layout
    [i, f, c~, o]."""
    outs = []
    b_gate = b[: 4 * h_dim]
    for seq in x_rows:
        h = np.zeros(h_dim, np.float64)
        c = np.zeros(h_dim, np.float64)
        hs = []
        for xt in seq.astype(np.float64):
            g = xt + h @ w.astype(np.float64) + b_gate
            gi, gf, gc, go = np.split(g, 4)
            if peep:
                gi = gi + c * b[4 * h_dim: 5 * h_dim]
                gf = gf + c * b[5 * h_dim: 6 * h_dim]
            i = 1 / (1 + np.exp(-gi))
            f = 1 / (1 + np.exp(-gf))
            c = f * c + i * np.tanh(gc)
            if peep:
                go = go + c * b[6 * h_dim: 7 * h_dim]
            o = 1 / (1 + np.exp(-go))
            h = o * np.tanh(c)
            hs.append(h.copy())
        outs.append(np.stack(hs))
    return outs


class TestDynamicLSTM:
    @pytest.mark.parametrize("peep", [False, True])
    def test_vs_numpy(self, peep):
        h_dim = 4
        x = fluid.layers.data(name="x", shape=[4 * h_dim], dtype="float32",
                              lod_level=1)
        hidden, cell = fluid.layers.dynamic_lstm(
            input=x, size=4 * h_dim, use_peepholes=peep)
        rows = [RNG.randn(n, 4 * h_dim).astype(np.float32) for n in (3, 5)]

        exe = fluid.Executor(fluid.CPUPlace())
        exe.run(fluid.default_startup_program())
        scope = executor_mod.global_scope()
        # pull the startup-initialized weights for the numpy reference
        params = fluid.default_main_program().global_block().all_parameters()
        by_shape = {tuple(p.shape): np.asarray(scope.find_var(p.name))
                    for p in params}
        w = by_shape[(h_dim, 4 * h_dim)]
        bias_width = 7 * h_dim if peep else 4 * h_dim
        b = by_shape[(1, bias_width)].reshape(-1).astype(np.float64)
        # randomize bias so peepholes actually bite
        b = RNG.randn(bias_width).astype(np.float32).astype(np.float64) * 0.3
        bias_name = [p.name for p in params
                     if tuple(p.shape) == (1, bias_width)][0]
        scope.set_var(bias_name, b.astype(np.float32).reshape(1, -1))

        res, = exe.run(fluid.default_main_program(),
                       feed={"x": make_lod(rows)}, fetch_list=[hidden])
        want = _np_lstm(rows, w, b, h_dim, peep)
        off = 0
        for wseq in want:
            np.testing.assert_allclose(res[off: off + len(wseq)], wseq,
                                       rtol=1e-4, atol=1e-5)
            off += len(wseq)
        assert res.shape[0] == off


class TestDynamicGRU:
    def test_vs_numpy(self):
        h_dim = 3
        x = fluid.layers.data(name="x", shape=[3 * h_dim], dtype="float32",
                              lod_level=1)
        hidden = fluid.layers.dynamic_gru(input=x, size=h_dim)
        rows = [RNG.randn(n, 3 * h_dim).astype(np.float32) for n in (2, 4)]

        exe = fluid.Executor(fluid.CPUPlace())
        exe.run(fluid.default_startup_program())
        scope = executor_mod.global_scope()
        params = fluid.default_main_program().global_block().all_parameters()
        w = np.asarray(scope.find_var(
            [p.name for p in params if tuple(p.shape) == (h_dim, 3 * h_dim)][0]
        )).astype(np.float64)
        res, = exe.run(fluid.default_main_program(),
                       feed={"x": make_lod(rows)}, fetch_list=[hidden])
        off = 0
        for seq in rows:
            h = np.zeros(h_dim, np.float64)
            for t_, xt in enumerate(seq.astype(np.float64)):
                ur = 1 / (1 + np.exp(-(xt[: 2 * h_dim]
                                       + h @ w[:, : 2 * h_dim])))
                u, r = ur[:h_dim], ur[h_dim:]
                c = np.tanh(xt[2 * h_dim:] + (r * h) @ w[:, 2 * h_dim:])
                h = u * h + (1 - u) * c
                np.testing.assert_allclose(res[off + t_], h, rtol=1e-4,
                                           atol=1e-5)
            off += len(seq)


class TestSequenceExpandConcat:
    def test_expand(self):
        x = fluid.layers.data(name="x", shape=[2], dtype="float32")
        y = fluid.layers.data(name="y", shape=[1], dtype="float32",
                              lod_level=1)
        out = fluid.layers.sequence_expand(x=x, y=y)
        xv = RNG.randn(2, 2).astype(np.float32)
        yrows = [RNG.randn(n, 1).astype(np.float32) for n in (2, 3)]
        res, = run_prog({"x": xv, "y": make_lod(yrows)}, [out],
                        return_numpy=False)
        assert res.recursive_sequence_lengths()[0] == [2, 3]
        arr = np.asarray(res.array())
        assert np.all(arr[:2] == xv[0])
        assert np.all(arr[2:] == xv[1])

    def test_concat(self):
        a = fluid.layers.data(name="a", shape=[2], dtype="float32",
                              lod_level=1)
        b = fluid.layers.data(name="b", shape=[2], dtype="float32",
                              lod_level=1)
        out = fluid.layers.sequence_concat(input=[a, b])
        arows = [RNG.randn(n, 2).astype(np.float32) for n in (2, 1)]
        brows = [RNG.randn(n, 2).astype(np.float32) for n in (1, 3)]
        res, = run_prog({"a": make_lod(arows), "b": make_lod(brows)}, [out],
                        return_numpy=False)
        assert isinstance(res, LoDTensor)
        want_rows = [np.concatenate([x, y]) for x, y in zip(arows, brows)]
        got_lens = res.recursive_sequence_lengths()[0]
        assert got_lens == [3, 4]
        np.testing.assert_allclose(
            np.asarray(res.array()), np.concatenate(want_rows), rtol=1e-5)


class TestSequenceMisc:
    def test_slice(self):
        x = fluid.layers.data(name="x", shape=[1], dtype="float32",
                              lod_level=1)
        off = fluid.layers.data(name="off", shape=[1], dtype="int32")
        ln = fluid.layers.data(name="ln", shape=[1], dtype="int32")
        out = fluid.layers.sequence_slice(x, off, ln)
        rows = [np.arange(5, dtype=np.float32).reshape(5, 1),
                np.arange(10, 14, dtype=np.float32).reshape(4, 1)]
        res, = run_prog({"x": make_lod(rows),
                         "off": np.array([[1], [0]], np.int32),
                         "ln": np.array([[2], [3]], np.int32)}, [out],
                        return_numpy=False)
        assert res.recursive_sequence_lengths()[0] == [2, 3]
        np.testing.assert_allclose(np.asarray(res.array())[:, 0],
                                   [1, 2, 10, 11, 12])

    def test_erase(self):
        x = fluid.layers.data(name="x", shape=[1], dtype="int64",
                              lod_level=1)
        out = fluid.layers.sequence_erase(x, tokens=[2, 5])
        rows = [np.array([[1], [2], [3], [5]], np.int64),
                np.array([[2], [2], [7]], np.int64)]
        res, = run_prog({"x": make_lod(rows)}, [out], return_numpy=False)
        lens = res.recursive_sequence_lengths()[0]
        assert lens == [2, 1]
        arr = np.asarray(res.array()).reshape(-1)
        np.testing.assert_array_equal(arr, [1, 3, 7])

    def test_reshape(self):
        x = fluid.layers.data(name="x", shape=[4], dtype="float32",
                              lod_level=1)
        out = fluid.layers.sequence_reshape(x, new_dim=2)
        rows = [RNG.randn(2, 4).astype(np.float32)]
        res, = run_prog({"x": make_lod(rows)}, [out], return_numpy=False)
        assert res.recursive_sequence_lengths()[0] == [4]
        np.testing.assert_allclose(np.asarray(res.array()),
                                   rows[0].reshape(4, 2), rtol=1e-6)


class TestLSTMTrains:
    def test_convergence(self):
        """Gradients flow through the scan: tiny sequence classifier must
        converge (label = 1 iff mean of sequence values > 0)."""
        h = 16
        x = fluid.layers.data(name="x", shape=[8], dtype="float32",
                              lod_level=1)
        label = fluid.layers.data(name="label", shape=[1], dtype="int64")
        proj = fluid.layers.fc(input=x, size=4 * h, num_flatten_dims=2)
        hidden, _ = fluid.layers.dynamic_lstm(input=proj, size=4 * h,
                                              use_peepholes=False)
        pooled = fluid.layers.sequence_pool(hidden, "last")
        logits = fluid.layers.fc(input=pooled, size=2)
        loss = fluid.layers.mean(fluid.layers.softmax_with_cross_entropy(
            logits=logits, label=label))
        fluid.optimizer.Adam(learning_rate=5e-3).minimize(loss)

        exe = fluid.Executor(fluid.CPUPlace())
        exe.run(fluid.default_startup_program())
        rng = np.random.RandomState(0)
        losses = []
        for step in range(60):
            rows, labs = [], []
            for _ in range(16):
                n = rng.randint(2, 7)
                bias = rng.choice([-0.5, 0.5])
                r = (rng.randn(n, 8) * 0.3 + bias).astype(np.float32)
                rows.append(r)
                labs.append([int(r.mean() > 0)])
            l, = exe.run(fluid.default_main_program(),
                         feed={"x": make_lod(rows),
                               "label": np.asarray(labs, np.int64)},
                         fetch_list=[loss])
            losses.append(float(np.ravel(l)[0]))
        assert np.mean(losses[-10:]) < np.mean(losses[:10]) * 0.6, losses
