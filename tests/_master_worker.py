"""Spawn-context worker for test_master_queue: lives in its own module so
the spawned child imports ONLY this file (stdlib + master.py loaded by
path), never the paddle_tpu package __init__ (which imports jax). Spawn
instead of fork because forking a jax-initialized parent is the documented
deadlock hazard (VERDICT r3 weak #6)."""

import os


def _load_master_standalone():
    import importlib.util
    path = os.path.join(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))), "paddle_tpu", "parallel", "master.py")
    spec = importlib.util.spec_from_file_location("_master_standalone", path)
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


def worker(d, wid, die_after, out_q):
    """Consume the elastic stream; optionally crash (os._exit) mid-task."""
    master = _load_master_standalone()
    q = master.TaskQueue(d, timeout_s=2.0)
    seen = []
    consumed = 0
    for s in master.elastic_reader(q, chunk_fetch=lambda c: c,
                                   worker=wid)():
        seen.append(s)
        consumed += 1
        if die_after is not None and consumed >= die_after:
            os._exit(17)               # crash WITHOUT finishing the task
    out_q.put((wid, seen))
