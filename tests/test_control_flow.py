"""Control-flow lowering tests: While/lax.while_loop, tensor arrays,
conditional blocks, Switch, IfElse, StaticRNN/DynamicRNN scan lowering
(reference: tests/unittests/test_while_op.py, test_dyn_rnn.py,
test_mnist_if_else_op.py)."""

import numpy as np

import paddle_tpu as fluid
from paddle_tpu.executor import LoDTensor


def make_lod(rows):
    flat = np.concatenate(rows, axis=0)
    offs = [0]
    for r in rows:
        offs.append(offs[-1] + len(r))
    return LoDTensor(flat, [offs])


def run_prog(feed, fetch, **kw):
    exe = fluid.Executor(fluid.CPUPlace())
    exe.run(fluid.default_startup_program())
    return exe.run(fluid.default_main_program(), feed=feed,
                   fetch_list=fetch, **kw)


class TestWhile:
    def test_counter_sum(self):
        """sum integers 0..9 with a while loop."""
        i = fluid.layers.fill_constant(shape=[1], dtype="int64", value=0)
        limit = fluid.layers.fill_constant(shape=[1], dtype="int64", value=10)
        acc = fluid.layers.fill_constant(shape=[1], dtype="float32", value=0.0)
        cond = fluid.layers.less_than(x=i, y=limit)
        w = fluid.layers.While(cond=cond)
        with w.block():
            casted = fluid.layers.cast(i, "float32")
            new_acc = fluid.layers.elementwise_add(acc, casted)
            fluid.layers.assign(new_acc, acc)
            fluid.layers.increment(i, value=1, in_place=True)
            fluid.layers.less_than(x=i, y=limit, cond=cond)
        res, = run_prog({}, [acc])
        assert float(res[0]) == sum(range(10))

    def test_array_accumulate(self):
        """write i^2 into a tensor array inside the loop, read back after."""
        i = fluid.layers.fill_constant(shape=[1], dtype="int64", value=0)
        limit = fluid.layers.fill_constant(shape=[1], dtype="int64", value=5)
        seed = fluid.layers.fill_constant(shape=[1], dtype="float32", value=0.0)
        arr = fluid.layers.array_write(seed, i, capacity=8)
        cond = fluid.layers.less_than(x=i, y=limit)
        w = fluid.layers.While(cond=cond)
        with w.block():
            fi = fluid.layers.cast(i, "float32")
            sq = fluid.layers.elementwise_mul(fi, fi)
            fluid.layers.array_write(sq, i, array=arr)
            fluid.layers.increment(i, value=1, in_place=True)
            fluid.layers.less_than(x=i, y=limit, cond=cond)
        third = fluid.layers.array_read(arr, fluid.layers.fill_constant(
            shape=[1], dtype="int64", value=3))
        ln = fluid.layers.array_length(arr)
        res, n = run_prog({}, [third, ln])
        assert float(res[0]) == 9.0
        assert int(n[0]) == 5


class TestConditionalBlock:
    def test_scalar_cond(self):
        x = fluid.layers.data(name="x", shape=[4], dtype="float32")
        flag = fluid.layers.data(name="flag", shape=[1], dtype="float32",
                                 append_batch_size=False)
        zero = fluid.layers.fill_constant(shape=[1], dtype="float32", value=0.0)
        out = fluid.layers.fill_constant(shape=[1], dtype="float32", value=-1.0)
        cond = fluid.layers.less_than(x=zero, y=flag)
        cb = fluid.layers.ConditionalBlock([cond], is_scalar_condition=True)
        with cb.block():
            s = fluid.layers.reduce_sum(x)
            fluid.layers.assign(s, out)
        xs = np.ones((2, 4), np.float32)
        r_true, = run_prog({"x": xs, "flag": np.array([1.0], np.float32)},
                           [out])
        assert float(r_true[0]) == 8.0
        r_false, = run_prog({"x": xs, "flag": np.array([-1.0], np.float32)},
                            [out])
        assert float(r_false[0]) == -1.0


class TestSwitch:
    def test_lr_warmup_style(self):
        step = fluid.layers.data(name="step", shape=[1], dtype="float32",
                                 append_batch_size=False)
        lr = fluid.layers.fill_constant(shape=[1], dtype="float32", value=0.0)
        warmup = fluid.layers.fill_constant(shape=[1], dtype="float32",
                                            value=100.0)
        with fluid.layers.Switch() as switch:
            with switch.case(fluid.layers.less_than(step, warmup)):
                v = fluid.layers.fill_constant(shape=[1], dtype="float32",
                                               value=0.01)
                fluid.layers.assign(v, lr)
            with switch.default():
                v = fluid.layers.fill_constant(shape=[1], dtype="float32",
                                               value=0.1)
                fluid.layers.assign(v, lr)
        r1, = run_prog({"step": np.array([10.0], np.float32)}, [lr])
        assert abs(float(r1[0]) - 0.01) < 1e-7
        r2, = run_prog({"step": np.array([200.0], np.float32)}, [lr])
        assert abs(float(r2[0]) - 0.1) < 1e-7


class TestIfElse:
    def test_row_select(self):
        x = fluid.layers.data(name="x", shape=[1], dtype="float32")
        zero = fluid.layers.fill_constant(shape=[1], dtype="float32",
                                          value=0.0)
        cond = fluid.layers.less_than(x=x, y=zero)
        ie = fluid.layers.IfElse(cond)
        with ie.true_block():
            neg = fluid.layers.scale(ie.input(x), scale=-1.0)
            ie.output(neg)
        with ie.false_block():
            ie.output(ie.input(x))
        out = ie()
        xs = np.array([[-2.0], [3.0], [-5.0]], np.float32)
        res, = run_prog({"x": xs}, [out])
        np.testing.assert_allclose(res, np.abs(xs))


class TestStaticRNN:
    def test_cumsum_recurrence(self):
        """h_t = h_{t-1} + x_t over a fixed-length sequence."""
        x = fluid.layers.data(name="x", shape=[3], dtype="float32",
                              lod_level=1)
        rnn = fluid.layers.StaticRNN()
        with rnn.step():
            xt = rnn.step_input(x)
            h = rnn.memory(shape=[3], value=0.0)
            nh = fluid.layers.elementwise_add(h, xt)
            rnn.update_memory(h, nh)
            rnn.step_output(nh)
        out = rnn()
        rows = [np.ones((4, 3), np.float32), np.ones((2, 3), np.float32)]
        res, = run_prog({"x": make_lod(rows)}, [out])
        # packed output: seq0 rows cumsum 1..4, seq1 rows 1..2
        np.testing.assert_allclose(res[:4, 0], [1, 2, 3, 4])
        np.testing.assert_allclose(res[4:, 0], [1, 2])


class TestDynamicRNNTrains:
    def test_convergence(self):
        """DynamicRNN-built GRU-ish cell trains on the vocab-split task."""
        x = fluid.layers.data(name="x", shape=[8], dtype="float32",
                              lod_level=1)
        label = fluid.layers.data(name="label", shape=[1], dtype="int64")
        rnn = fluid.layers.DynamicRNN()
        with rnn.block():
            xt = rnn.step_input(x)
            h = rnn.memory(shape=[16], value=0.0)
            concat = fluid.layers.concat([xt, h], axis=1)
            nh = fluid.layers.fc(input=concat, size=16, act="tanh")
            rnn.update_memory(h, nh)
            rnn.step_output(nh)
        hidden = rnn()
        last = fluid.layers.sequence_last_step(hidden)
        logits = fluid.layers.fc(input=last, size=2)
        loss = fluid.layers.mean(fluid.layers.softmax_with_cross_entropy(
            logits=logits, label=label))
        fluid.optimizer.Adam(learning_rate=5e-3).minimize(loss)

        exe = fluid.Executor(fluid.CPUPlace())
        exe.run(fluid.default_startup_program())
        rng = np.random.RandomState(0)
        losses = []
        for _ in range(60):
            rows, labs = [], []
            for _ in range(16):
                n = rng.randint(2, 7)
                bias = rng.choice([-0.5, 0.5])
                rows.append((rng.randn(n, 8) * 0.3 + bias).astype(np.float32))
                labs.append([int(rows[-1].mean() > 0)])
            l, = exe.run(fluid.default_main_program(),
                         feed={"x": make_lod(rows),
                               "label": np.asarray(labs, np.int64)},
                         fetch_list=[loss])
            losses.append(float(np.ravel(l)[0]))
        assert np.mean(losses[-10:]) < np.mean(losses[:10]) * 0.6, losses


class TestWhileGrad:
    """Gradients through user While loops (reference while_op.cc:96
    WhileGradOp; VERDICT r2 missing #1). Analytic grads from append_backward
    are checked against closed-form and numeric central differences."""

    def _build(self):
        x = fluid.layers.data(name="x", shape=[4], dtype="float32",
                              append_batch_size=False, stop_gradient=False)
        w = fluid.layers.data(name="w", shape=[4], dtype="float32",
                              append_batch_size=False, stop_gradient=False)
        y = fluid.layers.scale(x, scale=1.0)
        i = fluid.layers.fill_constant(shape=[1], dtype="int64", value=0)
        limit = fluid.layers.fill_constant(shape=[1], dtype="int64", value=3)
        cond = fluid.layers.less_than(x=i, y=limit)
        wl = fluid.layers.While(cond=cond)
        with wl.block():
            ny = fluid.layers.elementwise_add(
                fluid.layers.elementwise_mul(y, w), x)
            fluid.layers.assign(ny, y)
            fluid.layers.increment(i, value=1, in_place=True)
            fluid.layers.less_than(x=i, y=limit, cond=cond)
        loss = fluid.layers.reduce_sum(y)
        return loss

    def test_analytic_matches_closed_form(self):
        loss = self._build()
        fluid.backward.append_backward(loss)
        block = fluid.default_main_program().global_block()
        exe = fluid.Executor(fluid.CPUPlace())
        exe.run(fluid.default_startup_program())
        rng = np.random.RandomState(3)
        xv = rng.randn(4).astype(np.float32)
        wv = (rng.rand(4).astype(np.float32) * 0.8 + 0.1)
        gx, gw, lv = exe.run(
            fluid.default_main_program(), feed={"x": xv, "w": wv},
            fetch_list=[block.var("x@GRAD"), block.var("w@GRAD"), loss])
        # y3 = x*(w^3+w^2+w+1); dL/dx = w^3+w^2+w+1; dL/dw = x(3w^2+2w+1)
        np.testing.assert_allclose(
            float(np.ravel(lv)[0]), float(np.sum(xv * (wv**3 + wv**2 + wv + 1))),
            rtol=1e-5)
        np.testing.assert_allclose(gx, wv**3 + wv**2 + wv + 1, rtol=1e-5)
        np.testing.assert_allclose(gw, xv * (3 * wv**2 + 2 * wv + 1),
                                   rtol=1e-5)

    def test_numeric_gradient(self):
        loss = self._build()
        fluid.backward.append_backward(loss)
        block = fluid.default_main_program().global_block()
        exe = fluid.Executor(fluid.CPUPlace())
        exe.run(fluid.default_startup_program())
        rng = np.random.RandomState(7)
        xv = rng.randn(4).astype(np.float32)
        wv = (rng.rand(4).astype(np.float32) * 0.8 + 0.1)

        def run_loss(xa, wa):
            l, = exe.run(fluid.default_main_program(),
                         feed={"x": xa, "w": wa}, fetch_list=[loss])
            return float(np.ravel(l)[0])

        gx, = exe.run(fluid.default_main_program(),
                      feed={"x": xv, "w": wv},
                      fetch_list=[block.var("x@GRAD")])
        delta = 1e-2
        num = np.zeros(4, np.float64)
        for k in range(4):
            xp, xm = xv.copy(), xv.copy()
            xp[k] += delta
            xm[k] -= delta
            num[k] = (run_loss(xp, wv) - run_loss(xm, wv)) / (2 * delta)
        np.testing.assert_allclose(gx, num, rtol=2e-3, atol=2e-3)

    def test_while_training_converges(self):
        """A While-unrolled recurrence actually trains (the r2 failure mode
        was silent zero grads through While)."""
        x = fluid.layers.data(name="x", shape=[8], dtype="float32",
                              append_batch_size=False)
        target = fluid.layers.data(name="target", shape=[8], dtype="float32",
                                   append_batch_size=False)
        w = fluid.layers.create_parameter(shape=[8], dtype="float32",
                                          name="w_loop")
        y = fluid.layers.scale(x, scale=1.0)
        i = fluid.layers.fill_constant(shape=[1], dtype="int64", value=0)
        limit = fluid.layers.fill_constant(shape=[1], dtype="int64", value=2)
        cond = fluid.layers.less_than(x=i, y=limit)
        wl = fluid.layers.While(cond=cond)
        with wl.block():
            ny = fluid.layers.elementwise_add(y, w)
            fluid.layers.assign(ny, y)
            fluid.layers.increment(i, value=1, in_place=True)
            fluid.layers.less_than(x=i, y=limit, cond=cond)
        diff = fluid.layers.elementwise_sub(y, target)
        loss = fluid.layers.reduce_mean(fluid.layers.square(diff))
        fluid.optimizer.SGD(learning_rate=0.2).minimize(loss)
        exe = fluid.Executor(fluid.CPUPlace())
        exe.run(fluid.default_startup_program())
        xv = np.zeros(8, np.float32)
        tv = np.full(8, 3.0, np.float32)
        losses = []
        for _ in range(30):
            l, = exe.run(fluid.default_main_program(),
                         feed={"x": xv, "target": tv}, fetch_list=[loss])
            losses.append(float(np.ravel(l)[0]))
        assert losses[-1] < losses[0] * 1e-2, losses


class TestConditionalBlockGrad:
    """Gradients through conditional_block (reference
    conditional_block_op.cc grad registration; VERDICT r2 missing #1)."""

    def _build(self):
        x = fluid.layers.data(name="x", shape=[4], dtype="float32",
                              append_batch_size=False, stop_gradient=False)
        p = fluid.layers.data(name="p", shape=[1], dtype="float32",
                              append_batch_size=False, stop_gradient=False)
        flag = fluid.layers.data(name="flag", shape=[1], dtype="float32",
                                 append_batch_size=False)
        zero = fluid.layers.fill_constant(shape=[1], dtype="float32",
                                          value=0.0)
        out = fluid.layers.scale(p, scale=1.0)
        cond = fluid.layers.less_than(x=zero, y=flag)
        cb = fluid.layers.ConditionalBlock([cond], is_scalar_condition=True)
        with cb.block():
            s = fluid.layers.reduce_sum(fluid.layers.scale(x, scale=2.0))
            fluid.layers.assign(s, out)
        loss = fluid.layers.reduce_sum(out)
        fluid.backward.append_backward(loss)
        return loss

    def test_grads_both_branches(self):
        loss = self._build()
        block = fluid.default_main_program().global_block()
        exe = fluid.Executor(fluid.CPUPlace())
        exe.run(fluid.default_startup_program())
        xv = np.arange(4, dtype=np.float32)
        pv = np.array([5.0], np.float32)
        # cond TRUE: out = 2*sum(x) -> dL/dx = 2, dL/dp = 0
        gx, gp = exe.run(
            fluid.default_main_program(),
            feed={"x": xv, "p": pv, "flag": np.array([1.0], np.float32)},
            fetch_list=[block.var("x@GRAD"), block.var("p@GRAD")])
        np.testing.assert_allclose(gx, np.full(4, 2.0), rtol=1e-6)
        np.testing.assert_allclose(gp, [0.0], atol=1e-7)
        # cond FALSE: out = p (passthrough) -> dL/dx = 0, dL/dp = 1
        gx, gp = exe.run(
            fluid.default_main_program(),
            feed={"x": xv, "p": pv, "flag": np.array([-1.0], np.float32)},
            fetch_list=[block.var("x@GRAD"), block.var("p@GRAD")])
        np.testing.assert_allclose(gx, np.zeros(4), atol=1e-7)
        np.testing.assert_allclose(gp, [1.0], rtol=1e-6)


class TestSilentZeroGradRaises:
    def test_no_grad_op_on_loss_path_raises(self):
        """write_to_array is NO_GRAD; putting it on the loss path must raise
        instead of silently training with zero gradient (VERDICT r2 weak #6)."""
        import pytest
        x = fluid.layers.data(name="x", shape=[4], dtype="float32",
                              append_batch_size=False)
        i = fluid.layers.fill_constant(shape=[1], dtype="int64", value=0)
        arr = fluid.layers.array_write(x, i, capacity=4)
        y = fluid.layers.array_read(arr, i)
        loss = fluid.layers.reduce_sum(y)
        with pytest.raises(RuntimeError, match="no gradient"):
            fluid.backward.append_backward(loss)

    def test_cap_overflow_poisons_grads(self):
        """A loop running past max_loop_iters must NaN-poison its grads
        (truncated replay is undefined), not silently return wrong ones."""
        w = fluid.layers.data(name="w", shape=[2], dtype="float32",
                              append_batch_size=False, stop_gradient=False)
        y = fluid.layers.scale(w, scale=0.0)
        i = fluid.layers.fill_constant(shape=[1], dtype="int64", value=0)
        limit = fluid.layers.fill_constant(shape=[1], dtype="int64",
                                           value=200)
        cond = fluid.layers.less_than(x=i, y=limit)
        wl = fluid.layers.While(cond=cond)   # default cap 128 < 200
        with wl.block():
            ny = fluid.layers.elementwise_add(y, w)
            fluid.layers.assign(ny, y)
            fluid.layers.increment(i, value=1, in_place=True)
            fluid.layers.less_than(x=i, y=limit, cond=cond)
        loss = fluid.layers.reduce_sum(y)
        fluid.backward.append_backward(loss)
        block = fluid.default_main_program().global_block()
        exe = fluid.Executor(fluid.CPUPlace())
        exe.run(fluid.default_startup_program())
        gw, lv = exe.run(fluid.default_main_program(),
                         feed={"w": np.ones(2, np.float32)},
                         fetch_list=[block.var("w@GRAD"), loss])
        assert float(np.ravel(lv)[0]) == 400.0      # forward stays exact
        assert np.all(np.isnan(gw)), gw             # grads poisoned

    def test_cap_raised_via_max_iters(self):
        """Same loop with max_iters=256 gives the true gradient."""
        w = fluid.layers.data(name="w", shape=[2], dtype="float32",
                              append_batch_size=False, stop_gradient=False)
        y = fluid.layers.scale(w, scale=0.0)
        i = fluid.layers.fill_constant(shape=[1], dtype="int64", value=0)
        limit = fluid.layers.fill_constant(shape=[1], dtype="int64",
                                           value=200)
        cond = fluid.layers.less_than(x=i, y=limit)
        wl = fluid.layers.While(cond=cond, max_iters=256)
        with wl.block():
            ny = fluid.layers.elementwise_add(y, w)
            fluid.layers.assign(ny, y)
            fluid.layers.increment(i, value=1, in_place=True)
            fluid.layers.less_than(x=i, y=limit, cond=cond)
        loss = fluid.layers.reduce_sum(y)
        fluid.backward.append_backward(loss)
        block = fluid.default_main_program().global_block()
        exe = fluid.Executor(fluid.CPUPlace())
        exe.run(fluid.default_startup_program())
        gw, = exe.run(fluid.default_main_program(),
                      feed={"w": np.ones(2, np.float32)},
                      fetch_list=[block.var("w@GRAD")])
        np.testing.assert_allclose(gw, [200.0, 200.0], rtol=1e-6)


class TestIncrementGrad:
    def test_float_increment_differentiable(self):
        """d(increment(x))/dx = 1 (was NO_GRAD, which the zero-grad check
        would now reject on the loss path)."""
        x = fluid.layers.data(name="x", shape=[2], dtype="float32",
                              append_batch_size=False, stop_gradient=False)
        y = fluid.layers.increment(fluid.layers.scale(x, scale=3.0),
                                   value=1.0, in_place=False)
        loss = fluid.layers.reduce_sum(y)
        fluid.backward.append_backward(loss)
        block = fluid.default_main_program().global_block()
        exe = fluid.Executor(fluid.CPUPlace())
        exe.run(fluid.default_startup_program())
        gx, = exe.run(fluid.default_main_program(),
                      feed={"x": np.ones(2, np.float32)},
                      fetch_list=[block.var("x@GRAD")])
        np.testing.assert_allclose(gx, [3.0, 3.0], rtol=1e-6)
