"""Mixed-precision (bf16 compute / fp32 master weights) tests — amp.py +
ops.common.mxu_cast (TPU-native replacement for the reference's fp16 path,
reference platform/float16.h:64)."""

import jax
import numpy as np

import paddle_tpu as fluid
from paddle_tpu import executor as em

RNG = np.random.default_rng(11)


def _build_convnet(main, startup, seed=99):
    main.random_seed = seed
    startup.random_seed = seed
    with fluid.program_guard(main, startup):
        img = fluid.layers.data(name="img", shape=[3, 16, 16],
                                dtype="float32")
        label = fluid.layers.data(name="label", shape=[1], dtype="int64")
        c = fluid.layers.conv2d(input=img, num_filters=8, filter_size=3,
                                act="relu")
        p = fluid.layers.pool2d(c, pool_size=2, pool_stride=2)
        logits = fluid.layers.fc(input=p, size=4)
        loss = fluid.layers.mean(
            fluid.layers.softmax_with_cross_entropy(logits, label))
        fluid.optimizer.SGD(learning_rate=0.05).minimize(
            loss, startup_program=startup)
    return loss


def _run(amp, steps=3):
    from paddle_tpu.framework import unique_name
    unique_name.switch()
    main, startup = fluid.Program(), fluid.Program()
    loss = _build_convnet(main, startup)
    if amp:
        fluid.amp.enable(main)
    exe = fluid.Executor(fluid.CPUPlace())
    scope = em.Scope()
    losses, params = [], {}
    with em.scope_guard(scope):
        exe.run(startup)
        feeds = [(RNG.standard_normal((8, 3, 16, 16)).astype(np.float32),
                  RNG.integers(0, 4, (8, 1)).astype(np.int64))
                 for _ in range(steps)]
        for xv, yv in feeds:
            lv, = exe.run(main, feed={"img": xv, "label": yv},
                          fetch_list=[loss])
            losses.append(float(np.ravel(lv)[0]))
        for n in scope.local_var_names():
            v = scope.find_var(n)
            if n.endswith(".w_0"):
                params[n] = v
    return losses, params


def test_amp_close_to_fp32_and_master_weights_stay_fp32():
    global RNG
    RNG = np.random.default_rng(11)
    loss_fp32, _ = _run(amp=False)
    RNG = np.random.default_rng(11)
    loss_amp, params = _run(amp=True)

    # bf16 operand rounding gives ~1e-2 relative agreement on a tiny net
    np.testing.assert_allclose(loss_fp32, loss_amp, rtol=0.05, atol=0.02)
    # master weights (and their updates) stay float32
    assert params and all(
        np.asarray(v).dtype == np.float32 for v in params.values())


def test_amp_decorate_tags_program():
    main, startup = fluid.Program(), fluid.Program()
    main_l = _build_convnet(main, startup)
    with fluid.program_guard(main, startup):
        pass
    opt = fluid.amp.decorate(fluid.optimizer.SGD(learning_rate=0.1))
    assert getattr(main, "_amp_dtype", None) is None
    # decorate().minimize on a fresh program tags it
    from paddle_tpu.framework import unique_name
    unique_name.switch()
    m2, s2 = fluid.Program(), fluid.Program()
    with fluid.program_guard(m2, s2):
        img = fluid.layers.data(name="img", shape=[4], dtype="float32")
        y = fluid.layers.data(name="y", shape=[1], dtype="float32")
        pred = fluid.layers.fc(input=img, size=1)
        loss = fluid.layers.mean(fluid.layers.square_error_cost(pred, y))
        opt.minimize(loss, startup_program=s2)
    assert m2._amp_dtype == "bfloat16"


def test_amp_bf16_in_compiled_hlo():
    """The compiled train step must actually contain bf16 convolutions —
    guard against the policy silently not applying."""
    from paddle_tpu.framework import unique_name
    unique_name.switch()
    main, startup = fluid.Program(), fluid.Program()
    loss = _build_convnet(main, startup)
    fluid.amp.enable(main)
    exe = fluid.Executor(fluid.CPUPlace())
    scope = em.Scope()
    with em.scope_guard(scope):
        exe.run(startup)
        xv = RNG.standard_normal((8, 3, 16, 16)).astype(np.float32)
        yv = RNG.integers(0, 4, (8, 1)).astype(np.int64)
        exe.run(main, feed={"img": xv, "label": yv}, fetch_list=[loss])
        # the training-step entry is the one with persistable state;
        # the other cache entry is the startup program
        import jax.numpy as jnp
        cb = [c for c in exe._cache.values() if c.state_names][0]
        txt = str(cb.fn.lower(
            {"img": jnp.zeros((8, 3, 16, 16), jnp.float32),
             "label": jnp.zeros((8, 1), jnp.int32)},
            {n: jnp.asarray(scope.find_var(n)) for n in cb.state_names},
            np.uint32(0)).as_text())
    import re
    assert re.search(r"convolution.*bf16", txt), "no bf16 convolutions"
