"""Mixture-of-experts FFN: numpy oracle + expert-parallel ('ep' mesh)
loss parity with single device."""

import numpy as np
import pytest

import paddle_tpu as fluid
from paddle_tpu import executor as executor_mod
from paddle_tpu.parallel import mesh as mesh_mod


def moe_oracle(x, gw, w1, w2, cap_f):
    n, d = x.shape
    e = w1.shape[0]
    cap = max(int(np.ceil(n / e * cap_f)), 1)
    logits = x @ gw
    p = np.exp(logits - logits.max(-1, keepdims=True))
    p = p / p.sum(-1, keepdims=True)
    top = p.argmax(-1)
    top_p = p.max(-1)
    out = np.zeros_like(x)
    counts = np.zeros(e, int)
    for i in range(n):
        ex = top[i]
        if counts[ex] < cap:
            h = np.maximum(x[i] @ w1[ex], 0)
            out[i] = top_p[i] * (h @ w2[ex])
            counts[ex] += 1
        else:
            out[i] = x[i]          # overflow passes through
    return out


class TestMoeOracle:
    def test_matches_numpy(self):
        rng = np.random.RandomState(5)
        n, d, e, f = 16, 8, 4, 12
        x = rng.randn(n, d).astype("float32")
        gw = rng.randn(d, e).astype("float32")
        w1 = (rng.randn(e, d, f) * 0.3).astype("float32")
        w2 = (rng.randn(e, f, d) * 0.3).astype("float32")
        want = moe_oracle(x, gw, w1, w2, 1.25)

        main, startup = fluid.Program(), fluid.Program()
        with fluid.program_guard(main, startup):
            xv = fluid.layers.data(name="x", shape=[n, d], dtype="float32",
                                   append_batch_size=False)
            blk = main.global_block()
            for nm, arr in (("gw", gw), ("w1", w1), ("w2", w2)):
                blk.create_var(name=nm, shape=list(arr.shape),
                               dtype="float32", persistable=True)
            out = blk.create_var(name="moe_out", dtype="float32")
            blk.append_op(type="moe_ffn",
                          inputs={"X": [xv], "GateW": ["gw"],
                                  "W1": ["w1"], "W2": ["w2"]},
                          outputs={"Out": [out]},
                          attrs={"capacity_factor": 1.25})
        exe = fluid.Executor(fluid.CPUPlace())
        scope = executor_mod.Scope()
        with executor_mod.scope_guard(scope):
            for nm, arr in (("gw", gw), ("w1", w1), ("w2", w2)):
                scope.set_var(nm, arr)
            got, = exe.run(main, feed={"x": x}, fetch_list=[out])
        np.testing.assert_allclose(np.asarray(got), want, rtol=2e-4,
                                   atol=1e-5)


class TestExpertParallel:
    def _train(self, mesh):
        rng = np.random.RandomState(3)
        main, startup = fluid.Program(), fluid.Program()
        with fluid.program_guard(main, startup):
            x = fluid.layers.data(name="x", shape=[16], dtype="float32")
            y = fluid.layers.data(name="y", shape=[1], dtype="float32")
            h = fluid.layers.sparse_moe(x, num_experts=8, hidden_size=32)
            pred = fluid.layers.fc(input=h, size=1,
                                   param_attr=fluid.ParamAttr(name="mo_w"))
            loss = fluid.layers.mean(fluid.layers.square_error_cost(pred, y))
            fluid.optimizer.SGDOptimizer(learning_rate=0.02).minimize(loss)
        if mesh is not None:
            main._mesh = mesh
            for p in main.global_block().all_parameters():
                if p.shape is not None and len(p.shape) == 3:
                    fluid.parallel.shard_parameter(
                        main, p.name, ("ep", None, None))
        exe = fluid.Executor(fluid.CPUPlace())
        w = rng.randn(16, 1).astype(np.float32)
        scope = executor_mod.Scope()
        losses = []
        with executor_mod.scope_guard(scope):
            exe.run(startup)
            # deterministic params so both runs start identical
            for p in main.global_block().all_parameters():
                arr = np.asarray(scope.find_var(p.name))
                det = np.linspace(-0.25, 0.25, arr.size).astype(
                    np.float32).reshape(arr.shape)
                scope.set_var(p.name, det)
            for i in range(6):
                xs = rng.randn(64, 16).astype(np.float32)
                v, = exe.run(main, feed={"x": xs, "y": xs @ w},
                             fetch_list=[loss])
                losses.append(float(np.asarray(v).reshape(-1)[0]))
        return losses

    def test_ep_mesh_matches_single(self):
        single = self._train(None)
        ep = self._train(mesh_mod.make_mesh((8,), ("ep",)))
        np.testing.assert_allclose(ep, single, rtol=2e-4)
