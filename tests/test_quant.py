"""Quantization subsystem tests (ISSUE 20): `amp.decorate(level="O3")`
routes eligible matmul/conv compute through int8 (fp8 where the backend
supports it) with per-channel dynamic scaling and f32 accumulation.

The contracts under test, in order of how expensive they are to lose
silently:

  * O3 trains: loss trajectories track O2 within the quantization noise
    budget on fc and conv smoke models (the STE backward keeps the bf16
    gradient path, so divergence means the forward dequant is wrong).
  * Bitwise determinism: the dynamic scales are pure functions of the
    operands — two identical O3 runs agree to the bit.
  * Counted fallbacks: every op the gate refuses lands in
    quant_fallback_total{op,reason} with the REAL reason, mirroring
    pallas_fallback_total — nothing falls back silently.
  * Serving parity: `ServingEngine(quantize="int8")` answers within the
    noise budget of the f32 engine on the same bucket, with weights
    prequantized once at admission.
  * The off switch: PADDLE_TPU_QUANT=0 restores O2 numerics EXACTLY
    (bitwise) — O3 with the gate off must be indistinguishable from O2,
    the property that makes the flag a safe rollback.
"""

import numpy as np
import pytest

import paddle_tpu as fluid
from paddle_tpu import executor as em, quant, telemetry


def _train_fc(level, steps=5, seed=3, width=64, hid=64):
    """Tiny fc classifier trained for a few steps; returns the raw loss
    arrays (not floats — the bitwise tests compare exact bits)."""
    fluid.unique_name.switch()
    main, startup = fluid.Program(), fluid.Program()
    main.random_seed = startup.random_seed = 7
    with fluid.program_guard(main, startup):
        x = fluid.layers.data(name="x", shape=[width], dtype="float32")
        label = fluid.layers.data(name="label", shape=[1], dtype="int64")
        h = fluid.layers.fc(input=x, size=hid, act="relu")
        logits = fluid.layers.fc(input=h, size=10, act="softmax")
        cost = fluid.layers.cross_entropy(input=logits, label=label)
        avg = fluid.layers.mean(cost)
        opt = fluid.amp.decorate(fluid.optimizer.SGD(learning_rate=0.1),
                                 level=level)
        opt.minimize(avg, startup_program=startup)
    scope = em.Scope()
    exe = fluid.Executor(fluid.TPUPlace(0))
    losses = []
    with em.scope_guard(scope):
        exe.run(startup)
        rng = np.random.default_rng(seed)
        for _ in range(steps):
            xb = rng.standard_normal((16, width)).astype(np.float32)
            lb = rng.integers(0, 10, (16, 1)).astype(np.int64)
            out, = exe.run(main, feed={"x": xb, "label": lb},
                           fetch_list=[avg])
            losses.append(np.asarray(out).copy())
    return losses


def _train_conv(level, steps=3, seed=5):
    """Conv smoke model sized for the quantized Pallas kernel: 128-lane
    channels keep pallas_conv.ineligible (and therefore the conv quant
    gate) green, so O3 actually exercises the int8 conv path."""
    fluid.unique_name.switch()
    main, startup = fluid.Program(), fluid.Program()
    main.random_seed = startup.random_seed = 7
    with fluid.program_guard(main, startup):
        img = fluid.layers.data(name="img", shape=[128, 8, 8],
                                dtype="float32")
        label = fluid.layers.data(name="label", shape=[1], dtype="int64")
        c = fluid.layers.conv2d(input=img, num_filters=128, filter_size=3,
                                padding=1, act="relu")
        p = fluid.layers.pool2d(c, pool_size=8, pool_type="avg")
        logits = fluid.layers.fc(input=p, size=4, act="softmax")
        cost = fluid.layers.cross_entropy(input=logits, label=label)
        avg = fluid.layers.mean(cost)
        opt = fluid.amp.decorate(fluid.optimizer.SGD(learning_rate=0.05),
                                 level=level)
        opt.minimize(avg, startup_program=startup)
    scope = em.Scope()
    exe = fluid.Executor(fluid.TPUPlace(0))
    losses = []
    with em.scope_guard(scope):
        exe.run(startup)
        rng = np.random.default_rng(seed)
        for _ in range(steps):
            xb = rng.standard_normal((2, 128, 8, 8)).astype(np.float32)
            lb = rng.integers(0, 4, (2, 1)).astype(np.int64)
            out, = exe.run(main, feed={"img": xb, "label": lb},
                           fetch_list=[avg])
            losses.append(np.asarray(out).copy())
    return losses


# --- training parity ---------------------------------------------------


def test_o3_tracks_o2_fc():
    telemetry.reset()
    l2 = _train_fc("O2")
    assert not telemetry.read_series("quant_kernel_total")  # O2: none
    l3 = _train_fc("O3")
    np.testing.assert_allclose([float(np.ravel(v)[0]) for v in l2],
                               [float(np.ravel(v)[0]) for v in l3],
                               rtol=0.05, atol=0.02)
    hits = telemetry.read_series("quant_kernel_total")
    assert hits.get("op=mul", 0) > 0, hits
    # both fc matmuls pass the gate (K=64): nothing fell back
    assert not telemetry.read_series("quant_fallback_total")


def test_o3_tracks_o2_conv():
    telemetry.reset()
    l2 = _train_conv("O2")
    l3 = _train_conv("O3")
    np.testing.assert_allclose([float(np.ravel(v)[0]) for v in l2],
                               [float(np.ravel(v)[0]) for v in l3],
                               rtol=0.05, atol=0.03)
    hits = telemetry.read_series("quant_kernel_total")
    assert hits.get("op=conv2d", 0) > 0, hits


def test_o3_bitwise_deterministic():
    a = _train_fc("O3")
    b = _train_fc("O3")
    assert all(np.array_equal(x, y) for x, y in zip(a, b))


# --- counted fallbacks --------------------------------------------------


def test_fallback_counters_per_reason():
    """A K=24 fc fails the shape gate; a 3-channel conv fails the Pallas
    prerequisite — each books its own reason, nothing silent."""
    telemetry.reset()
    _train_fc("O3", steps=1, width=24, hid=64)
    fb = telemetry.read_series("quant_fallback_total")
    assert fb.get("op=mul,reason=shape", 0) > 0, fb

    telemetry.reset()
    fluid.unique_name.switch()
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        img = fluid.layers.data(name="img", shape=[3, 8, 8],
                                dtype="float32")
        label = fluid.layers.data(name="label", shape=[1], dtype="int64")
        c = fluid.layers.conv2d(input=img, num_filters=8, filter_size=3,
                                padding=1, act="relu")
        p = fluid.layers.pool2d(c, pool_size=8, pool_type="avg")
        logits = fluid.layers.fc(input=p, size=4, act="softmax")
        avg = fluid.layers.mean(
            fluid.layers.cross_entropy(input=logits, label=label))
        opt = fluid.amp.decorate(fluid.optimizer.SGD(learning_rate=0.05),
                                 level="O3")
        opt.minimize(avg, startup_program=startup)
    scope = em.Scope()
    exe = fluid.Executor(fluid.TPUPlace(0))
    with em.scope_guard(scope):
        exe.run(startup)
        exe.run(main, feed={
            "img": np.zeros((2, 3, 8, 8), np.float32),
            "label": np.zeros((2, 1), np.int64)}, fetch_list=[avg])
    fb = telemetry.read_series("quant_fallback_total")
    assert fb.get("op=conv2d,reason=kernel", 0) > 0, fb


def test_gate_reasons_are_declared():
    """Every reason either gate can produce on plain inputs is in the
    declared vocabulary (the registry lint pins the source; this pins
    the runtime behavior on live avals)."""
    import jax

    f32 = np.float32
    cases = [
        quant.ineligible_matmul(jax.ShapeDtypeStruct((4, 8, 64), f32),
                                jax.ShapeDtypeStruct((64, 64), f32)),
        quant.ineligible_matmul(jax.ShapeDtypeStruct((4, 64), np.int32),
                                jax.ShapeDtypeStruct((64, 64), f32)),
        quant.ineligible_matmul(jax.ShapeDtypeStruct((4, 24), f32),
                                jax.ShapeDtypeStruct((24, 64), f32)),
        quant.ineligible_matmul(jax.ShapeDtypeStruct((4, 64), f32),
                                jax.ShapeDtypeStruct((64, 64), f32),
                                mode="int4"),
    ]
    assert cases == ["rank", "dtype", "shape", "mode"]
    assert all(c in quant.FALLBACK_REASONS for c in cases)
    assert quant.ineligible_matmul(
        jax.ShapeDtypeStruct((4, 64), f32),
        jax.ShapeDtypeStruct((64, 64), f32)) is None


def test_quant_disabled_restores_o2_exactly(monkeypatch):
    """PADDLE_TPU_QUANT=0: O3 must be BITWISE O2 — same lowerings, same
    casts, only a counted 'disabled' fallback per quantizable op. This
    is the rollback story: flipping the env var off an O3 deployment
    reproduces the O2 numerics exactly, no retraining, no drift."""
    monkeypatch.setattr(quant, "QUANT", False)
    telemetry.reset()
    l2 = _train_fc("O2")
    l3 = _train_fc("O3")
    assert all(np.array_equal(x, y) for x, y in zip(l2, l3))
    fb = telemetry.read_series("quant_fallback_total")
    assert fb.get("op=mul,reason=disabled", 0) > 0, fb
    assert not telemetry.read_series("quant_kernel_total")


# --- kernels directly ---------------------------------------------------


def test_qmatmul_error_within_model_bound():
    rng = np.random.default_rng(0)
    x = rng.standard_normal((32, 256)).astype(np.float32)
    y = rng.standard_normal((256, 64)).astype(np.float32)
    ref = x @ y
    out = np.asarray(quant.qmatmul(x, y, "int8")).astype(np.float32)
    rel = np.linalg.norm(out - ref) / np.linalg.norm(ref)
    # error_estimate("int8") ~ 0.0032; generous 10x headroom for the
    # worst-case rows the RMS model averages over
    assert rel < 10 * quant.error_estimate(256, "int8"), rel


@pytest.mark.skipif(not quant.fp8_supported(),
                    reason="backend has no fp8 dot")
def test_qmatmul_fp8_error_within_model_bound():
    rng = np.random.default_rng(1)
    x = rng.standard_normal((32, 256)).astype(np.float32)
    y = rng.standard_normal((256, 64)).astype(np.float32)
    ref = x @ y
    out = np.asarray(quant.qmatmul(x, y, "fp8")).astype(np.float32)
    rel = np.linalg.norm(out - ref) / np.linalg.norm(ref)
    assert rel < 3 * quant.error_estimate(256, "fp8"), rel


def test_qmatmul_ste_backward_is_plain_bf16():
    """The custom_vjp backward is the straight-through estimator: plain
    bf16 matmul grads, no dependence on the quantization grid (round()
    has zero gradient — without STE the whole net would stop learning)."""
    import jax
    import jax.numpy as jnp

    rng = np.random.default_rng(2)
    x = jnp.asarray(rng.standard_normal((8, 64)), jnp.bfloat16)
    y = jnp.asarray(rng.standard_normal((64, 32)), jnp.bfloat16)
    gx, gy = jax.grad(
        lambda a, b: jnp.sum(quant.qmatmul(a, b, "int8")), (0, 1))(x, y)
    g = jnp.ones((8, 32), jnp.bfloat16)
    np.testing.assert_array_equal(np.asarray(gx, np.float32),
                                  np.asarray(g @ y.T, np.float32))
    np.testing.assert_array_equal(np.asarray(gy, np.float32),
                                  np.asarray(x.T @ g, np.float32))


def test_weight_qparams_per_channel():
    rng = np.random.default_rng(3)
    w = rng.standard_normal((64, 16)).astype(np.float32)
    w[:, 3] *= 100.0  # one hot column must not wreck the others' scale
    q, scale, err = quant.weight_qparams(w, axis=1)  # per-N columns
    assert q.dtype == np.int8 and scale.shape == (1, 16)
    assert err < quant.QUANT_TOL
    back = q.astype(np.float32) * scale
    rel = np.abs(back - w).max(axis=0) / np.abs(w).max(axis=0)
    assert rel.max() < 0.01  # per-channel: every column keeps 127 steps


# --- serving ------------------------------------------------------------


def _serving_pair(quantize):
    from paddle_tpu.serving.engine import ServingEngine

    fluid.unique_name.switch()
    main, startup = fluid.Program(), fluid.Program()
    main.random_seed = startup.random_seed = 7
    with fluid.program_guard(main, startup):
        x = fluid.layers.data(name="x", shape=[64], dtype="float32")
        h = fluid.layers.fc(input=x, size=256, act="relu")
        h = fluid.layers.fc(input=h, size=64, act="relu")
        out = fluid.layers.fc(input=h, size=8, act="softmax")
    scope = em.Scope()
    exe = fluid.Executor(fluid.TPUPlace(0))
    with em.scope_guard(scope):
        exe.run(startup)
    return ServingEngine(main.clone(), ["x"], [out.name], scope=scope,
                         max_batch=16, quantize=quantize), scope


def test_serving_int8_same_bucket_parity():
    eng_f32, _ = _serving_pair(None)
    eng_q, _ = _serving_pair("int8")
    assert eng_q.quant_report is not None
    assert len(eng_q.quant_report["quantized"]) == 3  # all three fc Ws
    assert not eng_q.quant_report["skipped"]
    feed = {"x": np.random.default_rng(4)
            .standard_normal((10, 64)).astype(np.float32)}
    assert eng_f32.bucket_for(10) == eng_q.bucket_for(10)
    r32 = eng_f32.infer(feed)[0]
    rq = eng_q.infer(feed)[0]
    assert rq.shape == r32.shape
    # softmax outputs: absolute tolerance is the natural budget
    np.testing.assert_allclose(rq.astype(np.float64),
                               r32.astype(np.float64), atol=0.05)
    # prequantized weights + dynamic scales are deterministic per call
    rq2 = eng_q.infer(feed)[0]
    np.testing.assert_array_equal(rq, rq2)
    eng_f32.close()
    eng_q.close()


def test_serving_rejects_unknown_quantize():
    with pytest.raises(ValueError, match="quantize"):
        _serving_pair("int3")


def test_serving_prequantize_skips_transposed_weight():
    """prequantize stores Y in [K, N] orientation; a transpose_Y matmul
    reads Y as [N, K], so admission must skip it (counted 'shape') and
    let the trace quantize dynamically instead of baking a wrong-way
    constant."""

    class _Scope:
        def __init__(self, vals):
            self._v = vals

        def find_var(self, name):
            return self._v.get(name)

    fluid.unique_name.switch()
    main = fluid.Program()
    with fluid.program_guard(main, fluid.Program()):
        x = fluid.layers.data(name="x", shape=[8, 64],
                              append_batch_size=False)
        yv = fluid.layers.create_parameter([32, 64], "float32", name="wt")
        fluid.layers.matmul(x, yv, transpose_y=True)
    telemetry.reset()
    report = quant.prequantize(
        main, _Scope({"wt": np.ones((32, 64), np.float32)}), "int8")
    assert report["skipped"].get("wt") == "shape", report
    assert not report["quantized"]
