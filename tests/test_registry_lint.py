"""Registry-consistency lint as a tier-1 gate (ISSUE 7 satellite): a
typo in layout.AGNOSTIC_OPS/AWARE_OPS or the fusion pattern tables
doesn't raise — the pattern just never matches and the optimization
silently turns off. tools/check_registry.py pins every table entry
against ops/registry.py; this test runs it both in-process (precise
assertion message) and as the CLI (the CI entry point)."""

import importlib.util
import os
import subprocess
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _load_checker():
    spec = importlib.util.spec_from_file_location(
        "check_registry", os.path.join(REPO, "tools", "check_registry.py"))
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


def test_tables_registered():
    problems = _load_checker().check_tables()
    assert not problems, (
        "optimization tables name unregistered ops: "
        + ", ".join(f"{t}:{n}" for t, n in problems))


def test_tables_nonempty():
    """The lint is vacuous if an import regression empties a table."""
    from paddle_tpu.ops import fusion, layout

    assert layout.AWARE_OPS and layout.AGNOSTIC_OPS
    assert fusion.CONV_OPS and fusion.ACT_OPS and fusion.CHAIN_OPS
    assert fusion.OPTIMIZER_BUCKET_OPS and fusion.FUSED_OP_TYPES


def test_jit_sites_consolidated():
    """ISSUE 9 satellite: executor.py keeps exactly ONE direct jit call
    site (Executor._jit_compile), where the overlap pass's
    compiler_options are threaded into both compile paths. A second
    site — or a helper that stops threading the options — trips the
    lint before it silently ships unscheduled compiles."""
    problems = _load_checker().check_jit_sites()
    assert not problems, "; ".join(f"{w}: {m}" for w, m in problems)


def test_jit_lint_reads_real_source():
    """The lint is vacuous if it stops seeing the executor module: pin
    that the counted source actually contains the helper it checks."""
    import inspect

    from paddle_tpu import executor

    src = inspect.getsource(executor)
    assert "_jit_compile" in src and src.count("jax.jit(") == 1


def test_sparse_table_consistent():
    """ISSUE 10 satellite: SPARSE_APPLY_OPS, the optimizer lowerings'
    SelectedRows branches, executor._SPARSE_AWARE_OPS and the
    fused_sparse_ bucket types must all agree — a gap in any of them
    silently densifies the gradient instead of failing."""
    problems = _load_checker().check_sparse_table()
    assert not problems, "; ".join(f"{w}: {m}" for w, m in problems)


def test_sparse_lint_catches_missing_entry(monkeypatch):
    """Sanity: dropping an op from SPARSE_APPLY_OPS trips the converse
    check (its _apply kernel still exists but would never run)."""
    from paddle_tpu.ops import sparse_ops

    checker = _load_checker()
    monkeypatch.setattr(sparse_ops, "SPARSE_APPLY_OPS",
                        ("sgd", "momentum"))
    problems = checker.check_sparse_table()
    assert any("adam" in m for _, m in problems), problems


def test_pallas_table_consistent():
    """ISSUE 11 satellite: pallas_conv.KERNELS must agree with the op
    registry, fusion.CONV_OPS and its own FALLBACK_REASONS — an orphan
    kernel or a missing grad twin doesn't raise, the dispatch just
    silently keeps the lax path (or worse, vjp's a pallas_call)."""
    problems = _load_checker().check_pallas_table()
    assert not problems, "; ".join(f"{w}: {m}" for w, m in problems)


def test_pallas_lint_catches_missing_grad(monkeypatch):
    """Sanity: dropping conv2d_grad from KERNELS trips the shared-gate
    pairing check, and shrinking FALLBACK_REASONS trips the reason
    audit."""
    from paddle_tpu.ops import pallas_conv

    checker = _load_checker()
    orig = pallas_conv.KERNELS
    kernels = dict(orig)
    del kernels["conv2d_grad"]
    monkeypatch.setattr(pallas_conv, "KERNELS", kernels)
    problems = checker.check_pallas_table()
    assert any("conv2d_grad" in m for _, m in problems), problems

    monkeypatch.setattr(pallas_conv, "KERNELS", orig)
    monkeypatch.setattr(pallas_conv, "FALLBACK_REASONS",
                        pallas_conv.FALLBACK_REASONS - {"geometry"})
    problems = checker.check_pallas_table()
    assert any("geometry" in m for _, m in problems), problems


def test_quant_table_consistent():
    """ISSUE 20 satellite: quant.QUANT_OPS must agree with the op
    registry, the lowering sources (each table entry's lowering consults
    the quant gate — directly or one delegation deep) and
    quant.FALLBACK_REASONS. A gap doesn't raise: the op just silently
    serves at full precision under O3, or a fallback reason ships as an
    unlabelled counter series."""
    problems = _load_checker().check_quant_table()
    assert not problems, "; ".join(f"{w}: {m}" for w, m in problems)


def test_quant_table_nonempty():
    """The lint is vacuous if an import regression empties the table."""
    from paddle_tpu import quant

    assert quant.QUANT_OPS and quant.FALLBACK_REASONS
    assert {"mul", "matmul", "conv2d"} <= set(quant.QUANT_OPS)


def test_quant_lint_catches_defects(monkeypatch):
    """Sanity, all four directions: an unregistered table entry, a table
    entry whose lowering never routes through quant, a bogus entry-point
    name, and a declared-but-never-produced fallback reason."""
    from paddle_tpu import quant

    checker = _load_checker()
    orig = quant.QUANT_OPS

    monkeypatch.setattr(quant, "QUANT_OPS",
                        {**orig, "phantom_matmul": "qmatmul"})
    problems = checker.check_quant_table()
    assert any("phantom_matmul" in m and "not registered" in m
               for _, m in problems), problems

    # relu is registered but its lowering never consults the quant gate
    monkeypatch.setattr(quant, "QUANT_OPS", {**orig, "relu": "qmatmul"})
    problems = checker.check_quant_table()
    assert any("relu" in m and "never consults" in m
               for _, m in problems), problems

    monkeypatch.setattr(quant, "QUANT_OPS", {**orig, "mul": "qphantom"})
    problems = checker.check_quant_table()
    assert any("qphantom" in m for _, m in problems), problems

    monkeypatch.setattr(quant, "QUANT_OPS", orig)
    monkeypatch.setattr(quant, "FALLBACK_REASONS",
                        quant.FALLBACK_REASONS | {"phase_of_moon"})
    problems = checker.check_quant_table()
    assert any("phase_of_moon" in m and "never produced" in m
               for _, m in problems), problems


def test_quant_lint_catches_missing_table_entry(monkeypatch):
    """Converse direction: a lowering that routes through quant whose op
    type is dropped from QUANT_OPS (prequantize/preflight/roofline
    would be blind to it)."""
    from paddle_tpu import quant

    checker = _load_checker()
    trimmed = {k: v for k, v in quant.QUANT_OPS.items() if k != "matmul"}
    monkeypatch.setattr(quant, "QUANT_OPS", trimmed)
    problems = checker.check_quant_table()
    assert any("'matmul'" in m and "not" in m and "QUANT_OPS" in m
               for _, m in problems), problems


def test_infer_rules_cover_registry():
    """ISSUE 12 satellite: every registered op resolves to exactly one
    shape-rule source in analysis/infer.py (checker, registry
    infer_shape, eval-shape probe, or the dynamic allowlist). An
    uncovered op makes the shapes pass silently mark everything
    downstream unknown."""
    problems = _load_checker().check_infer_rules()
    assert not problems, "; ".join(f"{w}: {m}" for w, m in problems)


def test_infer_lint_catches_uncovered_op(monkeypatch):
    """Sanity: registering an op with no infer rule trips the coverage
    direction of the lint."""
    from paddle_tpu.ops import registry

    checker = _load_checker()
    orig = registry.registered_ops

    def with_phantom():
        return list(orig()) + ["definitely_uncovered_op"]

    monkeypatch.setattr(registry, "registered_ops", with_phantom)
    problems = checker.check_infer_rules()
    assert any("definitely_uncovered_op" in m and "no shape rule" in m
               for _, m in problems), problems


def test_infer_lint_catches_orphan_and_overlap(monkeypatch):
    """Sanity: a table entry for an unregistered op is an orphan, and
    the same op in two tables trips the precedence check."""
    from paddle_tpu.analysis import infer

    checker = _load_checker()
    monkeypatch.setattr(
        infer, "DYNAMIC_SHAPE_OPS",
        infer.DYNAMIC_SHAPE_OPS | {"definitely_not_an_op"})
    problems = checker.check_infer_rules()
    assert any("definitely_not_an_op" in m and "orphan" in m
               for _, m in problems), problems

    overlap_op = next(iter(infer.EVAL_SHAPE_OPS))
    monkeypatch.setattr(
        infer, "DYNAMIC_SHAPE_OPS",
        infer.DYNAMIC_SHAPE_OPS | {overlap_op})
    problems = checker.check_infer_rules()
    assert any(overlap_op in m and "precedence" in m
               for _, m in problems), problems


def test_emb_cache_table_consistent():
    """ISSUE 14 satellite: emb_cache.CACHE_AWARE_OPS must stay exactly
    the lookup pair plus the SPARSE_APPLY_OPS scatter family, and every
    member must be sparse-aware in the executor — drift in either
    direction corrupts silently (enable() rejecting valid optimizers,
    or a densified grad overwriting stale slot tenants)."""
    problems = _load_checker().check_emb_cache()
    assert not problems, "; ".join(f"{w}: {m}" for w, m in problems)


def test_emb_cache_lint_catches_drift(monkeypatch):
    """Sanity both ways: an extra CACHE_AWARE_OPS member with no remap
    semantics trips the converse audit; a shrunken set trips the
    missing-scatter-op direction."""
    from paddle_tpu.parallel import emb_cache

    checker = _load_checker()
    orig = emb_cache.CACHE_AWARE_OPS
    monkeypatch.setattr(emb_cache, "CACHE_AWARE_OPS",
                        orig | {"matmul"})
    problems = checker.check_emb_cache()
    assert any("'matmul'" in m and "slot-remap" in m
               for _, m in problems), problems

    monkeypatch.setattr(emb_cache, "CACHE_AWARE_OPS", orig - {"adam"})
    problems = checker.check_emb_cache()
    assert any("'adam' missing" in m for _, m in problems), problems


def test_serving_programs_clean():
    """ISSUE 13 satellite: both shipped inference programs (transformer
    logits, DLRM probabilities), after the ServingEngine's own
    strip->prune->clone, contain only registered, non-training ops. A
    grad/optimizer op leaking through prune means serving would mutate
    weights per request; an unregistered op means the first serve
    compile fails long after export."""
    problems = _load_checker().check_serving_programs()
    assert not problems, "; ".join(f"{w}: {m}" for w, m in problems)


def test_serving_lint_catches_training_op(monkeypatch):
    """Sanity: widening the training-only set so a benign forward op
    (softmax) counts as training-only must trip the lint on the DLRM
    program — proving the checker actually walks the pruned ops."""
    from paddle_tpu import serving

    checker = _load_checker()
    orig = serving.is_training_only_op
    monkeypatch.setattr(
        serving, "is_training_only_op",
        lambda op_type, op_role=None: (op_type == "softmax"
                                       or orig(op_type, op_role)))
    problems = checker.check_serving_programs()
    assert any("training-only op 'softmax'" in m for _, m in problems), (
        problems)


def test_serving_lint_catches_unregistered_op(monkeypatch):
    """Sanity: hiding a core op from the registry trips the
    no-registered-lowering direction."""
    from paddle_tpu.ops import registry

    checker = _load_checker()
    orig = registry.registered_ops

    def without_softmax():
        return [t for t in orig() if t != "softmax"]

    monkeypatch.setattr(registry, "registered_ops", without_softmax)
    problems = checker.check_serving_programs()
    assert any("'softmax'" in m and "no registered lowering" in m
               for _, m in problems), problems


def test_planner_roles_consistent():
    """ISSUE 15 satellite: the sharding planner's vocabulary stays one
    vocabulary — every classifier-table op registered, SPEC_ROLES ==
    producible ROLES in both directions, and embedding.py's table specs
    agreeing with the planner's `embedding` role (SpecLayout identity +
    shard_table writing role_spec('embedding', 2))."""
    problems = _load_checker().check_planner_roles()
    assert not problems, "; ".join(f"{w}: {m}" for w, m in problems)


def test_planner_lint_catches_drift(monkeypatch):
    """Sanity in three directions: an unregistered op in a classifier
    table, a spec-table role no rule produces, and a producible role the
    spec table doesn't know."""
    from paddle_tpu.parallel import planner

    checker = _load_checker()
    orig_transparent = planner.TRANSPARENT_OPS
    monkeypatch.setattr(
        planner, "TRANSPARENT_OPS",
        orig_transparent | {"definitely_not_an_op"})
    problems = checker.check_planner_roles()
    assert any("definitely_not_an_op" in m for _, m in problems), problems

    monkeypatch.setattr(planner, "TRANSPARENT_OPS", orig_transparent)
    monkeypatch.setattr(planner, "SPEC_ROLES",
                        planner.SPEC_ROLES | {"bogus_role"})
    problems = checker.check_planner_roles()
    assert any("bogus_role" in m and "no classifier rule" in m
               for _, m in problems), problems

    monkeypatch.setattr(planner, "SPEC_ROLES",
                        planner.SPEC_ROLES - {"bogus_role", "ffn_down"})
    problems = checker.check_planner_roles()
    assert any("ffn_down" in m and "SPEC_ROLES" in m
               for _, m in problems), problems


def test_planner_lint_catches_embedding_divergence(monkeypatch):
    """Sanity: an embedding.py table spec diverging from the planner's
    embedding role (the second-vocabulary regression) trips the lint."""
    from paddle_tpu.parallel import embedding, planner

    checker = _load_checker()
    monkeypatch.setattr(
        planner.SpecLayout, "embeddings",
        lambda self: (self.fsdp_axis, None))
    problems = checker.check_planner_roles()
    assert any("embedding" in w for w, _ in problems), problems


def test_cli_passes():
    env = dict(os.environ, PYTHONPATH=REPO, JAX_PLATFORMS="cpu")
    r = subprocess.run(
        [sys.executable, os.path.join(REPO, "tools", "check_registry.py")],
        capture_output=True, text=True, env=env, timeout=300)
    assert r.returncode == 0, r.stdout + r.stderr[-1500:]
    assert "registry lint ok" in r.stdout


def test_cli_catches_typo():
    """Sanity: the checker actually reports a bogus table entry."""
    from paddle_tpu.ops import layout

    checker = _load_checker()
    layout.AGNOSTIC_OPS.add("definitely_not_an_op")
    try:
        problems = checker.check_tables()
    finally:
        layout.AGNOSTIC_OPS.discard("definitely_not_an_op")
    assert ("layout.AGNOSTIC_OPS", "definitely_not_an_op") in problems


def test_metric_names_consistent():
    """ISSUE 16 satellite: every telemetry family created anywhere in
    paddle_tpu/ must match telemetry.METRIC_CATALOG in name, kind, and
    label set — and every cataloged non-dynamic entry must still have an
    emitter. Either direction drifting means a dashboard/reader silently
    gets None."""
    problems = _load_checker().check_metric_names()
    assert not problems, "; ".join(f"{w}: {m}" for w, m in problems)


def test_metric_lint_catches_uncataloged_emitter(monkeypatch):
    """Sanity (and proof the AST scan is non-vacuous): dropping a real
    emitter's catalog entry trips the unknown-metric direction at its
    actual call site."""
    from paddle_tpu import telemetry

    checker = _load_checker()
    monkeypatch.delitem(telemetry.METRIC_CATALOG, "serving_shed_total")
    problems = checker.check_metric_names()
    assert any("serving_shed_total" in m and "not in" in m
               for _, m in problems), problems
    assert any(w.startswith("paddle_tpu") for w, m in problems
               if "serving_shed_total" in m)


def test_metric_lint_catches_kind_and_label_drift(monkeypatch):
    from paddle_tpu import telemetry

    checker = _load_checker()
    orig = telemetry.METRIC_CATALOG["serving_shed_total"]
    monkeypatch.setitem(
        telemetry.METRIC_CATALOG, "serving_shed_total",
        dict(orig, kind="gauge"))
    problems = checker.check_metric_names()
    assert any("created as counter" in m and "cataloged as gauge" in m
               for _, m in problems), problems

    monkeypatch.setitem(
        telemetry.METRIC_CATALOG, "serving_shed_total",
        dict(orig, labels=("program", "reason", "phantom")))
    problems = checker.check_metric_names()
    assert any("serving_shed_total" in m and "label-set drift" in m
               for _, m in problems), problems


def test_metric_lint_catches_dead_catalog_entry(monkeypatch):
    from paddle_tpu import telemetry

    checker = _load_checker()
    monkeypatch.setitem(
        telemetry.METRIC_CATALOG, "phantom_metric_total",
        {"kind": "counter", "labels": (), "help": "", "dynamic": False})
    problems = checker.check_metric_names()
    assert any("phantom_metric_total" in m and "no counter" in m
               for _, m in problems), problems


def test_alert_rules_consistent():
    """ISSUE 17 satellite: every sentinel.ALERT_CATALOG rule must watch
    a cataloged telemetry metric with a compatible label set, keep its
    schema inside the sentinel's vocabularies, and the alert counter's
    own catalog entry must carry exactly {rule, severity} — either
    direction drifting means a rule that silently never fires."""
    problems = _load_checker().check_alert_rules()
    assert not problems, "; ".join(f"{w}: {m}" for w, m in problems)


def test_alert_lint_catches_bogus_metric(monkeypatch):
    """Sanity: a rule watching a metric the catalog doesn't know trips
    the can-never-fire direction at the rule's name."""
    from paddle_tpu import sentinel

    checker = _load_checker()
    monkeypatch.setitem(
        sentinel.ALERT_CATALOG, "phantom_rule",
        dict(sentinel.ALERT_CATALOG["loss_spike"],
             metric="definitely_not_a_metric"))
    problems = checker.check_alert_rules()
    assert any("phantom_rule" in w and "never fire" in m
               for w, m in problems), problems


def test_alert_lint_catches_phantom_label_filter(monkeypatch):
    """Sanity: a label filter naming a label the watched family doesn't
    have would drop every sample — the lint must see it."""
    from paddle_tpu import sentinel

    checker = _load_checker()
    monkeypatch.setitem(
        sentinel.ALERT_CATALOG, "slo_fast_burn",
        dict(sentinel.ALERT_CATALOG["slo_fast_burn"],
             label_filter={"phantom": "x"}))
    problems = checker.check_alert_rules()
    assert any("slo_fast_burn" in w and "phantom" in m
               for w, m in problems), problems


def test_alert_lint_catches_schema_drift(monkeypatch):
    """Sanity: direction/severity/reducer outside the vocabularies and
    a drifted sentinel_alerts_total label set all trip."""
    from paddle_tpu import sentinel, telemetry

    checker = _load_checker()
    monkeypatch.setitem(
        sentinel.ALERT_CATALOG, "loss_spike",
        dict(sentinel.ALERT_CATALOG["loss_spike"], direction="sideways"))
    problems = checker.check_alert_rules()
    assert any("sideways" in m for _, m in problems), problems

    monkeypatch.setitem(
        sentinel.ALERT_CATALOG, "loss_spike",
        dict(sentinel.ALERT_CATALOG["loss_spike"], direction="high"))
    orig = telemetry.METRIC_CATALOG["sentinel_alerts_total"]
    monkeypatch.setitem(
        telemetry.METRIC_CATALOG, "sentinel_alerts_total",
        dict(orig, labels=("rule",)))
    problems = checker.check_alert_rules()
    assert any("sentinel_alerts_total" in m and "severity" in m
               for _, m in problems), problems


def test_metric_lint_catches_reader_label_drift(monkeypatch):
    """A reader passing a label set the emitter doesn't write is the
    silent-None bug: read_gauge call sites must match the catalog."""
    from paddle_tpu import telemetry

    checker = _load_checker()
    orig = telemetry.METRIC_CATALOG["executor_last_step_seconds"]
    monkeypatch.setitem(
        telemetry.METRIC_CATALOG, "executor_last_step_seconds",
        dict(orig, labels=("phantom",)))
    problems = checker.check_metric_names()
    assert any("read" in m and "None" in m
               and "executor_last_step_seconds" in m
               for _, m in problems) or \
        any("executor_last_step_seconds" in m and "drift" in m
            for _, m in problems), problems


def test_thread_catalog_consistent():
    """ISSUE 18 satellite: every Thread/go creation site in paddle_tpu/
    matches a THREAD_CATALOG entry and every entry matches a site, with
    daemon/joined declarations pinned to what the source actually does."""
    problems = _load_checker().check_thread_catalog()
    assert not problems, "; ".join(f"{w}: {m}" for w, m in problems)


def test_thread_lint_catches_uncataloged_site(monkeypatch):
    """Deleting a catalog entry must surface its creation site as
    undeclared — new background threads can't ship uncensused."""
    from paddle_tpu.analysis import threads

    checker = _load_checker()
    monkeypatch.delitem(threads.THREAD_CATALOG, "serving-batcher")
    problems = checker.check_thread_catalog()
    assert any("batcher.py" in w and "not declared" in m
               for w, m in problems), problems


def test_thread_lint_catches_stale_entry(monkeypatch):
    """A catalog entry whose creation site no longer exists is stale
    documentation; the lint must flag it for removal."""
    from paddle_tpu.analysis import threads

    checker = _load_checker()
    monkeypatch.setitem(
        threads.THREAD_CATALOG, "pd-phantom-",
        dict(module="paddle_tpu/phantom.py", prefix=True, daemon=True,
             joined=False, help="never created"))
    problems = checker.check_thread_catalog()
    assert any("pd-phantom-" in w and "no matching" in m
               for w, m in problems), problems


def test_thread_lint_catches_daemon_and_join_drift(monkeypatch):
    """Flipping declared daemon-ness or claiming a join that doesn't
    exist must both trip: the catalog documents lifetime contracts."""
    from paddle_tpu.analysis import threads

    checker = _load_checker()
    monkeypatch.setitem(
        threads.THREAD_CATALOG, "serving-batcher",
        dict(threads.THREAD_CATALOG["serving-batcher"], daemon=False))
    problems = checker.check_thread_catalog()
    assert any("daemon" in m and "serving-batcher" in m
               for _, m in problems), problems

    monkeypatch.setitem(
        threads.THREAD_CATALOG, "serving-batcher",
        dict(threads.THREAD_CATALOG["serving-batcher"], daemon=True))
    monkeypatch.setitem(
        threads.THREAD_CATALOG, "pd-reader-buffered",
        dict(threads.THREAD_CATALOG["pd-reader-buffered"], joined=True))
    problems = checker.check_thread_catalog()
    assert any("joined=True" in m and "no join site" in m
               for _, m in problems), problems


def test_dynamics_rules_consistent():
    """ISSUE 19 satellite: health codes emitted by dynamics._code sites
    match HEALTH_CATALOG both ways, the dynamics_* METRIC_CATALOG slice
    has no dead entries, and the observatory's sentinel rules exist and
    watch cataloged dynamics_* families."""
    problems = _load_checker().check_dynamics_rules()
    assert not problems, "; ".join(f"{w}: {m}" for w, m in problems)


def test_dynamics_lint_catches_uncataloged_code(monkeypatch):
    """Deleting a health code from the catalog must surface its emit
    site — verdict codes are a stable vocabulary, not ad-hoc strings."""
    from paddle_tpu import dynamics

    checker = _load_checker()
    monkeypatch.delitem(dynamics.HEALTH_CATALOG, "dead-layer")
    problems = checker.check_dynamics_rules()
    assert any("dead-layer" in m and "HEALTH_CATALOG" in m
               for _, m in problems), problems


def test_dynamics_lint_catches_dead_catalog_metric(monkeypatch):
    """A dynamics_* catalog entry nothing emits is stale documentation;
    and dropping a gauge the sentinel rules watch orphans the pager."""
    from paddle_tpu import telemetry

    checker = _load_checker()
    monkeypatch.setitem(
        telemetry.METRIC_CATALOG, "dynamics_phantom_gauge",
        telemetry.METRIC_CATALOG["dynamics_grad_rms"])
    problems = checker.check_dynamics_rules()
    assert any("dynamics_phantom_gauge" in m and "never emits" in m
               for _, m in problems), problems

    monkeypatch.delitem(telemetry.METRIC_CATALOG, "dynamics_phantom_gauge")
    monkeypatch.delitem(telemetry.METRIC_CATALOG, "dynamics_dead_layers")
    problems = checker.check_dynamics_rules()
    assert any("dynamics_dead_layer" in w and "can never fire" in m
               for w, m in problems), problems
