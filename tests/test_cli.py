"""CLI (`python -m paddle_tpu`) parity with `paddle train` (reference:
TrainerMain.cpp:32-64, submit_local.sh.in)."""

import os
import subprocess
import sys

import numpy as np
import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

CONFIG = '''
import numpy as np
import paddle_tpu as fluid

def build():
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        x = fluid.layers.data(name="x", shape=[4], dtype="float32")
        y = fluid.layers.data(name="y", shape=[1], dtype="float32")
        pred = fluid.layers.fc(input=x, size=1)
        loss = fluid.layers.mean(fluid.layers.square_error_cost(pred, y))
        fluid.optimizer.SGD(learning_rate=0.1).minimize(loss)
    return {"main_program": main, "startup_program": startup,
            "feed_order": ["x", "y"], "loss": loss, "fetch": [pred]}

_rng = np.random.RandomState(0)
_w = _rng.randn(4, 1).astype(np.float32)

def train_reader():
    rng = np.random.RandomState(1)
    for _ in range(192):
        x = rng.randn(4).astype(np.float32)
        yield x, (x @ _w).astype(np.float32)
'''


def run_cli(args, cwd):
    env = dict(os.environ, PYTHONPATH=REPO, JAX_PLATFORMS="cpu")
    return subprocess.run([sys.executable, "-m", "paddle_tpu"] + args,
                          capture_output=True, text=True, cwd=cwd, env=env,
                          timeout=300)


class TestCLI:
    def test_version(self, tmp_path):
        r = run_cli(["version"], str(tmp_path))
        assert r.returncode == 0 and "paddle_tpu" in r.stdout

    def test_train_save_infer_roundtrip(self, tmp_path):
        cfg = tmp_path / "conf.py"
        cfg.write_text(CONFIG)
        save_dir = tmp_path / "model"
        ckpt_dir = tmp_path / "ckpt"
        r = run_cli(["train", f"--config={cfg}", "--epochs=3",
                     "--batch-size=32", f"--save-dir={save_dir}",
                     f"--checkpoint-dir={ckpt_dir}"], str(tmp_path))
        assert r.returncode == 0, r.stderr[-1500:]
        assert "epoch 2" in r.stdout and "saved inference model" in r.stdout
        # training should actually have learned the linear map
        losses = [float(l.split("loss=")[1].split(" ")[0].rstrip(")"))
                  for l in r.stdout.splitlines() if "loss=" in l]
        assert losses[-1] < 0.05, r.stdout

        # resume path: epoch counter continues from checkpoint
        r2 = run_cli(["train", f"--config={cfg}", "--epochs=4",
                      f"--checkpoint-dir={ckpt_dir}", "--resume"],
                     str(tmp_path))
        assert r2.returncode == 0, r2.stderr[-1500:]
        assert "resumed from checkpoint epoch 2" in r2.stdout
        assert "epoch 3" in r2.stdout and "epoch 0" not in r2.stdout

        # infer on the saved model
        xs = np.random.RandomState(3).randn(5, 4).astype(np.float32)
        np.savez(tmp_path / "batch.npz", x=xs)
        r3 = run_cli(["infer", f"--model-dir={save_dir}",
                      f"--input={tmp_path / 'batch.npz'}"], str(tmp_path))
        assert r3.returncode == 0, r3.stderr[-1500:]
        assert "shape=[5, 1]" in r3.stdout

    def test_time_job(self, tmp_path):
        cfg = tmp_path / "conf.py"
        cfg.write_text(CONFIG)
        r = run_cli(["time", f"--config={cfg}", "--steps=5"], str(tmp_path))
        assert r.returncode == 0, r.stderr[-1500:]
        assert "steps/s" in r.stdout


class TestPerfCLI:
    def test_perf_smoke(self, tmp_path):
        # env probe overrides keep the run hermetic and fast (no
        # sustained-matmul / bandwidth measurement in CI)
        env = dict(os.environ, PYTHONPATH=REPO, JAX_PLATFORMS="cpu",
                   PADDLE_TPU_SUSTAINED_TFLOPS="0.5",
                   PADDLE_TPU_HBM_GBPS="20")
        r = subprocess.run(
            [sys.executable, "-m", "paddle_tpu", "perf", "--smoke",
             "--steps=2", "--batch=8"],
            capture_output=True, text=True, cwd=str(tmp_path), env=env,
            timeout=300)
        assert r.returncode == 0, r.stdout + r.stderr[-1500:]
        out = r.stdout
        assert "(unattributed)" in out
        assert "[waterfall]" in out and "[roofline]" in out
        assert "[mfu]" in out
        rows = [ln.split() for ln in out.splitlines()
                if ln.startswith("[device] ")]
        data_rows = [t for t in rows
                     if len(t) >= 8 and t[3].endswith("%")]
        assert data_rows, out
        # every row: op, ms, frac, GFLOPs, MB, TF/s, AI, bound verdict
        assert all(t[-1] in ("compute", "memory", "unattributed")
                   for t in data_rows), data_rows
        # fractions (incl. the unattributed pool) sum to the device total
        total = sum(float(t[3].rstrip("%")) for t in data_rows)
        assert abs(total - 100.0) < 1.0, out
        # at least one attributed row carries real numbers end to end
        attributed = [t for t in data_rows
                      if t[-1] in ("compute", "memory")]
        assert attributed, out
        assert all(t[4] != "-" and t[6] != "-" for t in attributed), out

    def test_perf_smoke_json(self, tmp_path):
        import json as json_mod
        env = dict(os.environ, PYTHONPATH=REPO, JAX_PLATFORMS="cpu",
                   PADDLE_TPU_SUSTAINED_TFLOPS="0.5",
                   PADDLE_TPU_HBM_GBPS="20")
        r = subprocess.run(
            [sys.executable, "-m", "paddle_tpu", "perf", "--smoke",
             "--steps=2", "--batch=8", "--json"],
            capture_output=True, text=True, cwd=str(tmp_path), env=env,
            timeout=300)
        assert r.returncode == 0, r.stdout + r.stderr[-1500:]
        report = json_mod.loads(r.stdout)
        assert report["rows"] and report["mapped"]
        for row in report["rows"]:
            assert {"op", "ps", "frac", "flops", "bytes", "tflops",
                    "bound"} <= set(row)
        assert report["ridge_intensity"] == 25.0
        assert report.get("device_duty_cycle") is not None


class TestCheckgrad:
    def test_checkgrad_passes(self, tmp_path):
        cfg = tmp_path / "conf.py"
        cfg.write_text(CONFIG)
        r = run_cli(["checkgrad", "--config", str(cfg), "--samples", "3"],
                    str(tmp_path))
        assert r.returncode == 0, r.stdout + r.stderr
        assert "checkgrad PASSED" in r.stdout, r.stdout

    def test_checkgrad_catches_wrong_grad(self, tmp_path):
        # a config whose loss path hides a stop_gradient: analytic grad is
        # legitimately zero for w2 but numeric is not -> checkgrad FAILs
        bad = CONFIG.replace(
            'pred = fluid.layers.fc(input=x, size=1)',
            'h = fluid.layers.fc(input=x, size=4)\n'
            '        h.stop_gradient = True\n'
            '        pred = fluid.layers.fc(input=h, size=1)')
        cfg = tmp_path / "bad.py"
        cfg.write_text(bad)
        r = run_cli(["checkgrad", "--config", str(cfg), "--samples", "3"],
                    str(tmp_path))
        # either the program refuses (no grads for the frozen slice) or
        # the check flags the mismatch — silence is the only failure
        assert r.returncode != 0, r.stdout + r.stderr


class TestFpTrap:
    def test_trap_fp_raises_on_nan(self, tmp_path):
        script = tmp_path / "nan.py"
        script.write_text(
            "import numpy as np\n"
            "import paddle_tpu as fluid\n"
            "x = fluid.layers.data(name='x', shape=[2], dtype='float32')\n"
            "y = fluid.layers.log(x)   # log(-1) -> NaN\n"
            "exe = fluid.Executor(fluid.CPUPlace())\n"
            "exe.run(fluid.default_startup_program())\n"
            "out, = exe.run(feed={'x': np.array([[-1.0, 1.0]],"
            " np.float32)}, fetch_list=[y])\n"
            "print('got', out)\n")
        env = dict(os.environ, PYTHONPATH=REPO, JAX_PLATFORMS="cpu",
                   PADDLE_TPU_TRAP_FP="1")
        r = subprocess.run([sys.executable, str(script)],
                           capture_output=True, text=True, env=env,
                           timeout=300)
        assert r.returncode != 0, r.stdout      # trapped, not silent NaN
        assert "nan" in (r.stdout + r.stderr).lower()
