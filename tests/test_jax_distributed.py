"""Real multi-process jax.distributed smoke: two spawned processes, CPU
backend, localhost coordinator, multihost.initialize + a cross-process
psum + one dp-sharded train step (closes VERDICT r3 weak #4 — multi-host
was previously simulated-only). Reference analogue: the localhost pserver
test, python/paddle/fluid/tests/unittests/test_recv_op.py:26-36."""

import os
import socket
import subprocess
import sys

HERE = os.path.dirname(os.path.abspath(__file__))


def _free_port():
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


def test_two_process_initialize_psum_and_sharded_step():
    coordinator = f"127.0.0.1:{_free_port()}"
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    # children get exactly one CPU device each (2-device global mesh)
    env.pop("XLA_FLAGS", None)
    env["PYTHONPATH"] = os.pathsep.join(
        [os.path.dirname(HERE), env.get("PYTHONPATH", "")])
    procs = [subprocess.Popen(
        [sys.executable, os.path.join(HERE, "_distributed_worker.py"),
         coordinator, "2", str(pid)],
        env=env, stdout=subprocess.PIPE, stderr=subprocess.PIPE, text=True)
        for pid in (0, 1)]
    outs = []
    try:
        for p in procs:
            out, err = p.communicate(timeout=180)
            outs.append((p.returncode, out, err))
    finally:
        for p in procs:
            if p.poll() is None:
                p.kill()
    for rc, out, err in outs:
        assert rc == 0, f"worker failed rc={rc}\nstdout:{out}\nstderr:{err}"
        assert "RESULT" in out, out
