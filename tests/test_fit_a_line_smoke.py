"""M1 smoke: linear regression end-to-end (reference book ch01
tests/book/test_fit_a_line.py:25-70)."""

import numpy as np

import paddle_tpu as fluid


def test_fit_a_line_trains():
    np.random.seed(0)
    x = fluid.layers.data(name="x", shape=[13], dtype="float32")
    y = fluid.layers.data(name="y", shape=[1], dtype="float32")
    y_predict = fluid.layers.fc(input=x, size=1, act=None)
    cost = fluid.layers.square_error_cost(input=y_predict, label=y)
    avg_cost = fluid.layers.mean(cost)

    sgd = fluid.optimizer.SGD(learning_rate=0.1)
    sgd.minimize(avg_cost)

    place = fluid.CPUPlace()
    exe = fluid.Executor(place)
    exe.run(fluid.default_startup_program())

    true_w = np.random.randn(13, 1).astype(np.float32)
    losses = []
    for step in range(150):
        xs = np.random.randn(32, 13).astype(np.float32)
        ys = xs @ true_w
        loss, = exe.run(fluid.default_main_program(),
                        feed={"x": xs, "y": ys},
                        fetch_list=[avg_cost])
        losses.append(float(loss[0]))
    assert losses[-1] < losses[0] * 0.1, f"no convergence: {losses[:3]} -> {losses[-3:]}"
    assert losses[-1] < 0.1
