"""Named-axis sharding planner (ISSUE 15): role classification on
transformer and DLRM programs, planned-vs-replicated training parity
(bitwise for a ZeRO-only fc model on 1 device and ulp-tight plus
bitwise-deterministic on 8, tolerance for the transformer block on the
full data x fsdp x tp mesh), per-shard byte
accounting pinned against memory.per_shard_param_bytes, preflight
diagnostics on planted bad specs, and the overlap integration showing a
dp bucket surviving on an fsdp-sharded program (the old `sharded_param`
skip's exact gap)."""

import os
from collections import Counter

import numpy as np
import pytest

import paddle_tpu as fluid
from paddle_tpu import executor as em
from paddle_tpu import memory, telemetry
from paddle_tpu.analysis import analyze_program
from paddle_tpu.framework import unique_name
from paddle_tpu.parallel import overlap, planner
from paddle_tpu.parallel.mesh import make_mesh

NDEV = 8


def _devices(n):
    import jax
    devs = jax.devices()
    if len(devs) < n:
        pytest.skip(f"needs {n} devices, have {len(devs)} "
                    f"(set XLA_FLAGS=--xla_force_host_platform_device_count={NDEV})")
    return devs[:n]


@pytest.fixture(autouse=True)
def _fresh():
    telemetry.reset()
    overlap._PLANS.clear()
    yield


def _by_code(report, code):
    return [d for d in report.diagnostics if d.code == code]


# ---------------------------------------------------------------------------
# model builders (dims divisible by every mesh factor used below)
# ---------------------------------------------------------------------------

def _build_transformer(vocab=128, d_model=32, n_layer=2, seqlen=64):
    from paddle_tpu.models.transformer import transformer_lm

    unique_name.switch()
    main, startup = fluid.Program(), fluid.Program()
    main.random_seed = startup.random_seed = 11
    with fluid.program_guard(main, startup):
        tokens = fluid.layers.data(name="tokens", shape=[seqlen],
                                   dtype="int64")
        labels = fluid.layers.data(name="labels", shape=[seqlen],
                                   dtype="int64")
        loss = transformer_lm(tokens, labels, vocab_size=vocab,
                              d_model=d_model, n_head=4, n_layer=n_layer)
        fluid.optimizer.Momentum(learning_rate=0.01, momentum=0.9) \
            .minimize(loss, startup_program=startup)

    def make_feed(rng):
        return {"tokens": rng.integers(0, vocab, (8, seqlen), dtype=np.int64),
                "labels": rng.integers(0, vocab, (8, seqlen), dtype=np.int64)}

    return main, startup, loss, make_feed


def _build_fc():
    """Two-fc relu net with every dim divisible by 8: the planner only
    assigns fsdp (ZeRO) specs here once the mesh has no tp axis.  See
    TestParity for what that buys: exact on one device, ulp-tight
    (GSPMD may still repartition a contraction) across eight."""
    unique_name.switch()
    main, startup = fluid.Program(), fluid.Program()
    main.random_seed = startup.random_seed = 11
    with fluid.program_guard(main, startup):
        x = fluid.layers.data(name="x", shape=[16])
        y = fluid.layers.data(name="y", shape=[8])
        h = fluid.layers.fc(input=x, size=16, act="relu")
        pred = fluid.layers.fc(input=h, size=8)
        loss = fluid.layers.mean(
            fluid.layers.square_error_cost(input=pred, label=y))
        fluid.optimizer.Momentum(learning_rate=0.05, momentum=0.9) \
            .minimize(loss, startup_program=startup)

    def make_feed(rng):
        return {"x": rng.standard_normal((8, 16)).astype(np.float32),
                "y": rng.standard_normal((8, 8)).astype(np.float32)}

    return main, startup, loss, make_feed


def _build_dlrm(vocab=64, dim=8):
    unique_name.switch()
    main, startup = fluid.Program(), fluid.Program()
    main.random_seed = startup.random_seed = 11
    with fluid.program_guard(main, startup):
        ids = fluid.layers.data(name="ids", shape=[4], dtype="int64")
        label = fluid.layers.data(name="label", shape=[1])
        emb = fluid.layers.embedding(input=ids, size=[vocab, dim])
        flat = fluid.layers.reshape(emb, shape=[-1, 4 * dim])
        h = fluid.layers.fc(input=flat, size=16, act="relu")
        pred = fluid.layers.fc(input=h, size=1)
        loss = fluid.layers.mean(
            fluid.layers.square_error_cost(input=pred, label=label))
        fluid.optimizer.SGD(learning_rate=0.05) \
            .minimize(loss, startup_program=startup)
    return main, startup, loss


# ---------------------------------------------------------------------------
# role classification
# ---------------------------------------------------------------------------

class TestClassify:
    def test_transformer_roles(self):
        main, _, _, _ = _build_transformer()
        roles = planner.classify_params(main)
        counts = Counter(roles.values())
        # 2 layers x 3 qkv projections (plus none mislabeled)
        assert counts["attn_qkv"] == 6
        assert counts["attn_out"] == 2
        assert counts["ffn_up"] == 2
        assert counts["ffn_down"] == 2
        assert counts["lm_head"] == 1
        assert counts["embedding"] == 1
        assert counts["norm"] == 10       # (2 per block) x 2 + final, x2
        assert roles["pos_emb"] == "dense"
        # every fc bias classified as bias, none as dense
        assert all(roles[n] == "bias" for n in roles
                   if n.startswith("fc_") and n.endswith(".b_0"))

    def test_dlrm_roles(self):
        main, _, _ = _build_dlrm()
        roles = planner.classify_params(main)
        counts = Counter(roles.values())
        assert counts["embedding"] == 1
        # fc tower: first weight feeds relu (ffn_up), second is fed by it
        assert counts["ffn_up"] == 1
        assert counts["ffn_down"] == 1
        assert counts["bias"] == 2

    def test_every_role_spec_covered(self):
        """Vocabulary closure at the Python level too (the registry lint
        pins it in CI): producible roles == spec-table roles."""
        assert planner.ROLES == planner.SPEC_ROLES


# ---------------------------------------------------------------------------
# plan(): channels, state resolution, mesh_from_env
# ---------------------------------------------------------------------------

class TestPlan:
    def test_plan_writes_existing_channels(self):
        main, _, _ = _build_dlrm()
        mesh = make_mesh((2, 2, 2), ("dp", "fsdp", "tp"), _devices(8))
        v0 = getattr(main, "_version", 0)
        p = planner.plan(main, mesh)
        assert main._mesh is mesh
        assert getattr(main, "_version", 0) > v0
        assert main._sharding_plan is p
        # embedding role routed through embedding.shard_table: the
        # sparse-path marker is set, not just the raw spec
        emb = [n for n, pp in p.params.items() if pp.role == "embedding"]
        assert emb and all(n in main._sharded_tables for n in emb)
        # spec channel: the ffn weights carry fsdp/tp axes
        specs = main._param_shardings
        assert any("fsdp" in str(specs[n]) for n in specs)
        # feeds batch-shard over (dp, fsdp)
        assert main._feed_shardings["ids"][0] == ("dp", "fsdp")
        assert main._feed_shardings["label"][0] == ("dp", "fsdp")

    def test_accumulators_follow_param(self):
        from paddle_tpu.parallel import embedding as embedding_mod

        main, _, _, _ = _build_fc()
        mesh = make_mesh((2, 4), ("dp", "fsdp"), _devices(8))
        p = planner.plan(main, mesh)
        sharded = [n for n, pp in p.params.items() if pp.factor > 1]
        assert sharded
        for n in sharded:
            accs = embedding_mod.table_accumulators(main, n)
            assert accs, f"no accumulators found for {n}"
            for a in accs:
                assert tuple(embedding_mod.resolve_state_spec(main, a)) \
                    == tuple(p.params[n].spec)

    def test_indivisible_degrades_with_counter(self):
        """A dim no axis product divides loses axes (not a crash, not
        silent): counted under planner_fallback_total{indivisible}."""
        unique_name.switch()
        main, startup = fluid.Program(), fluid.Program()
        with fluid.program_guard(main, startup):
            x = fluid.layers.data(name="x", shape=[6])
            fluid.layers.fc(input=x, size=6, act="relu")
        mesh = make_mesh((2, 4), ("dp", "fsdp"), _devices(8))
        p = planner.plan(main, mesh)
        # 6 % 4 != 0: the fsdp axis drops off the weight's dim 0
        w = [pp for pp in p.params.values() if len(pp.shape) == 2][0]
        assert w.factor == 1 and w.notes
        series = telemetry.read_series("planner_fallback_total")
        assert any("reason=indivisible" in k and v > 0
                   for k, v in series.items()), series

    def test_mesh_from_env(self, monkeypatch):
        monkeypatch.setenv("PADDLE_TPU_MESH", "dp=2,fsdp=2,tp=2")
        _devices(8)
        mesh = planner.mesh_from_env()
        assert mesh.axis_names == ("dp", "fsdp", "tp")
        assert dict(mesh.shape) == {"dp": 2, "fsdp": 2, "tp": 2}
        monkeypatch.setenv("PADDLE_TPU_MESH", "dp=3,bogus")
        with pytest.raises(ValueError):
            planner.mesh_from_env()
        monkeypatch.delenv("PADDLE_TPU_MESH")
        mesh = planner.mesh_from_env()
        assert mesh.axis_names == ("dp",)


# ---------------------------------------------------------------------------
# training parity: planned vs replicated
# ---------------------------------------------------------------------------

def _train(build, mesh_shape, mesh_axes, ndev, planned, steps=3):
    main, startup, loss, make_feed = build()
    if planned:
        mesh = make_mesh(mesh_shape, mesh_axes, _devices(ndev))
        planner.plan(main, mesh)
    elif ndev > 1:
        # replicated baseline still runs SPMD over a plain dp mesh so
        # the global batch math matches
        main._mesh = make_mesh((ndev,), ("dp",), _devices(ndev))
    exe = fluid.Executor(fluid.CPUPlace())
    rng = np.random.default_rng(3)
    losses = []
    scope = em.Scope()
    with em.scope_guard(scope):
        exe.run(startup)
        for _ in range(steps):
            out, = exe.run(main, feed=make_feed(rng), fetch_list=[loss])
            losses.append(float(np.ravel(np.asarray(out))[0]))
        state = {}
        for p in main.global_block().all_parameters():
            v = scope.find_var(p.name)
            if v is not None:
                state[p.name] = np.asarray(v)
    return losses, state


class TestParity:
    def test_fc_bitwise_single_device(self):
        """On a 1-device mesh every spec degrades to a single shard, so
        planning must be an exact no-op: losses and full parameter state
        bitwise equal to the unplanned run."""
        lp, sp = _train(_build_fc, (1, 1), ("dp", "fsdp"), 1, planned=True)
        lr, sr = _train(_build_fc, (1, 1), ("dp", "fsdp"), 1, planned=False)
        assert lp == lr
        assert sorted(sp) == sorted(sr)
        for n in sp:
            assert np.array_equal(sp[n], sr[n]), n

    def test_fc_parity_8dev(self):
        """fsdp shards a weight dim, and every weight dim is a contraction
        dim in either forward or backward — GSPMD may partition that
        contraction, changing the float reduction order.  Planned vs
        replicated therefore agrees to ulp-level tolerance (empirically
        max |delta| ~ 6e-8 on this model), not bitwise.  Planned vs
        planned, however, must be deterministic: re-running the exact
        same plan is bitwise reproducible."""
        lp, sp = _train(_build_fc, (2, 4), ("dp", "fsdp"), NDEV, planned=True)
        lr, sr = _train(_build_fc, (2, 4), ("dp", "fsdp"), NDEV, planned=False)
        np.testing.assert_allclose(lp, lr, rtol=1e-6, atol=1e-7)
        assert sorted(sp) == sorted(sr)
        for n in sp:
            np.testing.assert_allclose(sp[n], sr[n], rtol=1e-5,
                                       atol=1e-6, err_msg=n)
        # determinism: the same plan twice is bitwise identical
        lp2, sp2 = _train(_build_fc, (2, 4), ("dp", "fsdp"), NDEV,
                          planned=True)
        assert lp == lp2
        for n in sp:
            assert np.array_equal(sp[n], sp2[n]), n

    def test_transformer_tolerance(self):
        """tp splits matmul contractions (different reduction order), so
        the full data x fsdp x tp plan holds to tolerance, not bitwise."""
        lp, sp = _train(_build_transformer, (2, 2, 2),
                        ("dp", "fsdp", "tp"), NDEV, planned=True)
        lr, sr = _train(_build_transformer, (2, 2, 2),
                        ("dp", "fsdp", "tp"), NDEV, planned=False)
        np.testing.assert_allclose(lp, lr, rtol=2e-4, atol=2e-5)
        for n in sp:
            np.testing.assert_allclose(sp[n], sr[n], rtol=2e-3,
                                       atol=2e-4, err_msg=n)


# ---------------------------------------------------------------------------
# per-shard byte accounting
# ---------------------------------------------------------------------------

class TestBytes:
    def test_plan_bytes_match_memory_accounting(self):
        main, startup, _, _ = _build_fc()
        mesh = make_mesh((2, 4), ("dp", "fsdp"), _devices(8))
        planner.plan(main, mesh)
        exe = fluid.Executor(fluid.CPUPlace())
        scope = em.Scope()
        with em.scope_guard(scope):
            exe.run(startup)
            checked = planner.validate_plan_bytes(main, scope)
            acct = memory.per_shard_param_bytes(main, scope)
        assert checked, "validation covered no parameters"
        # the by_axes breakdown partitions the per-device total
        assert sum(acct["by_axes"].values()) == acct["per_device_bytes"]
        assert "replicated" in acct["by_axes"]    # biases stay replicated
        assert any(k != "replicated" for k in acct["by_axes"])

    def test_mismatch_is_hard_failure(self):
        main, startup, _, _ = _build_fc()
        mesh = make_mesh((2, 4), ("dp", "fsdp"), _devices(8))
        p = planner.plan(main, mesh)
        # plant a wrong prediction: >1% drift must raise, not warn
        name, pp = next((n, pp) for n, pp in p.params.items()
                        if pp.factor > 1)
        p.params[name] = planner.ParamPlan(
            name=pp.name, role=pp.role, spec=pp.spec, shape=pp.shape,
            bytes=pp.bytes, per_shard_bytes=pp.per_shard_bytes * 2,
            factor=pp.factor)
        exe = fluid.Executor(fluid.CPUPlace())
        scope = em.Scope()
        with em.scope_guard(scope):
            exe.run(startup)
            with pytest.raises(AssertionError, match="diverged"):
                planner.validate_plan_bytes(main, scope)


# ---------------------------------------------------------------------------
# preflight diagnostics
# ---------------------------------------------------------------------------

class TestPreflight:
    def test_batch_indivisible(self):
        unique_name.switch()
        main = fluid.Program()
        b = main.global_block()
        b.create_var(name="x", shape=[6, 16], dtype="float32")
        main._mesh = make_mesh((2, 2), ("dp", "fsdp"), _devices(4))
        main._feed_shardings = {"x": (("dp", "fsdp"), None)}
        report = analyze_program(main, feeds=[], fetches=[])
        errs = _by_code(report, "sharding-batch-indivisible")
        assert errs and errs[0].var == "x"
        assert "multiple of 4" in (errs[0].hint or "")

    def test_axis_overcommit(self):
        unique_name.switch()
        main = fluid.Program()
        main.global_block().create_var(
            name="w", shape=[2, 32], dtype="float32", persistable=True)
        main._mesh = make_mesh((2, 2), ("fsdp", "tp"), _devices(4))
        main._param_shardings = {"w": (("fsdp", "tp"), None)}
        report = analyze_program(main, feeds=[], fetches=[])
        errs = _by_code(report, "sharding-overcommit")
        assert errs and errs[0].var == "w"
        assert "2 shard(s) would be empty" in errs[0].message

    def test_norm_sharded_warning(self):
        main, _, _, _ = _build_fc()
        mesh = make_mesh((2, 4), ("dp", "fsdp"), _devices(8))
        planner.plan(main, mesh)
        # plant a spec on a bias param — a role the planner replicates
        bias = next(p.name for p in main.global_block().all_parameters()
                    if p.name.endswith(".b_0"))
        main._param_shardings[bias] = ("fsdp",)
        report = analyze_program(
            main, feeds=["x", "y"],
            fetches=[])
        warns = _by_code(report, "norm-sharded")
        assert warns and warns[0].var == bias

    def test_planned_program_is_clean(self):
        """The planner's own output never trips its own diagnostics."""
        main, _, _, _ = _build_fc()
        mesh = make_mesh((2, 4), ("dp", "fsdp"), _devices(8))
        planner.plan(main, mesh)
        report = analyze_program(main, feeds=["x", "y"], fetches=[])
        for code in ("sharding-batch-indivisible", "sharding-overcommit",
                     "norm-sharded", "sharding-indivisible",
                     "sharding-unknown-axis"):
            assert not _by_code(report, code), code


# ---------------------------------------------------------------------------
# overlap integration
# ---------------------------------------------------------------------------

class TestOverlapIntegration:
    def test_dp_bucket_survives_fsdp_plan(self):
        """The ISSUE 9 gap, closed: on an fsdp-planned program the
        replicated grads (biases) still form >= 1 dp bucket, the fsdp
        weight grads bucket per spec group (eager reduce-scatter), and
        NOTHING falls back as sharded_param."""
        main, _, _, _ = _build_fc()
        mesh = make_mesh((2, 4), ("dp", "fsdp"), _devices(8))
        planner.plan(main, mesh)
        p = overlap.plan(main)
        assert p is not None and p.buckets
        repl = [b for b in p.buckets if b.spec == ()]
        fsdp = [b for b in p.buckets if b.spec]
        assert repl, "no dp bucket survived the fsdp plan"
        assert fsdp, "fsdp grads did not bucket"
        assert all("fsdp" in str(b.spec) for b in fsdp)
        assert all(b.site.startswith("fsdp_grad_bucket") for b in fsdp)
        series = telemetry.read_series("overlap_fallback_total")
        assert not any("reason=sharded_param" in k and v > 0
                       for k, v in series.items()), series

    def test_tp_plan_counts_tp_sharded(self):
        """On the full mesh the tensor-parallel weights skip with the
        new counted reason (their grads differ per shard by design)."""
        main, _, _, _ = _build_transformer()
        mesh = make_mesh((2, 2, 2), ("dp", "fsdp", "tp"), _devices(8))
        planner.plan(main, mesh)
        p = overlap.plan(main)
        assert p is not None
        series = telemetry.read_series("overlap_fallback_total")
        assert any("reason=tp_sharded" in k and v > 0
                   for k, v in series.items()), series
        # and the replicated group (norm/bias grads) still buckets
        assert any(b.spec == () for b in p.buckets)
