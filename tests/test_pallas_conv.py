"""Pallas conv kernel suite (ops/pallas_conv.py, ISSUE 11): parity
gates for every kernel, the eligibility gate's reason labels, the
PADDLE_TPU_PALLAS_CONV=0 escape hatch, and the CPU scan+grad-conv
warning.

Each kernel ships a parity gate against the lax.conv reference it
replaces: forward/grad-input/grad-filter vs lax.conv_general_dilated /
jax.vjp on the same bf16-rounded operands (tolerance covers only f32
accumulation-order drift, observed relative error <=3e-4), conv2d_stats
vs conv2d bitwise, bn_apply vs the normalize formula bitwise. On CPU the
kernels run under Pallas interpret mode, so this whole file is tier-1
under JAX_PLATFORMS=cpu and re-runs compiled on a real TPU unchanged.
"""

import warnings

import numpy as np
import pytest

import jax
import jax.numpy as jnp

import paddle_tpu as fluid
from paddle_tpu import executor as em
from paddle_tpu import telemetry
from paddle_tpu.framework import unique_name
from paddle_tpu.ops import pallas_conv


@pytest.fixture(autouse=True)
def _fresh_telemetry():
    telemetry.reset()
    yield
    telemetry.reset()


def _with_pallas(on, fn, *args, **kw):
    """Run fn under PALLAS_CONV=on. Callers build a FRESH program inside
    fn — the jit and plan caches key on program identity."""
    old = pallas_conv.PALLAS_CONV
    pallas_conv.PALLAS_CONV = on
    try:
        return fn(*args, **kw)
    finally:
        pallas_conv.PALLAS_CONV = old


def _series(name, label=None):
    s = telemetry.read_series(name)
    if label is None:
        return sum(s.values())
    return sum(v for k, v in s.items() if label in k)


# --- direct-kernel parity ----------------------------------------------

# (H, W, KH, KW, strides, paddings, dilations) — C fixed at one 128 lane
# tile. Covers stride, asymmetric spatial dims, 1x1, dilation+padding,
# and mixed per-dim stride/padding.
CASES = [
    (6, 6, 3, 3, (1, 1), (1, 1), (1, 1)),
    (9, 9, 3, 3, (2, 2), (1, 1), (1, 1)),
    (8, 8, 1, 1, (1, 1), (0, 0), (1, 1)),
    (10, 10, 3, 3, (1, 1), (2, 2), (2, 2)),
    (7, 9, 2, 3, (2, 1), (1, 2), (1, 1)),
]


def _operands(h, w, kh, kw, n=2, c=128, seed=0):
    rng = np.random.default_rng(seed)
    x = jnp.asarray(rng.standard_normal((n, h, w, c)), jnp.bfloat16)
    wt = jnp.asarray(rng.standard_normal((c, c, kh, kw)) * 0.1,
                     jnp.bfloat16)
    return x, wt


def _ref_fwd(x, wt, s, p, d):
    """f32 lax.conv on the same bf16-rounded operands: the kernels only
    reassociate the f32 accumulation, so this is the exact target."""
    return jax.lax.conv_general_dilated(
        x.astype(jnp.float32), wt.astype(jnp.float32),
        window_strides=s, padding=[(p[0], p[0]), (p[1], p[1])],
        rhs_dilation=d, dimension_numbers=("NHWC", "OIHW", "NHWC"))


@pytest.mark.parametrize("case", CASES)
def test_forward_parity(case):
    h, w, kh, kw, s, p, d = case
    x, wt = _operands(h, w, kh, kw)
    assert pallas_conv.supports(x, wt, s, p, d)
    y = pallas_conv.conv2d(x, wt, s, p, d, out_dtype=jnp.float32)
    ref = _ref_fwd(x, wt, s, p, d)
    np.testing.assert_allclose(np.asarray(y), np.asarray(ref),
                               rtol=2e-2, atol=2e-2)


@pytest.mark.parametrize("case", CASES)
def test_grad_parity(case):
    h, w, kh, kw, s, p, d = case
    x, wt = _operands(h, w, kh, kw, seed=1)
    ref, vjp = jax.vjp(lambda a, b: _ref_fwd(a, b, s, p, d), x, wt)
    ct = jnp.asarray(
        np.random.default_rng(2).standard_normal(ref.shape), jnp.bfloat16)
    dx_ref, dw_ref = vjp(ct.astype(jnp.float32))
    dx = pallas_conv.conv2d_grad_input(ct, wt, (h, w), s, p, d,
                                       out_dtype=jnp.float32)
    dw = pallas_conv.conv2d_grad_filter(x, ct, (kh, kw), s, p, d,
                                        out_dtype=jnp.float32)
    np.testing.assert_allclose(np.asarray(dx), np.asarray(dx_ref),
                               rtol=3e-2, atol=3e-2)
    np.testing.assert_allclose(np.asarray(dw), np.asarray(dw_ref),
                               rtol=3e-2, atol=3e-2)


def test_stats_kernel_matches_plain_conv():
    """conv2d_stats' output tile is the SAME accumulation as conv2d —
    bitwise — and its channel sums match the rounded output."""
    h, w, kh, kw, s, p, d = CASES[1]
    x, wt = _operands(h, w, kh, kw, seed=3)
    y = pallas_conv.conv2d(x, wt, s, p, d)
    ys, csum, csq = pallas_conv.conv2d_stats(x, wt, s, p, d)
    np.testing.assert_array_equal(np.asarray(ys, np.float32),
                                  np.asarray(y, np.float32))
    yf = np.asarray(ys, np.float32).reshape(-1, 128)
    np.testing.assert_allclose(np.asarray(csum), yf.sum(0),
                               rtol=1e-3, atol=1e-3)
    np.testing.assert_allclose(np.asarray(csq), (yf * yf).sum(0),
                               rtol=1e-3, atol=1e-3)


def test_bn_apply_matches_formula():
    rng = np.random.default_rng(4)
    x2 = jnp.asarray(rng.standard_normal((16, 128)), jnp.bfloat16)
    scale = jnp.asarray(rng.standard_normal(128), jnp.float32)
    bias = jnp.asarray(rng.standard_normal(128), jnp.float32)
    mean = jnp.asarray(rng.standard_normal(128), jnp.float32)
    var = jnp.asarray(rng.random(128) + 0.5, jnp.float32)
    eps = 1e-5
    ybn, yact = pallas_conv.bn_apply(x2, scale, bias, mean, var, eps,
                                     jax.nn.relu)
    ref = ((x2.astype(jnp.float32) - mean) * jax.lax.rsqrt(var + eps)
           * scale + bias).astype(jnp.bfloat16)
    np.testing.assert_array_equal(np.asarray(ybn, np.float32),
                                  np.asarray(ref, np.float32))
    np.testing.assert_array_equal(
        np.asarray(yact, np.float32),
        np.asarray(jax.nn.relu(ref), np.float32))


# --- the eligibility gate ----------------------------------------------

def test_ineligible_reasons():
    x = jnp.zeros((2, 6, 6, 128), jnp.bfloat16)
    w = jnp.zeros((128, 128, 3, 3), jnp.bfloat16)
    args = ((1, 1), (1, 1), (1, 1))
    assert pallas_conv.ineligible(x, w, *args) is None
    assert pallas_conv.supports(x, w, *args)
    assert _with_pallas(
        False, pallas_conv.ineligible, x, w, *args) == "disabled"
    assert pallas_conv.ineligible(x[0], w, *args) == "rank"
    assert pallas_conv.ineligible(x, w, *args, groups=2) == "groups"
    assert pallas_conv.ineligible(
        x.astype(jnp.float32), w, *args) == "dtype"
    assert pallas_conv.ineligible(
        x[..., :120], w[:, :120], *args) == "channels"
    # padding beyond (K-1)*d breaks the grad-input transposed-conv pads
    assert pallas_conv.ineligible(
        x, w, (1, 1), (5, 5), (1, 1)) == "geometry"
    # output collapses to zero rows
    assert pallas_conv.ineligible(
        x, w, (1, 1), (0, 0), (4, 4)) == "geometry"
    # Paddle's legal 4-element [top, bottom, left, right] paddings: the
    # gate must label the fallback, not crash unpacking — these programs
    # ran on the lax path before the suite existed
    assert pallas_conv.ineligible(
        x, w, (1, 1), [1, 1, 1, 1], (1, 1)) == "attrs"
    # padded width beyond the VMEM row budget falls back instead of
    # failing Mosaic compilation at run time
    wide = jax.ShapeDtypeStruct((1, 6, 4096, 128), jnp.bfloat16)
    assert pallas_conv.ineligible(wide, w, *args) == "geometry"
    for reason in ("disabled", "rank", "groups", "dtype", "channels",
                   "attrs", "geometry"):
        assert reason in pallas_conv.FALLBACK_REASONS


def test_zero_cotangent_returns_zeros_without_retrace():
    """Output@GRAD absent (conv output unused by the loss): the grad
    lowering must emit explicit zero grads in the forward vars' shapes
    and dtypes — delegating to the generic vjp would re-trace the
    Pallas-eligible forward into pl.pallas_call, which has no transpose
    rule, and crash at trace time."""
    from paddle_tpu.framework.desc import OpDesc
    from paddle_tpu.framework.framework import Operator
    from paddle_tpu.ops import registry

    x, wt = _operands(6, 6, 3, 3)   # Pallas-eligible bf16 128-lane shape
    op_ = Operator.__new__(Operator)
    op_.block = None
    op_.desc = OpDesc(
        type="conv2d_grad",
        inputs={"Input": ["x"], "Filter": ["w"], "Output": ["y"],
                "Output@GRAD": ["y@GRAD"]},
        outputs={"Input@GRAD": ["x@GRAD"], "Filter@GRAD": ["w@GRAD"]},
        attrs={"strides": [1, 1], "paddings": [1, 1],
               "dilations": [1, 1], "groups": 1})
    outs = registry.get("conv2d_grad").lower(
        None, op_, {"Input": [x], "Filter": [wt], "Output@GRAD": [None]})
    dx, = outs["Input@GRAD"]
    dw, = outs["Filter@GRAD"]
    assert dx.shape == x.shape and dx.dtype == x.dtype
    assert dw.shape == wt.shape and dw.dtype == wt.dtype
    assert not np.asarray(dx, np.float32).any()
    assert not np.asarray(dw, np.float32).any()
    # a zero grad is not a kernel decision: neither counter moves
    assert _series("pallas_kernel_total") == 0
    assert _series("pallas_fallback_total") == 0


def test_suppress_counters_context():
    with pallas_conv.suppress_counters():
        pallas_conv.count_hit("conv2d")
        pallas_conv.count_fallback("conv2d", "dtype")
    assert _series("pallas_kernel_total") == 0
    assert _series("pallas_fallback_total") == 0
    pallas_conv.count_fallback("conv2d", "dtype")
    assert _series("pallas_fallback_total") == 1


# --- through-program: routing, counters, escape hatch ------------------

def _train_bf16_convnet(steps=3):
    """AMP O2 conv(C=128)+bn(relu)+pool+fc+SGD: the bf16 NHWC shape the
    Pallas suite targets — forward via the fused conv->bn->act window,
    backward via the conv2d_grad dispatch."""
    unique_name.switch()
    main, startup = fluid.Program(), fluid.Program()
    main.random_seed = startup.random_seed = 11
    with fluid.program_guard(main, startup):
        img = fluid.layers.data(name="img", shape=[128, 6, 6],
                                dtype="float32")
        label = fluid.layers.data(name="label", shape=[1], dtype="int64")
        c = fluid.layers.conv2d(input=img, num_filters=128, filter_size=3,
                                padding=1, bias_attr=False)
        b = fluid.layers.batch_norm(input=c, act="relu")
        gp = fluid.layers.pool2d(input=b, global_pooling=True,
                                 pool_type="avg")
        logits = fluid.layers.fc(input=gp, size=5)
        loss = fluid.layers.mean(
            fluid.layers.softmax_with_cross_entropy(logits, label))
        fluid.optimizer.SGD(learning_rate=0.05).minimize(
            loss, startup_program=startup)
    fluid.amp.enable(main, level="O2")
    exe = fluid.Executor(fluid.CPUPlace())
    rng = np.random.default_rng(6)
    losses = []
    scope = em.Scope()
    with em.scope_guard(scope):
        exe.run(startup)
        for _ in range(steps):
            xv = rng.standard_normal((4, 128, 6, 6)).astype(np.float32)
            yv = rng.integers(0, 5, (4, 1)).astype(np.int64)
            out, = exe.run(main, feed={"img": xv, "label": yv},
                           fetch_list=[loss])
            losses.append(float(np.ravel(out)[0]))
    return losses


def test_amp_o2_training_routes_through_pallas():
    """Gate ON: the forward conv is consumed by the fused conv->bn->act
    window (hits count as fused_conv_bn_act, not conv2d) and the
    backward routes through conv2d_grad; losses match the gate-OFF lax
    path within bf16 tolerance, and OFF counts per-op `disabled`
    fallbacks with zero kernel hits."""
    l_on = _with_pallas(True, _train_bf16_convnet)
    assert _series("pallas_kernel_total", "op=fused_conv_bn_act") > 0
    assert _series("pallas_kernel_total", "op=conv2d_grad") > 0
    assert _series("pallas_fallback_total") == 0

    telemetry.reset()
    l_off = _with_pallas(False, _train_bf16_convnet)
    assert _series("pallas_kernel_total") == 0
    assert _series("pallas_fallback_total", "reason=disabled") > 0
    np.testing.assert_allclose(l_on, l_off, rtol=0, atol=5e-3)


def test_gate_off_is_deterministic_old_path():
    """PADDLE_TPU_PALLAS_CONV=0 must restore the lax path bit-for-bit:
    two OFF runs from identical seeds are bitwise equal, and every conv
    family lowering reports reason=disabled (nothing else gates)."""
    l0 = _with_pallas(False, _train_bf16_convnet)
    series = telemetry.read_series("pallas_fallback_total")
    assert series and all("reason=disabled" in k for k in series), series
    telemetry.reset()
    l1 = _with_pallas(False, _train_bf16_convnet)
    assert l0 == l1


def test_f32_conv_counts_dtype_fallback():
    """A plain f32 program never reaches the bf16-only kernels: the
    fallback counter must say WHY (reason=dtype), and the program still
    runs to completion on the lax path — unsupported is never an
    error."""
    unique_name.switch()
    main, startup = fluid.Program(), fluid.Program()
    main.random_seed = startup.random_seed = 9
    with fluid.program_guard(main, startup):
        img = fluid.layers.data(name="img", shape=[8, 6, 6],
                                dtype="float32")
        c = fluid.layers.conv2d(input=img, num_filters=8, filter_size=3,
                                padding=1, bias_attr=False)
        loss = fluid.layers.mean(c)
    exe = fluid.Executor(fluid.CPUPlace())
    scope = em.Scope()
    with em.scope_guard(scope):
        exe.run(startup)
        out, = exe.run(main, feed={
            "img": np.ones((2, 8, 6, 6), np.float32)}, fetch_list=[loss])
    assert np.isfinite(np.asarray(out)).all()
    assert _series("pallas_fallback_total", "reason=dtype") > 0
    assert _series("pallas_kernel_total") == 0


def test_grad_fallback_counts_forward_once():
    """conv2d_grad's fallback re-traces the forward lowering inside
    generic_grad_lower; that re-trace must not book a second
    pallas_fallback_total{op=conv2d} sample on top of the one the
    forward trace already counted — the coverage-trending series would
    read 2x."""
    unique_name.switch()
    main, startup = fluid.Program(), fluid.Program()
    main.random_seed = startup.random_seed = 9
    with fluid.program_guard(main, startup):
        img = fluid.layers.data(name="img", shape=[8, 6, 6],
                                dtype="float32")
        c = fluid.layers.conv2d(input=img, num_filters=8, filter_size=3,
                                padding=1, bias_attr=False)
        loss = fluid.layers.mean(c)
        fluid.optimizer.SGD(learning_rate=0.1).minimize(
            loss, startup_program=startup)
    exe = fluid.Executor(fluid.CPUPlace())
    scope = em.Scope()
    with em.scope_guard(scope):
        exe.run(startup)
        exe.run(main, feed={"img": np.ones((2, 8, 6, 6), np.float32)},
                fetch_list=[loss])
    series = telemetry.read_series("pallas_fallback_total")
    fwd = _series("pallas_fallback_total", "op=conv2d,")
    bwd = _series("pallas_fallback_total", "op=conv2d_grad,")
    assert fwd == bwd > 0, series


def test_depthwise_conv2d_grad_falls_back_by_groups():
    """groups != 1 is outside the kernel envelope: the explicit
    depthwise_conv2d_grad lowering must count reason=groups (or dtype
    for an f32 trace — whichever gate fires first stays labelled) and
    delegate to the generic vjp, matching central differences."""
    from op_test import OpTest

    rng = np.random.default_rng(12)
    x = rng.random((1, 2, 4, 4)).astype("float32")
    wt = rng.random((2, 1, 3, 3)).astype("float32")
    t = OpTest()
    t.op_type = "depthwise_conv2d"
    t.inputs = {"Input": x, "Filter": wt}
    t.attrs = {"strides": [1, 1], "paddings": [0, 0], "groups": 2}
    t.outputs = {"Output": np.zeros((1, 2, 2, 2), "float32")}
    t.check_grad(["Input", "Filter"], "Output",
                 max_relative_error=0.02)
    assert _series("pallas_fallback_total",
                   "op=depthwise_conv2d_grad") > 0
    assert _series("pallas_kernel_total") == 0


# --- run_steps: windowed parity + the CPU scan+grad-conv warning -------

def _scan_convnet():
    unique_name.switch()
    main, startup = fluid.Program(), fluid.Program()
    main.random_seed = startup.random_seed = 17
    with fluid.program_guard(main, startup):
        img = fluid.layers.data(name="img", shape=[4, 6, 6],
                                dtype="float32")
        c = fluid.layers.conv2d(input=img, num_filters=4, filter_size=3,
                                padding=1, bias_attr=False)
        loss = fluid.layers.mean(c)
        fluid.optimizer.SGD(learning_rate=0.1).minimize(
            loss, startup_program=startup)
    return main, startup, loss


def _feeds(k=2):
    rng = np.random.default_rng(8)
    return [{"img": rng.standard_normal((2, 4, 6, 6)).astype(np.float32)}
            for _ in range(k)]


def test_fused_window_parity_under_run_steps(monkeypatch):
    """run_steps (lax.scan window) over the Pallas-routed bf16 net
    matches per-step dispatch: the fused conv->bn->act + grad kernels
    trace identically inside the scan body. Tolerance only for the
    scan's f32 reduction-order drift."""
    monkeypatch.setattr(em, "_WARNED_CPU_SCAN_CONV", True)  # mute here

    def run(windowed):
        unique_name.switch()
        main, startup = fluid.Program(), fluid.Program()
        main.random_seed = startup.random_seed = 11
        with fluid.program_guard(main, startup):
            img = fluid.layers.data(name="img", shape=[128, 6, 6],
                                    dtype="float32")
            label = fluid.layers.data(name="label", shape=[1],
                                      dtype="int64")
            c = fluid.layers.conv2d(input=img, num_filters=128,
                                    filter_size=3, padding=1,
                                    bias_attr=False)
            b = fluid.layers.batch_norm(input=c, act="relu")
            gp = fluid.layers.pool2d(input=b, global_pooling=True,
                                     pool_type="avg")
            logits = fluid.layers.fc(input=gp, size=5)
            loss = fluid.layers.mean(
                fluid.layers.softmax_with_cross_entropy(logits, label))
            fluid.optimizer.SGD(learning_rate=0.05).minimize(
                loss, startup_program=startup)
        fluid.amp.enable(main, level="O2")
        exe = fluid.Executor(fluid.CPUPlace())
        rng = np.random.default_rng(6)
        feeds = [{"img": rng.standard_normal((4, 128, 6, 6)).astype(
                      np.float32),
                  "label": rng.integers(0, 5, (4, 1)).astype(np.int64)}
                 for _ in range(2)]
        scope = em.Scope()
        with em.scope_guard(scope):
            exe.run(startup)
            if windowed:
                out, = exe.run_steps(main, feed_window=feeds,
                                     fetch_list=[loss],
                                     fetch_mode="stack")
                return [float(v) for v in np.ravel(out)]
            return [float(np.ravel(exe.run(main, feed=f,
                                           fetch_list=[loss])[0])[0])
                    for f in feeds]

    seq = run(False)
    win = run(True)
    np.testing.assert_allclose(seq, win, rtol=0, atol=5e-3)
    assert _series("pallas_kernel_total", "op=fused_conv_bn_act") > 0


def test_cpu_scan_grad_conv_warns_once(monkeypatch):
    """The PR 5 caveat surfaced at the API: a multi-step run_steps window
    with a conv backward on XLA:CPU warns (once per process) about the
    ~60x scan slowdown; steps=1 and conv-less programs stay silent."""
    monkeypatch.setattr(em, "_WARNED_CPU_SCAN_CONV", False)
    main, startup, loss = _scan_convnet()
    exe = fluid.Executor(fluid.CPUPlace())
    scope = em.Scope()
    with em.scope_guard(scope):
        exe.run(startup)
        with pytest.warns(RuntimeWarning, match="conv backward"):
            exe.run_steps(main, feed_window=_feeds(), fetch_list=[loss])
        with warnings.catch_warnings(record=True) as caught:
            warnings.simplefilter("always")
            exe.run_steps(main, feed_window=_feeds(), fetch_list=[loss])
        assert not [w for w in caught
                    if issubclass(w.category, RuntimeWarning)
                    and "conv backward" in str(w.message)], caught


def test_cpu_scan_warning_skips_single_step(monkeypatch):
    monkeypatch.setattr(em, "_WARNED_CPU_SCAN_CONV", False)
    em._maybe_warn_cpu_scan_conv(None, _scan_convnet()[0], steps=1)
    assert em._WARNED_CPU_SCAN_CONV is False
