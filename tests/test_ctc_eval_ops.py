"""CTC family + eval op tests (reference: test_warpctc_op.py,
test_ctc_align.py, test_edit_distance_op.py, test_chunk_eval_op.py,
test_precision_recall_op.py, test_positive_negative_pair_op.py).

LoD inputs follow the padded+@SEQLEN convention, fed as packed LoDTensors.
The CTC loss is checked against a brute-force path-enumeration oracle."""

import itertools

import numpy as np
import pytest

import paddle_tpu as fluid
from paddle_tpu import executor as executor_mod
from paddle_tpu.executor import LoDTensor

RNG = np.random.RandomState(3)


def make_lod(rows):
    flat = np.concatenate(rows, axis=0)
    offs = [0]
    for r in rows:
        offs.append(offs[-1] + len(r))
    return LoDTensor(flat, [offs])


def run_op(op_type, inputs, attrs, fetch_slots, lod_inputs=(), grad_of=None):
    """Build a one-op program; inputs mapping slot -> (name, array|LoDTensor)."""
    main = fluid.Program()
    startup = fluid.Program()
    feed = {}
    with fluid.program_guard(main, startup):
        op_inputs = {}
        for slot, (name, val) in inputs.items():
            arr = val.array() if isinstance(val, LoDTensor) else np.asarray(val)
            v = main.global_block().create_var(
                name=name, shape=list(arr.shape), dtype=arr.dtype.name,
                lod_level=1 if isinstance(val, LoDTensor) else 0,
                stop_gradient=False)
            feed[name] = val
            op_inputs[slot] = [name]
        op_outputs = {}
        out_names = {}
        for slot in fetch_slots:
            name = f"{op_type}_{slot.lower().replace('-', '_')}_out"
            main.global_block().create_var(name=name, dtype="float32")
            op_outputs[slot] = [name]
            out_names[slot] = name
        main.global_block().append_op(
            type=op_type, inputs=op_inputs, outputs=op_outputs, attrs=attrs)
        loss = None
        if grad_of is not None:
            loss = fluid.layers.mean(out_names_var(main, out_names[grad_of[1]]))
            fluid.append_backward(loss)
    exe = fluid.Executor(fluid.CPUPlace())
    scope = executor_mod.Scope()
    with executor_mod.scope_guard(scope):
        fetch = [out_names[s] for s in fetch_slots]
        if grad_of is not None:
            fetch.append(fluid.framework.grad_var_name(grad_of[0]))
        res = exe.run(main, feed=feed, fetch_list=fetch, return_numpy=False)
    return dict(zip(fetch_slots + ([f"{grad_of[0]}@GRAD"] if grad_of else []),
                    res))


def out_names_var(main, name):
    return main.global_block().var(name)


# --- CTC loss oracle ---------------------------------------------------------

def ctc_loss_brute(probs, label, blank):
    """Enumerate all length-T paths, sum probabilities of those collapsing to
    the label (exponential — only for tiny T/C)."""
    t, c = probs.shape
    total = 0.0
    for path in itertools.product(range(c), repeat=t):
        collapsed = []
        prev = -1
        for p in path:
            if p != blank and p != prev:
                collapsed.append(p)
            prev = p
        if collapsed == list(label):
            pr = 1.0
            for i, p in enumerate(path):
                pr *= probs[i, p]
            total += pr
    return -np.log(max(total, 1e-300))


class TestWarpCTC:
    def test_vs_bruteforce(self):
        t, c = 4, 3
        logits = RNG.randn(2, t, c).astype(np.float32)
        labels = [np.array([[1], [2]], np.int64),
                  np.array([[2]], np.int64)]
        rows_logits = [logits[0], logits[1, :3]]   # lengths 4, 3
        with fluid.program_guard(fluid.Program(), fluid.Program()):
            x = fluid.layers.data(name="logits", shape=[c], dtype="float32",
                                  lod_level=1)
            lbl = fluid.layers.data(name="label", shape=[1], dtype="int64",
                                    lod_level=1)
            loss = fluid.layers.warpctc(input=x, label=lbl, blank=0)
            exe = fluid.Executor(fluid.CPUPlace())
            scope = executor_mod.Scope()
            with executor_mod.scope_guard(scope):
                res, = exe.run(fluid.default_main_program(),
                               feed={"logits": make_lod(rows_logits),
                                     "label": make_lod(labels)},
                               fetch_list=[loss])
        def softmax(z):
            e = np.exp(z - z.max(-1, keepdims=True))
            return e / e.sum(-1, keepdims=True)
        want0 = ctc_loss_brute(softmax(rows_logits[0]), [1, 2], 0)
        want1 = ctc_loss_brute(softmax(rows_logits[1]), [2], 0)
        got = np.asarray(res).reshape(-1)
        np.testing.assert_allclose(got, [want0, want1], rtol=1e-4)

    def test_grad_descends(self):
        """Training on the CTC loss should reduce it (analytic grad sanity)."""
        t, c, h = 5, 4, 6
        with fluid.program_guard(fluid.Program(), fluid.Program()):
            x = fluid.layers.data(name="x", shape=[h], dtype="float32",
                                  lod_level=1)
            lbl = fluid.layers.data(name="label", shape=[1], dtype="int64",
                                    lod_level=1)
            proj = fluid.layers.fc(input=x, size=c, num_flatten_dims=2)
            loss = fluid.layers.warpctc(input=proj, label=lbl, blank=0)
            avg = fluid.layers.mean(loss)
            fluid.optimizer.SGDOptimizer(learning_rate=0.5).minimize(avg)
            exe = fluid.Executor(fluid.CPUPlace())
            scope = executor_mod.Scope()
            with executor_mod.scope_guard(scope):
                exe.run(fluid.default_startup_program())
                feed = {"x": make_lod([RNG.randn(t, h).astype(np.float32)]),
                        "label": make_lod([np.array([[1], [2]], np.int64)])}
                first = None
                for i in range(12):
                    v, = exe.run(fluid.default_main_program(), feed=feed,
                                 fetch_list=[avg])
                    first = first if first is not None else float(np.asarray(v).reshape(-1)[0])
                assert float(np.asarray(v).reshape(-1)[0]) < first * 0.8


class TestCTCAlign:
    def test_merge_and_blank(self):
        rows = [np.array([[0], [1], [1], [0], [2], [2]], np.int32),
                np.array([[3], [0], [3]], np.int32)]
        with fluid.program_guard(fluid.Program(), fluid.Program()):
            x = fluid.layers.data(name="x", shape=[1], dtype="int32",
                                  lod_level=1)
            out = fluid.layers.ctc_align(x, blank=0)
            exe = fluid.Executor(fluid.CPUPlace())
            scope = executor_mod.Scope()
            with executor_mod.scope_guard(scope):
                res, = exe.run(fluid.default_main_program(),
                               feed={"x": make_lod(rows)},
                               fetch_list=[out], return_numpy=False)
        got = res
        assert isinstance(got, LoDTensor)
        lod = got.lod[0]
        arr = got.array()
        seqs = [arr[lod[i]:lod[i + 1]].reshape(-1).tolist()
                for i in range(len(lod) - 1)]
        assert seqs == [[1, 2], [3, 3]]


class TestEditDistance:
    def test_vs_oracle(self):
        hyps = [np.array([[1], [2], [3]], np.int64),
                np.array([[5], [5]], np.int64)]
        refs = [np.array([[1], [3]], np.int64),
                np.array([[5], [6], [7]], np.int64)]

        def lev(a, b):
            m, n = len(a), len(b)
            d = np.zeros((m + 1, n + 1))
            d[:, 0] = np.arange(m + 1)
            d[0, :] = np.arange(n + 1)
            for i in range(1, m + 1):
                for j in range(1, n + 1):
                    d[i, j] = min(d[i-1, j] + 1, d[i, j-1] + 1,
                                  d[i-1, j-1] + (a[i-1] != b[j-1]))
            return d[m, n]

        for normalized in (False, True):
            with fluid.program_guard(fluid.Program(), fluid.Program()):
                h = fluid.layers.data(name="h", shape=[1], dtype="int64",
                                      lod_level=1)
                r = fluid.layers.data(name="r", shape=[1], dtype="int64",
                                      lod_level=1)
                dist, seq_num = fluid.layers.edit_distance(
                    h, r, normalized=normalized)
                exe = fluid.Executor(fluid.CPUPlace())
                scope = executor_mod.Scope()
                with executor_mod.scope_guard(scope):
                    res, sn = exe.run(fluid.default_main_program(),
                                      feed={"h": make_lod(hyps),
                                            "r": make_lod(refs)},
                                      fetch_list=[dist, seq_num])
            want = np.array([
                lev(hyps[i].reshape(-1), refs[i].reshape(-1))
                for i in range(2)], np.float64)
            if normalized:
                want = want / np.array([2.0, 3.0])
            np.testing.assert_allclose(np.asarray(res).reshape(-1), want,
                                       rtol=1e-5)
            assert int(np.asarray(sn).reshape(-1)[0]) == 2


class TestChunkEval:
    def _run(self, inf_rows, lab_rows, **attrs):
        with fluid.program_guard(fluid.Program(), fluid.Program()):
            inf = fluid.layers.data(name="inf", shape=[1], dtype="int64",
                                    lod_level=1)
            lab = fluid.layers.data(name="lab", shape=[1], dtype="int64",
                                    lod_level=1)
            (prec, rec, f1, n_inf, n_lab,
             n_cor) = fluid.layers.chunk_eval(input=inf, label=lab, **attrs)
            exe = fluid.Executor(fluid.CPUPlace())
            scope = executor_mod.Scope()
            with executor_mod.scope_guard(scope):
                return exe.run(
                    fluid.default_main_program(),
                    feed={"inf": make_lod(inf_rows),
                          "lab": make_lod(lab_rows)},
                    fetch_list=[prec, rec, f1, n_inf, n_lab, n_cor])

    def test_iob(self):
        # num_chunk_types=2, IOB: labels = type*2 + tag (B=0, I=1), O = 4
        # label chunks: [B0 I0] [B1], inference: [B0 I0] [B0]
        lab = [np.array([[0], [1], [4], [2]], np.int64)]
        inf = [np.array([[0], [1], [4], [0]], np.int64)]
        p, r, f1, ni, nl, nc = self._run(
            inf, lab, chunk_scheme="IOB", num_chunk_types=2)
        assert int(ni) == 2 and int(nl) == 2 and int(nc) == 1
        np.testing.assert_allclose(float(p), 0.5)
        np.testing.assert_allclose(float(r), 0.5)
        np.testing.assert_allclose(float(f1), 0.5)

    def test_plain_scheme_and_multiseq(self):
        # plain: adjacent equal labels form ONE chunk; O = num_chunk_types
        lab = [np.array([[1], [1], [3], [0]], np.int64),
               np.array([[2], [3]], np.int64)]
        inf = [np.array([[1], [1], [3], [3]], np.int64),
               np.array([[2], [2]], np.int64)]
        p, r, f1, ni, nl, nc = self._run(
            inf, lab, chunk_scheme="plain", num_chunk_types=3)
        # label chunks: {1:[0,1]},{0:[3]} in seq0 (3 is O), {2:[0]} in seq1
        # inf chunks:   {1:[0,1]} in seq0, {2:[0,1]} in seq1
        assert int(nl) == 3 and int(ni) == 2 and int(nc) == 1

    def test_excluded(self):
        lab = [np.array([[0], [2]], np.int64)]
        inf = [np.array([[0], [2]], np.int64)]
        p, r, f1, ni, nl, nc = self._run(
            inf, lab, chunk_scheme="plain", num_chunk_types=4,
            excluded_chunk_types=[0])
        assert int(ni) == 2 and int(nl) == 2 and int(nc) == 1


class TestPrecisionRecall:
    def test_vs_oracle(self):
        n, c = 12, 4
        idx = RNG.randint(0, c, (n, 1)).astype(np.int32)
        lab = RNG.randint(0, c, (n, 1)).astype(np.int32)
        states = np.zeros((c, 4), np.float32)
        for i in range(n):
            p, t = int(idx[i]), int(lab[i])
            if p == t:
                states[p, 0] += 1
                states[:, 2] += 1
                states[p, 2] -= 1
            else:
                states[t, 3] += 1
                states[p, 1] += 1
                states[:, 2] += 1
                states[p, 2] -= 1
                states[t, 2] -= 1

        def metrics(s):
            def prec(tp, fp):
                return tp / (tp + fp) if tp + fp > 0 else 1.0
            def rec(tp, fn):
                return tp / (tp + fn) if tp + fn > 0 else 1.0
            def f1(p, r):
                return 2 * p * r / (p + r) if p + r > 0 else 0.0
            mp = np.mean([prec(s[i, 0], s[i, 1]) for i in range(c)])
            mr = np.mean([rec(s[i, 0], s[i, 3]) for i in range(c)])
            up = prec(s[:, 0].sum(), s[:, 1].sum())
            ur = rec(s[:, 0].sum(), s[:, 3].sum())
            return [mp, mr, f1(mp, mr), up, ur, f1(up, ur)]

        res = run_op("precision_recall",
                     {"Indices": ("pr_idx", idx), "Labels": ("pr_lab", lab)},
                     {"class_number": c},
                     ["BatchMetrics", "AccumMetrics", "AccumStatesInfo"])
        np.testing.assert_allclose(np.asarray(res["BatchMetrics"]),
                                   metrics(states), rtol=1e-5)
        np.testing.assert_allclose(np.asarray(res["AccumStatesInfo"]),
                                   states, rtol=1e-5)


class TestPositiveNegativePair:
    def test_vs_oracle(self):
        score = np.array([[0.8], [0.2], [0.5], [0.4], [0.9]], np.float32)
        label = np.array([[1], [0], [1], [0], [1]], np.float32)
        query = np.array([[7], [7], [7], [8], [8]], np.int64)
        pos = neg = neu = 0.0
        for i in range(5):
            for j in range(i + 1, 5):
                if query[i] != query[j] or label[i] == label[j]:
                    continue
                ds = score[i, 0] - score[j, 0]
                dl = label[i, 0] - label[j, 0]
                if ds == 0:
                    neu += 1
                if ds * dl > 0:
                    pos += 1
                else:
                    neg += 1
        res = run_op("positive_negative_pair",
                     {"Score": ("pnp_s", score), "Label": ("pnp_l", label),
                      "QueryID": ("pnp_q", query)},
                     {}, ["PositivePair", "NegativePair", "NeutralPair"])
        assert float(np.asarray(res["PositivePair"])) == pos
        assert float(np.asarray(res["NegativePair"])) == neg
        assert float(np.asarray(res["NeutralPair"])) == neu


class TestEvaluatorsUnorphaned:
    """metrics.ChunkEvaluator / EditDistance fed by their in-graph producer
    ops across minibatches (previously API surface without a producing op)."""

    def test_chunk_evaluator_accumulates(self):
        from paddle_tpu.metrics import ChunkEvaluator
        ev = ChunkEvaluator()
        with fluid.program_guard(fluid.Program(), fluid.Program()):
            inf = fluid.layers.data(name="inf", shape=[1], dtype="int64",
                                    lod_level=1)
            lab = fluid.layers.data(name="lab", shape=[1], dtype="int64",
                                    lod_level=1)
            _, _, _, ni, nl, nc = fluid.layers.chunk_eval(
                input=inf, label=lab, chunk_scheme="IOB", num_chunk_types=2)
            exe = fluid.Executor(fluid.CPUPlace())
            with executor_mod.scope_guard(executor_mod.Scope()):
                for _ in range(2):
                    lab_rows = [np.array([[0], [1], [4], [2]], np.int64)]
                    inf_rows = [np.array([[0], [1], [4], [0]], np.int64)]
                    a, b, c = exe.run(
                        fluid.default_main_program(),
                        feed={"inf": make_lod(inf_rows),
                              "lab": make_lod(lab_rows)},
                        fetch_list=[ni, nl, nc])
                    ev.update(a, b, c)
        p, r, f1 = ev.eval()
        assert (p, r, f1) == (0.5, 0.5, 0.5)

    def test_edit_distance_metric(self):
        from paddle_tpu.metrics import EditDistance as EDMetric
        ev = EDMetric()
        with fluid.program_guard(fluid.Program(), fluid.Program()):
            h = fluid.layers.data(name="h", shape=[1], dtype="int64",
                                  lod_level=1)
            r = fluid.layers.data(name="r", shape=[1], dtype="int64",
                                  lod_level=1)
            dist, seq_num = fluid.layers.edit_distance(h, r,
                                                       normalized=False)
            exe = fluid.Executor(fluid.CPUPlace())
            with executor_mod.scope_guard(executor_mod.Scope()):
                d, n = exe.run(
                    fluid.default_main_program(),
                    feed={"h": make_lod([np.array([[1], [2]], np.int64),
                                         np.array([[5]], np.int64)]),
                          "r": make_lod([np.array([[1], [3]], np.int64),
                                         np.array([[5]], np.int64)])},
                    fetch_list=[dist, seq_num])
                ev.update(d, n)
        avg, instance_err = ev.eval()
        assert abs(avg - 0.5) < 1e-6          # distances [1, 0] over 2 seqs
