"""Elastic data-dispatch task queue (reference go/master/service.go:
partition/GetTask/TaskFinished/TaskFailed, timeout requeue :341, failure
budget :313/:455, snapshot :207 + recover :166; client NextRecord :244).
The file-backed queue must give the same at-least-once, no-loss contract
across worker crashes and master restarts."""

import multiprocessing as mp
import os
import sys
import time

import numpy as np

from paddle_tpu.parallel.master import TaskQueue, elastic_reader


class TestTaskQueueSemantics:
    def test_partition_idempotent_and_lease_cycle(self, tmp_path):
        d = str(tmp_path)
        q = TaskQueue(d, timeout_s=60)
        q.partition(list(range(10)), chunks_per_task=2)
        q.partition(list(range(999)), chunks_per_task=1)   # no-op
        assert q.stats() == {"todo": 5, "pending": 0, "done": 0,
                             "failed": 0}
        tid, chunks = q.get_task("w0")
        assert chunks == [0, 1]
        assert q.stats()["pending"] == 1
        q.task_finished(tid)
        assert q.stats()["done"] == 1
        assert not q.pass_done()

    def test_timeout_requeues_to_other_worker(self, tmp_path):
        now = [1000.0]
        q = TaskQueue(str(tmp_path), timeout_s=10, clock=lambda: now[0])
        q.partition([["a"], ["b"]])
        t1, _ = q.get_task("w0")           # w0 leases and "crashes"
        now[0] += 5
        t2, _ = q.get_task("w1")           # w1's lease is 5s fresher
        assert q.get_task("w1") is None    # nothing left while leased
        now[0] += 6                        # only w0's lease expires
        t3 = q.get_task("w1")
        assert t3 is not None and t3[0] == t1   # requeued, not lost
        q.task_finished(t2)
        q.task_finished(t3[0])
        assert q.pass_done()

    def test_failure_budget_discards(self, tmp_path):
        q = TaskQueue(str(tmp_path), timeout_s=60, failure_max=2)
        q.partition([["x"]])
        for _ in range(2):
            tid, _ = q.get_task()
            q.task_failed(tid)
        # two strikes with failure_max=2: discarded, pass drains
        assert q.get_task() is None
        assert q.stats()["failed"] == 1
        assert q.pass_done()

    def test_snapshot_recovery(self, tmp_path):
        d = str(tmp_path)
        q1 = TaskQueue(d, timeout_s=60)
        q1.partition(list(range(6)), chunks_per_task=2)
        tid, _ = q1.get_task("w0")
        q1.task_finished(tid)
        # "master" restart: a fresh object over the same dir sees the state
        q2 = TaskQueue(d, timeout_s=60)
        assert q2.stats() == {"todo": 2, "pending": 0, "done": 1,
                              "failed": 0}
        got = {tuple(q2.get_task()[1]) for _ in range(2)}
        assert got == {(2, 3), (4, 5)}

    def test_reset_pass(self, tmp_path):
        q = TaskQueue(str(tmp_path), timeout_s=60)
        q.partition([["a"], ["b"]])
        for _ in range(2):
            tid, _ = q.get_task()
            q.task_finished(tid)
        assert q.pass_done()
        q.reset_pass()
        assert q.stats()["todo"] == 2


class TestElasticWorkers:
    def test_crashed_worker_task_requeues_no_loss(self, tmp_path):
        # spawn (not fork): forking a jax-initialized parent risks
        # deadlock; the worker lives in _master_worker.py so the spawned
        # child never imports jax at all
        from _master_worker import worker as _worker

        d = str(tmp_path)
        q = TaskQueue(d, timeout_s=2.0)
        chunks = [[i * 10 + j for j in range(5)] for i in range(4)]
        q.partition(chunks)

        ctx = mp.get_context("spawn")
        out = ctx.Queue()
        # w0 crashes after 2 samples (mid-task); w1 starts after and
        # must pick up the requeued task once the lease expires
        w0 = ctx.Process(target=_worker, args=(d, "w0", 2, out))
        w0.start()
        w0.join(timeout=30)
        assert w0.exitcode == 17
        w1 = ctx.Process(target=_worker, args=(d, "w1", None, out))
        w1.start()
        w1.join(timeout=60)
        assert w1.exitcode == 0, w1.exitcode

        _, seen1 = out.get(timeout=10)
        flat = sorted(seen1)
        want = sorted(s for c in chunks for s in c)
        # w1 alone covers every sample (w0's partial task was requeued
        # whole — at-least-once, no loss)
        assert flat == want, (flat, want)
