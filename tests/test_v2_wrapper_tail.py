"""Oracle tests for the round-5 v2 wrapper tail (VERDICT r4 #5): every new
trainer_config_helpers-parity wrapper runs against a numpy oracle, plus
the ADVICE r4 fixes (initial_std/mean -> initializer, warn on lr kwargs,
true vanilla recurrence) and the v2/plot Ploter."""

import warnings

import numpy as np
import pytest

import paddle_tpu as fluid
from paddle_tpu import executor as executor_mod
from paddle_tpu.v2 import layer as v2l
from paddle_tpu.v2 import networks as v2n


def _run(fetch, feed):
    exe = fluid.Executor(fluid.CPUPlace())
    with executor_mod.scope_guard(executor_mod.Scope()):
        exe.run(fluid.default_startup_program())
        outs = exe.run(feed=feed, fetch_list=list(fetch))
    return [np.asarray(o) for o in outs]


def _data(name, shape, dtype="float32"):
    return fluid.layers.data(name=name, shape=shape, dtype=dtype,
                             append_batch_size=False)


RNG = np.random.RandomState(7)


class TestMatrixWrappers:
    def test_rotate_is_ccw_rot90(self):
        c, h, w = 2, 3, 4
        x = _data("x", [2, c * h * w])
        out = v2l.rotate(x, height=h, width=w)
        xs = RNG.randn(2, c * h * w).astype(np.float32)
        got, = _run([out], {"x": xs})
        want = np.rot90(xs.reshape(2, c, h, w), k=1, axes=(2, 3)) \
            .reshape(2, -1)
        np.testing.assert_allclose(got, want, rtol=1e-6)

    def test_sum_to_one_norm(self):
        x = _data("x", [3, 5])
        xs = np.abs(RNG.randn(3, 5)).astype(np.float32) + 0.1
        got, = _run([v2l.sum_to_one_norm(x)], {"x": xs})
        np.testing.assert_allclose(got, xs / xs.sum(1, keepdims=True),
                                   rtol=1e-5)

    def test_row_l2_norm(self):
        x = _data("x", [3, 5])
        xs = RNG.randn(3, 5).astype(np.float32)
        got, = _run([v2l.row_l2_norm(x)], {"x": xs})
        np.testing.assert_allclose(
            got, xs / np.linalg.norm(xs, axis=1, keepdims=True), rtol=1e-5)

    def test_l2_distance_and_dot_prod(self):
        a, b = _data("a", [4, 6]), _data("b", [4, 6])
        av = RNG.randn(4, 6).astype(np.float32)
        bv = RNG.randn(4, 6).astype(np.float32)
        d, p = _run([v2l.l2_distance(a, b), v2l.dot_prod(a, b)],
                    {"a": av, "b": bv})
        np.testing.assert_allclose(
            d[:, 0], np.linalg.norm(av - bv, axis=1), rtol=1e-5)
        np.testing.assert_allclose(p[:, 0], (av * bv).sum(1), rtol=1e-5)

    def test_out_prod(self):
        a, b = _data("a", [3, 4]), _data("b", [3, 5])
        av = RNG.randn(3, 4).astype(np.float32)
        bv = RNG.randn(3, 5).astype(np.float32)
        got, = _run([v2l.out_prod(a, b)], {"a": av, "b": bv})
        want = np.einsum("ni,nj->nij", av, bv).reshape(3, -1)
        np.testing.assert_allclose(got, want, rtol=1e-5)

    def test_linear_comb(self):
        m, size = 3, 4
        w, v = _data("w", [2, m]), _data("v", [2, m * size])
        wv = RNG.randn(2, m).astype(np.float32)
        vv = RNG.randn(2, m * size).astype(np.float32)
        got, = _run([v2l.linear_comb(w, v, size)], {"w": wv, "v": vv})
        want = np.einsum("nm,nms->ns", wv, vv.reshape(2, m, size))
        np.testing.assert_allclose(got, want, rtol=1e-5)

    def test_tensor_layer_bilinear(self):
        da, db, size = 3, 4, 2
        a, b = _data("a", [2, da]), _data("b", [2, db])
        out = v2l.tensor(a, b, size)
        av = RNG.randn(2, da).astype(np.float32)
        bv = RNG.randn(2, db).astype(np.float32)
        exe = fluid.Executor(fluid.CPUPlace())
        sc = executor_mod.Scope()
        with executor_mod.scope_guard(sc):
            exe.run(fluid.default_startup_program())
            wname = [p.name for p in fluid.default_main_program()
                     .global_block().all_parameters()][0]
            wv = np.asarray(sc.find_var(wname))
            got, = exe.run(feed={"a": av, "b": bv}, fetch_list=[out])
        want = np.einsum("ni,isj,nj->ns", av,
                         wv.reshape(da, size, db), bv)
        np.testing.assert_allclose(np.asarray(got), want, rtol=1e-4,
                                   atol=1e-5)


class TestProjectionsAndMixed:
    def test_mixed_sums_projections(self):
        x, y = _data("x", [2, 6]), _data("y", [2, 4])
        p1 = v2l.full_matrix_projection(x, size=4)
        p2 = v2l.identity_projection(y)
        out = v2l.mixed(input=[p1, p2])
        xs = RNG.randn(2, 6).astype(np.float32)
        ys = RNG.randn(2, 4).astype(np.float32)
        got, p1v = _run([out, p1], {"x": xs, "y": ys})
        np.testing.assert_allclose(got, p1v + ys, rtol=1e-5)

    def test_identity_projection_slice(self):
        x = _data("x", [3, 8])
        xs = RNG.randn(3, 8).astype(np.float32)
        got, = _run([v2l.identity_projection(x, offset=2, size=3)],
                    {"x": xs})
        np.testing.assert_allclose(got, xs[:, 2:5], rtol=1e-6)

    def test_dotmul_and_scaling_projection_param_counts(self):
        x = _data("x", [2, 5])
        v2l.dotmul_projection(x)
        v2l.scaling_projection(x)
        shapes = sorted(
            tuple(v.shape) for v in
            fluid.default_startup_program().global_block().vars.values()
            if getattr(v, "persistable", False))
        assert (1,) in shapes and (5,) in shapes

    def test_trans_full_matrix_projection_shares_transposed_weight(self):
        x = _data("x", [2, 4])
        out = v2l.trans_full_matrix_projection(x, size=3,
                                               param_attr="shared_w")
        xs = RNG.randn(2, 4).astype(np.float32)
        exe = fluid.Executor(fluid.CPUPlace())
        sc = executor_mod.Scope()
        with executor_mod.scope_guard(sc):
            exe.run(fluid.default_startup_program())
            wv = np.asarray(sc.find_var("shared_w"))
            got, = exe.run(feed={"x": xs}, fetch_list=[out])
        assert wv.shape == (3, 4)                    # stored [size, in]
        np.testing.assert_allclose(np.asarray(got), xs @ wv.T, rtol=1e-5)

    def test_table_projection_is_embedding(self):
        ids = fluid.layers.data(name="ids", shape=[4, 1], dtype="int64",
                                append_batch_size=False)
        out = v2l.table_projection(ids, size=3, vocab_size=10)
        got, = _run([out], {"ids": np.array([[1], [2], [3], [1]],
                                            np.int64)})
        assert got.shape[-1] == 3
        np.testing.assert_allclose(got[0], got[3], rtol=1e-6)  # same id

    def test_conv_projection_no_bias(self):
        img = _data("img", [1, 3, 8, 8])
        before = set(
            fluid.default_startup_program().global_block().vars)
        v2l.conv_projection(img, filter_size=3, num_filters=4, padding=1)
        new = [v for v in
               fluid.default_startup_program().global_block().vars
               if v not in before]
        assert len(new) == 1                         # weight only, no bias


class TestMiscWrappers:
    def test_maxid(self):
        x = _data("x", [3, 7])
        xs = RNG.randn(3, 7).astype(np.float32)
        got, = _run([v2l.maxid(x)], {"x": xs})
        np.testing.assert_array_equal(got[:, 0], xs.argmax(1))

    def test_clip_resize_pad(self):
        x = _data("x", [2, 6])
        img = _data("img", [1, 2, 3, 3])
        xs = RNG.randn(2, 6).astype(np.float32) * 3
        imgs = RNG.randn(1, 2, 3, 3).astype(np.float32)
        c, r, p = _run(
            [v2l.clip(x, min=-1.0, max=1.0), v2l.resize(x, 3),
             v2l.pad(img, pad_c=[1, 0], pad_h=[0, 2], pad_w=[1, 1])],
            {"x": xs, "img": imgs})
        np.testing.assert_allclose(c, np.clip(xs, -1, 1), rtol=1e-6)
        assert r.shape == (4, 3)
        assert p.shape == (1, 3, 5, 5)
        np.testing.assert_allclose(p[:, 1:, 0:3, 1:4], imgs, rtol=1e-6)

    def test_scale_shift_param_shapes(self):
        x = _data("x", [2, 4])
        out = v2l.scale_shift(x)
        xs = RNG.randn(2, 4).astype(np.float32)
        got, = _run([out], {"x": xs})
        assert got.shape == xs.shape                 # w*x+b, w/b scalars

    def test_prelu_negative_slope(self):
        x = _data("x", [2, 4])
        out = v2l.prelu(x)
        xs = np.array([[-2.0, -1.0, 1.0, 2.0]] * 2, np.float32)
        got, = _run([out], {"x": xs})
        # default alpha 0.25
        np.testing.assert_allclose(
            got, np.where(xs > 0, xs, 0.25 * xs), rtol=1e-5)

    def test_gated_unit(self):
        x = _data("x", [3, 5])
        out = v2l.gated_unit(x, size=4, act="tanh")
        xs = RNG.randn(3, 5).astype(np.float32)
        got, = _run([out], {"x": xs})
        assert got.shape == (3, 4)
        assert np.all(np.abs(got) <= 1.0)            # tanh * sigmoid bound

    def test_factorization_machine_oracle(self):
        n, d, f = 3, 5, 4
        x = _data("x", [n, d])
        out = v2l.factorization_machine(x, factor_size=f)
        xs = RNG.randn(n, d).astype(np.float32)
        exe = fluid.Executor(fluid.CPUPlace())
        sc = executor_mod.Scope()
        with executor_mod.scope_guard(sc):
            exe.run(fluid.default_startup_program())
            wname = [p.name for p in fluid.default_main_program()
                     .global_block().all_parameters()][0]
            vv = np.asarray(sc.find_var(wname))
            got, = exe.run(feed={"x": xs}, fetch_list=[out])
        want = 0.5 * (((xs @ vv) ** 2).sum(1)
                      - ((xs ** 2) @ (vv ** 2)).sum(1))
        np.testing.assert_allclose(np.asarray(got)[:, 0], want,
                                   rtol=1e-4, atol=1e-5)


class TestCosts:
    def test_sum_cost(self):
        x = _data("x", [2, 3])
        xs = RNG.randn(2, 3).astype(np.float32)
        got, = _run([v2l.sum_cost(x)], {"x": xs})
        np.testing.assert_allclose(float(got.ravel()[0]), xs.sum(),
                                   rtol=1e-5)

    def test_smooth_l1_cost(self):
        x, y = _data("x", [2, 3]), _data("y", [2, 3])
        xs = RNG.randn(2, 3).astype(np.float32)
        ys = RNG.randn(2, 3).astype(np.float32)
        got, = _run([v2l.smooth_l1_cost(x, y)], {"x": xs, "y": ys})
        assert np.isfinite(float(got.ravel()[0]))

    def test_multi_binary_label_cross_entropy(self):
        p = _data("p", [2, 3])
        lab = _data("lab", [2, 3])
        probs = np.array([[0.9, 0.1, 0.5], [0.2, 0.8, 0.6]], np.float32)
        labs = np.array([[1, 0, 1], [0, 1, 0]], np.float32)
        got, = _run([v2l.multi_binary_label_cross_entropy(p, lab)],
                    {"p": probs, "lab": labs})
        want = -(labs * np.log(probs)
                 + (1 - labs) * np.log(1 - probs)).sum(1).mean()
        np.testing.assert_allclose(float(got.ravel()[0]), want, rtol=1e-4)

    def test_huber_classification_cost_regions(self):
        f = _data("f", [4, 1])
        lab = _data("lab", [4, 1])
        fv = np.array([[2.0], [0.5], [-2.0], [-0.5]], np.float32)
        # labels {0,1} -> y' {-1,+1}
        lv = np.array([[1], [1], [1], [0]], np.float32)
        got, = _run([v2l.huber_classification_cost(f, lab)],
                    {"f": fv, "lab": lv})
        # z = y'*f = [2, .5, -2, .5] -> [0, .25, 8, .25]
        want = np.mean([0.0, 0.25, 8.0, 0.25])
        np.testing.assert_allclose(float(got.ravel()[0]), want, rtol=1e-5)


class TestAdviceFixes:
    def test_initial_std_becomes_initializer(self):
        x = _data("x", [64, 10])
        v2l.fc(x, size=50, initial_std=0.5, initial_mean=2.0)
        exe = fluid.Executor(fluid.CPUPlace())
        sc = executor_mod.Scope()
        with executor_mod.scope_guard(sc):
            exe.run(fluid.default_startup_program())
            wname = [v for v, var in fluid.default_startup_program()
                     .global_block().vars.items()
                     if getattr(var, "persistable", False)
                     and tuple(var.shape) == (10, 50)][0]
            w = np.asarray(sc.find_var(wname))
        assert abs(w.mean() - 2.0) < 0.2             # not default init
        assert 0.3 < w.std() < 0.7

    def test_learning_rate_kwarg_warns(self):
        x = _data("x", [2, 4])
        with warnings.catch_warnings(record=True) as rec:
            warnings.simplefilter("always")
            v2l.fc(x, size=3, learning_rate=0.1)
        assert any("learning_rate" in str(w.message) for w in rec)

    def test_unknown_kwarg_still_raises(self):
        x = _data("x", [2, 4])
        with pytest.raises(TypeError):
            v2l.fc(x, size=3, bogus_kwarg=1)

    def test_recurrent_true_vanilla_parameter_count_and_oracle(self):
        """h_t = tanh(x_t + W h_{t-1} + b): exactly one [size, size] W and
        one [size] bias; matches a numpy scan."""
        size = 4
        x = fluid.layers.data(name="x", shape=[size], dtype="float32",
                              lod_level=1)
        out = v2l.recurrent(x)
        last = fluid.layers.sequence_last_step(out)
        params = [(n, tuple(v.shape)) for n, v in
                  fluid.default_startup_program().global_block()
                  .vars.items() if getattr(v, "persistable", False)]
        shapes = sorted(s for _, s in params)
        assert shapes == [(4,), (4, 4)], params
        xs = RNG.randn(6, size).astype(np.float32)
        exe = fluid.Executor(fluid.CPUPlace())
        from paddle_tpu.executor import LoDTensor
        sc = executor_mod.Scope()
        with executor_mod.scope_guard(sc):
            exe.run(fluid.default_startup_program())
            wname = [n for n, s in params if s == (4, 4)][0]
            bname = [n for n, s in params if s == (4,)][0]
            w = np.asarray(sc.find_var(wname))
            b = np.asarray(sc.find_var(bname))
            got, = exe.run(feed={"x": LoDTensor(xs, [[0, 6]])},
                           fetch_list=[last])
        h = np.zeros(size, np.float32)
        for t in range(6):
            h = np.tanh(xs[t] + h @ w + b)
        np.testing.assert_allclose(np.asarray(got).ravel(), h, rtol=1e-4,
                                   atol=1e-5)


class TestNetworksTail:
    def test_bidirectional_gru_shapes(self):
        x = fluid.layers.data(name="x", shape=[6], dtype="float32",
                              lod_level=1)
        out = v2n.bidirectional_gru(x, size=5)
        assert out.shape[-1] == 10

    def test_simple_attention_is_convex_combination(self):
        """The context vector lies in the convex hull of the encoder
        states (softmax weights sum to 1)."""
        from paddle_tpu.executor import LoDTensor
        h = 4
        enc = fluid.layers.data(name="enc", shape=[h], dtype="float32",
                                lod_level=1)
        proj = fluid.layers.data(name="proj", shape=[h], dtype="float32",
                                 lod_level=1)
        state = fluid.layers.data(name="state", shape=[1, h],
                                  dtype="float32",
                                  append_batch_size=False)
        ctx = v2n.simple_attention(enc, proj, state)
        ev = RNG.randn(5, h).astype(np.float32)
        pv = RNG.randn(5, h).astype(np.float32)
        sv = RNG.randn(1, h).astype(np.float32)
        exe = fluid.Executor(fluid.CPUPlace())
        with executor_mod.scope_guard(executor_mod.Scope()):
            exe.run(fluid.default_startup_program())
            got, = exe.run(
                feed={"enc": LoDTensor(ev, [[0, 5]]),
                      "proj": LoDTensor(pv, [[0, 5]]),
                      "state": sv},
                fetch_list=[ctx])
        got = np.asarray(got).ravel()
        assert got.shape == (h,)
        lo, hi = ev.min(0), ev.max(0)
        assert np.all(got >= lo - 1e-5) and np.all(got <= hi + 1e-5)


class TestPloter:
    def test_ploter_collects_and_writes(self, tmp_path):
        from paddle_tpu.v2.plot import Ploter
        p = Ploter("train", "test")
        for i in range(5):
            p.append("train", i, 1.0 / (i + 1))
        p.append("test", 0, 0.5)
        out = tmp_path / "curve.png"
        p.plot(str(out))
        assert out.exists() and out.stat().st_size > 0
        p.reset()
        assert p.__plot_data__["train"].step == []

    def test_ploter_disabled(self, monkeypatch, tmp_path):
        monkeypatch.setenv("DISABLE_PLOT", "True")
        from paddle_tpu.v2.plot.plot import Ploter
        p = Ploter("train")
        p.append("train", 0, 1.0)
        out = tmp_path / "curve.png"
        p.plot(str(out))                 # no-op when disabled
        assert not out.exists()
