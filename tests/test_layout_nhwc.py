"""Internal NHWC layout convention (ops/layout.py): numeric parity with
the canonical NCHW path on training steps (forward + vjp + optimizer),
intermediate fetches, and the eager interpreter.

The TPU-native analogue of the reference's data_layout_transform tests
(framework/data_layout_transform.cc): the layout convention must be a
pure performance transform — no observable semantic change.
"""

import numpy as np
import pytest

import paddle_tpu as fluid
from paddle_tpu import executor as em
from paddle_tpu.framework import unique_name
from paddle_tpu.ops import layout as layout_mod


@pytest.fixture(params=[True, False], ids=["nhwc", "nchw"])
def layout_opt(request, monkeypatch):
    monkeypatch.setattr(layout_mod, "LAYOUT_OPT", request.param)
    return request.param


def _train_convnet(steps=3, fetch_inter=False, use_jit=True):
    """Small image classifier exercising conv(bias)+bn+relu+pool+residual:
    returns per-step losses, final params, and optionally an intermediate
    conv activation fetch."""
    unique_name.switch()
    main, startup = fluid.Program(), fluid.Program()
    main.random_seed = startup.random_seed = 77
    with fluid.program_guard(main, startup):
        img = fluid.layers.data(name="img", shape=[3, 16, 16],
                                dtype="float32")
        label = fluid.layers.data(name="label", shape=[1], dtype="int64")
        c1 = fluid.layers.conv2d(input=img, num_filters=8, filter_size=3,
                                 padding=1, act="relu")   # bias path axis=1
        b1 = fluid.layers.batch_norm(input=c1, act="relu")
        c2 = fluid.layers.conv2d(input=b1, num_filters=8, filter_size=3,
                                 padding=1, bias_attr=False)
        b2 = fluid.layers.batch_norm(input=c2)
        res = fluid.layers.elementwise_add(x=b1, y=b2, act="relu")
        p = fluid.layers.pool2d(input=res, pool_size=2, pool_stride=2)
        gp = fluid.layers.pool2d(input=p, global_pooling=True,
                                 pool_type="avg")
        logits = fluid.layers.fc(input=gp, size=5)
        loss = fluid.layers.mean(
            fluid.layers.softmax_with_cross_entropy(logits, label))
        fluid.optimizer.Momentum(learning_rate=0.05, momentum=0.9).minimize(
            loss, startup_program=startup)
    exe = fluid.Executor(fluid.CPUPlace())
    rng = np.random.default_rng(3)
    scope = em.Scope()
    losses, inter = [], None
    with em.scope_guard(scope):
        exe.run(startup)
        for _ in range(steps):
            x = rng.standard_normal((8, 3, 16, 16)).astype(np.float32)
            y = rng.integers(0, 5, (8, 1)).astype(np.int64)
            fetch = [loss] + ([c1] if fetch_inter else [])
            out = exe.run(main, feed={"img": x, "label": y},
                          fetch_list=fetch, use_jit=use_jit)
            losses.append(float(np.ravel(out[0])[0]))
            if fetch_inter:
                inter = np.asarray(out[1])
        params = {n: np.asarray(scope.find_var(n))
                  for n in scope.local_var_names()
                  if n.endswith((".w_0", ".b_0"))}
    return losses, params, inter


def _run_modes(fn):
    old = layout_mod.LAYOUT_OPT
    try:
        layout_mod.LAYOUT_OPT = False
        ref = fn()
        layout_mod.LAYOUT_OPT = True
        got = fn()
    finally:
        layout_mod.LAYOUT_OPT = old
    return ref, got


def test_convnet_train_parity():
    """NHWC-convention training matches canonical NCHW step for step —
    losses and every updated parameter."""
    (l_ref, p_ref, _), (l_got, p_got, _) = _run_modes(_train_convnet)
    np.testing.assert_allclose(l_got, l_ref, rtol=1e-4, atol=1e-5)
    assert p_ref.keys() == p_got.keys() and len(p_ref) >= 6
    for n in p_ref:
        np.testing.assert_allclose(p_got[n], p_ref[n], rtol=2e-4,
                                   atol=1e-5, err_msg=n)


def test_intermediate_fetch_is_canonical_nchw():
    """Fetching a conv activation mid-stack returns the user-visible NCHW
    layout and the same numbers as the NCHW path."""
    (_, _, i_ref), (_, _, i_got) = _run_modes(
        lambda: _train_convnet(steps=1, fetch_inter=True))
    assert i_got.shape == (8, 8, 16, 16)
    np.testing.assert_allclose(i_got, i_ref, rtol=1e-4, atol=1e-5)


def test_eager_matches_jit_under_nhwc(monkeypatch):
    """The eager interpreter shares the layout machinery: same numbers."""
    monkeypatch.setattr(layout_mod, "LAYOUT_OPT", True)
    l_jit, p_jit, _ = _train_convnet(steps=2, use_jit=True)
    l_eager, p_eager, _ = _train_convnet(steps=2, use_jit=False)
    np.testing.assert_allclose(l_eager, l_jit, rtol=1e-4, atol=1e-5)
    for n in p_jit:
        np.testing.assert_allclose(p_eager[n], p_jit[n], rtol=2e-4,
                                   atol=1e-5, err_msg=n)


def _train_deconv(steps=2):
    unique_name.switch()
    main, startup = fluid.Program(), fluid.Program()
    main.random_seed = startup.random_seed = 11
    with fluid.program_guard(main, startup):
        img = fluid.layers.data(name="img", shape=[4, 8, 8],
                                dtype="float32")
        tgt = fluid.layers.data(name="tgt", shape=[3, 16, 16],
                                dtype="float32")
        c = fluid.layers.conv2d(input=img, num_filters=6, filter_size=3,
                                padding=1, act="relu")
        up = fluid.layers.conv2d_transpose(input=c, num_filters=3,
                                           filter_size=4, stride=2,
                                           padding=1)
        loss = fluid.layers.mean(fluid.layers.square_error_cost(up, tgt))
        fluid.optimizer.SGD(learning_rate=0.1).minimize(
            loss, startup_program=startup)
    exe = fluid.Executor(fluid.CPUPlace())
    rng = np.random.default_rng(5)
    losses = []
    with em.scope_guard(em.Scope()):
        exe.run(startup)
        for _ in range(steps):
            x = rng.standard_normal((4, 4, 8, 8)).astype(np.float32)
            t = rng.standard_normal((4, 3, 16, 16)).astype(np.float32)
            v, = exe.run(main, feed={"img": x, "tgt": t},
                         fetch_list=[loss])
            losses.append(float(np.ravel(v)[0]))
    return losses


def test_conv2d_transpose_parity():
    """conv2d_transpose joins the NHWC convention (it previously ran NCHW,
    inconsistent with conv2d — VERDICT r2 weak #3)."""
    ref, got = _run_modes(_train_deconv)
    np.testing.assert_allclose(got, ref, rtol=1e-4, atol=1e-5)


def _train_conv3d(steps=2):
    unique_name.switch()
    main, startup = fluid.Program(), fluid.Program()
    main.random_seed = startup.random_seed = 13
    with fluid.program_guard(main, startup):
        vol = fluid.layers.data(name="vol", shape=[2, 6, 6, 6],
                                dtype="float32")
        y = fluid.layers.data(name="y", shape=[1], dtype="float32")
        c = fluid.layers.conv3d(input=vol, num_filters=4, filter_size=3,
                                padding=1, act="relu")
        gp = fluid.layers.reduce_mean(c, dim=[1, 2, 3, 4], keep_dim=False)
        pred = fluid.layers.reshape(gp, [-1, 1])
        loss = fluid.layers.mean(fluid.layers.square_error_cost(pred, y))
        fluid.optimizer.SGD(learning_rate=0.1).minimize(
            loss, startup_program=startup)
    exe = fluid.Executor(fluid.CPUPlace())
    rng = np.random.default_rng(6)
    losses = []
    with em.scope_guard(em.Scope()):
        exe.run(startup)
        for _ in range(steps):
            x = rng.standard_normal((4, 2, 6, 6, 6)).astype(np.float32)
            t = rng.standard_normal((4, 1)).astype(np.float32)
            v, = exe.run(main, feed={"vol": x, "y": t}, fetch_list=[loss])
            losses.append(float(np.ravel(v)[0]))
    return losses


def test_conv3d_parity():
    """conv3d runs NDHWC internally; same numbers as canonical NCDHW."""
    ref, got = _run_modes(_train_conv3d)
    np.testing.assert_allclose(got, ref, rtol=1e-4, atol=1e-5)


def _prelu_sum():
    """prelu is layout-aware (ISSUE 7): under the NHWC tag its channel
    alpha broadcasts on the minor axis instead of forcing a barrier.
    C != H here so a layout bug breaks broadcasting or silently
    mis-applies alpha."""
    unique_name.switch()
    main, startup = fluid.Program(), fluid.Program()
    main.random_seed = startup.random_seed = 9
    with fluid.program_guard(main, startup):
        x = fluid.layers.data(name="x", shape=[4, 6, 6], dtype="float32")
        c = fluid.layers.conv2d(input=x, num_filters=5, filter_size=3,
                                padding=1)
        p = fluid.layers.prelu(c, mode="channel")
        out = fluid.layers.reduce_sum(p)
    exe = fluid.Executor(fluid.CPUPlace())
    with em.scope_guard(em.Scope()):
        exe.run(startup)
        v, = exe.run(main, feed={"x": np.ones((2, 4, 6, 6), np.float32)},
                     fetch_list=[out])
    return float(np.ravel(v)[0])


def test_prelu_after_conv_parity():
    ref, got = _run_modes(_prelu_sum)
    np.testing.assert_allclose(got, ref, rtol=1e-4)


def _prelu_element_sum():
    """element-mode alpha is stored canonical [1, C, H, W]; under the
    NHWC tag the lowering must transpose it to minor-channel order, not
    reshape blindly (H != W != C here so a mix-up changes the sum)."""
    unique_name.switch()
    main, startup = fluid.Program(), fluid.Program()
    main.random_seed = startup.random_seed = 11
    with fluid.program_guard(main, startup):
        x = fluid.layers.data(name="x", shape=[4, 6, 7], dtype="float32")
        c = fluid.layers.conv2d(input=x, num_filters=5, filter_size=3,
                                padding=1)
        p = fluid.layers.prelu(c, mode="element")
        out = fluid.layers.reduce_sum(p)
    exe = fluid.Executor(fluid.CPUPlace())
    with em.scope_guard(em.Scope()):
        exe.run(startup)
        v, = exe.run(main, feed={"x": np.ones((2, 4, 6, 7), np.float32)},
                     fetch_list=[out])
    return float(np.ravel(v)[0])


def test_prelu_element_after_conv_parity():
    ref, got = _run_modes(_prelu_element_sum)
    np.testing.assert_allclose(got, ref, rtol=1e-4)


def test_persistable_set_after_run_invalidates_analysis():
    """Marking a var persistable between runs must reach the cached
    program analysis (r3 review finding: the executor caches read/write/
    persistable sets per program version)."""
    main, _ = fluid.Program(), fluid.Program()
    with fluid.program_guard(main):
        a = fluid.layers.data(name="a", shape=[4], dtype="float32")
        y = fluid.layers.scale(a, scale=2.0)
    exe = fluid.Executor(fluid.CPUPlace())
    s = em.Scope()
    feed = {"a": np.ones((2, 4), np.float32)}
    with em.scope_guard(s):
        exe.run(main, feed=feed, fetch_list=[y], use_jit=False)
        assert s.find_var(y.name) is None
        y.persistable = True
        exe.run(main, feed=feed, fetch_list=[y], use_jit=False)
        assert s.find_var(y.name) is not None
