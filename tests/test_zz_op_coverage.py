"""Op-coverage gate, run LAST (zz prefix; pytest collects test files in
alphabetical order): every registered op type must have been executed by
some earlier test in this session — the continuous-enforcement form of the
reference's one-OpTest-file-per-op discipline (reference
tests/unittests/op_test.py:212). Skips on partial runs (-k / single-file
invocations) so it only gates full-suite sessions.
"""

from paddle_tpu import executor as executor_mod
from paddle_tpu.ops import registry

import pytest

# executor-level plumbing with no kernel of its own
STRUCTURAL = {"feed", "fetch"}
# a full-suite run executes far more distinct op types than this; partial
# runs (single files, -k filters) stay below it and skip the gate
FULL_RUN_THRESHOLD = 150


def test_every_registered_op_executed():
    executed = set(executor_mod._RECORDED_OPS)
    if len(executed) < FULL_RUN_THRESHOLD:
        pytest.skip(f"partial run ({len(executed)} op types executed); "
                    "coverage gate applies to full-suite sessions")
    registered = set(registry.registered_ops())
    missing = sorted(registered - executed - STRUCTURAL)
    assert not missing, (
        f"{len(missing)} registered ops never executed by the suite: "
        f"{missing}")
