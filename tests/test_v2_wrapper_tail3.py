"""Third r5 v2 tranche: Print/printer, crop, switch_order,
AggregateLevel/ExpandLevel markers, ThreadPool-backed reader path."""

import numpy as np
import pytest

import paddle_tpu as fluid
from paddle_tpu import executor as executor_mod
from paddle_tpu.v2 import layer as v2l


def _run(fetch, feed):
    exe = fluid.Executor(fluid.CPUPlace())
    with executor_mod.scope_guard(executor_mod.Scope()):
        exe.run(fluid.default_startup_program())
        outs = exe.run(feed=feed, fetch_list=list(fetch))
    return [np.asarray(o) for o in outs]


def _data(name, shape, dtype="float32"):
    return fluid.layers.data(name=name, shape=shape, dtype=dtype,
                             append_batch_size=False)


RNG = np.random.RandomState(21)


class TestTrancheThree:
    def test_fluid_print_passes_through_and_logs(self, capfd):
        x = _data("x", [2, 3])
        out = fluid.layers.Print(x, message="dbg: ", summarize=2)
        s = fluid.layers.reduce_sum(out)
        xs = np.ones((2, 3), np.float32)
        got, = _run([s], {"x": xs})
        assert float(got.ravel()[0]) == 6.0
        logged = capfd.readouterr().out
        assert "dbg: " in logged and "shape=(2, 3)" in logged

    def test_v2_printer_alias(self):
        x = _data("x", [2, 2])
        out = v2l.printer(x, message="p: ")
        got, = _run([out], {"x": np.eye(2, dtype=np.float32)})
        np.testing.assert_allclose(got, np.eye(2))
        assert v2l.print_ is v2l.printer

    def test_crop(self):
        img = _data("img", [2, 3, 6, 8])
        out = v2l.crop(img, shape=[4, 5], offset=[1, 2], axis=2)
        xs = RNG.randn(2, 3, 6, 8).astype(np.float32)
        got, = _run([out], {"img": xs})
        np.testing.assert_allclose(got, xs[:, :, 1:5, 2:7], rtol=1e-6)

    def test_switch_order_nchw_to_nhwc(self):
        img = _data("img", [2, 3, 4, 5])
        out = v2l.switch_order(img, order=[2, 3, 1])
        xs = RNG.randn(2, 3, 4, 5).astype(np.float32)
        got, = _run([out], {"img": xs})
        np.testing.assert_allclose(got, xs.transpose(0, 2, 3, 1),
                                   rtol=1e-6)

    def test_aggregate_and_expand_levels(self):
        assert v2l.AggregateLevel.TO_NO_SEQUENCE == "non-seq"
        assert v2l.AggregateLevel.EACH_SEQUENCE == "seq"
        assert v2l.ExpandLevel.FROM_NO_SEQUENCE == "non-seq"
        with pytest.raises(ValueError):
            v2l.pooling(None, agg_level=v2l.AggregateLevel.TO_SEQUENCE)

    def test_context_projection_oracle(self):
        """Centered 3-window: out[t] = [x[t-1], x[t], x[t+1]] with zeros
        outside each sequence (reference ContextProjection)."""
        from paddle_tpu.executor import LoDTensor
        x = fluid.layers.data(name="x", shape=[2], dtype="float32",
                              lod_level=1)
        out = v2l.context_projection(x, context_len=3)
        rows = np.arange(1, 11, dtype=np.float32).reshape(5, 2)
        exe = fluid.Executor(fluid.CPUPlace())
        with executor_mod.scope_guard(executor_mod.Scope()):
            exe.run(fluid.default_startup_program())
            got, = exe.run(feed={"x": LoDTensor(rows, [[0, 3, 5]])},
                           fetch_list=[out])
        got = np.asarray(got)                   # packed [sum_len, 3*D]
        z = np.zeros(2, np.float32)
        seq1, seq2 = rows[:3], rows[3:]
        want = np.stack([
            np.concatenate([z, seq1[0], seq1[1]]),
            np.concatenate([seq1[0], seq1[1], seq1[2]]),
            np.concatenate([seq1[1], seq1[2], z]),
            np.concatenate([z, seq2[0], seq2[1]]),
            np.concatenate([seq2[0], seq2[1], z]),
        ])
        np.testing.assert_allclose(got, want, rtol=1e-6)

    def test_gru_step_inside_recurrent_group_matches_dynamic_gru(self):
        """gru_step + memory inside recurrent_group must reproduce
        dynamic_gru given shared parameters."""
        from paddle_tpu.executor import LoDTensor
        H = 4
        rows = RNG.randn(6, 3 * H).astype(np.float32)

        def run(build):
            main, startup = fluid.Program(), fluid.Program()
            with fluid.program_guard(main, startup):
                x = fluid.layers.data(name="x", shape=[3 * H],
                                      dtype="float32", lod_level=1)
                out = build(x)
            exe = fluid.Executor(fluid.CPUPlace())
            sc = executor_mod.Scope()
            with executor_mod.scope_guard(sc):
                exe.run(startup)
                got, = exe.run(main,
                               feed={"x": LoDTensor(rows, [[0, 6]])},
                               fetch_list=[out])
            return np.asarray(got)

        def via_group(x):
            def step(x_t):
                prev = v2l.memory("h", size=H)
                return v2l.gru_step(x_t, prev, size=H, name="h",
                                    param_attr="gw", bias_attr="gb")
            return v2l.recurrent_group(step, x)

        def via_dynamic(x):
            return fluid.layers.dynamic_gru(
                x, size=H, param_attr=fluid.ParamAttr(name="gw"),
                bias_attr=fluid.ParamAttr(name="gb"))

        got = run(via_group)
        want = run(via_dynamic)
        np.testing.assert_allclose(got, want, rtol=1e-4, atol=1e-5)

    def test_conv3d_pool3d_wrappers(self):
        vol = _data("vol", [1, 2, 4, 6, 6])
        h = v2l.img_conv3d(vol, filter_size=3, num_filters=3, padding=1)
        out = v2l.img_pool3d(h, pool_size=2, stride=2)
        got, = _run([out], {"vol": RNG.randn(1, 2, 4, 6, 6)
                            .astype(np.float32)})
        assert got.shape == (1, 3, 2, 3, 3)

    def test_slice_projection(self):
        x = _data("x", [2, 8])
        xs = RNG.randn(2, 8).astype(np.float32)
        got, = _run([v2l.slice_projection(x, [(0, 2), (5, 8)])],
                    {"x": xs})
        want = np.concatenate([xs[:, 0:2], xs[:, 5:8]], axis=1)
        np.testing.assert_allclose(got, want, rtol=1e-6)

    def test_priorbox(self):
        feat = _data("feat", [1, 4, 3, 3])
        img = _data("img", [1, 3, 24, 24])
        boxes, variances = v2l.priorbox(
            feat, img, min_size=[8.0], max_size=[16.0],
            aspect_ratio=[2.0], variance=[0.1, 0.1, 0.2, 0.2])
        b, v = _run([boxes, variances],
                    {"feat": RNG.randn(1, 4, 3, 3).astype(np.float32),
                     "img": RNG.randn(1, 3, 24, 24).astype(np.float32)})
        assert b.shape[-1] == 4 and b.shape == v.shape
        # centers are normalized to the image; corners of edge priors may
        # poke outside [0,1] (clip=False default, like the reference)
        assert np.isfinite(b).all()
        centers_x = (b[..., 0] + b[..., 2]) / 2
        assert np.all(centers_x >= 0.0) and np.all(centers_x <= 1.0)

    def test_pooling_accepts_agg_level_default(self):
        from paddle_tpu.executor import LoDTensor
        x = fluid.layers.data(name="x", shape=[3], dtype="float32",
                              lod_level=1)
        out = v2l.pooling(x, "sum",
                          agg_level=v2l.AggregateLevel.TO_NO_SEQUENCE)
        rows = np.arange(12, dtype=np.float32).reshape(4, 3)
        exe = fluid.Executor(fluid.CPUPlace())
        with executor_mod.scope_guard(executor_mod.Scope()):
            exe.run(fluid.default_startup_program())
            got, = exe.run(feed={"x": LoDTensor(rows, [[0, 2, 4]])},
                           fetch_list=[out])
        np.testing.assert_allclose(
            np.asarray(got), np.stack([rows[:2].sum(0), rows[2:].sum(0)]))
