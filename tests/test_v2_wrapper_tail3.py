"""Third r5 v2 tranche: Print/printer, crop, switch_order,
AggregateLevel/ExpandLevel markers, ThreadPool-backed reader path."""

import numpy as np
import pytest

import paddle_tpu as fluid
from paddle_tpu import executor as executor_mod
from paddle_tpu.v2 import layer as v2l


def _run(fetch, feed):
    exe = fluid.Executor(fluid.CPUPlace())
    with executor_mod.scope_guard(executor_mod.Scope()):
        exe.run(fluid.default_startup_program())
        outs = exe.run(feed=feed, fetch_list=list(fetch))
    return [np.asarray(o) for o in outs]


def _data(name, shape, dtype="float32"):
    return fluid.layers.data(name=name, shape=shape, dtype=dtype,
                             append_batch_size=False)


RNG = np.random.RandomState(21)


class TestTrancheThree:
    def test_fluid_print_passes_through_and_logs(self, capfd):
        x = _data("x", [2, 3])
        out = fluid.layers.Print(x, message="dbg: ", summarize=2)
        s = fluid.layers.reduce_sum(out)
        xs = np.ones((2, 3), np.float32)
        got, = _run([s], {"x": xs})
        assert float(got.ravel()[0]) == 6.0
        logged = capfd.readouterr().out
        assert "dbg: " in logged and "shape=(2, 3)" in logged

    def test_v2_printer_alias(self):
        x = _data("x", [2, 2])
        out = v2l.printer(x, message="p: ")
        got, = _run([out], {"x": np.eye(2, dtype=np.float32)})
        np.testing.assert_allclose(got, np.eye(2))
        assert v2l.print_ is v2l.printer

    def test_crop(self):
        img = _data("img", [2, 3, 6, 8])
        out = v2l.crop(img, shape=[4, 5], offset=[1, 2], axis=2)
        xs = RNG.randn(2, 3, 6, 8).astype(np.float32)
        got, = _run([out], {"img": xs})
        np.testing.assert_allclose(got, xs[:, :, 1:5, 2:7], rtol=1e-6)

    def test_switch_order_nchw_to_nhwc(self):
        img = _data("img", [2, 3, 4, 5])
        out = v2l.switch_order(img, order=[2, 3, 1])
        xs = RNG.randn(2, 3, 4, 5).astype(np.float32)
        got, = _run([out], {"img": xs})
        np.testing.assert_allclose(got, xs.transpose(0, 2, 3, 1),
                                   rtol=1e-6)

    def test_aggregate_and_expand_levels(self):
        assert v2l.AggregateLevel.TO_NO_SEQUENCE == "non-seq"
        assert v2l.AggregateLevel.EACH_SEQUENCE == "seq"
        assert v2l.ExpandLevel.FROM_NO_SEQUENCE == "non-seq"
        with pytest.raises(ValueError):
            v2l.pooling(None, agg_level=v2l.AggregateLevel.TO_SEQUENCE)

    def test_pooling_accepts_agg_level_default(self):
        from paddle_tpu.executor import LoDTensor
        x = fluid.layers.data(name="x", shape=[3], dtype="float32",
                              lod_level=1)
        out = v2l.pooling(x, "sum",
                          agg_level=v2l.AggregateLevel.TO_NO_SEQUENCE)
        rows = np.arange(12, dtype=np.float32).reshape(4, 3)
        exe = fluid.Executor(fluid.CPUPlace())
        with executor_mod.scope_guard(executor_mod.Scope()):
            exe.run(fluid.default_startup_program())
            got, = exe.run(feed={"x": LoDTensor(rows, [[0, 2, 4]])},
                           fetch_list=[out])
        np.testing.assert_allclose(
            np.asarray(got), np.stack([rows[:2].sum(0), rows[2:].sum(0)]))
