"""v2 beam_search + GeneratedInput (reference RecurrentGradientMachine
generation mode, RecurrentGradientMachine.h:73-150, surfaced as v2
beam_search): a memory-carrying decoder generated with beam_size=1 must
reproduce a numpy greedy rollout of the same parameters exactly, and a
wide beam must behave like the fluid beam ops (sorted lanes, bos
bootstrap)."""

import numpy as np

import paddle_tpu as fluid
from paddle_tpu import executor as executor_mod
from paddle_tpu.v2 import layer as v2l

V, H, E = 12, 6, 5
BOS, EOS = 0, 1
MAX_LEN = 4


def _build(beam_size):
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        enc = fluid.layers.data(name="enc", shape=[H], dtype="float32")

        def step(gen_emb, enc_static):
            prev = v2l.memory("h", boot_layer=enc_static)     # [B, K, H]
            dec_in = fluid.layers.concat([gen_emb, prev], axis=-1)
            h = v2l.fc(dec_in, size=H, act="tanh", num_flatten_dims=2,
                       name="h", param_attr="dw", bias_attr="db")
            logits = v2l.fc(h, size=V, num_flatten_dims=2,
                            param_attr="ow", bias_attr="ob")
            return fluid.layers.softmax(logits)

        sentences, scores = v2l.beam_search(
            step,
            input=[v2l.GeneratedInput(size=V, embedding_name="gen_emb_w",
                                      embedding_size=E),
                   v2l.StaticInput(enc)],
            bos_id=BOS, eos_id=EOS, beam_size=beam_size,
            max_length=MAX_LEN)
    return main, startup, sentences, scores


def _params(scope):
    names = ("gen_emb_w", "dw", "db", "ow", "ob")
    return {n: np.asarray(scope.find_var(n)) for n in names}


def _greedy_oracle(enc_row, p):
    """numpy rollout of the same decoder, argmax at each step."""
    h = enc_row.copy()    # boot passes through expand/assign unchanged
    tok = BOS
    toks = [BOS]
    for _ in range(MAX_LEN):
        e = p["gen_emb_w"][tok]
        dec_in = np.concatenate([e, h])
        h = np.tanh(dec_in @ p["dw"] + p["db"].reshape(-1))
        logits = h @ p["ow"] + p["ob"].reshape(-1)
        probs = np.exp(logits - logits.max())
        probs = probs / probs.sum()
        tok = int(np.argmax(np.log(np.clip(probs, 1e-12, 1.0))))
        toks.append(tok)
        if tok == EOS:
            break
    return toks


def test_beam1_matches_greedy_oracle():
    main, startup, sentences, scores = _build(beam_size=1)
    exe = fluid.Executor(fluid.CPUPlace())
    sc = executor_mod.Scope()
    rng = np.random.RandomState(5)
    encs = rng.randn(3, H).astype(np.float32)
    with executor_mod.scope_guard(sc):
        exe.run(startup)
        p = _params(sc)
        out_ids, out_scores = exe.run(main, feed={"enc": encs},
                                      fetch_list=[sentences, scores])
    out_ids = np.asarray(out_ids)
    assert out_ids.shape[0] == 3 and out_ids.shape[1] == 1
    for b in range(3):
        want = _greedy_oracle(encs[b].astype(np.float64), p)
        got = list(out_ids[b, 0, :len(want)])
        assert got == want, (b, got, want)


def test_reference_input_order_and_num_results():
    """Reference-ordered input=[StaticInput, GeneratedInput] must call
    step(static, gen_emb) — positional substitution like the reference's
    __real_step__ — and num_results_per_sample slices the lanes."""
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        enc = fluid.layers.data(name="enc", shape=[H], dtype="float32")

        def step(enc_static, gen_emb):       # static FIRST, like the ref
            assert enc_static.shape[-1] == H
            assert gen_emb.shape[-1] == E
            prev = v2l.memory("h", boot_layer=enc_static)
            dec_in = fluid.layers.concat([gen_emb, prev], axis=-1)
            h = v2l.fc(dec_in, size=H, act="tanh", num_flatten_dims=2,
                       name="h", param_attr="dw", bias_attr="db")
            return fluid.layers.softmax(
                v2l.fc(h, size=V, num_flatten_dims=2, param_attr="ow",
                       bias_attr="ob"))

        sentences, scores = v2l.beam_search(
            step,
            input=[v2l.StaticInput(enc),
                   v2l.GeneratedInput(size=V, embedding_name="gen_emb_w",
                                      embedding_size=E)],
            bos_id=BOS, eos_id=EOS, beam_size=4,
            num_results_per_sample=2, max_length=3)
    exe = fluid.Executor(fluid.CPUPlace())
    with executor_mod.scope_guard(executor_mod.Scope()):
        exe.run(startup)
        out_ids, out_scores = exe.run(
            main,
            feed={"enc": np.random.RandomState(9).randn(3, H)
                  .astype(np.float32)},
            fetch_list=[sentences, scores])
    out_ids = np.asarray(out_ids)
    assert out_ids.shape[:2] == (3, 2)       # lanes sliced to 2 of 4
    assert (out_ids[:, :, 0] == BOS).all()


def test_generated_input_requires_embedding_name():
    import pytest
    with pytest.raises(ValueError, match="embedding_name"):
        v2l.GeneratedInput(size=V, embedding_size=E)


def test_all_lanes_eos_stops_cleanly():
    """With the output head rigged so eos dominates, generation must
    stop after one emission (all lanes finished -> cond false) and the
    best hypothesis is exactly [BOS, EOS, ...]."""
    main, startup, sentences, scores = _build(beam_size=2)
    exe = fluid.Executor(fluid.CPUPlace())
    sc = executor_mod.Scope()
    rng = np.random.RandomState(3)
    encs = rng.randn(2, H).astype(np.float32)
    with executor_mod.scope_guard(sc):
        exe.run(startup)
        ob = np.asarray(sc.find_var("ob")).copy()
        ob[..., EOS] = 25.0                  # eos wins every step
        sc.set_var("ob", ob)
        out_ids, _ = exe.run(main, feed={"enc": encs},
                             fetch_list=[sentences, scores])
    out_ids = np.asarray(out_ids)
    assert (out_ids[:, 0, 0] == BOS).all()
    assert (out_ids[:, 0, 1] == EOS).all()
    # nothing generated past eos: remaining slots are eos padding
    assert (out_ids[:, 0, 2:] == EOS).all()


def test_wide_beam_lanes_sorted_and_bootstrapped():
    main, startup, sentences, scores = _build(beam_size=4)
    exe = fluid.Executor(fluid.CPUPlace())
    rng = np.random.RandomState(7)
    encs = rng.randn(2, H).astype(np.float32)
    with executor_mod.scope_guard(executor_mod.Scope()):
        exe.run(startup)
        out_ids, out_scores = exe.run(main, feed={"enc": encs},
                                      fetch_list=[sentences, scores])
    out_ids = np.asarray(out_ids)
    out_scores = np.asarray(out_scores)
    assert out_ids.shape[:2] == (2, 4)
    assert (out_ids[:, :, 0] == BOS).all()
    assert (np.diff(out_scores, axis=1) <= 1e-5).all()
    assert (out_ids >= 0).all() and (out_ids < V).all()
