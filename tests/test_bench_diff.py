"""bench_diff regression gate (ISSUE 16 satellite): direction
classification, threshold behaviour, drift reporting, exit codes."""

import json
import subprocess
import sys
from pathlib import Path

import pytest

sys.path.insert(0, str(Path(__file__).resolve().parent.parent))
from tools import bench_diff  # noqa: E402


def _payload(parsed):
    return {"benchmark": "serving", "parsed": parsed}


def test_direction_vocabulary():
    assert bench_diff.direction("overload.p99_ms") == "lower"
    assert bench_diff.direction("shed_fraction") == "lower"
    assert bench_diff.direction("compile_seconds") == "lower"
    assert bench_diff.direction("value") == "higher"
    assert bench_diff.direction("normal.qps") == "higher"
    assert bench_diff.direction("mfu_nominal") == "higher"
    assert bench_diff.direction("bucket_hits.b4") == "higher"
    # lower-better wins when both match (timeout_hits would be absurd,
    # but the order must be deterministic)
    assert bench_diff.direction("timeout_hit") == "lower"
    assert bench_diff.direction("rows") is None


def test_regression_and_improvement_classification():
    old = _payload({"value": 100.0, "p99_ms": 10.0,
                    "normal": {"qps": 50.0}})
    new = _payload({"value": 80.0,       # throughput down 20%: regress
                    "p99_ms": 8.0,       # latency down 20%: improve
                    "normal": {"qps": 51.0}})  # +2%: under threshold
    reg, imp, drift = bench_diff.diff(old, new, threshold=0.05)
    assert [e["key"] for e in reg] == ["value"]
    assert reg[0]["change"] == pytest.approx(-0.2)
    assert [e["key"] for e in imp] == ["p99_ms"]
    assert imp[0]["change"] == pytest.approx(0.2)
    assert drift == []


def test_lower_better_regression_direction():
    old = _payload({"p99_ms": 10.0})
    new = _payload({"p99_ms": 15.0})
    reg, imp, _ = bench_diff.diff(old, new)
    assert [e["key"] for e in reg] == ["p99_ms"]
    assert reg[0]["change"] == pytest.approx(-0.5)
    assert imp == []


def test_threshold_gates_regressions():
    old = _payload({"value": 100.0})
    new = _payload({"value": 92.0})
    reg, _, _ = bench_diff.diff(old, new, threshold=0.05)
    assert len(reg) == 1
    reg, _, _ = bench_diff.diff(old, new, threshold=0.10)
    assert reg == []


def test_one_sided_keys_are_drift_not_failures():
    old = _payload({"value": 100.0, "old_only_ms": 5.0})
    new = _payload({"value": 100.0, "new_only_qps": 7.0})
    reg, imp, drift = bench_diff.diff(old, new)
    assert reg == [] and imp == []
    assert drift == ["new_only_qps", "old_only_ms"]


def test_uncompared_and_bool_keys_ignored():
    old = _payload({"rows": 100.0, "ok": True})
    new = _payload({"rows": 1.0, "ok": False})
    reg, imp, drift = bench_diff.diff(old, new)
    assert reg == [] and imp == []


def _write(tmp_path, name, parsed):
    p = tmp_path / name
    p.write_text(json.dumps(_payload(parsed)))
    return str(p)


def test_main_exit_codes(tmp_path, capsys):
    clean_old = _write(tmp_path, "a.json", {"value": 100.0})
    clean_new = _write(tmp_path, "b.json", {"value": 101.0})
    assert bench_diff.main([clean_old, clean_new]) == 0
    assert "bench diff ok" in capsys.readouterr().out

    bad_new = _write(tmp_path, "c.json", {"value": 50.0})
    assert bench_diff.main([clean_old, bad_new]) == 1
    out = capsys.readouterr().out
    assert "REGRESSION value" in out
    # the same delta passes with a huge threshold
    assert bench_diff.main(
        [clean_old, bad_new, "--threshold", "0.9"]) == 0
    capsys.readouterr()

    missing = str(tmp_path / "nope.json")
    assert bench_diff.main([clean_old, missing]) == 2

    garbage = tmp_path / "junk.json"
    garbage.write_text("{not json")
    assert bench_diff.main([clean_old, str(garbage)]) == 2


def test_main_json_output(tmp_path, capsys):
    old = _write(tmp_path, "a.json", {"p99_ms": 10.0, "extra": 1.0})
    new = _write(tmp_path, "b.json", {"p99_ms": 20.0})
    rc = bench_diff.main([old, new, "--json"])
    assert rc == 1
    doc = json.loads(capsys.readouterr().out)
    assert doc["regressions"][0]["key"] == "p99_ms"
    assert doc["drift"] == ["extra"]


# --- --history ledger mode (ISSUE 17 satellite) ------------------------------

def _history(tmp_path, rows):
    p = tmp_path / "BENCH_HISTORY.jsonl"
    p.write_text("".join(json.dumps(r) + "\n" for r in rows))
    return str(p)


def _entry(mode, value, p99=10.0, **extra):
    return {"ts": 1.0, "git_sha": "abc", "mode": mode,
            "family": mode.partition("_")[0], "value": value,
            "p99_ms": p99, **extra}


def test_history_flat_ledger_exits_zero(tmp_path, capsys):
    path = _history(tmp_path, [
        _entry("fc", 100.0), _entry("fc", 101.0), _entry("fc", 100.5),
        _entry("resnet", 50.0), _entry("resnet", 50.2),
    ])
    assert bench_diff.main(["--history", path]) == 0
    assert "bench history ok (2 groups compared)" in \
        capsys.readouterr().out


def test_history_planted_regression_exits_nonzero(tmp_path, capsys):
    path = _history(tmp_path, [
        _entry("fc", 100.0), _entry("fc", 101.0),
        _entry("fc", 70.0, p99=19.0),   # value -30%, p99 +89%
    ])
    assert bench_diff.main(["--history", path]) == 1
    out = capsys.readouterr().out
    assert "REGRESSION fc/fc value" in out
    assert "REGRESSION fc/fc p99_ms" in out


def test_history_compares_median_not_last(tmp_path):
    # priors 100, 100, 900 (one wild outlier): median 100, so the
    # newest 98 is within threshold — mean-based gating would fail it
    path = _history(tmp_path, [
        _entry("fc", 100.0), _entry("fc", 100.0), _entry("fc", 900.0),
        _entry("fc", 98.0),
    ])
    assert bench_diff.main(["--history", path]) == 0


def test_history_single_entry_group_skipped(tmp_path, capsys):
    path = _history(tmp_path, [_entry("fc", 100.0)])
    assert bench_diff.main(["--history", path]) == 0
    assert "0 groups compared" in capsys.readouterr().out


def test_history_groups_isolated_by_mode(tmp_path):
    # fc_infer's 30 must not be compared against fc's 100s
    path = _history(tmp_path, [
        _entry("fc", 100.0), _entry("fc", 101.0),
        _entry("fc_infer", 31.0), _entry("fc_infer", 30.0),
    ])
    assert bench_diff.main(["--history", path]) == 0


def test_history_groups_isolated_by_precision_variant(tmp_path, capsys):
    """An O3 (quantized) or int8-serving line is a different configuration,
    not a regression of its f32 sibling — on XLA:CPU int8 is *slower* than
    bf16, so without variant grouping every quantized line would gate red."""
    path = _history(tmp_path, [
        _entry("fc", 100.0, amp_level="O2"),
        _entry("fc", 101.0, amp_level="O2"),
        _entry("fc", 48.0, amp_level="O3"),      # half speed: OK, own group
        _entry("fc", 49.0, amp_level="O3"),
        _entry("serving", 50.0), _entry("serving", 51.0),
        _entry("serving", 24.0, quant="int8"),
        _entry("serving", 25.0, quant="int8"),
    ])
    assert bench_diff.main(["--history", path]) == 0
    assert "4 groups compared" in capsys.readouterr().out
    # ...but a real regression inside a variant group still gates
    path = _history(tmp_path, [
        _entry("fc", 48.0, amp_level="O3"),
        _entry("fc", 30.0, amp_level="O3"),
    ])
    assert bench_diff.main(["--history", path]) == 1
    assert "REGRESSION fc[O3]/fc value" in capsys.readouterr().out


def test_history_quant_fallbacks_lower_better():
    assert bench_diff.direction("quant_fallbacks") == "lower"
    assert bench_diff.direction("quant_hits") == "higher"


def test_history_meta_keys_not_compared(tmp_path):
    rows = [_entry("fc", 100.0), _entry("fc", 100.0)]
    rows[-1]["ts"] = 9_999.0          # wildly different timestamp
    rows[-1]["git_sha"] = "zzz"
    path = _history(tmp_path, rows)
    assert bench_diff.main(["--history", path]) == 0


def test_history_threshold_and_json(tmp_path, capsys):
    path = _history(tmp_path, [
        _entry("fc", 100.0), _entry("fc", 92.0)])
    assert bench_diff.main(["--history", path, "--threshold", "0.10",
                            "--json"]) == 0
    doc = json.loads(capsys.readouterr().out)
    assert doc["regressions"] == []
    assert doc["groups"] == [{"mode": "fc", "family": "fc",
                              "entries": 2}]
    assert bench_diff.main(["--history", path]) == 1
    capsys.readouterr()


def test_history_missing_or_garbage_exits_two(tmp_path, capsys):
    assert bench_diff.main(
        ["--history", str(tmp_path / "nope.jsonl")]) == 2
    garbage = tmp_path / "junk.jsonl"
    garbage.write_text("{not json\n")
    assert bench_diff.main(["--history", str(garbage)]) == 2


def test_two_file_mode_requires_both_files(capsys):
    assert bench_diff.main([]) == 2


def test_bench_emit_appends_history(tmp_path, monkeypatch, capsys):
    """bench._emit must append its JSON line (plus the driver-passed
    git sha/timestamp meta) to the ledger, and a write failure must
    never kill the bench line."""
    import importlib.util
    spec = importlib.util.spec_from_file_location(
        "bench_history_under_test",
        str(Path(__file__).resolve().parent.parent / "bench.py"))
    bench = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(bench)

    ledger = tmp_path / "hist.jsonl"
    monkeypatch.setenv("BENCH_HISTORY", str(ledger))
    monkeypatch.setenv("BENCH_MODE", "fc")
    monkeypatch.setenv("BENCH_GIT_SHA", "deadbeef")
    monkeypatch.setenv("BENCH_TS", "1234.5")
    bench._emit({"metric": "fc", "value": None, "unit": None})
    capsys.readouterr()
    rec = json.loads(ledger.read_text().strip())
    assert rec["git_sha"] == "deadbeef" and rec["ts"] == 1234.5
    assert rec["mode"] == "fc" and rec["family"] == "fc"
    assert rec["metric"] == "fc"

    # disabled: no write
    monkeypatch.setenv("BENCH_HISTORY", "0")
    bench._emit({"metric": "fc", "value": None, "unit": None})
    out = capsys.readouterr().out
    assert json.loads(out.strip().splitlines()[-1])["metric"] == "fc"
    assert len(ledger.read_text().strip().splitlines()) == 1

    # unwritable path: the bench line still comes out
    monkeypatch.setenv("BENCH_HISTORY", str(tmp_path / "no" / "dir.jsonl"))
    bench._emit({"metric": "fc", "value": None, "unit": None})
    assert json.loads(
        capsys.readouterr().out.strip().splitlines()[-1])["metric"] == "fc"


def test_cli_subprocess(tmp_path):
    old = _write(tmp_path, "a.json", {"value": 100.0})
    new = _write(tmp_path, "b.json", {"value": 100.0})
    proc = subprocess.run(
        [sys.executable, "tools/bench_diff.py", old, new],
        cwd=str(Path(__file__).resolve().parent.parent),
        capture_output=True, text=True, timeout=60)
    assert proc.returncode == 0
    assert "bench diff ok" in proc.stdout
