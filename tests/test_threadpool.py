"""Framework ThreadPool (reference framework/threadpool.h:33-101): Run's
future re-raises, RunAndGetException's future returns the exception,
Wait drains, daemon workers never pin the interpreter, and
reader.xmap_readers runs on it."""

import threading
import time

import numpy as np
import pytest

from paddle_tpu.threadpool import ThreadPool, get_instance


class TestThreadPool:
    def test_run_result_and_reraise(self):
        pool = ThreadPool(2)
        assert pool.threads() == 2
        f = pool.run(lambda a, b: a + b, 2, 3)
        assert f.result(timeout=10) == 5

        def boom():
            raise ValueError("inside pool")

        with pytest.raises(ValueError):
            pool.run(boom).result(timeout=10)
        pool.shutdown()

    def test_run_and_get_exception_contract(self):
        pool = ThreadPool(1)

        def boom():
            raise RuntimeError("handed back")

        exc = pool.run_and_get_exception(boom).result(timeout=10)
        assert isinstance(exc, RuntimeError)
        ok = pool.run_and_get_exception(lambda: None).result(timeout=10)
        assert ok is None
        pool.shutdown()

    def test_wait_drains_all(self):
        pool = ThreadPool(4)
        hits = []
        lock = threading.Lock()

        def task(i):
            time.sleep(0.01)
            with lock:
                hits.append(i)

        for i in range(20):
            pool.run(task, i)
        pool.wait()
        assert sorted(hits) == list(range(20))
        pool.shutdown()

    def test_wait_survives_task_exception(self):
        pool = ThreadPool(2)
        pool.run(lambda: 1 / 0)
        pool.run(time.sleep, 0.01)
        pool.wait()                  # must not raise (reference contract)
        pool.shutdown()

    def test_singleton(self):
        assert get_instance() is get_instance()

    def test_workers_are_daemon(self):
        pool = ThreadPool(1)
        assert all(t.daemon for t in pool._workers)
        pool.shutdown()

    def test_reference_capitalized_aliases(self):
        pool = ThreadPool(1)
        assert pool.Run(lambda: 7).result(timeout=10) == 7
        assert pool.Threads() == 1
        pool.Wait()
        pool.shutdown()


class TestXmapOnPool:
    def test_xmap_readers_still_correct(self):
        from paddle_tpu import reader as reader_mod

        def src():
            yield from range(50)

        out = sorted(reader_mod.xmap_readers(
            lambda x: x * x, src, process_num=4, buffer_size=8)())
        assert out == [i * i for i in range(50)]

    def test_xmap_readers_ordered(self):
        from paddle_tpu import reader as reader_mod

        def src():
            yield from range(30)

        out = list(reader_mod.xmap_readers(
            lambda x: x + 1, src, process_num=4, buffer_size=4,
            order=True)())
        assert out == [i + 1 for i in range(30)]

    def test_run_after_shutdown_raises(self):
        pool = ThreadPool(1)
        pool.shutdown()
        with pytest.raises(RuntimeError, match="shut down"):
            pool.run(lambda: 1)

    def test_source_reader_exception_reraises_in_consumer(self):
        """A dying SOURCE (not just mapper) must fail loudly too."""
        from paddle_tpu import reader as reader_mod

        def bad_src():
            yield 1
            raise IOError("corrupt shard")

        with pytest.raises(IOError, match="corrupt shard"):
            list(reader_mod.xmap_readers(lambda x: x, bad_src,
                                         process_num=2, buffer_size=2)())

    def test_mapper_exception_reraises_in_consumer(self):
        """A bad sample must fail LOUDLY in the consuming thread, not
        stall the pipeline."""
        from paddle_tpu import reader as reader_mod

        def src():
            yield from range(10)

        def bad_mapper(x):
            if x == 5:
                raise ValueError("bad sample 5")
            return x

        with pytest.raises(ValueError, match="bad sample 5"):
            list(reader_mod.xmap_readers(bad_mapper, src, process_num=1,
                                         buffer_size=2)())

    def test_abandoned_reader_does_not_wedge(self):
        """Take a few samples and walk away: the daemon pool + bounded
        queues must not deadlock anything the caller still uses."""
        from paddle_tpu import reader as reader_mod

        def src():
            yield from range(10000)

        it = reader_mod.xmap_readers(lambda x: x, src, process_num=2,
                                     buffer_size=2)()
        got = [next(it) for _ in range(3)]
        assert len(got) == 3
        del it                        # abandoned mid-stream
