"""Communication/compute overlap (parallel/overlap.py, ISSUE 9): the
bucketed eager gradient sync must be BITWISE invisible to numerics, the
bucket plan deterministic, every skip reason counted, the compile-layer
options gated off non-TPU backends, and the auto steps-per-call bounded
by both the amortization and the memory model.

The per-bucket `pd.coll.dp_grad_bucket<i>` sites are pinned through the
synthetic-xplane path (test_fleet's hand-rolled encoder): real compiled
HLO attributes GSPMD's dp-grad all-reduces to the producer grad ops (the
constraint nodes fuse away — see the module docstring caveat), so the
reporting contract is asserted against traces that carry the sites."""

import types

import numpy as np
import pytest

import paddle_tpu as fluid
from paddle_tpu import executor as em
from paddle_tpu import fleet, telemetry
from paddle_tpu.framework import unique_name
from paddle_tpu.parallel import overlap

from test_fleet import (_event, _line, _meta, _plane,  # noqa: F401
                        _write_xspace, pinned_ici)


@pytest.fixture(autouse=True)
def _fresh(monkeypatch):
    telemetry.reset()
    old = overlap.OVERLAP_OPT
    yield
    overlap.OVERLAP_OPT = old
    overlap._PLANS.clear()
    telemetry.reset()


def _with_overlap(on, fn, *args, **kw):
    """Run fn under OVERLAP_OPT=on. Callers build a FRESH program inside
    fn — the jit and plan caches key on program identity."""
    old = overlap.OVERLAP_OPT
    overlap.OVERLAP_OPT = on
    try:
        return fn(*args, **kw)
    finally:
        overlap.OVERLAP_OPT = old


def _fallbacks(reason=None):
    series = telemetry.read_series("overlap_fallback_total")
    if reason is None:
        return sum(series.values())
    return sum(v for k, v in series.items() if f"reason={reason}" in k)


def _state(scope):
    return {n: np.asarray(scope.find_var(n))
            for n in scope.local_var_names()
            if isinstance(scope.find_var(n), np.ndarray)
            or hasattr(scope.find_var(n), "dtype")}


def _assert_state_equal(a, b):
    assert set(a) == set(b), set(a) ^ set(b)
    for n in sorted(a):
        np.testing.assert_array_equal(np.asarray(a[n]), np.asarray(b[n]),
                                      err_msg=f"state '{n}' diverged")


def _build_fc(main, startup):
    x = fluid.layers.data(name="x", shape=[12], dtype="float32")
    label = fluid.layers.data(name="label", shape=[1], dtype="int64")
    h = fluid.layers.fc(input=x, size=16, act="relu")
    logits = fluid.layers.fc(input=h, size=4)
    loss = fluid.layers.mean(
        fluid.layers.softmax_with_cross_entropy(logits, label))
    feed = lambda rng: {                                    # noqa: E731
        "x": rng.standard_normal((8, 12)).astype(np.float32),
        "label": rng.integers(0, 4, (8, 1)).astype(np.int64)}
    return loss, feed


def _build_conv(main, startup):
    img = fluid.layers.data(name="img", shape=[3, 8, 8], dtype="float32")
    label = fluid.layers.data(name="label", shape=[1], dtype="int64")
    c = fluid.layers.conv2d(input=img, num_filters=4, filter_size=3,
                            padding=1)
    p = fluid.layers.pool2d(input=c, global_pooling=True, pool_type="avg")
    logits = fluid.layers.fc(input=p, size=4)
    loss = fluid.layers.mean(
        fluid.layers.softmax_with_cross_entropy(logits, label))
    feed = lambda rng: {                                    # noqa: E731
        "img": rng.standard_normal((8, 3, 8, 8)).astype(np.float32),
        "label": rng.integers(0, 4, (8, 1)).astype(np.int64)}
    return loss, feed


def _train(build, ndev, steps=3):
    """Fresh program each call; dp mesh over the first ndev devices."""
    import jax
    from jax.sharding import Mesh

    unique_name.switch()
    main, startup = fluid.Program(), fluid.Program()
    main.random_seed = startup.random_seed = 11
    with fluid.program_guard(main, startup):
        loss, make_feed = build(main, startup)
        fluid.optimizer.Momentum(learning_rate=0.05, momentum=0.9).minimize(
            loss, startup_program=startup)
    main._mesh = Mesh(np.array(jax.devices()[:ndev]), ("dp",))
    exe = fluid.Executor(fluid.CPUPlace())
    rng = np.random.default_rng(3)
    scope = em.Scope()
    losses = []
    with em.scope_guard(scope):
        exe.run(startup)
        for _ in range(steps):
            out, = exe.run(main, feed=make_feed(rng), fetch_list=[loss])
            losses.append(float(np.ravel(out)[0]))
        state = _state(scope)
    return losses, state


@pytest.mark.parametrize("build", [_build_fc, _build_conv],
                         ids=["fc", "conv"])
@pytest.mark.parametrize("ndev", [1, 8])
def test_training_parity_bitwise(build, ndev, monkeypatch):
    """The eager bucket flush is a pure sharding annotation: losses AND
    full optimizer state bitwise equal with the pass on vs off, single
    device and across the dp mesh — with the cap shrunk so even these
    KB-sized models split into several buckets."""
    monkeypatch.setenv("PADDLE_TPU_OVERLAP_BUCKET_MB", "0.0001")
    l1, s1 = _with_overlap(True, _train, build, ndev)
    l0, s0 = _with_overlap(False, _train, build, ndev)
    assert l1 == l0
    _assert_state_equal(s1, s0)
    # the overlapped run actually flushed buckets (not a vacuous pass)
    assert sum(telemetry.read_series("overlap_buckets_total").values()) > 0


class TestPlan:
    def _program(self):
        import jax
        from jax.sharding import Mesh

        unique_name.switch()
        main, startup = fluid.Program(), fluid.Program()
        with fluid.program_guard(main, startup):
            loss, _ = _build_fc(main, startup)
            fluid.optimizer.SGD(learning_rate=0.1).minimize(
                loss, startup_program=startup)
        main._mesh = Mesh(np.array(jax.devices()[:2]), ("dp",))
        return main

    def test_deterministic_and_readiness_ordered(self, monkeypatch):
        monkeypatch.setenv("PADDLE_TPU_OVERLAP_BUCKET_MB", "0.0001")
        prog = self._program()
        a, b = overlap._build(prog), overlap._build(prog)
        assert [x.grads for x in a.buckets] == [x.grads for x in b.buckets]
        assert [x.site for x in a.buckets] == [x.site for x in b.buckets]
        # sites numbered in flush (anchor) order
        assert a.sites == [f"dp_grad_bucket{i}"
                           for i in range(len(a.buckets))]
        anchors = [x.anchor for x in a.buckets]
        assert anchors == sorted(anchors)
        # tiny cap: the 4 param grads (2 fc layers) split across buckets
        assert len(a.buckets) >= 2

    def test_plan_cached_per_program(self):
        prog = self._program()
        assert overlap.plan(prog) is overlap.plan(prog)

    def test_no_plan_without_dp_mesh(self):
        unique_name.switch()
        main, startup = fluid.Program(), fluid.Program()
        with fluid.program_guard(main, startup):
            loss, _ = _build_fc(main, startup)
            fluid.optimizer.SGD(learning_rate=0.1).minimize(
                loss, startup_program=startup)
        assert overlap.plan(main) is None          # no mesh at all

    def test_gate_off_no_plan(self):
        prog = self._program()
        assert _with_overlap(False, overlap.plan, prog) is None

    def test_tp_sharded_param_falls_back(self):
        """Model-parallel (tensor-sharded) grads hold different values
        per shard — no cross-dp sum to schedule, counted tp_sharded."""
        prog = self._program()
        some_param = prog.global_block().all_parameters()[0].name
        prog._param_shardings = {some_param: (None, "mp")}
        p = overlap._build(prog)
        assert _fallbacks("tp_sharded") == 1
        assert all(some_param not in b.params for b in p.buckets)

    def test_unknown_axis_param_keeps_sharded_param_reason(self):
        """A spec naming an axis this mesh lacks can't be pinned — the
        historical sharded_param reason stays for dashboards."""
        prog = self._program()
        some_param = prog.global_block().all_parameters()[0].name
        prog._param_shardings = {some_param: ("fsdp", None)}  # dp-only mesh
        p = overlap._build(prog)
        assert _fallbacks("sharded_param") == 1
        assert all(some_param not in b.params for b in p.buckets)

    def test_dp_sharded_param_buckets_per_spec_group(self):
        """ISSUE 15: a ZeRO/dp-sharded param no longer skips — its grad
        buckets in its OWN (dtype, spec) group, never mixed with
        replicated grads, and the bucket records the spec to pin to."""
        prog = self._program()
        some_param = prog.global_block().all_parameters()[0].name
        prog._param_shardings = {some_param: ("dp", None)}
        p = overlap._build(prog)
        assert _fallbacks("sharded_param") == 0
        assert _fallbacks("tp_sharded") == 0
        with_param = [b for b in p.buckets if some_param in b.params]
        assert len(with_param) == 1
        assert with_param[0].spec == ("dp",)
        # replicated grads keep the empty spec and never share a bucket
        for b in p.buckets:
            if some_param not in b.params:
                assert b.spec == ()


class TestFlushFallbacks:
    def _ctx(self):
        import jax
        from jax.sharding import Mesh

        prog = types.SimpleNamespace(
            _mesh=Mesh(np.array(jax.devices()[:2]), ("dp",)))
        return types.SimpleNamespace(program=prog)

    def test_sparse_grad_keeps_selected_rows(self):
        from paddle_tpu.ops.common import SelectedRowsVal
        import jax.numpy as jnp

        sr = SelectedRowsVal(rows=jnp.array([0, 1], jnp.int32),
                             values=jnp.ones((2, 3), jnp.float32),
                             height=5)
        env = {"emb@GRAD": sr}
        b = overlap.Bucket(index=0, params=("emb",), grads=("emb@GRAD",),
                           dtype="float32", bytes=24, anchor=0)
        overlap._flush(self._ctx(), b, env)
        assert env["emb@GRAD"] is sr               # untouched
        # no optimizer consumer is known for this synthetic program, so
        # the refined reason is "unsupported" (a real sgd/momentum/adam
        # consumer would count sparse_grad_handled instead)
        assert _fallbacks("sparse_grad_unsupported") == 1

    def test_missing_grad_counted(self):
        b = overlap.Bucket(index=0, params=("w",), grads=("w@GRAD",),
                           dtype="float32", bytes=4, anchor=0)
        overlap._flush(self._ctx(), b, {})
        assert _fallbacks("missing_grad") == 1


class TestCompilerOptions:
    def test_cpu_backend_counts_platform(self):
        import jax
        assert jax.default_backend() != "tpu"      # test-suite invariant
        assert overlap.compiler_options(
            types.SimpleNamespace(_mesh=object())) is None
        assert _fallbacks("platform") == 1

    def test_no_mesh_no_options(self):
        assert overlap.compiler_options(
            types.SimpleNamespace(_mesh=None)) is None
        assert _fallbacks() == 0                    # silent: nothing to do

    def test_gate_off_no_options(self):
        assert _with_overlap(
            False, overlap.compiler_options,
            types.SimpleNamespace(_mesh=object())) is None
        assert _fallbacks() == 0

    def test_env_override_rejected_by_probe(self, monkeypatch):
        monkeypatch.setenv("PADDLE_TPU_OVERLAP_XLA_FLAGS",
                           "xla_definitely_not_an_option_zzz=true")
        overlap._VALIDATED.clear()
        try:
            assert overlap.compiler_options(
                types.SimpleNamespace(_mesh=object())) is None
            assert _fallbacks("rejected_options") == 1
        finally:
            overlap._VALIDATED.clear()
        # the verdict is cached: a second ask does not re-probe but still
        # counts the fallback
        overlap._VALIDATED[(
            ("xla_definitely_not_an_option_zzz", "true"),)] = False
        assert overlap.compiler_options(
            types.SimpleNamespace(_mesh=object())) is None
        assert _fallbacks("rejected_options") == 2
        overlap._VALIDATED.clear()

    def test_env_override_bypasses_platform_gate(self, monkeypatch):
        """A validated env-provided set is returned even off-TPU (the
        escape hatch for flag experiments on any backend)."""
        monkeypatch.setenv("PADDLE_TPU_OVERLAP_XLA_FLAGS",
                           "xla_k=v, xla_k2")
        monkeypatch.setattr(overlap, "_validate", lambda opts: True)
        assert overlap.compiler_options(
            types.SimpleNamespace(_mesh=object())) == {
            "xla_k": "v", "xla_k2": "true"}

    def test_empty_env_override_disables(self, monkeypatch):
        monkeypatch.setenv("PADDLE_TPU_OVERLAP_XLA_FLAGS", "")
        assert overlap.compiler_options(
            types.SimpleNamespace(_mesh=object())) is None
        assert _fallbacks() == 0

    def test_probe_accepts_empty_options(self):
        assert overlap._validate({}) is True


class TestChooseStepsPerCall:
    def test_no_signals_means_hi(self):
        assert overlap.choose_steps_per_call() == 64
        assert overlap.choose_steps_per_call(hi=16) == 16

    def test_amortization_ceiling(self):
        # 1ms dispatch over 10ms steps at 2% target -> ceil(1/0.2) = 5
        assert overlap.choose_steps_per_call(
            python_overhead_ms=1.0, step_time_ms=10.0) == 5

    def test_memory_cap_shrinks(self):
        # headroom (3MB budget - 1MB fixed) / 1MB per window = 2 < the
        # amortization ask of 5
        mb = 1 << 20
        assert overlap.choose_steps_per_call(
            python_overhead_ms=1.0, step_time_ms=10.0,
            feed_bytes_per_step=mb, peak_bytes=2 * mb,
            budget_bytes=3 * mb) == 2

    def test_clamped_to_bounds(self):
        assert overlap.choose_steps_per_call(
            python_overhead_ms=0.001, step_time_ms=100.0, lo=4) == 4
        assert overlap.choose_steps_per_call(
            python_overhead_ms=100.0, step_time_ms=1.0, hi=8) == 8

    def test_memory_only_bounds_from_hi(self):
        mb = 1 << 20
        assert overlap.choose_steps_per_call(
            feed_bytes_per_step=mb, peak_bytes=2 * mb,
            budget_bytes=12 * mb) == 11


# --- per-bucket sites + exposure through the reporting path -----------------

_HLO_MONO = """\
HloModule jit_step

ENTRY main {
  %p0 = f32[1024,1024]{1,0} parameter(0)
  %all-reduce.1 = f32[2048,1024]{1,0} all-reduce(%p0), channel_id=1, \
replica_groups=[1,4]<=[4], to_apply=%add, \
metadata={op_name="jit(step)/pd.mul_grad/pd.coll.dp_grad/add"}
}
"""

_HLO_BUCKETED = """\
HloModule jit_step

ENTRY main {
  %p0 = f32[1024,1024]{1,0} parameter(0)
  %all-reduce.1 = f32[1024,1024]{1,0} all-reduce(%p0), channel_id=1, \
replica_groups=[1,4]<=[4], to_apply=%add, \
metadata={op_name="jit(step)/pd.fc_grad/pd.coll.dp_grad_bucket0/add"}
  %all-reduce.2 = f32[1024,1024]{1,0} all-reduce(%p0), channel_id=2, \
replica_groups=[1,4]<=[4], to_apply=%add, \
metadata={op_name="jit(step)/pd.conv2d_grad/pd.coll.dp_grad_bucket1/add"}
}
"""


def _write_mono(tmp_path):
    # one monolithic post-backward all-reduce, nothing left to overlap
    # with: 8us, fully exposed
    metas = [_meta(1, "fusion.1"), _meta(2, "all-reduce.1")]
    raw = _line("xla-ops", 0, [
        _event(1, 0, 2_000_000),               # backward: 0..2us
        _event(2, 2_000_000, 8_000_000),       # all-reduce.1: 2..10us
    ])
    d = tmp_path / "mono"
    d.mkdir()
    _write_xspace(d / "t.xplane.pb", [_plane("/device:TPU:0", [raw], metas)])
    return str(d)


def _write_bucketed(tmp_path):
    # same 8us of all-reduce split across two eager buckets: bucket0
    # launches while backward still computes (fully hidden), bucket1
    # trails the last grad op with only 2us exposed
    metas = [_meta(1, "fusion.1"), _meta(2, "all-reduce.1"),
             _meta(3, "all-reduce.2")]
    raw = _line("xla-ops", 0, [
        _event(1, 0, 6_000_000),               # backward: 0..6us
        _event(2, 1_000_000, 4_000_000),       # bucket0: 1..5us, hidden
        _event(3, 6_000_000, 4_000_000),       # bucket1: 6..10us, exposed
    ])
    d = tmp_path / "bucketed"
    d.mkdir()
    _write_xspace(d / "t.xplane.pb", [_plane("/device:TPU:0", [raw], metas)])
    return str(d)


class TestBucketSitesInFleetReport:
    def test_buckets_split_sites_and_cut_exposure(self, tmp_path,
                                                  pinned_ici):
        """The ISSUE 9 acceptance shape: dp-grad collectives appear under
        >= 2 per-bucket sites, and the bucketed schedule's exposed
        fraction beats the monolithic one at equal payload+time."""
        mono = fleet.collective_table(_write_mono(tmp_path), [_HLO_MONO],
                                      steps=1, probe=False)
        buck = fleet.collective_table(_write_bucketed(tmp_path),
                                      [_HLO_BUCKETED], steps=1,
                                      probe=False)
        sites = {r["site"] for r in buck["rows"]}
        assert {"dp_grad_bucket0", "dp_grad_bucket1"} <= sites
        es_m = fleet.exposed_summary(mono)
        es_b = fleet.exposed_summary(buck)
        # identical 8us of collective time in both scenarios...
        assert sum(r["time_ms"] for r in mono["rows"]) == pytest.approx(
            sum(r["time_ms"] for r in buck["rows"]))
        # ...but the bucketed one hides half of it
        assert (es_b["exposed_collective_seconds"]
                < es_m["exposed_collective_seconds"])
        assert es_b["overlap_fraction"] > es_m["overlap_fraction"]
        assert es_m["overlap_fraction"] == pytest.approx(0.0)
        assert es_b["overlap_fraction"] == pytest.approx(0.5)

    def test_exposed_summary_empty_table(self):
        assert fleet.exposed_summary(None) is None
        assert fleet.exposed_summary({"rows": []}) is None


class TestBenchAuto:
    def test_auto_probe_in_process(self):
        """bench._auto_steps_per_call on a real compiled program: returns
        a bounded int and never raises even with partial signals."""
        import bench

        unique_name.switch()
        main, startup = fluid.Program(), fluid.Program()
        with fluid.program_guard(main, startup):
            loss, make_feed = _build_fc(main, startup)
            fluid.optimizer.SGD(learning_rate=0.1).minimize(
                loss, startup_program=startup)
        exe = fluid.Executor(fluid.CPUPlace())
        rng = np.random.default_rng(0)
        feed = make_feed(rng)
        with em.scope_guard(em.Scope()):
            exe.run(startup)

            def run_step():
                out, = exe.run(main, feed=feed, fetch_list=[loss],
                               return_numpy=False)
                return out

            k = bench._auto_steps_per_call(exe, main, run_step, feed,
                                           loss)
        assert isinstance(k, int) and 1 <= k <= 64

    @pytest.mark.slow
    def test_bench_cli_end_to_end(self, tmp_path):
        """`bench.py --families fc --steps-per-call auto` emits a JSON
        line with the resolved integer K and mode=auto."""
        import json
        import os
        import subprocess
        import sys

        repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
        env = dict(os.environ, JAX_PLATFORMS="cpu", BENCH_PERF="0",
                   BENCH_STEPS="2", BENCH_WARMUP="1", BENCH_BATCH="8",
                   BENCH_FC_HIDDEN="32",
                   # skip the session roofline probe: its 4096^3 matmul
                   # warmup costs minutes on shared CI hosts
                   BENCH_ROOFLINE="0")
        r = subprocess.run(
            [sys.executable, os.path.join(repo, "bench.py"),
             "--families", "fc", "--steps-per-call", "auto"],
            capture_output=True, text=True, env=env, timeout=840)
        assert r.returncode == 0, r.stdout + r.stderr[-2000:]
        lines = [json.loads(ln) for ln in r.stdout.splitlines()
                 if ln.startswith("{")]
        fc = [ln for ln in lines if ln.get("steps_per_call_mode")]
        assert fc, lines
        assert fc[0]["steps_per_call_mode"] == "auto"
        assert isinstance(fc[0]["steps_per_call"], int)
        assert 1 <= fc[0]["steps_per_call"] <= 64


class TestExecutorIntegration:
    def test_plan_used_by_trace(self, monkeypatch):
        """End-to-end through Executor.run on the dp mesh: the flush
        counter moves, proving the trace loop consults the plan (not just
        plan() in isolation)."""
        monkeypatch.setenv("PADDLE_TPU_OVERLAP_BUCKET_MB", "0.0001")
        _with_overlap(True, _train, _build_fc, 8, 1)
        series = telemetry.read_series("overlap_buckets_total")
        assert sum(series.values()) >= 2        # >= 2 buckets flushed
