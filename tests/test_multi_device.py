"""Multi-device SPMD tests on the virtual 8-CPU-device mesh (conftest sets
--xla_force_host_platform_device_count=8).

The TPU-native replacement for the reference's multi-device tests
(reference: tests/unittests/test_parallel_op.py — parallel_do vs plain run
parity; nccl_op_test.cu.cc:140 — in-process multi-GPU collectives;
distribute_transpiler tests). Data-parallel here = program._mesh + GSPMD:
feeds sharded over the 'dp' axis, parameters replicated, gradient AllReduce
inserted by XLA over ICI.
"""

import jax
import numpy as np
import pytest

import paddle_tpu as fluid
from paddle_tpu import executor as em
from paddle_tpu import executor as executor_mod
from paddle_tpu.parallel import mesh as mesh_mod

RNG = np.random.default_rng(7)


def _build_mlp(main, startup, seed=321):
    main.random_seed = seed
    startup.random_seed = seed
    with fluid.program_guard(main, startup):
        x = fluid.layers.data(name="x", shape=[16], dtype="float32")
        y = fluid.layers.data(name="y", shape=[1], dtype="int64")
        h = fluid.layers.fc(input=x, size=32, act="relu")
        logits = fluid.layers.fc(input=h, size=4)
        loss = fluid.layers.mean(
            fluid.layers.softmax_with_cross_entropy(logits, y))
        fluid.optimizer.SGD(learning_rate=0.1).minimize(
            loss, startup_program=startup)
    return x, y, loss


def _train(mesh, steps=4, batch=16):
    # reset the name generator so both builds draw identical param names —
    # initializer PRNG streams are keyed on output var names
    from paddle_tpu.framework import unique_name
    unique_name.switch()
    main, startup = fluid.Program(), fluid.Program()
    x, y, loss = _build_mlp(main, startup)
    if mesh is not None:
        main._mesh = mesh
    exe = fluid.Executor(fluid.CPUPlace())
    scope = em.Scope()
    losses = []
    with em.scope_guard(scope):
        exe.run(startup)
        feeds = [(RNG.standard_normal((batch, 16)).astype(np.float32),
                  RNG.integers(0, 4, (batch, 1)).astype(np.int64))
                 for _ in range(steps)]
        for xv, yv in feeds:
            lv, = exe.run(main, feed={"x": xv, "y": yv}, fetch_list=[loss])
            losses.append(float(np.ravel(lv)[0]))
        params = {n: np.asarray(scope.find_var(n))
                  for n in scope.local_var_names()
                  if n.endswith(".w_0") or n.endswith(".b_0")}
    return losses, params


def test_eight_device_parity():
    """8-device SPMD training matches single-device training step for step
    (the test_parallel_op.py pattern: same feeds, compare loss + params)."""
    assert len(jax.devices()) >= 8, "conftest must force 8 host devices"
    global RNG
    RNG = np.random.default_rng(7)
    loss_1, params_1 = _train(mesh=None)
    RNG = np.random.default_rng(7)
    loss_8, params_8 = _train(mesh=mesh_mod.data_parallel_mesh(8))

    np.testing.assert_allclose(loss_1, loss_8, rtol=1e-4, atol=1e-5)
    assert params_1.keys() == params_8.keys() and len(params_1) >= 4
    for n in params_1:
        np.testing.assert_allclose(params_1[n], params_8[n],
                                   rtol=1e-4, atol=1e-5, err_msg=n)


def test_transpiler_driven_run():
    """DistributeTranspiler.transpile tags the program with a dp mesh and
    the executor runs it SPMD — parameters come back replicated across all
    8 devices (the pserver-tier replacement, SURVEY.md §2.5)."""
    main, startup = fluid.Program(), fluid.Program()
    x, y, loss = _build_mlp(main, startup)
    t = fluid.DistributeTranspiler()
    t.transpile(trainer_id=0, program=main, trainers=8)
    assert main._mesh is not None and main._mesh.devices.size == 8
    assert t.get_trainer_program() is main

    exe = fluid.Executor(fluid.CPUPlace())
    scope = em.Scope()
    with em.scope_guard(scope):
        exe.run(startup)
        xv = RNG.standard_normal((16, 16)).astype(np.float32)
        yv = RNG.integers(0, 4, (16, 1)).astype(np.int64)
        lv, = exe.run(main, feed={"x": xv, "y": yv}, fetch_list=[loss])
        assert np.isfinite(np.ravel(lv)).all()
        # updated parameters live on all 8 mesh devices (replicated)
        w = scope.find_var("fc_0.w_0")
        assert isinstance(w, jax.Array)
        assert len(w.sharding.device_set) == 8
    with pytest.raises(RuntimeError):
        t.get_pserver_program("127.0.0.1:6174")


def test_sharded_feed_shapes():
    """Feeds are split along the batch axis over the dp mesh: each device
    holds batch/8 rows (SplitLoDTensor parity, reference
    parallel_do_op.cc:39)."""
    mesh = mesh_mod.data_parallel_mesh(8)
    main, startup = fluid.Program(), fluid.Program()
    x, y, loss = _build_mlp(main, startup)
    main._mesh = mesh
    exe = fluid.Executor(fluid.CPUPlace())
    scope = em.Scope()
    with em.scope_guard(scope):
        exe.run(startup)
        xv = RNG.standard_normal((32, 16)).astype(np.float32)
        yv = RNG.integers(0, 4, (32, 1)).astype(np.int64)
        sharding = mesh_mod.batch_sharding(mesh, 2)
        xd = jax.device_put(xv, sharding)
        # device_put with the batch sharding places 4 rows per device
        assert {s.data.shape for s in xd.addressable_shards} == {(4, 16)}
        lv, = exe.run(main, feed={"x": xd, "y": yv}, fetch_list=[loss])
        assert np.isfinite(np.ravel(lv)).all()


def test_batch_not_divisible_raises_clearly():
    """A batch not divisible by the dp axis cannot be laid out by GSPMD;
    the error should surface, not silently mis-shard."""
    mesh = mesh_mod.data_parallel_mesh(8)
    main, startup = fluid.Program(), fluid.Program()
    x, y, loss = _build_mlp(main, startup)
    main._mesh = mesh
    exe = fluid.Executor(fluid.CPUPlace())
    scope = em.Scope()
    with em.scope_guard(scope):
        exe.run(startup)
        xv = RNG.standard_normal((12, 16)).astype(np.float32)
        yv = RNG.integers(0, 4, (12, 1)).astype(np.int64)
        with pytest.raises(Exception):
            exe.run(main, feed={"x": xv, "y": yv}, fetch_list=[loss])


class TestTensorParallel:
    """2-D (dp, mp) mesh: fc weights column-sharded over 'mp'
    (parallel/tensor_parallel.py); loss must track the single-device run."""

    def _train(self, mesh=None, shard=False, steps=6):
        import numpy as np
        main = fluid.Program()
        startup = fluid.Program()
        with fluid.program_guard(main, startup):
            x = fluid.layers.data(name="x", shape=[16], dtype="float32")
            y = fluid.layers.data(name="y", shape=[1], dtype="float32")
            h = fluid.layers.fc(input=x, size=32, act="relu",
                                param_attr=fluid.ParamAttr(name="tp_w1"))
            pred = fluid.layers.fc(input=h, size=1,
                                   param_attr=fluid.ParamAttr(name="tp_w2"))
            loss = fluid.layers.mean(fluid.layers.square_error_cost(pred, y))
            fluid.optimizer.SGDOptimizer(learning_rate=0.05).minimize(loss)
        if mesh is not None:
            main._mesh = mesh
            if shard:
                fluid.parallel.shard_fc_params(main, axis="mp")
        exe = fluid.Executor(fluid.CPUPlace())
        rng = np.random.RandomState(0)
        w = rng.randn(16, 1).astype(np.float32)
        scope = executor_mod.Scope()
        with executor_mod.scope_guard(scope):
            exe.run(startup)
            scope.set_var("tp_w1", np.linspace(-0.3, 0.3, 16 * 32)
                          .astype(np.float32).reshape(16, 32))
            scope.set_var("tp_w2", np.linspace(-0.2, 0.2, 32)
                          .astype(np.float32).reshape(32, 1))
            losses = []
            for _ in range(steps):
                xs = rng.randn(32, 16).astype(np.float32)
                v, = exe.run(main, feed={"x": xs, "y": xs @ w},
                             fetch_list=[loss])
                losses.append(float(np.asarray(v).reshape(-1)[0]))
        return losses

    def test_dp_mp_mesh_matches_single_device(self):
        import numpy as np
        from paddle_tpu.parallel import mesh as mesh_mod
        single = self._train(mesh=None)
        mesh = mesh_mod.make_mesh((2, 4), ("dp", "mp"))
        sharded = self._train(mesh=mesh, shard=True)
        np.testing.assert_allclose(sharded, single, rtol=2e-4,
                                   err_msg="tp-sharded loss diverged")

    def test_zero_param_sharding(self):
        import numpy as np
        from paddle_tpu.parallel import mesh as mesh_mod
        single = self._train(mesh=None)
        main_mesh = mesh_mod.data_parallel_mesh(8)

        # rebuild with ZeRO-style sharding over dp
        main = fluid.Program()
        startup = fluid.Program()
        with fluid.program_guard(main, startup):
            x = fluid.layers.data(name="x", shape=[16], dtype="float32")
            y = fluid.layers.data(name="y", shape=[1], dtype="float32")
            h = fluid.layers.fc(input=x, size=32, act="relu",
                                param_attr=fluid.ParamAttr(name="tp_w1"))
            pred = fluid.layers.fc(input=h, size=1,
                                   param_attr=fluid.ParamAttr(name="tp_w2"))
            loss = fluid.layers.mean(fluid.layers.square_error_cost(pred, y))
            fluid.optimizer.SGDOptimizer(learning_rate=0.05).minimize(loss)
        main._mesh = main_mesh
        fluid.parallel.shard_all_params_zero(main, axis="dp", min_size=8)
        exe = fluid.Executor(fluid.CPUPlace())
        rng = np.random.RandomState(0)
        w = rng.randn(16, 1).astype(np.float32)
        scope = executor_mod.Scope()
        with executor_mod.scope_guard(scope):
            exe.run(startup)
            scope.set_var("tp_w1", np.linspace(-0.3, 0.3, 16 * 32)
                          .astype(np.float32).reshape(16, 32))
            scope.set_var("tp_w2", np.linspace(-0.2, 0.2, 32)
                          .astype(np.float32).reshape(32, 1))
            losses = []
            for _ in range(6):
                xs = rng.randn(32, 16).astype(np.float32)
                v, = exe.run(main, feed={"x": xs, "y": xs @ w},
                             fetch_list=[loss])
                losses.append(float(np.asarray(v).reshape(-1)[0]))
        np.testing.assert_allclose(losses, single, rtol=2e-4)
