"""Book ch06: sentiment classification, conv + stacked-LSTM variants
(reference tests/book/test_understand_sentiment.py)."""

import numpy as np
import pytest

import paddle_tpu as fluid


def convolution_net(data, input_dim, class_dim=2, emb_dim=32, hid_dim=32):
    emb = fluid.layers.embedding(input=data, size=[input_dim, emb_dim])
    conv_3 = fluid.nets.sequence_conv_pool(input=emb, num_filters=hid_dim,
                                           filter_size=3, act="tanh",
                                           pool_type="sqrt")
    conv_4 = fluid.nets.sequence_conv_pool(input=emb, num_filters=hid_dim,
                                           filter_size=4, act="tanh",
                                           pool_type="sqrt")
    return fluid.layers.fc(input=[conv_3, conv_4], size=class_dim)


def stacked_lstm_net(data, input_dim, class_dim=2, emb_dim=32, hid_dim=32,
                     stacked_num=3):
    emb = fluid.layers.embedding(input=data, size=[input_dim, emb_dim])
    fc1 = fluid.layers.fc(input=emb, size=hid_dim, num_flatten_dims=2)
    lstm1, cell1 = fluid.layers.dynamic_lstm(input=fc1, size=hid_dim)
    inputs = [fc1, lstm1]
    for i in range(2, stacked_num + 1):
        fc = fluid.layers.fc(input=inputs, size=hid_dim, num_flatten_dims=2)
        lstm, cell = fluid.layers.dynamic_lstm(input=fc, size=hid_dim,
                                               is_reverse=(i % 2) == 0)
        inputs = [fc, lstm]
    fc_last = fluid.layers.sequence_pool(input=inputs[0], pool_type="max")
    lstm_last = fluid.layers.sequence_pool(input=inputs[1], pool_type="max")
    return fluid.layers.fc(input=[fc_last, lstm_last], size=class_dim)


@pytest.mark.parametrize("net", ["conv", "stacked_lstm"])
def test_understand_sentiment(net):
    word_dict = fluid.dataset.imdb.word_dict()
    dict_dim = len(word_dict)

    data = fluid.layers.data(name="words", shape=[1], dtype="int64",
                             lod_level=1)
    label = fluid.layers.data(name="label", shape=[1], dtype="int64")
    if net == "conv":
        logits = convolution_net(data, dict_dim)
    else:
        logits = stacked_lstm_net(data, dict_dim)
    cost = fluid.layers.softmax_with_cross_entropy(logits=logits, label=label)
    avg_cost = fluid.layers.mean(cost)
    acc = fluid.layers.accuracy(input=fluid.layers.softmax(logits),
                                label=label)
    fluid.optimizer.Adam(learning_rate=2e-3).minimize(avg_cost)

    train_reader = fluid.batch(
        fluid.reader.shuffle(fluid.dataset.imdb.train(word_dict),
                             buf_size=1000), batch_size=32)
    place = fluid.CPUPlace()
    exe = fluid.Executor(place)
    feeder = fluid.DataFeeder(place=place, feed_list=[data, label])
    exe.run(fluid.default_startup_program())

    accs = []
    for i, data_batch in enumerate(train_reader()):
        data_batch = [([[w] for w in ws], [l]) for ws, l in data_batch]
        loss, a = exe.run(fluid.default_main_program(),
                          feed=feeder.feed(data_batch),
                          fetch_list=[avg_cost, acc])
        accs.append(float(np.ravel(a)[0]))
        if i >= 30:
            break
    assert np.mean(accs[-5:]) > 0.8, accs

    from tests.book._roundtrip import assert_infer_roundtrip
    from paddle_tpu.executor import LoDTensor
    rng = np.random.RandomState(0)
    rows = [rng.randint(0, dict_dim, (n, 1)).astype(np.int64)
            for n in (5, 3)]
    feed = {"words": LoDTensor(np.concatenate(rows, 0), [[0, 5, 8]])}
    out, = assert_infer_roundtrip(exe, place, feed, [logits])
    assert np.asarray(out).shape == (2, 2)
