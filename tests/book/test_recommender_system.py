"""Book ch05: recommender system (reference
tests/book/test_recommender_system.py): user/movie feature embeddings,
cosine-ish matching via fc, squared-error on score."""

import numpy as np

import paddle_tpu as fluid


def test_recommender_system():
    ml = fluid.dataset.movielens

    uid = fluid.layers.data(name="user_id", shape=[1], dtype="int64")
    gender = fluid.layers.data(name="gender_id", shape=[1], dtype="int64")
    age = fluid.layers.data(name="age_id", shape=[1], dtype="int64")
    job = fluid.layers.data(name="job_id", shape=[1], dtype="int64")
    mid = fluid.layers.data(name="movie_id", shape=[1], dtype="int64")
    category = fluid.layers.data(name="category_id", shape=[1],
                                 dtype="int64", lod_level=1)
    title = fluid.layers.data(name="movie_title", shape=[1],
                              dtype="int64", lod_level=1)
    score = fluid.layers.data(name="score", shape=[1], dtype="float32")

    def fc_emb(var, size, dim=16):
        e = fluid.layers.embedding(input=var, size=[size, dim])
        return fluid.layers.fc(input=e, size=16)

    usr = fluid.layers.concat(
        [fc_emb(uid, ml.max_user_id() + 1),
         fc_emb(gender, 2), fc_emb(age, 8), fc_emb(job, ml.max_job_id() + 1)],
        axis=1)
    usr_feat = fluid.layers.fc(input=usr, size=32, act="tanh")

    mov_emb = fc_emb(mid, ml.max_movie_id() + 1)
    cat_emb = fluid.layers.embedding(input=category, size=[18, 16])
    cat_pool = fluid.layers.sequence_pool(cat_emb, "sum")
    tit_emb = fluid.layers.embedding(input=title, size=[5175, 16])
    tit_pool = fluid.layers.sequence_pool(tit_emb, "sum")
    mov = fluid.layers.concat([mov_emb, cat_pool, tit_pool], axis=1)
    mov_feat = fluid.layers.fc(input=mov, size=32, act="tanh")

    sim = fluid.layers.cos_sim(X=usr_feat, Y=mov_feat)
    predict = fluid.layers.scale(sim, scale=5.0)
    cost = fluid.layers.square_error_cost(input=predict, label=score)
    avg_cost = fluid.layers.mean(cost)
    fluid.optimizer.Adam(learning_rate=2e-3).minimize(avg_cost)

    train_reader = fluid.batch(ml.train(), batch_size=64)
    place = fluid.CPUPlace()
    exe = fluid.Executor(place)
    feeder = fluid.DataFeeder(
        place=place, feed_list=[uid, gender, age, job, mid, category, title,
                                score])
    exe.run(fluid.default_startup_program())

    losses = []
    for i, data in enumerate(train_reader()):
        loss, = exe.run(fluid.default_main_program(),
                        feed=feeder.feed(data), fetch_list=[avg_cost])
        losses.append(float(np.ravel(loss)[0]))
        if i >= 40:
            break
    # explicit threshold: below the score variance (~1.2 on the synthetic
    # ratings), i.e. the model predicts better than the mean rating
    assert losses[-1] < losses[0], (losses[0], losses[-1])
    assert np.mean(losses[-5:]) < 2.5, losses[-5:]

    from tests.book._roundtrip import assert_infer_roundtrip
    from paddle_tpu.executor import LoDTensor
    rng = np.random.RandomState(0)
    feed = {"user_id": rng.randint(0, 100, (3, 1)).astype(np.int64),
            "gender_id": rng.randint(0, 2, (3, 1)).astype(np.int64),
            "age_id": rng.randint(0, 7, (3, 1)).astype(np.int64),
            "job_id": rng.randint(0, 10, (3, 1)).astype(np.int64),
            "movie_id": rng.randint(0, 100, (3, 1)).astype(np.int64),
            "category_id": LoDTensor(
                rng.randint(0, 18, (5, 1)).astype(np.int64), [[0, 2, 4, 5]]),
            "movie_title": LoDTensor(
                rng.randint(0, 5175, (7, 1)).astype(np.int64), [[0, 3, 5, 7]])}
    out, = assert_infer_roundtrip(exe, place, feed, [predict])
    assert np.asarray(out).shape == (3, 1)
