"""Shared book-chapter acceptance epilogue (reference
tests/book/test_fit_a_line.py:139-146 + inference/tests/book/): after
training, every chapter must (1) compute predictions from the live scope
through the pruned test-mode program, (2) save_inference_model, (3) reload
into a FRESH scope and re-run, (4) get identical predictions — proving the
saved artifact reproduces the trained network, not merely that it loads."""

import os
import tempfile

import numpy as np

import paddle_tpu as fluid
from paddle_tpu import executor as executor_mod


def assert_infer_roundtrip(exe, place, feed_dict, targets,
                           main_program=None, rtol=1e-4, atol=1e-6):
    """Returns the reloaded model's outputs after asserting they match the
    live-scope predictions on the same feed."""
    targets = targets if isinstance(targets, list) else [targets]
    infer_prog = fluid.io.get_inference_program(targets, main_program)
    expected = exe.run(infer_prog, feed=dict(feed_dict), fetch_list=targets)

    with tempfile.TemporaryDirectory() as d:
        path = os.path.join(d, "model")
        fluid.io.save_inference_model(path, list(feed_dict), targets, exe,
                                      main_program=main_program)
        scope = executor_mod.Scope()
        with executor_mod.scope_guard(scope):
            infer_exe = fluid.Executor(place)
            prog, feed_names, fetch_targets = \
                fluid.io.load_inference_model(path, infer_exe)
            got = infer_exe.run(
                prog, feed={n: feed_dict[n] for n in feed_names},
                fetch_list=fetch_targets)
    for e, g in zip(expected, got):
        np.testing.assert_allclose(np.asarray(g), np.asarray(e),
                                   rtol=rtol, atol=atol)
    return got
