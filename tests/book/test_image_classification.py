"""Book ch03: CIFAR-10 image classification, VGG + ResNet variants
(reference tests/book/test_image_classification.py). Loss must drop on the
synthetic surrogate within a short budget."""

import numpy as np
import pytest

import paddle_tpu as fluid
from paddle_tpu import models


@pytest.mark.parametrize("net", ["resnet", "vgg"])
def test_image_classification(net):
    img = fluid.layers.data(name="img", shape=[3, 32, 32], dtype="float32")
    label = fluid.layers.data(name="label", shape=[1], dtype="int64")
    model_fn = models.resnet_cifar10 if net == "resnet" else models.vgg16
    avg_cost, predict, acc = models.build_image_classifier(
        model_fn, img, label, class_dim=10)
    fluid.optimizer.Adam(learning_rate=1e-3).minimize(avg_cost)

    train_reader = fluid.batch(
        fluid.reader.shuffle(fluid.dataset.cifar.train10(), buf_size=512),
        batch_size=32)
    place = fluid.TPUPlace()
    exe = fluid.Executor(place)
    feeder = fluid.DataFeeder(place=place, feed_list=[img, label])
    exe.run(fluid.default_startup_program())

    losses = []
    for i, data in enumerate(train_reader()):
        data = [(np.reshape(im, (3, 32, 32)), l) for im, l in data]
        loss, a = exe.run(fluid.default_main_program(),
                          feed=feeder.feed(data), fetch_list=[avg_cost, acc])
        losses.append(float(np.ravel(loss)[0]))
        if i >= 30:
            break
    assert np.mean(losses[-5:]) < np.mean(losses[:5]), losses
