"""Book ch03: CIFAR-10 image classification, VGG + ResNet variants
(reference tests/book/test_image_classification.py). Loss must drop on the
synthetic surrogate within a short budget."""

import numpy as np
import pytest

import paddle_tpu as fluid
from paddle_tpu import models


@pytest.mark.parametrize("net", ["resnet", "vgg"])
def test_image_classification(net):
    img = fluid.layers.data(name="img", shape=[3, 32, 32], dtype="float32")
    label = fluid.layers.data(name="label", shape=[1], dtype="int64")
    model_fn = models.resnet_cifar10 if net == "resnet" else models.vgg16
    avg_cost, predict, acc = models.build_image_classifier(
        model_fn, img, label, class_dim=10)
    # vgg16 has no batch norm: at 1e-3 its short run sits on the edge of
    # divergence, where float-reassociation differences between COMPILES
    # (fresh vs persistent-cache executables) flipped the outcome — the
    # round-4 "watch item" flake, finally captured. 2e-4 is stable for
    # every compile while still dropping the loss within the budget.
    lr = 1e-3 if net == "resnet" else 2e-4
    fluid.optimizer.Adam(learning_rate=lr).minimize(avg_cost)

    # vgg16 costs ~6x the residual net per step on the 1-core CI box; it
    # gets a smaller batch + shorter run with a relative-improvement
    # assert, while resnet carries the chapter's explicit-threshold
    # convergence gate (the reference CI had the same split: GPU jobs
    # trained to threshold, CPU jobs smoke-trained)
    bsz, max_steps = (16, 15) if net == "vgg" else (32, 30)
    train_reader = fluid.batch(
        fluid.reader.shuffle(fluid.dataset.cifar.train10(), buf_size=512),
        batch_size=bsz)
    place = fluid.TPUPlace()
    exe = fluid.Executor(place)
    feeder = fluid.DataFeeder(place=place, feed_list=[img, label])
    exe.run(fluid.default_startup_program())

    losses = []
    for i, data in enumerate(train_reader()):
        data = [(np.reshape(im, (3, 32, 32)), l) for im, l in data]
        loss, a = exe.run(fluid.default_main_program(),
                          feed=feeder.feed(data), fetch_list=[avg_cost, acc])
        losses.append(float(np.ravel(loss)[0]))
        if i >= max_steps:
            break
    if net == "resnet":
        # explicit threshold: below the ln(10)=2.303 uniform-guess floor —
        # the class-blob surrogate is separable, so learning must show
        assert np.mean(losses[-5:]) < 2.2, losses
    else:
        assert np.mean(losses[-5:]) < np.mean(losses[:5]), losses

    from tests.book._roundtrip import assert_infer_roundtrip
    xs = np.random.RandomState(0).rand(4, 3, 32, 32).astype(np.float32)
    probs, = assert_infer_roundtrip(exe, place, {"img": xs}, [predict],
                                    rtol=1e-3, atol=1e-5)
    probs = np.asarray(probs)
    assert probs.shape == (4, 10)
    np.testing.assert_allclose(probs.sum(axis=1), np.ones(4), rtol=1e-3)
