"""Book ch08: machine translation, seq2seq encoder-decoder with attention
(reference tests/book/test_machine_translation.py). Training path; beam
search decode is exercised in test_beam_search once available."""

import numpy as np

import paddle_tpu as fluid

DICT_SIZE = 200
WORD_DIM = 16
HID = 32


def encoder_decoder():
    src = fluid.layers.data(name="src_word_id", shape=[1], dtype="int64",
                            lod_level=1)
    src_emb = fluid.layers.embedding(input=src, size=[DICT_SIZE, WORD_DIM])
    fc1 = fluid.layers.fc(input=src_emb, size=HID * 4, num_flatten_dims=2,
                          act="tanh")
    enc_hidden, _ = fluid.layers.dynamic_lstm(input=fc1, size=HID * 4)
    enc_last = fluid.layers.sequence_last_step(enc_hidden)

    trg = fluid.layers.data(name="target_language_word", shape=[1],
                            dtype="int64", lod_level=1)
    trg_emb = fluid.layers.embedding(input=trg, size=[DICT_SIZE, WORD_DIM])

    rnn = fluid.layers.DynamicRNN()
    with rnn.block():
        x_t = rnn.step_input(trg_emb)
        mem = rnn.memory(init=enc_last)
        # additive attention over encoder states
        expanded = fluid.layers.sequence_expand(x=mem, y=enc_hidden)
        scores = fluid.layers.reduce_sum(
            fluid.layers.elementwise_mul(expanded, enc_hidden), dim=2,
            keep_dim=False)
        weights = fluid.layers.sequence_softmax(scores)
        weighted = fluid.layers.elementwise_mul(enc_hidden, weights, axis=0)
        context = fluid.layers.sequence_pool(weighted, "sum")
        decoder_inputs = fluid.layers.concat([context, x_t], axis=1)
        h = fluid.layers.fc(input=[decoder_inputs, mem], size=HID,
                            act="tanh")
        rnn.update_memory(mem, h)
        out = fluid.layers.fc(input=h, size=DICT_SIZE)
        rnn.step_output(out)
    logits = rnn()
    return src, trg, logits


def test_machine_translation_train():
    import random
    random.seed(90)  # reader.shuffle uses the global random state
    src, trg, logits = encoder_decoder()
    label = fluid.layers.data(name="target_language_next_word", shape=[1],
                              dtype="int64", lod_level=1)
    cost = fluid.layers.softmax_with_cross_entropy(
        logits=logits, label=label, seq_mask=True)
    avg_cost = fluid.layers.mean(cost)
    fluid.optimizer.Adam(learning_rate=4e-3).minimize(avg_cost)

    train_reader = fluid.batch(
        fluid.reader.shuffle(fluid.dataset.wmt14.train(DICT_SIZE),
                             buf_size=1000), batch_size=16)
    place = fluid.CPUPlace()
    exe = fluid.Executor(place)
    feeder = fluid.DataFeeder(place=place, feed_list=[src, trg, label])
    exe.run(fluid.default_startup_program())

    losses = []
    for epoch in range(2):
        for i, data in enumerate(train_reader()):
            data = [([[w] for w in s], [[w] for w in t], [[w] for w in n])
                    for s, t, n in data]
            loss, = exe.run(fluid.default_main_program(),
                            feed=feeder.feed(data), fetch_list=[avg_cost])
            losses.append(float(np.ravel(loss)[0]))
            if i >= 100:
                break
    assert np.mean(losses[-5:]) < losses[0] * 0.8, (losses[0], losses[-5:])
