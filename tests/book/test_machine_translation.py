"""Book ch08: machine translation, seq2seq encoder-decoder with attention
(reference tests/book/test_machine_translation.py). Three paths, matching
the reference's main() + decode_main() split:
  - training (teacher-forced DynamicRNN decoder),
  - greedy generation (argmax loop over dense beam lanes, K=1),
  - beam-search generation (While + beam_search/beam_search_decode ops,
    reference beam_search_op.cc) driving the SAME named parameters the
    training program learned."""

import numpy as np

import paddle_tpu as fluid

DICT_SIZE = 200
WORD_DIM = 16
HID = 32
START, END = 0, 1
BEAM = 3
MAX_LEN = 8


def encoder(src):
    """Shared encoder: embedding -> fc -> LSTM (params named so the decode
    programs reuse the trained weights, reference decode_main parity)."""
    src_emb = fluid.layers.embedding(
        input=src, size=[DICT_SIZE, WORD_DIM],
        param_attr=fluid.ParamAttr(name="src_emb_w"))
    fc1 = fluid.layers.fc(input=src_emb, size=HID * 4, num_flatten_dims=2,
                          act="tanh",
                          param_attr=fluid.ParamAttr(name="enc_fc_w"),
                          bias_attr=fluid.ParamAttr(name="enc_fc_b"))
    enc_hidden, _ = fluid.layers.dynamic_lstm(
        input=fc1, size=HID * 4,
        param_attr=fluid.ParamAttr(name="enc_lstm_w"),
        bias_attr=fluid.ParamAttr(name="enc_lstm_b"))
    enc_last = fluid.layers.sequence_last_step(enc_hidden)
    return enc_hidden, enc_last


def encoder_decoder():
    """Teacher-forced training graph."""
    src = fluid.layers.data(name="src_word_id", shape=[1], dtype="int64",
                            lod_level=1)
    enc_hidden, enc_last = encoder(src)

    trg = fluid.layers.data(name="target_language_word", shape=[1],
                            dtype="int64", lod_level=1)
    trg_emb = fluid.layers.embedding(
        input=trg, size=[DICT_SIZE, WORD_DIM],
        param_attr=fluid.ParamAttr(name="trg_emb_w"))

    rnn = fluid.layers.DynamicRNN()
    with rnn.block():
        x_t = rnn.step_input(trg_emb)
        mem = rnn.memory(init=enc_last)
        # dot-product attention over encoder states
        expanded = fluid.layers.sequence_expand(x=mem, y=enc_hidden)
        scores = fluid.layers.reduce_sum(
            fluid.layers.elementwise_mul(expanded, enc_hidden), dim=2,
            keep_dim=False)
        weights = fluid.layers.sequence_softmax(scores)
        weighted = fluid.layers.elementwise_mul(enc_hidden, weights, axis=0)
        context = fluid.layers.sequence_pool(weighted, "sum")
        dec_in = fluid.layers.concat([context, x_t, mem], axis=1)
        h = fluid.layers.fc(input=dec_in, size=HID, act="tanh",
                            param_attr=fluid.ParamAttr(name="dec_fc_w"),
                            bias_attr=fluid.ParamAttr(name="dec_fc_b"))
        rnn.update_memory(mem, h)
        out = fluid.layers.fc(input=h, size=DICT_SIZE,
                              param_attr=fluid.ParamAttr(name="dec_out_w"),
                              bias_attr=fluid.ParamAttr(name="dec_out_b"))
        rnn.step_output(out)
    logits = rnn()
    return src, trg, logits


def _lane_attention(mem, enc_hidden, neg_mask):
    """Dot-product attention for dense beam lanes: mem [B,K,H] over
    enc_hidden [B,T,H] -> context [B,K,H]; padded positions masked via
    neg_mask [B,T] (0 valid / -1e9 pad, from sequence_mask)."""
    scores = fluid.layers.matmul(mem, enc_hidden, transpose_y=True)  # [B,K,T]
    scores_t = fluid.layers.transpose(scores, [0, 2, 1])             # [B,T,K]
    scores_t = fluid.layers.elementwise_add(scores_t, neg_mask, axis=0)
    weights = fluid.layers.softmax(
        fluid.layers.transpose(scores_t, [0, 2, 1]))                 # [B,K,T]
    return fluid.layers.matmul(weights, enc_hidden)                  # [B,K,H]


def _lane_step(pre_ids, mem, enc_hidden, neg_mask, k):
    """One decoder step on [B,K] lanes, reusing the trained params."""
    tok_emb = fluid.layers.embedding(
        input=pre_ids, size=[DICT_SIZE, WORD_DIM],
        param_attr=fluid.ParamAttr(name="trg_emb_w"))                # [B,K,W]
    if k == 1:
        # lookup_table squeezes the trailing dim-1 axis (fluid's [sum,1]
        # ids convention); restore the lane axis for K=1 greedy
        tok_emb = fluid.layers.reshape(tok_emb, shape=[-1, 1, WORD_DIM])
    context = _lane_attention(mem, enc_hidden, neg_mask)             # [B,K,H]
    dec_in = fluid.layers.concat([context, tok_emb, mem], axis=2)
    h = fluid.layers.fc(input=dec_in, size=HID, act="tanh",
                        num_flatten_dims=2,
                        param_attr=fluid.ParamAttr(name="dec_fc_w"),
                        bias_attr=fluid.ParamAttr(name="dec_fc_b"))
    logits = fluid.layers.fc(input=h, size=DICT_SIZE, num_flatten_dims=2,
                             param_attr=fluid.ParamAttr(name="dec_out_w"),
                             bias_attr=fluid.ParamAttr(name="dec_out_b"))
    return h, logits


def _lane_init(enc_last, k):
    """Broadcast enc_last [B,H] to per-lane memory [B,K,H] with existing
    broadcast ops (zeros [B,H,K] + enc_last over trailing K, transpose)."""
    z = fluid.layers.fill_constant_batch_size_like(
        input=enc_last, shape=[-1, HID, k], dtype="float32", value=0.0)
    memt = fluid.layers.elementwise_add(z, enc_last, axis=0)
    return fluid.layers.transpose(memt, [0, 2, 1])


def decode_program(beam_size, use_beam):
    """Generation-mode decoder (reference decode_main): While loop over
    dense [B,K] lanes; beam_search ops when use_beam, else argmax greedy."""
    src = fluid.layers.data(name="src_word_id", shape=[1], dtype="int64",
                            lod_level=1)
    enc_hidden, enc_last = encoder(src)
    neg_mask = fluid.layers.scale(fluid.layers.sequence_mask(enc_hidden),
                                  scale=1e9, bias=-1e9)
    k = beam_size
    counter = fluid.layers.fill_constant(shape=[1], dtype="int64", value=0)
    max_len = fluid.layers.fill_constant(shape=[1], dtype="int64",
                                         value=MAX_LEN)
    init_ids = fluid.layers.fill_constant_batch_size_like(
        input=enc_last, shape=[-1, k], dtype="int64", value=START)
    lane_penalty = fluid.layers.assign(
        np.concatenate([[0.0], np.full(k - 1, -1e9)]).astype(np.float32))
    init_scores = fluid.layers.elementwise_add(
        fluid.layers.fill_constant_batch_size_like(
            input=enc_last, shape=[-1, k], dtype="float32", value=0.0),
        lane_penalty, axis=1)

    cap = MAX_LEN + 1
    ids_arr = fluid.layers.array_write(init_ids, counter, capacity=cap)
    parents_arr = fluid.layers.array_write(
        fluid.layers.cast(init_ids, "int32"), counter, capacity=cap)
    scores_arr = fluid.layers.array_write(init_scores, counter,
                                          capacity=cap)
    pre_ids = fluid.layers.assign(init_ids)
    pre_scores = fluid.layers.assign(init_scores)
    mem = _lane_init(enc_last, k)

    cond = fluid.layers.less_than(x=counter, y=max_len)
    w = fluid.layers.While(cond=cond)
    with w.block():
        h, logits = _lane_step(pre_ids, mem, enc_hidden, neg_mask, k)
        logp = fluid.layers.log(fluid.layers.softmax(logits))
        if use_beam:
            sel_ids, sel_scores, parent = fluid.layers.beam_search(
                pre_ids=pre_ids, pre_scores=pre_scores, scores=logp,
                beam_size=k, end_id=END)
        else:
            # greedy: argmax token per (single) lane; score accumulates
            nxt = fluid.layers.argmax(logp, axis=2)          # [B,K]
            sel_ids = fluid.layers.cast(nxt, "int64")
            step_best = fluid.layers.reduce_max(logp, dim=2, keep_dim=False)
            sel_scores = fluid.layers.elementwise_add(pre_scores, step_best)
            parent = fluid.layers.cast(
                fluid.layers.fill_constant_batch_size_like(
                    input=sel_scores, shape=[-1, k], dtype="int64",
                    value=0), "int32")
        fluid.layers.increment(counter, value=1, in_place=True)
        fluid.layers.array_write(sel_ids, counter, array=ids_arr)
        fluid.layers.array_write(parent, counter, array=parents_arr)
        fluid.layers.array_write(sel_scores, counter, array=scores_arr)
        fluid.layers.assign(sel_ids, pre_ids)
        fluid.layers.assign(sel_scores, pre_scores)
        fluid.layers.assign(h, mem)
        fluid.layers.less_than(x=counter, y=max_len, cond=cond)

    sentences, final_scores = fluid.layers.beam_search_decode(
        ids_arr, parents_arr, scores=scores_arr, end_id=END)
    return src, sentences, final_scores


def _toy_pairs(n, rng):
    """Copy-reverse toy task: target = reversed source (learnable fast)."""
    pairs = []
    for _ in range(n):
        ln = rng.randint(2, 5)
        s = rng.randint(2, DICT_SIZE, ln).tolist()
        t = [START] + s[::-1]
        nxt = s[::-1] + [END]
        pairs.append((s, t, nxt))
    return pairs


def _feed(pairs, feeder):
    data = [([[w] for w in s], [[w] for w in t], [[w] for w in n])
            for s, t, n in pairs]
    return feeder.feed(data)


def test_machine_translation_train():
    import random
    random.seed(90)  # reader.shuffle uses the global random state
    src, trg, logits = encoder_decoder()
    label = fluid.layers.data(name="target_language_next_word", shape=[1],
                              dtype="int64", lod_level=1)
    cost = fluid.layers.softmax_with_cross_entropy(
        logits=logits, label=label, seq_mask=True)
    avg_cost = fluid.layers.mean(cost)
    fluid.optimizer.Adam(learning_rate=4e-3).minimize(avg_cost)

    train_reader = fluid.batch(
        fluid.reader.shuffle(fluid.dataset.wmt14.train(DICT_SIZE),
                             buf_size=1000), batch_size=16)
    place = fluid.CPUPlace()
    exe = fluid.Executor(place)
    feeder = fluid.DataFeeder(place=place, feed_list=[src, trg, label])
    exe.run(fluid.default_startup_program())

    losses = []
    for epoch in range(2):
        for i, data in enumerate(train_reader()):
            data = [([[w] for w in s], [[w] for w in t], [[w] for w in n])
                    for s, t, n in data]
            loss, = exe.run(fluid.default_main_program(),
                            feed=feeder.feed(data), fetch_list=[avg_cost])
            losses.append(float(np.ravel(loss)[0]))
            if i >= 100:
                break
    assert np.mean(losses[-5:]) < losses[0] * 0.8, (losses[0], losses[-5:])


def test_machine_translation_decode():
    """Train briefly, then generate with greedy AND beam search from the
    same scope (reference decode_main over trained params)."""
    from paddle_tpu import executor as executor_mod

    rng = np.random.RandomState(5)
    scope = executor_mod.Scope()
    place = fluid.CPUPlace()
    exe = fluid.Executor(place)

    with executor_mod.scope_guard(scope):
        # --- training program
        train_prog, train_startup = fluid.Program(), fluid.Program()
        with fluid.program_guard(train_prog, train_startup):
            src, trg, logits = encoder_decoder()
            label = fluid.layers.data(name="target_language_next_word",
                                      shape=[1], dtype="int64", lod_level=1)
            cost = fluid.layers.softmax_with_cross_entropy(
                logits=logits, label=label, seq_mask=True)
            avg_cost = fluid.layers.mean(cost)
            fluid.optimizer.Adam(learning_rate=5e-3).minimize(avg_cost)
            feeder = fluid.DataFeeder(place=place,
                                      feed_list=[src, trg, label])
        exe.run(train_startup)
        first = last = None
        for i in range(30):
            l, = exe.run(train_prog, feed=_feed(_toy_pairs(16, rng), feeder),
                         fetch_list=[avg_cost])
            last = float(np.ravel(l)[0])
            first = first if first is not None else last
        assert last < first, (first, last)

        # --- inference round-trip of the trained seq2seq (teacher-forced
        # logits): save, reload into a fresh scope, predictions must match
        from tests.book._roundtrip import assert_infer_roundtrip
        rt_pairs = _feed(_toy_pairs(3, rng), feeder)
        rt_feed = {k: v for k, v in rt_pairs.items()
                   if k in ("src_word_id", "target_language_word")}
        rt_out, = assert_infer_roundtrip(exe, place, rt_feed, [logits],
                                         main_program=train_prog)
        assert np.isfinite(np.asarray(rt_out)).all()

        # --- decode programs share the scope's trained params by name
        from paddle_tpu.executor import LoDTensor
        rows = [np.array([[3], [7], [9]], np.int64),
                np.array([[12], [4]], np.int64)]
        flat = np.concatenate(rows, 0)
        src_feed = {"src_word_id": LoDTensor(flat, [[0, 3, 5]])}
        bsz = 2

        beam_prog = fluid.Program()
        with fluid.program_guard(beam_prog, fluid.Program()):
            _, sentences, final_scores = decode_program(BEAM, use_beam=True)
        out_ids, out_scores = exe.run(beam_prog, feed=src_feed,
                                      fetch_list=[sentences, final_scores])
        assert out_ids.shape[0] == bsz and out_ids.shape[1] == BEAM
        assert (out_ids >= 0).all() and (out_ids < DICT_SIZE).all()
        assert (out_ids[:, :, 0] == START).all()
        # beam lanes ranked: scores non-increasing across lanes
        assert (np.diff(out_scores, axis=1) <= 1e-5).all(), out_scores

        greedy_prog = fluid.Program()
        with fluid.program_guard(greedy_prog, fluid.Program()):
            _, g_sent, g_scores = decode_program(1, use_beam=False)
        g_ids, g_sc = exe.run(greedy_prog, feed=src_feed,
                              fetch_list=[g_sent, g_scores])
        assert g_ids.shape[0] == bsz and g_ids.shape[1] == 1
        assert (g_ids[:, :, 0] == START).all()

        # the best beam hypothesis scores at least as well as greedy
        # (beam explores a superset of greedy's single path)
        assert (out_scores[:, 0] >= g_sc[:, 0] - 1e-4).all(), \
            (out_scores[:, 0], g_sc[:, 0])
