"""Book ch04: word2vec N-gram LM (reference tests/book/test_word2vec.py):
4 context embeddings with a shared table -> fc -> softmax next-word."""

import numpy as np

import paddle_tpu as fluid


def test_word2vec():
    word_dict = fluid.dataset.imikolov.build_dict()
    dict_size = len(word_dict)
    EMBED = 32

    def emb(name_var):
        return fluid.layers.embedding(
            input=name_var, size=[dict_size, EMBED],
            param_attr=fluid.ParamAttr(name="shared_w"))

    first = fluid.layers.data(name="firstw", shape=[1], dtype="int64")
    second = fluid.layers.data(name="secondw", shape=[1], dtype="int64")
    third = fluid.layers.data(name="thirdw", shape=[1], dtype="int64")
    forth = fluid.layers.data(name="forthw", shape=[1], dtype="int64")
    next_word = fluid.layers.data(name="nextw", shape=[1], dtype="int64")

    concat = fluid.layers.concat(
        input=[emb(first), emb(second), emb(third), emb(forth)], axis=1)
    hidden = fluid.layers.fc(input=concat, size=128, act="sigmoid")
    logits = fluid.layers.fc(input=hidden, size=dict_size)
    cost = fluid.layers.softmax_with_cross_entropy(logits=logits,
                                                   label=next_word)
    avg_cost = fluid.layers.mean(cost)
    fluid.optimizer.Adam(learning_rate=2e-3).minimize(avg_cost)

    train_reader = fluid.batch(fluid.dataset.imikolov.train(word_dict, 5),
                               batch_size=64)
    place = fluid.CPUPlace()
    exe = fluid.Executor(place)
    feeder = fluid.DataFeeder(
        place=place, feed_list=[first, second, third, forth, next_word])
    exe.run(fluid.default_startup_program())

    losses = []
    for epoch in range(3):
        for data in train_reader():
            data = [([a], [b], [c], [d], [e]) for a, b, c, d, e in data]
            loss, = exe.run(fluid.default_main_program(),
                            feed=feeder.feed(data), fetch_list=[avg_cost])
            losses.append(float(np.ravel(loss)[0]))
    assert losses[-1] < losses[0] * 0.8, (losses[0], losses[-1])

    from tests.book._roundtrip import assert_infer_roundtrip
    rng = np.random.RandomState(0)
    ctx = {n: rng.randint(0, dict_size, (6, 1)).astype(np.int64)
           for n in ("firstw", "secondw", "thirdw", "forthw")}
    out, = assert_infer_roundtrip(exe, place, ctx, [logits])
    assert np.asarray(out).shape == (6, dict_size)
