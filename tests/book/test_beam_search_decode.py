"""Beam-search generation: While loop + TensorArrays + beam_search ops
(reference tests/book/test_machine_translation.py decode_main +
beam_search_op.cc/beam_search_decode_op.cc). Builds a decoder over dense
[B,K] beam lanes and checks the selected hypotheses are consistent."""

import numpy as np

import paddle_tpu as fluid

V = 50          # vocab
K = 4           # beam width
MAX_LEN = 6
START, END = 0, 1
H = 16


def build_decode_program(capacity=MAX_LEN + 1):
    src = fluid.layers.data(name="src", shape=[1], dtype="int64",
                            lod_level=1)
    src_emb = fluid.layers.embedding(input=src, size=[V, H])
    enc = fluid.layers.sequence_pool(src_emb, "sum")      # [B,H] context

    counter = fluid.layers.fill_constant(shape=[1], dtype="int64", value=0)
    max_len = fluid.layers.fill_constant(shape=[1], dtype="int64",
                                         value=MAX_LEN)
    # beam lanes: ids [B,K]; scores [B,K] with only lane 0 live initially
    init_ids = fluid.layers.fill_constant_batch_size_like(
        input=enc, shape=[-1, K], dtype="int64", value=START)
    lane_penalty = fluid.layers.assign(
        np.concatenate([[0.0], np.full(K - 1, -1e9)]).astype(np.float32))
    init_scores = fluid.layers.elementwise_add(
        fluid.layers.fill_constant_batch_size_like(
            input=enc, shape=[-1, K], dtype="float32", value=0.0),
        lane_penalty, axis=1)

    ids_arr = fluid.layers.array_write(init_ids, counter, capacity=capacity)
    parents_arr = fluid.layers.array_write(
        fluid.layers.cast(init_ids, "int32"), counter, capacity=capacity)
    scores_arr = fluid.layers.array_write(init_scores, counter,
                                          capacity=capacity)

    pre_ids = fluid.layers.assign(init_ids)
    pre_scores = fluid.layers.assign(init_scores)

    cond = fluid.layers.less_than(x=counter, y=max_len)
    w = fluid.layers.While(cond=cond)
    with w.block():
        tok_emb = fluid.layers.embedding(input=pre_ids, size=[V, H])  # [B,K,H]
        logits = fluid.layers.fc(input=tok_emb, size=V, num_flatten_dims=2)
        logp = fluid.layers.log(fluid.layers.softmax(logits))
        sel_ids, sel_scores, parent = fluid.layers.beam_search(
            pre_ids=pre_ids, pre_scores=pre_scores, scores=logp,
            beam_size=K, end_id=END)
        fluid.layers.increment(counter, value=1, in_place=True)
        fluid.layers.array_write(sel_ids, counter, array=ids_arr)
        fluid.layers.array_write(parent, counter, array=parents_arr)
        fluid.layers.array_write(sel_scores, counter, array=scores_arr)
        fluid.layers.assign(sel_ids, pre_ids)
        fluid.layers.assign(sel_scores, pre_scores)
        fluid.layers.less_than(x=counter, y=max_len, cond=cond)

    sentences, final_scores = fluid.layers.beam_search_decode(
        ids_arr, parents_arr, scores=scores_arr, end_id=END)
    return src, sentences, final_scores


def test_beam_search_decode():
    src, sentences, final_scores = build_decode_program()
    exe = fluid.Executor(fluid.CPUPlace())
    exe.run(fluid.default_startup_program())

    from paddle_tpu.executor import LoDTensor
    rows = [np.random.RandomState(i).randint(2, V, (3, 1)).astype(np.int64)
            for i in range(3)]
    flat = np.concatenate(rows, 0)
    offs = [0, 3, 6, 9]
    out_ids, out_scores = exe.run(
        fluid.default_main_program(),
        feed={"src": LoDTensor(flat, [offs])},
        fetch_list=[sentences, final_scores])

    bsz = 3
    assert out_ids.shape[0] == bsz and out_ids.shape[1] == K
    assert (out_ids >= 0).all() and (out_ids < V).all()
    # lanes come out of top_k: best lane first, scores non-increasing
    assert (np.diff(out_scores, axis=1) <= 1e-5).all()
    # every hypothesis starts from the START bootstrap lane
    assert (out_ids[:, :, 0] == START).all()


def test_beam_search_decode_slack_capacity():
    """TensorArray capacity larger than the written steps must not shift
    hypotheses: real tokens start at t=0, trailing slots are end_id padding
    (regression: the backtrack scan used to leave the (cap-n) invalid
    entries at the FRONT of the time axis)."""
    src, sentences, final_scores = build_decode_program(
        capacity=MAX_LEN + 5)
    exe = fluid.Executor(fluid.CPUPlace())
    exe.run(fluid.default_startup_program())

    from paddle_tpu.executor import LoDTensor
    rows = [np.random.RandomState(i).randint(2, V, (3, 1)).astype(np.int64)
            for i in range(3)]
    flat = np.concatenate(rows, 0)
    offs = [0, 3, 6, 9]
    out_ids, out_scores = exe.run(
        fluid.default_main_program(),
        feed={"src": LoDTensor(flat, [offs])},
        fetch_list=[sentences, final_scores])

    # hypotheses start with the real first token (the START bootstrap lane),
    # not with end_id slack
    assert (out_ids[:, :, 0] == START).all()
    # slack slots beyond the written steps are end_id padding at the BACK
    assert (out_ids[:, :, MAX_LEN + 1:] == END).all()
