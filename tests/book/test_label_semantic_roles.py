"""Book ch07: semantic role labeling with a linear-chain CRF (reference
tests/book/test_label_semantic_roles.py): 8 parallel input sequences ->
embeddings -> bidirectional LSTM stack -> emissions -> CRF cost; Viterbi
decode for evaluation."""

import numpy as np

import paddle_tpu as fluid

WORD_DICT_LEN = 2000   # active subset of the conll05 vocab
PRED_DICT_LEN = fluid.dataset.conll05.PRED_VOCAB
MARK_DICT_LEN = 2
LABEL_N = fluid.dataset.conll05.LABEL_N
EMB = 16
HID = 32


def db_lstm(word, predicate, ctx_n2, ctx_n1, ctx_0, ctx_p1, ctx_p2, mark):
    pred_emb = fluid.layers.embedding(input=predicate,
                                      size=[PRED_DICT_LEN, EMB])
    mark_emb = fluid.layers.embedding(input=mark, size=[MARK_DICT_LEN, EMB])
    word_inputs = [word, ctx_n2, ctx_n1, ctx_0, ctx_p1, ctx_p2]
    embs = [fluid.layers.embedding(
        input=w, size=[WORD_DICT_LEN, EMB],
        param_attr=fluid.ParamAttr(name="word_emb")) for w in word_inputs]
    embs += [pred_emb, mark_emb]

    hidden_0 = fluid.layers.fc(input=embs, size=HID, num_flatten_dims=2,
                               act="tanh")
    lstm_0, _ = fluid.layers.dynamic_lstm(input=fluid.layers.fc(
        input=hidden_0, size=HID * 4, num_flatten_dims=2), size=HID * 4)
    # stacked bidirectional: alternate direction each depth
    input_tmp = [hidden_0, lstm_0]
    for i in range(2):
        mix = fluid.layers.fc(input=input_tmp, size=HID * 4,
                              num_flatten_dims=2)
        lstm, _ = fluid.layers.dynamic_lstm(input=mix, size=HID * 4,
                                            is_reverse=(i % 2 == 0))
        input_tmp = [mix, lstm]
    emission = fluid.layers.fc(input=input_tmp, size=LABEL_N,
                               num_flatten_dims=2)
    return emission


def test_label_semantic_roles():
    names = ["word_data", "verb_data", "ctx_n2_data", "ctx_n1_data",
             "ctx_0_data", "ctx_p1_data", "ctx_p2_data", "mark_data"]
    feeds = [fluid.layers.data(name=n, shape=[1], dtype="int64", lod_level=1)
             for n in names]
    target = fluid.layers.data(name="target", shape=[1], dtype="int64",
                               lod_level=1)
    emission = db_lstm(*feeds)
    crf_cost = fluid.layers.linear_chain_crf(
        input=emission, label=target,
        param_attr=fluid.ParamAttr(name="crfw"))
    avg_cost = fluid.layers.mean(crf_cost)
    fluid.optimizer.Adam(learning_rate=5e-3).minimize(avg_cost)

    # Viterbi decode path shares the transition parameter
    decode = fluid.layers.crf_decoding(
        input=emission, param_attr=fluid.ParamAttr(name="crfw"))

    def sample(rng):
        ln = int(rng.randint(4, 12))
        words = rng.randint(0, 200, ln)
        pred_id = int(rng.randint(0, 50))
        labels = (words * 7) % LABEL_N  # word-determined tag: learnable
        ctxs = [np.roll(words, k) for k in (-2, -1, 0, 1, 2)]
        mark = (rng.rand(ln) < 0.2).astype(np.int64)
        return (words, np.full(ln, pred_id), *ctxs, mark, labels)

    place = fluid.CPUPlace()
    exe = fluid.Executor(place)
    feeder = fluid.DataFeeder(place=place, feed_list=feeds + [target])
    exe.run(fluid.default_startup_program())

    rng = np.random.RandomState(0)
    losses = []
    for step in range(55):
        batch = []
        for _ in range(16):
            fields = sample(rng)
            batch.append(tuple([[int(v)] for v in f] for f in fields))
        l, = exe.run(fluid.default_main_program(),
                     feed=feeder.feed(batch), fetch_list=[avg_cost])
        losses.append(float(np.ravel(l)[0]))
    assert np.mean(losses[-10:]) < np.mean(losses[:10]) * 0.7, (
        losses[:5], losses[-5:])

    # decode produces a valid path over the batch
    batch = []
    for _ in range(4):
        fields = sample(rng)
        batch.append(tuple([[int(v)] for v in f] for f in fields))
    path, = exe.run(fluid.default_main_program(),
                    feed=feeder.feed(batch), fetch_list=[decode])
    assert np.issubdtype(path.dtype, np.integer)
    assert (path >= 0).all() and (path < LABEL_N).all()

    # inference round-trip on the Viterbi decode path (the reference's C++
    # inference test loads exactly this artifact)
    from tests.book._roundtrip import assert_infer_roundtrip
    from paddle_tpu.executor import LoDTensor

    def lod_feed(batch_fields):
        feed = {}
        for name, col in zip(names, range(8)):
            rows, offs = [], [0]
            for b in batch_fields:
                arr = np.asarray(b[col], np.int64)
                rows.append(arr)
                offs.append(offs[-1] + len(arr))
            feed[name] = LoDTensor(np.concatenate(rows, 0), [offs])
        return feed
    fields4 = [tuple([[int(v)] for v in f] for f in sample(rng))
               for _ in range(4)]
    rt_path, = assert_infer_roundtrip(exe, place, lod_feed(fields4),
                                      [decode])
    rt_path = np.asarray(rt_path)
    assert (rt_path >= 0).all() and (rt_path < LABEL_N).all()
