"""Book ch02: digit recognition, MLP + conv variants (reference
tests/book/test_recognize_digits.py)."""

import numpy as np
import pytest

import paddle_tpu as fluid
from paddle_tpu import models


@pytest.mark.parametrize("net", ["mlp", "conv"])
def test_recognize_digits(net):
    if net == "mlp":
        img = fluid.layers.data(name="img", shape=[784], dtype="float32")
        model_fn = models.mnist_mlp
    else:
        img = fluid.layers.data(name="img", shape=[1, 28, 28],
                                dtype="float32")
        model_fn = models.mnist_conv
    label = fluid.layers.data(name="label", shape=[1], dtype="int64")
    avg_cost, predict, acc = models.build_image_classifier(
        model_fn, img, label, class_dim=10)
    fluid.optimizer.Adam(learning_rate=1e-3).minimize(avg_cost)

    train_reader = fluid.batch(
        fluid.reader.shuffle(fluid.dataset.mnist.train(), buf_size=500),
        batch_size=64)
    place = fluid.CPUPlace()
    exe = fluid.Executor(place)
    feeder = fluid.DataFeeder(place=place, feed_list=[img, label])
    exe.run(fluid.default_startup_program())

    accs, losses = [], []
    for i, data in enumerate(train_reader()):
        if net == "conv":
            data = [(np.reshape(im, (1, 28, 28)), l) for im, l in data]
        loss, a = exe.run(fluid.default_main_program(),
                          feed=feeder.feed(data), fetch_list=[avg_cost, acc])
        accs.append(float(np.ravel(a)[0]))
        losses.append(float(np.ravel(loss)[0]))
        if i >= 60:
            break
    # explicit thresholds (reference trains until avg_cost < 0.2-ish on a
    # per-pass test set; the synthetic blobs converge much faster)
    assert np.mean(accs[-10:]) > 0.7, accs[-10:]
    assert np.mean(losses[-10:]) < 1.0, losses[-10:]

    from tests.book._roundtrip import assert_infer_roundtrip
    shape = (4, 784) if net == "mlp" else (4, 1, 28, 28)
    xs = np.random.RandomState(0).rand(*shape).astype(np.float32)
    probs, = assert_infer_roundtrip(exe, place, {"img": xs}, [predict])
    probs = np.asarray(probs)
    assert probs.shape == (4, 10)
    np.testing.assert_allclose(probs.sum(axis=1), np.ones(4), rtol=1e-4)
