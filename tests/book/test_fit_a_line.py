"""Book ch01: linear regression (reference tests/book/test_fit_a_line.py):
train on uci_housing until loss threshold, save inference model, reload it
into a fresh scope and check predictions match."""

import os
import tempfile

import numpy as np

import paddle_tpu as fluid
from paddle_tpu import executor as executor_mod


def test_fit_a_line_book():
    x = fluid.layers.data(name="x", shape=[13], dtype="float32")
    y = fluid.layers.data(name="y", shape=[1], dtype="float32")
    y_predict = fluid.layers.fc(input=x, size=1, act=None)
    cost = fluid.layers.square_error_cost(input=y_predict, label=y)
    avg_cost = fluid.layers.mean(cost)
    fluid.optimizer.SGD(learning_rate=0.01).minimize(avg_cost)

    train_reader = fluid.batch(
        fluid.reader.shuffle(fluid.dataset.uci_housing.train(), buf_size=500),
        batch_size=20)

    place = fluid.CPUPlace()
    exe = fluid.Executor(place)
    feeder = fluid.DataFeeder(place=place, feed_list=[x, y])
    exe.run(fluid.default_startup_program())

    last = None
    for pass_id in range(12):
        for data in train_reader():
            loss, = exe.run(fluid.default_main_program(),
                            feed=feeder.feed(data), fetch_list=[avg_cost])
            last = float(np.ravel(loss)[0])
        if last < 0.3:
            break
    assert last is not None and last < 1.0, f"loss did not drop: {last}"

    from tests.book._roundtrip import assert_infer_roundtrip
    xs = np.random.RandomState(0).randn(8, 13).astype(np.float32)
    results, = assert_infer_roundtrip(exe, place, {"x": xs}, [y_predict])
    assert np.asarray(results).shape == (8, 1)
    assert np.isfinite(results).all()
