"""Beyond-reference book chapter: decoder-only transformer LM
(models/transformer.py) trained end-to-end — the config that makes the
Pallas flash-attention kernels (forward AND backward) load-bearing in a
real training graph. The 2018 reference has no attention op (SURVEY.md
§2.5 last row); the loss-decreases + save/infer pattern mirrors its book
tests (e.g. reference tests/book/test_word2vec.py)."""

import os
import tempfile

import numpy as np
import pytest

import paddle_tpu as fluid
from paddle_tpu import models


VOCAB, SEQLEN = 128, 64


def _data(rng, batch):
    seq = rng.integers(0, VOCAB, (batch, SEQLEN + 1))
    return (seq[:, :-1].astype(np.int64), seq[:, 1:].astype(np.int64))


@pytest.mark.parametrize("use_flash", [False, True])
def test_train_loss_decreases(use_flash):
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        tok = fluid.layers.data(name="tok", shape=[-1, SEQLEN],
                                dtype="int64", append_batch_size=False)
        lab = fluid.layers.data(name="lab", shape=[-1, SEQLEN],
                                dtype="int64", append_batch_size=False)
        loss = models.transformer_lm(tok, lab, vocab_size=VOCAB,
                                     d_model=64, n_head=2, n_layer=2,
                                     use_flash=use_flash)
        fluid.optimizer.Adam(learning_rate=1e-3).minimize(
            loss, startup_program=startup)

    exe = fluid.Executor(fluid.CPUPlace())
    exe.run(startup)
    rng = np.random.default_rng(7)
    toks, labs = _data(rng, 4)
    losses = []
    for _ in range(25):
        out, = exe.run(main, feed={"tok": toks, "lab": labs},
                       fetch_list=[loss])
        losses.append(float(np.asarray(out).ravel()[0]))
    assert np.isfinite(losses).all()
    # memorizing one fixed batch: loss must drop decisively
    assert losses[-1] < losses[0] * 0.7, losses


def test_flash_and_einsum_paths_agree():
    """Same seed, same feed: one training step under use_flash=True vs
    False produces the same loss to flash-recompute tolerance."""
    from paddle_tpu.framework import unique_name
    vals = {}
    for flash in (False, True):
        # identical parameter names across the two builds: name feeds the
        # per-parameter init stream, so the generator must restart
        unique_name.switch()
        main, startup = fluid.Program(), fluid.Program()
        with fluid.program_guard(main, startup):
            main.random_seed = startup.random_seed = 11
            tok = fluid.layers.data(name="tok", shape=[-1, SEQLEN],
                                    dtype="int64", append_batch_size=False)
            lab = fluid.layers.data(name="lab", shape=[-1, SEQLEN],
                                    dtype="int64", append_batch_size=False)
            loss = models.transformer_lm(tok, lab, vocab_size=VOCAB,
                                         d_model=64, n_head=2, n_layer=1,
                                         use_flash=flash)
            fluid.optimizer.SGD(learning_rate=0.1).minimize(
                loss, startup_program=startup)
        from paddle_tpu import executor as executor_mod
        exe = fluid.Executor(fluid.CPUPlace())
        with executor_mod.scope_guard(executor_mod.Scope()):
            exe.run(startup)
            rng = np.random.default_rng(3)
            toks, labs = _data(rng, 2)
            run = [float(np.asarray(exe.run(
                main, feed={"tok": toks, "lab": labs},
                fetch_list=[loss])[0]).ravel()[0]) for _ in range(3)]
        vals[flash] = run
    np.testing.assert_allclose(vals[True], vals[False], rtol=1e-4,
                               atol=1e-4)
