"""Kill-and-resume checkpoint training (reference fault-tolerance story:
go/master/service.go:166 recover, go/pserver/service.go:346 checkpoint load;
here checkpointed synchronous training — elastic is descoped, see README)."""

import json
import os
import subprocess
import sys

import numpy as np
import pytest

import paddle_tpu as fluid
from paddle_tpu.parallel import multihost

TRAINER = r'''
import os, sys, json
import numpy as np
os.environ.setdefault("JAX_PLATFORMS", "cpu")
import paddle_tpu as fluid
from paddle_tpu.parallel import multihost

ckpt_dir = sys.argv[1]
die_after = int(sys.argv[2])      # crash after this step (-1 = never)
total_steps = int(sys.argv[3])

main, startup = fluid.Program(), fluid.Program()
with fluid.program_guard(main, startup):
    x = fluid.layers.data(name="x", shape=[4], dtype="float32")
    y = fluid.layers.data(name="y", shape=[1], dtype="float32")
    pred = fluid.layers.fc(input=x, size=1, param_attr=fluid.ParamAttr(name="w"))
    loss = fluid.layers.mean(fluid.layers.square_error_cost(pred, y))
    fluid.optimizer.SGDOptimizer(learning_rate=0.1).minimize(loss)

exe = fluid.Executor(fluid.CPUPlace())
exe.run(startup)
meta = multihost.load_checkpoint(exe, ckpt_dir, main_program=main)
start = meta["step"] + 1 if meta else 0

rng = np.random.RandomState(0)
data = [(rng.randn(8, 4).astype(np.float32),) for _ in range(total_steps)]
w_true = rng.randn(4, 1).astype(np.float32)

for step in range(start, total_steps):
    xs, = data[step]
    exe.run(main, feed={"x": xs, "y": xs @ w_true}, fetch_list=[loss])
    multihost.save_checkpoint(exe, ckpt_dir, step, main_program=main)
    if step == die_after:
        os._exit(17)              # simulated crash: no cleanup

from paddle_tpu import executor as executor_mod
w = np.asarray(executor_mod.global_scope().find_var("w"))
print(json.dumps({"final_w": w.reshape(-1).tolist(), "start": start}))
'''


class TestKillAndResume:
    def _run(self, ckpt_dir, die_after, total):
        return subprocess.run(
            [sys.executable, "-c", TRAINER, ckpt_dir, str(die_after),
             str(total)],
            capture_output=True, text=True,
            cwd=os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

    def test_resume_matches_uninterrupted(self, tmp_path):
        total = 6
        # uninterrupted run
        clean_dir = str(tmp_path / "clean")
        os.makedirs(clean_dir)
        r = self._run(clean_dir, -1, total)
        assert r.returncode == 0, r.stderr[-2000:]
        clean = json.loads(r.stdout.strip().splitlines()[-1])

        # crashed at step 2, resumed
        crash_dir = str(tmp_path / "crash")
        os.makedirs(crash_dir)
        r1 = self._run(crash_dir, 2, total)
        assert r1.returncode == 17     # the simulated crash
        r2 = self._run(crash_dir, -1, total)
        assert r2.returncode == 0, r2.stderr[-2000:]
        resumed = json.loads(r2.stdout.strip().splitlines()[-1])

        assert resumed["start"] == 3   # resumed after the last checkpoint
        np.testing.assert_allclose(resumed["final_w"], clean["final_w"],
                                   rtol=1e-6)


class TestShardReader:
    def test_disjoint_partitions_cover_stream(self):
        samples = list(range(23))
        shards = [multihost.shard_reader(lambda: iter(samples),
                                         num_shards=4, shard_id=i)
                  for i in range(4)]
        seen = [list(s()) for s in shards]
        flat = sorted(x for part in seen for x in part)
        assert flat == samples                      # full coverage
        for i, part in enumerate(seen):             # disjoint + strided
            assert part == samples[i::4]


class TestCheckpointMeta:
    def test_atomic_meta_and_latest(self, tmp_path):
        d = str(tmp_path)
        main, startup = fluid.Program(), fluid.Program()
        with fluid.program_guard(main, startup):
            v = fluid.layers.tensor.create_global_var(
                shape=[2], value=1.5, dtype="float32", persistable=True,
                name="pv")
        exe = fluid.Executor(fluid.CPUPlace())
        from paddle_tpu import executor as executor_mod
        with executor_mod.scope_guard(executor_mod.Scope()):
            exe.run(startup)
            assert multihost.latest_checkpoint(d) is None
            multihost.save_checkpoint(exe, d, 0, main_program=main)
            multihost.save_checkpoint(exe, d, 1, main_program=main,
                                      extra_meta={"pass": 0})
            meta = multihost.latest_checkpoint(d)
            assert meta["step"] == 1 and meta["pass"] == 0


class TestCheckpointableReader:
    """Mid-pass resume without replaying or losing samples
    (go/master/service.go:207 snapshot / :166 recover parity)."""

    def test_mid_pass_resume_no_replay_no_loss(self, tmp_path):
        d = str(tmp_path)
        data = list(range(10))
        main, startup = fluid.Program(), fluid.Program()
        with fluid.program_guard(main, startup):
            fluid.layers.tensor.create_global_var(
                shape=[1], value=0.0, dtype="float32", persistable=True,
                name="pv2")
        exe = fluid.Executor(fluid.CPUPlace())
        from paddle_tpu import executor as executor_mod

        # "trainer" 1: consume 4 samples, checkpoint, crash
        r1 = multihost.CheckpointableReader(lambda: iter(data))
        consumed = []
        with executor_mod.scope_guard(executor_mod.Scope()):
            exe.run(startup)
            it = r1()
            for _ in range(4):
                consumed.append(next(it))
            multihost.save_checkpoint(exe, d, step=3, main_program=main,
                                      reader=r1)
        assert consumed == [0, 1, 2, 3]

        # "trainer" 2: fresh process, restore, drain the pass
        r2 = multihost.CheckpointableReader(lambda: iter(data))
        with executor_mod.scope_guard(executor_mod.Scope()):
            exe.run(startup)
            meta = multihost.load_checkpoint(exe, d, main_program=main,
                                             reader=r2)
        assert meta["step"] == 3
        rest = list(r2())
        # provably: no replay of 0-3, no loss of 4-9
        assert rest == [4, 5, 6, 7, 8, 9]
        # next pass starts clean
        assert list(r2()) == data
        assert r2.pass_id == 2

    def test_pass_id_survives(self, tmp_path):
        d = str(tmp_path)
        data = [10, 11, 12]
        main, startup = fluid.Program(), fluid.Program()
        with fluid.program_guard(main, startup):
            fluid.layers.tensor.create_global_var(
                shape=[1], value=0.0, dtype="float32", persistable=True,
                name="pv3")
        exe = fluid.Executor(fluid.CPUPlace())
        from paddle_tpu import executor as executor_mod
        r = multihost.CheckpointableReader(lambda: iter(data))
        list(r()); list(r())        # two full passes
        it = r(); next(it)          # one sample into pass 2
        with executor_mod.scope_guard(executor_mod.Scope()):
            exe.run(startup)
            multihost.save_checkpoint(exe, d, step=7, main_program=main,
                                      reader=r)
        r2 = multihost.CheckpointableReader(lambda: iter(data))
        with executor_mod.scope_guard(executor_mod.Scope()):
            exe.run(startup)
            multihost.load_checkpoint(exe, d, main_program=main, reader=r2)
        assert r2.pass_id == 2 and r2.offset == 1
        assert list(r2()) == [11, 12]

    def test_in_flight_samples_replayed_not_lost(self, tmp_path):
        """A prefetch buffer between reader and trainer: checkpoint with
        in_flight=k backs the position up so buffered samples are re-read."""
        d = str(tmp_path)
        data = list(range(8))
        main, startup = fluid.Program(), fluid.Program()
        with fluid.program_guard(main, startup):
            fluid.layers.tensor.create_global_var(
                shape=[1], value=0.0, dtype="float32", persistable=True,
                name="pv4")
        exe = fluid.Executor(fluid.CPUPlace())
        from paddle_tpu import executor as executor_mod
        r = multihost.CheckpointableReader(lambda: iter(data))
        it = r()
        # trainer processed 3 samples; prefetcher pulled 2 more (in flight)
        for _ in range(5):
            next(it)
        with executor_mod.scope_guard(executor_mod.Scope()):
            exe.run(startup)
            multihost.save_checkpoint(exe, d, step=2, main_program=main,
                                      reader=r, reader_in_flight=2)
        r2 = multihost.CheckpointableReader(lambda: iter(data))
        with executor_mod.scope_guard(executor_mod.Scope()):
            exe.run(startup)
            multihost.load_checkpoint(exe, d, main_program=main, reader=r2)
        # in-flight samples 3,4 come back (replayed), nothing lost
        assert list(r2()) == [3, 4, 5, 6, 7]
