"""Program inspector (ISSUE 2): on-device tensor-stat probes, NaN/Inf
origin attribution by bisection replay, gradient-flow audit, crash flight
recorder — plus the satellites that rode along (fetch-level NonFiniteError
with var name/dtype, runtime vlog + check_nan_inf toggles via flags.set,
debugger dot-failure fallback, probe-compat op report)."""

import json
import math
import os
import subprocess
import sys
import warnings

import numpy as np
import pytest

import paddle_tpu as fluid
from paddle_tpu import cli, debugger, inspector, telemetry
from paddle_tpu import executor as executor_mod
from paddle_tpu import flags
from paddle_tpu.errors import NonFiniteError

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


@pytest.fixture(autouse=True)
def _fresh_telemetry():
    telemetry.reset()
    yield
    inspector.disable_flight_recorder()
    telemetry.reset()


def _chain_program(n_scales_after=20):
    """feed x -> scale -> scale -> log (3rd op; NaN for negative x)
    -> n more scales -> reduce_sum."""
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        x = fluid.layers.data(name="x", shape=[4], dtype="float32")
        h = fluid.layers.scale(x, scale=2.0)
        h = fluid.layers.scale(h, scale=0.5)
        h = fluid.layers.log(h)                     # op index 2
        for _ in range(n_scales_after):
            h = fluid.layers.scale(h, scale=1.0)
        out = fluid.layers.reduce_sum(h)
    return main, startup, out


class TestProbes:
    def test_probed_run_matches_unprobed(self):
        main, startup, out = _chain_program(n_scales_after=3)
        exe = fluid.Executor(fluid.CPUPlace())
        exe.run(startup)
        feed = {"x": np.array([[1.0, 2.0, 3.0, 4.0]], np.float32)}
        base, = exe.run(main, feed=feed, fetch_list=[out])

        probed = inspector.instrument(main, every=True)
        got, = exe.run(probed, feed=feed, fetch_list=[out])
        np.testing.assert_array_equal(base, got)

        report = inspector.probe_report(probed)
        assert report, "probed run must record stats"
        by_var = {r["var"]: r["stats"] for r in report}
        # the log output's stats must agree with numpy
        ref = np.log(np.array([1.0, 2.0, 3.0, 4.0], np.float32))
        log_stats = [r["stats"] for r in report if r["op_type"] == "log"][0]
        assert log_stats["min"] == pytest.approx(float(ref.min()), rel=1e-6)
        assert log_stats["max"] == pytest.approx(float(ref.max()), rel=1e-6)
        assert log_stats["mean"] == pytest.approx(float(ref.mean()), rel=1e-6)
        assert log_stats["nan_count"] == 0 and log_stats["inf_count"] == 0
        assert all(s["nan_count"] == 0 for s in by_var.values())

    def test_probe_detects_nonfinite_and_attributes(self):
        main, startup, out = _chain_program(n_scales_after=3)
        exe = fluid.Executor(fluid.CPUPlace())
        exe.run(startup)
        probed = inspector.instrument(main, every=True)
        feed = {"x": np.array([[-1.0, 2.0, 3.0, 4.0]], np.float32)}
        with pytest.raises(NonFiniteError) as ei:
            exe.run(probed, feed=feed, fetch_list=[out])
        assert ei.value.attribution is not None
        assert ei.value.attribution.op_type == "log"

    def test_selection_modes(self):
        main, startup, out = _chain_program(n_scales_after=3)
        p_type = inspector.instrument(main, types=["log"])
        assert len(p_type._probe_sites) == 1
        assert p_type._probe_sites[0].op_type == "log"
        p_rx = inspector.instrument(main, regex=r"reduce_sum.*")
        assert all(s.op_type == "reduce_sum" for s in p_rx._probe_sites)
        with pytest.raises(ValueError):
            inspector.instrument(main, types=["no_such_op"])

    def test_auto_mode_targets_loss_and_grads(self):
        main, startup = fluid.Program(), fluid.Program()
        with fluid.program_guard(main, startup):
            x = fluid.layers.data(name="x", shape=[4], dtype="float32")
            y = fluid.layers.data(name="y", shape=[1], dtype="float32")
            pred = fluid.layers.fc(input=x, size=1)
            loss = fluid.layers.mean(
                fluid.layers.square_error_cost(pred, y))
            fluid.optimizer.SGD(learning_rate=0.1).minimize(loss)
        probed = inspector.instrument(main, auto=True)
        sites = probed._probe_sites
        assert any(s.var == loss.name for s in sites)
        assert any("@GRAD" in s.var for s in sites)
        exe = fluid.Executor(fluid.CPUPlace())
        exe.run(startup)
        rng = np.random.RandomState(0)
        feed = {"x": rng.randn(8, 4).astype(np.float32),
                "y": rng.randn(8, 1).astype(np.float32)}
        exe.run(probed, feed=feed, fetch_list=[loss])
        rep = inspector.probe_report(probed)
        assert len(rep) == len(sites)
        loss_stats = [r["stats"] for r in rep if r["var"] == loss.name][0]
        assert loss_stats["nan_count"] == 0

    def test_probe_compatible_predicate(self):
        assert inspector.probe_compatible("relu")
        assert inspector.probe_compatible("elementwise_add")
        assert not inspector.probe_compatible("while")
        assert not inspector.probe_compatible("feed")
        assert not inspector.probe_compatible("tensor_stats")
        assert not inspector.probe_compatible("not_a_registered_op")

    def test_op_coverage_probe_compat_report(self):
        env = dict(os.environ, PYTHONPATH=REPO, JAX_PLATFORMS="cpu")
        r = subprocess.run(
            [sys.executable, os.path.join(REPO, "tools", "op_coverage.py"),
             "--probe-compat"],
            capture_output=True, text=True, env=env, timeout=300)
        assert r.returncode == 0, r.stderr[-1500:]
        nums = {}
        for line in r.stdout.splitlines():
            if ":" in line and not line.startswith(" "):
                k, v = line.split(":")
                nums[k.strip()] = int(v)
        # a fresh interpreter registers a (possibly smaller) op set than a
        # long-lived test process, so check consistency, not exact counts
        assert nums["probe-compatible"] + nums["not probeable"] \
            == nums["registered ops"]
        assert nums["probe-compatible"] > nums["not probeable"]
        assert "NOT-PROBEABLE while" in r.stdout


class TestAttribution:
    def test_nan_at_third_op_found_in_log_runs(self):
        main, startup, out = _chain_program(n_scales_after=20)
        exe = fluid.Executor(fluid.CPUPlace())
        exe.run(startup)
        feed = {"x": np.array([[-1.0, 2.0, 3.0, 4.0]], np.float32)}
        attr = inspector.attribute_nonfinite(exe, main, feed)
        assert attr is not None
        assert attr.op_type == "log" and attr.op_index == 2
        # input stats of the offending op show the negative operand
        assert attr.input_stats
        in_st = next(iter(attr.input_stats.values()))
        assert in_st.min < 0 and in_st.nan_count == 0
        # O(log n) acceptance bound: bisection, not an op-by-op sweep
        n_cands = sum(1 for op in main.global_block().ops
                      if inspector.probe_compatible(op.type))
        bound = math.ceil(math.log2(max(n_cands, 2))) + 3
        assert attr.runs <= bound, (attr.runs, bound)

    def test_inconclusive_on_finite_feed(self):
        main, startup, out = _chain_program(n_scales_after=3)
        exe = fluid.Executor(fluid.CPUPlace())
        exe.run(startup)
        feed = {"x": np.array([[1.0, 2.0, 3.0, 4.0]], np.float32)}
        assert inspector.attribute_nonfinite(exe, main, feed) is None


class TestFetchCheck:
    def test_fetch_level_nonfinite_names_var_and_dtype(self, monkeypatch):
        monkeypatch.setattr(executor_mod, "_CHECK_NAN_INF", True)
        main, startup, out = _chain_program(n_scales_after=3)
        exe = fluid.Executor(fluid.CPUPlace())
        exe.run(startup)
        feed = {"x": np.array([[-1.0, 2.0, 3.0, 4.0]], np.float32)}
        with pytest.raises(NonFiniteError) as ei:
            exe.run(main, feed=feed, fetch_list=[out])
        e = ei.value
        assert e.var_name == out.name
        assert e.dtype == "float32"
        assert "float32" in str(e) and out.name in str(e)
        # legacy except-clauses must keep catching it
        assert isinstance(e, RuntimeError)
        assert isinstance(e, FloatingPointError)
        # attribution rode along and names the true origin, not the fetch
        assert e.attribution is not None
        assert e.attribution.op_type == "log"

    def test_check_nan_inf_runtime_toggle_fresh_subprocess(self, tmp_path):
        script = tmp_path / "toggle.py"
        script.write_text(
            "import numpy as np\n"
            "import paddle_tpu as fluid\n"
            "from paddle_tpu import flags\n"
            "from paddle_tpu.errors import NonFiniteError\n"
            "x = fluid.layers.data(name='x', shape=[2], dtype='float32')\n"
            "y = fluid.layers.log(x)\n"
            "exe = fluid.Executor(fluid.CPUPlace())\n"
            "exe.run(fluid.default_startup_program())\n"
            "feed = {'x': np.array([[-1.0, 1.0]], np.float32)}\n"
            "out, = exe.run(feed=feed, fetch_list=[y])\n"
            "print('OFF-OK', np.isnan(out).any())\n"
            "flags.set('check_nan_inf', True)\n"
            "assert flags.get('check_nan_inf') is True\n"
            "try:\n"
            "    exe.run(feed=feed, fetch_list=[y])\n"
            "    print('ON-MISSED')\n"
            "except NonFiniteError as e:\n"
            "    print('ON-RAISED', e.var_name)\n"
            "flags.set('check_nan_inf', False)\n"
            "out, = exe.run(feed=feed, fetch_list=[y])\n"
            "print('OFF-AGAIN-OK', np.isnan(out).any())\n")
        env = dict(os.environ, PYTHONPATH=REPO, JAX_PLATFORMS="cpu")
        env.pop("PADDLE_TPU_CHECK_NAN_INF", None)
        r = subprocess.run([sys.executable, str(script)],
                           capture_output=True, text=True, env=env,
                           timeout=300)
        assert r.returncode == 0, r.stderr[-2000:]
        assert "OFF-OK True" in r.stdout
        assert "ON-RAISED" in r.stdout and "ON-MISSED" not in r.stdout
        assert "OFF-AGAIN-OK True" in r.stdout

    def test_trap_fp_subprocess(self, tmp_path):
        script = tmp_path / "trap.py"
        script.write_text(
            "import numpy as np\n"
            "import paddle_tpu as fluid\n"
            "x = fluid.layers.data(name='x', shape=[2], dtype='float32')\n"
            "y = fluid.layers.log(x)\n"
            "exe = fluid.Executor(fluid.CPUPlace())\n"
            "exe.run(fluid.default_startup_program())\n"
            "exe.run(feed={'x': np.array([[-1.0, 1.0]], np.float32)},\n"
            "        fetch_list=[y])\n"
            "print('UNREACHED')\n")
        env = dict(os.environ, PYTHONPATH=REPO, JAX_PLATFORMS="cpu",
                   PADDLE_TPU_TRAP_FP="1")
        r = subprocess.run([sys.executable, str(script)],
                           capture_output=True, text=True, env=env,
                           timeout=300)
        assert r.returncode != 0
        assert "UNREACHED" not in r.stdout
        assert "nan" in (r.stdout + r.stderr).lower()


class TestVlogToggle:
    def test_flags_set_vlog_changes_runtime_verbosity(self, capsys):
        try:
            flags.set("vlog", 0)
            executor_mod.vlog(1, "quiet")
            assert "quiet" not in capsys.readouterr().err
            flags.set("vlog", 2)
            executor_mod.vlog(1, "loud-now")
            assert "loud-now" in capsys.readouterr().err
            executor_mod.vlog(3, "too-deep")
            assert "too-deep" not in capsys.readouterr().err
        finally:
            flags.set("vlog", None)


class TestGradientAudit:
    @staticmethod
    def _two_branch_model():
        main, startup = fluid.Program(), fluid.Program()
        with fluid.program_guard(main, startup):
            x = fluid.layers.data(name="x", shape=[4], dtype="float32")
            live = fluid.layers.fc(
                input=x, size=3,
                param_attr=fluid.ParamAttr(name="w_live"), bias_attr=False)
            dead = fluid.layers.fc(
                input=x, size=3,
                param_attr=fluid.ParamAttr(name="w_dead"), bias_attr=False)
            dead.stop_gradient = True       # grad blocked: zero-valued grad
            fluid.layers.fc(                # never reaches the loss at all
                input=x, size=3,
                param_attr=fluid.ParamAttr(name="w_orphan"),
                bias_attr=False)
            out = fluid.layers.elementwise_add(live, dead)
            loss = fluid.layers.reduce_mean(out)
            fluid.backward.append_backward(loss)
        return main, startup, loss

    def test_detached_param_flagged_zero(self):
        main, startup, loss = self._two_branch_model()
        exe = fluid.Executor(fluid.CPUPlace())
        exe.run(startup)
        audit = inspector.GradientAudit(main)
        exe.run(audit.program,
                feed={"x": np.ones((2, 4), np.float32)},
                fetch_list=[loss])
        rep = audit.report()
        # blocked-by-stop_gradient: a grad var exists but is all zeros
        assert rep["w_dead"]["status"] == "zero"
        assert rep["w_dead"]["l2"] == 0
        # never on the loss path: no grad op at all -> reported detached
        assert rep["w_orphan"]["status"] == "zero"
        assert "detached" in rep["w_orphan"]["reason"]
        assert rep["w_live"]["status"] == "ok"
        assert rep["w_live"]["l2"] > 0
        # telemetry rode along: live gauge + flag counter for the dead param
        label = telemetry.program_label(audit.program)
        assert telemetry.read_gauge("grad_l2", program=label,
                                    param="w_live") > 0
        snap = telemetry.snapshot()
        flagged = snap["counters"].get("grad_audit_flags_total", {})
        assert any("w_dead" in k and "status=zero" in k for k in flagged)

    def test_thresholds_classify(self):
        audit_cls = inspector.GradientAudit
        main, startup, loss = self._two_branch_model()
        a = audit_cls(main, vanishing_threshold=1e-8,
                      exploding_threshold=1e3)
        mk = lambda vec: inspector.TensorStats(np.array(vec, np.float64))
        # (min, max, mean, abs_mean, l2, nan, inf, size)
        assert a.classify(mk([0, 0, 0, 0, 0, 0, 0, 8])) == "zero"
        assert a.classify(mk([-1e-9, 1e-9, 0, 1e-9, 1e-8, 0, 0, 8])) \
            == "vanishing"
        assert a.classify(mk([-2e3, 1.0, 0, 1.0, 2e3, 0, 0, 8])) \
            == "exploding"
        assert a.classify(mk([0, 1, 0.5, 0.5, 1, 1, 0, 8])) == "nonfinite"
        assert a.classify(mk([-1, 1, 0, 0.5, 1, 0, 0, 8])) == "ok"


class TestFlightRecorder:
    def _crash(self, tmp_path, monkeypatch):
        dump = tmp_path / "crash.json"
        inspector.enable_flight_recorder(str(dump), capacity=16)
        monkeypatch.setattr(executor_mod, "_CHECK_NAN_INF", True)
        main, startup, out = _chain_program(n_scales_after=3)
        exe = fluid.Executor(fluid.CPUPlace())
        exe.run(startup)
        ok = {"x": np.array([[1.0, 2.0, 3.0, 4.0]], np.float32)}
        for _ in range(3):
            exe.run(main, feed=ok, fetch_list=[out])
        bad = {"x": np.array([[-1.0, 2.0, 3.0, 4.0]], np.float32)}
        with pytest.raises(NonFiniteError):
            exe.run(main, feed=bad, fetch_list=[out])
        inspector.disable_flight_recorder()
        assert dump.exists(), "crash hook must write the report"
        return dump

    def test_dump_round_trips_through_cli_reader(self, tmp_path, monkeypatch,
                                                 capsys):
        dump = self._crash(tmp_path, monkeypatch)
        report = inspector.read_crash_report(str(dump))
        assert report["format"] == "paddle_tpu-crash-report"
        assert report["kind"] == "exception"
        assert report["error"]["type"] == "NonFiniteError"
        assert report["error"]["attribution"]["op_type"] == "log"
        assert len(report["steps"]) >= 3
        capsys.readouterr()

        rc = cli.main(["inspect", str(dump)])
        out = capsys.readouterr().out
        assert rc == 0
        assert "crash report" in out and "kind=exception" in out
        assert "NonFiniteError" in out
        assert "'log'" in out
        assert "steps recorded:" in out

        rc = cli.main(["inspect", str(dump), "--json"])
        out = capsys.readouterr().out
        assert rc == 0
        assert json.loads(out)["format"] == "paddle_tpu-crash-report"

    def test_reader_rejects_non_reports(self, tmp_path):
        p = tmp_path / "nope.json"
        p.write_text(json.dumps({"hello": 1}))
        with pytest.raises(ValueError):
            inspector.read_crash_report(str(p))

    def test_ring_is_bounded(self, tmp_path):
        rec = inspector.enable_flight_recorder(str(tmp_path / "r.json"),
                                               capacity=4)
        main, startup, out = _chain_program(n_scales_after=1)
        exe = fluid.Executor(fluid.CPUPlace())
        exe.run(startup)
        feed = {"x": np.ones((1, 4), np.float32)}
        for _ in range(9):
            exe.run(main, feed=feed, fetch_list=[out])
        assert len(rec.records) == 4
        inspector.disable_flight_recorder()


class TestDebuggerDotFallback:
    @staticmethod
    def _tiny_program():
        main, startup = fluid.Program(), fluid.Program()
        with fluid.program_guard(main, startup):
            x = fluid.layers.data(name="x", shape=[2], dtype="float32")
            fluid.layers.relu(x)
        return main

    def test_dot_nonzero_exit_warns_and_keeps_dot(self, tmp_path,
                                                  monkeypatch):
        main = self._tiny_program()
        path = tmp_path / "g.dot"

        class FakeProc:
            returncode = 1
            stderr = b"boom: bad layout"

        monkeypatch.setattr(debugger.shutil, "which", lambda _: "/bin/dot")
        monkeypatch.setattr(debugger.subprocess, "run",
                            lambda *a, **k: FakeProc())
        with pytest.warns(RuntimeWarning, match="exited with status 1"):
            src = debugger.draw_program(main, path=str(path))
        assert path.exists() and "digraph" in src
        assert not (tmp_path / "g.dot.pdf").exists()

    def test_dot_oserror_warns_and_keeps_dot(self, tmp_path, monkeypatch):
        main = self._tiny_program()
        path = tmp_path / "g.dot"

        def boom(*a, **k):
            raise OSError("exec format error")

        monkeypatch.setattr(debugger.shutil, "which", lambda _: "/bin/dot")
        monkeypatch.setattr(debugger.subprocess, "run", boom)
        with pytest.warns(RuntimeWarning, match="could not be executed"):
            debugger.draw_program(main, path=str(path))
        assert path.exists()

    def test_no_warning_when_dot_absent(self, tmp_path, monkeypatch):
        main = self._tiny_program()
        monkeypatch.setattr(debugger.shutil, "which", lambda _: None)
        with warnings.catch_warnings():
            warnings.simplefilter("error")
            debugger.draw_program(main, path=str(tmp_path / "g.dot"))
