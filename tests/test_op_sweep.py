"""Per-op sweep: every registered op the rest of the suite does not already
exercise gets at least one OpTest here (reference discipline:
tests/unittests — 199 per-op files over op_test.py:212; coverage proven by
tools/op_coverage.py). Oracles are numpy; differentiable ops grad-check."""

import numpy as np
import pytest

import paddle_tpu as fluid
from paddle_tpu import executor as executor_mod
from op_test import OpTest

RNG = np.random.RandomState(33)


def _sigmoid(x):
    return 1.0 / (1.0 + np.exp(-x))


# --- activations -------------------------------------------------------------
# (name, oracle, attrs, grad?, domain)
ACTIVATIONS = [
    ("logsigmoid", lambda x: np.log(_sigmoid(x)), {}, True, (-2, 2)),
    ("ceil", np.ceil, {}, False, (-2, 2)),
    ("floor", np.floor, {}, False, (-2, 2)),
    ("round", np.round, {}, False, (-2, 2)),
    ("tanh_shrink", lambda x: x - np.tanh(x), {}, True, (-2, 2)),
    ("softshrink", lambda x: np.where(x > 0.5, x - 0.5,
                                      np.where(x < -0.5, x + 0.5, 0)),
     {"lambda": 0.5}, True, (-2, 2)),
    ("hard_shrink", lambda x: np.where(np.abs(x) > 0.5, x, 0),
     {"threshold": 0.5}, True, (-2, 2)),
    ("brelu", lambda x: np.clip(x, -0.5, 0.8),
     {"t_min": -0.5, "t_max": 0.8}, True, (-2, 2)),
    ("leaky_relu", lambda x: np.where(x >= 0, x, 0.1 * x),
     {"alpha": 0.1}, True, (-2, 2)),
    ("soft_relu", lambda x: np.log1p(np.exp(np.clip(x, -3, 3))),
     {"threshold": 3.0}, True, (-2, 2)),
    ("elu", lambda x: np.where(x >= 0, x, 1.2 * (np.exp(x) - 1)),
     {"alpha": 1.2}, True, (-2, 2)),
    ("relu6", lambda x: np.clip(x, 0, 6), {}, True, (-2, 8)),
    ("pow", lambda x: np.power(x, 3.0), {"factor": 3.0}, True, (0.5, 2)),
    ("stanh", lambda x: 1.7159 * np.tanh(2.0 / 3.0 * x), {}, True, (-2, 2)),
    ("hard_sigmoid", lambda x: np.clip(0.2 * x + 0.5, 0, 1), {},
     True, (-2, 2)),
    ("swish", lambda x: x * _sigmoid(2.0 * x), {"beta": 2.0}, True, (-2, 2)),
    ("silu", lambda x: x * _sigmoid(x), {}, True, (-2, 2)),
    ("gelu", lambda x: x * 0.5 * (1 + np.vectorize(_erf)(x / np.sqrt(2))),
     {}, True, (-2, 2)),
    ("thresholded_relu", lambda x: np.where(x > 1.0, x, 0),
     {"threshold": 1.0}, True, (-3, 3)),
    ("sign", np.sign, {}, False, (-2, 2)),
]


def _erf(v):
    import math
    return math.erf(v)


class TestActivationSweep:
    @pytest.mark.parametrize("name,oracle,attrs,do_grad,domain",
                             ACTIVATIONS, ids=[a[0] for a in ACTIVATIONS])
    def test(self, name, oracle, attrs, do_grad, domain):
        lo, hi = domain
        x = RNG.uniform(lo, hi, (3, 4)).astype("float32")
        # keep numeric grads away from kinks/rounding cliffs
        for kink in (0.0, 0.5, -0.5, 1.0, -0.5, 0.8, 6.0):
            x[np.abs(x - kink) < 0.08] += 0.17
        t = OpTest()
        t.op_type = name
        t.inputs = {"X": x}
        t.attrs = dict(attrs)
        t.outputs = {"Out": oracle(x).astype("float32")}
        t.check_output(atol=1e-5)
        if do_grad:
            t.check_grad(["X"], "Out", max_relative_error=0.02)


# --- elementwise / compare / logical -----------------------------------------

class TestElementwisePow(OpTest):
    op_type = "elementwise_pow"

    def test(self):
        x = RNG.uniform(0.5, 2, (3, 4)).astype("float32")
        y = RNG.uniform(1, 3, (3, 4)).astype("float32")
        self.inputs = {"X": x, "Y": y}
        self.outputs = {"Out": np.power(x, y)}
        self.check_output(rtol=1e-4)


class TestCompareOps:
    @pytest.mark.parametrize("op,fn", [
        ("equal", np.equal), ("not_equal", np.not_equal),
        ("less_equal", np.less_equal), ("greater_than", np.greater),
        ("greater_equal", np.greater_equal)])
    def test(self, op, fn):
        x = RNG.randint(0, 3, (2, 5)).astype("int32")
        y = RNG.randint(0, 3, (2, 5)).astype("int32")
        t = OpTest()
        t.op_type = op
        t.inputs = {"X": x, "Y": y}
        t.outputs = {"Out": fn(x, y)}
        t.check_output()


class TestLogicalOps:
    @pytest.mark.parametrize("op,fn", [
        ("logical_and", np.logical_and), ("logical_or", np.logical_or),
        ("logical_xor", np.logical_xor)])
    def test(self, op, fn):
        x = RNG.randint(0, 2, (6,)).astype(bool)
        y = RNG.randint(0, 2, (6,)).astype(bool)
        t = OpTest()
        t.op_type = op
        t.inputs = {"X": x, "Y": y}
        t.outputs = {"Out": fn(x, y)}
        t.check_output()


class TestClip(OpTest):
    op_type = "clip"

    def test(self):
        x = RNG.uniform(-2, 2, (3, 3)).astype("float32")
        x[np.abs(np.abs(x) - 0.7) < 0.1] = 0.0
        self.inputs = {"X": x}
        self.attrs = {"min": -0.7, "max": 0.7}
        self.outputs = {"Out": np.clip(x, -0.7, 0.7)}
        self.check_output()
        self.check_grad(["X"], "Out", max_relative_error=0.02)


class TestClipByNorm(OpTest):
    op_type = "clip_by_norm"

    def test(self):
        x = RNG.uniform(-1, 1, (4, 3)).astype("float32") * 3
        norm = np.sqrt((x ** 2).sum())
        self.inputs = {"X": x}
        self.attrs = {"max_norm": 1.5}
        self.outputs = {"Out": x * (1.5 / max(norm, 1.5))}
        self.check_output(rtol=1e-4)


class TestFillZerosLike(OpTest):
    op_type = "fill_zeros_like"

    def test(self):
        x = RNG.rand(2, 3).astype("float32")
        self.inputs = {"X": x}
        self.outputs = {"Out": np.zeros_like(x)}
        self.check_output()


# --- shape / data movement ---------------------------------------------------

class TestExpand(OpTest):
    op_type = "expand"

    def test(self):
        x = RNG.rand(2, 3).astype("float32")
        self.inputs = {"X": x}
        self.attrs = {"expand_times": [2, 3]}
        self.outputs = {"Out": np.tile(x, (2, 3))}
        self.check_output()
        self.check_grad(["X"], "Out")


class TestGather(OpTest):
    op_type = "gather"

    def test(self):
        x = RNG.rand(6, 3).astype("float32")
        idx = np.array([0, 2, 5, 2], "int32")
        self.inputs = {"X": x, "Index": idx}
        self.outputs = {"Out": x[idx]}
        self.check_output()
        self.check_grad(["X"], "Out")


class TestScatter(OpTest):
    op_type = "scatter"

    def test(self):
        x = RNG.rand(5, 3).astype("float32")
        ids = np.array([1, 3], "int32")
        upd = RNG.rand(2, 3).astype("float32")
        out = x.copy()
        out[ids] = upd
        self.inputs = {"X": x, "Ids": ids, "Updates": upd}
        self.outputs = {"Out": out}
        self.check_output()


class TestSplit:
    def test(self):
        x = RNG.rand(4, 6).astype("float32")
        with fluid.program_guard(fluid.Program(), fluid.Program()):
            xv = fluid.layers.data(name="x", shape=[4, 6], dtype="float32",
                                   append_batch_size=False)
            a, b, c = fluid.layers.split(xv, 3, dim=1)
            exe = fluid.Executor(fluid.CPUPlace())
            with executor_mod.scope_guard(executor_mod.Scope()):
                ra, rb, rc = exe.run(fluid.default_main_program(),
                                     feed={"x": x}, fetch_list=[a, b, c])
        np.testing.assert_allclose(np.asarray(ra), x[:, :2])
        np.testing.assert_allclose(np.asarray(rb), x[:, 2:4])
        np.testing.assert_allclose(np.asarray(rc), x[:, 4:])


class TestSqueezeUnsqueeze:
    def test(self):
        x = RNG.rand(3, 1, 4).astype("float32")
        t = OpTest()
        t.op_type = "squeeze"
        t.inputs = {"X": x}
        t.attrs = {"axes": [1]}
        t.outputs = {"Out": x.reshape(3, 4)}
        t.check_output()
        t2 = OpTest()
        t2.op_type = "unsqueeze"
        t2.inputs = {"X": x.reshape(3, 4)}
        t2.attrs = {"axes": [0]}
        t2.outputs = {"Out": x.reshape(1, 3, 4)}
        t2.check_output()


class TestShapeOp(OpTest):
    op_type = "shape"

    def test(self):
        x = RNG.rand(3, 5, 2).astype("float32")
        self.inputs = {"Input": x}
        self.outputs = {"Out": np.array([3, 5, 2], "int32")}
        self.check_output()


class TestCumsum(OpTest):
    op_type = "cumsum"

    def test(self):
        x = RNG.rand(3, 4).astype("float32")
        self.inputs = {"X": x}
        self.attrs = {"axis": 1}
        self.outputs = {"Out": np.cumsum(x, axis=1)}
        self.check_output(rtol=1e-5)
        self.check_grad(["X"], "Out")


class TestMultiplex(OpTest):
    op_type = "multiplex"

    def test(self):
        xs = [RNG.rand(4, 3).astype("float32") for _ in range(3)]
        ids = np.array([[0], [2], [1], [0]], "int32")
        out = np.stack([xs[int(i)][r] for r, i in enumerate(ids[:, 0])])
        self.inputs = {"X": [(f"mx_{i}", x) for i, x in enumerate(xs)],
                       "Ids": ids}
        self.outputs = {"Out": out}
        self.check_output()


class TestOneHot(OpTest):
    op_type = "one_hot"

    def test(self):
        x = np.array([[1], [0], [3]], "int64")
        out = np.zeros((3, 4), "float32")
        out[np.arange(3), x[:, 0]] = 1.0
        self.inputs = {"X": x.reshape(-1)}
        self.attrs = {"depth": 4}
        self.outputs = {"Out": out}
        self.check_output()


class TestArgMinMax:
    @pytest.mark.parametrize("op,fn", [("arg_max", np.argmax),
                                       ("arg_min", np.argmin)])
    def test(self, op, fn):
        x = RNG.rand(3, 5).astype("float32")
        t = OpTest()
        t.op_type = op
        t.inputs = {"X": x}
        t.attrs = {"axis": 1}
        t.outputs = {"Out": fn(x, axis=1).astype("int64")}
        t.check_output()


class TestPad(OpTest):
    op_type = "pad"

    def test(self):
        x = RNG.rand(2, 3).astype("float32")
        self.inputs = {"X": x}
        self.attrs = {"paddings": [1, 0, 0, 2], "pad_value": 0.5}
        self.outputs = {"Out": np.pad(x, ((1, 0), (0, 2)),
                                      constant_values=0.5)}
        self.check_output()
        self.check_grad(["X"], "Out")


class TestReduceMinProd:
    @pytest.mark.parametrize("op,fn", [("reduce_min", np.min),
                                       ("reduce_prod", np.prod)])
    def test(self, op, fn):
        x = (RNG.rand(3, 4).astype("float32") + 0.5)
        t = OpTest()
        t.op_type = op
        t.inputs = {"X": x}
        t.attrs = {"dim": [1]}
        t.outputs = {"Out": fn(x, axis=1)}
        t.check_output(rtol=1e-5)
        t.check_grad(["X"], "Out", max_relative_error=0.02)


# --- losses ------------------------------------------------------------------

class TestHingeLoss(OpTest):
    op_type = "hinge_loss"

    def test(self):
        logits = RNG.uniform(-2, 2, (6, 1)).astype("float32")
        logits[np.abs(np.abs(logits) - 1) < 0.1] = 0.0
        labels = RNG.randint(0, 2, (6, 1)).astype("float32")
        y = 2 * labels - 1
        self.inputs = {"Logits": logits, "Labels": labels}
        self.outputs = {"Loss": np.maximum(0, 1 - y * logits)}
        self.check_output()
        self.check_grad(["Logits"], "Loss", max_relative_error=0.02)


class TestHuberLoss(OpTest):
    op_type = "huber_loss"

    def test(self):
        x = RNG.uniform(-2, 2, (8, 1)).astype("float32")
        y = RNG.uniform(-2, 2, (8, 1)).astype("float32")
        d = 1.0
        r = y - x
        r[np.abs(np.abs(r) - d) < 0.1] *= 1.3
        x = (y - r).astype("float32")
        loss = np.where(np.abs(r) <= d, 0.5 * r * r,
                        d * (np.abs(r) - 0.5 * d))
        self.inputs = {"X": x, "Y": y}
        self.attrs = {"delta": d}
        self.outputs = {"Residual": r, "Out": loss}
        self.check_output(no_check_set=("Residual",))
        self.check_grad(["X"], "Out", max_relative_error=0.02)


class TestLogLoss(OpTest):
    op_type = "log_loss"

    def test(self):
        p = RNG.uniform(0.1, 0.9, (6, 1)).astype("float32")
        y = RNG.randint(0, 2, (6, 1)).astype("float32")
        eps = 1e-4
        loss = -y * np.log(p + eps) - (1 - y) * np.log(1 - p + eps)
        self.inputs = {"Predicted": p, "Labels": y}
        self.attrs = {"epsilon": eps}
        self.outputs = {"Loss": loss}
        self.check_output(rtol=1e-4)
        self.check_grad(["Predicted"], "Loss", max_relative_error=0.02)


class TestRankLoss(OpTest):
    op_type = "rank_loss"

    def test(self):
        left = RNG.uniform(-1, 1, (5, 1)).astype("float32")
        right = RNG.uniform(-1, 1, (5, 1)).astype("float32")
        label = RNG.randint(0, 2, (5, 1)).astype("float32")
        d = left - right
        loss = np.log1p(np.exp(d)) - label * d
        self.inputs = {"Left": left, "Right": right, "Label": label}
        self.outputs = {"Out": loss}
        self.check_output(rtol=1e-4)
        self.check_grad(["Left", "Right"], "Out", max_relative_error=0.02)


class TestMarginRankLoss(OpTest):
    op_type = "margin_rank_loss"

    def test(self):
        x1 = RNG.uniform(-1, 1, (5, 1)).astype("float32")
        x2 = RNG.uniform(-1, 1, (5, 1)).astype("float32")
        label = np.where(RNG.rand(5, 1) > 0.5, 1.0,
                         -1.0).astype("float32")
        m = 0.1
        act = -label * (x1 - x2) + m
        act[np.abs(act) < 0.05] += 0.12
        x1 = ((m - act) / -label + x2).astype("float32")
        loss = np.maximum(0, -label * (x1 - x2) + m)
        self.inputs = {"X1": x1, "X2": x2, "Label": label}
        self.attrs = {"margin": m}
        self.outputs = {"Out": loss}
        self.check_output(rtol=1e-4)
        self.check_grad(["X1", "X2"], "Out", max_relative_error=0.02)


class TestSmoothL1Loss(OpTest):
    op_type = "smooth_l1_loss"

    def test(self):
        x = RNG.uniform(-1.5, 1.5, (4, 3)).astype("float32")
        y = RNG.uniform(-1.5, 1.5, (4, 3)).astype("float32")
        d = x - y
        d[np.abs(np.abs(d) - 1.0) < 0.1] *= 1.25
        x = (y + d).astype("float32")
        ad = np.abs(d)
        el = np.where(ad < 1.0, 0.5 * d * d, ad - 0.5)
        out = el.sum(axis=1, keepdims=True)
        self.inputs = {"X": x, "Y": y}
        self.attrs = {"sigma": 1.0}
        self.outputs = {"Out": out, "Diff": d}
        self.check_output(no_check_set=("Diff",))
        self.check_grad(["X"], "Out", max_relative_error=0.02)


class TestSigmoidCEWithLogits(OpTest):
    op_type = "sigmoid_cross_entropy_with_logits"

    def test(self):
        x = RNG.uniform(-2, 2, (4, 3)).astype("float32")
        lbl = RNG.uniform(0, 1, (4, 3)).astype("float32")
        loss = np.maximum(x, 0) - x * lbl + np.log1p(np.exp(-np.abs(x)))
        self.inputs = {"X": x, "Label": lbl}
        self.outputs = {"Out": loss}
        self.check_output(rtol=1e-4)
        self.check_grad(["X"], "Out", max_relative_error=0.02)


class TestSquaredL2:
    def test_norm(self):
        x = RNG.rand(3, 4).astype("float32")
        t = OpTest()
        t.op_type = "squared_l2_norm"
        t.inputs = {"X": x}
        t.outputs = {"Out": np.array([(x ** 2).sum()], "float32")}
        t.check_output(rtol=1e-5)
        t.check_grad(["X"], "Out", max_relative_error=0.02)

    def test_distance(self):
        x = RNG.rand(4, 3).astype("float32")
        y = RNG.rand(4, 3).astype("float32")
        t = OpTest()
        t.op_type = "squared_l2_distance"
        t.inputs = {"X": x, "Y": y}
        t.outputs = {"sub_result": x - y,
                     "Out": ((x - y) ** 2).sum(axis=1, keepdims=True)}
        t.check_output(no_check_set=("sub_result",), rtol=1e-5)
        t.check_grad(["X"], "Out", max_relative_error=0.02)


# --- NN ----------------------------------------------------------------------

class TestBilinearTensorProduct(OpTest):
    op_type = "bilinear_tensor_product"

    def test(self):
        b, m, n, o = 3, 4, 5, 2
        x = RNG.rand(b, m).astype("float32")
        y = RNG.rand(b, n).astype("float32")
        w = RNG.rand(o, m, n).astype("float32")
        bias = RNG.rand(1, o).astype("float32")
        out = np.einsum("bm,omn,bn->bo", x, w, y) + bias
        self.inputs = {"X": x, "Y": y, "Weight": w, "Bias": bias}
        self.outputs = {"Out": out}
        self.check_output(rtol=1e-4)
        self.check_grad(["X", "Y", "Weight"], "Out",
                        max_relative_error=0.02)


class TestLabelSmooth(OpTest):
    op_type = "label_smooth"

    def test(self):
        x = np.eye(4, dtype="float32")[RNG.randint(0, 4, 5)]
        eps = 0.1
        self.inputs = {"X": x}
        self.attrs = {"epsilon": eps}
        self.outputs = {"Out": (1 - eps) * x + eps / 4}
        self.check_output(rtol=1e-5)


class TestLrn(OpTest):
    op_type = "lrn"

    def test(self):
        x = RNG.rand(2, 6, 3, 3).astype("float32")
        n, k, alpha, beta = 5, 2.0, 1e-4, 0.75
        sq = np.zeros_like(x)
        c = x.shape[1]
        for i in range(c):
            lo, hi = max(0, i - n // 2), min(c, i + n // 2 + 1)
            sq[:, i] = (x[:, lo:hi] ** 2).sum(axis=1)
        out = x / (k + alpha * sq) ** beta
        self.inputs = {"X": x}
        self.attrs = {"n": n, "k": k, "alpha": alpha, "beta": beta}
        self.outputs = {"Out": out}
        self.check_output(rtol=1e-4)


class TestNormOp(OpTest):
    op_type = "norm"

    def test(self):
        x = RNG.rand(3, 4).astype("float32") + 0.1
        out = x / np.sqrt((x ** 2).sum(axis=1, keepdims=True) + 1e-10)
        self.inputs = {"X": x}
        self.attrs = {"axis": 1, "epsilon": 1e-10}
        self.outputs = {"Out": out}
        self.check_output(rtol=1e-4)
        self.check_grad(["X"], "Out", max_relative_error=0.02)


class TestAuc:
    def test_perfect_ranking(self):
        pred = np.array([[0.1, 0.9], [0.8, 0.2], [0.3, 0.7], [0.6, 0.4]],
                        "float32")
        label = np.array([[1], [0], [1], [0]], "int64")
        with fluid.program_guard(fluid.Program(), fluid.Program()):
            p = fluid.layers.data(name="p", shape=[4, 2], dtype="float32",
                                  append_batch_size=False)
            l = fluid.layers.data(name="l", shape=[4, 1], dtype="int64",
                                  append_batch_size=False)
            auc = fluid.layers.auc(p, l)
            exe = fluid.Executor(fluid.CPUPlace())
            with executor_mod.scope_guard(executor_mod.Scope()):
                out = exe.run(fluid.default_main_program(),
                              feed={"p": pred, "l": label},
                              fetch_list=[auc] if not isinstance(auc, tuple)
                              else [auc[0]])
        assert abs(float(np.asarray(out[0]).reshape(-1)[0]) - 1.0) < 0.02


# --- conv variants through layers -------------------------------------------

class TestConvVariants:
    def _run_conv(self, build, feed):
        main, startup = fluid.Program(), fluid.Program()
        with fluid.program_guard(main, startup):
            out = build()
        exe = fluid.Executor(fluid.CPUPlace())
        with executor_mod.scope_guard(executor_mod.Scope()):
            exe.run(startup)
            return exe.run(main, feed=feed, fetch_list=[out])

    def test_conv2d_transpose_shape_and_values(self):
        x = np.ones((1, 2, 4, 4), "float32")

        def build():
            xv = fluid.layers.data(name="x", shape=[2, 4, 4],
                                   dtype="float32")
            return fluid.layers.conv2d_transpose(
                xv, num_filters=3, filter_size=2, stride=2,
                param_attr=fluid.ParamAttr(
                    name="ct_w",
                    initializer=fluid.initializer.Constant(0.5)),
                bias_attr=False)

        got, = self._run_conv(build, {"x": x})
        got = np.asarray(got)
        assert got.shape == (1, 3, 8, 8)
        # every output position receives exactly one kernel tap of each of
        # 2 input channels: 2 * 0.5 * 1 = 1.0
        np.testing.assert_allclose(got, np.ones_like(got), rtol=1e-5)

    def test_conv3d_matches_oracle(self):
        x = RNG.rand(1, 1, 3, 3, 3).astype("float32")
        w = RNG.rand(1, 1, 2, 2, 2).astype("float32")
        import itertools
        out = np.zeros((1, 1, 2, 2, 2), "float32")
        for d, h, ww in itertools.product(range(2), range(2), range(2)):
            out[0, 0, d, h, ww] = (x[0, 0, d:d+2, h:h+2, ww:ww+2] * w).sum()

        t = OpTest()
        t.op_type = "conv3d"
        t.inputs = {"Input": x, "Filter": w}
        t.attrs = {"strides": [1, 1, 1], "paddings": [0, 0, 0]}
        t.outputs = {"Output": out}
        t.check_output(rtol=1e-4)

    def test_depthwise_conv2d(self):
        x = RNG.rand(1, 2, 4, 4).astype("float32")
        w = RNG.rand(2, 1, 3, 3).astype("float32")
        out = np.zeros((1, 2, 2, 2), "float32")
        for c in range(2):
            for i in range(2):
                for j in range(2):
                    out[0, c, i, j] = (x[0, c, i:i+3, j:j+3] * w[c, 0]).sum()
        t = OpTest()
        t.op_type = "depthwise_conv2d"
        t.inputs = {"Input": x, "Filter": w}
        t.attrs = {"strides": [1, 1], "paddings": [0, 0], "groups": 2}
        t.outputs = {"Output": out}
        t.check_output(rtol=1e-4)


# --- RNN units ---------------------------------------------------------------

class TestRnnUnits:
    def test_gru_unit_trains(self):
        """gru_unit single step wired into a classifier converges."""
        B, D, H = 4, 6, 5
        with fluid.program_guard(fluid.Program(), fluid.Program()):
            x = fluid.layers.data(name="x", shape=[D], dtype="float32")
            h0 = fluid.layers.data(name="h", shape=[H], dtype="float32")
            y = fluid.layers.data(name="y", shape=[1], dtype="int64")
            xp = fluid.layers.fc(input=x, size=3 * H)
            hidden, _, _ = fluid.layers.gru_unit(input=xp, hidden=h0,
                                                 size=3 * H)
            logits = fluid.layers.fc(input=hidden, size=3)
            loss = fluid.layers.mean(
                fluid.layers.softmax_with_cross_entropy(logits, y))
            fluid.optimizer.Adam(learning_rate=0.05).minimize(loss)
            exe = fluid.Executor(fluid.CPUPlace())
            with executor_mod.scope_guard(executor_mod.Scope()):
                exe.run(fluid.default_startup_program())
                feed = {"x": RNG.randn(B, D).astype("float32"),
                        "h": np.zeros((B, H), "float32"),
                        "y": RNG.randint(0, 3, (B, 1)).astype("int64")}
                first = None
                for _ in range(30):
                    v, = exe.run(fluid.default_main_program(), feed=feed,
                                 fetch_list=[loss])
                    first = first if first is not None else \
                        float(np.asarray(v).reshape(-1)[0])
        assert float(np.asarray(v).reshape(-1)[0]) < first * 0.5

    def test_lstm_unit_trains(self):
        B, D, H = 4, 6, 5
        with fluid.program_guard(fluid.Program(), fluid.Program()):
            x = fluid.layers.data(name="x", shape=[D], dtype="float32")
            h0 = fluid.layers.data(name="h", shape=[H], dtype="float32")
            c0 = fluid.layers.data(name="c", shape=[H], dtype="float32")
            y = fluid.layers.data(name="y", shape=[1], dtype="int64")
            h1, c1 = fluid.layers.lstm_unit(x, h0, c0)
            logits = fluid.layers.fc(input=h1, size=3)
            loss = fluid.layers.mean(
                fluid.layers.softmax_with_cross_entropy(logits, y))
            fluid.optimizer.Adam(learning_rate=0.05).minimize(loss)
            exe = fluid.Executor(fluid.CPUPlace())
            with executor_mod.scope_guard(executor_mod.Scope()):
                exe.run(fluid.default_startup_program())
                feed = {"x": RNG.randn(B, D).astype("float32"),
                        "h": np.zeros((B, H), "float32"),
                        "c": np.zeros((B, H), "float32"),
                        "y": RNG.randint(0, 3, (B, 1)).astype("int64")}
                first = None
                for _ in range(30):
                    v, = exe.run(fluid.default_main_program(), feed=feed,
                                 fetch_list=[loss])
                    first = first if first is not None else \
                        float(np.asarray(v).reshape(-1)[0])
        assert float(np.asarray(v).reshape(-1)[0]) < first * 0.5

    def test_lstmp_projection_shape(self):
        """dynamic_lstmp: projected output must have the projection size."""
        from paddle_tpu.executor import LoDTensor
        B_rows = [RNG.randn(3, 16).astype("float32"),
                  RNG.randn(2, 16).astype("float32")]
        offs = [0, 3, 5]
        with fluid.program_guard(fluid.Program(), fluid.Program()):
            x = fluid.layers.data(name="x", shape=[16], dtype="float32",
                                  lod_level=1)
            proj, cell = fluid.layers.dynamic_lstmp(
                input=x, size=16, proj_size=3)
            exe = fluid.Executor(fluid.CPUPlace())
            with executor_mod.scope_guard(executor_mod.Scope()):
                exe.run(fluid.default_startup_program())
                got, = exe.run(
                    fluid.default_main_program(),
                    feed={"x": LoDTensor(np.concatenate(B_rows), [offs])},
                    fetch_list=[proj], return_numpy=False)
        assert got.array().shape[-1] == 3


# --- misc --------------------------------------------------------------------

class TestIsEmpty(OpTest):
    op_type = "is_empty"

    def test(self):
        x = RNG.rand(3).astype("float32")
        self.inputs = {"X": x}
        self.outputs = {"Out": np.array([False])}
        self.check_output()


class TestLodReset:
    def test(self):
        from paddle_tpu.executor import LoDTensor
        flat = RNG.rand(6, 2).astype("float32")
        with fluid.program_guard(fluid.Program(), fluid.Program()):
            x = fluid.layers.data(name="x", shape=[2], dtype="float32",
                                  lod_level=1)
            out = fluid.layers.lod_reset(x, target_lod=[0, 2, 6])
            exe = fluid.Executor(fluid.CPUPlace())
            with executor_mod.scope_guard(executor_mod.Scope()):
                got, = exe.run(fluid.default_main_program(),
                               feed={"x": LoDTensor(flat, [[0, 3, 6]])},
                               fetch_list=[out], return_numpy=False)
        assert got.lod[0] == [0, 2, 6]
        np.testing.assert_allclose(got.array(), flat, rtol=1e-6)

    def test_print_op_passthrough(self):
        x = RNG.rand(2, 2).astype("float32")
        main = fluid.Program()
        with fluid.program_guard(main, fluid.Program()):
            xv = fluid.layers.data(name="x", shape=[2, 2], dtype="float32",
                                   append_batch_size=False)
            out = main.global_block().create_var(name="print_out",
                                                 dtype="float32")
            main.global_block().append_op(
                type="print", inputs={"In": [xv]}, outputs={"Out": [out]},
                attrs={"message": "sweep: "})
            exe = fluid.Executor(fluid.CPUPlace())
            with executor_mod.scope_guard(executor_mod.Scope()):
                got, = exe.run(main, feed={"x": x}, fetch_list=[out])
        np.testing.assert_allclose(np.asarray(got), x)

    def test_shrink_rnn_memory_passthrough(self):
        x = RNG.rand(3, 4).astype("float32")
        t = OpTest()
        t.op_type = "shrink_rnn_memory"
        t.inputs = {"X": x}
        t.outputs = {"Out": x}
        t.check_output()


class TestRandomBatchSizeLike:
    @pytest.mark.parametrize("op", ["uniform_random_batch_size_like",
                                    "gaussian_random_batch_size_like"])
    def test(self, op):
        x = np.zeros((7, 3), "float32")
        main = fluid.Program()
        with fluid.program_guard(main, fluid.Program()):
            xv = fluid.layers.data(name="x", shape=[7, 3], dtype="float32",
                                   append_batch_size=False)
            out = main.global_block().create_var(name=f"{op}_out",
                                                 dtype="float32")
            main.global_block().append_op(
                type=op, inputs={"Input": [xv]}, outputs={"Out": [out]},
                attrs={"shape": [-1, 5], "min": -1.0, "max": 1.0,
                       "mean": 0.0, "std": 1.0})
            exe = fluid.Executor(fluid.CPUPlace())
            with executor_mod.scope_guard(executor_mod.Scope()):
                got, = exe.run(main, feed={"x": x}, fetch_list=[out])
        got = np.asarray(got)
        assert got.shape == (7, 5)
        assert got.std() > 0.1


# --- optimizer ops vs numpy oracles ------------------------------------------

def _opt_run(opt, steps=2):
    """Run `steps` updates of a single 4-param weight under `opt`; return
    the weight trajectory and the (constant) gradient."""
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        x = fluid.layers.data(name="x", shape=[4], dtype="float32")
        pred = fluid.layers.fc(input=x, size=1, bias_attr=False,
                               param_attr=fluid.ParamAttr(name="ow"))
        loss = fluid.layers.mean(pred)
        opt.minimize(loss)
    exe = fluid.Executor(fluid.CPUPlace())
    xs = np.ones((2, 4), "float32") * np.array([1., 2., 3., 4.])
    w0 = np.array([[0.5], [-0.3], [0.2], [0.1]], "float32")
    # d(mean(x @ w))/dw = mean over batch of x = [1,2,3,4]^T / 1
    grad = xs.mean(axis=0, keepdims=True).T
    scope = executor_mod.Scope()
    traj = [w0.copy()]
    with executor_mod.scope_guard(scope):
        exe.run(startup)
        scope.set_var("ow", w0.copy())
        for _ in range(steps):
            exe.run(main, feed={"x": xs}, fetch_list=[loss])
            traj.append(np.asarray(scope.find_var("ow")).copy())
    return np.array(traj), grad


class TestOptimizerOracles:
    LR = 0.1

    def test_momentum(self):
        traj, g = _opt_run(fluid.optimizer.Momentum(self.LR, momentum=0.9))
        v = np.zeros_like(g)
        w = traj[0]
        for t in range(1, 3):
            v = 0.9 * v + g
            w = w - self.LR * v
            np.testing.assert_allclose(traj[t], w, rtol=1e-5, atol=1e-6)

    def test_adagrad(self):
        traj, g = _opt_run(fluid.optimizer.Adagrad(self.LR))
        m = np.zeros_like(g)
        w = traj[0]
        for t in range(1, 3):
            m = m + g * g
            w = w - self.LR * g / (np.sqrt(m) + 1e-6)
            np.testing.assert_allclose(traj[t], w, rtol=1e-5, atol=1e-6)

    def test_decayed_adagrad(self):
        traj, g = _opt_run(fluid.optimizer.DecayedAdagrad(self.LR,
                                                          decay=0.95))
        m = np.zeros_like(g)
        w = traj[0]
        for t in range(1, 3):
            m = 0.95 * m + 0.05 * g * g
            w = w - self.LR * g / (np.sqrt(m) + 1e-6)
            np.testing.assert_allclose(traj[t], w, rtol=1e-4, atol=1e-6)

    def test_adadelta(self):
        traj, g = _opt_run(fluid.optimizer.Adadelta(
            self.LR, epsilon=1e-6, rho=0.95))
        ag = np.zeros_like(g)
        au = np.zeros_like(g)
        w = traj[0]
        for t in range(1, 3):
            ag = 0.95 * ag + 0.05 * g * g
            upd = -np.sqrt((au + 1e-6) / (ag + 1e-6)) * g
            au = 0.95 * au + 0.05 * upd * upd
            # reference adadelta applies the raw update, no learning rate
            # (adadelta_op.cc)
            w = w + upd
            np.testing.assert_allclose(traj[t], w, rtol=1e-4, atol=1e-6)

    def test_adamax(self):
        traj, g = _opt_run(fluid.optimizer.Adamax(
            self.LR, beta1=0.9, beta2=0.999, epsilon=1e-8))
        m = np.zeros_like(g)
        u = np.zeros_like(g)
        w = traj[0]
        b1p = 1.0
        for t in range(1, 3):
            m = 0.9 * m + 0.1 * g
            u = np.maximum(0.999 * u, np.abs(g))
            b1p *= 0.9
            w = w - self.LR / (1 - b1p) * m / (u + 1e-8)
            np.testing.assert_allclose(traj[t], w, rtol=1e-4, atol=1e-6)

    def test_rmsprop(self):
        traj, g = _opt_run(fluid.optimizer.RMSProp(
            self.LR, rho=0.9, epsilon=1e-6, momentum=0.0))
        ms = np.zeros_like(g)
        mom = np.zeros_like(g)
        w = traj[0]
        for t in range(1, 3):
            ms = 0.9 * ms + 0.1 * g * g
            mom = 0.0 * mom + self.LR * g / np.sqrt(ms + 1e-6)
            w = w - mom
            np.testing.assert_allclose(traj[t], w, rtol=1e-4, atol=1e-6)

    def test_ftrl_runs_and_descends(self):
        traj, g = _opt_run(fluid.optimizer.Ftrl(self.LR), steps=3)
        assert not np.allclose(traj[0], traj[-1])

    def test_proximal_gd(self):
        main, startup = fluid.Program(), fluid.Program()
        with fluid.program_guard(main, startup):
            x = fluid.layers.data(name="x", shape=[4], dtype="float32")
            pred = fluid.layers.fc(input=x, size=1, bias_attr=False,
                                   param_attr=fluid.ParamAttr(name="pw"))
            loss = fluid.layers.mean(pred)
            block = main.global_block()
        # append proximal ops directly (no python optimizer class for these)
        for op, extra in (("proximal_gd", {}),):
            t = OpTest()
            t.op_type = op
            w = np.array([0.5, -0.3, 0.2], "float32")
            g = np.array([0.1, 0.1, -0.2], "float32")
            lr = np.array([0.1], "float32")
            l1, l2 = 0.05, 0.05
            prox = w - 0.1 * g
            out = (np.sign(prox) * np.maximum(np.abs(prox) - 0.1 * l1, 0)
                   / (1 + 0.1 * l2))
            t.inputs = {"Param": w, "Grad": g, "LearningRate": lr}
            t.attrs = {"l1": l1, "l2": l2}
            t.outputs = {"ParamOut": out}
            t.check_output(rtol=1e-5)

    def test_proximal_adagrad(self):
        w = np.array([0.5, -0.3, 0.2], "float32")
        g = np.array([0.1, 0.1, -0.2], "float32")
        m = np.array([0.01, 0.01, 0.01], "float32")
        lr, l1, l2 = 0.1, 0.05, 0.05
        m2 = m + g * g
        alr = lr / np.sqrt(m2)
        prox = w - alr * g
        out = (np.sign(prox) * np.maximum(np.abs(prox) - alr * l1, 0)
               / (1 + alr * l2))
        t = OpTest()
        t.op_type = "proximal_adagrad"
        t.inputs = {"Param": w, "Grad": g, "Moment": m,
                    "LearningRate": np.array([lr], "float32")}
        t.attrs = {"l1": l1, "l2": l2}
        t.outputs = {"ParamOut": out, "MomentOut": m2}
        t.check_output(rtol=1e-4)


class TestMaxout(OpTest):
    op_type = "maxout"

    def test(self):
        x = RNG.rand(2, 6, 3, 3).astype("float32")
        out = x.reshape(2, 3, 2, 3, 3).max(axis=2)
        self.inputs = {"X": x}
        self.attrs = {"groups": 2}
        self.outputs = {"Out": out}
        self.check_output()


class TestIm2Sequence(OpTest):
    op_type = "im2sequence"

    def test(self):
        x = RNG.rand(1, 2, 4, 4).astype("float32")
        kh = kw = 2
        rows = []
        for oh in range(3):
            for ow in range(3):
                # XLA patch layout: channel-major [C, kh, kw]
                rows.append(x[0, :, oh:oh+2, ow:ow+2].reshape(-1))
        self.inputs = {"X": x}
        self.attrs = {"kernels": [kh, kw], "strides": [1, 1]}
        self.outputs = {"Out": np.stack(rows)}
        self.check_output()


class TestRowConv(OpTest):
    op_type = "row_conv"

    def test(self):
        t, d, k = 5, 3, 2
        x = RNG.rand(t, d).astype("float32")
        w = RNG.rand(k + 1, d).astype("float32")
        out = np.zeros_like(x)
        for i in range(t):
            for j in range(k + 1):
                if i + j < t:
                    out[i] += x[i + j] * w[j]
        self.inputs = {"X": x, "Filter": w}
        self.outputs = {"Out": out}
        self.check_output(rtol=1e-5)
        self.check_grad(["X", "Filter"], "Out", max_relative_error=0.02)


class TestNce:
    def test_trains(self):
        """NCE loss over sampled negatives decreases with training
        (stochastic sampling — convergence, not an oracle)."""
        B, D, C = 8, 6, 20
        with fluid.program_guard(fluid.Program(), fluid.Program()):
            x = fluid.layers.data(name="x", shape=[D], dtype="float32")
            y = fluid.layers.data(name="y", shape=[1], dtype="int64")
            cost = fluid.layers.nce(input=x, label=y, num_total_classes=C,
                                    num_neg_samples=5)
            loss = fluid.layers.mean(cost)
            fluid.optimizer.Adam(learning_rate=0.05).minimize(loss)
            exe = fluid.Executor(fluid.CPUPlace())
            with executor_mod.scope_guard(executor_mod.Scope()):
                exe.run(fluid.default_startup_program())
                feed = {"x": RNG.randn(B, D).astype("float32"),
                        "y": RNG.randint(0, C, (B, 1)).astype("int64")}
                first = None
                for _ in range(40):
                    v, = exe.run(fluid.default_main_program(), feed=feed,
                                 fetch_list=[loss])
                    first = first if first is not None else \
                        float(np.asarray(v).reshape(-1)[0])
        assert float(np.asarray(v).reshape(-1)[0]) < first * 0.8
