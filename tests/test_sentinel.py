"""Run sentinel (ISSUE 17): statistical anomaly detection over live
telemetry, hang forensics around executor dispatches, and the surfacing
endpoints.

The acceptance properties pinned here: a planted step-time regression
and a planted loss spike each raise exactly ONE deduplicated alert (in
the ledger, in sentinel_alerts_total, and over HTTP in /alerts); healthy
series raise none; cooldown suppresses repeats; an injected stall
produces a hang report containing the stalled thread's stack and flips
/healthz to 503 with reason=hang within the deadline, and the verdict
recovers cleanly on disarm; the `inspect` CLI renders the hang report;
fleet snapshots carry per-host alert counts; and the trace
capture/adopt handle parents window-builder prefetch spans under the
owning step trace.
"""

import http.client
import json
import subprocess
import sys
import threading
import time
from pathlib import Path

import numpy as np
import pytest

import paddle_tpu as fluid
from paddle_tpu import executor as executor_mod
from paddle_tpu import fleet, inspector, obs_server, sentinel, telemetry
from paddle_tpu import tracing
from paddle_tpu.reader.pipeline import DoubleBufferedFeeder


@pytest.fixture(autouse=True)
def _fresh_sentinel_state():
    telemetry.reset()
    tracing.reset()
    sentinel.reset()
    yield
    sentinel.reset()
    obs_server.stop()
    telemetry.reset()
    tracing.reset()


def _warm(s, rule, base=0.1, n=16, jitter=0.001):
    """Feed a healthy series (small deterministic jitter) past warmup."""
    for i in range(n):
        assert s.feed(rule, base + jitter * (i % 3)) is None


# --- anomaly detection -------------------------------------------------------

def test_healthy_series_raise_no_alerts():
    s = sentinel.Sentinel()
    _warm(s, "step_time_regression", base=0.1, n=64)
    _warm(s, "loss_spike", base=2.5, n=64, jitter=0.01)
    assert s.alerts() == []
    assert telemetry.read_series("sentinel_alerts_total") == {}


def test_planted_step_time_regression_raises_exactly_one_alert():
    s = sentinel.Sentinel()
    _warm(s, "step_time_regression")
    a = s.feed("step_time_regression", 0.35)
    assert a is not None and a["rule"] == "step_time_regression"
    assert a["severity"] == "warn" and a["zscore"] > 4.0
    # the regression persists across following samples: same incident,
    # still one ledger entry, still one counter increment
    for v in (0.36, 0.34, 0.4):
        assert s.feed("step_time_regression", v) is None
    ledger = s.alerts()
    assert len(ledger) == 1
    assert ledger[0]["count"] == 4
    series = telemetry.read_series("sentinel_alerts_total")
    assert series == {"rule=step_time_regression,severity=warn": 1.0}
    kinds = [e["rule"] for e in telemetry.recent_events(kind="alert")]
    assert kinds == ["step_time_regression"]


def test_planted_loss_spike_raises_one_page_alert():
    s = sentinel.Sentinel()
    _warm(s, "loss_spike", base=2.5, jitter=0.01)
    a = s.feed("loss_spike", 30.0)
    assert a is not None and a["severity"] == "page"
    assert s.feed("loss_spike", 28.0) is None
    assert telemetry.read_series("sentinel_alerts_total") == {
        "rule=loss_spike,severity=page": 1.0}


def test_warmup_gates_alerting():
    s = sentinel.Sentinel()
    # fewer than `warmup` samples: even a wild value cannot alert
    for v in (0.1, 0.1, 0.1, 50.0):
        assert s.feed("step_time_regression", v) is None


def test_low_direction_rule_fires_on_drop_only():
    s = sentinel.Sentinel()
    _warm(s, "duty_cycle_drop", base=0.9, n=16)
    assert s.feed("duty_cycle_drop", 0.95) is None   # up is fine
    a = s.feed("duty_cycle_drop", 0.2)
    assert a is not None and a["rule"] == "duty_cycle_drop"


def test_cooldown_suppresses_then_expires():
    s = sentinel.Sentinel()
    t0 = 1_000_000.0
    for i in range(16):
        s.feed("step_time_regression", 0.1 + 0.001 * (i % 3), now=t0 + i)
    assert s.feed("step_time_regression", 0.5, now=t0 + 20) is not None
    # within the 60s cooldown: deduped
    assert s.feed("step_time_regression", 0.6, now=t0 + 40) is None
    assert len(s.alerts()) == 1
    # past the cooldown: a NEW incident
    a = s.feed("step_time_regression", 5.0, now=t0 + 200)
    assert a is not None
    assert len(s.alerts()) == 2
    assert telemetry.read_series("sentinel_alerts_total") == {
        "rule=step_time_regression,severity=warn": 2.0}


def test_min_value_gates_slo_burn_rule():
    s = sentinel.Sentinel()
    # statistically huge z but absolute burn < 1.0: budget not being
    # overspent, stay quiet
    for _ in range(16):
        assert s.feed("slo_fast_burn", 0.01) is None
    assert s.feed("slo_fast_burn", 0.5) is None
    for _ in range(8):
        s.feed("slo_fast_burn", 0.5)
    assert s.feed("slo_fast_burn", 3.0) is not None


def test_poll_reads_live_gauges_with_label_filter():
    s = sentinel.Sentinel()
    gauge = telemetry.gauge("executor_last_step_seconds",
                            "wall seconds of the latest step")
    burn = telemetry.gauge("slo_burn_rate",
                           "error-budget burn rate by window",
                           labels=("model", "window"))
    for i in range(16):
        gauge.set(0.1 + 0.001 * (i % 3))
        burn.labels(model="m", window="fast").set(1.5 + 0.01 * (i % 3))
        burn.labels(model="m", window="slow").set(0.1)
        assert s.poll(now=1_000_000.0 + i) == []
    gauge.set(0.4)
    burn.labels(model="m", window="fast").set(9.0)
    fired = s.poll(now=1_000_100.0)
    assert sorted(a["rule"] for a in fired) == ["slo_fast_burn",
                                               "step_time_regression"]
    # the slow-window series was filtered out the whole time: no rule
    # ever saw 0.1
    assert all(a["value"] != 0.1 for a in fired)


def test_observe_loss_feeds_the_loss_rule_via_poll():
    s = sentinel.Sentinel()
    for i in range(16):
        sentinel.observe_loss(2.5 + 0.01 * (i % 3))
        s.poll(now=1_000_000.0 + i)
    sentinel.observe_loss(40.0)
    fired = s.poll(now=1_000_050.0)
    assert [a["rule"] for a in fired] == ["loss_spike"]


# --- hang watchdog -----------------------------------------------------------

def test_inject_stall_dumps_report_and_recovers(tmp_path):
    path = str(tmp_path / "hang.json")
    s = sentinel.Sentinel(report_path=path)
    drill = s.inject_stall(0.6, budget_s=0.1)
    deadline = time.time() + 5.0
    while s.hang_state() is None and time.time() < deadline:
        s.check_hangs()
        time.sleep(0.02)
    hang = s.hang_state()
    assert hang is not None and hang["reason"] == "hang"
    assert hang["program"] == "injected_stall"

    report = inspector.read_crash_report(path)
    assert report["kind"] == "hang"
    assert "hang deadline" in report["error"]["message"]
    stalled = [t for t in report["threads"] if t["stalled"]]
    assert len(stalled) == 1
    assert any("_stalled_dispatch" in ln for ln in stalled[0]["stack"])
    assert telemetry.read_series("sentinel_hangs_total") == {"": 1.0}
    assert telemetry.recent_events(kind="hang")

    # clean disarm after recovery: the stalled dispatch returns and the
    # verdict clears without a restart
    drill.join(timeout=5.0)
    assert s.hang_state() is None
    assert telemetry.recent_events(kind="hang_recovered")


def test_hang_report_renders_via_inspect_cli(tmp_path):
    path = str(tmp_path / "hang.json")
    s = sentinel.Sentinel(report_path=path)
    drill = s.inject_stall(0.5, budget_s=0.05)
    deadline = time.time() + 5.0
    while s.hang_state() is None and time.time() < deadline:
        s.check_hangs()
        time.sleep(0.02)
    drill.join(timeout=5.0)
    text = inspector.format_crash_report(
        inspector.read_crash_report(path))
    assert "kind=hang" in text
    assert "STALLED" in text
    assert "_stalled_dispatch" in text


def test_healthz_flips_503_reason_hang_and_recovers(tmp_path):
    srv = obs_server.start(port=0)
    s = sentinel.start(report_path=str(tmp_path / "hang.json"),
                       interval_s=999.0, watch_tick_s=0.02)

    def get(route):
        conn = http.client.HTTPConnection("127.0.0.1", srv.port,
                                          timeout=10)
        try:
            conn.request("GET", route)
            resp = conn.getresponse()
            return resp.status, json.loads(resp.read())
        finally:
            conn.close()

    st, rep = get("/healthz")
    assert st == 200 and "reason" not in rep
    drill = s.inject_stall(1.0, budget_s=0.1)
    deadline = time.time() + 5.0
    while s.hang_state() is None and time.time() < deadline:
        time.sleep(0.02)
    st, rep = get("/healthz")
    assert st == 503
    assert rep["reason"] == "hang"
    assert rep["checks"]["hang"]["program"] == "injected_stall"
    drill.join(timeout=5.0)
    st, rep = get("/healthz")
    assert st == 200 and rep["healthy"]


def test_alerts_endpoint_serves_ledger_and_summary():
    srv = obs_server.start(port=0)
    s = sentinel.start(interval_s=999.0)
    _warm(s, "step_time_regression")
    s.feed("step_time_regression", 0.5)
    s.feed("step_time_regression", 0.55)   # deduped

    conn = http.client.HTTPConnection("127.0.0.1", srv.port, timeout=10)
    try:
        conn.request("GET", "/alerts")
        resp = conn.getresponse()
        assert resp.status == 200
        doc = json.loads(resp.read())
    finally:
        conn.close()
    assert doc["enabled"]
    assert len(doc["alerts"]) == 1
    assert doc["alerts"][0]["rule"] == "step_time_regression"
    assert doc["alerts"][0]["count"] == 2
    assert doc["summary"]["total"] == 1
    assert "loss_spike" in doc["rules"]


def test_active_page_alert_degrades_healthz():
    s = sentinel.start(interval_s=999.0)
    _warm(s, "loss_spike", base=2.5, jitter=0.01)
    s.feed("loss_spike", 30.0)
    rep = obs_server.health_report()
    assert rep["healthy"] and rep["status"] == "degraded"
    assert rep["checks"]["alerts"]["active_page"] == 1


def test_healthz_unaffected_when_sentinel_off():
    rep = obs_server.health_report()
    assert rep["status"] == "ok"
    assert rep["checks"]["alerts"]["total"] == 0
    assert rep["checks"]["hang"] is None


# --- executor integration ----------------------------------------------------

def test_executor_dispatches_arm_the_watchdog():
    s = sentinel.start(interval_s=999.0, watch_tick_s=999.0)
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        x = fluid.layers.data(name="x", shape=[4], dtype="float32")
        y = fluid.layers.fc(input=x, size=2)
    exe = fluid.Executor(fluid.CPUPlace())
    with executor_mod.scope_guard(executor_mod.Scope()):
        exe.run(startup)
        exe.run(main, feed={"x": np.ones((3, 4), np.float32)},
                fetch_list=[y])
    assert s.dispatches_total >= 2      # startup + main
    assert s._dispatches == {}          # all disarmed
    assert s.hang_state() is None


# --- fleet integration -------------------------------------------------------

def test_fleet_snapshot_carries_alert_counts():
    snap = fleet.local_snapshot()
    assert snap["alerts_total"] == 0.0 and snap["alerts_page"] == 0.0

    s = sentinel.Sentinel()
    _warm(s, "loss_spike", base=2.5, jitter=0.01)
    s.feed("loss_spike", 30.0)
    snap = fleet.local_snapshot()
    assert snap["alerts_total"] == 1.0
    assert snap["alerts_page"] == 1.0

    fs = fleet.fleet_snapshot()
    assert fs["alerting_host"] == {"host": 0, "alerts_total": 1.0,
                                   "alerts_page": 1.0}
    assert fs["straggler"]["alerts_total"] == 1.0
    assert "alerting host 0" in fleet.format_fleet(fs)


def test_fleet_snapshot_no_alerting_host_when_quiet():
    fs = fleet.fleet_snapshot()
    assert fs["alerting_host"] is None
    assert "alerting host" not in fleet.format_fleet(fs)


# --- trace-context propagation -----------------------------------------------

def test_capture_adopt_parents_cross_thread_spans():
    tracing.enable()
    with tracing.span("step") as step:
        ctx = tracing.capture_context()
        assert ctx is step

        def worker():
            with tracing.adopt(ctx):
                with tracing.span("child"):
                    pass

        t = threading.Thread(target=worker)
        t.start()
        t.join()
    spans = {d["name"]: d for d in tracing.recent_spans()}
    assert spans["child"]["parent_id"] == spans["step"]["span_id"]
    assert spans["child"]["trace_id"] == spans["step"]["trace_id"]


def test_capture_context_none_and_adopt_noop():
    tracing.enable()
    assert tracing.capture_context() is None
    with tracing.adopt(None):
        with tracing.span("root"):
            pass
    (root,) = tracing.recent_spans(name="root")
    assert root["parent_id"] is None


def test_window_builder_spans_join_owning_trace():
    tracing.enable()

    def reader():
        def gen():
            for i in range(8):
                yield {"x": np.full((2, 3), i, np.float32)}
        return gen()

    feeder = DoubleBufferedFeeder(reader, window_prefetch=2)
    try:
        with tracing.span("train_step") as step:
            feeder.next_window(2)
            # the builder records asynchronously; wait for the span
            deadline = time.time() + 5.0
            while (not tracing.recent_spans(name="input_window_build")
                   and time.time() < deadline):
                time.sleep(0.01)
        builds = tracing.recent_spans(name="input_window_build")
        assert builds, "window-builder recorded no spans"
        assert builds[0]["trace_id"] == step.trace_id
        assert builds[0]["parent_id"] == step.span_id
    finally:
        feeder.stop()


def test_sync_window_build_span_is_child_of_caller():
    tracing.enable()

    def reader():
        def gen():
            for i in range(4):
                yield {"x": np.full((2, 3), i, np.float32)}
        return gen()

    feeder = DoubleBufferedFeeder(reader)   # window_prefetch=1: sync
    try:
        with tracing.span("train_step") as step:
            feeder.next_window(2)
        (build,) = tracing.recent_spans(name="input_window_build")
        assert build["parent_id"] == step.span_id
    finally:
        feeder.stop()


# --- lifecycle / CLI ---------------------------------------------------------

def test_singleton_start_stop_and_env(monkeypatch):
    assert sentinel.active() is None
    monkeypatch.setenv("PADDLE_TPU_SENTINEL", "1")
    s = sentinel.maybe_start_from_env()
    assert s is not None and sentinel.active() is s
    assert sentinel.start() is s        # idempotent
    sentinel.stop()
    assert sentinel.active() is None
    monkeypatch.setenv("PADDLE_TPU_SENTINEL", "0")
    assert sentinel.maybe_start_from_env() is None


def test_hang_budget_env_override(monkeypatch):
    monkeypatch.setenv("PADDLE_TPU_SENTINEL_HANG_S", "123.5")
    s = sentinel.Sentinel()
    tok = s.arm("p0")
    assert s._dispatches[tok]["budget_s"] == 123.5
    s.disarm(tok)


def test_hang_budget_scales_with_rolling_step_time():
    s = sentinel.Sentinel()
    tok = s.arm("p0")
    assert s._dispatches[tok]["budget_s"] == sentinel.HANG_FLOOR_S
    s.disarm(tok)
    telemetry.gauge("executor_last_step_seconds",
                    "wall seconds of the latest step").set(10.0)
    tok = s.arm("p0")
    assert s._dispatches[tok]["budget_s"] == pytest.approx(200.0)
    s.disarm(tok)


def test_cmd_sentinel_smoke(tmp_path, capsys):
    from paddle_tpu import cli
    rc = cli.main(["sentinel", "--smoke",
                   "--report", str(tmp_path / "hang.json")])
    assert rc == 0
    out = capsys.readouterr().out.strip().splitlines()[-1]
    doc = json.loads(out)
    assert doc["hang"]["fired"] and doc["hang"]["recovered"]
    assert sorted(doc["rules_fired"]) == ["loss_spike",
                                         "step_time_regression"]
    assert (tmp_path / "hang.json").exists()


# --- subprocess drill --------------------------------------------------------

_HANG_DRILL = r"""
import json, sys, time
import http.client

from paddle_tpu import obs_server, sentinel

srv = obs_server.start(port=0)
sent = sentinel.start(report_path=sys.argv[1], interval_s=999.0,
                      watch_tick_s=0.02)
drill = sent.inject_stall(1.2, budget_s=0.15)

def get(route):
    conn = http.client.HTTPConnection("127.0.0.1", srv.port, timeout=10)
    try:
        conn.request("GET", route)
        resp = conn.getresponse()
        return resp.status, json.loads(resp.read())
    finally:
        conn.close()

deadline = time.time() + 10.0
while sent.hang_state() is None and time.time() < deadline:
    time.sleep(0.02)
st_hung, rep_hung = get("/healthz")
drill.join(timeout=10.0)
st_rec, rep_rec = get("/healthz")
print(json.dumps({
    "hung_status": st_hung, "hung_reason": rep_hung.get("reason"),
    "recovered_status": st_rec,
    "hang_cleared": sent.hang_state() is None}))
"""


def test_subprocess_hang_drill(tmp_path):
    """Full-fidelity drill in a fresh process: injected stall -> hang
    report with the stalled thread's stack on disk, /healthz 503 with
    reason=hang within the deadline, clean recovery after disarm."""
    import os
    report = tmp_path / "hang.json"
    env = dict(os.environ, JAX_PLATFORMS="cpu",
               PYTHONPATH=str(Path(__file__).resolve().parent.parent))
    env.pop("PADDLE_TPU_SENTINEL", None)
    env.pop("PADDLE_TPU_OBS_PORT", None)
    proc = subprocess.run(
        [sys.executable, "-c", _HANG_DRILL, str(report)],
        cwd=str(Path(__file__).resolve().parent.parent),
        env=env, capture_output=True, text=True, timeout=180)
    assert proc.returncode == 0, proc.stderr
    doc = json.loads(proc.stdout.strip().splitlines()[-1])
    assert doc["hung_status"] == 503
    assert doc["hung_reason"] == "hang"
    assert doc["recovered_status"] == 200
    assert doc["hang_cleared"]

    rep = json.loads(report.read_text())
    assert rep["format"] == "paddle_tpu-crash-report"
    assert rep["kind"] == "hang"
    stalled = [t for t in rep["threads"] if t["stalled"]]
    assert stalled and any("_stalled_dispatch" in ln
                           for ln in stalled[0]["stack"])
