"""Profiler: host event table, per-op eager events, chrome-trace export
(reference: profiler.py:76, platform/profiler.h, tools/timeline.py:31,
test_profiler.py)."""

import json

import numpy as np

import paddle_tpu as fluid
from paddle_tpu import executor as executor_mod
from paddle_tpu import profiler


class TestProfiler:
    def _run_once(self, use_jit):
        with fluid.program_guard(fluid.Program(), fluid.Program()):
            x = fluid.layers.data(name="x", shape=[4], dtype="float32")
            y = fluid.layers.fc(input=x, size=3)
            out = fluid.layers.reduce_sum(y)
            exe = fluid.Executor(fluid.CPUPlace())
            with executor_mod.scope_guard(executor_mod.Scope()):
                exe.run(fluid.default_startup_program())
                exe.run(fluid.default_main_program(),
                        feed={"x": np.zeros((2, 4), np.float32)},
                        fetch_list=[out], use_jit=use_jit)

    def test_jit_run_records_block_event(self, capsys, tmp_path):
        profiler.reset_profiler()
        with profiler.profiler("All", sorted_key="total"):
            self._run_once(use_jit=True)
        captured = capsys.readouterr().out
        assert "executor_run(jit)" in captured

        trace = str(tmp_path / "trace.json")
        profiler.export_chrome_trace(trace)
        data = json.load(open(trace))
        names = {e["name"] for e in data["traceEvents"]}
        assert "executor_run(jit)" in names
        assert all(e["ph"] == "X" and e["dur"] >= 0
                   for e in data["traceEvents"])

    def test_eager_run_records_per_op_events(self, capsys):
        profiler.reset_profiler()
        with profiler.profiler("All"):
            self._run_once(use_jit=False)
        captured = capsys.readouterr().out
        assert "mul" in captured and "reduce_sum" in captured

    def test_jit_device_table_attributes_hot_op(self, capsys, tmp_path):
        """Per-op device-time attribution in JIT mode (VERDICT r4 #8):
        the xplane trace joined with the compiled HLO's pd.<op> scopes
        must rank the known-hot op — a 768x768 matmul dwarfing the other
        ops — first, like the reference's ParseEvents table
        (platform/profiler.h:137-166)."""
        n = 768
        profiler.reset_profiler()
        with fluid.program_guard(fluid.Program(), fluid.Program()):
            x = fluid.layers.data(name="x", shape=[n, n], dtype="float32",
                                  append_batch_size=False)
            y = fluid.layers.matmul(x, x)
            out = fluid.layers.reduce_sum(fluid.layers.sigmoid(y))
            exe = fluid.Executor(fluid.CPUPlace())
            with executor_mod.scope_guard(executor_mod.Scope()):
                exe.run(fluid.default_startup_program())
                xs = np.random.RandomState(0).randn(n, n) \
                    .astype(np.float32) * 0.01
                exe.run(fluid.default_main_program(), feed={"x": xs},
                        fetch_list=[out])       # warm: compile outside
                with profiler.profiler("All", sorted_key="total",
                                       trace_dir=str(tmp_path / "tr")):
                    for _ in range(5):
                        exe.run(fluid.default_main_program(),
                                feed={"x": xs}, fetch_list=[out])
        captured = capsys.readouterr().out
        device_rows = [ln for ln in captured.splitlines()
                       if ln.startswith("[device]")]
        assert device_rows, captured
        assert device_rows[0].split()[1] == "matmul", device_rows
