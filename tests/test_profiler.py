"""Profiler: host event table, per-op eager events, chrome-trace export
(reference: profiler.py:76, platform/profiler.h, tools/timeline.py:31,
test_profiler.py)."""

import json

import numpy as np

import paddle_tpu as fluid
from paddle_tpu import executor as executor_mod
from paddle_tpu import profiler


class TestProfiler:
    def _run_once(self, use_jit):
        with fluid.program_guard(fluid.Program(), fluid.Program()):
            x = fluid.layers.data(name="x", shape=[4], dtype="float32")
            y = fluid.layers.fc(input=x, size=3)
            out = fluid.layers.reduce_sum(y)
            exe = fluid.Executor(fluid.CPUPlace())
            with executor_mod.scope_guard(executor_mod.Scope()):
                exe.run(fluid.default_startup_program())
                exe.run(fluid.default_main_program(),
                        feed={"x": np.zeros((2, 4), np.float32)},
                        fetch_list=[out], use_jit=use_jit)

    def test_jit_run_records_block_event(self, capsys, tmp_path):
        profiler.reset_profiler()
        with profiler.profiler("All", sorted_key="total"):
            self._run_once(use_jit=True)
        captured = capsys.readouterr().out
        assert "executor_run(jit)" in captured

        trace = str(tmp_path / "trace.json")
        profiler.export_chrome_trace(trace)
        data = json.load(open(trace))
        names = {e["name"] for e in data["traceEvents"]}
        assert "executor_run(jit)" in names
        assert all(e["ph"] == "X" and e["dur"] >= 0
                   for e in data["traceEvents"])

    def test_eager_run_records_per_op_events(self, capsys):
        profiler.reset_profiler()
        with profiler.profiler("All"):
            self._run_once(use_jit=False)
        captured = capsys.readouterr().out
        assert "mul" in captured and "reduce_sum" in captured
