"""Profiler: host event table, per-op eager events, chrome-trace export
(reference: profiler.py:76, platform/profiler.h, tools/timeline.py:31,
test_profiler.py)."""

import json

import numpy as np

import paddle_tpu as fluid
from paddle_tpu import executor as executor_mod
from paddle_tpu import profiler


class TestProfiler:
    def _run_once(self, use_jit):
        with fluid.program_guard(fluid.Program(), fluid.Program()):
            x = fluid.layers.data(name="x", shape=[4], dtype="float32")
            y = fluid.layers.fc(input=x, size=3)
            out = fluid.layers.reduce_sum(y)
            exe = fluid.Executor(fluid.CPUPlace())
            with executor_mod.scope_guard(executor_mod.Scope()):
                exe.run(fluid.default_startup_program())
                exe.run(fluid.default_main_program(),
                        feed={"x": np.zeros((2, 4), np.float32)},
                        fetch_list=[out], use_jit=use_jit)

    def test_jit_run_records_block_event(self, capsys, tmp_path):
        profiler.reset_profiler()
        with profiler.profiler("All", sorted_key="total"):
            self._run_once(use_jit=True)
        captured = capsys.readouterr().out
        assert "executor_run(jit)" in captured

        trace = str(tmp_path / "trace.json")
        profiler.export_chrome_trace(trace)
        data = json.load(open(trace))
        names = {e["name"] for e in data["traceEvents"]}
        assert "executor_run(jit)" in names
        assert all(e["ph"] == "X" and e["dur"] >= 0
                   for e in data["traceEvents"])

    def test_eager_run_records_per_op_events(self, capsys):
        profiler.reset_profiler()
        with profiler.profiler("All"):
            self._run_once(use_jit=False)
        captured = capsys.readouterr().out
        assert "mul" in captured and "reduce_sum" in captured

    def test_jit_device_table_attributes_hot_op(self, capsys, tmp_path):
        """Per-op device-time attribution in JIT mode (VERDICT r4 #8):
        the xplane trace joined with the compiled HLO's pd.<op> scopes
        must rank the known-hot op — a 768x768 matmul dwarfing the other
        ops — first, like the reference's ParseEvents table
        (platform/profiler.h:137-166)."""
        n = 768
        profiler.reset_profiler()
        with fluid.program_guard(fluid.Program(), fluid.Program()):
            x = fluid.layers.data(name="x", shape=[n, n], dtype="float32",
                                  append_batch_size=False)
            y = fluid.layers.matmul(x, x)
            out = fluid.layers.reduce_sum(fluid.layers.sigmoid(y))
            exe = fluid.Executor(fluid.CPUPlace())
            with executor_mod.scope_guard(executor_mod.Scope()):
                exe.run(fluid.default_startup_program())
                xs = np.random.RandomState(0).randn(n, n) \
                    .astype(np.float32) * 0.01
                exe.run(fluid.default_main_program(), feed={"x": xs},
                        fetch_list=[out])       # warm: compile outside
                with profiler.profiler("All", sorted_key="total",
                                       trace_dir=str(tmp_path / "tr")):
                    for _ in range(5):
                        exe.run(fluid.default_main_program(),
                                feed={"x": xs}, fetch_list=[out])
        captured = capsys.readouterr().out
        device_rows = [ln for ln in captured.splitlines()
                       if ln.startswith("[device]")]
        assert device_rows, captured
        assert device_rows[0].split()[1] == "matmul", device_rows


class TestXplaneRoundTrip:
    """Real jax.profiler.trace -> xplane parser round-trip on the CPU
    backend (ISSUE 6): CPU jax writes only host planes, so these pin the
    host-plane fallback, the timeline/offset parsing, and the analytic
    FLOPs vs XLA cost_analysis cross-check."""

    def _trace(self, tmp_path):
        import jax
        import jax.numpy as jnp
        f = jax.jit(lambda a: (a @ a).sum())
        x = jnp.ones((128, 128), jnp.float32)
        f(x).block_until_ready()            # compile outside the trace
        with jax.profiler.trace(str(tmp_path)):
            for _ in range(3):
                f(x).block_until_ready()

    def test_host_plane_fallback_keeps_only_instructions(self, tmp_path):
        from paddle_tpu import xplane
        self._trace(tmp_path)
        agg = xplane.aggregate_dir(str(tmp_path))
        assert agg, "trace produced no aggregatable events"
        # the fallback must admit only instruction-like names: the python
        # line's '$profiler.py:226 trace' event spans the whole session
        # and would otherwise dwarf every real instruction
        assert all(xplane.instr_like(name) for name in agg), agg
        assert any(name.startswith("dot") for name in agg), agg

    def test_timeline_parses_offsets_and_timestamps(self, tmp_path):
        from paddle_tpu import xplane
        self._trace(tmp_path)
        records = xplane.timeline_dir(str(tmp_path))
        lines = [r for r in records if r["events"]]
        assert lines
        assert any(r["timestamp_ns"] > 0 for r in lines)
        # offsets place events within their line: the three timed calls
        # must yield distinct, increasing offsets for the repeated dot
        dots = sorted(off for r in lines for (name, off, dur) in r["events"]
                      if name.startswith("dot") and dur > 0)
        assert len(dots) >= 2 and dots[0] < dots[-1], dots

    def test_matmul_flops_crosscheck_within_10pct(self, tmp_path,
                                                  monkeypatch):
        from paddle_tpu import roofline
        monkeypatch.setenv("PADDLE_TPU_SUSTAINED_TFLOPS", "0.5")
        monkeypatch.setenv("PADDLE_TPU_HBM_GBPS", "20")
        monkeypatch.setattr(roofline, "_PROBES", {})
        n = 256
        profiler.reset_profiler()
        with fluid.program_guard(fluid.Program(), fluid.Program()):
            x = fluid.layers.data(name="x", shape=[n, n], dtype="float32",
                                  append_batch_size=False)
            out = fluid.layers.reduce_sum(fluid.layers.matmul(x, x))
            exe = fluid.Executor(fluid.CPUPlace())
            with executor_mod.scope_guard(executor_mod.Scope()):
                exe.run(fluid.default_startup_program())
                xs = np.random.RandomState(0).randn(n, n) \
                    .astype(np.float32) * 0.01
                main = fluid.default_main_program()

                def step():
                    exe.run(main, feed={"x": xs}, fetch_list=[out])

                step()                      # warm: compile outside
                report = roofline.capture(step, steps=4)
        assert report is not None
        rows = {r["op"]: r for r in report["rows"]}
        assert "matmul" in rows, rows
        assert rows["matmul"]["flops"] == 2.0 * n ** 3
        cc = report.get("cost_crosscheck")
        assert cc, report["notes"]
        assert cc["rel_err"] <= 0.10, cc
        # fractions sum to the true device total, unattributed included
        assert abs(sum(r["frac"] for r in report["rows"]) - 1.0) < 1e-6
