"""Child process for test_jax_distributed: a real 2-process jax.distributed
bring-up on the CPU backend (localhost coordinator), the moral equivalent
of the reference's localhost pserver test
(reference python/paddle/fluid/tests/unittests/test_recv_op.py:26-36).

Run as:  python _distributed_worker.py <coordinator> <nprocs> <pid>

Prints one line `RESULT <json>` on success. Kept importable without pytest
so both children stay lightweight."""

import json
import os
import sys


def main(coordinator, nprocs, pid):
    os.environ["JAX_PLATFORMS"] = "cpu"
    # one local CPU device per process: the 2-process mesh is 2 devices
    os.environ.setdefault("XLA_FLAGS", "")
    import numpy as np
    import jax

    jax.config.update("jax_platforms", "cpu")

    from paddle_tpu.parallel import multihost

    assert multihost.initialize(coordinator_address=coordinator,
                                num_processes=nprocs, process_id=pid)
    assert jax.process_count() == nprocs, jax.process_count()
    assert jax.process_index() == pid

    import jax.numpy as jnp
    from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

    devs = jax.devices()
    assert len(devs) == nprocs, devs
    mesh = Mesh(np.array(devs), ("dp",))

    # 1) cross-process psum: each process contributes (pid + 1); the
    # replicated sum must be visible on every process
    local = np.full((1, 4), pid + 1, np.float32)
    garr = jax.make_array_from_process_local_data(
        NamedSharding(mesh, P("dp")), local, (nprocs, 4))
    total = jax.jit(jnp.sum,
                    out_shardings=NamedSharding(mesh, P()))(garr)
    psum_val = float(np.asarray(total))
    want = sum(range(1, nprocs + 1)) * 4.0
    assert psum_val == want, (psum_val, want)

    # 2) one sharded SGD step: batch sharded over the 2-process 'dp' axis,
    # params replicated — XLA inserts the cross-host gradient AllReduce.
    # Identical data/init on both processes => loss must equal the
    # single-process oracle computed locally.
    rng = np.random.default_rng(0)
    x_all = rng.standard_normal((4, 8)).astype(np.float32)
    y_all = rng.standard_normal((4, 1)).astype(np.float32)
    w0 = rng.standard_normal((8, 1)).astype(np.float32) * 0.1

    def loss_fn(w, x, y):
        return jnp.mean((x @ w - y) ** 2)

    def step(w, x, y):
        g = jax.grad(loss_fn)(w, x, y)
        w = w - 0.1 * g
        return w, loss_fn(w, x, y)

    # oracle on host numpy (single process math)
    import numpy.linalg  # noqa: F401
    gw = (2.0 / 4) * x_all.T @ (x_all @ w0 - y_all)
    w1 = w0 - 0.1 * gw
    oracle = float(np.mean((x_all @ w1 - y_all) ** 2))

    per = x_all.shape[0] // nprocs
    x_g = jax.make_array_from_process_local_data(
        NamedSharding(mesh, P("dp")), x_all[pid * per:(pid + 1) * per],
        x_all.shape)
    y_g = jax.make_array_from_process_local_data(
        NamedSharding(mesh, P("dp")), y_all[pid * per:(pid + 1) * per],
        y_all.shape)
    w_g = jax.device_put(w0, NamedSharding(mesh, P()))
    _, loss = jax.jit(step, out_shardings=(
        NamedSharding(mesh, P()), NamedSharding(mesh, P())))(w_g, x_g, y_g)
    loss = float(np.asarray(loss))
    assert abs(loss - oracle) < 1e-5, (loss, oracle)

    print(f"RESULT {json.dumps({'pid': pid, 'psum': psum_val, 'loss': loss})}",
          flush=True)


if __name__ == "__main__":
    main(sys.argv[1], int(sys.argv[2]), int(sys.argv[3]))
