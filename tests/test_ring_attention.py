"""Ring attention (sequence/context parallelism) vs single-device oracle
on the 8-device host mesh — forward and gradients, causal and full."""

import numpy as np
import pytest
import jax
import jax.numpy as jnp

from paddle_tpu.parallel import mesh as mesh_mod
from paddle_tpu.parallel.ring_attention import (attention_reference,
                                                ring_attention_sharded)

RNG = np.random.RandomState(13)


@pytest.fixture(scope="module")
def mesh():
    return mesh_mod.make_mesh((8,), ("sp",))


def _qkv(b=2, t=32, h=2, d=8):
    q = RNG.randn(b, t, h, d).astype(np.float32)
    k = RNG.randn(b, t, h, d).astype(np.float32)
    v = RNG.randn(b, t, h, d).astype(np.float32)
    return jnp.asarray(q), jnp.asarray(k), jnp.asarray(v)


class TestRingAttention:
    @pytest.mark.parametrize("causal", [False, True])
    def test_matches_reference(self, mesh, causal):
        q, k, v = _qkv()
        want = attention_reference(q, k, v, causal=causal)
        got = ring_attention_sharded(q, k, v, mesh, causal=causal)
        np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                                   rtol=2e-5, atol=2e-6)

    @pytest.mark.parametrize("causal", [False, True])
    def test_gradients_match(self, mesh, causal):
        q, k, v = _qkv(b=1, t=16, h=1, d=4)

        def loss_ref(q, k, v):
            return jnp.sum(attention_reference(q, k, v, causal=causal) ** 2)

        def loss_ring(q, k, v):
            return jnp.sum(
                ring_attention_sharded(q, k, v, mesh, causal=causal) ** 2)

        g_ref = jax.grad(loss_ref, argnums=(0, 1, 2))(q, k, v)
        g_ring = jax.grad(loss_ring, argnums=(0, 1, 2))(q, k, v)
        for a, b in zip(g_ring, g_ref):
            np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                       rtol=5e-5, atol=5e-6)

    def test_long_sequence_never_materializes_full_scores(self, mesh):
        """Smoke at a length where full [T, T] scores would be 64x the
        per-shard block: just asserts the sharded form runs and matches."""
        q, k, v = _qkv(b=1, t=256, h=1, d=8)
        want = attention_reference(q, k, v, causal=True)
        got = ring_attention_sharded(q, k, v, mesh, causal=True)
        np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                                   rtol=5e-5, atol=5e-6)


class TestAttentionOpInProgram:
    def _run(self, mesh, seq_par):
        import paddle_tpu as fluid
        from paddle_tpu import executor as executor_mod
        local = np.random.RandomState(77)
        q_np = local.randn(2, 32, 2, 8).astype(np.float32)
        k_np = local.randn(2, 32, 2, 8).astype(np.float32)
        v_np = local.randn(2, 32, 2, 8).astype(np.float32)
        main, startup = fluid.Program(), fluid.Program()
        with fluid.program_guard(main, startup):
            q = fluid.layers.data(name="q", shape=[2, 32, 2, 8],
                                  dtype="float32", append_batch_size=False)
            k = fluid.layers.data(name="k", shape=[2, 32, 2, 8],
                                  dtype="float32", append_batch_size=False)
            v = fluid.layers.data(name="v", shape=[2, 32, 2, 8],
                                  dtype="float32", append_batch_size=False)
            out = fluid.layers.fused_attention(
                q, k, v, causal=True, sequence_parallel=seq_par)
        if mesh is not None:
            main._mesh = mesh
            for n in ("q", "k", "v"):
                fluid.parallel.shard_feed(main, n, (None, "sp", None, None))
        exe = fluid.Executor(fluid.CPUPlace())
        with executor_mod.scope_guard(executor_mod.Scope()):
            got, = exe.run(main, feed={"q": q_np, "k": k_np, "v": v_np},
                           fetch_list=[out])
        return np.asarray(got)

    def test_program_level_ring_matches_single(self):
        import paddle_tpu as fluid
        from paddle_tpu.parallel import mesh as mesh_mod
        single = self._run(None, False)
        ring = self._run(mesh_mod.make_mesh((8,), ("sp",)), True)
        np.testing.assert_allclose(ring, single, rtol=2e-5, atol=2e-6)


class TestRingAttentionNegativeLogits:
    def test_strongly_negative_scores_causal(self, mesh):
        """Regression: a later fully-masked visiting block must not reset
        the running max to 0 when all true logits are very negative."""
        local = np.random.RandomState(99)
        q = jnp.asarray(local.randn(1, 16, 1, 4).astype(np.float32)) * 10.0
        k = -q  # q·k strongly negative everywhere
        v = jnp.asarray(local.randn(1, 16, 1, 4).astype(np.float32))
        want = attention_reference(q, k, v, causal=True)
        got = ring_attention_sharded(q, k, v, mesh, causal=True)
        np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                                   rtol=1e-4, atol=1e-5)
        assert not np.allclose(np.asarray(got), 0.0)

    def test_gradients_finite_negative_logits(self, mesh):
        """Regression: gradients stay finite (and match the reference) in
        the strongly-negative-logit regime."""
        local = np.random.RandomState(99)
        q = jnp.asarray(local.randn(1, 16, 1, 4).astype(np.float32)) * 10.0
        k = -q
        v = jnp.asarray(local.randn(1, 16, 1, 4).astype(np.float32))

        def loss_ring(q, k, v):
            return jnp.sum(
                ring_attention_sharded(q, k, v, mesh, causal=True) ** 2)

        def loss_ref(q, k, v):
            return jnp.sum(attention_reference(q, k, v, causal=True) ** 2)

        g_ring = jax.grad(loss_ring, argnums=(0, 1, 2))(q, k, v)
        g_ref = jax.grad(loss_ref, argnums=(0, 1, 2))(q, k, v)
        for a, b in zip(g_ring, g_ref):
            assert np.isfinite(np.asarray(a)).all()
            np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                       rtol=1e-3, atol=1e-5)
