"""Ring attention (sequence/context parallelism) vs single-device oracle
on the 8-device host mesh — forward and gradients, causal and full."""

import numpy as np
import pytest
import jax
import jax.numpy as jnp

from paddle_tpu.parallel import mesh as mesh_mod
from paddle_tpu.parallel.ring_attention import (attention_reference,
                                                ring_attention_sharded)

RNG = np.random.RandomState(13)


@pytest.fixture(scope="module")
def mesh():
    return mesh_mod.make_mesh((8,), ("sp",))


def _qkv(b=2, t=32, h=2, d=8):
    q = RNG.randn(b, t, h, d).astype(np.float32)
    k = RNG.randn(b, t, h, d).astype(np.float32)
    v = RNG.randn(b, t, h, d).astype(np.float32)
    return jnp.asarray(q), jnp.asarray(k), jnp.asarray(v)


class TestRingAttention:
    @pytest.mark.parametrize("causal", [False, True])
    def test_matches_reference(self, mesh, causal):
        q, k, v = _qkv()
        want = attention_reference(q, k, v, causal=causal)
        got = ring_attention_sharded(q, k, v, mesh, causal=causal)
        np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                                   rtol=2e-5, atol=2e-6)

    @pytest.mark.parametrize("causal", [False, True])
    def test_gradients_match(self, mesh, causal):
        q, k, v = _qkv(b=1, t=16, h=1, d=4)

        def loss_ref(q, k, v):
            return jnp.sum(attention_reference(q, k, v, causal=causal) ** 2)

        def loss_ring(q, k, v):
            return jnp.sum(
                ring_attention_sharded(q, k, v, mesh, causal=causal) ** 2)

        g_ref = jax.grad(loss_ref, argnums=(0, 1, 2))(q, k, v)
        g_ring = jax.grad(loss_ring, argnums=(0, 1, 2))(q, k, v)
        for a, b in zip(g_ring, g_ref):
            np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                       rtol=5e-5, atol=5e-6)

    def test_long_sequence_never_materializes_full_scores(self, mesh):
        """Smoke at a length where full [T, T] scores would be 64x the
        per-shard block: just asserts the sharded form runs and matches."""
        q, k, v = _qkv(b=1, t=256, h=1, d=8)
        want = attention_reference(q, k, v, causal=True)
        got = ring_attention_sharded(q, k, v, mesh, causal=True)
        np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                                   rtol=5e-5, atol=5e-6)


class TestAttentionOpInProgram:
    def _run(self, mesh, seq_par):
        import paddle_tpu as fluid
        from paddle_tpu import executor as executor_mod
        local = np.random.RandomState(77)
        q_np = local.randn(2, 32, 2, 8).astype(np.float32)
        k_np = local.randn(2, 32, 2, 8).astype(np.float32)
        v_np = local.randn(2, 32, 2, 8).astype(np.float32)
        main, startup = fluid.Program(), fluid.Program()
        with fluid.program_guard(main, startup):
            q = fluid.layers.data(name="q", shape=[2, 32, 2, 8],
                                  dtype="float32", append_batch_size=False)
            k = fluid.layers.data(name="k", shape=[2, 32, 2, 8],
                                  dtype="float32", append_batch_size=False)
            v = fluid.layers.data(name="v", shape=[2, 32, 2, 8],
                                  dtype="float32", append_batch_size=False)
            out = fluid.layers.fused_attention(
                q, k, v, causal=True, sequence_parallel=seq_par)
        if mesh is not None:
            main._mesh = mesh
            for n in ("q", "k", "v"):
                fluid.parallel.shard_feed(main, n, (None, "sp", None, None))
        exe = fluid.Executor(fluid.CPUPlace())
        with executor_mod.scope_guard(executor_mod.Scope()):
            got, = exe.run(main, feed={"q": q_np, "k": k_np, "v": v_np},
                           fetch_list=[out])
        return np.asarray(got)

    def test_program_level_ring_matches_single(self):
        import paddle_tpu as fluid
        from paddle_tpu.parallel import mesh as mesh_mod
        single = self._run(None, False)
        ring = self._run(mesh_mod.make_mesh((8,), ("sp",)), True)
        np.testing.assert_allclose(ring, single, rtol=2e-5, atol=2e-6)

    def _run_grads(self, mesh, seq_par, t=128):
        """Train-direction ring: append_backward over the attention op with
        an sp mesh; returns (dq, dk, dv, lse). t=128 makes the per-shard
        length (16) flash-tileable, so the op takes the DIRECT blockwise
        ring backward from the saved (Out, LSE) — no forward re-run
        (ADVICE r4; nn_ops._sdpa_grad_kernel ring branch)."""
        import paddle_tpu as fluid
        from paddle_tpu import executor as executor_mod
        from paddle_tpu.framework.framework import grad_var_name
        local = np.random.RandomState(31)
        shp = (2, t, 2, 8)
        feed = {n: local.randn(*shp).astype(np.float32)
                for n in ("q", "k", "v")}
        main, startup = fluid.Program(), fluid.Program()
        with fluid.program_guard(main, startup):
            qkv = []
            for n in ("q", "k", "v"):
                var = fluid.layers.data(name=n, shape=list(shp),
                                        dtype="float32",
                                        append_batch_size=False)
                var.stop_gradient = False
                var.desc.stop_gradient = False
                qkv.append(var)
            out = fluid.layers.fused_attention(
                *qkv, causal=True, sequence_parallel=seq_par)
            loss = fluid.layers.mean(
                fluid.layers.elementwise_mul(out, out))
            fluid.backward.append_backward(loss)
        sdpa_op, = [op for op in main.global_block().ops
                    if op.type == "scaled_dot_product_attention"]
        lse_name = sdpa_op.output("LSE")[0]
        if mesh is not None:
            main._mesh = mesh
            for n in ("q", "k", "v"):
                fluid.parallel.shard_feed(main, n, (None, "sp", None, None))
        exe = fluid.Executor(fluid.CPUPlace())
        with executor_mod.scope_guard(executor_mod.Scope()):
            res = exe.run(main, feed=feed,
                          fetch_list=[grad_var_name(n)
                                      for n in ("q", "k", "v")] + [lse_name])
        return [np.asarray(r) for r in res]

    def test_ring_grads_match_single_and_lse_is_real(self):
        """The flash-ring explicit backward (direct from saved Out+LSE)
        matches the single-device einsum gradients, and the ring forward
        emits the true logsumexp — not the r4 zeros placeholder."""
        from paddle_tpu.parallel import mesh as mesh_mod
        *single_grads, single_lse = self._run_grads(None, False)
        *ring_grads, ring_lse = self._run_grads(
            mesh_mod.make_mesh((8,), ("sp",)), True)
        for g, w in zip(ring_grads, single_grads):
            np.testing.assert_allclose(g, w, rtol=1e-3, atol=1e-5)
        assert not np.allclose(ring_lse, 0.0)
        np.testing.assert_allclose(ring_lse, single_lse, rtol=1e-4,
                                   atol=1e-4)


class TestRingAttentionNegativeLogits:
    def test_strongly_negative_scores_causal(self, mesh):
        """Regression: a later fully-masked visiting block must not reset
        the running max to 0 when all true logits are very negative."""
        local = np.random.RandomState(99)
        q = jnp.asarray(local.randn(1, 16, 1, 4).astype(np.float32)) * 10.0
        k = -q  # q·k strongly negative everywhere
        v = jnp.asarray(local.randn(1, 16, 1, 4).astype(np.float32))
        want = attention_reference(q, k, v, causal=True)
        got = ring_attention_sharded(q, k, v, mesh, causal=True)
        np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                                   rtol=1e-4, atol=1e-5)
        assert not np.allclose(np.asarray(got), 0.0)

    def test_gradients_finite_negative_logits(self, mesh):
        """Regression: gradients stay finite (and match the reference) in
        the strongly-negative-logit regime."""
        local = np.random.RandomState(99)
        q = jnp.asarray(local.randn(1, 16, 1, 4).astype(np.float32)) * 10.0
        k = -q
        v = jnp.asarray(local.randn(1, 16, 1, 4).astype(np.float32))

        def loss_ring(q, k, v):
            return jnp.sum(
                ring_attention_sharded(q, k, v, mesh, causal=True) ** 2)

        def loss_ref(q, k, v):
            return jnp.sum(attention_reference(q, k, v, causal=True) ** 2)

        g_ring = jax.grad(loss_ring, argnums=(0, 1, 2))(q, k, v)
        g_ref = jax.grad(loss_ref, argnums=(0, 1, 2))(q, k, v)
        for a, b in zip(g_ring, g_ref):
            assert np.isfinite(np.asarray(a)).all()
            np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                       rtol=1e-3, atol=1e-5)
