"""v2 user-surface breadth (VERDICT r3 missing #3): networks composites,
numpy image augmentation, pooling/evaluator shims, mq2007 dataset, and the
acceptance bar — a reference-shaped v2 sentiment-LSTM script that touches
ONLY paddle_tpu.v2.* end-to-end (reference python/paddle/v2 demo style)."""

import numpy as np
import pytest

from paddle_tpu import v2 as paddle


class TestV2Networks:
    def test_sentiment_lstm_end_to_end(self):
        """The VERDICT acceptance script: data -> embedding -> simple_lstm
        -> pooling -> fc -> classification_cost, trained by the v2 SGD
        event loop on the imdb reader surface, then infer()."""
        from paddle_tpu.dataset import imdb

        vocab = len(imdb.word_dict())
        words = paddle.layer.data(
            name="words", type=paddle.data_type.integer_value_sequence(vocab))
        label = paddle.layer.data(
            name="label", type=paddle.data_type.integer_value(2))
        emb = paddle.layer.embedding(input=words, size=32, vocab_size=vocab)
        lstm = paddle.networks.simple_lstm(input=emb, size=32)
        pooled = paddle.layer.pooling(lstm,
                                      pooling_type=paddle.pooling.Max)
        logits = paddle.layer.fc(input=pooled, size=2,
                                 act=paddle.activation.Linear)
        cost = paddle.layer.classification_cost(input=logits, label=label)

        parameters = paddle.parameters.create(cost)
        trainer = paddle.SGD(
            cost=cost, parameters=parameters,
            update_equation=paddle.optimizer.Adam(learning_rate=1e-2))

        def reader():
            src = imdb.train()()
            batch = []
            for i, (ws, lab) in enumerate(src):
                if i >= 96:
                    break
                batch.append((ws, [lab]))
                if len(batch) == 16:
                    yield batch
                    batch = []

        costs = []

        def handler(e):
            if isinstance(e, paddle.event.EndIteration):
                costs.append(e.cost)

        trainer.train(reader, num_passes=8, event_handler=handler,
                      feeding={"words": 0, "label": 1})
        assert np.isfinite(costs).all()
        # synthetic imdb splits vocab by sentiment: easily separable
        assert costs[-1] < costs[0] * 0.6, (costs[0], costs[-1])

        out = paddle.infer(output_layer=logits, parameters=parameters,
                           input=[([5, 6, 7],), ([3000, 3001],)],
                           feeding={"words": 0})
        assert np.asarray(out).shape == (2, 2)

        # SGD.test: forward-only evaluation on held-out data — trained
        # on separable synthetic imdb, test cost must be low and the
        # parameters must be untouched by testing
        from paddle_tpu.dataset import imdb as imdb_mod

        def test_reader():
            batch = []
            for i, (ws, lab) in enumerate(imdb_mod.test()()):
                if i >= 32:
                    break
                batch.append((ws, [lab]))
                if len(batch) == 16:
                    yield batch
                    batch = []

        before = {n: parameters[n].copy() for n in parameters.names()}
        result = trainer.test(test_reader, feeding={"words": 0, "label": 1})
        assert isinstance(result, paddle.event.TestResult)
        assert result.num_samples == 32
        assert result.cost < 0.5, result.cost
        for n, w in before.items():
            np.testing.assert_array_equal(parameters[n], w)

    def test_img_conv_pool_and_group(self):
        import paddle_tpu as fluid
        img = paddle.layer.data(name="im",
                                type=paddle.data_type.dense_vector(3 * 16 * 16))
        img4 = fluid.layers.reshape(img, [-1, 3, 16, 16])
        c1 = paddle.networks.simple_img_conv_pool(
            input=img4, filter_size=3, num_filters=4, pool_size=2,
            pool_stride=2, act=paddle.activation.Relu())
        g = paddle.networks.img_conv_group(
            input=img4, conv_num_filter=[4, 4], pool_size=2,
            conv_act=paddle.activation.Relu())
        # conv 3x3 valid on 16 -> 14, pool 2/2 -> 7; group keeps channels
        assert c1.shape[-1] == 7 and g.shape[1] == 4

    def test_bidirectional_lstm_and_gru_shapes(self):
        vocab = 50
        w = paddle.layer.data(
            name="w2", type=paddle.data_type.integer_value_sequence(vocab))
        emb = paddle.layer.embedding(input=w, size=8, vocab_size=vocab)
        bi = paddle.networks.bidirectional_lstm(input=emb, size=8)
        gru = paddle.networks.simple_gru(input=emb, size=8)
        assert bi.shape[-1] == 16 and gru.shape[-1] == 8


class TestV2NamespaceAliases:
    def test_canonical_reader_composition(self):
        """The composition every reference v2 script opens with:
        paddle.batch(paddle.reader.shuffle(paddle.dataset.X.train()))."""
        r = paddle.batch(
            paddle.reader.shuffle(paddle.dataset.uci_housing.train(),
                                  buf_size=64), batch_size=8)
        b = next(iter(r()))
        assert len(b) == 8 and len(b[0]) == 2

    def test_reader_creators(self):
        import os
        import tempfile
        from paddle_tpu.reader import creator
        from paddle_tpu.recordio import write_samples

        rows = list(creator.np_array(np.arange(6).reshape(3, 2))())
        assert [list(r) for r in rows] == [[0, 1], [2, 3], [4, 5]]
        with tempfile.TemporaryDirectory() as d:
            p = os.path.join(d, "t.txt")
            with open(p, "w") as f:
                f.write("a\nbb\n")
            assert list(creator.text_file(p)()) == ["a", "bb"]
            rp = os.path.join(d, "x.recordio")
            write_samples(rp, [("s", 1), ("t", 2)])
            assert list(creator.recordio(rp, decode=True)()) == [
                ("s", 1), ("t", 2)]
            assert all(isinstance(r, bytes)
                       for r in creator.recordio(rp)())


class TestV2Image:
    def test_simple_transform_train_and_test(self):
        from paddle_tpu.v2 import image as v2_image
        rng = np.random.RandomState(0)
        im = rng.randint(0, 255, (40, 60, 3)).astype(np.uint8)
        test_out = v2_image.simple_transform(im, 32, 24, is_train=False,
                                             mean=[1.0, 2.0, 3.0])
        assert test_out.shape == (3, 24, 24) and test_out.dtype == np.float32
        train_out = v2_image.simple_transform(
            im, 32, 24, is_train=True, rng=np.random.RandomState(3))
        assert train_out.shape == (3, 24, 24)
        batch = v2_image.batch_images([test_out, train_out])
        assert batch.shape == (2, 3, 24, 24)

    def test_resize_short_keeps_aspect(self):
        from paddle_tpu.v2 import image as v2_image
        im = np.arange(20 * 10 * 3, dtype=np.uint8).reshape(20, 10, 3)
        out = v2_image.resize_short(im, 5)
        assert out.shape == (10, 5, 3)
        # constant image resizes to the same constant (bilinear sanity)
        const = np.full((8, 12, 3), 77, np.uint8)
        out2 = v2_image.resize_short(const, 6)
        assert (out2 == 77).all()

    def test_flip_and_crops(self):
        from paddle_tpu.v2 import image as v2_image
        im = np.arange(16).reshape(4, 4).astype(np.float32)
        np.testing.assert_array_equal(v2_image.left_right_flip(im),
                                      im[:, ::-1])
        assert v2_image.center_crop(im, 2).shape == (2, 2)
        assert v2_image.random_crop(
            im, 2, rng=np.random.RandomState(0)).shape == (2, 2)


class TestV2RecurrentGroup:
    def test_vanilla_rnn_matches_manual_recurrence(self):
        """recurrent_group with a named-memory fc step (the reference's
        canonical custom-RNN shape) vs a numpy recurrence oracle."""
        import paddle_tpu as fluid
        from paddle_tpu import executor as executor_mod

        H, D, vocab = 4, 3, 20
        seq = paddle.layer.data(
            name="sq3", type=paddle.data_type.integer_value_sequence(vocab))
        emb = paddle.layer.embedding(input=seq, size=D, vocab_size=vocab,
                                     param_attr="rg_emb")

        def step(x_t):
            prev = paddle.layer.memory(name="h", size=H)
            h = paddle.layer.fc(input=[x_t, prev], size=H,
                                act=paddle.activation.Tanh(),
                                param_attr="rg_w", bias_attr="rg_b",
                                name="h")
            return h

        out = paddle.layer.recurrent_group(step=step, input=emb)
        last = paddle.layer.last_seq(out)

        exe = fluid.Executor(fluid.CPUPlace())
        sc = executor_mod.Scope()
        with executor_mod.scope_guard(sc):
            exe.run(fluid.framework.framework.default_startup_program())
            LoD = executor_mod.LoDTensor
            ids = np.array([[1], [2], [3], [7], [8]], np.int64)
            feed = {"sq3": LoD(ids, [[0, 3, 5]])}
            got, = exe.run(
                fluid.framework.framework.default_main_program(),
                feed=feed, fetch_list=[last])
            emb_w = np.asarray(sc.find_var("rg_emb"))
            # fc over [x_t, prev]: first weight keeps the given name, the
            # second replica gets a generated one (reference
            # multiple_param_attr semantics) — find it by shape [H, H]
            w = np.asarray(sc.find_var("rg_w"))
            b = np.asarray(sc.find_var("rg_b"))
            w2_name, = [n for n in sc.local_var_names()
                        if n not in ("rg_w", "rg_b", "rg_emb")
                        and getattr(sc.find_var(n), "shape", None) == (H, H)]
            w2 = np.asarray(sc.find_var(w2_name))

        def run_seq(token_ids):
            h = np.zeros(H, np.float32)
            for t in token_ids:
                x = emb_w[t]
                h = np.tanh(x @ w + h @ w2 + b)
            return h

        want = np.stack([run_seq([1, 2, 3]), run_seq([7, 8])])
        np.testing.assert_allclose(np.asarray(got), want, rtol=1e-5,
                                   atol=1e-6)

    def test_static_input_visible_every_step(self):
        """StaticInput: the same per-batch vector joins every step's
        computation (the reference seq2seq pattern for the encoded
        source)."""
        import paddle_tpu as fluid
        from paddle_tpu import executor as executor_mod

        seq = paddle.layer.data(name="sq5",
                                type=paddle.data_type.dense_vector_sequence(2))
        ctxv = paddle.layer.data(name="cx5",
                                 type=paddle.data_type.dense_vector(2))

        def step(x_t, c):
            prev = paddle.layer.memory(name="acc", size=2)
            s = paddle.layer.addto([x_t, c, prev], name="acc")
            return s

        out = paddle.layer.recurrent_group(
            step=step, input=[seq, paddle.layer.StaticInput(ctxv)])
        last = paddle.layer.last_seq(out)
        exe = fluid.Executor(fluid.CPUPlace())
        with executor_mod.scope_guard(executor_mod.Scope()):
            exe.run(fluid.framework.framework.default_startup_program())
            LoD = executor_mod.LoDTensor
            x = np.array([[1, 1], [2, 2], [10, 10]], np.float32)
            feed = {"sq5": LoD(x, [[0, 2, 3]]),
                    "cx5": np.array([[0.5, 0.5], [3.0, 3.0]], np.float32)}
            got, = exe.run(
                fluid.framework.framework.default_main_program(),
                feed=feed, fetch_list=[last])
        # seq1: (1+.5) then +(2+.5) = 4; seq2: 10+3 = 13 — the static
        # vector is added at EVERY step
        np.testing.assert_allclose(np.asarray(got),
                                   [[4.0, 4.0], [13.0, 13.0]], rtol=1e-6)

    def test_memory_without_named_target_raises(self):
        emb = paddle.layer.data(name="sq4",
                                type=paddle.data_type.dense_vector(4))

        def bad_step(x_t):
            prev = paddle.layer.memory(name="nope", size=4)
            return paddle.layer.fc(input=[x_t, prev], size=4)  # unnamed

        with pytest.raises(ValueError, match="nope"):
            paddle.layer.recurrent_group(step=bad_step, input=emb)


class TestV2Evaluator:
    def test_classification_error(self):
        import paddle_tpu as fluid
        from paddle_tpu import executor as executor_mod
        pred = paddle.layer.data(name="p",
                                 type=paddle.data_type.dense_vector(3))
        lab = paddle.layer.data(name="l",
                                type=paddle.data_type.integer_value(3))
        err = paddle.evaluator.classification_error(input=pred, label=lab)
        exe = fluid.Executor(fluid.CPUPlace())
        with executor_mod.scope_guard(executor_mod.Scope()):
            p = np.array([[0.8, 0.1, 0.1], [0.2, 0.7, 0.1],
                          [0.3, 0.3, 0.4], [0.9, 0.05, 0.05]], np.float32)
            y = np.array([[0], [1], [1], [2]], np.int64)  # 2 right, 2 wrong
            from paddle_tpu.framework.framework import default_main_program
            got, = exe.run(default_main_program(), feed={"p": p, "l": y},
                           fetch_list=[err])
        assert abs(float(np.ravel(got)[0]) - 0.5) < 1e-6


class TestV2LayerWrappers:
    def _run(self, fetch, feed):
        import paddle_tpu as fluid
        from paddle_tpu import executor as executor_mod
        from paddle_tpu.framework.framework import default_main_program
        exe = fluid.Executor(fluid.CPUPlace())
        with executor_mod.scope_guard(executor_mod.Scope()):
            exe.run(fluid.framework.framework.default_startup_program())
            out, = exe.run(default_main_program(), feed=feed,
                           fetch_list=[fetch])
        return np.asarray(out)

    def test_elementwise_combinator_wrappers(self):
        a = paddle.layer.data(name="a", type=paddle.data_type.dense_vector(4))
        b = paddle.layer.data(name="b", type=paddle.data_type.dense_vector(4))
        w = paddle.layer.data(name="w", type=paddle.data_type.dense_vector(1))
        av = np.array([[1, 2, 3, 4], [5, 6, 7, 8]], np.float32)
        bv = np.array([[4, 3, 2, 1], [8, 7, 6, 5]], np.float32)
        wv = np.array([[0.25], [0.5]], np.float32)
        feed = {"a": av, "b": bv, "w": wv}   # whole program runs per fetch
        got = self._run(paddle.layer.interpolation([a, b], w), feed)
        np.testing.assert_allclose(got, wv * av + (1 - wv) * bv, rtol=1e-6)
        got = self._run(paddle.layer.scaling(a, w), feed)
        np.testing.assert_allclose(got, av * wv, rtol=1e-6)
        got = self._run(paddle.layer.slope_intercept(a, slope=2.0,
                                                     intercept=1.0), feed)
        np.testing.assert_allclose(got, 2 * av + 1, rtol=1e-6)
        got = self._run(paddle.layer.repeat(a, 2), feed)
        assert got.shape == (2, 8)

    def test_structural_wrappers_build(self):
        """img_cmrnorm/maxout/bilinear_interp/crf/ctc/nce/hsigmoid build
        valid IR over the fluid ops (shape-level smoke; the underlying
        ops have their own numeric tests)."""
        import paddle_tpu as fluid
        img = paddle.layer.data(name="im4",
                                type=paddle.data_type.dense_vector(4 * 8 * 8))
        img4 = fluid.layers.reshape(img, [-1, 4, 8, 8])
        assert paddle.layer.img_cmrnorm(img4, size=5).shape[1] == 4
        assert paddle.layer.maxout(img4, groups=2).shape[1] == 2
        bi = paddle.layer.bilinear_interp(img4, out_size_x=16, out_size_y=16)
        assert tuple(bi.shape[2:]) == (16, 16)
        seq = paddle.layer.data(
            name="sq", type=paddle.data_type.integer_value_sequence(30))
        emb = paddle.layer.embedding(input=seq, size=8, vocab_size=30)
        tags = paddle.layer.data(
            name="tg", type=paddle.data_type.integer_value_sequence(5))
        feat = paddle.layer.fc(input=emb, size=5, num_flatten_dims=2)
        cost = paddle.layer.crf(input=feat, label=tags)
        assert cost is not None

    def test_huber_matches_definition(self):
        p = paddle.layer.data(name="p", type=paddle.data_type.dense_vector(1))
        y = paddle.layer.data(name="y", type=paddle.data_type.dense_vector(1))
        cost = paddle.layer.huber_regression_cost(p, y, delta=2.0)
        pv = np.array([[0.0], [5.0]], np.float32)   # residuals 0 and 5
        yv = np.zeros((2, 1), np.float32)
        got = float(np.ravel(self._run(cost, {"p": pv, "y": yv}))[0])
        # per-element: 0 (quadratic at 0) and 2*5 - 0.5*4 = 8 -> mean 4
        assert abs(got - 4.0) < 1e-5, got


class TestMQ2007:
    def test_pairwise_reader_schema(self):
        from paddle_tpu.dataset import mq2007
        it = mq2007.train(format="pairwise")()
        label, hi, lo = next(it)
        assert label == 1.0 and hi.shape == (46,) and lo.shape == (46,)

    def test_listwise_and_pointwise(self):
        from paddle_tpu.dataset import mq2007
        rels, feats = next(mq2007.test(format="listwise")())
        assert feats.shape == (len(rels), 46)
        f, r = next(mq2007.test(format="pointwise")())
        assert f.shape == (46,) and r in (0.0, 1.0, 2.0)

    def test_ranknet_learns_pairwise_order(self):
        """rank_cost over mq2007 pairs: the planted LETOR signal must be
        learnable through the v2 surface (reference ssd/rank demos)."""
        from paddle_tpu.dataset import mq2007
        left = paddle.layer.data(name="left",
                                 type=paddle.data_type.dense_vector(46))
        right = paddle.layer.data(name="right",
                                  type=paddle.data_type.dense_vector(46))
        lab = paddle.layer.data(name="lab",
                                type=paddle.data_type.dense_vector(1))
        shared = paddle.layer.fc  # one scoring tower, shared weights
        sl = shared(input=left, size=1, param_attr="rank_w",
                    bias_attr="rank_b")
        sr = shared(input=right, size=1, param_attr="rank_w",
                    bias_attr="rank_b")
        cost = paddle.layer.rank_cost(left=sl, right=sr, label=lab)
        parameters = paddle.parameters.create(cost)
        trainer = paddle.SGD(
            cost=cost, parameters=parameters,
            update_equation=paddle.optimizer.Adam(learning_rate=1e-2))

        def reader():
            batch = []
            for i, (y, hi, lo) in enumerate(mq2007.train()()):
                if i >= 256:
                    break
                batch.append((hi, lo, [y]))
                if len(batch) == 32:
                    yield batch
                    batch = []

        costs = []
        trainer.train(
            reader, num_passes=3,
            event_handler=lambda e: costs.append(e.cost) if isinstance(
                e, paddle.event.EndIteration) else None,
            feeding={"left": 0, "right": 1, "lab": 2})
        assert costs[-1] < costs[0] * 0.9, (costs[0], costs[-1])
