"""Misc + LoD-array op tests (reference: test_assign_value_op.py,
test_fill_op.py, test_minus_op.py, test_modified_huber_loss_op.py,
test_l1_norm_op.py, test_lod_tensor_array_ops.py, test_split_and_merge_
lod_tensor_op.py, test_reorder_lod_tensor.py)."""

import os
import tempfile

import numpy as np

import paddle_tpu as fluid
from paddle_tpu import executor as executor_mod
from paddle_tpu.executor import LoDTensor
from op_test import OpTest

RNG = np.random.RandomState(11)


def make_lod(rows):
    flat = np.concatenate(rows, axis=0)
    offs = [0]
    for r in rows:
        offs.append(offs[-1] + len(r))
    return LoDTensor(flat, [offs])


class TestAssignValue(OpTest):
    op_type = "assign_value"

    def test(self):
        vals = RNG.rand(2, 3).astype("float32")
        self.inputs = {}
        self.attrs = {"shape": [2, 3], "dtype": "float32",
                      "fp32_values": vals.reshape(-1).tolist()}
        self.outputs = {"Out": vals}
        self.check_output()


class TestFill(OpTest):
    op_type = "fill"

    def test(self):
        vals = RNG.rand(6).astype("float32")
        self.inputs = {}
        self.attrs = {"shape": [2, 3], "dtype": "float32",
                      "value": vals.tolist()}
        self.outputs = {"Out": vals.reshape(2, 3)}
        self.check_output()


class TestMinus(OpTest):
    op_type = "minus"

    def test(self):
        x = RNG.rand(3, 4).astype("float32")
        y = RNG.rand(3, 4).astype("float32")
        self.inputs = {"X": x, "Y": y}
        self.outputs = {"Out": x - y}
        self.check_output()
        self.check_grad(["X", "Y"], "Out")


class TestModifiedHuberLoss(OpTest):
    op_type = "modified_huber_loss"

    def test(self):
        x = RNG.uniform(-2.5, 2.5, (8, 1)).astype("float32")
        y = RNG.randint(0, 2, (8, 1)).astype("float32")
        a = x * (2 * y - 1)
        # keep numeric grad away from the kinks at -1 and 1
        x[np.abs(np.abs(a) - 1) < 0.15] *= 1.4
        a = x * (2 * y - 1)
        loss = np.where(a < -1, -4 * a, np.where(a < 1, (1 - a) ** 2, 0))
        self.inputs = {"X": x, "Y": y}
        self.outputs = {"IntermediateVal": a, "Out": loss}
        self.check_output()
        self.check_grad(["X"], "Out", max_relative_error=0.02)


class TestL1Norm(OpTest):
    op_type = "l1_norm"

    def test(self):
        x = (RNG.rand(5, 3).astype("float32") - 0.5)
        x[np.abs(x) < 0.05] = 0.2
        self.inputs = {"X": x}
        self.outputs = {"Out": np.array([np.abs(x).sum()], "float32")}
        self.check_output()
        self.check_grad(["X"], "Out", max_relative_error=0.02)


class TestSaveLoadOps:
    def test_roundtrip(self):
        val = RNG.rand(3, 4).astype("float32")
        with tempfile.TemporaryDirectory() as d:
            path = os.path.join(d, "var0.save")
            main = fluid.Program()
            with fluid.program_guard(main, fluid.Program()):
                x = fluid.layers.data(name="x", shape=[3, 4], dtype="float32",
                                      append_batch_size=False)
                main.global_block().append_op(
                    type="save", inputs={"X": [x]}, outputs={},
                    attrs={"file_path": path})
                # a fetchable op so the program has an output
                out = fluid.layers.scale(x, scale=1.0)
            exe = fluid.Executor(fluid.CPUPlace())
            scope = executor_mod.Scope()
            with executor_mod.scope_guard(scope):
                exe.run(main, feed={"x": val}, fetch_list=[out])
            assert os.path.exists(path)

            main2 = fluid.Program()
            with fluid.program_guard(main2, fluid.Program()):
                y = main2.global_block().create_var(
                    name="y_loaded", shape=[3, 4], dtype="float32")
                main2.global_block().append_op(
                    type="load", inputs={}, outputs={"Out": [y]},
                    attrs={"file_path": path})
            with executor_mod.scope_guard(executor_mod.Scope()):
                got, = exe.run(main2, feed={}, fetch_list=[y])
            np.testing.assert_allclose(np.asarray(got), val)

    def test_combine_roundtrip(self):
        a = RNG.rand(2, 2).astype("float32")
        b = RNG.rand(4).astype("float32")
        with tempfile.TemporaryDirectory() as d:
            path = os.path.join(d, "combined")
            main = fluid.Program()
            with fluid.program_guard(main, fluid.Program()):
                va = fluid.layers.data(name="a", shape=[2, 2],
                                       dtype="float32",
                                       append_batch_size=False)
                vb = fluid.layers.data(name="b", shape=[4], dtype="float32",
                                       append_batch_size=False)
                main.global_block().append_op(
                    type="save_combine", inputs={"X": [va, vb]}, outputs={},
                    attrs={"file_path": path})
                out = fluid.layers.scale(va, scale=1.0)
            exe = fluid.Executor(fluid.CPUPlace())
            with executor_mod.scope_guard(executor_mod.Scope()):
                exe.run(main, feed={"a": a, "b": b}, fetch_list=[out])

            main2 = fluid.Program()
            with fluid.program_guard(main2, fluid.Program()):
                va2 = main2.global_block().create_var(
                    name="a", shape=[2, 2], dtype="float32")
                vb2 = main2.global_block().create_var(
                    name="b", shape=[4], dtype="float32")
                main2.global_block().append_op(
                    type="load_combine", inputs={},
                    outputs={"Out": [va2, vb2]},
                    attrs={"file_path": path})
            with executor_mod.scope_guard(executor_mod.Scope()):
                ga, gb = exe.run(main2, feed={}, fetch_list=[va2, vb2])
            np.testing.assert_allclose(np.asarray(ga), a)
            np.testing.assert_allclose(np.asarray(gb), b)


class TestLoDArrayRoundtrip:
    def test_to_array_and_back(self):
        rows = [RNG.randn(n, 3).astype(np.float32) for n in (2, 4, 1)]
        with fluid.program_guard(fluid.Program(), fluid.Program()):
            x = fluid.layers.data(name="x", shape=[3], dtype="float32",
                                  lod_level=1)
            table = fluid.layers.lod_rank_table(x)
            arr = fluid.layers.lod_tensor_to_array(x, table)
            back = fluid.layers.array_to_lod_tensor(arr, table)
            exe = fluid.Executor(fluid.CPUPlace())
            with executor_mod.scope_guard(executor_mod.Scope()):
                got, = exe.run(fluid.default_main_program(),
                               feed={"x": make_lod(rows)},
                               fetch_list=[back], return_numpy=False)
        lod = got.lod[0]
        arr_np = got.array()
        for i, r in enumerate(rows):
            np.testing.assert_allclose(arr_np[lod[i]:lod[i + 1]], r,
                                       rtol=1e-6)

    def test_max_sequence_len(self):
        rows = [RNG.randn(n, 2).astype(np.float32) for n in (3, 5, 2)]
        with fluid.program_guard(fluid.Program(), fluid.Program()):
            x = fluid.layers.data(name="x", shape=[2], dtype="float32",
                                  lod_level=1)
            table = fluid.layers.lod_rank_table(x)
            mlen = fluid.layers.max_sequence_len(table)
            exe = fluid.Executor(fluid.CPUPlace())
            with executor_mod.scope_guard(executor_mod.Scope()):
                got, = exe.run(fluid.default_main_program(),
                               feed={"x": make_lod(rows)},
                               fetch_list=[mlen])
        assert int(np.asarray(got).reshape(-1)[0]) == 5


class TestSplitMergeLoDTensor:
    def test_roundtrip(self):
        x_np = RNG.randn(5, 3).astype(np.float32)
        mask_np = np.array([[1], [0], [1], [1], [0]], "int32")
        with fluid.program_guard(fluid.Program(), fluid.Program()):
            x = fluid.layers.data(name="x", shape=[5, 3], dtype="float32",
                                  append_batch_size=False)
            m = fluid.layers.data(name="m", shape=[5, 1], dtype="int32",
                                  append_batch_size=False)
            t, f = fluid.layers.split_lod_tensor(x, m)
            merged = fluid.layers.merge_lod_tensor(t, f, x, m)
            exe = fluid.Executor(fluid.CPUPlace())
            with executor_mod.scope_guard(executor_mod.Scope()):
                tt, ff, mm = exe.run(fluid.default_main_program(),
                                     feed={"x": x_np, "m": mask_np},
                                     fetch_list=[t, f, merged])
        sel = mask_np.reshape(-1).astype(bool)
        np.testing.assert_allclose(np.asarray(tt)[sel], x_np[sel])
        np.testing.assert_allclose(np.asarray(ff)[~sel], x_np[~sel])
        assert (np.asarray(tt)[~sel] == 0).all()
        np.testing.assert_allclose(np.asarray(mm), x_np)


class TestReorderLoDTensorByRank:
    def test_reorder(self):
        rows = [RNG.randn(n, 2).astype(np.float32) for n in (2, 5, 3)]
        with fluid.program_guard(fluid.Program(), fluid.Program()):
            x = fluid.layers.data(name="x", shape=[2], dtype="float32",
                                  lod_level=1)
            table = fluid.layers.lod_rank_table(x)
            out = fluid.layers.reorder_lod_tensor_by_rank(x, table)
            exe = fluid.Executor(fluid.CPUPlace())
            with executor_mod.scope_guard(executor_mod.Scope()):
                got, = exe.run(fluid.default_main_program(),
                               feed={"x": make_lod(rows)},
                               fetch_list=[out], return_numpy=False)
        lod = got.lod[0]
        arr = got.array()
        # descending length order: rows[1] (5), rows[2] (3), rows[0] (2)
        want = [rows[1], rows[2], rows[0]]
        for i, w in enumerate(want):
            np.testing.assert_allclose(arr[lod[i]:lod[i + 1]], w, rtol=1e-6)


class TestWeightedAverage:
    def test_running_average(self):
        from paddle_tpu.average import WeightedAverage
        wa = WeightedAverage()
        wa.add(2.0, 1)
        wa.add(4.0, 3)
        assert abs(wa.eval() - (2 + 12) / 4) < 1e-9
        wa.reset()
        import pytest
        with pytest.raises(ValueError):
            wa.eval()
        with pytest.raises(ValueError):
            wa.add("x", 1)


class TestDetectionMAPEvaluator:
    def test_accumulates_across_batches(self):
        import numpy as np
        from paddle_tpu.metrics import DetectionMAP
        ev = DetectionMAP(overlap_threshold=0.5, ap_version="integral")
        # batch 1: one perfect detection of the single gt
        det1 = np.array([[[1, 0.9, 0.1, 0.1, 0.4, 0.4]]], np.float32)
        gt1 = np.array([[[1, 0, 0.1, 0.1, 0.4, 0.4]]], np.float32)
        ev.update(det1, [1], gt1, [1])
        m1 = ev.eval()
        assert m1 > 0.99, m1
        # batch 2: a miss (wrong place) for a second gt lowers the mAP
        det2 = np.array([[[1, 0.8, 0.6, 0.6, 0.9, 0.9]]], np.float32)
        gt2 = np.array([[[1, 0, 0.1, 0.1, 0.4, 0.4]]], np.float32)
        ev.update(det2, [1], gt2, [1])
        m2 = ev.eval()
        assert m2 < m1, (m1, m2)
        ev.reset()
        import pytest
        with pytest.raises(ValueError):
            ev.eval()


class TestNewDatasets:
    def test_flowers_schema(self):
        import numpy as np
        from paddle_tpu.dataset import flowers
        it = flowers.train()()
        img, label = next(it)
        assert img.shape == (3 * 224 * 224,) and img.dtype == np.float32
        assert 0 <= label < flowers.CLASS_NUM

    def test_voc2012_schema(self):
        import numpy as np
        from paddle_tpu.dataset import voc2012
        img, mask = next(voc2012.train()())
        assert img.shape[0] == 3 and img.ndim == 3
        assert mask.shape == img.shape[1:]
        assert mask.max() < voc2012.CLASS_NUM

    def test_wmt16_schema(self):
        from paddle_tpu.dataset import wmt16
        src, trg, nxt = next(wmt16.train(100, 120)())
        assert trg[0] == 0 and nxt[-1] == 1
        assert len(trg) == len(nxt)
        assert wmt16.get_dict("en", 50)["<e>"] == 1

    def test_sentiment_schema(self):
        from paddle_tpu.dataset import sentiment
        words, label = next(sentiment.train()())
        assert label in (0, 1)
        assert all(isinstance(w, int) for w in words)


class TestUtilsParity:
    def test_flag_registry(self):
        from paddle_tpu import flags
        d = flags.dump()
        assert "check_nan_inf" in d and "benchmark" in d
        assert flags.get("max_loop_iters") == 128
        import os
        os.environ["PADDLE_TPU_VLOG"] = "3"
        try:
            assert flags.get("vlog") == 3
        finally:
            del os.environ["PADDLE_TPU_VLOG"]

    def test_enforce_not_met_carries_context(self):
        import pytest
        import paddle_tpu as fluid
        from paddle_tpu.errors import EnforceNotMet
        x = fluid.layers.data(name="x", shape=[4], dtype="float32")
        y = fluid.layers.data(name="y", shape=[5], dtype="float32")
        bad = fluid.layers.elementwise_add(x, y)   # shape mismatch at run
        exe = fluid.Executor(fluid.CPUPlace())
        exe.run(fluid.default_startup_program())
        with pytest.raises(EnforceNotMet) as ei:
            exe.run(feed={"x": np.ones((2, 4), np.float32),
                          "y": np.ones((2, 5), np.float32)},
                    fetch_list=[bad], use_jit=False)
        assert ei.value.op_type == "elementwise_add"
        # creation site points at THIS test file, not framework internals
        assert ei.value.creation_site and \
            "test_misc_ops.py" in ei.value.creation_site

    def test_benchmark_sync_mode_logs(self):
        import subprocess, sys as _sys, os as _os
        repo = _os.path.dirname(_os.path.dirname(_os.path.abspath(__file__)))
        script = (
            "import numpy as np\n"
            "import paddle_tpu as fluid\n"
            "x = fluid.layers.data(name='x', shape=[4], dtype='float32')\n"
            "y = fluid.layers.scale(x, scale=2.0)\n"
            "exe = fluid.Executor(fluid.CPUPlace())\n"
            "exe.run(fluid.default_startup_program())\n"
            "r, = exe.run(feed={'x': np.ones((2, 4), np.float32)},"
            " fetch_list=[y], use_jit=False)\n"
            "print('ok', float(r.sum()))\n")
        env = dict(_os.environ, PYTHONPATH=repo, JAX_PLATFORMS="cpu",
                   PADDLE_TPU_EAGER="1", PADDLE_TPU_BENCHMARK="1",
                   PADDLE_TPU_VLOG="1")
        r = subprocess.run([_sys.executable, "-c", script],
                           capture_output=True, text=True, env=env,
                           timeout=300)
        assert r.returncode == 0, r.stderr[-800:]
        assert "[benchmark] scale" in r.stderr, r.stderr[-800:]
