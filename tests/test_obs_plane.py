"""Live observability plane (ISSUE 16): causal span tracing, the
scrapeable HTTP endpoint, and SLO burn-rate monitoring.

The acceptance properties pinned here: a serving request traced through
submit -> coalesce -> engine yields a span tree whose
queue+pad+compute+scatter children tile the parent (sum within 10%),
exportable as valid chrome-trace JSON; /metrics, /healthz and /spans
answer over real HTTP (http.client against the in-process server) while
a workload runs; /healthz flips to 503 when steps stall and when a crash
event lands; and the SLO monitor's fast/slow windows burn past 1.0
exactly when the error budget is being overspent.
"""

import http.client
import json
import time

import numpy as np
import pytest

import paddle_tpu as fluid
from paddle_tpu import executor as executor_mod
from paddle_tpu import obs_server, telemetry, tracing
from paddle_tpu.serving import DynamicBatcher, ServingEngine
from paddle_tpu.serving import slo as slo_mod


@pytest.fixture(autouse=True)
def _fresh_obs_state():
    telemetry.reset()
    tracing.reset()
    slo_mod.reset()
    yield
    obs_server.stop()
    telemetry.reset()
    tracing.reset()
    slo_mod.reset()


def _get(port, route):
    conn = http.client.HTTPConnection("127.0.0.1", port, timeout=10)
    try:
        conn.request("GET", route)
        resp = conn.getresponse()
        return resp.status, resp.read()
    finally:
        conn.close()


def _get_json(port, route):
    status, body = _get(port, route)
    return status, json.loads(body)


def _build_fc_engine(scope, max_batch=8):
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        x = fluid.layers.data(name="x", shape=[16], dtype="float32")
        h = fluid.layers.fc(input=x, size=32, act="relu")
        logits = fluid.layers.fc(input=h, size=4)
    exe = fluid.Executor(fluid.CPUPlace())
    with executor_mod.scope_guard(scope):
        exe.run(startup)
    return ServingEngine(main, feed_names=["x"],
                         fetch_names=[logits.name], scope=scope,
                         max_batch=max_batch)


# --- tracing core ------------------------------------------------------------

def test_span_context_nesting_and_parent_links():
    tracing.enable()
    with tracing.span("outer", program="p0") as outer:
        with tracing.span("inner") as inner:
            assert tracing.current_span() is inner
        assert tracing.current_span() is outer
    spans = {s["name"]: s for s in tracing.recent_spans()}
    assert spans["inner"]["parent_id"] == spans["outer"]["span_id"]
    assert spans["inner"]["trace_id"] == spans["outer"]["trace_id"]
    assert spans["outer"]["parent_id"] is None
    assert spans["outer"]["attrs"]["program"] == "p0"
    assert spans["outer"]["end"] >= spans["inner"]["end"]


def test_tracing_disabled_is_noop():
    assert not tracing.enabled()
    with tracing.span("nope") as sp:
        sp.set_attr("k", "v").add_event("e")
    assert tracing.recent_spans() == []
    assert tracing.start_span("also_nope").sampled is False


def test_record_span_retroactive_and_tree():
    tracing.enable()
    t0 = time.monotonic()
    root = tracing.record_span("step", t0, t0 + 0.5,
                               attrs={"program": "p0"})
    tracing.record_span("compile", t0, t0 + 0.3, parent=root)
    roots = tracing.trace_tree(root.trace_id)
    assert len(roots) == 1
    assert roots[0]["name"] == "step"
    kids = roots[0]["children"]
    assert [k["name"] for k in kids] == ["compile"]
    assert abs(roots[0]["dur_s"] - 0.5) < 1e-9
    assert abs(kids[0]["dur_s"] - 0.3) < 1e-9


def test_head_sampling_is_deterministic_and_whole_trace():
    tracing.enable(sample=0.25)
    kept = 0
    for _ in range(16):
        root = tracing.start_span("req")
        child = tracing.start_span("phase", parent=root)
        child.end()
        root.end()
        kept += root.sampled
        # the keep/drop decision is inherited: never a partial tree
        assert child.sampled == root.sampled
    assert kept == 4
    assert len(tracing.recent_spans(name="req")) == 4


def test_ring_buffer_bounded_with_drop_counter():
    tracing.enable(capacity=10)
    t0 = time.monotonic()
    for i in range(25):
        tracing.record_span(f"s{i}", t0, t0 + 0.001)
    spans = tracing.recent_spans()
    assert len(spans) == 10
    assert spans[-1]["name"] == "s24"   # newest survives
    dropped = telemetry.read_series("trace_spans_dropped_total")
    assert sum(dropped.values()) == 15


def test_jsonl_export(tmp_path):
    tracing.enable()
    t0 = time.monotonic()
    tracing.record_span("a", t0, t0 + 0.1)
    tracing.record_span("b", t0, t0 + 0.2)
    path = tmp_path / "spans.jsonl"
    assert tracing.export_jsonl(str(path)) == 2
    lines = [json.loads(l) for l in path.read_text().splitlines()]
    assert [l["name"] for l in lines] == ["a", "b"]


def test_env_enable_sampling(monkeypatch):
    monkeypatch.setenv("PADDLE_TPU_TRACE", "0.5")
    tracing.maybe_enable_from_env()
    assert tracing.enabled()
    tracing.reset()
    monkeypatch.setenv("PADDLE_TPU_TRACE", "0")
    tracing.maybe_enable_from_env()
    assert not tracing.enabled()


# --- serving request span tree (acceptance) ----------------------------------

def test_serving_span_tree_children_sum_to_parent(tmp_path):
    """A traced request's queue+pad+compute+scatter children must account
    for the parent within 10%, and the ring must export as loadable
    chrome-trace JSON (acceptance criterion)."""
    scope = executor_mod.Scope()
    eng = _build_fc_engine(scope)
    rng = np.random.RandomState(0)
    # warm every bucket the test could hit OUTSIDE tracing, so compile
    # time doesn't dominate bucket_select
    for n in (1, 2, 4, 8):
        eng.run_batch({"x": rng.randn(n, 16).astype(np.float32)})
    tracing.enable()
    with DynamicBatcher(eng, max_delay_ms=2.0) as batcher:
        futs = [batcher.submit(
                    {"x": rng.randn(2, 16).astype(np.float32)})
                for _ in range(4)]
        for f in futs:
            f.result(timeout=30.0)
    roots = tracing.recent_spans(name="serving_request")
    assert len(roots) == 4
    for root in roots:
        assert root["attrs"]["outcome"] == "ok"
        tree = tracing.trace_tree(root["trace_id"])
        assert len(tree) == 1
        kids = tree[0]["children"]
        names = [k["name"] for k in kids]
        for want in ("queue", "pad", "bucket_select", "compute",
                     "scatter"):
            assert want in names, f"missing child {want} in {names}"
        parent_dur = tree[0]["dur_s"]
        core = sum(k["dur_s"] for k in kids
                   if k["name"] in ("queue", "pad", "compute",
                                    "scatter"))
        every = sum(k["dur_s"] for k in kids)
        assert parent_dur > 0
        # all children tile the parent; the named four are within 10%
        assert abs(every - parent_dur) <= 0.10 * parent_dur + 1e-4
        assert core >= 0.90 * parent_dur - 1e-4
        assert core <= parent_dur + 1e-4

    out = tmp_path / "trace.json"
    n_events = tracing.export_chrome_trace(str(out))
    doc = json.loads(out.read_text())
    assert isinstance(doc["traceEvents"], list)
    assert n_events == len(doc["traceEvents"])
    xs = [e for e in doc["traceEvents"] if e["ph"] == "X"]
    assert len(xs) == len(tracing.recent_spans())
    for e in xs:
        assert e["dur"] >= 0 and "name" in e and "ts" in e


def test_serving_shed_requests_end_spans():
    """Queue-full rejections happen before a span exists; deadline sheds
    end the request span with outcome=shed."""
    scope = executor_mod.Scope()
    eng = _build_fc_engine(scope)
    rng = np.random.RandomState(1)
    eng.run_batch({"x": rng.randn(4, 16).astype(np.float32)})
    tracing.enable()
    batcher = DynamicBatcher(eng, max_delay_ms=1.0)  # never started
    fut = batcher.submit({"x": rng.randn(2, 16).astype(np.float32)},
                         deadline_ms=0.0)
    time.sleep(0.01)
    batcher.start()
    with pytest.raises(Exception):
        fut.result(timeout=30.0)
    batcher.stop()
    shed = [s for s in tracing.recent_spans(name="serving_request")
            if s["attrs"].get("outcome") == "shed"]
    assert len(shed) == 1
    assert shed[0]["attrs"]["reason"] == "deadline"


# --- training step spans -----------------------------------------------------

def test_executor_step_spans():
    tracing.enable()
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        x = fluid.layers.data(name="x", shape=[4], dtype="float32")
        y = fluid.layers.data(name="y", shape=[1], dtype="float32")
        pred = fluid.layers.fc(input=x, size=1)
        loss = fluid.layers.mean(
            fluid.layers.square_error_cost(input=pred, label=y))
        fluid.optimizer.SGD(learning_rate=0.1).minimize(
            loss, startup_program=startup)
    scope = executor_mod.Scope()
    exe = fluid.Executor(fluid.CPUPlace())
    rng = np.random.RandomState(0)
    feed = {"x": rng.randn(4, 4).astype(np.float32),
            "y": rng.randn(4, 1).astype(np.float32)}
    with executor_mod.scope_guard(scope):
        exe.run(startup)
        for _ in range(3):
            exe.run(main, feed=feed, fetch_list=[loss])
    steps = tracing.recent_spans(name="step")
    assert len(steps) >= 3
    assert all(s["attrs"]["program"] for s in steps)
    # the first (compiling) step carries a compile child
    compiles = tracing.recent_spans(name="compile")
    assert compiles, "no compile child recorded for the cold step"
    step_ids = {s["span_id"] for s in steps}
    assert all(c["parent_id"] in step_ids for c in compiles)
    for c in compiles:
        parent = next(s for s in steps
                      if s["span_id"] == c["parent_id"])
        assert c["dur_s"] <= parent["dur_s"] + 1e-9


def test_checkpoint_spans(tmp_path):
    tracing.enable()
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        x = fluid.layers.data(name="x", shape=[4], dtype="float32")
        fluid.layers.fc(input=x, size=2)
    scope = executor_mod.Scope()
    exe = fluid.Executor(fluid.CPUPlace())
    with executor_mod.scope_guard(scope):
        exe.run(startup)
        fluid.io.save_params(exe, str(tmp_path), main)
        fluid.io.load_params(exe, str(tmp_path), main)
    assert len(tracing.recent_spans(name="checkpoint_save")) == 1
    assert len(tracing.recent_spans(name="checkpoint_load")) == 1
    save = tracing.recent_spans(name="checkpoint_save")[0]
    assert save["attrs"]["bytes"] > 0


# --- SLO burn rate -----------------------------------------------------------

def test_slo_burn_rate_windows():
    clock = [1000.0]
    mon = slo_mod.SLOMonitor(
        slo_mod.SLO("m0", availability=0.999),
        clock=lambda: clock[0])
    for _ in range(995):
        mon.record(ok=True)
    assert mon.burn_rate(slo_mod.FAST_WINDOW_S) == 0.0
    for _ in range(5):
        mon.record(ok=False)
    # 5/1000 bad against a 0.001 budget: burning 5x
    rep = mon.report()
    assert rep["windows"]["fast"]["burn_rate"] == pytest.approx(5.0)
    assert rep["windows"]["slow"]["burn_rate"] == pytest.approx(5.0)
    assert telemetry.read_gauge("slo_burn_rate", model="m0",
                                window="fast") == pytest.approx(5.0)
    # fast window forgets the incident, slow window still remembers
    clock[0] += slo_mod.FAST_WINDOW_S + 1
    rep = mon.report()
    assert rep["windows"]["fast"]["burn_rate"] == 0.0
    assert rep["windows"]["slow"]["burn_rate"] == pytest.approx(5.0)
    # and the slow window ages out too
    clock[0] += slo_mod.SLOW_WINDOW_S
    rep = mon.report()
    assert rep["windows"]["slow"]["burn_rate"] == 0.0
    assert rep["windows"]["slow"]["total"] == 0


def test_slo_latency_objective_counts_slow_success_as_bad():
    mon = slo_mod.SLOMonitor(
        slo_mod.SLO("m1", availability=0.9, latency_ms=50.0))
    mon.record(ok=True, latency_s=0.01)
    mon.record(ok=True, latency_s=0.2)   # completed but too slow
    rep = mon.report()
    assert rep["windows"]["fast"]["bad"] == 1
    assert rep["windows"]["fast"]["burn_rate"] == pytest.approx(5.0)


def test_slo_registry_shared_per_model():
    a = slo_mod.monitor_for("modelA")
    assert slo_mod.monitor_for("modelA") is a
    a.record(ok=False)
    reports = slo_mod.all_reports()
    assert "modelA" in reports
    assert reports["modelA"]["windows"]["fast"]["bad"] == 1


def test_batcher_stats_carry_slo():
    scope = executor_mod.Scope()
    eng = _build_fc_engine(scope)
    rng = np.random.RandomState(2)
    with DynamicBatcher(eng, max_delay_ms=2.0) as batcher:
        fut = batcher.submit(
            {"x": rng.randn(2, 16).astype(np.float32)})
        fut.result(timeout=30.0)
        stats = batcher.stats()
    assert stats["slo"]["windows"]["fast"]["total"] == 1
    assert stats["slo"]["windows"]["fast"]["burn_rate"] == 0.0
    assert stats["slo"]["objective"]["availability"] == 0.999


# --- HTTP endpoints ----------------------------------------------------------

def test_obs_endpoints_serve_live_data():
    srv = obs_server.start(port=0)
    assert srv.port
    tracing.enable()
    telemetry.counter("input_batches_total",
                      "reader batches produced").inc(3)
    t0 = time.monotonic()
    tracing.record_span("step", t0, t0 + 0.01,
                        attrs={"program": "p0"})

    status, body = _get(srv.port, "/metrics")
    assert status == 200
    text = body.decode()
    assert "# TYPE input_batches_total counter" in text
    assert "input_batches_total 3" in text
    assert "obs_requests_total" in text   # the scrape counts itself

    status, spans = _get_json(srv.port, "/spans?n=5")
    assert status == 200
    assert spans["enabled"] is True
    assert [s["name"] for s in spans["spans"]] == ["step"]

    status, report = _get_json(srv.port, "/report")
    assert status == 200
    assert report["spans_buffered"] == 1
    assert report["metrics_families"] >= 1

    status, index = _get_json(srv.port, "/")
    assert status == 200
    assert "/metrics" in index["endpoints"]

    status, _err = _get_json(srv.port, "/nope")
    assert status == 404


def test_healthz_verdicts_and_stall_flip():
    srv = obs_server.start(port=0)
    # never stepped: healthy (a pure serving process is not stalled)
    status, rep = _get_json(srv.port, "/healthz")
    assert status == 200
    assert rep["checks"]["step"]["ran"] is False

    telemetry.log_event("run", program="p0", seconds=0.01)
    telemetry.gauge(
        "executor_last_step_seconds",
        "wall seconds of the most recent executor step").set(0.01)
    status, rep = _get_json(srv.port, "/healthz?max_age=60")
    assert status == 200 and rep["status"] == "ok"
    assert rep["checks"]["step"]["stalled"] is False

    # steps stall: the same scrape with a tight staleness threshold
    # flips to 503 (acceptance criterion)
    time.sleep(0.05)
    status, rep = _get_json(srv.port, "/healthz?max_age=0.01")
    assert status == 503
    assert rep["status"] == "unhealthy"
    assert rep["checks"]["step"]["stalled"] is True


def test_healthz_crash_and_slo_degraded():
    srv = obs_server.start(port=0)
    # SLO burning fast -> degraded but still 200 (alert, not dead)
    mon = slo_mod.monitor_for("m9")
    for _ in range(10):
        mon.record(ok=False)
    status, rep = _get_json(srv.port, "/healthz")
    assert status == 200
    assert rep["status"] == "degraded"
    assert rep["checks"]["slo"]["burn_rates"]["m9"]["fast"] > 1.0

    # a crash event is a hard unhealthy
    telemetry.log_event("crash", error="RuntimeError: boom",
                        program="p0")
    status, rep = _get_json(srv.port, "/healthz")
    assert status == 503
    assert rep["checks"]["last_error"]["error"] \
        == "RuntimeError: boom"


def test_crash_hook_logs_event():
    """inspector.notify_crash feeds the event /healthz reads."""
    from paddle_tpu import inspector
    main = fluid.Program()
    inspector.notify_crash(None, main, RuntimeError("kaput"))
    evs = telemetry.recent_events(kind="crash")
    assert len(evs) == 1
    assert "kaput" in evs[0]["error"]


def test_obs_cli_subcommand(tmp_path, capsys):
    """`python -m paddle_tpu obs` end-to-end in-process: server up,
    traced smoke steps, self-scrape over HTTP, chrome-trace export."""
    from paddle_tpu import cli
    out = tmp_path / "trace.json"
    rc = cli.main(["obs", "--steps", "2", "--batch", "4",
                   "--export-trace", str(out)])
    assert rc == 0
    line = capsys.readouterr().out.strip().splitlines()[-1]
    summary = json.loads(line)
    assert summary["metrics"]["status"] == 200
    assert summary["metrics"]["bytes"] > 0
    assert summary["healthz"]["checks"]["step"]["ran"] is True
    assert summary["spans"]["buffered"] > 0
    doc = json.loads(out.read_text())
    assert any(e.get("name") == "step" for e in doc["traceEvents"])


def test_env_port_autostart(monkeypatch):
    monkeypatch.setenv("PADDLE_TPU_OBS_PORT", "0")
    srv = obs_server.maybe_start_from_env()
    assert srv is not None and srv.port
    status, _ = _get(srv.port, "/metrics")
    assert status == 200
