"""Child process for test_telemetry's multihost-reduce test: a real
2-process jax.distributed bring-up (same harness as _distributed_worker.py)
where each process populates distinct metric values and asserts that
telemetry.snapshot(reduce=True) returns the fleet-wide sums on BOTH sides.

Run as:  python _telemetry_worker.py <coordinator> <nprocs> <pid>

Prints one line `RESULT <json>` on success."""

import json
import os
import sys


def main(coordinator, nprocs, pid):
    os.environ["JAX_PLATFORMS"] = "cpu"
    os.environ.setdefault("XLA_FLAGS", "")
    import jax

    jax.config.update("jax_platforms", "cpu")

    from paddle_tpu import telemetry
    from paddle_tpu.parallel import multihost

    assert multihost.initialize(coordinator_address=coordinator,
                                num_processes=nprocs, process_id=pid)
    assert jax.process_count() == nprocs

    # initialize() exported the process id, so snapshots label correctly
    assert telemetry._host_index() == pid

    # distinct per-process contributions: counter pid+1, one gauge each,
    # one histogram observation each
    telemetry.counter("tw_steps_total", labels=("role",)) \
        .labels(role="trainer").inc(pid + 1)
    telemetry.gauge("tw_queue_depth").set(10.0 * (pid + 1))
    telemetry.histogram("tw_lat_seconds").observe(0.001 * (pid + 1))

    local = telemetry.snapshot()
    assert local["counters"]["tw_steps_total"]["role=trainer"] == pid + 1

    fleet = telemetry.snapshot(reduce=True)
    want_counter = sum(range(1, nprocs + 1))          # 1+2+...+n
    got_counter = fleet["counters"]["tw_steps_total"]["role=trainer"]
    assert got_counter == want_counter, (got_counter, want_counter)
    want_gauge = 10.0 * want_counter
    got_gauge = fleet["gauges"]["tw_queue_depth"][""]
    assert got_gauge == want_gauge, (got_gauge, want_gauge)
    h = fleet["histograms"]["tw_lat_seconds"][""]
    assert h["count"] == nprocs, h
    assert abs(h["sum"] - 0.001 * want_counter) < 1e-9, h
    assert fleet["hosts"] == nprocs, fleet

    # the fleet snapshot renders through the same exporter
    text = telemetry.prometheus_text(fleet)
    assert f'tw_steps_total{{role="trainer"}} {want_counter}' in text, text

    print(f"RESULT {json.dumps({'pid': pid, 'counter': got_counter})}",
          flush=True)


if __name__ == "__main__":
    main(sys.argv[1], int(sys.argv[2]), int(sys.argv[3]))
