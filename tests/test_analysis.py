"""Whole-program static verifier (ISSUE 12): every analyzer pass
against hand-built broken programs, zero error-severity diagnostics
over each shipped example, the PADDLE_TPU_VERIFY executor hook, the
`python -m paddle_tpu analyze` CLI, and the desc attr JSON round-trip
(tuples must survive with type intact — the analyzer clones descs and
op lowerings compare attrs with `== (0, 1)`)."""

import importlib.util
import json
import os
import subprocess
import sys
from types import SimpleNamespace

import numpy as np
import pytest

import paddle_tpu as fluid
from paddle_tpu import executor as executor_mod
from paddle_tpu.analysis import analyze_program

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

RNG = np.random.RandomState(7)


def _by_code(report, code):
    return [d for d in report.diagnostics if d.code == code]


def _fit_a_line():
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        x = fluid.layers.data(name="x", shape=[13])
        y = fluid.layers.data(name="y", shape=[1])
        pred = fluid.layers.fc(input=x, size=1)
        loss = fluid.layers.mean(
            fluid.layers.square_error_cost(input=pred, label=y))
        fluid.optimizer.SGD(learning_rate=0.01).minimize(
            loss, startup_program=startup)
    return main, startup, loss


# ---------------------------------------------------------------------------
# shapes pass
# ---------------------------------------------------------------------------

class TestShapesPass:
    def test_clean_program_has_no_errors(self):
        main, _, loss = _fit_a_line()
        report = analyze_program(main, feeds=["x", "y"],
                                 fetches=[loss.name])
        assert report.ok, report.format(show_info=True)

    def test_rank_mismatch_cites_op_and_site(self):
        main, _, loss = _fit_a_line()
        # corrupt the feed declaration after build: rank 2 -> rank 1
        main.global_block().desc.var("x").shape = [-1]
        report = analyze_program(main, feeds=["x", "y"],
                                 fetches=[loss.name])
        errs = _by_code(report, "rank-mismatch")
        assert errs, report.format(show_info=True)
        d = errs[0]
        assert d.op_index is not None and d.op_type == "mul"
        # creation_site points back at this test file's fc() call
        assert d.site and "test_analysis.py" in d.site

    def test_unregistered_op_is_an_error(self):
        from paddle_tpu.framework.desc import OpDesc

        main = fluid.Program()
        with fluid.program_guard(main, fluid.Program()):
            fluid.layers.data(name="x", shape=[4])
            b = main.global_block()
            b.create_var(name="o", shape=[-1, 4], dtype="float32")
            # append_op refuses unregistered types, so plant it in the
            # desc directly and rebuild the Operator wrappers
            b.desc.ops.append(OpDesc(
                type="definitely_not_an_op",
                inputs={"X": ["x"]}, outputs={"Out": ["o"]}))
            b._sync_ops()
        report = analyze_program(main, feeds=["x"], fetches=["o"])
        assert _by_code(report, "unregistered-op"), \
            report.format(show_info=True)


# ---------------------------------------------------------------------------
# dataflow pass
# ---------------------------------------------------------------------------

class TestDataflowPass:
    def test_use_before_def(self):
        main = fluid.Program()
        with fluid.program_guard(main, fluid.Program()):
            fluid.layers.data(name="x", shape=[4])
            b = main.global_block()
            b.create_var(name="t", shape=[-1, 4], dtype="float32")
            b.create_var(name="o", shape=[-1, 4], dtype="float32")
            # consumer appended before its producer
            b.append_op(type="scale", inputs={"X": ["t"]},
                        outputs={"Out": ["o"]}, attrs={"scale": 2.0})
            b.append_op(type="scale", inputs={"X": ["x"]},
                        outputs={"Out": ["t"]}, attrs={"scale": 1.0})
        report = analyze_program(main, feeds=["x"], fetches=["o"])
        errs = _by_code(report, "use-before-def")
        assert errs and errs[0].op_index == 0 and errs[0].var == "t"
        assert "reorder" in (errs[0].hint or "")

    def test_dead_op(self):
        main = fluid.Program()
        with fluid.program_guard(main, fluid.Program()):
            x = fluid.layers.data(name="x", shape=[4])
            kept = fluid.layers.scale(x, scale=2.0)
            fluid.layers.scale(x, scale=3.0)  # never fetched
        report = analyze_program(main, feeds=["x"], fetches=[kept.name])
        dead = _by_code(report, "dead-op")
        assert dead and "prune" in (dead[0].hint or "")
        assert dead[0].op_index is not None

    def test_donated_and_fetched(self):
        main, _, loss = _fit_a_line()
        params = [n for n, v in
                  main.global_block().desc.vars.items()
                  if v.persistable and n.endswith(".w_0")]
        assert params, "expected an fc weight param"
        report = analyze_program(main, feeds=["x", "y"],
                                 fetches=[loss.name, params[0]])
        hits = _by_code(report, "donated-fetch")
        assert hits and hits[0].var == params[0]

    def test_param_grad_pairing_breaks_on_desc_edit(self):
        main, _, loss = _fit_a_line()
        pairs = getattr(main, "_grad_param_pairs", [])
        dense = [g for _, g in pairs if g.endswith(".w_0@GRAD")]
        assert dense, pairs
        main.global_block().desc.var(dense[0]).shape = [3, 3, 3]
        report = analyze_program(main, feeds=["x", "y"],
                                 fetches=[loss.name])
        assert _by_code(report, "param-grad-shape"), \
            report.format(show_info=True)


# ---------------------------------------------------------------------------
# preflight pass
# ---------------------------------------------------------------------------

class TestPreflightPass:
    def test_sharding_indivisible(self):
        main = fluid.Program()
        b = main.global_block()
        b.create_var(name="w", shape=[10, 6], dtype="float32",
                     persistable=True)
        main._param_shardings = {"w": (None, "mp")}
        main._mesh = SimpleNamespace(shape={"mp": 4}, axis_names=("mp",))
        report = analyze_program(main, feeds=[], fetches=[])
        errs = _by_code(report, "sharding-indivisible")
        assert errs and errs[0].var == "w"
        assert "pad the dim to 8" in (errs[0].hint or "")

    def test_sharding_unknown_axis(self):
        main = fluid.Program()
        main.global_block().create_var(
            name="w", shape=[8, 8], dtype="float32", persistable=True)
        main._param_shardings = {"w": ("tp", None)}
        main._mesh = SimpleNamespace(shape={"mp": 4}, axis_names=("mp",))
        report = analyze_program(main, feeds=[], fetches=[])
        assert _by_code(report, "sharding-unknown-axis")

    def test_conv_channel_miss_gets_pallas_hint(self):
        main = fluid.Program()
        with fluid.program_guard(main, fluid.Program()):
            img = fluid.layers.data(name="img", shape=[64, 16, 16])
            out = fluid.layers.conv2d(input=img, num_filters=128,
                                      filter_size=3, padding=1)
        main._amp_dtype = "bfloat16"  # bf16 datapath: dtype gate passes
        report = analyze_program(main, feeds=["img"], fetches=[out.name])
        warns = _by_code(report, "pallas-conv-fallback")
        assert warns, report.format(show_info=True)
        assert not report.errors  # a fast-path miss is advisory, not fatal
        d = warns[0]
        assert d.op_index is not None
        assert "multiple of 128" in (d.hint or "") and "Ci=64" in d.hint

    def test_quant_preflight_flags_shallow_matmul(self):
        """ISSUE 20 satellite: planted defect — a K=24 fc under O3
        fails the shape gate (K < 32), and the preflight quant pass
        says so before compile by dry-running quant.gate_for_op on the
        desc avals; the K=64 layer downstream passes and stays quiet."""
        main = fluid.Program()
        with fluid.program_guard(main, fluid.Program()):
            x = fluid.layers.data(name="x", shape=[24], dtype="float32")
            h = fluid.layers.fc(input=x, size=64, act="relu")
            out = fluid.layers.fc(input=h, size=64)
        main._amp_dtype = "bfloat16"
        main._amp_level = "O3"
        main._quant_mode = "int8"
        report = analyze_program(main, feeds=["x"], fetches=[out.name])
        warns = _by_code(report, "quant-fallback")
        assert len(warns) == 1, report.format(show_info=True)
        assert not report.errors  # advisory, not fatal
        d = warns[0]
        assert "reason: shape" in d.message and d.op_index is not None
        assert "K=24" in (d.hint or "")

    def test_quant_preflight_silent_below_o3(self):
        """The same shallow matmul without _quant_mode emits nothing:
        an O1/O2 program falling back everywhere is configuration, not
        a diagnosis."""
        main = fluid.Program()
        with fluid.program_guard(main, fluid.Program()):
            x = fluid.layers.data(name="x", shape=[24], dtype="float32")
            out = fluid.layers.fc(input=x, size=64)
        main._amp_dtype = "bfloat16"
        report = analyze_program(main, feeds=["x"], fetches=[out.name])
        assert not _by_code(report, "quant-fallback")

    def test_emb_cache_thrash_warning(self):
        """ISSUE 14 satellite: a cache_rows request below the static
        per-step touched-row bound (batch x slots ids can all be
        distinct) warns BEFORE any step runs — at runtime that config
        evicts rows staged the same step, and a fused window can fail
        outright on the union-must-fit check."""
        def prog(cache_rows):
            main = fluid.Program()
            with fluid.program_guard(main, fluid.Program()):
                with fluid.unique_name.guard():
                    ids = fluid.layers.data(name="ids", shape=[26],
                                            dtype="int64")
                    fluid.layers.embedding(
                        ids, size=[1000, 8], is_sparse=True,
                        param_attr=fluid.ParamAttr(name="emb_w"),
                        cache_rows=cache_rows)
            return main

        # bound = _PROBE_BATCH(8) x 26 slots = 208 > 64 -> warn
        report = analyze_program(prog(64), feeds=["ids"], fetches=[])
        warns = _by_code(report, "emb-cache-thrash")
        assert warns and warns[0].var == "emb_w"
        assert not report.errors       # advisory: sizing, not soundness
        assert "208" in warns[0].message
        assert "cache_rows" in (warns[0].hint or "")
        # a bound-covering cache_rows is silent
        report = analyze_program(prog(256), feeds=["ids"], fetches=[])
        assert not _by_code(report, "emb-cache-thrash")


# ---------------------------------------------------------------------------
# shipped examples: the acceptance bar is zero error-severity findings
# ---------------------------------------------------------------------------

def _load_example(name):
    path = os.path.join(REPO, "examples", "fluid", f"train_{name}.py")
    spec = importlib.util.spec_from_file_location(f"_ex_{name}", path)
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


@pytest.mark.parametrize("name", [
    "fit_a_line", "criteo_dlrm", "transformer_long_context"])
def test_examples_analyze_clean(name):
    built = _load_example(name).build_programs()
    report = analyze_program(built["main"], feeds=built["feeds"],
                             fetches=built["fetches"])
    assert not report.errors, report.format(show_info=True)
    startup_report = analyze_program(built["startup"], feeds=[],
                                     fetches=[])
    assert not startup_report.errors, \
        startup_report.format(show_info=True)


# ---------------------------------------------------------------------------
# PADDLE_TPU_VERIFY executor hook
# ---------------------------------------------------------------------------

class TestVerifyMode:
    def test_clean_program_still_runs(self, monkeypatch):
        monkeypatch.setattr(executor_mod, "_VERIFY", True)
        main, startup, loss = _fit_a_line()
        exe = fluid.Executor(fluid.CPUPlace())
        with executor_mod.scope_guard(executor_mod.Scope()):
            exe.run(startup)
            out, = exe.run(main,
                           feed={"x": RNG.rand(4, 13).astype("float32"),
                                 "y": RNG.rand(4, 1).astype("float32")},
                           fetch_list=[loss])
        assert np.isfinite(float(np.ravel(out)[0]))

    def test_broken_program_raises_before_compile(self, monkeypatch):
        from paddle_tpu import errors

        monkeypatch.setattr(executor_mod, "_VERIFY", True)
        main, startup, loss = _fit_a_line()
        main.global_block().desc.var("x").shape = [-1]
        main._version += 1  # desc edited behind the cache's back
        exe = fluid.Executor(fluid.CPUPlace())
        with executor_mod.scope_guard(executor_mod.Scope()):
            exe.run(startup)
            with pytest.raises(errors.ProgramVerifyError) as ei:
                exe.run(main,
                        feed={"x": RNG.rand(4).astype("float32"),
                              "y": RNG.rand(4, 1).astype("float32")},
                        fetch_list=[loss])
        assert ei.value.diagnostics
        assert "rank-mismatch" in str(ei.value)

    def test_off_by_default(self):
        assert executor_mod._VERIFY is False


# ---------------------------------------------------------------------------
# CLI
# ---------------------------------------------------------------------------

def test_analyze_cli_json():
    env = dict(os.environ, PYTHONPATH=REPO, JAX_PLATFORMS="cpu")
    r = subprocess.run(
        [sys.executable, "-m", "paddle_tpu", "analyze",
         "--example", "fit_a_line", "--json"],
        capture_output=True, text=True, env=env, cwd=REPO, timeout=300)
    assert r.returncode == 0, r.stdout + r.stderr[-2000:]
    payload = json.loads(r.stdout)
    reports = payload if isinstance(payload, list) else [payload]
    assert reports and all(p["counts"]["error"] == 0 for p in reports), \
        r.stdout


# ---------------------------------------------------------------------------
# desc attr JSON round-trip (tuples keep their type)
# ---------------------------------------------------------------------------

class TestAttrRoundTrip:
    def test_every_attr_type(self):
        from paddle_tpu.framework.desc import (BlockRef, BlocksRef,
                                               OpDesc)

        attrs = {
            "b": True, "i": 7, "f": 0.5, "s": "NCHW", "none": None,
            "li": [1, 2, 3], "lf": [0.1, 0.2], "ls": ["a", "b"],
            "t": (0, 1),
            "lt": [(1, 2), (3, 4)],
            "nested": ((1, [2, 3]), "x"),
            "blk": BlockRef(1), "blks": BlocksRef([1, 2]),
        }
        op = OpDesc(type="anything", inputs={"X": ["a"]},
                    outputs={"Out": ["b"]}, attrs=dict(attrs))
        back = OpDesc.from_dict(json.loads(json.dumps(op.to_dict())))
        assert back.attrs == attrs
        # equality alone can't prove it in older pythons; pin the types
        assert isinstance(back.attrs["t"], tuple)
        assert isinstance(back.attrs["li"], list)
        assert all(isinstance(x, tuple) for x in back.attrs["lt"])
        assert isinstance(back.attrs["nested"], tuple)
        assert isinstance(back.attrs["nested"][0][1], list)

    def test_program_level_roundtrip(self):
        main, _, loss = _fit_a_line()
        from paddle_tpu.framework.desc import ProgramDesc

        s = main.desc.to_json()
        back = ProgramDesc.from_json(s)
        assert back.to_json() == s
