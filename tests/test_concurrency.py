"""Go-style channels + select (reference framework/channel.h:25-86,
fluid/concurrency.py:27-429 — the F15 capability, redesigned host-side:
see paddle_tpu/concurrency.py docstring for why in-graph CSP is subsumed
under whole-block XLA while the host orchestration role survives)."""

import threading
import time

import numpy as np
import pytest

import paddle_tpu as fluid
from paddle_tpu.concurrency import (Channel, ChannelClosedError, Select,
                                    channel_close, channel_recv,
                                    channel_send, go, make_channel)


class TestChannelSemantics:
    def test_buffered_fifo_and_close_drain(self):
        ch = make_channel(capacity=3)
        for i in range(3):
            channel_send(ch, i)
        channel_close(ch)
        got = [channel_recv(ch) for _ in range(4)]
        # pending items drain after close, then (None, False)
        assert got == [(0, True), (1, True), (2, True), (None, False)]

    def test_send_on_closed_raises(self):
        ch = make_channel(capacity=1)
        channel_close(ch)
        with pytest.raises(ChannelClosedError):
            channel_send(ch, 1)

    def test_buffered_send_blocks_when_full(self):
        ch = make_channel(capacity=1)
        channel_send(ch, "a")
        with pytest.raises(TimeoutError):
            ch.send("b", timeout=0.05)
        assert channel_recv(ch) == ("a", True)
        channel_send(ch, "b")                  # room again
        assert channel_recv(ch) == ("b", True)

    def test_unbuffered_rendezvous(self):
        """capacity=0: the send completes only when a receiver takes the
        value (channel.h:25 unbuffered contract)."""
        ch = make_channel(capacity=0)
        order = []

        def sender():
            channel_send(ch, 42)
            order.append("send-done")

        t = go(sender)
        time.sleep(0.05)
        assert not order                       # blocked: nobody received
        val, ok = channel_recv(ch)
        t.join(timeout=5)
        assert (val, ok) == (42, True)
        assert order == ["send-done"]

    def test_unbuffered_send_raises_if_closed_while_blocked(self):
        ch = make_channel(capacity=0)
        errs = []

        def sender():
            try:
                channel_send(ch, 1)
            except ChannelClosedError:
                errs.append("closed")

        t = go(sender)
        time.sleep(0.05)
        channel_close(ch)
        t.join(timeout=5)
        assert errs == ["closed"]

    def test_recv_blocks_until_send(self):
        ch = make_channel(capacity=0)
        out = []

        def receiver():
            out.append(channel_recv(ch))

        t = go(receiver)
        time.sleep(0.05)
        assert not out
        channel_send(ch, "x")
        t.join(timeout=5)
        assert out == [("x", True)]

    def test_is_copy_snapshots_value(self):
        ch = make_channel(capacity=1)
        arr = np.zeros(3)
        channel_send(ch, arr, is_copy=True)
        arr += 99                              # producer mutates after send
        got, ok = channel_recv(ch)
        assert ok and np.all(got == 0)


class TestGoAndPipelines:
    def test_producer_consumer_pipeline(self):
        """The reference demos' channel idiom: a producer goroutine feeds
        a bounded channel, the consumer drains until close."""
        ch = make_channel(capacity=4)

        def producer():
            for i in range(20):
                channel_send(ch, i * i)
            channel_close(ch)

        go(producer)
        got = []
        while True:
            val, ok = channel_recv(ch)
            if not ok:
                break
            got.append(val)
        assert got == [i * i for i in range(20)]

    def test_fan_in_two_producers(self):
        ch = make_channel(capacity=2)
        done = make_channel(capacity=2)

        def producer(tag):
            for i in range(5):
                channel_send(ch, (tag, i))
            channel_send(done, tag)

        go(producer, "a")
        go(producer, "b")
        finished = 0
        got = []
        while finished < 2:
            sel = Select() \
                .case("recv", ch, callback=lambda v, ok: got.append(v)) \
                .case("recv", done,
                      callback=lambda v, ok: got.append(("done", v)))
            idx = sel.run(timeout=10)
            if idx == 1:
                finished += 1
        # drain any stragglers
        while True:
            item = ch.try_recv()
            if not item or not item[1]:
                break
            got.append(item[0])
        vals = [g for g in got if g and g[0] in ("a", "b")]
        assert len(vals) == 10
        for tag in ("a", "b"):
            assert [i for t, i in vals if t == tag] == list(range(5))

    def test_channel_fed_training(self):
        """End-to-end: an IO goroutine streams minibatches through a
        channel into a training loop — the host-side role the reference's
        in-graph channels actually served."""
        ch = make_channel(capacity=2)
        rng = np.random.RandomState(0)
        w = rng.randn(8, 1).astype(np.float32)

        def loader():
            for _ in range(30):
                xs = rng.randn(32, 8).astype(np.float32)
                channel_send(ch, (xs, xs @ w))
            channel_close(ch)

        x = fluid.layers.data(name="x", shape=[8], dtype="float32")
        y = fluid.layers.data(name="y", shape=[1], dtype="float32")
        pred = fluid.layers.fc(input=x, size=1)
        loss = fluid.layers.mean(fluid.layers.square_error_cost(pred, y))
        fluid.optimizer.SGD(learning_rate=0.1).minimize(loss)
        exe = fluid.Executor(fluid.CPUPlace())
        exe.run(fluid.default_startup_program())

        go(loader)
        last = None
        while True:
            batch, ok = channel_recv(ch)
            if not ok:
                break
            l, = exe.run(feed={"x": batch[0], "y": batch[1]},
                         fetch_list=[loss])
            last = float(l[0])
        assert last is not None and last < 0.05


class TestSelect:
    def test_select_picks_ready_case(self):
        a, b = make_channel(capacity=1), make_channel(capacity=1)
        channel_send(b, "bee")
        hits = []
        idx = Select() \
            .case("recv", a, callback=lambda v, ok: hits.append(("a", v))) \
            .case("recv", b, callback=lambda v, ok: hits.append(("b", v))) \
            .run(timeout=5)
        assert idx == 1 and hits == [("b", "bee")]

    def test_select_default_when_nothing_ready(self):
        a = make_channel(capacity=1)
        hits = []
        idx = Select() \
            .case("recv", a) \
            .default(lambda: hits.append("default")) \
            .run()
        assert idx == -1 and hits == ["default"]

    def test_select_send_case(self):
        a = make_channel(capacity=1)
        idx = Select().case("send", a, value=7).run(timeout=5)
        assert idx == 0
        assert channel_recv(a) == (7, True)

    def test_select_blocks_then_fires(self):
        a = make_channel(capacity=1)

        def later():
            time.sleep(0.05)
            channel_send(a, "late")

        go(later)
        t0 = time.monotonic()
        idx = Select().case("recv", a).run(timeout=10)
        assert idx == 0 and time.monotonic() - t0 >= 0.04

    def test_empty_select_raises(self):
        with pytest.raises(ValueError):
            Select().run()
