"""Trace-time kernel fusion (ops/fusion.py, ISSUE 7): numeric parity
with the unfused per-op trace, per-reason fallback counters, and the
PADDLE_TPU_FUSION=0 escape hatch.

The fusion pass has three value-rewriting paths (inference BN fold,
the Pallas bn+act kernel, bucketed optimizer applies); everything else
composes the registered member lowerings and must therefore be BITWISE
identical to the unfused trace — these tests pin exactly that: bitwise
asserts for compose/bucket paths, tolerance asserts only where the
rewrite legitimately reassociates float math (BN fold).
"""

import numpy as np
import pytest

import paddle_tpu as fluid
from paddle_tpu import executor as em
from paddle_tpu import telemetry
from paddle_tpu.framework import unique_name
from paddle_tpu.ops import fusion as fusion_mod


@pytest.fixture(autouse=True)
def _fresh_telemetry():
    telemetry.reset()
    yield
    telemetry.reset()


def _with_fusion(fuse, fn, *args, **kw):
    """Run fn under FUSION_OPT=fuse. Callers build a FRESH program inside
    fn — the jit and plan caches key on program identity."""
    old = fusion_mod.FUSION_OPT
    fusion_mod.FUSION_OPT = fuse
    try:
        return fn(*args, **kw)
    finally:
        fusion_mod.FUSION_OPT = old


def _fallbacks(reason=None):
    series = telemetry.read_series("fusion_fallback_total")
    if reason is None:
        return sum(series.values())
    return sum(v for k, v in series.items() if f"reason={reason}" in k)


def _state(scope):
    return {n: np.asarray(scope.find_var(n))
            for n in scope.local_var_names()
            if isinstance(scope.find_var(n), np.ndarray)
            or hasattr(scope.find_var(n), "dtype")}


def _assert_state_equal(a, b):
    assert set(a) == set(b), set(a) ^ set(b)
    for n in sorted(a):
        np.testing.assert_array_equal(np.asarray(a[n]), np.asarray(b[n]),
                                      err_msg=f"state '{n}' diverged")


def _train_convnet(opt_factory, steps=3):
    """conv+bn(relu)+pool + an elementwise chain + two fc layers + an
    optimizer: one program that plans conv_bn_act, chain, fc_act and
    opt_bucket windows at once."""
    unique_name.switch()
    main, startup = fluid.Program(), fluid.Program()
    main.random_seed = startup.random_seed = 21
    with fluid.program_guard(main, startup):
        img = fluid.layers.data(name="img", shape=[3, 8, 8],
                                dtype="float32")
        label = fluid.layers.data(name="label", shape=[1], dtype="int64")
        c = fluid.layers.conv2d(input=img, num_filters=8, filter_size=3,
                                padding=1, bias_attr=False)
        b = fluid.layers.batch_norm(input=c, act="relu")
        p = fluid.layers.pool2d(input=b, pool_size=2, pool_stride=2)
        s = fluid.layers.abs(fluid.layers.scale(p, scale=1.5))  # chain
        gp = fluid.layers.pool2d(input=s, global_pooling=True,
                                 pool_type="avg")
        h = fluid.layers.fc(input=gp, size=16, act="relu")      # fc_act
        logits = fluid.layers.fc(input=h, size=5)               # fc, no act
        loss = fluid.layers.mean(
            fluid.layers.softmax_with_cross_entropy(logits, label))
        opt_factory().minimize(loss, startup_program=startup)
    exe = fluid.Executor(fluid.CPUPlace())
    rng = np.random.default_rng(5)
    scope = em.Scope()
    losses = []
    with em.scope_guard(scope):
        exe.run(startup)
        for _ in range(steps):
            x = rng.standard_normal((4, 3, 8, 8)).astype(np.float32)
            y = rng.integers(0, 5, (4, 1)).astype(np.int64)
            out, = exe.run(main, feed={"img": x, "label": y},
                           fetch_list=[loss])
            losses.append(float(np.ravel(out)[0]))
        state = _state(scope)
    return losses, state


@pytest.mark.parametrize("opt", ["sgd", "momentum", "adam"])
def test_training_parity_bitwise(opt):
    """Fused trace (conv+bn+act compose, chain, fc windows, bucketed
    optimizer) is bitwise identical to the unfused per-op trace."""
    factory = {
        "sgd": lambda: fluid.optimizer.SGD(learning_rate=0.05),
        "momentum": lambda: fluid.optimizer.Momentum(learning_rate=0.05,
                                                     momentum=0.9),
        "adam": lambda: fluid.optimizer.Adam(learning_rate=0.01),
    }[opt]
    l1, s1 = _with_fusion(True, _train_convnet, factory)
    l0, s0 = _with_fusion(False, _train_convnet, factory)
    assert l1 == l0
    _assert_state_equal(s1, s0)


def test_kernel_gate_counts_f32_fallback():
    """f32 training bn+act is outside the Pallas kernel's envelope (the
    kernel mirrors the bf16 one-pass stats); the group must still fuse
    via compose and count one per-reason fallback per trace."""
    before = _fallbacks("kernel_dtype")
    _with_fusion(True, _train_convnet,
                 lambda: fluid.optimizer.SGD(learning_rate=0.05))
    assert _fallbacks("kernel_dtype") > before


def _bn_act_net(steps=2):
    """batch_norm(act) directly on the feed — the conv-less bn_act window
    — trained with SGD so the bn scale/bias pair exercises a 2-param
    fused_sgd bucket."""
    unique_name.switch()
    main, startup = fluid.Program(), fluid.Program()
    main.random_seed = startup.random_seed = 13
    with fluid.program_guard(main, startup):
        x = fluid.layers.data(name="x", shape=[6, 4, 4], dtype="float32")
        b = fluid.layers.batch_norm(input=x, act="relu")
        loss = fluid.layers.mean(b)
        fluid.optimizer.SGD(learning_rate=0.1).minimize(
            loss, startup_program=startup)
    exe = fluid.Executor(fluid.CPUPlace())
    rng = np.random.default_rng(7)
    scope = em.Scope()
    losses = []
    with em.scope_guard(scope):
        exe.run(startup)
        for _ in range(steps):
            xv = rng.standard_normal((4, 6, 4, 4)).astype(np.float32)
            out, = exe.run(main, feed={"x": xv}, fetch_list=[loss])
            losses.append(float(np.ravel(out)[0]))
        state = _state(scope)
    return losses, state


def test_bn_act_without_conv_parity():
    l1, s1 = _with_fusion(True, _bn_act_net)
    l0, s0 = _with_fusion(False, _bn_act_net)
    assert l1 == l0
    _assert_state_equal(s1, s0)


def _infer_conv_bn(fetch_inter=False):
    """Inference-mode conv+bn(relu): the BN-fold path (or its
    fetched-intermediate fallback when the conv activation is fetched)."""
    unique_name.switch()
    main, startup = fluid.Program(), fluid.Program()
    main.random_seed = startup.random_seed = 31
    with fluid.program_guard(main, startup):
        img = fluid.layers.data(name="img", shape=[3, 8, 8],
                                dtype="float32")
        # bias_attr=False: a conv bias would interpose an elementwise_add
        # between conv and bn and break the window (and thus the fold)
        c = fluid.layers.conv2d(input=img, num_filters=8, filter_size=3,
                                padding=1, bias_attr=False)
        b = fluid.layers.batch_norm(input=c, act="relu", is_test=True)
        out = fluid.layers.pool2d(input=b, global_pooling=True,
                                  pool_type="avg")
    exe = fluid.Executor(fluid.CPUPlace())
    x = np.random.default_rng(9).standard_normal((2, 3, 8, 8)) \
        .astype(np.float32)
    with em.scope_guard(em.Scope()):
        exe.run(startup)
        fetch = [out] + ([c] if fetch_inter else [])
        res = exe.run(main, feed={"img": x}, fetch_list=fetch)
    return [np.asarray(r) for r in res]


def test_bn_fold_inference_parity():
    """Folding BN into the conv weights reassociates float math — close,
    not bitwise."""
    got, = _with_fusion(True, _infer_conv_bn)
    ref, = _with_fusion(False, _infer_conv_bn)
    np.testing.assert_allclose(got, ref, rtol=1e-5, atol=1e-6)


def test_fold_blocked_by_intermediate_fetch():
    """Fetching the conv activation protects it: the fold (which never
    materializes that tensor) must fall back to per-member execution —
    bitwise vs unfused — and count fetched_intermediate."""
    before = _fallbacks("fetched_intermediate")
    got = _with_fusion(True, _infer_conv_bn, fetch_inter=True)
    assert _fallbacks("fetched_intermediate") > before
    ref = _with_fusion(False, _infer_conv_bn, fetch_inter=True)
    for g, r in zip(got, ref):
        np.testing.assert_array_equal(g, r)


def _sparse_emb_net(steps=3):
    """is_sparse embedding under Adam: the SelectedRows grad keeps the
    per-param fast path (reason sparse_grad) while the dense fc pair
    still buckets."""
    unique_name.switch()
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        ids = fluid.layers.data(name="ids", shape=[4], dtype="int64")
        lbl = fluid.layers.data(name="lbl", shape=[1], dtype="int64")
        emb = fluid.layers.embedding(
            ids, size=[50, 8], is_sparse=True,
            param_attr=fluid.ParamAttr(name="emb_w"))
        flat = fluid.layers.reshape(emb, shape=[-1, 32])
        logits = fluid.layers.fc(input=flat, size=50)
        loss = fluid.layers.mean(
            fluid.layers.softmax_with_cross_entropy(logits, lbl))
        fluid.optimizer.Adam(learning_rate=0.1).minimize(
            loss, startup_program=startup)
    exe = fluid.Executor(fluid.CPUPlace())
    scope = em.Scope()
    feed = {"ids": np.array([[1, 7, 7, 3], [0, 2, 2, 2]], np.int64),
            "lbl": np.array([[5], [9]], np.int64)}
    losses = []
    with em.scope_guard(scope):
        exe.run(startup)
        scope.set_var("emb_w", np.linspace(
            -1, 1, 50 * 8).astype(np.float32).reshape(50, 8))
        for _ in range(steps):
            v, = exe.run(main, feed=feed, fetch_list=[loss])
            losses.append(float(np.ravel(v)[0]))
        state = _state(scope)
    return losses, state


def test_sparse_grad_keeps_per_param_path():
    before = _fallbacks("sparse_grad")
    l1, s1 = _with_fusion(True, _sparse_emb_net)
    assert _fallbacks("sparse_grad") > before
    l0, s0 = _with_fusion(False, _sparse_emb_net)
    assert l1 == l0
    _assert_state_equal(s1, s0)


def _run_steps_window(steps=3):
    """K-step run_steps window (lax.scan carries + donation) over the
    fused trace."""
    unique_name.switch()
    main, startup = fluid.Program(), fluid.Program()
    main.random_seed = startup.random_seed = 17
    with fluid.program_guard(main, startup):
        img = fluid.layers.data(name="img", shape=[3, 8, 8],
                                dtype="float32")
        label = fluid.layers.data(name="label", shape=[1], dtype="int64")
        c = fluid.layers.conv2d(input=img, num_filters=4, filter_size=3,
                                padding=1, bias_attr=False)
        b = fluid.layers.batch_norm(input=c, act="relu")
        gp = fluid.layers.pool2d(input=b, global_pooling=True,
                                 pool_type="avg")
        logits = fluid.layers.fc(input=gp, size=5)
        loss = fluid.layers.mean(
            fluid.layers.softmax_with_cross_entropy(logits, label))
        fluid.optimizer.Momentum(learning_rate=0.05, momentum=0.9).minimize(
            loss, startup_program=startup)
    exe = fluid.Executor(fluid.CPUPlace())
    rng = np.random.default_rng(23)
    feeds = [{"img": rng.standard_normal((4, 3, 8, 8)).astype(np.float32),
              "label": rng.integers(0, 5, (4, 1)).astype(np.int64)}
             for _ in range(steps)]
    scope = em.Scope()
    with em.scope_guard(scope):
        exe.run(startup)
        win, = exe.run_steps(main, feed_window=feeds, fetch_list=[loss],
                             fetch_mode="stack")
        state = _state(scope)
    return np.asarray(win), state


def test_run_steps_window_parity():
    w1, s1 = _with_fusion(True, _run_steps_window)
    w0, s0 = _with_fusion(False, _run_steps_window)
    np.testing.assert_array_equal(w1, w0)
    _assert_state_equal(s1, s0)


def test_pallas_bn_act_kernel_parity():
    """The fused bn+act Pallas kernel (interpret mode off-TPU) matches
    the unfused bf16 one-pass batch_norm math exactly."""
    import jax
    import jax.numpy as jnp

    rng = np.random.default_rng(2)
    x = jnp.asarray(rng.standard_normal((2, 4, 4, 128)),
                    dtype=jnp.bfloat16).reshape(-1, 128)
    scale = jnp.asarray(rng.standard_normal(128), dtype=jnp.float32)
    bias = jnp.asarray(rng.standard_normal(128), dtype=jnp.float32)
    eps = 1e-5

    xf = x.astype(jnp.float32)
    m_ref = jnp.mean(xf, axis=0)
    v_ref = jnp.maximum(
        jnp.mean(jnp.square(xf), axis=0) - jnp.square(m_ref), 0.0)
    inv = jax.lax.rsqrt(v_ref + eps)
    y_ref = ((xf - m_ref) * (inv * scale) + bias).astype(x.dtype)

    for act_fn in (None, lambda v, a=None: jnp.maximum(v, 0)):
        res = fusion_mod._pallas_bn_act(x, scale, bias, eps, act_fn)
        ybn, mean, var = res[0], res[-2], res[-1]
        np.testing.assert_array_equal(np.asarray(mean), np.asarray(m_ref))
        np.testing.assert_array_equal(np.asarray(var), np.asarray(v_ref))
        np.testing.assert_array_equal(
            np.asarray(ybn.astype(jnp.float32)),
            np.asarray(y_ref.astype(jnp.float32)))
        if act_fn is not None:
            yact = res[1]
            np.testing.assert_array_equal(
                np.asarray(yact), np.asarray(jnp.maximum(ybn, 0)))


def test_roofline_sees_fused_ops():
    """The analytic cost model prices fused types from their prefixed
    member slots, and hlo_counts parses instruction/fusion counts."""
    import jax
    from paddle_tpu import roofline

    aval = jax.ShapeDtypeStruct((2, 8, 8, 8), np.float32)
    filt = jax.ShapeDtypeStruct((8, 3, 3, 3), np.float32)
    flops, bytes_ = roofline.op_cost(
        "fused_conv_bn_act",
        {"0:Input": [jax.ShapeDtypeStruct((2, 3, 8, 8), np.float32)],
         "0:Filter": [filt]},
        {"1:Y": [aval]})
    # 2*out_elems*cin*kh*kw for the conv + ~10/elem for bn+act
    out_elems = 2 * 8 * 8 * 8
    assert flops == 2.0 * out_elems * 3 * 3 * 3 + 10.0 * out_elems
    assert bytes_ > 0

    p = jax.ShapeDtypeStruct((100,), np.float32)
    flops, _ = roofline.op_cost(
        "fused_adam", {"Param": [p, p], "Grad": [p, p]}, {})
    assert flops == 12.0 * 200

    hlo = """HloModule m
fused_computation {
  p0 = f32[8]{0} parameter(0)
  ROOT add = f32[8]{0} add(p0, p0)
}
ENTRY main {
  x = f32[8]{0} parameter(0)
  f = f32[8]{0} fusion(x), kind=kLoop, calls=fused_computation
  ROOT t = (f32[8]{0}, f32[8]{0}) tuple(f, x)
}
"""
    counts = roofline.hlo_counts(hlo)
    assert counts["fusions"] == 1
    assert counts["instructions"] >= 5


def test_plan_window_kinds():
    """The planner finds every expected window in the convnet and the
    gate turns it off wholesale."""
    unique_name.switch()
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        img = fluid.layers.data(name="img", shape=[3, 8, 8],
                                dtype="float32")
        label = fluid.layers.data(name="label", shape=[1], dtype="int64")
        c = fluid.layers.conv2d(input=img, num_filters=8, filter_size=3,
                                padding=1, bias_attr=False)
        b = fluid.layers.batch_norm(input=c, act="relu")
        s = fluid.layers.abs(fluid.layers.scale(b, scale=1.5))
        gp = fluid.layers.pool2d(input=s, global_pooling=True,
                                 pool_type="avg")
        logits = fluid.layers.fc(input=gp, size=5, act="relu")
        loss = fluid.layers.mean(
            fluid.layers.softmax_with_cross_entropy(logits, label))
        fluid.optimizer.Momentum(learning_rate=0.05, momentum=0.9).minimize(
            loss, startup_program=startup)

    old = fusion_mod.FUSION_OPT
    try:
        fusion_mod.FUSION_OPT = True
        groups = fusion_mod.plan(main)
        kinds = {g.kind for g in groups.values()}
        assert {"conv_bn_act", "chain", "fc_act", "opt_bucket"} <= kinds
        # anchor map is non-overlapping and in block order
        spans = sorted((g.start, g.end) for g in groups.values())
        for (s0, e0), (s1, _) in zip(spans, spans[1:]):
            assert e0 <= s1
        fusion_mod.FUSION_OPT = False
        assert fusion_mod.plan(main) is None
    finally:
        fusion_mod.FUSION_OPT = old
