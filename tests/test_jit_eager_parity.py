"""Program-level jit-vs-eager parity (VERDICT r3 weak #7): the executor
has two semantics — whole-block XLA jit and the op-by-op eager interpreter
(reference executor.cc's interpretation model, executor.py:1-17). Per-op
tests pin individual kernels; THIS pins the program-level glue (scope
handling, feed normalization, LoD side-channels, RNG stream, persistable
write-back) by running real book-shaped programs in both modes and
asserting identical results."""

import numpy as np
import pytest

import paddle_tpu as fluid
from paddle_tpu import executor as executor_mod
from paddle_tpu.framework import unique_name


def _run_both(build, feeds, steps=2, seed=7):
    """Build the same program twice (fresh name generator => identical
    parameter init streams), run `steps` training steps in jit and eager
    mode, return the two loss trajectories."""
    out = {}
    for use_jit in (True, False):
        unique_name.switch()
        main, startup = fluid.Program(), fluid.Program()
        with fluid.program_guard(main, startup):
            main.random_seed = startup.random_seed = seed
            loss = build()
        exe = fluid.Executor(fluid.CPUPlace())
        with executor_mod.scope_guard(executor_mod.Scope()):
            exe.run(startup, use_jit=use_jit)
            traj = []
            for _ in range(steps):
                r, = exe.run(main, feed=dict(feeds), fetch_list=[loss],
                             use_jit=use_jit)
                traj.append(float(np.asarray(r).ravel()[0]))
        out[use_jit] = traj
    return out[True], out[False]


def test_fit_a_line_parity():
    rng = np.random.RandomState(0)
    feeds = {"x": rng.randn(16, 13).astype(np.float32),
             "y": rng.randn(16, 1).astype(np.float32)}

    def build():
        x = fluid.layers.data(name="x", shape=[13], dtype="float32")
        y = fluid.layers.data(name="y", shape=[1], dtype="float32")
        pred = fluid.layers.fc(input=x, size=1)
        loss = fluid.layers.mean(fluid.layers.square_error_cost(pred, y))
        fluid.optimizer.SGD(learning_rate=0.01).minimize(loss)
        return loss

    jit, eager = _run_both(build, feeds)
    np.testing.assert_allclose(jit, eager, rtol=1e-5, atol=1e-7)


def test_conv_classifier_parity():
    rng = np.random.RandomState(1)
    feeds = {"img": rng.rand(4, 1, 12, 12).astype(np.float32),
             "label": rng.randint(0, 4, (4, 1)).astype(np.int64)}

    def build():
        img = fluid.layers.data(name="img", shape=[1, 12, 12],
                                dtype="float32")
        label = fluid.layers.data(name="label", shape=[1], dtype="int64")
        conv = fluid.nets.simple_img_conv_pool(
            input=img, num_filters=4, filter_size=3, pool_size=2,
            pool_stride=2, act="relu")
        logits = fluid.layers.fc(input=conv, size=4)
        loss = fluid.layers.mean(fluid.layers.softmax_with_cross_entropy(
            logits=logits, label=label))
        fluid.optimizer.Adam(learning_rate=1e-3).minimize(loss)
        return loss

    jit, eager = _run_both(build, feeds)
    np.testing.assert_allclose(jit, eager, rtol=1e-5, atol=1e-7)


def test_lod_sequence_parity():
    """Sequence program with a LoD feed: the padded-pack emulation and its
    @SEQLEN side channel must behave identically in both executors."""
    rng = np.random.RandomState(2)
    LoD = executor_mod.LoDTensor
    feeds = {"words": LoD(rng.randint(0, 30, (11, 1)).astype(np.int64),
                          [[0, 4, 7, 11]]),
             "label": rng.randint(0, 2, (3, 1)).astype(np.int64)}

    def build():
        words = fluid.layers.data(name="words", shape=[1], dtype="int64",
                                  lod_level=1)
        label = fluid.layers.data(name="label", shape=[1], dtype="int64")
        emb = fluid.layers.embedding(input=words, size=[30, 8])
        proj = fluid.layers.fc(input=emb, size=32, num_flatten_dims=2)
        h, _c = fluid.layers.dynamic_lstm(input=proj, size=32)
        last = fluid.layers.sequence_last_step(h)
        logits = fluid.layers.fc(input=last, size=2)
        loss = fluid.layers.mean(fluid.layers.softmax_with_cross_entropy(
            logits=logits, label=label))
        fluid.optimizer.SGD(learning_rate=0.05).minimize(loss)
        return loss

    jit, eager = _run_both(build, feeds)
    np.testing.assert_allclose(jit, eager, rtol=1e-5, atol=1e-7)


def test_dropout_rng_stream_parity():
    """Random ops draw from the scope's __rng_counter__-derived stream —
    jit and eager must consume the SAME stream (r3 pinned the seed into
    the jit cache key; this pins the runtime draw)."""
    rng = np.random.RandomState(3)
    feeds = {"x": rng.randn(8, 16).astype(np.float32),
             "y": rng.randn(8, 1).astype(np.float32)}

    def build():
        x = fluid.layers.data(name="x", shape=[16], dtype="float32")
        y = fluid.layers.data(name="y", shape=[1], dtype="float32")
        h = fluid.layers.fc(input=x, size=16, act="relu")
        h = fluid.layers.dropout(h, dropout_prob=0.5)
        pred = fluid.layers.fc(input=h, size=1)
        loss = fluid.layers.mean(fluid.layers.square_error_cost(pred, y))
        fluid.optimizer.SGD(learning_rate=0.01).minimize(loss)
        return loss

    jit, eager = _run_both(build, feeds, steps=3)
    np.testing.assert_allclose(jit, eager, rtol=1e-5, atol=1e-7)
