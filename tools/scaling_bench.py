#!/usr/bin/env python
"""Data-parallel scaling-efficiency benchmark — the measurement apparatus
for the reference's distributed headline (reference
benchmark/cluster/vgg16/README.md:40-49: VGG-16 CIFAR-10 over the gRPC
parameter server scaled at 78.6% efficiency on 20 trainers falling to
60.9% at 100; BASELINE.md §5 sets >= 90% on ICI as the target this
design must beat).

Runs the same config (VGG-16, 32x32 inputs, per-device batch 128) over dp
meshes of growing size and reports samples/sec + efficiency vs linear
scaling from the 1-device point. On a real TPU slice this measures the
ICI AllReduce target directly:

    python tools/scaling_bench.py                 # all local devices
    python tools/scaling_bench.py 1 4 8           # specific mesh sizes
    python tools/scaling_bench.py --steps-per-call 8 1 4 8
                                  # fused K-step windows (Executor.run_steps)

`--steps-per-call K` (or SCALE_STEPS_PER_CALL) drives each mesh size
through Executor.run_steps — K steps per dispatch via one lax.scan window,
state shardings riding the scan carry — so the sweep captures the
dispatch-overhead trend next to the scaling trend; every per-mesh JSON
line carries a `steps_per_call` column.

SCALE_MODEL=embedding swaps the image model for the criteo-style sparse
embedding net (ISSUE 10): a [SCALE_EMB_ROWS x SCALE_EMB_DIM] table looked
up by SCALE_EMB_SLOTS features per example, fsdp-row-sharded over the
mesh, Adam scatter-apply end-to-end. Its per-mesh lines add
rows_touched_per_sec and table_bytes_per_shard — the memory column falls
~1/n while throughput holds. SCALE_EMB_BUDGET=<MB> swaps the sharding
for the beyond-HBM hot-row cache (ISSUE 14): the table stays unsharded,
only a budget-sized slab is device-resident, and the lines add
cache_rows / cache_hit_rate / prefetch_overlap_fraction /
flush_bytes_per_step (null when the cache is off).

SCALE_MODEL=lm swaps in the planner-sharded transformer LM (ISSUE 15):
each mesh size is factored into data x fsdp x tp named axes
(SCALE_LM_TP picks the tp degree, default 2 when it divides) and
`paddle_tpu.parallel.planner.plan` writes every spec — no hand
annotation. Its per-mesh lines always carry `param_bytes_per_shard`
(per-device param HBM under the plan — falls as fsdp x tp grows),
`overlap_fraction` and `busbw` (null when the trace shows no
collectives, e.g. 1-device runs). SCALE_LM_VOCAB / SCALE_LM_DMODEL /
SCALE_LM_LAYERS / SCALE_LM_SEQLEN size the model (defaults are a smoke
config; scale them up on a real slice).

On a CPU host it exercises the identical GSPMD path over virtual devices
— mechanism check only; the shared core makes the timings say nothing
about ICI. Use SCALE_PLATFORM=cpu (the env var JAX_PLATFORMS alone does
not override a TPU plugin) with
XLA_FLAGS=--xla_force_host_platform_device_count=8, plus
SCALE_MODEL=smallnet_mnist_cifar SCALE_BS=16 to keep 1-core compiles
quick.

Prints one JSON line per mesh size plus a summary line. Each per-mesh
line also carries roofline attribution (`top_ops`, `bound`,
`device_duty_cycle` — see paddle_tpu/roofline.py) from a short traced
re-run of the compiled step; SCALE_PERF=0 skips that pass.
"""

import json
import os
import sys
import time

import numpy as np

# `python tools/scaling_bench.py` puts tools/ (not the repo root) on
# sys.path; make the tool runnable from anywhere
sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(
    __file__))))


def _parse_steps_per_call(v):
    v = str(v).strip().lower()
    return "auto" if v == "auto" else int(v)


def _auto_steps_per_call(exe, prog, run_step, feed, fetch):
    """`--steps-per-call auto` (ISSUE 9): probe the already-compiled K=1
    path for per-dispatch Python overhead and per-step device time, bound
    the window by the HBM headroom over the K=1 footprint, and let
    overlap.choose_steps_per_call pick K. Probe failures degrade to
    whatever signals remain — the sweep must never die here."""
    from paddle_tpu.parallel import overlap as overlap_mod

    step_ms = overhead_ms = None
    try:
        out = run_step()
        float(np.asarray(out).ravel()[0])         # compile + drain
        n = 5
        t0 = time.perf_counter()
        for _ in range(n):
            out = run_step()
        float(np.asarray(out).ravel()[0])
        step_ms = (time.perf_counter() - t0) / n * 1e3
        t0 = time.perf_counter()
        for _ in range(n):
            out = run_step()              # enqueue-only: host-side cost
        overhead_ms = (time.perf_counter() - t0) / n * 1e3
        float(np.asarray(out).ravel()[0])
    except Exception as e:  # noqa: BLE001 - probe is best-effort
        print(f"auto steps-per-call timing probe failed: {e}",
              file=sys.stderr)
    peak = budget = feed_bytes = None
    try:
        from paddle_tpu import memory as memory_mod
        rec = exe.static_memory_analysis(prog, feed=feed,
                                         fetch_list=[fetch])
        peak = rec.total_bytes
        budget = memory_mod.default_budget(exe.device)
        feed_bytes = int(sum(np.asarray(v).nbytes for v in feed.values()))
    except Exception as e:  # noqa: BLE001 - probe is best-effort
        print(f"auto steps-per-call memory probe failed: {e}",
              file=sys.stderr)
    k = overlap_mod.choose_steps_per_call(
        python_overhead_ms=overhead_ms, step_time_ms=step_ms,
        feed_bytes_per_step=feed_bytes, peak_bytes=peak,
        budget_bytes=budget)
    print(f"steps-per-call auto -> {k}", file=sys.stderr)
    return k


def measure(n_devices, steps=None, warmup=None, per_device_batch=None,
            steps_per_call=None):
    # SCALE_BS/SCALE_STEPS shrink the config for mechanism checks on CPU
    # hosts (VGG jit compiles cost minutes per mesh size on 1-core boxes);
    # real-slice measurements should keep the reference bs128
    if steps is None:
        steps = int(os.environ.get("SCALE_STEPS", "10"))
    if warmup is None:
        warmup = int(os.environ.get("SCALE_WARMUP", "8"))
    if per_device_batch is None:
        per_device_batch = int(os.environ.get("SCALE_BS", "128"))
    if steps_per_call is None:
        steps_per_call = _parse_steps_per_call(
            os.environ.get("SCALE_STEPS_PER_CALL", "1"))
    if steps < 1 or per_device_batch < 1 or (
            steps_per_call != "auto" and steps_per_call < 1):
        raise SystemExit(
            "SCALE_STEPS, SCALE_BS and SCALE_STEPS_PER_CALL must be >= 1")
    warmup = max(warmup, 1)   # the sync readback needs at least one run
    model_name = os.environ.get("SCALE_MODEL", "vgg16")
    import jax
    from jax.sharding import Mesh

    import paddle_tpu as fluid
    from paddle_tpu import executor as em
    from paddle_tpu import models
    from paddle_tpu.framework import unique_name

    batch = per_device_batch * n_devices
    emb_cfg = lm_cfg = None
    with unique_name.guard():
        main, startup = fluid.Program(), fluid.Program()
        rng = np.random.default_rng(0)
        if model_name == "embedding":
            # sparse-embedding scaling family (ISSUE 10): the table and its
            # adam moments shard ROW-wise over an fsdp mesh, so the sweep's
            # memory column shows per-shard table HBM falling ~1/n while
            # rows_touched_per_sec holds — the recommender-model motivation
            # for fsdp-partitioned tables
            emb_cfg = {
                "rows": int(os.environ.get("SCALE_EMB_ROWS", "100000")),
                "dim": int(os.environ.get("SCALE_EMB_DIM", "64")),
                "slots": int(os.environ.get("SCALE_EMB_SLOTS", "26"))}
            with fluid.program_guard(main, startup):
                ids = fluid.layers.data(name="img",
                                        shape=[emb_cfg["slots"]],
                                        dtype="int64")
                label = fluid.layers.data(name="label", shape=[1],
                                          dtype="int64")
                emb = fluid.layers.embedding(
                    ids, size=[emb_cfg["rows"], emb_cfg["dim"]],
                    is_sparse=True,
                    param_attr=fluid.ParamAttr(name="emb_table"))
                flat = fluid.layers.reshape(
                    emb, shape=[-1, emb_cfg["slots"] * emb_cfg["dim"]])
                h = fluid.layers.fc(input=flat, size=256, act="relu")
                logits = fluid.layers.fc(input=h, size=2)
                avg_cost = fluid.layers.mean(
                    fluid.layers.softmax_with_cross_entropy(logits, label))
                fluid.optimizer.Adam(learning_rate=1e-3).minimize(
                    avg_cost, startup_program=startup)
            # SCALE_EMB_BUDGET=<MB> mirrors bench.py's BENCH_EMB_BUDGET:
            # the beyond-HBM hot-row cache instead of fsdp row-sharding
            # (mutually exclusive per table) — the table stays unsharded
            # at every mesh size and only a budget-sized slab is
            # device-resident; extra columns report cache behavior
            emb_cfg["budget_mb"] = os.environ.get("SCALE_EMB_BUDGET")
            if n_devices > 1 and emb_cfg["budget_mb"] is None:
                from paddle_tpu.parallel import embedding as emb_mod
                main._mesh = Mesh(np.array(jax.devices()[:n_devices]),
                                  ("fsdp",))
                emb_mod.shard_table(main, "emb_table", "fsdp")
            x = rng.integers(0, emb_cfg["rows"],
                             (batch, emb_cfg["slots"])).astype(np.int64)
            y = rng.integers(0, 2, (batch, 1)).astype(np.int64)
        elif model_name == "lm":
            # planner-sharded LM family (ISSUE 15): the mesh size under
            # test is factored into data x fsdp x tp named axes and every
            # spec comes from planner.plan's role classification — the
            # sweep shows param_bytes_per_shard falling with fsdp x tp
            # while the planned collectives stay hidden (overlap_fraction)
            lm_cfg = {
                "vocab": int(os.environ.get("SCALE_LM_VOCAB", "512")),
                "d_model": int(os.environ.get("SCALE_LM_DMODEL", "64")),
                "layers": int(os.environ.get("SCALE_LM_LAYERS", "2")),
                "seqlen": int(os.environ.get("SCALE_LM_SEQLEN", "64"))}
            # feeds reuse the sweep's img/label plumbing (ids-as-img, like
            # the embedding family)
            with fluid.program_guard(main, startup):
                tok = fluid.layers.data(name="img",
                                        shape=[lm_cfg["seqlen"]],
                                        dtype="int64")
                lab = fluid.layers.data(name="label",
                                        shape=[lm_cfg["seqlen"]],
                                        dtype="int64")
                avg_cost = models.transformer_lm(
                    tok, lab, vocab_size=lm_cfg["vocab"],
                    d_model=lm_cfg["d_model"], n_head=4,
                    n_layer=lm_cfg["layers"])
                fluid.optimizer.Momentum(learning_rate=0.01,
                                         momentum=0.9).minimize(
                    avg_cost, startup_program=startup)
            if n_devices > 1:
                from paddle_tpu.parallel import planner as planner_mod
                tp = int(os.environ.get("SCALE_LM_TP", "2"))
                tp = tp if tp > 0 and n_devices % tp == 0 else 1
                rest = n_devices // tp
                fsdp = 2 if rest % 2 == 0 else 1
                dp = rest // fsdp
                mesh = Mesh(np.array(jax.devices()[:n_devices]).reshape(
                    dp, fsdp, tp), ("dp", "fsdp", "tp"))
                planner_mod.plan(main, mesh)
            x = rng.integers(0, lm_cfg["vocab"],
                             (batch, lm_cfg["seqlen"])).astype(np.int64)
            y = rng.integers(0, lm_cfg["vocab"],
                             (batch, lm_cfg["seqlen"])).astype(np.int64)
        else:
            with fluid.program_guard(main, startup):
                img = fluid.layers.data(name="img", shape=[3, 32, 32],
                                        dtype="float32")
                label = fluid.layers.data(name="label", shape=[1],
                                          dtype="int64")
                avg_cost, _, _ = models.build_image_classifier(
                    getattr(models, model_name), img, label, class_dim=10)
                fluid.optimizer.Momentum(learning_rate=0.001,
                                         momentum=0.9).minimize(
                    avg_cost, startup_program=startup)
            if n_devices > 1:
                main._mesh = Mesh(np.array(jax.devices()[:n_devices]),
                                  ("dp",))
            x = rng.standard_normal((batch, 3, 32, 32), dtype=np.float32)
            y = rng.integers(0, 10, (batch, 1)).astype(np.int64)

        exe = fluid.Executor(fluid.TPUPlace(0))
        k = steps_per_call
        # per-step feed is always built: the k=1 path runs on it (also the
        # probe path for `auto`), and static_memory_analysis below reports
        # the per-STEP footprint
        feed = {"img": jax.device_put(x), "label": jax.device_put(y)}

        def run_step():
            out, = exe.run(main, feed=feed, fetch_list=[avg_cost],
                           return_numpy=False)
            return out

        with em.scope_guard(em.Scope()):
            exe.run(startup)
            emb_cache = None
            if emb_cfg is not None and emb_cfg.get("budget_mb"):
                from paddle_tpu.parallel import emb_cache as emb_cache_mod
                emb_cache = emb_cache_mod.enable(
                    main, budget_bytes=int(
                        float(emb_cfg["budget_mb"]) * (1 << 20)))
            if k == "auto":
                # probe the compiled K=1 path for dispatch overhead, step
                # time and HBM headroom, then let the overlap pass pick K
                k = _auto_steps_per_call(exe, main, run_step, feed,
                                         avg_cost)
            if k > 1:
                # fused window: one [K, B, ...] feed, K steps per
                # dispatch; the dp state shardings ride the scan carry
                window = {"img": jax.device_put(np.stack([x] * k)),
                          "label": jax.device_put(np.stack([y] * k))}

                def run_one():
                    out, = exe.run_steps(main, feed_window=window,
                                         steps=k, fetch_list=[avg_cost],
                                         fetch_mode="last",
                                         return_numpy=False)
                    return out
            else:
                run_one = run_step

            warm_calls = max(1, -(-warmup // k))
            calls = max(1, steps // k)
            for _ in range(warm_calls):
                out = run_one()
            float(np.asarray(out).ravel()[0])
            cache_base = emb_cache.stats() if emb_cache else None
            t0 = time.perf_counter()
            for _ in range(calls):
                out = run_one()
            final = float(np.asarray(out).ravel()[0])
            dt = time.perf_counter() - t0
            steps = calls * k   # actual device steps timed
            peak_hbm = None
            try:
                # per-shard static footprint (memory_analysis of an SPMD
                # program is post-partitioning) — the memory column of the
                # memory/throughput trade-off this sweep exists to show
                rec = exe.static_memory_analysis(
                    main, feed=feed, fetch_list=[avg_cost])
                peak_hbm = rec.total_bytes
            except Exception:
                pass
            perf = _perf_fields(run_one)
            if emb_cfg is not None:
                perf.update(_embedding_fields(
                    main, emb_cfg, batch * steps / dt))
                perf.update(_emb_cache_fields(emb_cache, cache_base,
                                              steps))
            if lm_cfg is not None:
                # lm lines always carry the three planner columns;
                # overlap_fraction/busbw stay whatever the trace showed
                # (null when it had no collectives — 1-device runs)
                perf.update(_lm_fields(main))
                perf.setdefault("overlap_fraction", None)
                perf.setdefault("busbw", None)
            perf.update(_analyze_fields(main))
    assert np.isfinite(final)
    return batch * steps / dt, peak_hbm, perf, k


def measure_serving(n_devices):
    """SCALE_MODEL=serving (ISSUE 13): serve the criteo-style DLRM scorer
    with its table fsdp-row-sharded over an n-device mesh, through
    ServingEngine (per-bucket AOT executables) + DynamicBatcher under
    concurrent clients, and return the serving-trajectory line for this
    mesh size: p50_ms/p99_ms/qps/shed_fraction/bucket_hits/
    goodput_fraction (+ the 2x overload phase) — the serve-side companion
    to the training sweep's samples_per_sec."""
    import jax
    from jax.sharding import Mesh

    import paddle_tpu as fluid
    from paddle_tpu import executor as em
    from paddle_tpu import telemetry
    from paddle_tpu.framework import unique_name
    from paddle_tpu.serving import DynamicBatcher, ServingEngine, run_load

    rows = int(os.environ.get("SCALE_EMB_ROWS", "100000"))
    dim = int(os.environ.get("SCALE_EMB_DIM", "64"))
    slots = int(os.environ.get("SCALE_EMB_SLOTS", "26"))
    clients = int(os.environ.get("SCALE_SERVE_CLIENTS", "4"))
    requests = int(os.environ.get("SCALE_SERVE_REQUESTS", "16"))
    max_batch = int(os.environ.get("SCALE_SERVE_MAX_BATCH", "16"))
    delay_ms = float(os.environ.get("SCALE_SERVE_DELAY_MS", "3.0"))

    with unique_name.guard():
        main_prog, startup = fluid.Program(), fluid.Program()
        with fluid.program_guard(main_prog, startup):
            ids = fluid.layers.data(name="ids", shape=[slots],
                                    dtype="int64")
            emb = fluid.layers.embedding(
                ids, size=[rows, dim], is_sparse=True,
                param_attr=fluid.ParamAttr(name="emb_table"))
            flat = fluid.layers.reshape(emb, shape=[-1, slots * dim])
            h = fluid.layers.fc(input=flat, size=256, act="relu")
            prob = fluid.layers.softmax(fluid.layers.fc(input=h, size=2))
        if n_devices > 1:
            from paddle_tpu.parallel import embedding as emb_mod
            main_prog._mesh = Mesh(np.array(jax.devices()[:n_devices]),
                                   ("fsdp",))
            emb_mod.shard_table(main_prog, "emb_table", "fsdp")

        scope = em.Scope()
        exe = fluid.Executor(fluid.TPUPlace(0))
        with em.scope_guard(scope):
            exe.run(startup)
        engine = ServingEngine(main_prog, feed_names=["ids"],
                               fetch_names=[prob.name], scope=scope,
                               max_batch=max_batch)
        rng = np.random.default_rng(0)
        choices = [1, 2, 3, max(1, max_batch // 4)]

        def make_feed(ci, ri):
            n = choices[(ci + ri) % len(choices)]
            return {"ids": rng.integers(0, rows, (n, slots))
                    .astype(np.int64)}

        batcher = DynamicBatcher(engine, max_delay_ms=delay_ms,
                                 max_queue_depth=32).start()
        try:
            # compile the buckets the load will hit outside the timed phase
            for b in sorted({engine.bucket_for(c) for c in choices}):
                engine.run_batch({"ids": rng.integers(0, rows, (b, slots))
                                  .astype(np.int64)})
            normal = run_load(batcher, make_feed, clients=clients,
                              requests_per_client=requests, label="normal")
            overload = run_load(batcher, make_feed, clients=2 * clients,
                                requests_per_client=requests,
                                deadline_ms=max(delay_ms * 8, 50.0),
                                label="overload")
        finally:
            batcher.stop()
        densify = telemetry.read_series("sparse_densify_fallback_total")
        line = {
            "devices": n_devices,
            "p50_ms": normal["p50_ms"], "p99_ms": normal["p99_ms"],
            "qps": round(normal["qps"], 1),
            "shed_fraction": normal["shed_fraction"],
            "bucket_hits": normal["bucket_hits"],
            "goodput_fraction": normal["goodput_fraction"],
            "overload": {k: overload[k] for k in
                         ("p50_ms", "p99_ms", "qps", "shed_fraction",
                          "bucket_hits", "goodput_fraction")},
            "table_rows": rows, "max_batch": max_batch,
            "compile_cache": {"hits": engine.cache_hits,
                              "misses": engine.cache_misses},
            "densify_fallbacks": sum(densify.values()),
        }
        engine.close()
    return line


def _analyze_fields(main):
    """analyze_errors / analyze_warnings for the per-mesh JSON line (same
    contract as bench.py): one static-verifier pass over the measured
    program. SCALE_ANALYZE=0 skips; failures degrade to no fields."""
    if os.environ.get("SCALE_ANALYZE", "1") != "1":
        return {}
    try:
        from paddle_tpu.analysis import analyze_program

        counts = analyze_program(main).counts()
        return {"analyze_errors": counts.get("error", 0),
                "analyze_warnings": counts.get("warning", 0)}
    except Exception as e:  # noqa: BLE001 - advisory, never kills the line
        print(f"static analysis skipped: {e}", file=sys.stderr)
        return {}


def _lm_fields(main):
    """Planner columns for the lm family: per-device parameter HBM under
    the written specs (`memory.per_shard_param_bytes` — the same number
    planner.validate_plan_bytes pins the plan against), null if the
    accounting fails. The 1-device run has no plan, so the column reads
    the full replicated footprint — the sweep's falling trend starts
    from it."""
    try:
        from paddle_tpu.parallel import per_shard_param_bytes
        return {"param_bytes_per_shard":
                per_shard_param_bytes(main)["per_device_bytes"]}
    except Exception:  # noqa: BLE001 - bytes column is best-effort
        return {"param_bytes_per_shard": None}


def _embedding_fields(main, emb_cfg, examples_per_sec):
    """Extra per-mesh columns for the embedding family: sparse-path
    throughput in rows touched (ids presented to the table) per second,
    the table geometry, whether scatter-apply was live, and per-shard
    table bytes — the 1/n memory trend the fsdp sharding buys."""
    from paddle_tpu.ops import sparse_ops
    out = {"rows_touched_per_sec": round(
               examples_per_sec * emb_cfg["slots"], 1),
           "table_rows": emb_cfg["rows"],
           "sparse_apply": sparse_ops.sparse_apply_enabled()}
    try:
        from paddle_tpu.parallel import embedding as emb_mod
        t = emb_mod.per_shard_table_bytes(main)["tables"].get("emb_table")
        if t is None:     # 1-device run: table never sharded
            t = {"bytes": emb_cfg["rows"] * emb_cfg["dim"] * 4,
                 "per_shard_bytes": emb_cfg["rows"] * emb_cfg["dim"] * 4}
        out["table_bytes"] = t["bytes"]
        out["table_bytes_per_shard"] = t["per_shard_bytes"]
    except Exception:  # noqa: BLE001 - bytes columns are best-effort
        pass
    return out


def _emb_cache_fields(emb_cache, base, steps):
    """bench.py-mirrored columns for the SCALE_EMB_BUDGET config: hit
    rate / flush bytes are deltas over the timed phase only (the warmup
    phase pays the compulsory misses), prefetch overlap is cumulative
    (null-equivalent 0.0 here — the sweep's fixed-feed loop issues no
    explicit prefetches; bench.py's BENCH_MODE=embedding drives that
    path). Columns emit null when the cache is off so the sweep's CSV
    stays rectangular across configs."""
    if emb_cache is None:
        return {"cache_rows": None, "cache_hit_rate": None,
                "prefetch_overlap_fraction": None,
                "flush_bytes_per_step": None}
    s = emb_cache.stats()
    d_hit = s["hits"] - base["hits"]
    d_miss = s["misses"] - base["misses"]
    t = next(iter(emb_cache.tables().values()))
    return {
        "cache_rows": t.cache_rows,
        "cache_hit_rate": round(d_hit / max(d_hit + d_miss, 1), 4),
        "prefetch_overlap_fraction": round(s["overlap_fraction"], 4),
        "flush_bytes_per_step": round(
            (s["flush_bytes"] - base["flush_bytes"]) / max(steps, 1), 1),
    }


def _perf_fields(run_one):
    """`top_ops` / `bound` / `device_duty_cycle` for the per-mesh JSON line
    (same contract as bench.py): re-run the already-compiled step a few
    times under a silent traced session and join the roofline report, so
    the sweep shows WHERE each mesh size spends its step next to how fast
    it goes. SCALE_PERF=0 skips it; any failure degrades to no extra
    fields — the scaling line itself must never die here."""
    if os.environ.get("SCALE_PERF", "1") != "1":
        return {}
    try:
        from paddle_tpu import roofline

        def step():
            float(np.asarray(run_one()).ravel()[0])

        report = roofline.capture(step, steps=3)
        if not report:
            return {}
        out = {"top_ops": roofline.top_ops(report),
               "device_duty_cycle": report.get("device_duty_cycle")}
        hc = report.get("hlo_counts")
        if hc:
            out["hlo_instructions"] = hc["instructions"]
            out["hlo_fusions"] = hc["fusions"]
        attributed = [r for r in report["rows"]
                      if r["bound"] != "unattributed"]
        out["bound"] = (attributed[0]["bound"] if attributed
                        else "unattributed")
        # per-kernel scoreboard + Pallas conv coverage + input-bound
        # verdict (ISSUE 11), same columns as bench.py
        ke = report.get("kernel_efficiency")
        if ke:
            out["kernel_efficiency"] = ke[:5]
        if report.get("pallas_kernel_coverage") is not None:
            out["pallas_kernel_coverage"] = round(
                report["pallas_kernel_coverage"], 4)
        if report.get("input_bound") is not None:
            out["input_bound"] = report["input_bound"]
            if report.get("input_bound_remedy"):
                out["input_bound_remedy"] = report["input_bound_remedy"]
        try:
            # fleet fields (ISSUE 8): per-kind busbw for the mesh size
            # under test, cross-host skew, goodput — scaling regressions
            # show up here as busbw flatlining while devices grow
            from paddle_tpu import fleet
            bus = fleet.busbw_by_kind(report.get("collectives"))
            if bus:
                out["busbw"] = bus
            # overlap fields (ISSUE 9): exposed collective seconds and
            # the hidden fraction, per mesh size
            es = fleet.exposed_summary(report.get("collectives"))
            if es:
                out.update(es)
            snap = fleet.fleet_snapshot()
            out["fleet_skew"] = round(snap["step_skew"], 4)
            gp = fleet.goodput_report()
            if gp:
                out["goodput"] = round(gp["goodput_fraction"], 4)
        except Exception:  # noqa: BLE001 - fleet fields are best-effort
            pass
        return out
    except Exception as e:  # noqa: BLE001 - attribution is best-effort
        print(f"perf attribution skipped: {e}", file=sys.stderr)
        return {}


def main(argv):
    import jax
    # SCALE_PLATFORM=cpu forces the host platform for mechanism checks:
    # in TPU-attached terminals the JAX_PLATFORMS env var alone does not
    # override the accelerator plugin — only jax.config does
    plat = os.environ.get("SCALE_PLATFORM")
    if plat:
        jax.config.update("jax_platforms", plat)
    argv = list(argv)
    steps_per_call = None
    if "--steps-per-call" in argv:
        i = argv.index("--steps-per-call")
        try:
            steps_per_call = _parse_steps_per_call(argv[i + 1])
        except (IndexError, ValueError):
            raise SystemExit(
                "--steps-per-call needs an integer argument or 'auto'")
        del argv[i:i + 2]
    if steps_per_call is None:
        steps_per_call = int(os.environ.get("SCALE_STEPS_PER_CALL", "1"))
    sizes = sorted({int(a) for a in argv}) or sorted(
        {1, 2, len(jax.devices())} & set(range(1, len(jax.devices()) + 1)))
    too_big = [s for s in sizes if s > len(jax.devices())]
    if too_big:
        raise SystemExit(
            f"requested mesh sizes {too_big} exceed the "
            f"{len(jax.devices())} available devices")
    if os.environ.get("SCALE_MODEL") == "serving":
        # serving sweep: one line per mesh size carrying the serving
        # trajectory keys instead of samples_per_sec
        last = None
        for n in sizes:
            line = measure_serving(n)
            last = line
            print(json.dumps(line), flush=True)
        if last is not None:
            print(json.dumps({
                "metric": "serving_qps", "value": last["qps"],
                "unit": "requests/sec", "devices": last["devices"],
                "p99_ms": last["p99_ms"],
                "goodput_fraction": last["overload"]["goodput_fraction"],
            }))
        return
    results = {}
    for n in sizes:
        sps, peak_hbm, perf, k = measure(n, steps_per_call=steps_per_call)
        results[n] = sps
        base = results[min(results)]
        eff = sps / (base / min(results) * n)
        # `steps_per_call` is the K that actually ran (auto resolves
        # per mesh size); the summary line keeps the requested value
        line = {"devices": n,
                "samples_per_sec": round(sps, 2),
                "scaling_efficiency": round(eff, 4),
                "steps_per_call": k,
                "peak_hbm_bytes": peak_hbm}
        line.update(perf)
        print(json.dumps(line), flush=True)
    if len(results) > 1:
        top = max(results)
        base = results[min(results)]
        eff = results[top] / (base / min(results) * top)
        model_name = os.environ.get("SCALE_MODEL", "vgg16")
        print(json.dumps({
            "metric": f"{model_name}_dp_scaling_efficiency",
            "value": round(eff, 4), "unit": "fraction",
            "devices": top, "steps_per_call": steps_per_call,
            "vs_baseline": round(eff / 0.6089, 3),  # ref 60.89% @ 100 tr
        }))


if __name__ == "__main__":
    main(sys.argv[1:])
