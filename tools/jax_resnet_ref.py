"""Framework-independent ceiling probe: hand-rolled pure-JAX ResNet-50
training step (NHWC, bf16 compute, f32 master weights + momentum), same
batch/protocol as bench.py. Used to separate framework overhead from the
chip/XLA ceiling when tuning the flagship bench (VERDICT r2 weak #2)."""

import time

import jax
import jax.numpy as jnp
import numpy as np

BATCH = 768
STEPS = 20
WARMUP = 3


def conv(x, w, stride=1):
    return jax.lax.conv_general_dilated(
        x, w, window_strides=(stride, stride), padding="SAME",
        dimension_numbers=("NHWC", "HWIO", "NHWC"))


def bn(x, scale, bias):
    # training-mode batch stats in f32, like the framework's batch_norm
    xf = x.astype(jnp.float32)
    m = jnp.mean(xf, axis=(0, 1, 2))
    v = jnp.maximum(jnp.mean(jnp.square(xf), axis=(0, 1, 2)) - m * m, 0.0)
    y = (xf - m) * jax.lax.rsqrt(v + 1e-5) * scale + bias
    return y.astype(x.dtype)


CFG = [(3, 64, 256, 1), (4, 128, 512, 2), (6, 256, 1024, 2),
       (3, 512, 2048, 2)]


def init_params(rng):
    p = {}

    def cw(key, kh, kw, ci, co):
        k = rng.standard_normal((kh, kw, ci, co)).astype(np.float32)
        p[key] = k * np.sqrt(2.0 / (kh * kw * ci))

    def bnp(key, c):
        p[key + "/s"] = np.ones((c,), np.float32)
        p[key + "/b"] = np.zeros((c,), np.float32)

    cw("stem", 7, 7, 3, 64)
    bnp("stem_bn", 64)
    ci = 64
    for si, (n, mid, out, _stride) in enumerate(CFG):
        for bi in range(n):
            pre = f"s{si}b{bi}"
            cw(pre + "/c1", 1, 1, ci if bi == 0 else out, mid)
            cw(pre + "/c2", 3, 3, mid, mid)
            cw(pre + "/c3", 1, 1, mid, out)
            for j in (1, 2, 3):
                bnp(pre + f"/bn{j}", [mid, mid, out][j - 1])
            if bi == 0:
                cw(pre + "/proj", 1, 1, ci, out)
                bnp(pre + "/bnp", out)
        ci = out
    p["fc/w"] = rng.standard_normal((2048, 1000)).astype(np.float32) * 0.01
    p["fc/b"] = np.zeros((1000,), np.float32)
    return p


def forward(params, x):
    h = x.astype(jnp.bfloat16)
    h = conv(h, params["stem"].astype(jnp.bfloat16), 2)
    h = jax.nn.relu(bn(h, params["stem_bn/s"], params["stem_bn/b"]))
    h = jax.lax.reduce_window(h, -jnp.inf, jax.lax.max, (1, 3, 3, 1),
                              (1, 2, 2, 1), "SAME")
    for si, (n, mid, out, stride) in enumerate(CFG):
        for bi in range(n):
            pre = f"s{si}b{bi}"
            st = stride if bi == 0 else 1
            y = conv(h, params[pre + "/c1"].astype(jnp.bfloat16), st)
            y = jax.nn.relu(bn(y, params[pre + "/bn1/s"],
                               params[pre + "/bn1/b"]))
            y = conv(y, params[pre + "/c2"].astype(jnp.bfloat16), 1)
            y = jax.nn.relu(bn(y, params[pre + "/bn2/s"],
                               params[pre + "/bn2/b"]))
            y = conv(y, params[pre + "/c3"].astype(jnp.bfloat16), 1)
            y = bn(y, params[pre + "/bn3/s"], params[pre + "/bn3/b"])
            if bi == 0:
                h = conv(h, params[pre + "/proj"].astype(jnp.bfloat16), st)
                h = bn(h, params[pre + "/bnp/s"], params[pre + "/bnp/b"])
            h = jax.nn.relu(h + y)
    h = jnp.mean(h.astype(jnp.float32), axis=(1, 2))
    return h @ params["fc/w"] + params["fc/b"]


def loss_fn(params, x, y):
    logits = forward(params, x)
    logp = jax.nn.log_softmax(logits)
    return -jnp.mean(jnp.take_along_axis(logp, y, axis=-1))


@jax.jit
def step(params, mom, x, y):
    loss, g = jax.value_and_grad(loss_fn)(params, x, y)
    new_m = {k: 0.9 * mom[k] + g[k] for k in g}
    new_p = {k: params[k] - 0.1 * new_m[k] for k in params}
    return loss, new_p, new_m


def main():
    dev = jax.devices()[0]
    rng = np.random.default_rng(0)
    params = {k: jax.device_put(v, dev)
              for k, v in init_params(rng).items()}
    mom = {k: jax.device_put(np.zeros_like(np.asarray(v)), dev)
           for k, v in params.items()}
    x = jax.device_put(
        rng.standard_normal((BATCH, 224, 224, 3), dtype=np.float32), dev)
    y = jax.device_put(rng.integers(0, 1000, (BATCH, 1)).astype(np.int32),
                       dev)
    for _ in range(WARMUP):
        loss, params, mom = step(params, mom, x, y)
    float(np.asarray(loss))
    t0 = time.perf_counter()
    for _ in range(STEPS):
        loss, params, mom = step(params, mom, x, y)
    final = float(np.asarray(loss))
    dt = time.perf_counter() - t0
    img_s = BATCH * STEPS / dt
    mfu = img_s * 3 * 4.09e9 / 197e12
    print(f"pure-jax resnet50: {img_s:.0f} img/s  "
          f"({dt / STEPS * 1000:.0f} ms/step, mfu {mfu:.3f}, "
          f"loss {final:.3f})")


if __name__ == "__main__":
    main()
