#!/usr/bin/env python
"""Regression gate between two BENCH_*.json files (ISSUE 16 satellite).

The BENCH_rNN campaign tracks one headline metric per round plus a
`parsed` payload of secondary numbers (p50/p99 latency, MFU, goodput,
shed fraction, bucket hits...). Nothing gated those numbers: a round
could regress images/sec or p99 and the only trace would be a human
eyeballing two JSON files. This tool is the gate:

    python tools/bench_diff.py BENCH_r05.json BENCH_r06.json
    python tools/bench_diff.py old.json new.json --threshold 0.10
    python tools/bench_diff.py old.json new.json --json

It walks both `parsed` dicts (recursing into sub-dicts like
`overload`/`normal` phases), classifies each shared numeric key by
direction — higher-better (value, qps, *fraction that measures goodput,
MFU, hit counts) vs lower-better (latencies, shed/miss/eviction rates,
seconds) — and flags any metric whose relative change exceeds the
threshold in the losing direction. Exit status: 0 clean, 1 regressions
found, 2 usage/parse errors. Keys present in only one file are reported
as informational drift, not failures (benchmarks grow fields).
"""

from __future__ import annotations

import argparse
import json
import sys
from typing import Dict, List, Optional, Tuple

# direction vocabulary: a key matches the first rule whose substring it
# contains (checked in order) — explicit names first, suffix families
# after. "bucket_hits" style count dicts are compared per-key as
# higher-better (a bucket losing all its traffic is a distribution
# shift worth seeing).
LOWER_BETTER_MARKERS = (
    "p50_ms", "p99_ms", "latency", "_seconds", "seconds_", "wall_s",
    "shed_fraction", "miss", "eviction", "stall", "skew", "dropped",
    "timeout", "error", "exposed",
)
HIGHER_BETTER_MARKERS = (
    "value", "qps", "images_per_sec", "mfu", "tflops", "goodput",
    "hit", "coverage", "duty_cycle", "busbw", "overlap", "vs_baseline",
)


def direction(key: str) -> Optional[str]:
    """'higher' | 'lower' | None (uncompared) for one metric key."""
    k = key.lower()
    for marker in LOWER_BETTER_MARKERS:
        if marker in k:
            return "lower"
    for marker in HIGHER_BETTER_MARKERS:
        if marker in k:
            return "higher"
    return None


def _flatten(d: Dict, prefix: str = "") -> Dict[str, float]:
    """parsed dict -> {dotted.key: float} over numeric leaves."""
    out: Dict[str, float] = {}
    for k, v in d.items():
        path = f"{prefix}.{k}" if prefix else str(k)
        if isinstance(v, dict):
            out.update(_flatten(v, path))
        elif isinstance(v, bool):
            continue
        elif isinstance(v, (int, float)) and v is not None:
            out[path] = float(v)
    return out


def diff(old: Dict, new: Dict, threshold: float = 0.05) \
        -> Tuple[List[Dict], List[Dict], List[str]]:
    """-> (regressions, improvements, drift). Each entry: {key, old,
    new, change} with change as signed relative delta in the metric's
    natural direction (positive = better)."""
    old_flat = _flatten(old.get("parsed") or {})
    new_flat = _flatten(new.get("parsed") or {})
    regressions, improvements = [], []
    drift = sorted(set(old_flat) ^ set(new_flat))
    for key in sorted(set(old_flat) & set(new_flat)):
        sense = direction(key)
        if sense is None:
            continue
        a, b = old_flat[key], new_flat[key]
        if a == b:
            continue
        base = max(abs(a), 1e-12)
        rel = (b - a) / base
        gain = rel if sense == "higher" else -rel
        entry = {"key": key, "old": a, "new": b,
                 "direction": sense, "change": gain}
        if gain < -threshold:
            regressions.append(entry)
        elif gain > threshold:
            improvements.append(entry)
    regressions.sort(key=lambda e: e["change"])
    improvements.sort(key=lambda e: -e["change"])
    return regressions, improvements, drift


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        description="compare two BENCH_*.json files; nonzero exit on "
                    "regression beyond --threshold")
    ap.add_argument("old", help="baseline BENCH json")
    ap.add_argument("new", help="candidate BENCH json")
    ap.add_argument("--threshold", type=float, default=0.05,
                    help="relative regression tolerance (default 0.05 "
                         "= 5%%)")
    ap.add_argument("--json", action="store_true",
                    help="machine-readable single-line JSON output")
    args = ap.parse_args(argv)

    payloads = []
    for path in (args.old, args.new):
        try:
            with open(path) as f:
                payloads.append(json.load(f))
        except (OSError, json.JSONDecodeError) as e:
            print(f"bench_diff: cannot read {path}: {e}",
                  file=sys.stderr)
            return 2
    regressions, improvements, drift = diff(
        payloads[0], payloads[1], threshold=args.threshold)

    if args.json:
        print(json.dumps({
            "old": args.old, "new": args.new,
            "threshold": args.threshold, "regressions": regressions,
            "improvements": improvements, "drift": drift},
            sort_keys=True))
    else:
        for e in regressions:
            print(f"REGRESSION {e['key']}: {e['old']:g} -> {e['new']:g} "
                  f"({e['change']:+.1%}, {e['direction']}-is-better)")
        for e in improvements:
            print(f"improved   {e['key']}: {e['old']:g} -> {e['new']:g} "
                  f"({e['change']:+.1%})")
        for key in drift:
            print(f"drift      {key}: present in only one file")
        verdict = (f"{len(regressions)} regression"
                   f"{'' if len(regressions) == 1 else 's'} beyond "
                   f"{args.threshold:.0%}"
                   if regressions else "bench diff ok")
        print(verdict)
    return 1 if regressions else 0


if __name__ == "__main__":
    sys.exit(main())
