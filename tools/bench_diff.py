#!/usr/bin/env python
"""Regression gate between two BENCH_*.json files (ISSUE 16 satellite).

The BENCH_rNN campaign tracks one headline metric per round plus a
`parsed` payload of secondary numbers (p50/p99 latency, MFU, goodput,
shed fraction, bucket hits...). Nothing gated those numbers: a round
could regress images/sec or p99 and the only trace would be a human
eyeballing two JSON files. This tool is the gate:

    python tools/bench_diff.py BENCH_r05.json BENCH_r06.json
    python tools/bench_diff.py old.json new.json --threshold 0.10
    python tools/bench_diff.py old.json new.json --json
    python tools/bench_diff.py --history BENCH_HISTORY.jsonl

`--history` (ISSUE 17 satellite) gates the standing ledger bench.py
appends to instead of two hand-picked files: entries are grouped by
(mode, family) plus precision variant (amp_level / quant, so an O3 or
int8 line never gates against its f32 sibling), and within each group
the NEWEST entry is compared
against the per-key rolling MEDIAN of all prior entries with the same
direction-aware thresholds — the standing regression gate the BENCH_r*
campaign runs after every round. Groups with fewer than two entries are
skipped (nothing to compare against).

It walks both `parsed` dicts (recursing into sub-dicts like
`overload`/`normal` phases), classifies each shared numeric key by
direction — higher-better (value, qps, *fraction that measures goodput,
MFU, hit counts) vs lower-better (latencies, shed/miss/eviction rates,
seconds) — and flags any metric whose relative change exceeds the
threshold in the losing direction. Exit status: 0 clean, 1 regressions
found, 2 usage/parse errors. Keys present in only one file are reported
as informational drift, not failures (benchmarks grow fields).
"""

from __future__ import annotations

import argparse
import json
import sys
from typing import Dict, List, Optional, Tuple

# direction vocabulary: a key matches the first rule whose substring it
# contains (checked in order) — explicit names first, suffix families
# after. "bucket_hits" style count dicts are compared per-key as
# higher-better (a bucket losing all its traffic is a distribution
# shift worth seeing).
LOWER_BETTER_MARKERS = (
    "p50_ms", "p99_ms", "latency", "_seconds", "seconds_", "wall_s",
    "shed_fraction", "miss", "eviction", "stall", "skew", "dropped",
    "timeout", "error", "exposed", "overhead", "fallback",
)
HIGHER_BETTER_MARKERS = (
    "value", "qps", "images_per_sec", "mfu", "tflops", "goodput",
    "hit", "coverage", "duty_cycle", "busbw", "overlap", "vs_baseline",
)


def direction(key: str) -> Optional[str]:
    """'higher' | 'lower' | None (uncompared) for one metric key."""
    k = key.lower()
    for marker in LOWER_BETTER_MARKERS:
        if marker in k:
            return "lower"
    for marker in HIGHER_BETTER_MARKERS:
        if marker in k:
            return "higher"
    return None


def _flatten(d: Dict, prefix: str = "") -> Dict[str, float]:
    """parsed dict -> {dotted.key: float} over numeric leaves."""
    out: Dict[str, float] = {}
    for k, v in d.items():
        path = f"{prefix}.{k}" if prefix else str(k)
        if isinstance(v, dict):
            out.update(_flatten(v, path))
        elif isinstance(v, bool):
            continue
        elif isinstance(v, (int, float)) and v is not None:
            out[path] = float(v)
    return out


def diff(old: Dict, new: Dict, threshold: float = 0.05) \
        -> Tuple[List[Dict], List[Dict], List[str]]:
    """-> (regressions, improvements, drift). Each entry: {key, old,
    new, change} with change as signed relative delta in the metric's
    natural direction (positive = better)."""
    old_flat = _flatten(old.get("parsed") or {})
    new_flat = _flatten(new.get("parsed") or {})
    regressions, improvements = [], []
    drift = sorted(set(old_flat) ^ set(new_flat))
    for key in sorted(set(old_flat) & set(new_flat)):
        sense = direction(key)
        if sense is None:
            continue
        a, b = old_flat[key], new_flat[key]
        if a == b:
            continue
        base = max(abs(a), 1e-12)
        rel = (b - a) / base
        gain = rel if sense == "higher" else -rel
        entry = {"key": key, "old": a, "new": b,
                 "direction": sense, "change": gain}
        if gain < -threshold:
            regressions.append(entry)
        elif gain > threshold:
            improvements.append(entry)
    regressions.sort(key=lambda e: e["change"])
    improvements.sort(key=lambda e: -e["change"])
    return regressions, improvements, drift


# ledger metadata stamped by bench._append_history (or non-numeric):
# excluded from comparison so a sha change is not a "regression"
_HISTORY_META_KEYS = {"ts", "git_sha", "mode", "family", "metric",
                      "unit", "errors", "amp_level", "quant"}


def _median(vals: List[float]) -> float:
    vals = sorted(vals)
    k = len(vals) // 2
    return vals[k] if len(vals) % 2 else 0.5 * (vals[k - 1] + vals[k])


def _variant(e: Dict) -> str:
    """Precision-variant tag for grouping: an O3/int8 line is a different
    configuration, not a regression of the O2/f32 line it rides next to
    in the ledger (XLA:CPU int8 matmuls are *slower* than bf16, so mixing
    them in one group would flag every quantized run)."""
    tags = [str(t) for t in (e.get("amp_level"), e.get("quant")) if t]
    return "+".join(tags)


def history_diff(entries: List[Dict], threshold: float = 0.05) \
        -> Tuple[List[Dict], List[Tuple[str, str, int]]]:
    """-> (regressions, groups). Newest entry per (mode, family,
    precision-variant) vs the per-key median of that group's prior
    entries, direction-aware. Each regression entry adds 'group';
    `groups` lists (mode, family, n) for every group seen (n < 2 means
    skipped)."""
    by_group: Dict[Tuple[str, str], List[Dict]] = {}
    for e in entries:
        mode = str(e.get("mode", "?"))
        tag = _variant(e)
        if tag:
            mode = f"{mode}[{tag}]"
        key = (mode, str(e.get("family", "?")))
        by_group.setdefault(key, []).append(e)

    regressions: List[Dict] = []
    groups: List[Tuple[str, str, int]] = []
    for (mode, family), group in sorted(by_group.items()):
        groups.append((mode, family, len(group)))
        if len(group) < 2:
            continue
        newest = _flatten({k: v for k, v in group[-1].items()
                           if k not in _HISTORY_META_KEYS})
        prior_flat = [_flatten({k: v for k, v in e.items()
                                if k not in _HISTORY_META_KEYS})
                      for e in group[:-1]]
        for key in sorted(newest):
            sense = direction(key)
            if sense is None:
                continue
            priors = [p[key] for p in prior_flat if key in p]
            if not priors:
                continue
            med = _median(priors)
            b = newest[key]
            if med == b:
                continue
            base = max(abs(med), 1e-12)
            rel = (b - med) / base
            gain = rel if sense == "higher" else -rel
            if gain < -threshold:
                regressions.append({
                    "group": f"{mode}/{family}", "key": key,
                    "old": med, "new": b, "direction": sense,
                    "change": gain, "n_prior": len(priors)})
    regressions.sort(key=lambda e: e["change"])
    return regressions, groups


def _main_history(args) -> int:
    entries: List[Dict] = []
    try:
        with open(args.history) as f:
            for ln in f:
                ln = ln.strip()
                if ln:
                    entries.append(json.loads(ln))
    except (OSError, json.JSONDecodeError) as e:
        print(f"bench_diff: cannot read {args.history}: {e}",
              file=sys.stderr)
        return 2
    regressions, groups = history_diff(entries,
                                       threshold=args.threshold)
    if args.json:
        print(json.dumps({
            "history": args.history, "threshold": args.threshold,
            "groups": [{"mode": m, "family": f, "entries": n}
                       for m, f, n in groups],
            "regressions": regressions}, sort_keys=True))
    else:
        for e in regressions:
            print(f"REGRESSION {e['group']} {e['key']}: "
                  f"median {e['old']:g} -> {e['new']:g} "
                  f"({e['change']:+.1%}, {e['direction']}-is-better, "
                  f"n={e['n_prior']})")
        compared = sum(1 for _, _, n in groups if n >= 2)
        verdict = (f"{len(regressions)} regression"
                   f"{'' if len(regressions) == 1 else 's'} beyond "
                   f"{args.threshold:.0%}" if regressions
                   else f"bench history ok ({compared} group"
                        f"{'' if compared == 1 else 's'} compared)")
        print(verdict)
    return 1 if regressions else 0


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        description="compare two BENCH_*.json files (or gate the "
                    "BENCH_HISTORY.jsonl ledger with --history); "
                    "nonzero exit on regression beyond --threshold")
    ap.add_argument("old", nargs="?", help="baseline BENCH json")
    ap.add_argument("new", nargs="?", help="candidate BENCH json")
    ap.add_argument("--history", default=None,
                    help="BENCH_HISTORY.jsonl ledger: compare the newest "
                         "entry per (mode, family, precision variant) "
                         "against the median of prior entries")
    ap.add_argument("--threshold", type=float, default=0.05,
                    help="relative regression tolerance (default 0.05 "
                         "= 5%%)")
    ap.add_argument("--json", action="store_true",
                    help="machine-readable single-line JSON output")
    args = ap.parse_args(argv)

    if args.history is not None:
        return _main_history(args)
    if not args.old or not args.new:
        print("bench_diff: need OLD and NEW files (or --history LEDGER)",
              file=sys.stderr)
        return 2

    payloads = []
    for path in (args.old, args.new):
        try:
            with open(path) as f:
                payloads.append(json.load(f))
        except (OSError, json.JSONDecodeError) as e:
            print(f"bench_diff: cannot read {path}: {e}",
                  file=sys.stderr)
            return 2
    regressions, improvements, drift = diff(
        payloads[0], payloads[1], threshold=args.threshold)

    if args.json:
        print(json.dumps({
            "old": args.old, "new": args.new,
            "threshold": args.threshold, "regressions": regressions,
            "improvements": improvements, "drift": drift},
            sort_keys=True))
    else:
        for e in regressions:
            print(f"REGRESSION {e['key']}: {e['old']:g} -> {e['new']:g} "
                  f"({e['change']:+.1%}, {e['direction']}-is-better)")
        for e in improvements:
            print(f"improved   {e['key']}: {e['old']:g} -> {e['new']:g} "
                  f"({e['change']:+.1%})")
        for key in drift:
            print(f"drift      {key}: present in only one file")
        verdict = (f"{len(regressions)} regression"
                   f"{'' if len(regressions) == 1 else 's'} beyond "
                   f"{args.threshold:.0%}"
                   if regressions else "bench diff ok")
        print(verdict)
    return 1 if regressions else 0


if __name__ == "__main__":
    sys.exit(main())
