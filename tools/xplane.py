"""CLI over paddle_tpu.xplane: per-op time aggregation of jax.profiler
xplane traces (the tensorboard profile plugin in this image can't load
them — TF version skew — so this decodes the wire format directly).

Usage: python tools/xplane.py <trace_dir_or_file> [top_n]
       python tools/xplane.py --timeline <trace_dir_or_file> [max_events]
       python tools/xplane.py --collectives <trace_dir> [top_n]

The default view aggregates per-op totals; --timeline prints each line's
events in execution order (XLine.timestamp_ns anchor + XEvent.offset_ps),
the raw view behind the profiler's step-time waterfall; --collectives
prints the collective events only — kind, total ms and exposed ms (time
not hidden under concurrent compute), summed per kind at the end — the
stdlib view behind `python -m paddle_tpu fleet`.
"""

from __future__ import annotations

import glob
import importlib.util
import os
import sys

# load paddle_tpu/xplane.py directly by path: it is pure stdlib, and going
# through the package __init__ would drag in jax/the framework — this CLI
# must keep working in the stripped TF-skew environments it exists for
_xp_path = os.path.join(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))), "paddle_tpu", "xplane.py")
_spec = importlib.util.spec_from_file_location("_xplane_standalone",
                                               _xp_path)
_xplane = importlib.util.module_from_spec(_spec)
_spec.loader.exec_module(_xplane)
aggregate, category = _xplane.aggregate, _xplane.category


def timeline(target, limit):
    if os.path.isdir(target):
        records = _xplane.timeline_dir(target)
    else:
        records = [{"plane": pname, "line": line["name"],
                    "timestamp_ns": line["timestamp_ns"],
                    "events": line["events"]}
                   for pname, lines in _xplane.plane_events(target).items()
                   for line in lines]
    for rec in records:
        if not rec["events"]:
            continue
        print(f"-- {rec['plane']} / '{rec['line']}' "
              f"@ {rec['timestamp_ns']} ns")
        evs = sorted(rec["events"], key=lambda e: e[1])[:limit]
        base = evs[0][1]
        for name, off, dur in evs:
            print(f"   +{(off - base) / 1e6:12.3f} us  "
                  f"{dur / 1e6:10.3f} us  {name[:90]}")


def collectives(target, limit):
    evs = _xplane.collective_events_dir(target)
    if not evs:
        print("(no collective events)")
        return
    by_kind = {}
    rows = sorted(evs.items(), key=lambda kv: -kv[1]["total_ps"])
    print(f"{'total ms':>10s} {'exposed ms':>11s}  kind / event")
    for name, rec in rows[:limit]:
        print(f"{rec['total_ps'] / 1e9:10.3f} "
              f"{rec['exposed_ps'] / 1e9:11.3f}  "
              f"{rec['kind']:18s} {name[:80]}")
        agg = by_kind.setdefault(rec["kind"], [0, 0])
        agg[0] += rec["total_ps"]
        agg[1] += rec["exposed_ps"]
    for kind, (tot, exp) in sorted(by_kind.items(), key=lambda kv: -kv[1][0]):
        print(f"[kind] {kind:18s} {tot / 1e9:10.3f} ms total, "
              f"{exp / 1e9:.3f} ms exposed")


def main():
    args = sys.argv[1:]
    want_timeline = "--timeline" in args
    if want_timeline:
        args.remove("--timeline")
    want_collectives = "--collectives" in args
    if want_collectives:
        args.remove("--collectives")
    target = args[0] if args else "."
    top = int(args[1]) if len(args) > 1 else 30
    if want_collectives:
        collectives(target, top)
        return
    if want_timeline:
        timeline(target, top)
        return
    if os.path.isdir(target):
        paths = glob.glob(os.path.join(target, "**", "*.xplane.pb"),
                          recursive=True)
    else:
        paths = [target]
    for p in paths:
        print(f"== {p}")
        for pname, agg in aggregate(p).items():
            total = sum(agg.values())
            if not total:
                continue
            print(f"-- plane '{pname}': sum {total / 1e9:.2f} ms")
            cats = {}
            for name, ps in agg.items():
                c = category(name)
                cats[c] = cats.get(c, 0) + ps
            for c, ps in sorted(cats.items(), key=lambda kv: -kv[1])[:15]:
                print(f"   [cat] {ps / 1e9:10.2f} ms  {c}")
            for name, ps in sorted(agg.items(), key=lambda kv: -kv[1])[:top]:
                print(f"   {ps / 1e9:10.2f} ms  {name[:110]}")


if __name__ == "__main__":
    main()
