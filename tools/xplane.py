"""Minimal XSpace/XPlane (.xplane.pb) parser + per-op time aggregation.

jax.profiler.trace writes xplane protos; the tensorboard profile plugin in
this image can't load them (TF version skew), so this decodes the wire
format directly — only the fields needed to aggregate device-op time:

  XSpace.planes=1 / XPlane{name=2, lines=3, event_metadata=4}
  XLine{events=6} / XEvent{metadata_id=1, duration_ps=3}
  XEventMetadata map entry {key=1, value=2} / XEventMetadata{id=1, name=2}

Usage: python tools/xplane.py <trace_dir_or_file> [top_n]
"""

from __future__ import annotations

import glob
import os
import sys


def _varint(buf, i):
    r = 0
    shift = 0
    while True:
        b = buf[i]
        i += 1
        r |= (b & 0x7F) << shift
        if not b & 0x80:
            return r, i
        shift += 7


def fields(buf):
    """Yield (field_number, wire_type, value) over a serialized message."""
    i = 0
    n = len(buf)
    while i < n:
        key, i = _varint(buf, i)
        fno, wt = key >> 3, key & 7
        if wt == 0:
            v, i = _varint(buf, i)
        elif wt == 2:
            ln, i = _varint(buf, i)
            v = buf[i: i + ln]
            i += ln
        elif wt == 5:
            v = buf[i: i + 4]
            i += 4
        elif wt == 1:
            v = buf[i: i + 8]
            i += 8
        else:
            raise ValueError(f"wire type {wt}")
        yield fno, wt, v


def parse_plane(buf):
    name = ""
    lines = []
    meta = {}
    for fno, wt, v in fields(buf):
        if fno == 2 and wt == 2:
            name = v.decode("utf-8", "replace")
        elif fno == 3 and wt == 2:
            lines.append(v)
        elif fno == 4 and wt == 2:
            k = None
            mname = None
            for f2, w2, v2 in fields(v):
                if f2 == 1 and w2 == 0:
                    k = v2
                elif f2 == 2 and w2 == 2:
                    for f3, w3, v3 in fields(v2):
                        if f3 == 1 and w3 == 0 and k is None:
                            k = v3
                        elif f3 == 2 and w3 == 2:
                            mname = v3.decode("utf-8", "replace")
            if k is not None and mname is not None:
                meta[k] = mname
    return name, lines, meta


def aggregate(path):
    """-> {plane_name: {op_name: total_ps}}"""
    buf = open(path, "rb").read()
    out = {}
    for fno, wt, v in fields(buf):
        if fno != 1 or wt != 2:
            continue
        pname, lines, meta = parse_plane(v)
        agg = out.setdefault(pname, {})
        for line in lines:
            for f2, w2, v2 in fields(line):
                if f2 != 4 or w2 != 2:   # XLine.events
                    continue
                mid = dur = 0
                for f3, w3, v3 in fields(v2):
                    if f3 == 1 and w3 == 0:
                        mid = v3
                    elif f3 == 3 and w3 == 0:
                        dur = v3
                name = meta.get(mid, f"#{mid}")
                agg[name] = agg.get(name, 0) + dur
    return out


def category(name: str) -> str:
    """HLO instruction text -> coarse op kind ('%fusion.123 = ...' ->
    'fusion'; falls back to the leading token)."""
    tok = name.lstrip("%").split(" ", 1)[0]
    return tok.split(".")[0]


def main():
    target = sys.argv[1] if len(sys.argv) > 1 else "."
    top = int(sys.argv[2]) if len(sys.argv) > 2 else 30
    if os.path.isdir(target):
        paths = glob.glob(os.path.join(target, "**", "*.xplane.pb"),
                          recursive=True)
    else:
        paths = [target]
    for p in paths:
        print(f"== {p}")
        for pname, agg in aggregate(p).items():
            total = sum(agg.values())
            if not total:
                continue
            print(f"-- plane '{pname}': sum {total / 1e9:.2f} ms")
            cats = {}
            for name, ps in agg.items():
                c = category(name)
                cats[c] = cats.get(c, 0) + ps
            for c, ps in sorted(cats.items(), key=lambda kv: -kv[1])[:15]:
                print(f"   [cat] {ps / 1e9:10.2f} ms  {c}")
            for name, ps in sorted(agg.items(), key=lambda kv: -kv[1])[:top]:
                print(f"   {ps / 1e9:10.2f} ms  {name[:110]}")


if __name__ == "__main__":
    main()
