#!/usr/bin/env python
"""Op-coverage report: which registered ops does the test suite execute?

The suite itself enforces coverage continuously (tests/test_zz_op_coverage.py
reads the in-process record); this tool is the offline report form:

    rm -f /tmp/op_coverage.txt
    PADDLE_TPU_RECORD_OPS=/tmp/op_coverage.txt python -m pytest tests/ -q
    python tools/op_coverage.py /tmp/op_coverage.txt

(reference test discipline: tests/unittests has one OpTest file per op —
op_test.py:212; this report proves the same property for the new corpus.)
"""

import os
import sys

# force the host platform BEFORE importing jax/paddle_tpu: in a TPU-attached
# terminal a plain setdefault would leave the import initializing the (slow,
# tunneled) accelerator backend just to read a registry
os.environ["JAX_PLATFORMS"] = "cpu"

# `python tools/op_coverage.py` puts tools/ (not the repo root) on
# sys.path; make the tool runnable from anywhere
sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(
    __file__))))


def inventory():
    """Scriptable surface counts (VERDICT r4 #9: self-reported inventory
    must come from dir(), not prose): fluid layer functions, v2 layer
    wrappers, v2 networks composites, registered ops."""
    import inspect
    import paddle_tpu  # noqa: F401
    from paddle_tpu import layers as fluid_layers
    from paddle_tpu.ops import registry
    from paddle_tpu.v2 import layer as v2_layer
    from paddle_tpu.v2 import networks as v2_networks

    def _public_callables(mod):
        out = []
        for n in dir(mod):
            if n.startswith("_"):
                continue
            obj = getattr(mod, n)
            if callable(obj) and not inspect.ismodule(obj):
                out.append(n)
        return sorted(out)

    counts = {
        "fluid_layer_fns": len(_public_callables(fluid_layers)),
        "v2_layer_wrappers": len(_public_callables(v2_layer)),
        "v2_networks_composites": len(_public_callables(v2_networks)),
        "registered_ops": len(registry.registered_ops()),
    }
    import json
    print(json.dumps(counts))
    return 0


def probe_compat():
    """Report which registered op types the inspector's tensor-stat probe
    pass can instrument (inspector.probe_compatible): structural and
    no-kernel ops are excluded, everything else gets on-device stats."""
    import paddle_tpu  # noqa: F401  (registers all ops)
    from paddle_tpu import inspector
    from paddle_tpu.ops import registry

    registered = sorted(registry.registered_ops())
    compat = [t for t in registered if inspector.probe_compatible(t)]
    incompat = [t for t in registered if not inspector.probe_compatible(t)]
    print(f"registered ops   : {len(registered)}")
    print(f"probe-compatible : {len(compat)}")
    print(f"not probeable    : {len(incompat)}")
    for t in incompat:
        print(f"  NOT-PROBEABLE {t}")
    return 0


def main(path):
    if path == "--inventory":
        return inventory()
    if path == "--probe-compat":
        return probe_compat()
    if not os.path.exists(path):
        print(f"no record file at {path} — run the suite with "
              f"PADDLE_TPU_RECORD_OPS={path} first (see module docstring)")
        return 2
    import paddle_tpu  # noqa: F401  (registers all ops)
    from paddle_tpu.ops import registry

    executed = set()
    with open(path) as f:
        for line in f:
            executed.add(line.strip())
    registered = set(registry.registered_ops())
    # executor-level ops with no kernel of their own
    structural = {"feed", "fetch"}
    covered = sorted(registered & executed)
    missing = sorted(registered - executed - structural)
    grad_only = sorted(e for e in executed if e.endswith("_grad")
                       and e not in registered)
    print(f"registered ops : {len(registered)}")
    print(f"executed       : {len(covered)} "
          f"(+{len(grad_only)} auto-generated grad ops)")
    print(f"missing        : {len(missing)}")
    for m in missing:
        print(f"  UNCOVERED {m}")
    return 1 if missing else 0


if __name__ == "__main__":
    sys.exit(main(sys.argv[1] if len(sys.argv) > 1 else "/tmp/op_coverage.txt"))
