#!/usr/bin/env python
"""Registry-consistency lint (ISSUE 7 satellite): every op named in the
layout pass's AGNOSTIC_OPS/AWARE_OPS sets and in the fusion pass's
pattern tables must actually be registered in ops/registry.py. A typo in
one of those tables doesn't raise at runtime — the pattern just never
matches and the optimization silently turns off — so CI pins the sets
against the registry instead.

    python tools/check_registry.py        # exits 1 and lists offenders

Names ending in `_grad` are checked against their base op: grad kernels
are materialized lazily by registry.try_get, so only the forward
registration proves the name is real.

The collective-kind lint (ISSUE 8 satellite) pins xplane.COLLECTIVE_KINDS
the same way: every pattern must classify back to its own kind through
`collective_kind` (match order matters — a pattern shadowed by an earlier
kind silently misattributes), each busbw factor table entry must have a
kind and vice versa, and each kind's canonical HLO spelling must land in
the roofline waterfall's "collective" bucket — otherwise a new kind falls
into "(unattributed)" or the wrong waterfall bar without any test failing.

The sparse-table lint (ISSUE 10 satellite) pins sparse_ops.SPARSE_APPLY_OPS
against the optimizer lowerings, the executor's sparse-aware boundary set
and the fused-bucket types: a missing entry doesn't raise either — the
gradient silently densifies and the update goes O(table rows).

The Pallas-table lint (ISSUE 11 satellite) pins pallas_conv.KERNELS the
same way: orphan kernels, conv window kinds without a dispatch entry,
forward kernels missing their grad twin (the shared-gate/vjp contract),
and fallback reasons the gate produces but FALLBACK_REASONS omits.

The quant-table lint (ISSUE 20 satellite) pins quant.QUANT_OPS the same
way, both directions: every quantizable op must be registered AND its
lowering must consult the quant gate (else the op silently loses
quantization under O3), every lowering that routes through quant must be
in the table (else prequantize/preflight/roofline don't know it exists),
and the gates' produced fallback reasons must match FALLBACK_REASONS
exactly (an undeclared reason is an unlabelled quant_fallback_total
series; a declared-but-never-produced one is a dead counter label).

The infer-rules lint (ISSUE 12 satellite) pins the static analyzer's
shape-pass coverage: every registered op must resolve to exactly one
rule source (a hand-written analysis CHECKER, the registry's own
infer_shape, the jax.eval_shape fallback list, or the explicit
DYNAMIC_SHAPE_OPS allowlist) — a newly registered op with no rule makes
the analyzer silently blind to everything downstream of it. Orphan
entries in the analysis tables are flagged in the converse direction.

The serving lint (ISSUE 13 satellite) builds both shipped examples'
inference programs (transformer logits, DLRM probabilities), applies the
ServingEngine's own strip->prune->clone, and pins the result: every
surviving op must have a registered lowering and none may be a
training-only op (optimizer / `_grad` / fused-optimizer) — a leak here
means prune kept a training subgraph and serving would mutate weights.
"""

import sys


def check_tables():
    """[(table, name), ...] for every table entry with no registration."""
    from paddle_tpu.ops import fusion, layout, registry

    registered = set(registry.registered_ops())
    tables = {
        "layout.AWARE_OPS": layout.AWARE_OPS,
        "layout.AGNOSTIC_OPS": layout.AGNOSTIC_OPS,
        "fusion.CONV_OPS": fusion.CONV_OPS,
        "fusion.ACT_OPS": fusion.ACT_OPS,
        "fusion.CHAIN_OPS": fusion.CHAIN_OPS,
        "fusion.OPTIMIZER_BUCKET_OPS": fusion.OPTIMIZER_BUCKET_OPS,
        "fusion.FUSED_OP_TYPES": fusion.FUSED_OP_TYPES,
    }
    problems = []
    for tname in sorted(tables):
        for name in sorted(tables[tname]):
            base = name[:-5] if name.endswith("_grad") else name
            if base not in registered:
                problems.append((tname, name))
    return problems


def check_collective_kinds():
    """[(where, message), ...] consistency problems in the collective
    classification tables (xplane.COLLECTIVE_KINDS / _BUSBW_FACTOR) and
    their agreement with the roofline waterfall's bucket patterns."""
    from paddle_tpu import roofline, xplane

    problems = []
    kinds = [k for k, _ in xplane.COLLECTIVE_KINDS]
    if len(set(kinds)) != len(kinds):
        problems.append(("xplane.COLLECTIVE_KINDS", "duplicate kind"))
    for kind, pats in xplane.COLLECTIVE_KINDS:
        for pat in pats:
            got = xplane.collective_kind(pat)
            if got != kind:
                problems.append((
                    "xplane.COLLECTIVE_KINDS",
                    f"pattern '{pat}' of kind '{kind}' classifies as "
                    f"'{got}' — match order shadows it"))
        # the canonical (first) pattern must also land in the waterfall's
        # collective bucket, or fleet and waterfall disagree on the split
        if roofline._bucket(pats[0] + ".1") != "collective":
            problems.append((
                "roofline._COLLECTIVE_PAT",
                f"kind '{kind}' spelling '{pats[0]}' not bucketed as "
                f"'collective' by the waterfall"))
        if xplane.busbw_factor(kind, 4) <= 0:
            problems.append((
                "xplane._BUSBW_FACTOR",
                f"kind '{kind}' has no busbw factor — its busbw column "
                f"would read as raw algbw"))
    for kind in xplane._BUSBW_FACTOR:
        if kind not in kinds:
            problems.append((
                "xplane._BUSBW_FACTOR",
                f"factor for unknown kind '{kind}'"))
    return problems


def check_jit_sites():
    """[(where, message), ...] — executor.py must funnel every compile
    through the single `Executor._jit_compile` jit call site (ISSUE 9):
    that is where the overlap pass's compiler_options (latency-hiding
    scheduler, async collectives) are threaded, so a new direct call
    site would silently compile without them. The module-level `@jax.jit`
    decorator (no parenthesis) is the one sanctioned exception."""
    import inspect

    from paddle_tpu import executor

    problems = []
    src = inspect.getsource(executor)
    sites = src.count("jax.jit(")
    if sites != 1:
        problems.append((
            "executor.jax.jit",
            f"{sites} direct jit call sites in executor.py (expected "
            f"exactly 1, inside _jit_compile) — a new site skips the "
            f"overlap compiler_options threading"))
    helper = getattr(executor.Executor, "_jit_compile", None)
    if helper is None:
        problems.append(("executor._jit_compile",
                         "Executor._jit_compile helper is missing"))
    else:
        hsrc = inspect.getsource(helper)
        if "jax.jit(" not in hsrc:
            problems.append((
                "executor._jit_compile",
                "the single jit call site is not inside _jit_compile"))
        if "compiler_options(" not in hsrc:
            problems.append((
                "executor._jit_compile",
                "_jit_compile does not thread overlap.compiler_options"))
    return problems


def check_sparse_table():
    """[(where, message), ...] — pin sparse_ops.SPARSE_APPLY_OPS (ISSUE 10)
    against the three layers that must agree on it: every listed optimizer
    needs a `<op>_apply` scatter kernel in ops/sparse_ops.py AND a
    SelectedRows branch in ops/optimizer_ops.py that calls it, every
    listed op (plus its fused_sparse_ bucket variant) must sit in
    executor._SPARSE_AWARE_OPS so the sparse boundary doesn't densify its
    Grad input first, and the fused variants must be registered +
    FUSED_OP_TYPES-listed. The converse holds too: a `*_apply` kernel for
    an op missing from SPARSE_APPLY_OPS silently never runs — `sum` (grad
    accumulation) is the one sparse-aware op with no apply kernel."""
    import inspect

    from paddle_tpu import executor
    from paddle_tpu.ops import fusion, optimizer_ops, registry, sparse_ops

    problems = []
    registered = set(registry.registered_ops())
    opt_src = inspect.getsource(optimizer_ops)
    for t in sparse_ops.SPARSE_APPLY_OPS:
        if t not in registered:
            problems.append(("sparse_ops.SPARSE_APPLY_OPS",
                             f"'{t}' is not registered in ops/registry.py"))
        if not callable(getattr(sparse_ops, t + "_apply", None)):
            problems.append(("sparse_ops.SPARSE_APPLY_OPS",
                             f"'{t}' has no {t}_apply scatter kernel in "
                             f"ops/sparse_ops.py"))
        if f"sparse_ops.{t}_apply" not in opt_src:
            problems.append((
                "optimizer_ops", f"'{t}' lowering never calls "
                f"sparse_ops.{t}_apply — its SelectedRows branch is gone "
                f"and the boundary would densify silently"))
        for name in (t, "fused_sparse_" + t):
            if name not in executor._SPARSE_AWARE_OPS:
                problems.append((
                    "executor._SPARSE_AWARE_OPS",
                    f"'{name}' missing — the sparse boundary densifies "
                    f"its Grad input before the scatter kernel sees it"))
        if "fused_sparse_" + t not in fusion.FUSED_OP_TYPES:
            problems.append((
                "fusion.FUSED_OP_TYPES",
                f"'fused_sparse_{t}' missing — its bucket op would fail "
                f"the registration lint"))
    for name in dir(sparse_ops):
        if name.endswith("_apply") and callable(getattr(sparse_ops, name)):
            op = name[:-len("_apply")]
            if op not in sparse_ops.SPARSE_APPLY_OPS:
                problems.append((
                    "sparse_ops.SPARSE_APPLY_OPS",
                    f"kernel '{name}' exists but '{op}' is not listed — "
                    f"the scatter path silently never runs"))
    if "sum" not in executor._SPARSE_AWARE_OPS:
        problems.append((
            "executor._SPARSE_AWARE_OPS",
            "'sum' missing — SelectedRows grad accumulation densifies"))
    return problems


def check_emb_cache():
    """[(where, message), ...] — pin parallel/emb_cache.CACHE_AWARE_OPS
    (ISSUE 14) against the layers that make slot remapping sound. The
    cache swaps a [rows, dim] table for a [cache_rows, dim] slab and
    remaps feed ids to slots, so exactly two op families may touch a
    cached table: the lookup pair (gathers by the remapped ids) and the
    SelectedRows scatter-apply optimizers (their rows ARE the remapped
    ids). Drift in either direction corrupts silently: a SPARSE_APPLY_OPS
    member missing from CACHE_AWARE_OPS makes enable() reject valid
    programs using that optimizer, while a CACHE_AWARE_OPS member that is
    NOT sparse-aware in the executor densifies its Grad — and a dense
    update writes EVERY slot, including stale tenants of other rows."""
    import inspect

    from paddle_tpu import executor
    from paddle_tpu.ops import sparse_ops
    from paddle_tpu.parallel import emb_cache

    problems = []
    aware = emb_cache.CACHE_AWARE_OPS
    for name in ("lookup_table", "lookup_table_grad"):
        if name not in aware:
            problems.append((
                "emb_cache.CACHE_AWARE_OPS",
                f"'{name}' missing — enable() would refuse every program "
                f"containing the op the cache exists to serve"))
    scatter = set()
    for t in sparse_ops.SPARSE_APPLY_OPS:
        for name in (t, "fused_sparse_" + t):
            scatter.add(name)
            if name not in aware:
                problems.append((
                    "emb_cache.CACHE_AWARE_OPS",
                    f"'{name}' missing — enable() rejects any cached "
                    f"table trained with that optimizer even though its "
                    f"SelectedRows rows are exactly the remapped slots"))
            if name not in executor._SPARSE_AWARE_OPS:
                problems.append((
                    "executor._SPARSE_AWARE_OPS",
                    f"'{name}' missing — under the hot-row cache its "
                    f"densified Grad would update every cache slot, "
                    f"silently corrupting rows resident for other ids"))
    for name in sorted(aware - scatter
                       - {"lookup_table", "lookup_table_grad"}):
        problems.append((
            "emb_cache.CACHE_AWARE_OPS",
            f"'{name}' is listed but is neither the lookup pair nor a "
            f"SPARSE_APPLY_OPS scatter op — no slot-remap semantics "
            f"justify letting it touch a cache slab"))
    dsrc = inspect.getsource(emb_cache._discover)
    if "CACHE_AWARE_OPS" not in dsrc:
        problems.append((
            "emb_cache._discover",
            "table discovery no longer validates referencing ops against "
            "CACHE_AWARE_OPS — an op with no remap path could index the "
            "slab with global row ids"))
    return problems


def check_pallas_table():
    """[(where, message), ...] — pin pallas_conv.KERNELS (ISSUE 11)
    against ops/registry.py and fusion.CONV_OPS. Three silent failure
    modes: an orphan kernel (dispatched for an op that isn't registered,
    or not in the fusion window table — the kernel never runs), a
    registered conv op missing from KERNELS (it silently keeps the lax
    path), and a fallback reason produced by the gate but absent from
    FALLBACK_REASONS (an unlabelled counter series). The forward/grad
    pairing is load-bearing, not stylistic: the generated grad path
    vjp's the forward lowering and pallas_call is not differentiable, so
    every dispatched forward MUST have a dispatched grad (and vice
    versa) sharing the same gate."""
    import inspect
    import re

    from paddle_tpu.ops import fusion, pallas_conv, registry

    problems = []
    registered = set(registry.registered_ops())
    fwd_keys = {k for k in pallas_conv.KERNELS if not k.endswith("_grad")}
    grad_keys = set(pallas_conv.KERNELS) - fwd_keys
    for name in sorted(pallas_conv.KERNELS):
        base = name[:-5] if name.endswith("_grad") else name
        if base not in registered:
            problems.append((
                "pallas_conv.KERNELS",
                f"'{name}' dispatched but '{base}' is not registered in "
                f"ops/registry.py — orphan kernel"))
        for fn in pallas_conv.KERNELS[name]:
            if not callable(fn):
                problems.append(("pallas_conv.KERNELS",
                                 f"'{name}' lists a non-callable kernel"))
    for name in sorted(fwd_keys):
        if name not in fusion.CONV_OPS:
            problems.append((
                "pallas_conv.KERNELS",
                f"forward '{name}' is not a fusion.CONV_OPS window kind — "
                f"the conv_bn_act window would never see its kernel"))
        if name + "_grad" not in grad_keys:
            problems.append((
                "pallas_conv.KERNELS",
                f"'{name}' has no '{name}_grad' dispatch — the generic "
                f"vjp would re-trace a non-differentiable pallas_call"))
    for name in sorted(fusion.CONV_OPS):
        if name not in fwd_keys:
            problems.append((
                "pallas_conv.KERNELS",
                f"fusion.CONV_OPS '{name}' has no Pallas dispatch entry — "
                f"it silently keeps the lax path"))
    for name in sorted(grad_keys):
        if name[:-5] not in fwd_keys:
            problems.append((
                "pallas_conv.KERNELS",
                f"grad '{name}' has no forward dispatch — the gate "
                f"predicate can't be shared"))
    # every reason the gate can return must be declared, and vice versa
    src = inspect.getsource(pallas_conv.ineligible)
    produced = set(re.findall(r'return "([a-z_]+)"', src))
    for reason in sorted(produced - pallas_conv.FALLBACK_REASONS):
        problems.append((
            "pallas_conv.FALLBACK_REASONS",
            f"gate returns '{reason}' but it is not declared — an "
            f"unlabelled pallas_fallback_total series"))
    for reason in sorted(pallas_conv.FALLBACK_REASONS - produced):
        problems.append((
            "pallas_conv.FALLBACK_REASONS",
            f"declared reason '{reason}' is never produced by the gate — "
            f"dead counter label"))
    return problems


def check_quant_table():
    """[(where, message), ...] — pin quant.QUANT_OPS (ISSUE 20) against
    ops/registry.py, the lowering sources, and the fallback-reason
    vocabulary, both directions (module docstring lists the silent
    failure modes). A lowering "consults the gate" when its source (or,
    one delegation deep, a `_name(ctx, op_, ins)` callee's source —
    depthwise_conv2d delegates to _conv2d) references the quant routing
    surface: ineligible_* / qmatmul / qconv2d."""
    import inspect
    import re

    from paddle_tpu import quant
    from paddle_tpu.ops import registry

    _ROUTE = re.compile(r"quant\.(ineligible_matmul|ineligible_conv|"
                        r"qmatmul|qconv2d)\(")

    def _consults_gate(fn, depth=1):
        try:
            src = inspect.getsource(fn)
        except (OSError, TypeError):
            return False
        if _ROUTE.search(src):
            return True
        if depth <= 0:
            return False
        mod = inspect.getmodule(fn)
        return any(
            callable(getattr(mod, callee, None)) and
            _consults_gate(getattr(mod, callee), depth - 1)
            for callee in re.findall(r"\b(_[a-z0-9_]+)\(ctx, op_, ins\)",
                                     src))

    problems = []
    registered = set(registry.registered_ops())
    for op_type, entry in sorted(quant.QUANT_OPS.items()):
        if op_type not in registered:
            problems.append((
                "quant.QUANT_OPS",
                f"'{op_type}' is quantizable but not registered in "
                f"ops/registry.py — the route can never run"))
            continue
        if not callable(getattr(quant, entry, None)):
            problems.append((
                "quant.QUANT_OPS",
                f"'{op_type}' names entry point '{entry}' which is not "
                f"a callable in quant.py"))
        lower = registry.get(op_type).lower
        if lower is None or not _consults_gate(lower):
            problems.append((
                "quant.QUANT_OPS",
                f"'{op_type}' lowering never consults the quant gate — "
                f"the op silently loses quantization under O3"))
    for op_type in sorted(registered - set(quant.QUANT_OPS)):
        lower = registry.get(op_type).lower
        if lower is None:
            continue
        try:
            src = inspect.getsource(lower)
        except (OSError, TypeError):
            continue
        if _ROUTE.search(src):
            problems.append((
                "quant.QUANT_OPS",
                f"'{op_type}' lowering routes through quant but is not "
                f"in QUANT_OPS — prequantize/preflight/roofline are "
                f"blind to it"))
    produced = set()
    for gate in (quant.ineligible_matmul, quant.ineligible_conv):
        produced |= set(re.findall(r'return "([a-z_]+)"',
                                   inspect.getsource(gate)))
    for reason in sorted(produced - quant.FALLBACK_REASONS):
        problems.append((
            "quant.FALLBACK_REASONS",
            f"a gate returns '{reason}' but it is not declared — an "
            f"unlabelled quant_fallback_total series"))
    for reason in sorted(quant.FALLBACK_REASONS - produced):
        problems.append((
            "quant.FALLBACK_REASONS",
            f"declared reason '{reason}' is never produced by a gate — "
            f"dead counter label"))
    return problems


def check_infer_rules():
    """[(where, message), ...] — pin the static analyzer's shape-pass
    coverage (ISSUE 12) against ops/registry.py. Every registered op
    must be covered by one of analysis/infer.py's rule sources
    (`rule_kind` != None); an uncovered op makes the shapes pass mark
    all downstream shapes unknown without any test noticing. Conversely,
    names in the analysis tables that aren't registered are typos: the
    rule silently never fires. Overlap between the explicit tables is
    flagged too — precedence would hide one of the entries."""
    from paddle_tpu.analysis import infer
    from paddle_tpu.ops import registry

    problems = []
    registered = set(registry.registered_ops())
    for t in sorted(registered):
        if infer.rule_kind(t) is None:
            problems.append((
                "analysis.infer",
                f"registered op '{t}' has no shape rule: add a CHECKER, "
                f"a registry infer_shape, or list it in EVAL_SHAPE_OPS / "
                f"DYNAMIC_SHAPE_OPS"))
    tables = {
        "analysis.DYNAMIC_SHAPE_OPS": infer.DYNAMIC_SHAPE_OPS,
        "analysis.EVAL_SHAPE_OPS": infer.EVAL_SHAPE_OPS,
        "analysis.CHECKERS": set(infer.CHECKERS),
    }
    for tname in sorted(tables):
        for name in sorted(tables[tname]):
            base = name[:-5] if name.endswith("_grad") else name
            if base not in registered:
                problems.append((
                    tname, f"'{name}' is not registered in "
                           f"ops/registry.py — orphan rule entry"))
    for a in sorted(tables):
        for b in sorted(tables):
            if a >= b:
                continue
            for name in sorted(tables[a] & tables[b]):
                problems.append((
                    a, f"'{name}' also listed in {b} — rule-source "
                       f"precedence hides one of them"))
    return problems


def check_serving_programs():
    """[(where, message), ...] — pin the two shipped inference programs
    (ISSUE 13) against the registry and the serving admission gate. Each
    example's build_programs() declares its serving surface
    (infer_feeds/infer_fetches); after the same strip->prune->clone the
    ServingEngine applies, every surviving op must have a registered
    lowering (an unregistered op only fails at first compile, long after
    model export) and none may be training-only: an optimizer/grad op
    leaking into a pruned program means prune kept a training subgraph
    alive and every serve call would silently mutate the weights."""
    import os

    problems = []
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    if repo not in sys.path:
        sys.path.insert(0, repo)
    import importlib.util

    from paddle_tpu import io as io_mod
    from paddle_tpu import serving
    from paddle_tpu.framework import unique_name
    from paddle_tpu.ops import registry

    registered = set(registry.registered_ops())
    examples = {
        "transformer_long_context": dict(seqlen=8, vocab=32),
        "criteo_dlrm": dict(rows=64, dim=4, slots=3),
    }
    for name, tiny in sorted(examples.items()):
        path = os.path.join(repo, "examples", "fluid",
                            f"train_{name}.py")
        spec = importlib.util.spec_from_file_location(
            f"_lint_{name}", path)
        mod = importlib.util.module_from_spec(spec)
        try:
            spec.loader.exec_module(mod)
            with unique_name.guard():
                progs = mod.build_programs(**tiny)
        except Exception as e:  # noqa: BLE001 - a broken example IS a finding
            problems.append((f"examples.{name}",
                             f"build_programs failed: {e}"))
            continue
        feeds = progs.get("infer_feeds")
        fetches = progs.get("infer_fetches")
        if not feeds or not fetches:
            problems.append((
                f"examples.{name}",
                "build_programs declares no infer_feeds/infer_fetches — "
                "the example has no serving surface"))
            continue
        pruned = (io_mod._strip_training_ops(progs["main"])
                  .prune(feeds, fetches).clone(for_test=True))
        for op in pruned.global_block().ops:
            role = op.desc.attrs.get("op_role")
            if serving.is_training_only_op(op.type, role):
                problems.append((
                    f"examples.{name}",
                    f"training-only op '{op.type}' (role={role!r}) "
                    f"survived the inference prune — serving it would "
                    f"mutate weights per request"))
            if op.type not in registered:
                problems.append((
                    f"examples.{name}",
                    f"pruned inference program contains '{op.type}' with "
                    f"no registered lowering — first serve compile would "
                    f"fail after export"))
    return problems


def check_planner_roles():
    """[(where, msg)] pinning the sharding planner's vocabulary (ISSUE 15
    satellite) — one data x fsdp x tp vocabulary, no drift:

      * every op the classifier tables name (OP_INPUT_ROLES keys,
        TRANSPARENT_OPS, ATTENTION_OPS, HEAD_OPS, MATMUL_OPS) is
        registered — a typo'd op never raises, the rule just silently
        stops matching;
      * SPEC_ROLES == ROLES in both directions: a role the spec table
        distinguishes but no classifier rule produces is dead code, and
        a classifier role the spec table doesn't know silently falls
        into the replicated default;
      * embedding.py agrees with the planner's `embedding` role: its
        SpecLayout IS the planner's class (re-export, not a copy) and
        shard_table's written spec for a default-axes 2-D table matches
        `role_spec("embedding", 2)` — the second vocabulary staying gone.
    """
    from paddle_tpu.ops import registry
    from paddle_tpu.parallel import embedding, planner

    registered = set(registry.registered_ops())
    problems = []

    tables = {
        "planner.OP_INPUT_ROLES":
            sorted({op for (op, _slot) in planner.OP_INPUT_ROLES}),
        "planner.TRANSPARENT_OPS": sorted(planner.TRANSPARENT_OPS),
        "planner.ATTENTION_OPS": sorted(planner.ATTENTION_OPS),
        "planner.HEAD_OPS": sorted(planner.HEAD_OPS),
        "planner.MATMUL_OPS": sorted(planner.MATMUL_OPS),
    }
    for tname in sorted(tables):
        for name in tables[tname]:
            base = name[:-5] if name.endswith("_grad") else name
            if base not in registered:
                problems.append(
                    (tname, f"names op '{name}', which is not registered "
                            f"in ops/registry.py"))

    for role in sorted(planner.SPEC_ROLES - planner.ROLES):
        problems.append(
            ("planner.SPEC_ROLES",
             f"role '{role}' has a spec but no classifier rule produces "
             f"it (not in OP_INPUT_ROLES values or WALK_ROLES)"))
    for role in sorted(planner.ROLES - planner.SPEC_ROLES):
        problems.append(
            ("planner.ROLES",
             f"classifier role '{role}' is missing from SPEC_ROLES — "
             f"role_spec silently replicates it"))

    if embedding.SpecLayout is not planner.SpecLayout:
        problems.append(
            ("embedding.SpecLayout",
             "is not planner.SpecLayout — a second spec vocabulary "
             "crept back"))
    layout = planner.SpecLayout()
    if tuple(layout.embeddings()) != tuple(layout.role_spec("embedding", 2)):
        problems.append(
            ("embedding role",
             f"SpecLayout.embeddings() {layout.embeddings()} != "
             f"role_spec('embedding', 2) "
             f"{layout.role_spec('embedding', 2)}"))
    # shard_table writes what the planner would: synthesize a program
    # with one 2-D table and compare channels
    import paddle_tpu as pd
    from paddle_tpu.framework import unique_name
    with unique_name.guard():
        prog = pd.Program()
        start = pd.Program()
        with pd.program_guard(prog, start):
            import paddle_tpu.layers as pd_layers
            ids = pd_layers.data(name="_lint_ids", shape=[1], dtype="int64")
            pd_layers.embedding(input=ids, size=[16, 4])
        tables = embedding.shard_embeddings(
            prog, mesh=None, layout=layout,
            axis=(layout.fsdp_axis, layout.tensor_axis))
        for t in tables:
            wrote = tuple((prog._param_shardings or {}).get(t) or ())
            want = tuple(layout.role_spec("embedding", 2))
            if wrote != want:
                problems.append(
                    ("embedding.shard_table",
                     f"wrote spec {wrote} for '{t}' but the planner's "
                     f"embedding role says {want}"))
    return problems


def check_metric_names():
    """[(where, message), ...] — pin every telemetry metric family
    created anywhere in paddle_tpu/ against telemetry.METRIC_CATALOG
    (ISSUE 16 satellite), both directions. A mistyped metric name or a
    drifted label set never raises at runtime: the emitter happily
    creates a new family, and the reader (read_gauge / fleet.py /
    dashboards) silently gets None forever. The scan is AST-based
    (literal first arguments to counter()/gauge()/histogram() calls);
    dynamically-named families (the roofline gauge loop, the executor's
    program-attached side-fetch marks, multihost's f-string histograms)
    carry `dynamic=True` catalog entries, which exempts them from the
    needs-an-emitter direction. Reader call sites with literal names
    (read_gauge/read_histogram/read_series/histogram_quantile) are
    checked too: the read helpers return None on a label-set mismatch,
    so a reader asking for labels the emitter doesn't write is exactly
    the silent-drift bug this lint exists to catch."""
    import ast
    import os

    from paddle_tpu import telemetry

    catalog = telemetry.METRIC_CATALOG
    problems = []

    def _literal_labels(node):
        """A labels= AST node -> tuple of label names, or None when it
        is not a literal sequence of string constants."""
        if node is None:
            return ()
        if isinstance(node, (ast.Tuple, ast.List)):
            out = []
            for el in node.elts:
                if isinstance(el, ast.Constant) and isinstance(el.value,
                                                               str):
                    out.append(el.value)
                else:
                    return None
            return tuple(out)
        return None

    root = os.path.join(
        os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
        "paddle_tpu")
    emitters = {}   # name -> list of (kind, labels-or-None, where)
    readers = []    # (fn, name, label-names-or-None, where)
    read_kinds = {"read_gauge": ("gauge",),
                  "read_histogram": ("histogram",),
                  "histogram_quantile": ("histogram",),
                  "read_series": ("counter", "gauge")}
    for dirpath, _dirs, files in os.walk(root):
        for fname in sorted(files):
            if not fname.endswith(".py"):
                continue
            path = os.path.join(dirpath, fname)
            rel = os.path.relpath(path, os.path.dirname(root))
            with open(path) as f:
                try:
                    tree = ast.parse(f.read())
                except SyntaxError as e:
                    problems.append((rel, f"unparseable: {e}"))
                    continue
            for node in ast.walk(tree):
                if not isinstance(node, ast.Call):
                    continue
                fn = node.func
                attr = (fn.attr if isinstance(fn, ast.Attribute)
                        else fn.id if isinstance(fn, ast.Name) else None)
                if attr is None or not node.args:
                    continue
                first = node.args[0]
                name = (first.value
                        if isinstance(first, ast.Constant)
                        and isinstance(first.value, str) else None)
                where = f"{rel}:{node.lineno}"
                if attr in ("counter", "gauge", "histogram"):
                    if name is None:
                        continue  # dynamic name: catalog covers it
                    labels_node = None
                    for kw in node.keywords:
                        if kw.arg == "labels":
                            labels_node = kw.value
                    if labels_node is None and len(node.args) >= 3:
                        labels_node = node.args[2]
                    emitters.setdefault(name, []).append(
                        (attr, _literal_labels(labels_node), where))
                elif attr in read_kinds and name is not None:
                    # keyword args on the read helpers ARE label names;
                    # a **dynamic expansion (arg=None) is unverifiable
                    labelnames = []
                    for kw in node.keywords:
                        if kw.arg is None:
                            labelnames = None
                            break
                        labelnames.append(kw.arg)
                    readers.append((attr, name,
                                    None if labelnames is None
                                    else tuple(labelnames), where))

    # direction 1: every literal emitter must match the catalog
    for name in sorted(emitters):
        entry = catalog.get(name)
        for kind, labels, where in emitters[name]:
            if entry is None:
                problems.append((
                    where, f"metric '{name}' ({kind}) is not in "
                           f"telemetry.METRIC_CATALOG — add it or fix "
                           f"the typo"))
                continue
            if kind != entry["kind"]:
                problems.append((
                    where, f"metric '{name}' created as {kind} but "
                           f"cataloged as {entry['kind']}"))
            if labels is not None and set(labels) != set(entry["labels"]):
                problems.append((
                    where, f"metric '{name}' created with labels "
                           f"{sorted(labels)} but cataloged with "
                           f"{sorted(entry['labels'])} — label-set "
                           f"drift"))

    # direction 2: every non-dynamic catalog entry needs an emitter
    for name in sorted(catalog):
        if catalog[name].get("dynamic"):
            continue
        if name not in emitters:
            problems.append((
                "telemetry.METRIC_CATALOG",
                f"'{name}' is cataloged but no counter/gauge/histogram "
                f"call site in paddle_tpu/ creates it — dead entry or "
                f"renamed emitter"))

    # readers: the silent-None direction
    for fn, name, labelnames, where in readers:
        entry = catalog.get(name)
        if entry is None:
            problems.append((
                where, f"{fn}('{name}') reads a metric that is not in "
                       f"the catalog — returns None forever"))
            continue
        if entry["kind"] not in read_kinds[fn]:
            problems.append((
                where, f"{fn}('{name}') reads a {entry['kind']} family "
                       f"— kind mismatch returns None"))
        if fn != "read_series" and labelnames is not None \
                and set(labelnames) != set(entry["labels"]):
            problems.append((
                where, f"{fn}('{name}') passes labels "
                       f"{sorted(labelnames)} but the family is labeled "
                       f"{sorted(entry['labels'])} — the read helper "
                       f"returns None on this mismatch"))
    return problems


def check_alert_rules():
    """[(where, message), ...] — pin sentinel.ALERT_CATALOG against
    telemetry.METRIC_CATALOG (ISSUE 17 satellite), the same
    both-directions discipline as check_metric_names. A rule watching a
    mistyped metric never raises: `Sentinel.poll` reads None forever and
    the rule silently never fires — exactly the drift this catches. Also
    pins the rule schema (direction/severity/reducer vocabularies,
    positive z, non-negative cooldown), that label filters only name
    labels the watched family actually has, and that the alert counter's
    own catalog entry carries exactly the {rule, severity} labels
    `Sentinel._raise` emits."""
    from paddle_tpu import sentinel, telemetry

    catalog = telemetry.METRIC_CATALOG
    problems = []
    for name, rule in sorted(sentinel.ALERT_CATALOG.items()):
        where = f"sentinel.ALERT_CATALOG['{name}']"
        entry = catalog.get(rule["metric"])
        if entry is None:
            problems.append((
                where, f"watches metric '{rule['metric']}' which is not "
                       f"in telemetry.METRIC_CATALOG — the rule can "
                       f"never fire"))
            continue
        if entry["kind"] not in ("gauge", "counter"):
            problems.append((
                where, f"watches a {entry['kind']} family — the sentinel "
                       f"reads gauges/counters only"))
        lf = rule.get("label_filter") or {}
        extra = set(lf) - set(entry["labels"])
        if extra:
            problems.append((
                where, f"label filter names {sorted(extra)} but "
                       f"'{rule['metric']}' is labeled "
                       f"{sorted(entry['labels'])} — the filter would "
                       f"drop every sample"))
        if lf and not entry["labels"]:
            problems.append((
                where, f"label filter on unlabeled family "
                       f"'{rule['metric']}'"))
        if rule["direction"] not in sentinel.DIRECTIONS:
            problems.append((
                where, f"direction '{rule['direction']}' not in "
                       f"{sentinel.DIRECTIONS}"))
        if rule["severity"] not in sentinel.SEVERITIES:
            problems.append((
                where, f"severity '{rule['severity']}' not in "
                       f"{sentinel.SEVERITIES}"))
        if rule.get("reduce") not in sentinel.REDUCERS:
            problems.append((
                where, f"reducer '{rule.get('reduce')}' not in "
                       f"{sentinel.REDUCERS}"))
        if not rule["z"] > 0:
            problems.append((where, f"z threshold {rule['z']} must be "
                                    f"positive"))
        if rule["cooldown_s"] < 0:
            problems.append((where, "negative cooldown"))

    # the emitter side: the ledger's counter must be cataloged with
    # exactly the labels Sentinel._raise sets (rule, severity) — the
    # call-site/catalog match itself is check_metric_names' job
    alerts_entry = catalog.get("sentinel_alerts_total")
    if alerts_entry is None:
        problems.append((
            "telemetry.METRIC_CATALOG",
            "'sentinel_alerts_total' missing — sentinel alerts would "
            "mint an uncataloged family"))
    elif set(alerts_entry["labels"]) != {"rule", "severity"}:
        problems.append((
            "telemetry.METRIC_CATALOG",
            f"'sentinel_alerts_total' labeled "
            f"{sorted(alerts_entry['labels'])} but the sentinel emits "
            f"{{rule, severity}}"))
    return problems


def check_dynamics_rules():
    """[(where, message), ...] — pin the training-dynamics observatory
    (ISSUE 19 satellite) in both directions:

    * every health code a classification site emits (literal argument to
      dynamics._code(...)) exists in dynamics.HEALTH_CATALOG, and every
      cataloged code has at least one emit site — a stable code the docs
      and dashboards key on can't silently vanish or be minted ad hoc;
    * every dynamics_* metric the observatory emits is in
      telemetry.METRIC_CATALOG and vice versa (the catalog's dynamics_*
      slice has no dead entries) — the emit-site/catalog match itself is
      check_metric_names' job;
    * the dynamics_* sentinel rules exist, watch cataloged dynamics_*
      families, and every dynamics_* ALERT_CATALOG rule resolves — a
      renamed gauge can't orphan the pager."""
    import ast
    import os

    from paddle_tpu import dynamics, sentinel, telemetry

    problems = []
    path = os.path.join(
        os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
        "paddle_tpu", "dynamics.py")
    rel = os.path.join("paddle_tpu", "dynamics.py")
    with open(path) as f:
        tree = ast.parse(f.read())

    emitted_codes = {}   # code -> first where
    for node in ast.walk(tree):
        if not isinstance(node, ast.Call):
            continue
        fn = node.func
        attr = (fn.attr if isinstance(fn, ast.Attribute)
                else fn.id if isinstance(fn, ast.Name) else None)
        if attr != "_code" or not node.args:
            continue
        first = node.args[0]
        where = f"{rel}:{node.lineno}"
        if not (isinstance(first, ast.Constant)
                and isinstance(first.value, str)):
            problems.append((
                where, "_code() called with a non-literal health code — "
                       "the catalog lint cannot pin it"))
            continue
        emitted_codes.setdefault(first.value, where)

    for code, where in sorted(emitted_codes.items()):
        if code not in dynamics.HEALTH_CATALOG:
            problems.append((
                where, f"health code '{code}' is not in "
                       f"dynamics.HEALTH_CATALOG — add it or fix the "
                       f"typo"))
    for code in sorted(dynamics.HEALTH_CATALOG):
        if code not in emitted_codes:
            problems.append((
                "dynamics.HEALTH_CATALOG",
                f"'{code}' is cataloged but no _code() site in "
                f"dynamics.py emits it — dead entry or renamed code"))

    # dynamics_* metric slice, both directions (emitter literals in
    # dynamics.py vs the METRIC_CATALOG dynamics_* entries)
    emitted_metrics = set()
    for node in ast.walk(tree):
        if not isinstance(node, ast.Call):
            continue
        fn = node.func
        attr = (fn.attr if isinstance(fn, ast.Attribute)
                else fn.id if isinstance(fn, ast.Name) else None)
        if attr not in ("counter", "gauge", "histogram") or not node.args:
            continue
        first = node.args[0]
        if isinstance(first, ast.Constant) and isinstance(first.value, str):
            emitted_metrics.add(first.value)
    cataloged = {n for n in telemetry.METRIC_CATALOG
                 if n.startswith("dynamics_")}
    for n in sorted(emitted_metrics - cataloged):
        problems.append((
            rel, f"dynamics emits metric '{n}' with no dynamics_* "
                 f"METRIC_CATALOG entry"))
    for n in sorted(cataloged - emitted_metrics):
        problems.append((
            "telemetry.METRIC_CATALOG",
            f"'{n}' is cataloged but dynamics.py never emits it — dead "
            f"entry or renamed gauge"))

    # the sentinel slice: the observatory's pager rules must exist and
    # resolve against cataloged dynamics_* families
    dyn_rules = {n: r for n, r in sentinel.ALERT_CATALOG.items()
                 if n.startswith("dynamics_")}
    for expect in ("dynamics_update_ratio_spike", "dynamics_dead_layer"):
        if expect not in dyn_rules:
            problems.append((
                "sentinel.ALERT_CATALOG",
                f"'{expect}' rule missing — the observatory has no pager "
                f"for this failure mode"))
    for name, rule in sorted(dyn_rules.items()):
        if rule["metric"] not in cataloged:
            problems.append((
                f"sentinel.ALERT_CATALOG['{name}']",
                f"watches '{rule['metric']}' which is not a cataloged "
                f"dynamics_* family — the rule can never fire"))
    return problems


def check_thread_catalog():
    """[(where, message), ...] — pin analysis/threads.THREAD_CATALOG
    against the actual `threading.Thread`/`go()` creation sites in
    paddle_tpu/ in both directions (ISSUE 18 satellite). An uncataloged
    thread has no declared lifetime discipline (daemon? joined by its
    owner?) and renders anonymously in sentinel hang reports; a stale
    catalog entry documents a thread that no longer exists. Declared
    daemon/joined flags are also checked against what the census can
    prove at each site, so the catalog can't quietly drift into
    documenting the wrong shutdown contract."""
    from paddle_tpu.analysis import threads

    return threads.catalog_problems()


def main():
    problems = check_tables()
    for tname, name in problems:
        print(f"{tname}: '{name}' is not registered in ops/registry.py")
    coll = check_collective_kinds()
    for where, msg in coll:
        print(f"{where}: {msg}")
    jit = check_jit_sites()
    for where, msg in jit:
        print(f"{where}: {msg}")
    sparse = check_sparse_table()
    for where, msg in sparse:
        print(f"{where}: {msg}")
    embc = check_emb_cache()
    for where, msg in embc:
        print(f"{where}: {msg}")
    pallas = check_pallas_table()
    for where, msg in pallas:
        print(f"{where}: {msg}")
    quantp = check_quant_table()
    for where, msg in quantp:
        print(f"{where}: {msg}")
    inferp = check_infer_rules()
    for where, msg in inferp:
        print(f"{where}: {msg}")
    servp = check_serving_programs()
    for where, msg in servp:
        print(f"{where}: {msg}")
    plroles = check_planner_roles()
    for where, msg in plroles:
        print(f"{where}: {msg}")
    metrics = check_metric_names()
    for where, msg in metrics:
        print(f"{where}: {msg}")
    alerts = check_alert_rules()
    for where, msg in alerts:
        print(f"{where}: {msg}")
    thrc = check_thread_catalog()
    for where, msg in thrc:
        print(f"{where}: {msg}")
    dynp = check_dynamics_rules()
    for where, msg in dynp:
        print(f"{where}: {msg}")
    problems = problems + coll + jit + sparse + embc + pallas + quantp \
        + inferp + servp + plroles + metrics + alerts + thrc + dynp
    if problems:
        print(f"{len(problems)} lint problem"
              f"{'' if len(problems) == 1 else 's'}")
        return 1
    print("registry lint ok")
    return 0


if __name__ == "__main__":
    sys.exit(main())
