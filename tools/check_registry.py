#!/usr/bin/env python
"""Registry-consistency lint (ISSUE 7 satellite): every op named in the
layout pass's AGNOSTIC_OPS/AWARE_OPS sets and in the fusion pass's
pattern tables must actually be registered in ops/registry.py. A typo in
one of those tables doesn't raise at runtime — the pattern just never
matches and the optimization silently turns off — so CI pins the sets
against the registry instead.

    python tools/check_registry.py        # exits 1 and lists offenders

Names ending in `_grad` are checked against their base op: grad kernels
are materialized lazily by registry.try_get, so only the forward
registration proves the name is real.
"""

import sys


def check_tables():
    """[(table, name), ...] for every table entry with no registration."""
    from paddle_tpu.ops import fusion, layout, registry

    registered = set(registry.registered_ops())
    tables = {
        "layout.AWARE_OPS": layout.AWARE_OPS,
        "layout.AGNOSTIC_OPS": layout.AGNOSTIC_OPS,
        "fusion.CONV_OPS": fusion.CONV_OPS,
        "fusion.ACT_OPS": fusion.ACT_OPS,
        "fusion.CHAIN_OPS": fusion.CHAIN_OPS,
        "fusion.OPTIMIZER_BUCKET_OPS": fusion.OPTIMIZER_BUCKET_OPS,
        "fusion.FUSED_OP_TYPES": fusion.FUSED_OP_TYPES,
    }
    problems = []
    for tname in sorted(tables):
        for name in sorted(tables[tname]):
            base = name[:-5] if name.endswith("_grad") else name
            if base not in registered:
                problems.append((tname, name))
    return problems


def main():
    problems = check_tables()
    for tname, name in problems:
        print(f"{tname}: '{name}' is not registered in ops/registry.py")
    if problems:
        print(f"{len(problems)} unregistered table entr"
              f"{'y' if len(problems) == 1 else 'ies'}")
        return 1
    print("registry lint ok")
    return 0


if __name__ == "__main__":
    sys.exit(main())
